"""Fleet-scale decision-plane bench: sharded planning at 1024+ hosts.

ROADMAP item 1: the r05 trace already pushed scheduler cycle p50 to
42.7 ms on 64 hosts; this bench scales the cluster to a multi-pool
fleet — mixed v5e / v5p / v6e machine classes, 16 failure domains —
and measures the sharded decision plane against the ROADMAP targets:

  plan p50 < 150 ms and scheduler cycle p99 < 100 ms at 1024 hosts,
  utilization >= 0.95 held.

Three measurements, all through the REAL control-plane code paths:

- **plan**: `ParallelGeometryPlanner` (pool-sharded, per-shard COW
  forks on the worker pool) over a half-saturated 1024-host snapshot
  with a mixed pending batch, against the sequential
  `MultiHostGeometryPlanner` on the identical inputs (the speedup is
  measured in-repo, not asserted);
- **cycle**: steady-state `Scheduler.run_cycle()` wall over the full
  fleet with a resident set of never-fitting pending pods (the
  worst-case full-cluster Filter scan every cycle, served by the
  native prescreen);
- **convergence**: the whole loop — planner, actuator, per-node slice
  agents, gang scheduler — cranked as `nos_tpu.sim` engine rounds
  until a capacity-tiling demand set is bound; utilization =
  bound chips / fleet chips.

The **scale tier** (ISSUE 18, ROADMAP item 3) extends this to 16384
hosts / 100000 bound pods: `--hosts 16384 --pods 100000` constructs a
converged fleet directly on the APIServer and measures the STEADY-STATE
decision plane — incremental `run_cycle` p99 against the 10 ms bar and
the delta-batch plan p50 against the 200 ms bar (`scale_targets` in the
JSON).  `--scale-smoke` is the named CI perf gate on a reduced fleet.

stdout carries EXACTLY one JSON document (the harness contract);
progress goes to stderr.  `--smoke` is the CI gate (scripts/check.sh):
a reduced fleet, asserting shard count, node coverage, and a generous
wall bound so planner regressions fail fast.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from nos_tpu.api import constants as C
from nos_tpu.kube.objects import RUNNING
from nos_tpu.kube.resources import pod_request
from nos_tpu.partitioning.core import ParallelGeometryPlanner
from nos_tpu.partitioning.slicepart import (
    SlicePartitionCalculator, SliceProfileCalculator, SliceSnapshotTaker,
)
from nos_tpu.partitioning.slicepart.group import MultiHostGeometryPlanner
from nos_tpu.partitioning.state import ClusterState
from nos_tpu.scheduler.framework import Framework
from nos_tpu.sim import SimEngine
from nos_tpu.testing.factory import make_pod, make_slice_pod, make_tpu_node
from nos_tpu.topology import Shape, V5E, V5P, V6E
from nos_tpu.topology.profile import free_chip_equivalents

# Fleet layout: (generation, short name, pool count).  Hosts divide
# evenly across pools; 1024 hosts => 64 hosts per pool across 16
# failure domains, 6144 chips.
FLEET = [(V5E, "v5e", 8), (V5P, "v5p", 4), (V6E, "v6e", 4)]
POOLS = sum(n for _, _, n in FLEET)

# Pending-batch profile mix per generation: (profile, weight, gang)
# — gang profiles span multiple hosts and exercise the group pass.
BATCH_MIX = {
    "v5e": [("1x1", 8), ("1x2", 6), ("2x2", 4), ("2x4", 2), ("4x4", 2)],
    "v5p": [("1x1x1", 8), ("1x1x2", 6), ("1x2x2", 4), ("2x2x2", 2)],
    "v6e": [("1x1", 8), ("1x2", 6), ("2x2", 2), ("2x4", 2)],
}
VIRGIN_FREE = {"v5e": "2x4", "v5p": "1x2x2", "v6e": "2x2"}
# Never-fitting resident pending set for the steady-state cycle
# measurement: shapes no carved host advertises on a full cluster.
RESIDENT_PENDING = {"v5e": "8x8", "v5p": "4x4x4", "v6e": "8x8"}

ROADMAP_PLAN_P50_MS = 150.0
ROADMAP_CYCLE_P99_MS = 100.0
ROADMAP_UTILIZATION = 0.95

SMOKE_HOSTS = 256
SMOKE_WALL_BOUND_MS = 4000.0

# -- scale tier (ISSUE 18 / ROADMAP item 3): 16k hosts, 100k pods -----------
# Single-chip filler profile per generation for the converged fleet.
SCALE_FILLER = {"v5e": "1x1", "v5p": "1x1x1", "v6e": "1x1"}
SCALE_HOSTS = 16384
SCALE_PODS = 100000
SCALE_RESIDENTS_PER_GEN = 8
SCALE_CYCLE_P99_MS = 10.0
SCALE_PLAN_P50_MS = 200.0
# CI smoke variant: same code path, scaled-down fleet, named bounds
# generous enough for a loaded 1-core runner (the full tier holds the
# real bars; the smoke catches order-of-magnitude regressions).
SCALE_SMOKE_HOSTS = 512
SCALE_SMOKE_PODS = 3072
SCALE_SMOKE_CYCLE_P99_MS = 50.0
SCALE_SMOKE_PLAN_P50_MS = 1500.0


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def percentile(xs: list[float], q: float) -> float:
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


def wall_summary(samples_ms: list[float]) -> dict:
    return {"p50": round(percentile(samples_ms, 0.50), 3),
            "p99": round(percentile(samples_ms, 0.99), 3)}


def fleet_hosts(hosts: int):
    """Yield (name, generation, gen_name, pool_id, host_index)."""
    per_pool = hosts // POOLS
    i = 0
    for gen, gname, pools in FLEET:
        for p in range(pools):
            pod_id = f"{gname}-pod-{p}"
            for h in range(per_pool):
                yield f"{gname}-{p}-h{h}", gen, gname, pod_id, h
                i += 1


def make_fleet_state(hosts: int, full_fraction: float = 0.5) -> ClusterState:
    """Planner-side fleet snapshot source: virgin free blocks, a
    fraction of each pool genuinely full (bound fillers), mirroring a
    saturated trace where only part of the fleet has re-carvable
    headroom."""
    state = ClusterState()
    per_pool = hosts // POOLS
    full_per_pool = int(per_pool * full_fraction)
    for name, gen, gname, pod_id, h in fleet_hosts(hosts):
        if h < full_per_pool:
            node = make_tpu_node(
                name, generation=gen, pod_id=pod_id, host_index=h,
                status_geometry={"used": {VIRGIN_FREE[gname]: 1}})
            filler = make_pod(name=f"filler-{name}", node_name=name,
                              resources=dict(node.status.allocatable))
            state.update_node(node, [filler])
        else:
            node = make_tpu_node(
                name, generation=gen, pod_id=pod_id, host_index=h,
                status_geometry={"free": {VIRGIN_FREE[gname]: 1}})
            state.update_node(node, [])
    return state


def make_fleet_batch(hosts: int, pods_per_64_hosts: int = 40) -> list:
    """Mixed pending batch, weighted by each generation's fleet share."""
    per_pool = hosts // POOLS
    out = []
    i = 0
    for gen, gname, pools in FLEET:
        want = max(1, pods_per_64_hosts * per_pool * pools // 64)
        mix = BATCH_MIX[gname]
        n = 0
        while n < want:
            for profile, weight in mix:
                for _ in range(weight):
                    if n >= want:
                        break
                    multihost = gen.hosts_for(Shape.parse(profile)) > 1
                    labels = ({C.LABEL_POD_GROUP: f"fleet-gang-{i}"}
                              if multihost else None)
                    out.append(make_slice_pod(
                        profile, 1, name=f"fleet-{gname}-{i}",
                        labels=labels, priority=i % 3))
                    i += 1
                    n += 1
    return out


def make_planner(sharded: bool, plan_workers: int = 0):
    def factory() -> MultiHostGeometryPlanner:
        return MultiHostGeometryPlanner(
            framework=Framework(),
            calculator=SliceProfileCalculator(),
            partition_calculator=SlicePartitionCalculator(),
        )

    if not sharded:
        return factory()
    return ParallelGeometryPlanner(
        factory, SliceProfileCalculator(), kind="slice",
        max_workers=plan_workers, min_shard_hosts=0)


def run_plan_bench(hosts: int = 1024, repeats: int = 5,
                   compare_sequential: bool = True) -> dict:
    from nos_tpu.device import native

    native.install_native_packer(build=True)
    state = make_fleet_state(hosts)
    pods = make_fleet_batch(hosts)
    taker = SliceSnapshotTaker()
    out: dict = {"hosts": hosts, "pending_pods": len(pods)}

    sharded = make_planner(sharded=True)
    walls: list[float] = []
    for r in range(repeats):
        snap = taker.take_snapshot(state)
        t0 = time.perf_counter()
        desired = sharded.plan(snap, pods)
        walls.append((time.perf_counter() - t0) * 1e3)
        log(f"plan[sharded] {r}: {walls[-1]:.1f} ms")
    out["plan_wall_ms"] = wall_summary(walls)
    out["shards"] = len(sharded.last_shard_seconds)
    out["shard_seconds"] = {
        k: round(v, 4) for k, v in sorted(
            sharded.last_shard_seconds.items())}
    out["planned_nodes"] = len(desired)
    sharded.close()

    if compare_sequential:
        seq = make_planner(sharded=False)
        seq_walls: list[float] = []
        for r in range(max(2, repeats // 2)):
            snap = taker.take_snapshot(state)
            t0 = time.perf_counter()
            seq.plan(snap, pods)
            seq_walls.append((time.perf_counter() - t0) * 1e3)
            log(f"plan[sequential] {r}: {seq_walls[-1]:.1f} ms")
        out["sequential_plan_wall_ms"] = wall_summary(seq_walls)
        if out["plan_wall_ms"]["p50"] > 0:
            out["plan_speedup_vs_sequential"] = round(
                out["sequential_plan_wall_ms"]["p50"]
                / out["plan_wall_ms"]["p50"], 2)
    return out


# ---------------------------------------------------------------------------
# Convergence + steady-state cycle: the full loop, hand-cranked
# ---------------------------------------------------------------------------


def make_tiling_demand(api, hosts: int) -> list:
    """Demand that exactly tiles every pool's chips: mostly whole-host
    blocks, plus sub-host re-carve classes and a few multi-host gangs
    per generation (utilization target >= 0.95)."""
    from nos_tpu.api.podgroup import PodGroup, PodGroupSpec
    from nos_tpu.kube.client import KIND_POD_GROUP
    from nos_tpu.kube.objects import ObjectMeta

    per_pool = hosts // POOLS
    pods = []
    gangs = 0
    for gen, gname, pools in FLEET:
        whole = VIRGIN_FREE[gname]
        mix = BATCH_MIX[gname]
        subhost = [pr for pr, _ in mix
                   if gen.hosts_for(Shape.parse(pr)) == 1 and pr != whole]
        multihost = [pr for pr, _ in mix
                     if gen.hosts_for(Shape.parse(pr)) > 1]
        for p in range(pools):
            # per pool of H hosts: H-2k-4 whole-host blocks, 2 hosts of
            # each sub-host class, and one 2-host gang when available
            h_left = per_pool
            i = 0
            if multihost:
                shape = Shape.parse(multihost[0])
                span = gen.hosts_for(shape)
                if h_left >= span + 2:
                    gang = f"{gname}-{p}-gang"
                    api.create(KIND_POD_GROUP, PodGroup(
                        metadata=ObjectMeta(name=gang, namespace="default"),
                        spec=PodGroupSpec(min_member=span)))
                    for m in range(span):
                        pods.append(make_slice_pod(
                            multihost[0], 1, name=f"{gang}-{m}",
                            labels={C.LABEL_POD_GROUP: gang}, priority=5))
                    gangs += 1
                    h_left -= span
            for pr in subhost:
                if h_left < 3:
                    break
                per_host = gen.chips_per_host // Shape.parse(pr).chips
                for _ in range(2):          # two hosts of this class
                    for _ in range(per_host):
                        pods.append(make_slice_pod(
                            pr, 1, name=f"fill-{gname}-{p}-{i}"))
                        i += 1
                    h_left -= 1
            for _ in range(h_left):
                pods.append(make_slice_pod(
                    whole, 1, name=f"fill-{gname}-{p}-{i}"))
                i += 1
    log(f"tiling demand: {len(pods)} pods, {gangs} gangs")
    return pods


def build_fleet_api(hosts: int):
    """Full control plane on the in-memory substrate: node/pod state
    controllers, sharded partitioner controller, per-node slice agents,
    the real scheduler."""
    from nos_tpu.cmd.assembly import build_scheduler
    from nos_tpu.controllers.node_controller import NodeController
    from nos_tpu.controllers.pod_controller import PodController
    from nos_tpu.controllers.sliceagent.agent import SliceAgent
    from nos_tpu.device import default_tpu_runtime
    from nos_tpu.device.fake import FakePodResources
    from nos_tpu.kube.client import APIServer, KIND_NODE
    from nos_tpu.partitioning.slicepart import SliceNodeInitializer
    from nos_tpu.partitioning.slicepart.factory import (
        new_slice_partitioner_controller,
    )

    api = APIServer()
    state = ClusterState()
    NodeController(api, state, SliceNodeInitializer(api)).bind()
    PodController(api, state).bind()
    ctl = new_slice_partitioner_controller(
        api, state, batch_timeout_s=2.0, batch_idle_s=0.5,
        plan_shard_min_hosts=0)
    ctl.bind()
    agents = []
    for name, gen, gname, pod_id, h in fleet_hosts(hosts):
        api.create(KIND_NODE, make_tpu_node(
            name, generation=gen, pod_id=pod_id, host_index=h))
        agent = SliceAgent(api, name, default_tpu_runtime(gen),
                           FakePodResources())
        agent.start()
        agents.append(agent)
    scheduler = build_scheduler(api)
    return api, ctl, agents, scheduler


def run_convergence_bench(hosts: int = 1024, max_rounds: int = 30,
                          steady_cycles: int = 300) -> dict:
    from nos_tpu.kube.client import KIND_POD

    t_build = time.perf_counter()
    api, ctl, agents, scheduler = build_fleet_api(hosts)
    log(f"fleet api built in {time.perf_counter() - t_build:.1f}s")
    demand = make_tiling_demand(api, hosts)
    for pod in demand:
        api.create(KIND_POD, pod)
    total = len(demand)
    total_chips = sum(
        free_chip_equivalents(n.status.allocatable)
        for n in api.list("Node"))

    plan_walls: list[float] = []
    cycle_walls: list[float] = []
    bound = 0
    t0 = time.perf_counter()
    # Convergence rounds ride the sim engine: each round is one tick of
    # the virtual clock (round number == virtual second) and the loop
    # self-terminates through while_fn the moment the fleet is bound —
    # the same crank, expressed as the one shared run-loop idiom.
    eng = SimEngine()

    def convergence_round() -> None:
        nonlocal bound
        t = time.perf_counter()
        scheduler.run_cycle()
        cycle_walls.append((time.perf_counter() - t) * 1e3)
        t = time.perf_counter()
        ctl.process_pending_pods()
        plan_walls.append((time.perf_counter() - t) * 1e3)
        for agent in agents:
            agent.tick()
        t = time.perf_counter()
        scheduler.run_cycle()
        cycle_walls.append((time.perf_counter() - t) * 1e3)
        bound = sum(1 for p in api.list(KIND_POD)
                    if p.spec.node_name and p.status.phase == RUNNING)
        log(f"round {int(eng.now()) - 1}: bound {bound}/{total} "
            f"(cycle {cycle_walls[-1]:.0f} ms, plan {plan_walls[-1]:.0f} ms)")

    eng.tick_loop(1.0, convergence_round, until=float(max_rounds),
                  while_fn=lambda: bound < total,
                  label="convergence-round")
    eng.run()
    converge_s = time.perf_counter() - t0

    # host-shard accounting: a multi-host gang member requests the full
    # slice shape but physically owns only its host's shard of it, so
    # its chip claim is shape.chips / member hosts (the quota
    # calculator's shard_chips_per_host discipline)
    from nos_tpu.topology import DEFAULT_REGISTRY
    from nos_tpu.topology.profile import extract_slice_requests

    gen_by_node = {
        n.metadata.name: DEFAULT_REGISTRY.generations.get(
            n.metadata.labels.get(C.LABEL_ACCELERATOR, ""))
        for n in api.list("Node")}
    bound_chips = 0.0
    for p in api.list(KIND_POD):
        if not p.spec.node_name or p.status.phase != RUNNING:
            continue
        gen = gen_by_node.get(p.spec.node_name)
        for shape, qty in extract_slice_requests(pod_request(p)).items():
            hosts_span = gen.hosts_for(shape) if gen is not None else 1
            bound_chips += shape.chips * qty / hosts_span
    utilization = bound_chips / total_chips if total_chips else 0.0

    # steady state: resident never-fitting pods force the full-cluster
    # Filter scan every cycle — the fleet's worst-case cycle
    for gen, gname, _ in FLEET:
        for i in range(8):
            api.create(KIND_POD, make_slice_pod(
                RESIDENT_PENDING[gname], 1, name=f"resident-{gname}-{i}"))
    # The converged fleet is a large LONG-LIVED object graph (nodes,
    # bound pods, device tables); without freezing it, periodic gen-2
    # GC walks the whole thing mid-cycle and owns the p99 (measured:
    # ~118 ms p99 unfrozen vs ~72 ms frozen at 1024 hosts).  Freezing
    # after warmup is the standard long-running-service tactic and is
    # what a production scheduler process would do — the steady-state
    # number should measure the scheduler, not the collector.
    import gc

    gc.collect()
    gc.freeze()
    steady: list[float] = []
    for _ in range(steady_cycles):
        t = time.perf_counter()
        scheduler.run_cycle()
        steady.append((time.perf_counter() - t) * 1e3)
    gc.unfreeze()       # don't pin this fleet's graph on later benches
    log(f"steady cycles: {wall_summary(steady)}")
    scheduler.close()
    planner = ctl._planner
    if isinstance(planner, ParallelGeometryPlanner):
        planner.close()

    return {
        "hosts": hosts,
        "demand_pods": total,
        "bound_pods": bound,
        "utilization": round(utilization, 4),
        "convergence_s": round(converge_s, 2),
        "convergence_plan_wall_ms": wall_summary(plan_walls),
        "convergence_cycle_wall_ms": wall_summary(cycle_walls),
        "scheduler_cycle_wall_ms": wall_summary(steady),
    }


# ---------------------------------------------------------------------------
# Scale tier: 16384 hosts / 100000 pods, steady-state decision plane
# ---------------------------------------------------------------------------


def build_scale_api(hosts: int, pods: int):
    """A CONVERGED fleet constructed directly on the APIServer: every
    host carved into single-chip slices, every slice bound, the pod
    count topped up to `pods` with bound cpu-only sidecars.  No
    controllers or per-node agents — the scale tier measures the
    steady-state decision plane (what each cycle costs once the fleet
    is converged), not convergence itself; convergence at fleet scale
    is run_convergence_bench's job at the 1024-host tier."""
    from nos_tpu.kube.client import APIServer, KIND_NODE, KIND_POD

    api = APIServer()
    layout = list(fleet_hosts(hosts))
    caps = [gen.chips_per_host for _, gen, _, _, _ in layout]
    fills = [0] * len(layout)
    tpu_pods = min(pods, sum(caps))
    # round-robin single-slice fill so every pool carries load
    left = tpu_pods
    while left > 0:
        placed = 0
        for i in range(len(layout)):
            if left == 0:
                break
            if fills[i] < caps[i]:
                fills[i] += 1
                left -= 1
                placed += 1
        if placed == 0:
            break
    cpu_pods = pods - tpu_pods
    created = 0
    for i, (name, gen, gname, pod_id, h) in enumerate(layout):
        profile = SCALE_FILLER[gname]
        geometry = {"used": {profile: fills[i]}} if fills[i] else None
        api.create(KIND_NODE, make_tpu_node(
            name, generation=gen, pod_id=pod_id, host_index=h,
            status_geometry=geometry))
        for k in range(fills[i]):
            api.create(KIND_POD, make_slice_pod(
                profile, 1, name=f"sf-{name}-{k}", node_name=name,
                phase=RUNNING))
            created += 1
    for k in range(cpu_pods):
        name = layout[k % len(layout)][0]
        api.create(KIND_POD, make_pod(
            name=f"cf-{k}", node_name=name, phase=RUNNING,
            resources={"cpu": 0.05}))
        created += 1
    return api, created


def run_scale_bench(hosts: int = SCALE_HOSTS, pods: int = SCALE_PODS,
                    steady_cycles: int = 200, warmup_cycles: int = 5,
                    plan_repeats: int = 3,
                    incremental: bool = True) -> dict:
    """The ISSUE 18 scale tier.  Two steady-state measurements:

    - **cycle**: `Scheduler.run_cycle()` over the converged fleet with
      a resident set of never-fitting pending pods.  Incrementally this
      is O(dirty set + residents): the class scans, the victim-screen
      masks and the waste skeleton all persist across cycles under the
      frozen view epoch, so the fleet size drops out of the steady
      cycle entirely.  Warm-up cycles (which pay the one-time scan
      builds) are excluded — they are the cold path the full-rescan
      backstop also pays, reported separately.
    - **plan**: `ParallelGeometryPlanner.plan` over the converged
      16k-host snapshot with a steady-state DELTA batch (the handful of
      pods a converged cluster actually re-plans per pass), snapshot
      capture excluded (same timer discipline as run_plan_bench).
    """
    import gc

    from nos_tpu.cmd.assembly import build_scheduler
    from nos_tpu.device import native
    from nos_tpu.kube.client import KIND_POD

    native.install_native_packer(build=True)
    t_build = time.perf_counter()
    api, created = build_scale_api(hosts, pods)
    log(f"scale fleet built in {time.perf_counter() - t_build:.1f}s: "
        f"{hosts} hosts, {created} bound pods")
    scheduler = build_scheduler(api, incremental=incremental)
    residents = 0
    for gen, gname, _ in FLEET:
        for i in range(SCALE_RESIDENTS_PER_GEN):
            api.create(KIND_POD, make_slice_pod(
                RESIDENT_PENDING[gname], 1, name=f"resident-{gname}-{i}"))
            residents += 1

    warm: list[float] = []
    for _ in range(warmup_cycles):
        t = time.perf_counter()
        scheduler.run_cycle()
        warm.append((time.perf_counter() - t) * 1e3)
    gc.collect()
    gc.freeze()         # same long-lived-graph tactic as the 1024 tier
    steady: list[float] = []
    for _ in range(steady_cycles):
        t = time.perf_counter()
        scheduler.run_cycle()
        steady.append((time.perf_counter() - t) * 1e3)
    gc.unfreeze()
    log(f"scale steady cycles: {wall_summary(steady)} "
        f"(warm-up p50 {percentile(warm, 0.5):.1f} ms)")
    # The full-rescan backstop re-levels every index at most once per
    # `full_rescan_every` (512) cycles — under 1% of cycles, so it
    # amortizes out of the steady p99.  Measure it honestly anyway:
    # force a total invalidation and time the recovery cycle.
    backstop_ms = None
    if incremental and scheduler._cache is not None:
        scheduler._cache.invalidate_all()
        t = time.perf_counter()
        scheduler.run_cycle()
        backstop_ms = (time.perf_counter() - t) * 1e3
        log(f"scale backstop (full-rescan) cycle: {backstop_ms:.1f} ms")
    scheduler.close()

    taker = SliceSnapshotTaker()
    state = make_fleet_state(hosts, full_fraction=1.0)
    delta = make_fleet_batch(64)        # the steady per-pass re-plan load
    planner = make_planner(sharded=True)
    plan_walls: list[float] = []
    for r in range(plan_repeats):
        snap = taker.take_snapshot(state)
        # freeze AFTER the snapshot build: the 16k-node object graph is
        # long-lived for the duration of the plan, and a mid-plan major
        # collection over it costs ~200 ms of pure interpreter noise
        # (same tactic as the steady-cycle loop above)
        gc.collect()
        gc.freeze()
        t0 = time.perf_counter()
        planner.plan(snap, delta)
        plan_walls.append((time.perf_counter() - t0) * 1e3)
        gc.unfreeze()
        log(f"scale plan {r}: {plan_walls[-1]:.1f} ms")
    planner.close()

    cycle_p99 = wall_summary(steady)["p99"]
    plan_p50 = wall_summary(plan_walls)["p50"]
    return {
        "hosts": hosts,
        "pods": created,
        "resident_pending": residents,
        "incremental": incremental,
        "warmup_cycle_wall_ms": wall_summary(warm),
        "scheduler_cycle_wall_ms": wall_summary(steady),
        "backstop_cycle_ms": backstop_ms,
        "plan_delta_pods": len(delta),
        "plan_wall_ms": wall_summary(plan_walls),
        "scale_targets": {
            "cycle_p99_ms": {"target": SCALE_CYCLE_P99_MS,
                             "value": cycle_p99,
                             "ok": cycle_p99 < SCALE_CYCLE_P99_MS},
            "plan_p50_ms": {"target": SCALE_PLAN_P50_MS,
                            "value": plan_p50,
                            "ok": plan_p50 < SCALE_PLAN_P50_MS},
        },
    }


def run_scale_smoke() -> int:
    """Named CI perf gate (scripts/check.sh "perf-gate" stage): the
    scale tier's exact code path on a scaled-down fleet, with named
    cycle-p99 / plan-p50 bounds.  Exit 1 on any breach."""
    result = run_scale_bench(
        hosts=SCALE_SMOKE_HOSTS, pods=SCALE_SMOKE_PODS,
        steady_cycles=50, warmup_cycles=3, plan_repeats=2)
    failures = []
    cyc = result["scheduler_cycle_wall_ms"]["p99"]
    if cyc > SCALE_SMOKE_CYCLE_P99_MS:
        failures.append(
            f"steady cycle p99 {cyc:.1f} ms exceeds the "
            f"{SCALE_SMOKE_CYCLE_P99_MS:.0f} ms perf-gate bound")
    plan = result["plan_wall_ms"]["p50"]
    if plan > SCALE_SMOKE_PLAN_P50_MS:
        failures.append(
            f"delta plan p50 {plan:.1f} ms exceeds the "
            f"{SCALE_SMOKE_PLAN_P50_MS:.0f} ms perf-gate bound")
    print(json.dumps({"perf_gate": "fail" if failures else "ok",
                      "hosts": result["hosts"],
                      "pods": result["pods"],
                      "scheduler_cycle_wall_ms":
                          result["scheduler_cycle_wall_ms"],
                      "plan_wall_ms": result["plan_wall_ms"],
                      "failures": failures}))
    return 1 if failures else 0


def run_bench(hosts: int = 1024, plan_repeats: int = 5,
              convergence: bool = True) -> dict:
    out = {"fleet": {"hosts": hosts, "pools": POOLS,
                     "generations": [g for _, g, _ in FLEET]}}
    out["plan"] = run_plan_bench(hosts, repeats=plan_repeats)
    if convergence:
        out["convergence"] = run_convergence_bench(hosts)
        util = out["convergence"]["utilization"]
        cyc = out["convergence"]["scheduler_cycle_wall_ms"]["p99"]
    else:
        util, cyc = None, None
    plan_p50 = out["plan"]["plan_wall_ms"]["p50"]
    out["targets"] = {
        "plan_p50_ms": {"target": ROADMAP_PLAN_P50_MS, "value": plan_p50,
                        "ok": plan_p50 < ROADMAP_PLAN_P50_MS},
        "cycle_p99_ms": {"target": ROADMAP_CYCLE_P99_MS, "value": cyc,
                         "ok": cyc is not None and cyc < ROADMAP_CYCLE_P99_MS},
        "utilization": {"target": ROADMAP_UTILIZATION, "value": util,
                        "ok": util is not None and
                        util >= ROADMAP_UTILIZATION},
    }
    return out


def run_smoke() -> int:
    """CI gate: reduced fleet, shard-count + coverage + wall bounds."""
    hosts = SMOKE_HOSTS
    result = run_plan_bench(hosts, repeats=2, compare_sequential=False)
    failures = []
    if result["shards"] != POOLS:
        failures.append(
            f"expected {POOLS} plan shards (one per pool), got "
            f"{result['shards']} — pool partitioning broken?")
    if result["planned_nodes"] != hosts:
        failures.append(
            f"merged desired state covers {result['planned_nodes']} of "
            f"{hosts} nodes — shard merge dropped nodes")
    if result["plan_wall_ms"]["p50"] > SMOKE_WALL_BOUND_MS:
        failures.append(
            f"sharded plan p50 {result['plan_wall_ms']['p50']:.1f} ms "
            f"exceeds the {SMOKE_WALL_BOUND_MS:.0f} ms smoke bound")
    print(json.dumps({"smoke": "fail" if failures else "ok",
                      "hosts": hosts,
                      "plan_wall_ms": result["plan_wall_ms"],
                      "shards": result["shards"],
                      "failures": failures}))
    return 1 if failures else 0


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--smoke", action="store_true",
                        help="fast CI gate: shard count + wall bounds")
    parser.add_argument("--scale-smoke", action="store_true",
                        help="named CI perf gate: scale tier on a "
                        "reduced fleet, cycle-p99/plan-p50 bounds")
    parser.add_argument("--hosts", type=int, default=1024)
    parser.add_argument("--pods", type=int, default=0,
                        help="run the SCALE tier (converged fleet of "
                        "--hosts hosts with this many bound pods, "
                        "steady-state cycle + delta plan); e.g. "
                        "--hosts 16384 --pods 100000")
    parser.add_argument("--repeats", type=int, default=5)
    parser.add_argument("--steady-cycles", type=int, default=200)
    parser.add_argument("--full-rescan", action="store_true",
                        help="scale tier only: run with the dirty-set "
                        "scheduler disabled (incremental=off baseline)")
    parser.add_argument("--no-convergence", action="store_true")
    args = parser.parse_args()
    if args.smoke:
        return run_smoke()
    if args.scale_smoke:
        return run_scale_smoke()
    if args.pods:
        print(json.dumps(run_scale_bench(
            args.hosts, args.pods, steady_cycles=args.steady_cycles,
            incremental=not args.full_rescan)))
        return 0
    print(json.dumps(run_bench(args.hosts, plan_repeats=args.repeats,
                               convergence=not args.no_convergence)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
