"""Placement-aware planning regression tests (VERDICT r3 weak #1).

The BENCH_r03 failure loop: the planner validated geometries as multiset
tilings of the EMPTY host block, but the device layer must place creates
around *pinned* used slices — a count-feasible geometry can be
placement-infeasible given where used slices physically sit, and the
failed-plan retry reapplied the same doomed plan forever ("cannot place
['1x2', '2x2'] on unit 0", host-12, repeated).

Three layers of defense, each tested here:
1. the reporter exports device placements in status annotations;
2. SliceUnit.can_apply_geometry consults the pins via packing.extend;
3. the actuator surfaces PlacementInfeasibleError as a distinct outcome
   that waits for a re-plan instead of retrying.
"""

from __future__ import annotations

import pytest

from nos_tpu.api import constants as C
from nos_tpu.device.fake import FakePodResources, FakeTpuRuntime
from nos_tpu.kube.client import KIND_NODE, KIND_POD
from nos_tpu.testing.factory import make_slice_pod
from nos_tpu.topology import Shape, SliceUnit, V5E
from nos_tpu.topology.annotations import (
    encode_placement_records, parse_placement_annotations,
    parse_spec_annotations,
)
from nos_tpu.topology.errors import PlacementInfeasibleError
from nos_tpu.topology.packing import Placement

from test_e2e_slice import Harness

S11 = Shape.parse("1x1").canonical()
S12 = Shape.parse("1x2").canonical()
S22 = Shape.parse("2x2").canonical()

# Two vertical 1x2 slices pinned at columns 1 and 2 of the 2x4 host block:
# count-feasible geometries containing a 2x2 exist, but no 2x2 placement
# avoids both pins (aligned offsets are columns 0 and 2 only).
AWKWARD_PINS = [
    Placement(S12, (0, 1), (2, 1)),
    Placement(S12, (0, 2), (2, 1)),
]


class TestPinnedGeometryChecks:
    def test_count_feasible_but_placement_infeasible(self):
        bare = SliceUnit(generation=V5E, used={S12: 2})
        pinned = SliceUnit(generation=V5E, used={S12: 2},
                           placed_used=list(AWKWARD_PINS))
        geo = {S12: 2, S22: 1}
        assert bare.can_apply_geometry(geo)          # the r3 blind spot
        assert not pinned.can_apply_geometry(geo)    # the fix

    def test_friendly_pins_still_allow_geometry(self):
        # same counts, pins at columns 0 and 1: a 2x2 fits at column 2
        pins = [Placement(S12, (0, 0), (2, 1)), Placement(S12, (0, 1), (2, 1))]
        u = SliceUnit(generation=V5E, used={S12: 2}, placed_used=pins)
        assert u.can_apply_geometry({S12: 2, S22: 1})

    def test_update_geometry_for_skips_unplaceable_candidates(self):
        u = SliceUnit(generation=V5E, used={S12: 2},
                      placed_used=list(AWKWARD_PINS))
        assert not u.update_geometry_for({S22: 1})
        # but it can still provide profiles that DO place around the pins
        assert u.update_geometry_for({S11: 4})
        assert u.free.get(S11, 0) >= 2

    def test_stale_placement_data_degrades_to_count_checks(self):
        # pins disagree with used counts (claim window): don't trust them
        u = SliceUnit(generation=V5E, used={S12: 2},
                      placed_used=[AWKWARD_PINS[0]])
        assert not u.has_placement_data()
        assert u.can_apply_geometry({S12: 2, S22: 1})

    def test_allocate_release_move_pins(self):
        u = SliceUnit(generation=V5E)
        u.apply_geometry({S12: 2, S22: 1})
        u.placed_free = [
            Placement(S12, (0, 0), (2, 1)),
            Placement(S12, (0, 1), (2, 1)),
            Placement(S22, (0, 2), (2, 2)),
        ]
        assert u.allocate(S22)
        assert u.has_placement_data()
        assert [p.shape for p in u.placed_used] == [S22]
        assert u.release(S22)
        assert not u.placed_used

    def test_apply_geometry_recomputes_free_placements(self):
        pins = [Placement(S22, (0, 0), (2, 2))]
        u = SliceUnit(generation=V5E, used={S22: 1}, placed_used=pins)
        u.apply_geometry({S22: 2})
        assert len(u.placed_free) == 1
        assert u.placed_free[0].offset == (0, 2)


class TestPlacementAnnotationCodec:
    def test_round_trip(self):
        records = [("u", AWKWARD_PINS[0]), ("f", Placement(S22, (0, 2), (2, 2)))]
        encoded = encode_placement_records(records)
        parsed = parse_placement_annotations(
            {f"{C.ANNOT_PLACEMENTS_PREFIX}0": encoded})
        assert sorted(parsed[0]) == sorted(records)

    def test_corrupt_records_skipped(self):
        parsed = parse_placement_annotations({
            f"{C.ANNOT_PLACEMENTS_PREFIX}0":
                "u|1x2|0.1|2.1;garbage;x|1x1|0|1.1;u|bad|a.b|1.1",
        })
        assert len(parsed[0]) == 1

    def test_units_from_node_parses_pins(self):
        from nos_tpu.partitioning.slicepart.node import units_from_node
        from nos_tpu.testing.factory import make_node

        node = make_node("h", labels={C.LABEL_ACCELERATOR: "tpu-v5e"})
        node.metadata.annotations.update({
            f"{C.ANNOT_STATUS_PREFIX}0-1x2-used": "2",
            f"{C.ANNOT_PLACEMENTS_PREFIX}0": encode_placement_records(
                [("u", p) for p in AWKWARD_PINS]),
        })
        units = units_from_node(node)
        assert units[0].has_placement_data()
        assert not units[0].can_apply_geometry({S12: 2, S22: 1})


class TestActuatorInfeasibleHandling:
    """The VERDICT pattern end-to-end at the agent: an infeasible spec is
    attempted ONCE, remembered, and skipped until a new plan arrives."""

    def _pin_awkward_used(self, h: Harness) -> None:
        """Carve 4 horizontal 1x2s and bind a pod holding the two at
        (0,0) and (0,2) — the whole top row — so no aligned 2x2
        placement (columns 0 or 2) avoids the pins."""
        from nos_tpu.topology.annotations import strip_spec_annotations

        h.agent.tick()                       # init geometry 2x4

        def carve(node):
            strip_spec_annotations(node.metadata.annotations, family="slice")
            node.metadata.annotations.update({
                f"{C.ANNOT_SPEC_PREFIX}0-1x2": "4",
                C.spec_plan_annotation("slice"): "pin-setup",
            })
        h.api.patch(KIND_NODE, "host-0", mutate=carve)
        h.agent.tick()                       # deletes 2x4, carves 4x 1x2
        # bound pod: the kubelet sim allocates the first two device ids,
        # which the deterministic packer placed at (0,0) and (0,2)
        h.api.create(KIND_POD, make_slice_pod(
            "1x2", 2, name="pinner", node_name="host-0"))
        h.agent.tick()                       # admit + report used/placements
        pins = {pl.offset for did, pl in h.runtime.placements().items()
                if did in h.pod_resources.used_device_ids()}
        assert pins == {(0, 0), (0, 2)}

    def test_infeasible_plan_not_retried(self):
        from nos_tpu.topology.annotations import strip_spec_annotations

        h = Harness()
        self._pin_awkward_used(h)

        def mutate(node):
            strip_spec_annotations(node.metadata.annotations, family="slice")
            node.metadata.annotations.update({
                f"{C.ANNOT_SPEC_PREFIX}0-1x2": "2",
                f"{C.ANNOT_SPEC_PREFIX}0-2x2": "1",
                C.spec_plan_annotation("slice"): "doomed-plan",
            })
        h.api.patch(KIND_NODE, "host-0", mutate=mutate)

        calls_before = h.runtime.create_calls
        h.agent.tick()                       # attempts once, fails
        assert h.runtime.create_calls == calls_before + 1
        h.agent.tick()                       # remembered: no retry
        h.agent.tick()
        assert h.runtime.create_calls == calls_before + 1

        # a NEW plan clears the verdict and actuates
        def replan(node):
            strip_spec_annotations(node.metadata.annotations, family="slice")
            node.metadata.annotations.update({
                f"{C.ANNOT_SPEC_PREFIX}0-1x2": "4",
                C.spec_plan_annotation("slice"): "good-plan",
            })
        h.api.patch(KIND_NODE, "host-0", mutate=replan)
        h.agent.tick()
        assert h.runtime.create_calls == calls_before + 2
        names = sorted(d.resource_name for d in h.runtime.list_devices())
        assert names == ["nos.tpu/slice-1x2"] * 4

    def test_planner_avoids_doomed_geometry_e2e(self):
        """The full loop: with placements reported, the planner never
        writes the infeasible spec in the first place — the pending 2x2
        pod stays pending with ZERO failed creates (the r3 loop is dead)."""
        h = Harness()
        self._pin_awkward_used(h)

        h.api.create(KIND_POD, make_slice_pod("2x2", 1, name="want-2x2"))
        assert h.scheduler.run_cycle() == 0
        h.advance(11.0)
        assert h.partitioner.process_if_ready()

        node = h.get_node()
        spec = {(a.index, a.profile): a.quantity
                for a in parse_spec_annotations(node.metadata.annotations)}
        assert (0, "2x2") not in spec        # planner knew better

        calls_before = h.runtime.create_calls
        h.agent.tick()
        h.agent.tick()
        assert h.runtime.create_calls == calls_before  # no doomed creates

    def test_placement_feasible_request_still_served(self):
        """Control: with the same pins, profiles that CAN place are carved
        and the pod schedules."""
        h = Harness()
        self._pin_awkward_used(h)

        h.api.create(KIND_POD, make_slice_pod("1x1", 2, name="want-1x1"))
        assert h.scheduler.run_cycle() == 0
        h.advance(11.0)
        assert h.partitioner.process_if_ready()
        h.agent.tick()
        assert h.scheduler.run_cycle() == 1
