"""Serving-plane tests: the nos.tpu/tier contract, tiered admission
ordering, serving-never-a-victim preemption semantics, the burst-trace
e2e (zero serving preemptions + autoscaler tracking through the real
scheduler), the pending-age gauge regression, and the obs scoreboard's
per-tier rows.
"""

from __future__ import annotations

import pytest

from nos_tpu.api import constants as C
from nos_tpu.api.elasticquota import (
    ElasticQuota, ElasticQuotaSpec, install_quota_webhooks,
)
from nos_tpu.cmd.assembly import build_scheduler
from nos_tpu.exporter.metrics import REGISTRY
from nos_tpu.kube.client import (
    APIServer, KIND_ELASTIC_QUOTA, KIND_NODE, KIND_POD,
)
from nos_tpu.kube.objects import ObjectMeta, RUNNING
from nos_tpu.serving import DiurnalTrace, ReplicaAutoscaler, ServingService
from nos_tpu.testing.factory import (
    admit_all, make_pod, make_slice_pod, make_tpu_node,
)
from nos_tpu.utils.pod_util import (
    class_tier, tier_rank, workload_class, workload_tier,
)


def serving_labels(extra: dict | None = None) -> dict:
    labels = {C.LABEL_TIER: C.TIER_SERVING}
    labels.update(extra or {})
    return labels


class TestTierContract:
    def test_workload_tier_defaults_to_batch(self):
        assert workload_tier(make_pod()) == C.TIER_BATCH
        assert workload_tier(make_pod(
            labels={C.LABEL_TIER: "gold"})) == C.TIER_BATCH

    def test_workload_tier_reads_the_label(self):
        assert workload_tier(make_pod(
            labels={C.LABEL_TIER: C.TIER_SERVING})) == C.TIER_SERVING
        assert workload_tier(make_pod(
            labels={C.LABEL_TIER: C.TIER_BEST_EFFORT})) \
            == C.TIER_BEST_EFFORT

    def test_tier_rank_orders_serving_first(self):
        ranks = [tier_rank(make_pod(labels={C.LABEL_TIER: t}))
                 for t in (C.TIER_SERVING, C.TIER_BATCH,
                           C.TIER_BEST_EFFORT)]
        assert ranks == sorted(ranks) and len(set(ranks)) == 3

    def test_workload_class_tiers(self):
        serving = make_slice_pod("1x1", 1, labels=serving_labels())
        assert workload_class(serving) == "serving"
        be = make_slice_pod("2x2", 1,
                            labels={C.LABEL_TIER: C.TIER_BEST_EFFORT})
        assert workload_class(be) == "be-slice-2x2"
        assert workload_class(make_slice_pod("2x2", 1)) == "slice-2x2"

    def test_class_tier_inverse(self):
        assert class_tier("serving") == C.TIER_SERVING
        assert class_tier("be-slice-2x2") == C.TIER_BEST_EFFORT
        assert class_tier("slice-2x2") == C.TIER_BATCH
        assert class_tier("ts-8") == C.TIER_BATCH


def carved_node(name: str, units: int = 8):
    """A host with `units` pre-carved 1x1 slices (no agents needed)."""
    return make_tpu_node(name, pod_id="pod-0", host_index=0,
                         status_geometry={"free": {"1x1": units}})


class TestTieredScheduling:
    def test_serving_scheduled_first_under_contention(self):
        """Three pods, one per tier, equal priority, two free units:
        serving and batch bind; best-effort waits — regardless of
        creation order."""
        api = APIServer()
        api.create(KIND_NODE, carved_node("host-0", units=2))
        scheduler = build_scheduler(api)
        # created WORST tier first: creation order must not win
        api.create(KIND_POD, make_slice_pod(
            "1x1", 1, name="be",
            labels={C.LABEL_TIER: C.TIER_BEST_EFFORT},
            creation_timestamp=1.0))
        api.create(KIND_POD, make_slice_pod(
            "1x1", 1, name="batch", creation_timestamp=2.0))
        api.create(KIND_POD, make_slice_pod(
            "1x1", 1, name="serve", labels=serving_labels(),
            creation_timestamp=3.0))
        assert scheduler.run_cycle() == 2
        bound = {p.metadata.name: bool(p.spec.node_name)
                 for p in api.list(KIND_POD)}
        assert bound == {"serve": True, "batch": True, "be": False}

    def test_serving_outranks_higher_priority_batch(self):
        api = APIServer()
        api.create(KIND_NODE, carved_node("host-0", units=1))
        scheduler = build_scheduler(api)
        api.create(KIND_POD, make_slice_pod(
            "1x1", 1, name="batch", priority=100,
            creation_timestamp=1.0))
        api.create(KIND_POD, make_slice_pod(
            "1x1", 1, name="serve", labels=serving_labels(),
            creation_timestamp=2.0))
        scheduler.run_cycle()
        serve = next(p for p in api.list(KIND_POD)
                     if p.metadata.name == "serve")
        assert serve.spec.node_name


def quota(api, ns: str, min_gb: float, max_gb: float) -> None:
    api.create(KIND_ELASTIC_QUOTA, ElasticQuota(
        metadata=ObjectMeta(name=ns, namespace=ns),
        spec=ElasticQuotaSpec(
            min={C.RESOURCE_TPU_MEMORY: min_gb},
            max={C.RESOURCE_TPU_MEMORY: max_gb})))


class TestServingNeverVictim:
    def _cluster(self):
        api = APIServer()
        install_quota_webhooks(api)
        api.create(KIND_NODE, carved_node("host-0", units=2))
        quota(api, "serve", 16.0, 64.0)
        quota(api, "batch", 16.0, 64.0)
        scheduler = build_scheduler(api)
        return api, scheduler

    def test_over_quota_batch_is_preempted_for_serving(self):
        api, scheduler = self._cluster()
        # both units held by batch; one borrowing over its 1-chip min
        for i, cap in enumerate([C.CAPACITY_IN_QUOTA,
                                 C.CAPACITY_OVER_QUOTA]):
            api.create(KIND_POD, make_slice_pod(
                "1x1", 1, name=f"b{i}", namespace="batch",
                node_name="host-0", phase=RUNNING,
                labels={C.LABEL_CAPACITY: cap},
                creation_timestamp=1.0))
        api.create(KIND_POD, make_slice_pod(
            "1x1", 1, name="replica", namespace="serve",
            labels=serving_labels(), creation_timestamp=2.0))
        scheduler.run_cycle()
        names = {p.metadata.name for p in api.list(KIND_POD)}
        assert "b1" not in names, "over-quota borrower not reclaimed"
        assert "b0" in names
        # same cycle: the replica bound into the synchronously freed
        # unit (post-preemption retry) — no nomination window for a
        # lower tier to race into
        replica = next(p for p in api.list(KIND_POD)
                       if p.metadata.name == "replica")
        assert replica.spec.node_name == "host-0"

    def test_in_quota_serving_is_never_selected_as_victim(self):
        """A high-priority batch preemptor in the same namespace could
        take any lower-priority pod under pre-tier semantics; in-quota
        serving pods are excluded from every victim branch."""
        api = APIServer()
        install_quota_webhooks(api)
        api.create(KIND_NODE, carved_node("host-0", units=1))
        quota(api, "team", 8.0, 64.0)
        api.create(KIND_POD, make_slice_pod(
            "1x1", 1, name="replica", namespace="team",
            node_name="host-0", phase=RUNNING, priority=0,
            labels=serving_labels(
                {C.LABEL_CAPACITY: C.CAPACITY_IN_QUOTA}),
            creation_timestamp=1.0))
        scheduler = build_scheduler(api)
        api.create(KIND_POD, make_slice_pod(
            "1x1", 1, name="train", namespace="team", priority=100,
            creation_timestamp=2.0))
        scheduler.run_cycle()
        names = {p.metadata.name for p in api.list(KIND_POD)}
        assert "replica" in names, "serving pod was evicted"
        train = next(p for p in api.list(KIND_POD)
                     if p.metadata.name == "train")
        assert not train.spec.node_name

    def test_over_quota_serving_borrower_is_still_reclaimable(self):
        """The quota guarantee outranks the tier shield: a serving
        namespace borrowing beyond its min can be reclaimed by a
        lender claiming its own min — otherwise a self-applied tier
        label would capture borrowed capacity forever."""
        api = APIServer()
        install_quota_webhooks(api)
        api.create(KIND_NODE, carved_node("host-0", units=1))
        quota(api, "serve", 8.0, 64.0)       # min < the replica's 16GB
        quota(api, "lender", 16.0, 64.0)
        api.create(KIND_POD, make_slice_pod(
            "1x1", 1, name="replica", namespace="serve",
            node_name="host-0", phase=RUNNING,
            labels=serving_labels(
                {C.LABEL_CAPACITY: C.CAPACITY_OVER_QUOTA}),
            creation_timestamp=1.0))
        scheduler = build_scheduler(api)
        api.create(KIND_POD, make_slice_pod(
            "1x1", 1, name="claim", namespace="lender",
            creation_timestamp=2.0))
        scheduler.run_cycle()
        names = {p.metadata.name for p in api.list(KIND_POD)}
        assert "replica" not in names, \
            "over-quota serving borrower was not reclaimable"
        claim = next(p for p in api.list(KIND_POD)
                     if p.metadata.name == "claim")
        assert claim.spec.node_name == "host-0"

    def test_best_effort_victims_go_before_batch(self):
        """Tier-ordered victim walk: with a best-effort and a batch
        borrower both evictable, the scavenger dies first."""
        api = APIServer()
        install_quota_webhooks(api)
        api.create(KIND_NODE, carved_node("host-0", units=2))
        quota(api, "serve", 16.0, 64.0)
        quota(api, "batch", 8.0, 64.0)
        quota(api, "scrap", 8.0, 64.0)
        api.create(KIND_POD, make_slice_pod(
            "1x1", 1, name="batchpod", namespace="batch",
            node_name="host-0", phase=RUNNING,
            labels={C.LABEL_CAPACITY: C.CAPACITY_OVER_QUOTA},
            creation_timestamp=1.0))
        api.create(KIND_POD, make_slice_pod(
            "1x1", 1, name="scrappod", namespace="scrap",
            node_name="host-0", phase=RUNNING,
            labels={C.LABEL_TIER: C.TIER_BEST_EFFORT,
                    C.LABEL_CAPACITY: C.CAPACITY_OVER_QUOTA},
            creation_timestamp=1.0))
        scheduler = build_scheduler(api)
        api.create(KIND_POD, make_slice_pod(
            "1x1", 1, name="replica", namespace="serve",
            labels=serving_labels(), creation_timestamp=2.0))
        scheduler.run_cycle()
        names = {p.metadata.name for p in api.list(KIND_POD)}
        assert "scrappod" not in names, "best-effort spared over batch"
        assert "batchpod" in names


class TestBurstE2E:
    @pytest.mark.usefixtures("lock_discipline")
    def test_burst_trace_zero_serving_preemptions(self, lock_discipline):
        """Mini end-to-end burst: batch soaks 16 pre-carved units
        over-quota, a burst scales the service 2 -> 6 replicas through
        the REAL scheduler; every scale-up binds by preempting batch
        borrowers, no serving pod is ever a victim, and tier ordering
        holds (best-effort stays pending throughout)."""
        from nos_tpu.controllers.elasticquota.controller import (
            ElasticQuotaReconciler,
        )
        from nos_tpu.quota import TPUResourceCalculator
        from nos_tpu.scheduler.capacityscheduling import CapacityScheduling
        from nos_tpu.testing.lockcheck import guard_state

        now = [0.0]
        api = APIServer()
        install_quota_webhooks(api)
        for h in range(2):
            api.create(KIND_NODE, carved_node(f"host-{h}", units=8))
        # serve's guaranteed min covers the full band; batch's min sits
        # below its steady-state usage so its fillers run over-quota
        quota(api, "serve", 96.0, 128.0)
        quota(api, "batch", 144.0, 256.0)
        quota(api, "scrap", 16.0, 256.0)
        calc = TPUResourceCalculator(16, chips_per_host=8)
        reconciler = ElasticQuotaReconciler(api, calc)
        scheduler = build_scheduler(api, 16, shard_chips_per_host=8,
                                    preempt_budget_per_cycle=4,
                                    clock=lambda: now[0])
        svc = ServingService(name="chat", namespace="serve",
                             slice_shape="1x1", min_replicas=2,
                             max_replicas=6,
                             target_load_per_replica=8.0,
                             scale_up_cooldown_s=0.0,
                             scale_down_cooldown_s=5.0)
        autoscaler = ReplicaAutoscaler(api, [svc],
                                       clock=lambda: now[0])
        guard_state(autoscaler, lock_discipline, name="autoscaler")
        capacity = next(p for p in scheduler._framework.plugins
                        if isinstance(p, CapacityScheduling))
        victims_by_tier: dict[str, int] = {}

        def on_preempt(preemptor, victims):
            for v in victims:
                t = workload_tier(v)
                victims_by_tier[t] = victims_by_tier.get(t, 0) + 1
        capacity.on_preempt = on_preempt

        # batch fills every unit; two best-effort scavengers — ONE unit
        # of guaranteed min between them (tier ordering governs the
        # queue and the victim walk; a namespace's guaranteed quota min
        # is still honored, so exactly one may claim capacity)
        for i in range(16):
            api.create(KIND_POD, make_slice_pod(
                "1x1", 1, name=f"fill-{i}", namespace="batch",
                creation_timestamp=0.0))
        for i in range(2):
            api.create(KIND_POD, make_slice_pod(
                "1x1", 1, name=f"scavenge-{i}", namespace="scrap",
                labels={C.LABEL_TIER: C.TIER_BEST_EFFORT},
                creation_timestamp=0.0))

        def load(t: float) -> float:
            return 40.0 if 1.0 <= t < 2.0 else 10.0

        serving_latencies = []
        seen: set[str] = set()
        for tick in range(60):
            now[0] += 0.05
            for p in api.list(KIND_POD, namespace="serve"):
                api.patch(KIND_POD, p.metadata.name, "serve",
                          mutate=lambda q, t=load(now[0]): q.metadata.
                          annotations.__setitem__(
                              C.ANNOT_SERVING_LOAD, str(t / max(
                                  1, len(api.list(
                                      KIND_POD, namespace="serve"))))))
            autoscaler.reconcile()
            scheduler.run_cycle()
            admit_all(api)      # kubelet-phase sim: bound -> Running
            reconciler.reconcile_all()
            for p in api.list(KIND_POD, namespace="serve"):
                if p.spec.node_name and p.metadata.name not in seen:
                    seen.add(p.metadata.name)
                    serving_latencies.append(
                        now[0] - p.metadata.creation_timestamp)

        assert victims_by_tier.get(C.TIER_SERVING, 0) == 0, \
            f"serving pods preempted: {victims_by_tier}"
        assert victims_by_tier, "burst never exercised preemption"
        replicas = [p for p in api.list(KIND_POD, namespace="serve")
                    if p.spec.node_name]
        assert len(seen) >= 5, f"burst never scaled up: {len(seen)}"
        assert len(replicas) >= 2
        # every post-burst scale-up bound within two cycles (100 ms)
        assert serving_latencies and max(serving_latencies) <= 0.101, \
            f"serving bind latencies: {sorted(serving_latencies)[-3:]}"


class TestPendingAgeGauge:
    def _gauges(self):
        snap = REGISTRY.snapshot()
        return (snap.get("nos_tpu_schedule_pending_pods", {}),
                snap.get("nos_tpu_schedule_pending_age_seconds", {}))

    def test_restarted_scheduler_resets_stale_class_gauges(self):
        """Regression: the reset set must come from the registry's own
        series, not per-instance memory — a class published by a PRIOR
        scheduler (or before a publish skipped by a raising cycle) that
        is empty now must read 0, not its stale max age."""
        REGISTRY.set("nos_tpu_schedule_pending_pods", 3.0,
                     labels={"class": "slice-stale-test"})
        REGISTRY.set("nos_tpu_schedule_pending_age_seconds", 37.5,
                     labels={"class": "slice-stale-test"})
        api = APIServer()
        api.create(KIND_NODE, carved_node("host-0"))
        scheduler = build_scheduler(api, clock=lambda: 100.0)
        scheduler.run_cycle()       # fresh instance, empty queue
        pods, age = self._gauges()
        assert pods["class=slice-stale-test"] == 0.0
        assert age["class=slice-stale-test"] == 0.0

    def test_empty_and_refill_within_one_cycle_reports_live_age(self):
        """A class that drains and refills inside one cycle must report
        the LIVE queue's age (the fresh pod's), never carry the drained
        pod's larger age forward."""
        now = [100.0]
        api = APIServer()
        api.create(KIND_NODE, carved_node("host-0", units=1))
        scheduler = build_scheduler(api, clock=lambda: now[0])
        api.create(KIND_POD, make_slice_pod(
            "1x1", 1, name="old", creation_timestamp=40.0))
        scheduler.run_cycle()       # old (age 60) binds...
        old = next(p for p in api.list(KIND_POD)
                   if p.metadata.name == "old")
        assert old.spec.node_name
        # ...and a FRESH pod of the same class arrives before the next
        # cycle's publish
        api.create(KIND_POD, make_slice_pod(
            "1x1", 1, name="fresh", creation_timestamp=99.0))
        now[0] = 101.0
        scheduler.run_cycle()
        _, age = self._gauges()
        assert age["class=slice-1x1"] == pytest.approx(2.0)


class TestObsTierSurfaces:
    def _payload(self):
        from nos_tpu.kube.serialize import dump_state

        api = APIServer()
        api.create(KIND_NODE, carved_node("host-0", units=1))
        api.create(KIND_POD, make_slice_pod(
            "1x1", 1, name="r0", namespace="serve",
            labels=serving_labels()))
        api.create(KIND_POD, make_slice_pod("2x2", 1, name="b0"))
        return {
            "state": dump_state(api),
            "slo": {
                "fast_window_s": 10.0, "slow_window_s": 40.0,
                "burn_threshold": 2.0, "objectives": [],
                "verdicts": [
                    {"objective": "serving-schedule-latency",
                     "metric": "nos_tpu_schedule_latency_seconds",
                     "class": "serving", "target": 0.1, "value": 0.19,
                     "burn_fast": 9.0, "burn_slow": 8.0,
                     "budget_remaining": -7.0, "breached": True},
                    {"objective": "schedule-latency",
                     "metric": "nos_tpu_schedule_latency_seconds",
                     "class": "slice-2x2", "target": 60.0,
                     "value": 12.0, "burn_fast": 0.1,
                     "burn_slow": 0.1, "budget_remaining": 0.9,
                     "breached": False},
                ],
            },
            "journal": [
                {"category": "pod-rejected", "subject": "serve/r0",
                 "attrs": {"class": "serving",
                           "plugin": "NodeResourcesFit",
                           "reason": "", "message": "no fit"}},
            ],
        }

    def test_top_prints_per_tier_rows(self, capsys):
        from nos_tpu.obs.__main__ import cmd_top

        assert cmd_top(self._payload()) == 0
        out = capsys.readouterr().out
        lines = out.splitlines()
        header = next(i for i, line in enumerate(lines)
                      if line.startswith("tier"))
        tier_rows = {line.split()[0]: line
                     for line in lines[header + 1:header + 4]}
        assert set(tier_rows) == {"serving", "batch", "best-effort"}
        assert "1" in tier_rows["serving"]   # one pending serving pod
        assert "BREACH" in tier_rows["serving"]
        assert "0.190" in tier_rows["serving"]      # p99 value
        assert "0.9" in tier_rows["batch"]          # budget remaining
        assert "BREACH" not in tier_rows["batch"]

    def test_slo_joins_serving_breach_to_rejecting_plugin(self, capsys):
        from nos_tpu.obs.__main__ import cmd_slo

        assert cmd_slo(self._payload()) == 0
        out = capsys.readouterr().out
        assert "rejecting plugin for class serving: NodeResourcesFit" \
            in out


class TestTrace:
    def test_same_seed_same_curve(self):
        a = DiurnalTrace(seed=3)
        b = DiurnalTrace(seed=3)
        assert [a.load_at(t * 0.5) for t in range(200)] \
            == [b.load_at(t * 0.5) for t in range(200)]

    def test_diurnal_swing_and_bursts(self):
        t = DiurnalTrace(seed=1, base_users=100_000.0,
                         peak_users=1_000_000.0, period_s=100.0,
                         burst_rate_per_s=0.0)
        loads = [t.load_at(x) for x in range(0, 100)]
        assert max(loads) > 5 * min(loads)      # real diurnal swing
        assert all(x > 0 for x in loads)
        bursty = DiurnalTrace(seed=1, burst_rate_per_s=0.5,
                              burst_multiplier=4.0)
        assert any(bursty.burst_multiplier_at(float(x)) > 1.0
                   for x in range(60))
        assert all(bursty.burst_multiplier_at(float(x)) >= 1.0
                   for x in range(60))

    def test_validation(self):
        with pytest.raises(ValueError):
            DiurnalTrace(peak_users=1.0, base_users=2.0)
        with pytest.raises(ValueError):
            DiurnalTrace(burst_multiplier=0.5)
