"""noslint v3 determinism certification (nos_tpu/analysis/rules_det.py)
and the dual-run nosdiff harness (nos_tpu/analysis/determinism.py).

Per-rule fixtures follow tests/test_analysis.py's pattern: a violating
snippet, a clean snippet, and a pragma-suppressed snippet through
``lint_source`` — rule semantics pinned independently of the tree's
current state (the tree-clean gate itself lives in test_analysis.py
and now sweeps N011/N012 too, since default_rules() includes them).

The nosdiff golden run executes the real benchmark trace (bench_plan's
64-host v5e-256 cluster) in child interpreters across a reduced
PYTHONHASHSEED x plan_workers matrix and asserts byte-identical
decision journals — the full {0,1,random} x {1,4} matrix is the
check.sh gate; tier-1 keeps a 2x2 corner of it so a determinism
regression fails fast with the first differing record in the message.
"""

from __future__ import annotations

import json

import pytest

from nos_tpu.analysis import lint_source
from nos_tpu.analysis.determinism import (
    _first_divergence, run_matrix, run_trace,
)
from nos_tpu.analysis.rules_det import (
    InvalidationProtocol, UnorderedIterationHazard,
)
from nos_tpu.obs.journal import (
    DecisionJournal, JournalCapture, capture_records, get_journal,
    record, set_journal,
)

pytestmark = pytest.mark.analysis

# In-scope placement for N011 (the decision directories).
SCHED = "nos_tpu/scheduler/fixture.py"


def rules_of(v):
    return [x.rule for x in v]


# ---------------------------------------------------------------------------
# N011: unordered iteration flowing into decisions
# ---------------------------------------------------------------------------

class TestN011:
    def test_flags_set_iteration_into_order_sensitive_sinks(self):
        src = (
            "def f(xs: set):\n"
            "    out = []\n"
            "    for x in xs:\n"
            "        out.append(x)\n"
            "    return out\n"
            "\n"
            "def g(nodes: set):\n"
            "    return next(iter(nodes))\n"
            "\n"
            "def h(tainted: frozenset):\n"
            "    return [x for x in tainted]\n"
        )
        v = lint_source(src, [UnorderedIterationHazard()], relpath=SCHED)
        assert rules_of(v) == ["N011", "N011", "N011"]
        assert [x.line for x in v] == [3, 8, 11]

    def test_keyed_min_ties_break_in_hash_order(self):
        # min(xs) uses the elements' total order — deterministic; a key
        # function can TIE, and ties return the first element visited
        src = ("def f(nodes: set):\n"
               "    return min(nodes, key=len)\n")
        v = lint_source(src, [UnorderedIterationHazard()], relpath=SCHED)
        assert rules_of(v) == ["N011"]

    def test_blessed_orders_and_insensitive_consumers_pass(self):
        src = (
            "def f(xs: set):\n"
            "    out = []\n"
            "    for x in sorted(xs):\n"
            "        out.append(x)\n"
            "    return out\n"
            "\n"
            "def g(nodes: set):\n"
            "    return min(nodes)\n"
            "\n"
            "def h(xs: set):\n"
            "    return len(xs)\n"
            "\n"
            "def commutes(xs: set):\n"
            "    total = 0\n"
            "    for x in xs:\n"
            "        total += x\n"
            "    return total\n"
        )
        assert lint_source(src, [UnorderedIterationHazard()],
                           relpath=SCHED) == []

    def test_pragma_with_reason_suppresses(self):
        src = (
            "def f(xs: set):\n"
            "    out = []\n"
            "    for x in xs:  # noslint: N011 — audited: singleton\n"
            "        out.append(x)\n"
            "    return out\n"
        )
        assert lint_source(src, [UnorderedIterationHazard()],
                           relpath=SCHED) == []

    def test_out_of_scope_directories_are_exempt(self):
        src = ("def f(xs: set):\n"
               "    out = []\n"
               "    for x in xs:\n"
               "        out.append(x)\n")
        assert lint_source(src, [UnorderedIterationHazard()],
                           relpath="nos_tpu/obs/fixture.py") == []


# ---------------------------------------------------------------------------
# N012: cross-cycle cached state must emit its invalidation event
# ---------------------------------------------------------------------------

_N012_CLASS = (
    "from nos_tpu.utils.guards import invalidated_by\n"
    "\n"
    "@invalidated_by('_bump', '_idx')\n"
    "class C:\n"
    "    def __init__(self):\n"
    "        self._idx = {}\n"
    "        self._gen = 0\n"
    "\n"
)


class TestN012:
    def test_mutation_without_emission_convicted(self):
        src = _N012_CLASS + (
            "    def mutate(self, k, v):\n"
            "        self._idx[k] = v\n"
            "\n"
            "    def _bump(self):\n"
            "        self._gen += 1\n"
        )
        v = lint_source(src, [InvalidationProtocol()], relpath=SCHED)
        assert rules_of(v) == ["N012"]
        assert "_bump" in v[0].message

    def test_post_dominating_emission_passes(self):
        src = _N012_CLASS + (
            "    def mutate(self, k, v):\n"
            "        self._idx[k] = v\n"
            "        self._bump()\n"
            "\n"
            "    def _bump(self):\n"
            "        self._gen += 1\n"
        )
        assert lint_source(src, [InvalidationProtocol()],
                           relpath=SCHED) == []

    def test_counter_bump_emission_form_passes(self):
        # ClusterSnapshot's form: the event is an attribute the mutator
        # writes (self._gen += 1), not a method call
        src = (
            "from nos_tpu.utils.guards import invalidated_by\n"
            "\n"
            "@invalidated_by('_gen', '_idx')\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._idx = {}\n"
            "        self._gen = 0\n"
            "\n"
            "    def mutate(self, k, v):\n"
            "        self._idx[k] = v\n"
            "        self._gen += 1\n"
        )
        assert lint_source(src, [InvalidationProtocol()],
                           relpath=SCHED) == []

    def test_whole_field_rebind_is_exempt(self):
        # invalidate-by-rebuild: replacing the container IS the
        # invalidation (Scheduler._class_scan_cache = {})
        src = _N012_CLASS + (
            "    def reset(self):\n"
            "        self._idx = {}\n"
            "\n"
            "    def _bump(self):\n"
            "        self._gen += 1\n"
        )
        assert lint_source(src, [InvalidationProtocol()],
                           relpath=SCHED) == []

    def test_pragma_with_reason_suppresses(self):
        src = _N012_CLASS + (
            "    def mutate(self, k, v):\n"
            "        self._idx[k] = v  "
            "# noslint: N012 — caller bumps, audited\n"
            "\n"
            "    def _bump(self):\n"
            "        self._gen += 1\n"
        )
        assert lint_source(src, [InvalidationProtocol()],
                           relpath=SCHED) == []

    def test_declared_carriers_stay_declared(self):
        # The REQUIRED registry names the real cross-cycle cache
        # carriers; importing them must show live declarations (the
        # static sweep separately proves their mutators emit).
        from nos_tpu.partitioning.core.snapshot import ClusterSnapshot
        from nos_tpu.scheduler.cache import SchedulerCache
        from nos_tpu.scheduler.scheduler import Scheduler
        from nos_tpu.utils.guards import invalidated_fields

        assert invalidated_fields(SchedulerCache)["_node_objs"] \
            == "_bump_locked"
        assert invalidated_fields(ClusterSnapshot)["_nodes"] \
            == "_mutation_gen"
        assert invalidated_fields(Scheduler)["_cycle_lister_cache"] \
            == "_invalidate_scans"
        # the window-busy map rides its own event (ISSUE 18 satellite):
        # every in-place flip must route through _mark_busy
        assert invalidated_fields(Scheduler)["_busy_map_cache"] \
            == "_mark_busy"

    def test_busy_map_mutation_off_the_event_convicted(self):
        # Conviction fixture mirroring Scheduler's stacked declaration:
        # an in-place write to the window-busy map that does not ride
        # _mark_busy must be an N012 verdict, with the event named.
        src = (
            "from nos_tpu.utils.guards import invalidated_by\n"
            "\n"
            "@invalidated_by('_invalidate', '_lister')\n"
            "@invalidated_by('_mark_busy', '_busy_map_cache')\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._busy_map_cache = {}\n"
            "        self._lister = None\n"
            "\n"
            "    def bind(self, key):\n"
            "        self._busy_map_cache[key] = True\n"
            "\n"
            "    def _mark_busy(self, key):\n"
            "        self._busy_map_cache[key] = True\n"
            "\n"
            "    def _invalidate(self):\n"
            "        pass\n"
        )
        v = lint_source(src, [InvalidationProtocol()], relpath=SCHED)
        assert rules_of(v) == ["N012"]
        assert "_mark_busy" in v[0].message

    def test_busy_map_mutation_riding_the_event_passes(self):
        src = (
            "from nos_tpu.utils.guards import invalidated_by\n"
            "\n"
            "@invalidated_by('_mark_busy', '_busy_map_cache')\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._busy_map_cache = {}\n"
            "\n"
            "    def bind(self, key):\n"
            "        self._mark_busy(key)\n"
            "\n"
            "    def _mark_busy(self, key):\n"
            "        self._busy_map_cache[key] = True\n"
        )
        assert lint_source(src, [InvalidationProtocol()],
                           relpath=SCHED) == []

    def test_carrier_rejects_non_string_names(self):
        # both checkers read the table as attribute names; a non-string
        # entry is unresolvable for them, so it must fail at declaration
        from nos_tpu.utils.guards import guarded_by, invalidated_by
        with pytest.raises(ValueError):
            invalidated_by(123, "_f")       # type: ignore[arg-type]
        with pytest.raises(ValueError):
            invalidated_by("_bump", b"_f")  # type: ignore[arg-type]
        with pytest.raises(ValueError):
            guarded_by("_lock", 7)          # type: ignore[arg-type]

    def test_registry_covers_required_modules(self):
        required = {(m, c) for m, c, _ in InvalidationProtocol.REQUIRED}
        assert ("nos_tpu.scheduler.cache", "SchedulerCache") in required
        assert ("nos_tpu.scheduler.scheduler", "Scheduler") in required
        assert ("nos_tpu.partitioning.core.snapshot",
                "ClusterSnapshot") in required


# ---------------------------------------------------------------------------
# Journal capture/replay (the plan_workers determinism substrate)
# ---------------------------------------------------------------------------

class TestJournalCapture:
    def test_capture_buffers_and_replay_restamps(self):
        prev = set_journal(DecisionJournal(clock=lambda: 42.0))
        try:
            capture = JournalCapture()
            with capture_records(capture):
                record("plan-node-committed", "host-1", placed=3)
                record("plan-node-reverted", "host-2")
            # nothing reached the ambient journal yet
            assert get_journal().events() == []
            capture.replay()
            events = get_journal().events()
            assert [(r.category, r.subject) for r in events] == [
                ("plan-node-committed", "host-1"),
                ("plan-node-reverted", "host-2"),
            ]
            # seq/ts are the AMBIENT journal's — replay is
            # indistinguishable from inline recording
            assert [r.seq for r in events] == [1, 2]
            assert all(r.ts == 42.0 for r in events)
        finally:
            set_journal(prev)

    def test_capture_is_context_scoped(self):
        prev = set_journal(DecisionJournal())
        try:
            with capture_records(JournalCapture()):
                record("pod-bound", "ns/captured")
            record("pod-bound", "ns/direct")
            assert [r.subject for r in get_journal().events()] \
                == ["ns/direct"]
        finally:
            set_journal(prev)


# ---------------------------------------------------------------------------
# nosdiff: the dual-run harness
# ---------------------------------------------------------------------------

class TestNosdiff:
    def test_run_trace_is_deterministic_in_process(self):
        # same interpreter, twice: everything except PYTHONHASHSEED —
        # which needs subprocesses — must already be pinned
        prev = set_journal(get_journal())
        try:
            first = run_trace(plan_workers=1, cycles=1)
            second = run_trace(plan_workers=1, cycles=1)
        finally:
            set_journal(prev)
        assert first == second
        assert len(first) > 50      # the trace actually decides things

    def test_golden_matrix_corner_byte_identical(self):
        # tier-1 corner of the full check.sh matrix: 2 seeds x sharded
        # workers x incremental on/off, one scheduler cycle; the
        # journals must byte-match — incremental off vs on is the
        # ISSUE 18 dirty-set equivalence anchor
        report = run_matrix(hash_seeds=("0", "random"),
                            plan_workers=(4,),
                            incremental=("on", "off"), cycles=1,
                            verbose=False)
        assert report.ok, "\n".join(report.failures)
        assert len(report.cells) == 4
        assert report.records > 50
        # the cells really ran under different interpreters/settings
        assert len({c.label for c in report.cells}) == 4
        assert {c.incremental for c in report.cells} == {"on", "off"}
        # output is canonical JSON lines
        line = report.cells[0].output.splitlines()[0]
        rec = json.loads(line)
        assert {"category", "subject", "seq", "ts"} <= set(rec)

    def test_first_divergence_reports_record_index(self):
        ref = b'{"a":1}\n{"a":2}\n'
        other = b'{"a":1}\n{"a":3}\n'
        msg = _first_divergence(ref, other)
        assert "record 2" in msg
        prefix = _first_divergence(ref, ref + b'{"a":4}\n')
        assert "prefix" in prefix
