"""Gang scheduling tests: all-or-nothing PodGroups + ICI topology pinning
(BASELINE config #4 shape: multi-host JAX job across one TPU pod)."""

from __future__ import annotations

from nos_tpu.api import constants as C
from nos_tpu.api.podgroup import PodGroup, PodGroupSpec
from nos_tpu.controllers.node_controller import NodeController
from nos_tpu.controllers.pod_controller import PodController
from nos_tpu.controllers.sliceagent.agent import SliceAgent
from nos_tpu.device.fake import FakePodResources, FakeTpuRuntime
from nos_tpu.kube.client import (
    APIServer, KIND_ELASTIC_QUOTA, KIND_NODE, KIND_POD, KIND_POD_GROUP,
)
from nos_tpu.kube.objects import ObjectMeta, RUNNING
from nos_tpu.partitioning.slicepart import SliceNodeInitializer
from nos_tpu.partitioning.slicepart.factory import new_slice_partitioner_controller
from nos_tpu.partitioning.state import ClusterState
from nos_tpu.quota import TPUResourceCalculator
from nos_tpu.scheduler.capacityscheduling import CapacityScheduling
from nos_tpu.scheduler.framework import Framework, NodeResourcesFit
from nos_tpu.scheduler.gang import TopologyFilter
from nos_tpu.scheduler.scheduler import Scheduler
from nos_tpu.testing.factory import (
    admit_all, make_node, make_pod, make_slice_pod, make_tpu_node,
)
from nos_tpu.topology import V5E


def make_cluster(*, hosts_per_pod: dict[str, int], chips: int = 8):
    api = APIServer()
    fw = Framework([NodeResourcesFit(), TopologyFilter(api)])
    i = 0
    for pod_id, n in hosts_per_pod.items():
        for h in range(n):
            api.create(KIND_NODE, make_node(
                f"host-{i}",
                labels={C.LABEL_POD_ID: pod_id, C.LABEL_CHIP_COUNT: str(chips)},
                allocatable={"cpu": 64.0, C.RESOURCE_TPU: float(chips)},
            ))
            i += 1
    return api, Scheduler(api, fw)


def gang_pod(name: str, gang: str, chips: int = 8, **kw):
    return make_pod(name=name, labels={C.LABEL_POD_GROUP: gang},
                    resources={C.RESOURCE_TPU: chips, "cpu": 1.0}, **kw)


def create_pod_group(api, name: str, min_member: int, mesh: str = "",
                     namespace: str = "default"):
    api.create(KIND_POD_GROUP, PodGroup(
        metadata=ObjectMeta(name=name, namespace=namespace),
        spec=PodGroupSpec(min_member=min_member, mesh=mesh)))


class TestGangAdmission:
    def test_gang_binds_atomically(self):
        api, sched = make_cluster(hosts_per_pod={"pod-a": 4})
        create_pod_group(api, "train", min_member=4)
        for i in range(4):
            api.create(KIND_POD, gang_pod(f"w-{i}", "train"))
        assert sched.run_cycle() == 4
        nodes = {api.get(KIND_POD, f"w-{i}", "default").spec.node_name
                 for i in range(4)}
        assert len(nodes) == 4  # one worker per host
        pg = api.get(KIND_POD_GROUP, "train", "default")
        assert pg.status.phase == "Scheduled" and pg.status.scheduled == 4

    def test_waits_for_min_member(self):
        api, sched = make_cluster(hosts_per_pod={"pod-a": 4})
        create_pod_group(api, "train", min_member=4)
        for i in range(3):
            api.create(KIND_POD, gang_pod(f"w-{i}", "train"))
        assert sched.run_cycle() == 0
        pod = api.get(KIND_POD, "w-0", "default")
        assert pod.is_unschedulable()
        # the straggler arrives -> whole gang binds
        api.create(KIND_POD, gang_pod("w-3", "train"))
        assert sched.run_cycle() == 4

    def test_no_partial_binding_when_gang_cannot_fit(self):
        api, sched = make_cluster(hosts_per_pod={"pod-a": 2})
        create_pod_group(api, "train", min_member=3)
        for i in range(3):
            api.create(KIND_POD, gang_pod(f"w-{i}", "train"))
        assert sched.run_cycle() == 0
        for i in range(3):
            assert api.get(KIND_POD, f"w-{i}", "default").spec.node_name == ""

    def test_reserve_failure_journals_gang_rejected(self):
        """The reserve-plugin rollback path is a terminal gang outcome
        like any other: it must journal GANG_REJECTED, or `explain` for
        a member of a previously-admitted gang reports the stale
        admission under the fresh rejection (review regression)."""
        from nos_tpu import obs
        from nos_tpu.obs import journal as J

        class RefuseReserve:
            name = "RefuseReserve"

            def reserve(self, state, pod, node_name):
                from nos_tpu.scheduler.framework import Status
                return Status.unschedulable("reserve ledger full")

            def unreserve(self, state, pod, node_name):
                pass

        api, default_sched = make_cluster(hosts_per_pod={"pod-a": 4})
        default_sched.close()   # replaced: reserve must be able to fail
        sched = Scheduler(api, Framework(
            [NodeResourcesFit(), TopologyFilter(api), RefuseReserve()]))
        create_pod_group(api, "train", min_member=4)
        for i in range(4):
            api.create(KIND_POD, gang_pod(f"w-{i}", "train"))
        journal = obs.DecisionJournal(maxlen=64)
        with obs.scoped(journal=journal):
            assert sched.run_cycle() == 0
        rejected = journal.events(category=J.GANG_REJECTED)
        assert rejected, [r.category for r in journal.events()]
        assert "reserve failed" in rejected[-1].attrs["message"]
        assert not journal.events(category=J.GANG_ADMITTED)

    def test_mixed_gang_and_singles(self):
        api, sched = make_cluster(hosts_per_pod={"pod-a": 3})
        create_pod_group(api, "train", min_member=2)
        api.create(KIND_POD, gang_pod("w-0", "train"))
        api.create(KIND_POD, gang_pod("w-1", "train"))
        api.create(KIND_POD, make_pod(
            name="single", resources={C.RESOURCE_TPU: 8}))
        assert sched.run_cycle() == 3


class TestTopologyPinning:
    def test_gang_lands_on_single_tpu_pod(self):
        # pod-a has spare hosts but only pod-b can hold the whole gang
        api, sched = make_cluster(hosts_per_pod={"pod-a": 2, "pod-b": 4})
        create_pod_group(api, "train", min_member=3)
        for i in range(3):
            api.create(KIND_POD, gang_pod(f"w-{i}", "train"))
        assert sched.run_cycle() == 3
        pods_of = set()
        for i in range(3):
            node = api.get(KIND_NODE, api.get(
                KIND_POD, f"w-{i}", "default").spec.node_name)
            pods_of.add(node.metadata.labels[C.LABEL_POD_ID])
        assert pods_of == {"pod-b"}

    def test_mesh_chip_requirement_rejects_small_pod(self):
        # mesh 4x8 = 32 chips; pod-a has 2 hosts x 8 = 16 chips
        api, sched = make_cluster(hosts_per_pod={"pod-a": 2})
        create_pod_group(api, "train", min_member=2, mesh="4x8")
        for i in range(2):
            api.create(KIND_POD, gang_pod(f"w-{i}", "train"))
        assert sched.run_cycle() == 0

    def test_mesh_fits_pod(self):
        # mesh 4x8 = 32 chips; pod-b has 4 hosts x 8 = 32 chips
        api, sched = make_cluster(hosts_per_pod={"pod-b": 4})
        create_pod_group(api, "train", min_member=4, mesh="4x8")
        for i in range(4):
            api.create(KIND_POD, gang_pod(f"w-{i}", "train"))
        assert sched.run_cycle() == 4


class TestGangWithPartitioner:
    def test_gang_triggers_repartition_then_binds(self):
        """Unschedulable gang feeds the partitioner its full demand; after
        the re-carve the gang binds atomically (BASELINE config #4 on one
        host group)."""
        api = APIServer()
        state = ClusterState()
        now = [0.0]
        NodeController(api, state, SliceNodeInitializer(api)).bind()
        PodController(api, state).bind()
        pc = new_slice_partitioner_controller(
            api, state, batch_idle_s=10.0, clock=lambda: now[0])
        pc.bind()
        agents = []
        for i in range(2):
            api.create(KIND_NODE, make_tpu_node(
                f"host-{i}", pod_id="pod-a", host_index=i))
            a = SliceAgent(api, f"host-{i}", FakeTpuRuntime(V5E),
                           FakePodResources())
            a.start()
            a.tick()
            agents.append(a)
        fw = Framework([NodeResourcesFit(), TopologyFilter(api)])
        sched = Scheduler(api, fw)
        create_pod_group(api, "fsdp", min_member=4)
        for i in range(4):
            api.create(KIND_POD, make_slice_pod(
                "2x2", 1, name=f"w-{i}",
                labels={C.LABEL_POD_GROUP: "fsdp"}))
        assert sched.run_cycle() == 0            # nothing advertised yet
        now[0] += 11.0
        assert pc.process_if_ready()
        for a in agents:
            a.tick()
        assert sched.run_cycle() == 4
        for a in agents:
            a.tick()  # kubelet-phase sim: agents admit the bound pods
        for i in range(4):
            assert api.get(KIND_POD, f"w-{i}", "default").status.phase == RUNNING


class TestGangRegressions:
    def test_gang_cannot_collectively_exceed_quota_max(self):
        """Each member alone fits under max, but the gang together exceeds
        it — nothing may bind (members must see gang-mates' usage)."""
        from nos_tpu.api.elasticquota import ElasticQuota, ElasticQuotaSpec
        api = APIServer()
        calc = TPUResourceCalculator(16)
        plugin = CapacityScheduling(calc)
        fw = Framework([NodeResourcesFit(), TopologyFilter(api), plugin])
        plugin.set_framework(fw)
        plugin.attach(api)
        for i in range(2):
            api.create(KIND_NODE, make_node(
                f"host-{i}", labels={C.LABEL_POD_ID: "pod-a"},
                allocatable={"cpu": 64.0, C.RESOURCE_TPU: 8.0,
                             C.RESOURCE_TPU_MEMORY: 128.0}))
        api.create(KIND_ELASTIC_QUOTA, ElasticQuota(
            metadata=ObjectMeta(name="eq-a", namespace="ns-a"),
            spec=ElasticQuotaSpec(min={C.RESOURCE_TPU_MEMORY: 256},
                                  max={C.RESOURCE_TPU_MEMORY: 128})))
        sched = Scheduler(api, fw)
        create_pod_group(api, "big", min_member=2, namespace="ns-a")
        for i in range(2):
            api.create(KIND_POD, gang_pod(f"w-{i}", "big", namespace="ns-a"))
        assert sched.run_cycle() == 0
        for i in range(2):
            assert api.get(KIND_POD, f"w-{i}", "ns-a").spec.node_name == ""

    def test_gang_never_spans_labeled_and_unlabeled_hosts(self):
        """The unlabeled-host candidate must use ONLY unlabeled hosts."""
        api, sched = make_cluster(hosts_per_pod={"pod-a": 2, "pod-b": 2})
        api.create(KIND_NODE, make_node(
            "bare-0", allocatable={"cpu": 64.0, C.RESOURCE_TPU: 8.0}))
        create_pod_group(api, "train", min_member=3)
        for i in range(3):
            api.create(KIND_POD, gang_pod(f"w-{i}", "train"))
        # no single domain holds 3 full hosts -> nothing binds
        assert sched.run_cycle() == 0

    def test_recreated_member_of_running_gang_schedules(self):
        """Running gang-mates count toward min_member, so a replacement
        worker schedules instead of deadlocking on 'waiting for members'."""
        api, sched = make_cluster(hosts_per_pod={"pod-a": 4})
        create_pod_group(api, "train", min_member=4)
        for i in range(4):
            api.create(KIND_POD, gang_pod(f"w-{i}", "train"))
        assert sched.run_cycle() == 4
        api.delete(KIND_POD, "w-3", "default")
        api.create(KIND_POD, gang_pod("w-3b", "train"))
        assert sched.run_cycle() == 1
        assert api.get(KIND_POD, "w-3b", "default").spec.node_name != ""


class TestGangPreemption:
    def test_whole_gang_evicted(self):
        api = APIServer()
        calc = TPUResourceCalculator(16)
        plugin = CapacityScheduling(calc)
        fw = Framework([NodeResourcesFit(), TopologyFilter(api), plugin])
        plugin.set_framework(fw)
        plugin.attach(api)
        for i in range(2):
            api.create(KIND_NODE, make_node(
                f"host-{i}", labels={C.LABEL_POD_ID: "pod-a"},
                allocatable={"cpu": 64.0, C.RESOURCE_TPU: 8.0,
                             C.RESOURCE_TPU_MEMORY: 128.0}))
        sched = Scheduler(api, fw)
        from nos_tpu.api.elasticquota import ElasticQuota, ElasticQuotaSpec
        api.create(KIND_ELASTIC_QUOTA, ElasticQuota(
            metadata=ObjectMeta(name="eq-a", namespace="ns-a"),
            spec=ElasticQuotaSpec(min={C.RESOURCE_TPU_MEMORY: 128})))
        api.create(KIND_ELASTIC_QUOTA, ElasticQuota(
            metadata=ObjectMeta(name="eq-b", namespace="ns-b"),
            spec=ElasticQuotaSpec(min={C.RESOURCE_TPU_MEMORY: 128})))
        # ns-b gang fills both hosts (borrowing half from ns-a)
        create_pod_group(api, "borrower", min_member=2, namespace="ns-b")
        for i in range(2):
            api.create(KIND_POD, gang_pod(
                f"b-{i}", "borrower", namespace="ns-b",
                creation_timestamp=float(i)))
        assert sched.run_cycle() == 2
        admit_all(api)  # kubelet-phase sim: victims must be Running
        from nos_tpu.controllers.elasticquota import ElasticQuotaReconciler
        ElasticQuotaReconciler(api, calc).reconcile_all()
        # ns-a claims its min back with one 8-chip pod: one member of the
        # gang is the victim, but the WHOLE gang must go
        api.create(KIND_POD, make_pod(
            name="a-0", namespace="ns-a",
            resources={C.RESOURCE_TPU: 8, "cpu": 1.0}))
        sched.run_cycle()
        assert api.list(KIND_POD, namespace="ns-b") == []
        sched.run_cycle()
        assert api.get(KIND_POD, "a-0", "ns-a").spec.node_name != ""

    def test_gang_aggregate_demand_preempts_when_members_fit_alone(self):
        """The stuck member's preemption runs WITH its gang-mates booked:
        if each member individually fits beside the victims (2 free chips
        per host, members need 2), a naive single-pod preemption would
        reprieve every victim and evict nothing — the gang's aggregate
        claim must drive the eviction."""
        api = APIServer()
        calc = TPUResourceCalculator(16)
        plugin = CapacityScheduling(calc)
        fw = Framework([NodeResourcesFit(), TopologyFilter(api), plugin])
        plugin.set_framework(fw)
        plugin.attach(api)
        for i in range(2):
            api.create(KIND_NODE, make_node(
                f"host-{i}", labels={C.LABEL_POD_ID: "pod-a"},
                allocatable={"cpu": 64.0, C.RESOURCE_TPU: 8.0,
                             C.RESOURCE_TPU_MEMORY: 128.0}))
        sched = Scheduler(api, fw)
        from nos_tpu.api.elasticquota import ElasticQuota, ElasticQuotaSpec
        api.create(KIND_ELASTIC_QUOTA, ElasticQuota(
            metadata=ObjectMeta(name="eq-a", namespace="ns-a"),
            spec=ElasticQuotaSpec(min={C.RESOURCE_TPU_MEMORY: 256})))
        api.create(KIND_ELASTIC_QUOTA, ElasticQuota(
            metadata=ObjectMeta(name="eq-b", namespace="ns-b"),
            spec=ElasticQuotaSpec(min={C.RESOURCE_TPU_MEMORY: 96})))
        # borrower gang: 6 chips on each host (2 chips stay free per host)
        create_pod_group(api, "borrower", min_member=2, namespace="ns-b")
        for i in range(2):
            api.create(KIND_POD, gang_pod(
                f"b-{i}", "borrower", chips=6, namespace="ns-b",
                creation_timestamp=float(i)))
        assert sched.run_cycle() == 2
        admit_all(api)  # kubelet-phase sim: victims must be Running
        from nos_tpu.controllers.elasticquota import ElasticQuotaReconciler
        ElasticQuotaReconciler(api, calc).reconcile_all()
        # claimant gang: 8 members x 2 chips = its full 256 GB min; any
        # single member fits in the 4 free chips, the gang does not
        create_pod_group(api, "claimant", min_member=8, namespace="ns-a")
        for i in range(8):
            api.create(KIND_POD, gang_pod(
                f"a-{i}", "claimant", chips=2, namespace="ns-a",
                creation_timestamp=float(10 + i)))
        sched.run_cycle()
        assert api.list(KIND_POD, namespace="ns-b") == []
        assert sched.run_cycle() == 8
        for i in range(8):
            assert api.get(KIND_POD, f"a-{i}", "ns-a").spec.node_name

    def test_infeasible_gang_does_not_evict(self):
        """A gang that cannot fit even with every evictable pod gone
        (here: 3 members x 8 chips on a 2-host cluster) must not evict
        over-quota victims cycle after cycle to no effect."""
        api = APIServer()
        calc = TPUResourceCalculator(16)
        plugin = CapacityScheduling(calc)
        fw = Framework([NodeResourcesFit(), TopologyFilter(api), plugin])
        plugin.set_framework(fw)
        plugin.attach(api)
        for i in range(2):
            api.create(KIND_NODE, make_node(
                f"host-{i}", labels={C.LABEL_POD_ID: "pod-a"},
                allocatable={"cpu": 64.0, C.RESOURCE_TPU: 8.0,
                             C.RESOURCE_TPU_MEMORY: 128.0}))
        sched = Scheduler(api, fw)
        from nos_tpu.api.elasticquota import ElasticQuota, ElasticQuotaSpec
        api.create(KIND_ELASTIC_QUOTA, ElasticQuota(
            metadata=ObjectMeta(name="eq-a", namespace="ns-a"),
            spec=ElasticQuotaSpec(min={C.RESOURCE_TPU_MEMORY: 384})))
        api.create(KIND_ELASTIC_QUOTA, ElasticQuota(
            metadata=ObjectMeta(name="eq-b", namespace="ns-b"),
            spec=ElasticQuotaSpec(min={C.RESOURCE_TPU_MEMORY: 128})))
        create_pod_group(api, "borrower", min_member=2, namespace="ns-b")
        for i in range(2):
            api.create(KIND_POD, gang_pod(
                f"b-{i}", "borrower", namespace="ns-b",
                creation_timestamp=float(i)))
        assert sched.run_cycle() == 2
        admit_all(api)  # kubelet-phase sim: victims must be Running
        from nos_tpu.controllers.elasticquota import ElasticQuotaReconciler
        ElasticQuotaReconciler(api, calc).reconcile_all()
        create_pod_group(api, "claimant", min_member=3, namespace="ns-a")
        for i in range(3):
            api.create(KIND_POD, gang_pod(
                f"a-{i}", "claimant", namespace="ns-a",
                creation_timestamp=float(10 + i)))
        for _ in range(3):  # several cycles: still no pointless eviction
            sched.run_cycle()
            assert len(api.list(KIND_POD, namespace="ns-b")) == 2

    def test_gang_preemptor_evicts_over_quota_gang(self):
        """Mirror of test_whole_gang_evicted with the GANG as preemptor:
        a gang claiming its guaranteed min must not starve behind an
        over-quota borrower gang (ADVICE r1: schedule_gang previously
        never ran PostFilter, so 'min is guaranteed' was not honored for
        multi-host jobs)."""
        api = APIServer()
        calc = TPUResourceCalculator(16)
        plugin = CapacityScheduling(calc)
        fw = Framework([NodeResourcesFit(), TopologyFilter(api), plugin])
        plugin.set_framework(fw)
        plugin.attach(api)
        for i in range(2):
            api.create(KIND_NODE, make_node(
                f"host-{i}", labels={C.LABEL_POD_ID: "pod-a"},
                allocatable={"cpu": 64.0, C.RESOURCE_TPU: 8.0,
                             C.RESOURCE_TPU_MEMORY: 128.0}))
        sched = Scheduler(api, fw)
        from nos_tpu.api.elasticquota import ElasticQuota, ElasticQuotaSpec
        api.create(KIND_ELASTIC_QUOTA, ElasticQuota(
            metadata=ObjectMeta(name="eq-a", namespace="ns-a"),
            spec=ElasticQuotaSpec(min={C.RESOURCE_TPU_MEMORY: 256})))
        api.create(KIND_ELASTIC_QUOTA, ElasticQuota(
            metadata=ObjectMeta(name="eq-b", namespace="ns-b"),
            spec=ElasticQuotaSpec(min={C.RESOURCE_TPU_MEMORY: 128})))
        # ns-b gang fills the cluster, borrowing beyond its min
        create_pod_group(api, "borrower", min_member=2, namespace="ns-b")
        for i in range(2):
            api.create(KIND_POD, gang_pod(
                f"b-{i}", "borrower", namespace="ns-b",
                creation_timestamp=float(i)))
        assert sched.run_cycle() == 2
        admit_all(api)  # kubelet-phase sim: victims must be Running
        from nos_tpu.controllers.elasticquota import ElasticQuotaReconciler
        ElasticQuotaReconciler(api, calc).reconcile_all()
        # ns-a's gang claims its min (2 x 8 chips = its entire guarantee)
        create_pod_group(api, "claimant", min_member=2, namespace="ns-a")
        for i in range(2):
            api.create(KIND_POD, gang_pod(
                f"a-{i}", "claimant", namespace="ns-a",
                creation_timestamp=float(10 + i)))
        sched.run_cycle()  # no fit -> gang preemption evicts borrower gang
        assert api.list(KIND_POD, namespace="ns-b") == []
        assert sched.run_cycle() == 2  # freed capacity: claimant binds
        admit_all(api)  # kubelet-phase sim
        for i in range(2):
            pod = api.get(KIND_POD, f"a-{i}", "ns-a")
            assert pod.spec.node_name
            assert pod.status.phase == RUNNING


class TestQuotaHeadOfLine:
    """A quota-rejected high-priority claimant blocks lower-priority
    same-namespace pods from eating the freed ledger headroom
    (scheduler.py quota HOL): without it, every chunk of quota that
    frees is taken by a small single before a big gang's requirement
    accumulates, starving the gang forever."""

    def test_lower_priority_single_defers_behind_quota_claim(self):
        from nos_tpu.api.elasticquota import ElasticQuota, ElasticQuotaSpec
        from nos_tpu.cmd.assembly import build_scheduler
        from nos_tpu.kube.client import KIND_ELASTIC_QUOTA

        api = APIServer()
        # plenty of physical room; quota max is the binding constraint
        for h in range(2):
            api.create(KIND_NODE, make_tpu_node(
                f"host-{h}", pod_id="pod-a", host_index=h,
                status_geometry={"free": {"2x2": 2}}))
        api.create(KIND_ELASTIC_QUOTA, ElasticQuota(
            metadata=ObjectMeta(name="q", namespace="team"),
            spec=ElasticQuotaSpec(
                min={C.RESOURCE_TPU_MEMORY: 32.0},
                max={C.RESOURCE_TPU_MEMORY: 128.0})))
        # idle lender: aggregate min 128, so the 128 GB claimant is
        # satisfiable (borrowing) — the unsatisfiability guard must NOT
        # trip
        api.create(KIND_ELASTIC_QUOTA, ElasticQuota(
            metadata=ObjectMeta(name="lender", namespace="lender"),
            spec=ElasticQuotaSpec(
                min={C.RESOURCE_TPU_MEMORY: 96.0})))
        sched = build_scheduler(api)
        # occupant holds 64 GB; big claimant (128 GB) is SATISFIABLE
        # (fits max + aggregate alone) but blocked while the occupant
        # lives
        api.create(KIND_POD, make_slice_pod(
            "2x2", 1, name="occ", namespace="team", node_name="host-0",
            phase=RUNNING))
        api.create(KIND_POD, make_slice_pod(
            "2x2", 2, name="big", namespace="team", priority=10))
        # small: 64 GB, fits max — but must defer behind the claimant
        api.create(KIND_POD, make_slice_pod(
            "2x2", 1, name="small", namespace="team", priority=0,
            creation_timestamp=1.0))
        sched.run_cycle()
        small = api.get(KIND_POD, "small", "team")
        assert not small.spec.node_name
        msgs = " ".join(c.message or "" for c in small.status.conditions)
        assert "higher-priority quota claim" in msgs
        # ecosystem-exact reason; the machine-readable class rides on
        # the nos.tpu/unschedulable-class label (ADVICE round 5)
        assert any(c.reason == "Unschedulable"
                   for c in small.status.conditions)
        assert small.unschedulable_class() == "quota-hol"
        # other namespaces are unaffected by team's HOL
        api.create(KIND_POD, make_slice_pod(
            "2x2", 1, name="other", namespace="free-ns",
            creation_timestamp=1.0))
        sched.run_cycle()
        assert api.get(KIND_POD, "other", "free-ns").spec.node_name

    def test_unsatisfiable_claimant_does_not_block_namespace(self):
        """A claimant whose request ALONE exceeds the namespace max can
        never schedule; it must not hold the head-of-line (permanent
        namespace starvation)."""
        from nos_tpu.api.elasticquota import ElasticQuota, ElasticQuotaSpec
        from nos_tpu.cmd.assembly import build_scheduler
        from nos_tpu.kube.client import KIND_ELASTIC_QUOTA

        api = APIServer()
        api.create(KIND_NODE, make_tpu_node(
            "host-0", pod_id="pod-a", host_index=0,
            status_geometry={"free": {"2x2": 2}}))
        api.create(KIND_ELASTIC_QUOTA, ElasticQuota(
            metadata=ObjectMeta(name="q", namespace="team"),
            spec=ElasticQuotaSpec(
                min={C.RESOURCE_TPU_MEMORY: 64.0},
                max={C.RESOURCE_TPU_MEMORY: 64.0})))
        sched = build_scheduler(api)
        api.create(KIND_POD, make_slice_pod(
            "2x2", 2, name="impossible", namespace="team", priority=10))
        api.create(KIND_POD, make_slice_pod(
            "2x2", 1, name="small", namespace="team", priority=0,
            creation_timestamp=1.0))
        sched.run_cycle()
        # the impossible claimant never binds; small proceeds anyway
        assert not api.get(KIND_POD, "impossible",
                           "team").spec.node_name
        assert api.get(KIND_POD, "small", "team").spec.node_name

    def test_equal_priority_not_deferred(self):
        from nos_tpu.api.elasticquota import ElasticQuota, ElasticQuotaSpec
        from nos_tpu.cmd.assembly import build_scheduler
        from nos_tpu.kube.client import KIND_ELASTIC_QUOTA

        api = APIServer()
        api.create(KIND_NODE, make_tpu_node(
            "host-0", pod_id="pod-a", host_index=0,
            status_geometry={"free": {"2x2": 2}}))
        api.create(KIND_ELASTIC_QUOTA, ElasticQuota(
            metadata=ObjectMeta(name="q", namespace="team"),
            spec=ElasticQuotaSpec(
                min={C.RESOURCE_TPU_MEMORY: 64.0},
                max={C.RESOURCE_TPU_MEMORY: 64.0})))
        sched = build_scheduler(api)
        api.create(KIND_POD, make_slice_pod(
            "2x2", 2, name="big", namespace="team", priority=0))
        api.create(KIND_POD, make_slice_pod(
            "2x2", 1, name="peer", namespace="team", priority=0,
            creation_timestamp=1.0))
        sched.run_cycle()
        # first-come at equal priority: the peer binds
        assert api.get(KIND_POD, "peer", "team").spec.node_name


class TestDrainPreemption:
    """Opt-in eviction of the last stragglers off a long-held drain
    window: the lease counts cycles; once past the threshold with the
    stragglers at or under the busy fraction, they are evicted
    (whole-gang amplified, PDB-respecting) so the window empties."""

    def _cluster(self, after=3, fraction=0.25):
        from nos_tpu.scheduler.framework import NodeResourcesFit
        from nos_tpu.scheduler.gang import TopologyFilter

        api = APIServer()
        fw = Framework([NodeResourcesFit(), TopologyFilter(api)])
        # 4 slice hosts in one domain, each advertising one 4x8 share +
        # a 1x1: a 4-host window for the gang, small slices for noise
        for h in range(4):
            api.create(KIND_NODE, make_tpu_node(
                f"host-{h}", pod_id="pod-a", host_index=h,
                status_geometry={"free": {"2x4": 1}}))
        sched = Scheduler(api, fw, drain_preempt_after_cycles=after,
                          drain_preempt_max_busy_fraction=fraction)
        return api, sched

    def _stuck_gang(self, api):
        create_pod_group(api, "big", min_member=4)
        for i in range(4):
            api.create(KIND_POD, make_slice_pod(
                "4x8", 1, name=f"big-{i}",
                labels={C.LABEL_POD_GROUP: "big"}))

    def test_straggler_evicted_after_threshold(self):
        api, sched = self._cluster(after=3)
        # a straggler single occupying one host's whole 2x4
        api.create(KIND_POD, make_slice_pod("2x4", 1, name="straggler",
                                            node_name="host-1",
                                            phase=RUNNING))
        self._stuck_gang(api)
        # cycle 1 earns the lease; cycle 2 adopts it into the drain
        # counter; cycles 3-4 accumulate; cycle 5 crosses the threshold
        for _ in range(4):
            sched.run_cycle()
            assert api.try_get(KIND_POD, "straggler", "default") is not None
        sched.run_cycle()       # threshold crossed: eviction
        assert api.try_get(KIND_POD, "straggler", "default") is None

    def test_too_busy_window_not_preempted(self):
        api, sched = self._cluster(after=2, fraction=0.25)
        # stragglers hold 16 of 32 chips: 50% > 25% — wait, don't evict
        for h in (0, 1):
            api.create(KIND_POD, make_slice_pod(
                "2x4", 1, name=f"busy-{h}", node_name=f"host-{h}",
                phase=RUNNING))
        self._stuck_gang(api)
        for _ in range(6):
            sched.run_cycle()
        assert api.try_get(KIND_POD, "busy-0", "default") is not None
        assert api.try_get(KIND_POD, "busy-1", "default") is not None

    def test_pdb_protected_straggler_reprieved(self):
        from nos_tpu.api.pdb import (
            KIND_POD_DISRUPTION_BUDGET, PodDisruptionBudget,
            PodDisruptionBudgetSpec,
        )

        api, sched = self._cluster(after=2)
        api.create(KIND_POD, make_slice_pod(
            "2x4", 1, name="protected", node_name="host-1", phase=RUNNING,
            labels={"app": "serving"}))
        api.create(KIND_POD_DISRUPTION_BUDGET, PodDisruptionBudget(
            metadata=ObjectMeta(name="pdb", namespace="default"),
            spec=PodDisruptionBudgetSpec(min_available=1,
                                         selector={"app": "serving"})))
        self._stuck_gang(api)
        for _ in range(6):
            sched.run_cycle()
        assert api.try_get(KIND_POD, "protected", "default") is not None

    def test_near_done_straggler_spared(self):
        """Remaining-work-aware selection: a straggler whose reported
        progress (ANNOT_JOB_PROGRESS) reached the spare threshold is
        never drain-evicted — it frees the window by finishing — while
        a fresh straggler on the same window still is."""
        api, sched = self._cluster(after=3, fraction=0.5)
        api.create(KIND_POD, make_slice_pod(
            "2x4", 1, name="nearly-done", node_name="host-1",
            phase=RUNNING,
            annotations={C.ANNOT_JOB_PROGRESS: "0.9"}))
        api.create(KIND_POD, make_slice_pod(
            "2x4", 1, name="fresh", node_name="host-2", phase=RUNNING,
            annotations={C.ANNOT_JOB_PROGRESS: "0.1"}))
        self._stuck_gang(api)
        for _ in range(6):
            sched.run_cycle()
        assert api.try_get(KIND_POD, "nearly-done", "default") is not None
        assert api.try_get(KIND_POD, "fresh", "default") is None

    def test_gang_straggler_spared_by_mates_progress(self):
        """Sparing is gang-level: eviction amplifies to the whole gang,
        so a member with no progress annotation is spared when any
        gang-mate reports progress past the threshold ('inf' from a
        buggy mate reads as 0, not an auto-spare)."""
        api, sched = self._cluster(after=3, fraction=0.8)
        create_pod_group(api, "straggler-gang", min_member=2)
        api.create(KIND_POD, make_slice_pod(
            "2x4", 1, name="sg-0", node_name="host-1", phase=RUNNING,
            labels={C.LABEL_POD_GROUP: "straggler-gang"},
            annotations={C.ANNOT_JOB_PROGRESS: "0.9"}))
        api.create(KIND_POD, make_slice_pod(
            "2x4", 1, name="sg-1", node_name="host-2", phase=RUNNING,
            labels={C.LABEL_POD_GROUP: "straggler-gang"}))
        api.create(KIND_POD, make_slice_pod(
            "2x4", 1, name="inf-pod", node_name="host-3", phase=RUNNING,
            annotations={C.ANNOT_JOB_PROGRESS: "inf"}))
        self._stuck_gang(api)
        for _ in range(6):
            sched.run_cycle()
        assert api.try_get(KIND_POD, "sg-0", "default") is not None
        assert api.try_get(KIND_POD, "sg-1", "default") is not None
        assert api.try_get(KIND_POD, "inf-pod", "default") is None

    def test_progress_fn_injection(self):
        """A simulation's progress table (drain_preempt_progress_fn)
        replaces the annotation source."""
        from nos_tpu.scheduler.framework import NodeResourcesFit
        from nos_tpu.scheduler.gang import TopologyFilter

        api = APIServer()
        fw = Framework([NodeResourcesFit(), TopologyFilter(api)])
        for h in range(4):
            api.create(KIND_NODE, make_tpu_node(
                f"host-{h}", pod_id="pod-a", host_index=h,
                status_geometry={"free": {"2x4": 1}}))
        sched = Scheduler(
            api, fw, drain_preempt_after_cycles=3,
            drain_preempt_progress_fn=lambda p: 0.95)
        api.create(KIND_POD, make_slice_pod(
            "2x4", 1, name="s", node_name="host-1", phase=RUNNING))
        self._stuck_gang(api)
        for _ in range(8):
            sched.run_cycle()
        assert api.try_get(KIND_POD, "s", "default") is not None

    def test_duration_aware_backfill(self):
        """Opt-in backfill: a single whose expected duration fits inside
        the reserved window's drain ETA may bind there; a longer one is
        excluded outright (it would outlive the drain); unknown duration
        never backfills."""
        from nos_tpu.scheduler.framework import NodeResourcesFit
        from nos_tpu.scheduler.gang import TopologyFilter

        api = APIServer()
        for h in range(4):
            api.create(KIND_NODE, make_tpu_node(
                f"host-{h}", pod_id="pod-a", host_index=h,
                status_geometry={"free": {"1x2": 4}}))
        durations = {"straggler": 20.0, "short": 5.0, "long": 60.0}
        sched = Scheduler(
            api, Framework([NodeResourcesFit(), TopologyFilter(api)]),
            backfill_remaining_fn=lambda p: durations.get(
                p.metadata.name),
            backfill_duration_fn=lambda p: durations.get(
                p.metadata.name))
        # a straggler with 20 s left occupies the window the stuck gang
        # is draining
        api.create(KIND_POD, make_slice_pod(
            "1x2", 1, name="straggler", node_name="host-1",
            phase=RUNNING))
        self._stuck_gang(api)
        sched.run_cycle()       # gang earns the lease on hosts 0-3
        assert sched._reserved_hosts
        api.create(KIND_POD, make_slice_pod("1x2", 1, name="short"))
        api.create(KIND_POD, make_slice_pod("1x2", 1, name="long"))
        api.create(KIND_POD, make_slice_pod("1x2", 1, name="unknown"))
        sched.run_cycle()
        assert api.get(KIND_POD, "short", "default").spec.node_name
        assert not api.get(KIND_POD, "long", "default").spec.node_name
        assert not api.get(KIND_POD, "unknown",
                           "default").spec.node_name

    def test_disabled_by_default(self):
        api, sched = self._cluster()
        sched2 = Scheduler(api, Framework([]))
        assert sched2._drain_after is None
        api.create(KIND_POD, make_slice_pod("2x4", 1, name="s",
                                            node_name="host-1",
                                            phase=RUNNING))
        self._stuck_gang(api)
        for _ in range(10):
            sched2.run_cycle()
        assert api.try_get(KIND_POD, "s", "default") is not None
