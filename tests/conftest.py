"""Test configuration.

Tests run on CPU with a virtual 8-device platform so multi-chip sharding
paths (mesh creation, pjit shardings, collectives) execute without TPU
hardware — the analog of the reference's envtest-without-GPUs strategy
(SURVEY.md §4).  Set NOS_TPU_TEST_REAL=1 to run against real devices.

The environment may pre-import jax with a TPU platform pinned (a
sitecustomize registering a PJRT plugin), so plain env vars can be too
late; `jax.config.update` works any time before first backend use.
"""

import os

import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from tier-1 "
        "(`-m 'not slow'`)")
    config.addinivalue_line(
        "markers", "chaos: deep chaos soak (seeded fault-injection runs "
        "beyond the small tier-1 depth); select with `-m chaos`")
    config.addinivalue_line(
        "markers", "analysis: noslint static checks + lockcheck over the "
        "tree (tests/test_analysis.py); select with `-m analysis`")
    config.addinivalue_line(
        "markers", "interleave: DPOR-lite interleaving explorer smoke "
        "(tests/test_interleave.py, runs in tier-1); select with "
        "`-m interleave`")


@pytest.fixture
def lock_discipline():
    """Lockdep-instrumented test: every threading.Lock/RLock constructed
    while the test runs is checked (nos_tpu/testing/lockcheck.py), and a
    lock-order inversion or unguarded write observed anywhere fails the
    test at teardown.  Opt in per-module with
    ``pytestmark = pytest.mark.usefixtures("lock_discipline")``."""
    from nos_tpu.testing.lockcheck import LockGraph, unguard_all

    graph = LockGraph(name="lock-discipline")
    with graph.install():
        yield graph
    try:
        graph.assert_clean()
    finally:
        graph.close()   # threads leaked past teardown record nothing
        unguard_all()   # restore any guard_state class patches


if not os.environ.get("NOS_TPU_TEST_REAL"):
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except ImportError:
        pass
