"""Test configuration.

Tests run on CPU with a virtual 8-device platform so multi-chip sharding
paths (mesh creation, pjit shardings, collectives) execute without TPU
hardware — the analog of the reference's envtest-without-GPUs strategy
(SURVEY.md §4).  Set NOS_TPU_TEST_REAL=1 to run against real devices.

The environment may pre-import jax with a TPU platform pinned (a
sitecustomize registering a PJRT plugin), so plain env vars can be too
late; `jax.config.update` works any time before first backend use.
"""

import os


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from tier-1 "
        "(`-m 'not slow'`)")
    config.addinivalue_line(
        "markers", "chaos: deep chaos soak (seeded fault-injection runs "
        "beyond the small tier-1 depth); select with `-m chaos`")


if not os.environ.get("NOS_TPU_TEST_REAL"):
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except ImportError:
        pass
