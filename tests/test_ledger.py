"""Chip-second waste ledger tests: conservation as a property, the
scheduler's verdict-driven attribution, hold lifecycles from the owning
call sites, the shared stranded-free definition, and the `obs waste` /
`obs top --watch` CLI surfaces (docs/observability.md, "The chip-second
waterfall")."""

from __future__ import annotations

import json
import random
from types import SimpleNamespace

import pytest

from nos_tpu import obs
from nos_tpu.exporter.metrics import REGISTRY
from nos_tpu.kube.client import APIServer, KIND_NODE, KIND_POD
from nos_tpu.obs import ledger as ledger_mod
from nos_tpu.obs.ledger import (
    ACTUATION, CATEGORIES, DRAIN, FRAG_STRANDED, GANG_WAIT,
    IDLE_NO_DEMAND, PRODUCTIVE, QUARANTINE, QUOTA_STRANDED,
    ChipSecondLedger, conservation_ok, pod_chip_equiv, stranded_fraction,
    stranded_free, waste_ranking,
)
from nos_tpu.scheduler.framework import Framework, NodeResourcesFit
from nos_tpu.scheduler.scheduler import Scheduler
from nos_tpu.testing.factory import make_slice_pod, make_tpu_node


def make_ledger(clock):
    return ChipSecondLedger(clock=lambda: clock[0])


class TestConservationProperty:
    @pytest.mark.parametrize("seed", range(8))
    def test_random_transitions_conserve_exactly(self, seed):
        """Property: whatever category churn the caller reports — and
        whatever garbage sums it reports (under- AND over-committed) —
        Σ category chip-seconds equals ∫ capacity dt per pool, exactly
        for normalized samples and within ε for clamped ones."""
        rng = random.Random(seed)
        clock = [0.0]
        led = make_ledger(clock)
        pools = ["pod-0", "pod-1", "-"]
        caps = {p: rng.choice([16.0, 64.0, 256.0]) for p in pools}
        for _ in range(rng.randrange(20, 60)):
            clock[0] += rng.uniform(0.01, 5.0)
            sample = {}
            for p in rng.sample(pools, rng.randrange(1, len(pools) + 1)):
                cats = {}
                budget = caps[p] * rng.uniform(0.0, 1.2)  # may overcommit
                for cat in rng.sample(CATEGORIES,
                                      rng.randrange(0, len(CATEGORIES))):
                    take = rng.uniform(0.0, budget)
                    budget -= take
                    if take > 0:
                        cats[cat] = take
                sample[p] = {"capacity": caps[p], "categories": cats}
            led.observe(sample)
        clock[0] += 1.0
        led.observe({p: {"capacity": caps[p], "categories": {}}
                     for p in pools})
        report = led.report()
        assert conservation_ok(report), report["pools"]
        for p, block in report["pools"].items():
            assert block["capacity_chip_seconds"] >= 0.0
            assert all(v >= 0.0 for v in block["chip_seconds"].values())

    def test_exact_accrual_and_elapsed(self):
        clock = [0.0]
        led = make_ledger(clock)
        led.observe({"p": {"capacity": 8.0,
                           "categories": {PRODUCTIVE: 6.0,
                                          FRAG_STRANDED: 2.0}}})
        clock[0] = 10.0
        led.observe({"p": {"capacity": 8.0,
                           "categories": {PRODUCTIVE: 8.0}}})
        clock[0] = 15.0
        led.observe({"p": {"capacity": 8.0, "categories": {}}})
        block = led.report()["pools"]["p"]
        assert block["chip_seconds"][PRODUCTIVE] == 6.0 * 10 + 8.0 * 5
        assert block["chip_seconds"][FRAG_STRANDED] == 2.0 * 10
        assert block["elapsed_s"] == 15.0
        assert block["capacity_chip_seconds"] == 8.0 * 15
        assert block["conservation_delta"] == 0.0

    def test_residual_lands_in_idle_and_overcommit_is_clamped(self):
        clock = [0.0]
        led = make_ledger(clock)
        # undercommitted sample: the residual is idle_no_demand
        led.observe({"p": {"capacity": 10.0,
                           "categories": {PRODUCTIVE: 4.0}}})
        clock[0] = 1.0
        # overcommitted sample (caller bug): scaled down + counted
        led.observe({"p": {"capacity": 10.0,
                           "categories": {PRODUCTIVE: 8.0,
                                          GANG_WAIT: 4.0}}})
        clock[0] = 2.0
        led.observe({"p": {"capacity": 10.0, "categories": {}}})
        report = led.report()
        block = report["pools"]["p"]
        assert block["chip_seconds"][IDLE_NO_DEMAND] == pytest.approx(6.0)
        assert report["overcommit_events"] == 1
        assert conservation_ok(report)

    @pytest.mark.parametrize("seed", range(8))
    def test_node_kill_transitions_conserve_exactly(self, seed):
        """Node-loss property (ISSUE 15): pools randomly LOSE capacity
        (a host dies: capacity drops, the displaced share lands in
        gang_wait/frag/quarantine), regain it (spare promoted), vanish
        outright and come back — with drain/quarantine holds toggling
        through the churn, Σ category chip-seconds still equals
        ∫ capacity dt per pool exactly."""
        rng = random.Random(1000 + seed)
        clock = [0.0]
        led = make_ledger(clock)
        pools = ["pod-0", "pod-1"]
        full = {p: 64.0 for p in pools}
        cap = dict(full)
        for _ in range(rng.randrange(30, 70)):
            clock[0] += rng.uniform(0.01, 5.0)
            event = rng.random()
            victim = rng.choice(pools)
            if event < 0.25:
                # a host dies: one 8-chip host's capacity gone
                cap[victim] = max(0.0, cap[victim] - 8.0)
            elif event < 0.45:
                # spare promoted / replacement joined
                cap[victim] = min(full[victim], cap[victim] + 8.0)
            elif event < 0.55:
                cap[victim] = 0.0       # whole pool lost
            sample = {}
            for p in pools:
                if cap[p] <= 0.0 and rng.random() < 0.5:
                    continue            # vanished pools stop reporting
                cats = {}
                budget = cap[p]
                # displaced-wait categories first (the node-loss
                # shape), then the rest of the waterfall
                for cat in (GANG_WAIT, FRAG_STRANDED, QUARANTINE,
                            DRAIN, PRODUCTIVE):
                    take = rng.uniform(0.0, budget)
                    budget -= take
                    if take > 0.0:
                        cats[cat] = take
                sample[p] = {"capacity": cap[p], "categories": cats,
                             "evidence": {GANG_WAIT: {
                                 "gang": "work/gang-1",
                                 "displaced_cause": "node-loss"}}}
            led.observe(sample)
        clock[0] += 1.0
        led.observe({p: {"capacity": cap[p], "categories": {}}
                     for p in pools})
        report = led.report()
        assert conservation_ok(report), report["pools"]
        # the displaced evidence survives into the report
        ev = report["pools"]["pod-0"]["evidence"].get(GANG_WAIT, {})
        assert ev.get("displaced_cause") == "node-loss"

    def test_capacity_change_mid_run_conserves(self):
        """Node loss: capacity drops between observes; both sides of
        the invariant integrate the same snapshots."""
        clock = [0.0]
        led = make_ledger(clock)
        led.observe({"p": {"capacity": 16.0,
                           "categories": {PRODUCTIVE: 16.0}}})
        clock[0] = 5.0
        led.observe({"p": {"capacity": 8.0,
                           "categories": {PRODUCTIVE: 8.0}}})
        clock[0] = 9.0
        led.observe({"p": {"capacity": 8.0, "categories": {}}})
        block = led.report()["pools"]["p"]
        assert block["capacity_chip_seconds"] == 16.0 * 5 + 8.0 * 4
        assert conservation_ok(led.report())

    def test_vanished_pool_stops_accruing_but_keeps_totals(self):
        clock = [0.0]
        led = make_ledger(clock)
        led.observe({"gone": {"capacity": 4.0,
                              "categories": {PRODUCTIVE: 4.0}}})
        clock[0] = 2.0
        led.observe({})                 # the pool's nodes all left
        clock[0] = 50.0
        led.observe({})
        block = led.report()["pools"]["gone"]
        assert block["chip_seconds"][PRODUCTIVE] == 8.0
        assert block["capacity_chip_seconds"] == 8.0
        assert conservation_ok(led.report())


class TestHoldsAndEvidence:
    def test_hold_lifecycle_and_owner_merge(self):
        led = make_ledger([0.0])
        led.set_hold("n1", ACTUATION, owner="slice", plan_id="abc")
        led.set_hold("n1", ACTUATION, owner="timeshare", plan_id="xyz")
        assert led.hold_count() == 2
        assert ACTUATION in led.holds()["n1"]
        led.clear_hold("n1", ACTUATION, owner="slice")
        # the other plane still holds the hybrid host
        assert ACTUATION in led.holds()["n1"]
        led.clear_hold("n1", ACTUATION, owner="timeshare")
        assert led.holds() == {}
        assert led.hold_count() == 0

    def test_quarantine_list_stamps_and_clears_holds(self):
        """The owning call site: QuarantineList's transitions drive the
        ledger's quarantine holds (and carry the reason as evidence)."""
        from nos_tpu.partitioning.core.quarantine import QuarantineList

        led = make_ledger([0.0])
        with obs.scoped(ledger=led):
            q = QuarantineList(kind="slice")
            q.quarantine("h-9", "plan-deadline")
            assert led.holds()["h-9"][QUARANTINE]["reason"] \
                == "plan-deadline"
            q.unquarantine("h-9")
            assert led.holds() == {}

    def test_evidence_persists_after_the_window(self):
        clock = [0.0]
        led = make_ledger(clock)
        led.observe({"p": {"capacity": 8.0,
                           "categories": {GANG_WAIT: 8.0},
                           "evidence": {GANG_WAIT:
                                        {"gang": "ns/job-1"}}}})
        clock[0] = 1.0
        led.observe({"p": {"capacity": 8.0,
                           "categories": {PRODUCTIVE: 8.0}}})
        block = led.report()["pools"]["p"]
        assert block["evidence"][GANG_WAIT] == {"gang": "ns/job-1"}

    def test_quota_flip_note(self):
        led = make_ledger([0.0])
        led.note_quota_flip("ns/p1", "ns", borrowed=True)
        led.note_quota_flip("ns/p2", "ns", borrowed=False)
        assert led.report()["quota_last_flip"] == {
            "pod": "ns/p2", "namespace": "ns", "borrowed": False}

    def test_chip_seconds_counter_exported(self):
        clock = [0.0]
        led = make_ledger(clock)
        led.observe({"ctr-pool": {"capacity": 4.0,
                                  "categories": {PRODUCTIVE: 4.0}}})
        clock[0] = 3.0
        led.observe({"ctr-pool": {"capacity": 4.0, "categories": {}}})
        snap = REGISTRY.snapshot()["nos_tpu_chip_seconds_total"]
        assert snap["category=productive,pool=ctr-pool"] \
            == pytest.approx(12.0)


class TestSharedStrandedDefinition:
    def test_helper_arithmetic(self):
        free = {"a": 4.0, "b": 8.0, "c": 0.0}
        assert stranded_free(free, {"a"}) == 4.0
        assert stranded_free(free, {"a", "c"}) == 4.0
        assert stranded_fraction(free, {"a"}) == pytest.approx(4.0 / 12)
        assert stranded_fraction({}, {"a"}) == 0.0

    def test_obs_top_frag_column_uses_the_shared_helper(self, capsys):
        """Pin: the frag number `obs top` prints IS
        stranded_fraction() over the state's free-by-host — the same
        arithmetic the ledger's frag accounting uses, so the column and
        the waterfall can never disagree on the definition."""
        from nos_tpu.kube.serialize import dump_state
        from nos_tpu.obs.__main__ import cmd_top

        api = APIServer()
        api.create(KIND_NODE, make_tpu_node(
            "h-0", pod_id="pod-0", host_index=0,
            status_geometry={"free": {"2x2": 1}, "used": {"2x2": 1}}))
        api.create(KIND_NODE, make_tpu_node(
            "h-1", pod_id="pod-0", host_index=1,
            status_geometry={"free": {"2x2": 2}}))
        pod = make_slice_pod("2x2", 1, name="busy")
        pod.spec.node_name = "h-0"
        api.create(KIND_POD, pod)
        assert cmd_top({"state": dump_state(api)}) == 0
        out = capsys.readouterr().out
        row = next(ln for ln in out.splitlines()
                   if ln.startswith("pod-0"))
        # h-0: 8 cap - 4 used = 4 free, busy => stranded; h-1: 8 free
        expect = stranded_fraction({"h-0": 4.0, "h-1": 8.0}, {"h-0"})
        assert row.split()[-1] == f"{expect:.2f}"

    def test_ledger_frag_agrees_with_helper_on_verdict_set(self):
        """The live side of the same definition: the scheduler's
        frag_stranded chips for a cycle equal stranded_free() over its
        free-by-host map and verdict-derived stranded set."""
        clock = [0.0]
        led = make_ledger(clock)
        api = APIServer()
        # h-0 partially used (4 free), h-1 wholly free: pending demand
        # (3x 2x2 = 12 chips, needs one host with 12) fits neither
        api.create(KIND_NODE, make_tpu_node(
            "h-0", pod_id="pod-0", host_index=0,
            status_geometry={"free": {"2x2": 1}, "used": {"2x2": 1}}))
        api.create(KIND_NODE, make_tpu_node(
            "h-1", pod_id="pod-0", host_index=1,
            status_geometry={"free": {"2x2": 2}}))
        busy = make_slice_pod("2x2", 1, name="busy")
        busy.spec.node_name = "h-0"
        api.create(KIND_POD, busy)
        sched = Scheduler(api, Framework([NodeResourcesFit()]),
                          clock=lambda: clock[0])
        with obs.scoped(ledger=led):
            api.create(KIND_POD, make_slice_pod("2x2", 3, name="big"))
            clock[0] = 1.0
            sched.run_cycle()
            clock[0] = 2.0
            sched.run_cycle()
        frag = led.report()["pools"]["pod-0"]["chip_seconds"].get(
            FRAG_STRANDED, 0.0)
        # both hosts rejected the only pending class: both stranded
        assert frag == pytest.approx(
            stranded_free({"h-0": 4.0, "h-1": 8.0}, {"h-0", "h-1"}))

    def test_pod_chip_equiv_currency(self):
        from nos_tpu.kube.resources import pod_request
        from nos_tpu.testing.factory import make_timeshare_pod

        slice_pod = make_slice_pod("4x4", 1, name="s")
        assert pod_chip_equiv(pod_request(slice_pod), 8.0, 16.0) == 8.0
        ts_pod = make_timeshare_pod(8, 1, name="t")
        assert pod_chip_equiv(pod_request(ts_pod), 8.0, 16.0) == 0.5


class TestSchedulerAttribution:
    def _cluster(self, clock, hosts=2):
        api = APIServer()
        for i in range(hosts):
            api.create(KIND_NODE, make_tpu_node(
                f"h-{i}", pod_id="pod-0", host_index=i,
                status_geometry={"free": {"2x2": 2}}))
        sched = Scheduler(api, Framework([NodeResourcesFit()]),
                          clock=lambda: clock[0])
        return api, sched

    def _accrue(self, clock, sched, dt=1.0):
        clock[0] += dt
        sched.run_cycle()

    def test_idle_no_demand_without_pending(self):
        clock = [0.0]
        led = make_ledger(clock)
        api, sched = self._cluster(clock)
        with obs.scoped(ledger=led):
            self._accrue(clock, sched)
            self._accrue(clock, sched)
        cats = led.report()["pools"]["pod-0"]["chip_seconds"]
        assert cats == {IDLE_NO_DEMAND: pytest.approx(16.0)}

    def test_frag_from_rejection_verdicts_with_evidence(self):
        clock = [0.0]
        led = make_ledger(clock)
        api, sched = self._cluster(clock)
        with obs.scoped(ledger=led):
            api.create(KIND_POD, make_slice_pod("2x2", 3, name="big"))
            self._accrue(clock, sched)
            self._accrue(clock, sched)
        pool = led.report()["pools"]["pod-0"]
        assert pool["chip_seconds"][FRAG_STRANDED] == pytest.approx(16.0)
        assert pool["evidence"][FRAG_STRANDED]["class"] == "slice-2x2"

    def test_gang_wait_while_members_missing_is_demand_capped(self):
        """A stuck gang outside any lease marks gang_wait only up to
        its members' own chip demand — the rest of the free fleet is
        idle, not gang wait."""
        from nos_tpu.api import constants as C
        from nos_tpu.api.podgroup import PodGroup, PodGroupSpec
        from nos_tpu.kube.client import KIND_POD_GROUP
        from nos_tpu.kube.objects import ObjectMeta

        clock = [0.0]
        led = make_ledger(clock)
        api, sched = self._cluster(clock)
        with obs.scoped(ledger=led):
            api.create(KIND_POD_GROUP, PodGroup(
                metadata=ObjectMeta(name="g1", namespace="default"),
                spec=PodGroupSpec(min_member=3)))
            api.create(KIND_POD, make_slice_pod(
                "2x2", 1, name="m0",
                labels={C.LABEL_POD_GROUP: "g1"}))
            self._accrue(clock, sched)
            self._accrue(clock, sched)
        pool = led.report()["pools"]["pod-0"]
        # one 2x2 member pending = 4 chips of gang demand; 16 free
        assert pool["chip_seconds"][GANG_WAIT] == pytest.approx(4.0)
        assert pool["chip_seconds"][IDLE_NO_DEMAND] == pytest.approx(12.0)
        assert pool["evidence"][GANG_WAIT]["gang"] == "default/g1"

    def test_displaced_gang_wait_evidence_names_kill_cause(self):
        """Satellite (ISSUE 15): when the stuck gang is a displaced
        node-loss victim, the gang_wait evidence carries the kill
        cause — displaced wait is distinguishable from ordinary gang
        assembly in the waterfall."""
        from nos_tpu.api import constants as C
        from nos_tpu.api.podgroup import PodGroup, PodGroupSpec
        from nos_tpu.kube.client import KIND_POD_GROUP
        from nos_tpu.kube.objects import ObjectMeta
        from nos_tpu.utils.pod_util import displaced_value

        clock = [10.0]
        led = make_ledger(clock)
        api, sched = self._cluster(clock)
        with obs.scoped(ledger=led):
            api.create(KIND_POD_GROUP, PodGroup(
                metadata=ObjectMeta(name="g1", namespace="default"),
                spec=PodGroupSpec(min_member=3)))
            api.create(KIND_POD, make_slice_pod(
                "2x2", 1, name="m0",
                labels={C.LABEL_POD_GROUP: "g1"},
                annotations={C.ANNOT_DISPLACED: displaced_value(
                    "node-loss", 9.0)}))
            self._accrue(clock, sched)
            self._accrue(clock, sched)
        ev = led.report()["pools"]["pod-0"]["evidence"][GANG_WAIT]
        assert ev["gang"] == "default/g1"
        assert ev["displaced_cause"] == "node-loss"

    def test_hold_precedence_quarantine_over_actuation(self):
        clock = [0.0]
        led = make_ledger(clock)
        api, sched = self._cluster(clock, hosts=1)
        led.set_hold("h-0", ACTUATION, owner="slice", plan_id="p1",
                     kind="slice")
        led.set_hold("h-0", QUARANTINE, owner="slice", reason="dead")
        with obs.scoped(ledger=led):
            self._accrue(clock, sched)
            self._accrue(clock, sched)
        cats = led.report()["pools"]["pod-0"]["chip_seconds"]
        assert cats == {QUARANTINE: pytest.approx(8.0)}

    def test_actuation_and_drain_holds_attribute(self):
        clock = [0.0]
        led = make_ledger(clock)
        api, sched = self._cluster(clock)
        led.set_hold("h-0", ACTUATION, owner="slice", plan_id="p1",
                     kind="slice")
        led.set_hold("h-1", DRAIN, owner="s", gang="ns/g")
        with obs.scoped(ledger=led):
            self._accrue(clock, sched)
            self._accrue(clock, sched)
        pool = led.report()["pools"]["pod-0"]
        assert pool["chip_seconds"][ACTUATION] == pytest.approx(8.0)
        assert pool["chip_seconds"][DRAIN] == pytest.approx(8.0)
        assert pool["evidence"][ACTUATION]["plan_id"] == "p1"
        assert pool["evidence"][DRAIN]["gang"] == "ns/g"

    def test_quota_stranded_precedence_and_demand_cap(self):
        """White-box: quota-blocked demand (PreFilter rejections carry
        no per-node scan) turns unscanned free chips quota_stranded —
        but only up to the blocked demand's own size; one small
        rejection must not paint the whole pool."""
        clock = [0.0]
        led = make_ledger(clock)
        api, sched = self._cluster(clock, hosts=1)   # 8 free chips
        with obs.scoped(ledger=led):
            sched._waste_quota_blocked["slice-2x2"] = 4.0
            sched._observe_waste({"slice-2x2": 1})
            clock[0] += 2.0
            sched._waste_quota_blocked["slice-2x2"] = 4.0
            sched._observe_waste({"slice-2x2": 1})
        pool = led.report()["pools"]["pod-0"]
        assert pool["chip_seconds"][QUOTA_STRANDED] == pytest.approx(8.0)
        assert pool["chip_seconds"][IDLE_NO_DEMAND] == pytest.approx(8.0)
        assert pool["evidence"][QUOTA_STRANDED]["class"] == "slice-2x2"
        assert conservation_ok(led.report())

    def test_productive_is_bound_running_chips(self):
        clock = [0.0]
        led = make_ledger(clock)
        api, sched = self._cluster(clock, hosts=1)
        with obs.scoped(ledger=led):
            api.create(KIND_POD, make_slice_pod("2x2", 1, name="p"))
            self._accrue(clock, sched)      # binds; 4 used / 4 free
            self._accrue(clock, sched)
        cats = led.report()["pools"]["pod-0"]["chip_seconds"]
        assert cats[PRODUCTIVE] == pytest.approx(4.0)
        assert cats[IDLE_NO_DEMAND] == pytest.approx(4.0)
        assert conservation_ok(led.report())

    def test_flight_snapshot_carries_waste(self):
        clock = [0.0]
        led = make_ledger(clock)
        api, sched = self._cluster(clock, hosts=1)
        with obs.scoped(ledger=led):
            self._accrue(clock, sched)
            self._accrue(clock, sched)
            snapshot = obs.flight_snapshot()
        assert "waste" in snapshot
        assert "pod-0" in snapshot["waste"]["pools"]


def _demo_waste_payload():
    """A flight-style payload: waterfall + the journal records each
    culprit joins to (the node-loss shape: gang stalled on a lease,
    frag defined by a class's rejections, a quarantined node)."""
    clock = [0.0]
    led = make_ledger(clock)
    led.observe({"pod-0": {
        "capacity": 16.0,
        "categories": {PRODUCTIVE: 8.0, GANG_WAIT: 5.0,
                       FRAG_STRANDED: 2.0, QUARANTINE: 1.0},
        "evidence": {
            GANG_WAIT: {"gang": "train-a/job-7"},
            FRAG_STRANDED: {"class": "slice-2x4", "rejected_nodes": 3},
            QUARANTINE: {"node": "host-3", "reason": "plan-deadline"},
        }}})
    clock[0] = 10.0
    led.observe({"pod-0": {"capacity": 16.0, "categories": {}}})
    journal = obs.DecisionJournal(maxlen=64, clock=lambda: clock[0])
    journal.record("pod-rejected", "train-a/job-7-0",
                   reason="", message="no fit",
                   nodes={"host-1": "NodeResourcesFit: insufficient "
                                    "nos.tpu/slice-2x4"},
                   reason_counts={"NodeResourcesFit: insufficient "
                                  "nos.tpu/slice-2x4": 3},
                   **{"class": "slice-2x4"})
    journal.record("gang-rejected", "train-a/job-7",
                   message="gang does not fit as a whole",
                   members=["train-a/job-7-0"], members_total=2)
    journal.record("quarantined", "host-3", kind="slice",
                   reason="plan-deadline")
    return {"waste": led.report(), "journal": journal.dump()}


class TestWasteCLI:
    def test_golden_path_names_journal_joined_culprits(self, capsys):
        from nos_tpu.obs.__main__ import cmd_waste

        assert cmd_waste(_demo_waste_payload()) == 0
        out = capsys.readouterr().out
        assert "conservation: ok" in out
        # ranked: gang_wait (5) > frag (2) > quarantine (1)
        assert out.index("1. gang_wait") < out.index("2. frag_stranded")
        # every top waste category names a journal-joined culprit
        assert "culprit gang train-a/job-7" in out
        assert "gang does not fit as a whole" in out
        assert "culprit class slice-2x4" in out
        assert "NodeResourcesFit: insufficient nos.tpu/slice-2x4" in out
        assert "culprit node host-3" in out

    def test_main_entrypoint_with_snapshot_file(self, tmp_path, capsys):
        from nos_tpu.obs.__main__ import main

        path = tmp_path / "flight.json"
        path.write_text(json.dumps(_demo_waste_payload()))
        assert main(["waste", "--snapshot", str(path)]) == 0
        assert "chip-second waste waterfall" in capsys.readouterr().out

    def test_bench_nesting_is_found(self, capsys):
        """bench.py nests the block under utilization — the CLI finds
        it there too (one command over any saved payload)."""
        from nos_tpu.obs.__main__ import cmd_waste

        payload = {"utilization": _demo_waste_payload()}
        payload["utilization"].pop("journal")
        assert cmd_waste(payload) == 0

    def test_no_block_is_a_clean_error(self, capsys):
        from nos_tpu.obs.__main__ import cmd_waste

        assert cmd_waste({"spans": []}) == 1
        assert "no waste waterfall" in capsys.readouterr().err

    def test_conservation_violation_is_loud_and_nonzero(self, capsys):
        from nos_tpu.obs.__main__ import cmd_waste

        payload = _demo_waste_payload()
        pool = payload["waste"]["pools"]["pod-0"]
        pool["conservation_delta"] = 5.0
        assert cmd_waste(payload) == 1
        assert "VIOLATED" in capsys.readouterr().out

    def test_waste_ranking_excludes_productive(self):
        rows = waste_ranking(_demo_waste_payload()["waste"])
        assert [r["category"] for r in rows[:2]] \
            == [GANG_WAIT, FRAG_STRANDED]
        assert all(r["category"] != PRODUCTIVE for r in rows)


class TestTopWatch:
    def test_watch_renders_frames_and_clears(self, tmp_path, capsys):
        from nos_tpu.kube.serialize import dump_state
        from nos_tpu.obs.__main__ import _watch_top

        api = APIServer()
        api.create(KIND_NODE, make_tpu_node("h-0"))
        path = tmp_path / "snap.json"
        path.write_text(json.dumps({"state": dump_state(api)}))
        sleeps: list[float] = []
        args = SimpleNamespace(snapshot=str(path), url="",
                               watch=2.5, frames=3)
        rc = _watch_top(args, "/snapshot", sleep=sleeps.append)
        out = capsys.readouterr().out
        assert rc == 0
        assert out.count("\x1b[2J") == 3          # cleared per frame
        assert out.count("fleet: 1 host(s)") == 3
        assert sleeps == [2.5, 2.5]               # no sleep after last

    def test_one_shot_unchanged_without_watch(self, tmp_path, capsys):
        from nos_tpu.kube.serialize import dump_state
        from nos_tpu.obs.__main__ import main

        api = APIServer()
        api.create(KIND_NODE, make_tpu_node("h-0"))
        path = tmp_path / "snap.json"
        path.write_text(json.dumps({"state": dump_state(api)}))
        assert main(["top", "--snapshot", str(path)]) == 0
        out = capsys.readouterr().out
        assert "\x1b[2J" not in out
        assert out.count("fleet: 1 host(s)") == 1


class TestMetricFamilyRegistration:
    def test_chip_seconds_metric_is_described(self):
        """noslint N003's dynamic twin: the new family is registered
        exactly once with stable help text (the rule checks the call
        sites statically; this pins the runtime registration)."""
        with pytest.raises(ValueError, match="already registered"):
            REGISTRY.describe("nos_tpu_chip_seconds_total",
                              "a conflicting re-registration")

    def test_ledger_module_is_in_noslint_scope(self):
        """obs/ledger.py must stay inside N003's scope (metric naming /
        registration discipline) — the rule's exclude list names only
        the Registry itself and the analyzer."""
        from nos_tpu.analysis.rules import MetricDiscipline

        rule = MetricDiscipline()
        path = "nos_tpu/obs/ledger.py"
        assert any(path.startswith(s) for s in rule.scope)
        assert not any(path.startswith(e) for e in rule.exclude)
