"""Hybrid-node family split (nos_tpu/topology/hybrid.py): the slice and
timeshare strategies own disjoint chips of one host block, the analog of
the reference's per-GPU strategy assignment (pkg/gpu/partitioning.go:81-135).
"""

from __future__ import annotations

from nos_tpu.api import constants as C
from nos_tpu.kube.client import APIServer, KIND_NODE, KIND_POD
from nos_tpu.kube.objects import PENDING, RUNNING
from nos_tpu.testing.factory import (
    make_slice_pod, make_timeshare_pod, make_tpu_node,
)
from nos_tpu.topology import Shape, V4, V5E
from nos_tpu.topology.hybrid import (
    hybrid_slice_block, slice_generation_for, timeshare_cells,
)


class TestSplitConvention:
    def test_non_hybrid_node_not_split(self):
        labels = {C.LABEL_PARTITIONING: "slice"}
        assert hybrid_slice_block(labels, V5E) is None
        assert slice_generation_for(labels, V5E) is V5E
        assert timeshare_cells(labels, V5E) is None

    def test_default_split_halves_first_wide_axis(self):
        labels = {C.LABEL_PARTITIONING: "hybrid"}
        assert hybrid_slice_block(labels, V5E) == Shape.parse("1x4")
        assert timeshare_cells(labels, V5E) == frozenset({4, 5, 6, 7})
        # v4 host block is 1x2x2: the first axis of size >= 2 is axis 1
        assert hybrid_slice_block(labels, V4) == Shape((1, 1, 2))
        assert timeshare_cells(labels, V4) == frozenset({2, 3})

    def test_labelled_split(self):
        labels = {C.LABEL_PARTITIONING: "hybrid",
                  C.LABEL_SLICE_BLOCK: "1x4"}
        gen = slice_generation_for(labels, V5E)
        assert gen.host_block == Shape.parse("1x4")
        assert gen.chips_per_host == 4

    def test_invalid_label_falls_back_to_default(self):
        for bad in ("2x2",       # not a row-major prefix (axis 1 differs
                                 # while axis 0 is 2 in the host block)
                    "2x4",       # equal to the host block (no split)
                    "3x4",       # exceeds the host block
                    "banana",    # unparseable
                    "1x1x1"):    # wrong rank
            labels = {C.LABEL_PARTITIONING: "hybrid",
                      C.LABEL_SLICE_BLOCK: bad}
            assert hybrid_slice_block(labels, V5E) == Shape.parse("1x4"), bad

    def test_units_respect_split(self):
        from nos_tpu.partitioning.slicepart.node import (
            units_from_node as slice_units,
        )
        from nos_tpu.partitioning.timeshare.node import (
            units_from_node as ts_units,
        )

        node = make_tpu_node("h", partitioning="hybrid", status_geometry={
            "free": {"1x2": 2}})
        # stale timeshare replica reported on a slice-family chip: dropped
        node.metadata.annotations[f"{C.ANNOT_STATUS_PREFIX}1-8gb-free"] = "1"
        node.metadata.annotations[f"{C.ANNOT_STATUS_PREFIX}5-8gb-free"] = "1"
        su = slice_units(node)
        assert all(u.generation.host_block == Shape.parse("1x4") for u in su)
        tu = ts_units(node)
        assert sorted(u.index for u in tu) == [4, 5, 6, 7]
        assert tu[1].free == {8: 1}          # chip 5 keeps its replica
        assert all(not u.free for u in tu if u.index != 5)


class TestNoOversubscription:
    def test_hybrid_host_cannot_exceed_block(self):
        """Both families under demand pressure on one hybrid host admit
        at most the block's 8 chips of work (regression: before the
        split, 12 chip-equivalents were admitted)."""
        from nos_tpu.cmd.assembly import build_scheduler
        from nos_tpu.controllers.chipagent import ChipAgent
        from nos_tpu.controllers.node_controller import NodeController
        from nos_tpu.controllers.pod_controller import PodController
        from nos_tpu.controllers.sliceagent.agent import SliceAgent
        from nos_tpu.device import default_tpu_runtime
        from nos_tpu.device.fake import FakePodResources
        from nos_tpu.partitioning.slicepart import SliceNodeInitializer
        from nos_tpu.partitioning.slicepart.factory import (
            new_slice_partitioner_controller,
        )
        from nos_tpu.partitioning.state import ClusterState
        from nos_tpu.partitioning.timeshare.factory import (
            new_timeshare_partitioner_controller,
        )

        now = [0.0]
        api = APIServer()
        state = ClusterState()
        NodeController(api, state, SliceNodeInitializer(api)).bind()
        PodController(api, state).bind()
        ctls = [
            new_slice_partitioner_controller(
                api, state, batch_timeout_s=1.0, batch_idle_s=0.25,
                clock=lambda: now[0]),
            new_timeshare_partitioner_controller(
                api, state, batch_timeout_s=1.0, batch_idle_s=0.25,
                clock=lambda: now[0]),
        ]
        for c in ctls:
            c.bind()
        node = make_tpu_node("hyb-0", partitioning="hybrid", pod_id="",
                             host_index=0)
        api.create(KIND_NODE, node)
        gen = slice_generation_for(node.metadata.labels, V5E)
        sa = SliceAgent(api, "hyb-0", default_tpu_runtime(gen),
                        FakePodResources())
        sa.start()
        ca = ChipAgent(api, "hyb-0")
        ca.start()
        sched = build_scheduler(api)
        for i in range(3):
            api.create(KIND_POD, make_slice_pod("1x2", 1, name=f"sl-{i}"))
        for i in range(5):
            api.create(KIND_POD, make_timeshare_pod(16, 1, name=f"ts-{i}"))
        for _ in range(120):
            now[0] += 0.25
            sched.run_cycle()
            for c in ctls:
                c.process_if_ready()
            sa.tick()
            ca.tick()
        running = [p.metadata.name for p in api.list(KIND_POD)
                   if p.status.phase == RUNNING]
        pending = [p.metadata.name for p in api.list(KIND_POD)
                   if p.status.phase == PENDING]
        chip_equiv = sum(2 for n in running if n.startswith("sl")) \
            + sum(1 for n in running if n.startswith("ts"))
        assert chip_equiv <= 8
        # both families actually got their halves
        assert sum(1 for n in running if n.startswith("sl")) == 2
        assert sum(1 for n in running if n.startswith("ts")) == 4
        assert len(pending) == 2
