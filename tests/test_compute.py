"""JAX compute path tests: mesh, ring attention, flash kernel, model,
sharded training.  Run on the virtual 8-device CPU platform (conftest)."""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from nos_tpu.models.llama import Llama, TINY
from nos_tpu.models.train import ShardedTrainer, cross_entropy_loss
from nos_tpu.ops.attention import flash_attention, repeat_kv
from nos_tpu.parallel.mesh import MeshSpec, make_mesh
from nos_tpu.parallel.ring import dense_attention, ring_attention


@pytest.fixture(scope="module")
def qkv():
    key = jax.random.PRNGKey(0)
    return tuple(
        jax.random.normal(k, (2, 32, 4, 16), jnp.float32)
        for k in jax.random.split(key, 3)
    )


class TestMeshSpec:
    def test_parse_kv(self):
        s = MeshSpec.parse("fsdp=4,tp=2")
        assert (s.dp, s.fsdp, s.tp, s.sp) == (1, 4, 2, 1)

    def test_parse_topology(self):
        s = MeshSpec.parse("2x2x4")
        assert s.size == 16 and s.fsdp == 4

    def test_for_device_count(self):
        for n in (1, 2, 4, 8, 16, 64):
            s = MeshSpec.for_device_count(n)
            assert s.size == n

    def test_make_mesh_wrong_count(self):
        with pytest.raises(ValueError):
            make_mesh(MeshSpec(dp=3), devices=jax.devices()[:2])


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("spec", [
        MeshSpec(1, 1, 1, 4), MeshSpec(1, 2, 1, 4), MeshSpec(1, 2, 2, 2),
    ])
    def test_matches_dense(self, qkv, spec, causal):
        q, k, v = qkv
        mesh = make_mesh(spec, devices=jax.devices()[:spec.size])
        ref = dense_attention(q, k, v, causal)
        out = ring_attention(mesh, q, k, v, causal)
        assert jnp.max(jnp.abs(out - ref)) < 1e-5

    @pytest.mark.parametrize("causal", [True, False])
    def test_overlap_rotation_is_numerically_identical(self, qkv, causal):
        """The double-buffered rotation (ppermute issued before the
        block's matmuls) must be a pure scheduling change: both
        orderings equal dense, and each other bitwise."""
        q, k, v = qkv
        mesh = make_mesh(MeshSpec(1, 1, 1, 4), devices=jax.devices()[:4])
        ref = dense_attention(q, k, v, causal)
        outs = {ov: ring_attention(mesh, q, k, v, causal, overlap=ov)
                for ov in (True, False)}
        for ov, out in outs.items():
            assert jnp.max(jnp.abs(out - ref)) < 1e-5, f"overlap={ov}"
        assert jnp.array_equal(outs[True], outs[False])

    def test_overlap_differentiable(self, qkv):
        q, k, v = qkv
        mesh = make_mesh(MeshSpec(1, 1, 1, 4), devices=jax.devices()[:4])
        g = jax.grad(lambda q: ring_attention(
            mesh, q, k, v, True, overlap=True).sum())(q)
        g_ref = jax.grad(lambda q: ring_attention(
            mesh, q, k, v, True, overlap=False).sum())(q)
        assert bool(jnp.all(jnp.isfinite(g)))
        assert jnp.max(jnp.abs(g - g_ref)) < 1e-6

    def test_differentiable(self, qkv):
        q, k, v = qkv
        mesh = make_mesh(MeshSpec(1, 2, 1, 4))
        g = jax.grad(lambda q: ring_attention(mesh, q, k, v, True).sum())(q)
        assert bool(jnp.all(jnp.isfinite(g)))

    def test_long_context_over_full_sp_mesh(self):
        """Long-context leg: S=4096 sequence-parallel over all 8 virtual
        devices (512 tokens per device, 8 ring steps) must still match
        dense — the configuration the single-chip kernel never sees."""
        key = jax.random.PRNGKey(11)
        q, k, v = (jax.random.normal(kk, (1, 4096, 2, 32), jnp.float32)
                   for kk in jax.random.split(key, 3))
        mesh = make_mesh(MeshSpec(1, 1, 1, 8))
        ref = dense_attention(q, k, v, causal=True)
        out = ring_attention(mesh, q, k, v, causal=True)
        assert jnp.max(jnp.abs(out - ref)) < 1e-4


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_kernel_matches_dense(self, causal):
        # interpret=True exercises the pallas kernel body on CPU
        key = jax.random.PRNGKey(1)
        q, k, v = (jax.random.normal(kk, (1, 256, 2, 128), jnp.float32)
                   for kk in jax.random.split(key, 3))
        ref = dense_attention(q, k, v, causal)
        out = flash_attention(q, k, v, causal, 128, 128, True)
        assert jnp.max(jnp.abs(out - ref)) < 1e-4

    def test_fallback_for_unaligned(self, qkv):
        q, k, v = qkv  # head_dim 16: not kernel-eligible -> XLA path
        out = flash_attention(q, k, v, True)
        assert jnp.max(jnp.abs(out - dense_attention(q, k, v, True))) < 1e-5

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("impl", ["split", "fused"])
    def test_backward_kernels_match_dense(self, causal, impl):
        """Both flash backward implementations (classic dq/dkv split and
        the fused 5-matmul kernel) vs autodiff of the dense path, for all
        three inputs and a non-trivial cotangent."""
        from nos_tpu.ops import attention as A

        prev = A.set_backward_impl(impl)
        try:
            self._check_backward(causal)
        finally:
            A.set_backward_impl(prev)

    def _check_backward(self, causal):
        key = jax.random.PRNGKey(1)
        q, k, v = (jax.random.normal(kk, (2, 256, 2, 128), jnp.float32)
                   for kk in jax.random.split(key, 3))

        def loss(fn):
            return lambda q, k, v: (fn(q, k, v) ** 2).sum()

        flash = loss(lambda q, k, v: flash_attention(
            q, k, v, causal, 128, 128, True))
        dense = loss(lambda q, k, v: dense_attention(q, k, v, causal))
        g = jax.grad(flash, (0, 1, 2))(q, k, v)
        g_ref = jax.grad(dense, (0, 1, 2))(q, k, v)
        for got, want in zip(g, g_ref):
            scale = float(jnp.max(jnp.abs(want))) + 1e-9
            assert float(jnp.max(jnp.abs(got - want))) / scale < 2e-2

    def test_fused_backward_bf16_inputs_match_split(self):
        """bf16 training path: the fused backward stores per-k-block dq
        partials in the ARRAY dtype, so in bf16 each partial is rounded
        before the XLA-side fp32 sum — an error source the split path
        does not have.  Pin the documented 'within bf16 gradient
        tolerance' claim: fused-vs-split on bf16 inputs must agree to
        bf16 resolution (~2^-8 relative), and both must track the fp32
        dense reference."""
        from nos_tpu.ops import attention as A

        key = jax.random.PRNGKey(5)
        q32, k32, v32 = (jax.random.normal(kk, (2, 256, 2, 128),
                                           jnp.float32)
                         for kk in jax.random.split(key, 3))
        qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q32, k32, v32))

        def loss(fn):
            return lambda q, k, v: (
                fn(q, k, v).astype(jnp.float32) ** 2).sum()

        flash = loss(lambda q, k, v: flash_attention(
            q, k, v, True, 128, 128, True))
        grads = {}
        for impl in ("split", "fused"):
            prev = A.set_backward_impl(impl)
            try:
                grads[impl] = jax.grad(flash, (0, 1, 2))(qb, kb, vb)
            finally:
                A.set_backward_impl(prev)
        dense = loss(lambda q, k, v: dense_attention(q, k, v, True))
        g_ref = jax.grad(dense, (0, 1, 2))(q32, k32, v32)
        for got_f, got_s, want in zip(grads["fused"], grads["split"],
                                      g_ref):
            scale = float(jnp.max(jnp.abs(want))) + 1e-9
            # fused vs split: same inputs, difference is only the bf16
            # partial rounding — a few ulps at bf16 resolution
            rel_fs = float(jnp.max(jnp.abs(
                got_f.astype(jnp.float32)
                - got_s.astype(jnp.float32)))) / scale
            assert rel_fs < 3e-2, rel_fs
            # and both track the fp32 reference within bf16 tolerance
            rel_ref = float(jnp.max(jnp.abs(
                got_f.astype(jnp.float32) - want))) / scale
            assert rel_ref < 8e-2, rel_ref

    @pytest.mark.parametrize("impl", ["split", "fused"])
    def test_backward_rectangular_blocks(self, impl):
        """block_q != block_k exercises the diagonal bounds in every
        backward kernel, for BOTH implementations."""
        from nos_tpu.ops import attention as A

        prev = A.set_backward_impl(impl)
        try:
            self._check_rectangular()
        finally:
            A.set_backward_impl(prev)

    def _check_rectangular(self):
        key = jax.random.PRNGKey(2)
        q, k, v = (jax.random.normal(kk, (1, 512, 1, 128), jnp.float32)
                   for kk in jax.random.split(key, 3))
        flash = lambda q, k, v: flash_attention(  # noqa: E731
            q, k, v, True, 128, 256, True).sum()
        dense = lambda q, k, v: dense_attention(q, k, v, True).sum()
        g = jax.grad(flash, (0, 1, 2))(q, k, v)
        g_ref = jax.grad(dense, (0, 1, 2))(q, k, v)
        for got, want in zip(g, g_ref):
            scale = float(jnp.max(jnp.abs(want))) + 1e-9
            assert float(jnp.max(jnp.abs(got - want))) / scale < 2e-2

    def test_causal_rectangle_takes_fallback(self):
        """Decode-style causal shapes (seq_q < seq_k over cached keys)
        need bottom-right mask alignment; the kernel's mask is top-left
        aligned, so _plan must route them to the XLA fallback."""
        from nos_tpu.ops.attention import _plan

        key = jax.random.PRNGKey(3)
        q = jax.random.normal(key, (1, 1, 2, 128), jnp.float32)
        k, v = (jax.random.normal(kk, (1, 256, 2, 128), jnp.float32)
                for kk in jax.random.split(key, 2))
        assert _plan(q, k, True, 128, 128) is None
        assert _plan(q, k, False, 128, 128) is not None
        out = flash_attention(q, k, v, True, 128, 128, True)
        ref = dense_attention(q, k, v, True)
        assert jnp.max(jnp.abs(out - ref)) < 1e-5

    def test_repeat_kv(self):
        x = jnp.arange(2 * 4 * 2 * 3, dtype=jnp.float32).reshape(2, 4, 2, 3)
        y = repeat_kv(x, 2)
        assert y.shape == (2, 4, 4, 3)
        assert jnp.array_equal(y[:, :, 0], y[:, :, 1])  # repeated pairs
        assert jnp.array_equal(repeat_kv(x, 1), x)


class TestFusedBwdBudgetFallback:
    def test_budget_exceeded_selects_split(self, monkeypatch):
        """Long-context shapes whose dq-partial buffer exceeds the budget
        must take the split kernels (no partial buffer) — validated on
        real hardware at B16 S8192 (2 GiB partials, r4); here the
        selection logic is pinned with a shrunken budget."""
        from nos_tpu.ops import attention as A

        calls = []
        real_fused, real_split = A._flash_backward_fused, A._flash_backward
        monkeypatch.setattr(
            A, "_flash_backward_fused",
            lambda *a, **k: calls.append("fused") or real_fused(*a, **k))
        monkeypatch.setattr(
            A, "_flash_backward",
            lambda *a, **k: calls.append("split") or real_split(*a, **k))

        key = jax.random.PRNGKey(0)
        q, k, v = (jax.random.normal(kk, (1, 256, 1, 128), jnp.float32)
                   for kk in jax.random.split(key, 3))

        def loss(q, k, v):
            return (flash_attention(q, k, v, True, 128, 128, True) ** 2).sum()

        jax.grad(loss, (0, 1, 2))(q, k, v)
        assert calls == ["fused"]

        calls.clear()
        monkeypatch.setattr(A, "FUSED_PARTIAL_BUDGET", 1)
        jax.grad(loss, (0, 1, 2))(q, k, v)
        assert calls == ["split"]


class TestLlama:
    def test_forward_shape_and_finite(self):
        model = Llama(TINY)
        tokens = jnp.zeros((2, 16), jnp.int32)
        variables = model.init(jax.random.PRNGKey(0), tokens)
        logits = model.apply(variables, tokens)
        assert logits.shape == (2, 16, TINY.vocab_size)
        assert bool(jnp.all(jnp.isfinite(logits)))

    def test_causality(self):
        """Changing a future token must not affect earlier logits."""
        model = Llama(TINY)
        t1 = jnp.zeros((1, 16), jnp.int32)
        t2 = t1.at[0, 10].set(5)
        variables = model.init(jax.random.PRNGKey(0), t1)
        l1 = model.apply(variables, t1)
        l2 = model.apply(variables, t2)
        assert jnp.allclose(l1[0, :10], l2[0, :10], atol=1e-5)
        assert not jnp.allclose(l1[0, 10:], l2[0, 10:], atol=1e-5)

    def test_fused_projections_match_unfused(self):
        """fused_qkv / fused_gate_up are pure layout changes: stitching the
        unfused kernels into the fused shapes must reproduce the logits
        bit-for-bit modulo matmul tiling (tight atol)."""
        import flax
        import flax.linen as nn

        tokens = jax.random.randint(
            jax.random.PRNGKey(3), (2, 16), 0, TINY.vocab_size, jnp.int32)
        cfg = dataclasses.replace(TINY, scan_layers=False)
        base = Llama(cfg)
        vs = base.init(jax.random.PRNGKey(0), tokens)
        ref = base.apply(vs, tokens)

        fused_cfg = dataclasses.replace(
            cfg, fused_qkv=True, fused_gate_up=True)
        fused_params = nn.meta.unbox(flax.core.unfreeze(vs))["params"]
        for lyr in (f"layer_{i}" for i in range(TINY.num_layers)):
            attn = fused_params[lyr]["attn"]
            qkv = jnp.concatenate(
                [attn.pop("q_proj")["kernel"],
                 attn.pop("k_proj")["kernel"],
                 attn.pop("v_proj")["kernel"]], axis=1)
            attn["qkv_proj"] = {"kernel": qkv}
            mlp = fused_params[lyr]["mlp"]
            gate_up = jnp.concatenate(
                [mlp.pop("gate_proj")["kernel"],
                 mlp.pop("up_proj")["kernel"]], axis=1)
            mlp["gate_up_proj"] = {"kernel": gate_up}
        out = Llama(fused_cfg).apply({"params": fused_params}, tokens)
        assert jnp.max(jnp.abs(out - ref)) < 1e-5


class TestRematPolicies:
    def test_all_policies_compute_identical_loss_and_grads(self):
        """Remat changes what backward recomputes, never the math: every
        policy must produce the same loss and gradients."""
        from nos_tpu.models.llama import _REMAT_POLICIES

        tokens = jax.random.randint(
            jax.random.PRNGKey(7), (2, 16), 0, TINY.vocab_size, jnp.int32)
        ref_loss = ref_grads = None
        for policy in _REMAT_POLICIES:
            cfg = dataclasses.replace(TINY, remat_policy=policy)
            model = Llama(cfg)
            vs = model.init(jax.random.PRNGKey(0), tokens)

            def loss_fn(v):
                return model.apply(v, tokens, targets=tokens)

            loss, grads = jax.value_and_grad(loss_fn)(vs)
            if ref_loss is None:
                ref_loss, ref_grads = loss, grads
                continue
            assert jnp.allclose(loss, ref_loss, atol=1e-5), policy
            jax.tree_util.tree_map(
                lambda a, b: None if jnp.allclose(a, b, atol=1e-4)
                else (_ for _ in ()).throw(
                    AssertionError(f"grad mismatch under {policy}")),
                ref_grads, grads)


class TestScanLayers:
    """scan_layers is a compile-strategy change, never a math change:
    the scanned model at stacked params must reproduce the unrolled
    model exactly, with remat on and off (the interaction that made the
    bench opt out — rope captured into the scan body instead of riding
    as an nn.broadcast input — is pinned here)."""

    def _stacked_pair(self, remat):
        import flax.linen as nn

        from nos_tpu.models.llama import stack_layer_params

        tokens = jax.random.randint(
            jax.random.PRNGKey(7), (2, 32), 0, TINY.vocab_size, jnp.int32)
        cfg_u = dataclasses.replace(TINY, scan_layers=False, remat=remat,
                                    remat_policy="rots")
        cfg_s = dataclasses.replace(TINY, scan_layers=True, remat=remat,
                                    remat_policy="rots")
        model_u, model_s = Llama(cfg_u), Llama(cfg_s)
        vs = model_u.init(jax.random.PRNGKey(0), tokens)
        params = nn.meta.unbox(vs)["params"]
        stacked = stack_layer_params(params, TINY.num_layers)
        return model_u, model_s, params, stacked, tokens

    @pytest.mark.parametrize("remat", [True, False])
    def test_loss_matches_unrolled(self, remat):
        model_u, model_s, params, stacked, tokens = self._stacked_pair(remat)
        loss_u = model_u.apply({"params": params}, tokens, targets=tokens)
        loss_s = model_s.apply({"params": stacked}, tokens, targets=tokens)
        assert abs(float(loss_u) - float(loss_s)) < 1e-5

    @pytest.mark.parametrize("remat", [True, False])
    def test_grads_match_unrolled(self, remat):
        from nos_tpu.models.llama import stack_layer_params

        model_u, model_s, params, stacked, tokens = self._stacked_pair(remat)
        g_u = jax.grad(lambda p: model_u.apply(
            {"params": p}, tokens, targets=tokens))(params)
        g_s = jax.grad(lambda p: model_s.apply(
            {"params": p}, tokens, targets=tokens))(stacked)
        g_u_stacked = stack_layer_params(g_u, TINY.num_layers)
        for a, b in zip(jax.tree_util.tree_leaves(g_u_stacked),
                        jax.tree_util.tree_leaves(g_s)):
            scale = float(jnp.max(jnp.abs(a))) + 1e-9
            assert float(jnp.max(jnp.abs(a - b))) / scale < 1e-4


class TestBenchTrainConfig:
    def test_bench_350m_train_is_the_roofline_config(self):
        """The single source of truth bench_compute/cmd.train consume:
        scanned layers, flash kernels, 'rots' selective remat — and the
        same architecture as BENCH_350M."""
        from nos_tpu.models.llama import BENCH_350M, BENCH_350M_TRAIN

        assert BENCH_350M_TRAIN.scan_layers is True
        assert BENCH_350M_TRAIN.attn_impl == "flash"
        assert BENCH_350M_TRAIN.remat_policy == "rots"
        assert dataclasses.replace(
            BENCH_350M_TRAIN, attn_impl=BENCH_350M.attn_impl,
            remat_policy=BENCH_350M.remat_policy,
            scan_layers=BENCH_350M.scan_layers) == BENCH_350M

    def test_train_config_defaults_match(self):
        from nos_tpu.cmd.train import TrainConfig
        from nos_tpu.models.llama import BENCH_350M_TRAIN

        cfg = TrainConfig()
        assert cfg.attn_impl == BENCH_350M_TRAIN.attn_impl
        assert cfg.remat_policy == BENCH_350M_TRAIN.remat_policy
        assert cfg.scan_layers == BENCH_350M_TRAIN.scan_layers


class TestCollectiveOverlapFlags:
    def _env(self, **kw):
        return dict(kw)

    def test_applied_when_tpu_expected(self):
        from nos_tpu.parallel.mesh import (
            OVERLAP_XLA_FLAGS, enable_collective_overlap,
        )

        env = self._env(JAX_PLATFORMS="tpu")
        assert enable_collective_overlap(env, initialized=False)
        for flag in OVERLAP_XLA_FLAGS:
            assert flag in env["XLA_FLAGS"]

    def test_idempotent_and_preserves_user_flags(self):
        from nos_tpu.parallel.mesh import enable_collective_overlap

        env = self._env(JAX_PLATFORMS="tpu",
                        XLA_FLAGS="--xla_foo=1 "
                        "--xla_tpu_enable_latency_hiding_scheduler=false")
        assert enable_collective_overlap(env, initialized=False)
        first = env["XLA_FLAGS"]
        # the user's explicit =false pin was NOT overridden
        assert "--xla_tpu_enable_latency_hiding_scheduler=false" in first
        assert first.count("latency_hiding_scheduler") == 1
        assert enable_collective_overlap(env, initialized=False)
        assert env["XLA_FLAGS"] == first

    def test_opt_out_and_cpu_skip(self):
        from nos_tpu.parallel.mesh import enable_collective_overlap

        assert not enable_collective_overlap(
            self._env(JAX_PLATFORMS="tpu", NOS_TPU_NO_OVERLAP="1"),
            initialized=False)
        assert not enable_collective_overlap(
            self._env(JAX_PLATFORMS="cpu"), initialized=False)

    def test_too_late_after_backend_init(self):
        from nos_tpu.parallel.mesh import enable_collective_overlap

        env = self._env(JAX_PLATFORMS="tpu")
        assert not enable_collective_overlap(env, initialized=True)
        assert "XLA_FLAGS" not in env


class TestShardedTrainer:
    def test_fsdp_tp_sp_training_step(self):
        cfg = dataclasses.replace(TINY, attn_impl="ring")
        mesh = make_mesh(MeshSpec(dp=1, fsdp=2, tp=2, sp=2))
        tr = ShardedTrainer(cfg, mesh, batch_size=4, seq_len=32)
        state = tr.init_state(0)

        # param shardings: vocab over tp, embed over fsdp
        import flax.linen as nn
        unboxed = nn.unbox(state.params)
        assert unboxed["embed"].sharding.spec == jax.sharding.PartitionSpec(
            "tp", "fsdp")

        step = tr.train_step()
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size)
        losses = []
        for _ in range(8):
            state, loss = step(state, tokens)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_forward_jit(self):
        cfg = TINY
        mesh = make_mesh(MeshSpec(dp=1, fsdp=4, tp=2, sp=1))
        tr = ShardedTrainer(cfg, mesh, batch_size=4, seq_len=16)
        state = tr.init_state(0)
        fwd = tr.forward()
        logits = fwd(state.params, jnp.zeros((4, 16), jnp.int32))
        assert logits.shape == (4, 16, cfg.vocab_size)

    def test_cross_entropy_perfect_prediction(self):
        v = 8
        tokens = jnp.array([[1, 2, 3, 4]])
        # position i predicts token i+1
        next_tokens = jnp.concatenate([tokens[:, 1:], tokens[:, :1]], axis=1)
        logits = jax.nn.one_hot(next_tokens, v) * 100.0
        assert cross_entropy_loss(logits, tokens) < 1e-3


class TestChunkedLoss:
    def test_fused_loss_matches_logits_path(self):
        """__call__(tokens, targets=tokens) must equal
        cross_entropy_loss(__call__(tokens), tokens) — same math, chunked
        and head-fused."""
        import dataclasses

        from nos_tpu.models.llama import Llama, TINY
        from nos_tpu.models.train import cross_entropy_loss

        cfg = dataclasses.replace(TINY, loss_chunk=32, max_seq_len=128)
        model = Llama(cfg)
        tokens = jax.random.randint(
            jax.random.PRNGKey(0), (2, 128), 0, cfg.vocab_size, jnp.int32)
        params = model.init(jax.random.PRNGKey(1), tokens)
        logits = model.apply(params, tokens)
        ref = cross_entropy_loss(logits, tokens)
        fused = model.apply(params, tokens, targets=tokens)
        assert abs(float(ref) - float(fused)) < 1e-4

    def test_fused_loss_grads_match(self):
        import dataclasses

        from nos_tpu.models.llama import Llama, TINY
        from nos_tpu.models.train import cross_entropy_loss

        cfg = dataclasses.replace(TINY, loss_chunk=64, max_seq_len=128)
        model = Llama(cfg)
        tokens = jax.random.randint(
            jax.random.PRNGKey(0), (2, 128), 0, cfg.vocab_size, jnp.int32)
        params = model.init(jax.random.PRNGKey(1), tokens)

        g_ref = jax.grad(lambda p: cross_entropy_loss(
            model.apply(p, tokens), tokens))(params)
        g_fused = jax.grad(lambda p: model.apply(
            p, tokens, targets=tokens))(params)
        flat_r = jax.tree_util.tree_leaves(g_ref)
        flat_f = jax.tree_util.tree_leaves(g_fused)
        for a, b in zip(flat_r, flat_f):
            scale = float(jnp.max(jnp.abs(a))) + 1e-9
            assert float(jnp.max(jnp.abs(a - b))) / scale < 1e-3

    def test_uneven_chunk_falls_back_whole(self):
        import dataclasses

        from nos_tpu.models.llama import Llama, TINY
        from nos_tpu.models.train import cross_entropy_loss

        cfg = dataclasses.replace(TINY, loss_chunk=48, max_seq_len=128)
        model = Llama(cfg)
        tokens = jax.random.randint(
            jax.random.PRNGKey(0), (1, 128), 0, cfg.vocab_size, jnp.int32)
        params = model.init(jax.random.PRNGKey(1), tokens)
        ref = cross_entropy_loss(model.apply(params, tokens), tokens)
        fused = model.apply(params, tokens, targets=tokens)
        assert abs(float(ref) - float(fused)) < 1e-4


class TestGenerate:
    def _model(self):
        import dataclasses

        from nos_tpu.models.llama import Llama, TINY

        cfg = dataclasses.replace(TINY, max_seq_len=64)
        model = Llama(cfg)
        prompt = jax.random.randint(
            jax.random.PRNGKey(0), (2, 5), 0, cfg.vocab_size, jnp.int32)
        params = model.init(jax.random.PRNGKey(1), prompt)
        return model, params, prompt

    def test_greedy_matches_stepwise_argmax(self):
        """One fused lax.scan decode must equal the naive python loop."""
        from nos_tpu.models.generate import generate

        model, params, prompt = self._model()
        out = generate(model, params, prompt, steps=6)
        assert out.shape == (2, 11)
        assert jnp.array_equal(out[:, :5], prompt)

        buf = prompt
        for _ in range(6):
            logits = model.apply(params, buf)
            nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            buf = jnp.concatenate([buf, nxt[:, None]], axis=1)
        assert jnp.array_equal(out, buf)

    def test_sampling_is_seeded_and_jit_compatible(self):
        from nos_tpu.models.generate import make_generate

        model, params, prompt = self._model()
        gen = make_generate(model, steps=4, temperature=0.8)
        a = gen(params, prompt, jax.random.PRNGKey(7))
        b = gen(params, prompt, jax.random.PRNGKey(7))
        c = gen(params, prompt, jax.random.PRNGKey(8))
        assert jnp.array_equal(a, b)
        assert a.shape == (2, 9)
        assert not jnp.array_equal(a, c)  # different seed, different path


    def test_over_length_decode_rejected(self):
        from nos_tpu.models.generate import generate

        model, params, prompt = self._model()  # max_seq_len 64
        with pytest.raises(ValueError, match="max_seq_len"):
            generate(model, params, prompt, steps=60)


class TestGraftEntry:
    def test_dryrun_multichip(self):
        import sys
        sys.path.insert(0, "/root/repo")
        from __graft_entry__ import dryrun_multichip
        dryrun_multichip(8)
