"""Defragmentation plane + malleable gangs.

Covers the PR-14 tentpole and satellites:
- subset()/fork() dirty-set independence (the defragmenter is the first
  caller to fork a subset; refused-forked-parent both ways);
- the DefragProposer lifecycle: frag detection, what-if relocation,
  payback scoring, actuation (annotations + ledger holds + evictions),
  drain cleanup, serving-tier shields and rate limiting;
- randomized conservation of the waste attribution during defrag
  actuation: chip-seconds spent draining land in drain/actuation,
  never double-counted with frag_stranded;
- elastic dp gangs: grow pass, shrink-before-evict rung, dp-resize
  stamp and the cmd/train checkpoint hook;
- the `obs waste` frag culprit ranking by stranded chip-seconds.
"""

import random

import pytest

from nos_tpu.api import constants as C
from nos_tpu.kube.client import APIServer, KIND_NODE, KIND_POD
from nos_tpu.obs import journal as J, scoped as obs_scoped
from nos_tpu.obs.journal import DecisionJournal
from nos_tpu.obs.ledger import (
    ChipSecondLedger, conservation_ok,
)
from nos_tpu.partitioning.core import DefragProposer, SnapshotError
from nos_tpu.partitioning.slicepart import (
    SliceProfileCalculator, SliceSnapshotTaker,
)
from nos_tpu.partitioning.state import ClusterState
from nos_tpu.testing.factory import make_slice_pod, make_tpu_node


def snapshot_for(nodes_with_pods):
    state = ClusterState()
    for node, pods in nodes_with_pods:
        state.update_node(node, pods)
    return SliceSnapshotTaker().take_snapshot(state)


def fragged_host(name, idx, used=1, pod_ns="default", progress=None):
    """A v5e host carved into 1x1s with `used` of them occupied by a
    movable filler pod.  Returns (node, pods)."""
    node = make_tpu_node(
        name, host_index=idx,
        status_geometry={"free": {"1x1": 8 - used}, "used": {"1x1": used}})
    pods = []
    for i in range(used):
        annotations = {}
        if progress is not None:
            annotations[C.ANNOT_JOB_PROGRESS] = str(progress)
        pods.append(make_slice_pod(
            "1x1", 1, name=f"{name}-filler-{i}", namespace=pod_ns,
            node_name=name, phase="Running", annotations=annotations))
    return node, pods


class TestSubsetForkIsolation:
    """Satellite: subset() + defrag what-if forks must not share
    dirty-set state with the live controller snapshot."""

    def _snap(self):
        return snapshot_for([
            (make_tpu_node(f"n{i}", host_index=i,
                           status_geometry={"free": {"2x4": 1}}), [])
            for i in range(3)
        ])

    def test_forked_parent_refuses_subset_and_clone(self):
        snap = self._snap()
        snap.fork()
        with pytest.raises(SnapshotError):
            snap.subset(["n0"])
        with pytest.raises(SnapshotError):
            snap.clone()
        snap.revert()
        assert snap.subset(["n0"]).nodes().keys() == {"n0"}

    def test_forked_subset_refuses_further_subset(self):
        sub = self._snap().subset(["n0", "n1"])
        sub.fork()
        with pytest.raises(SnapshotError):
            sub.subset(["n0"])

    def test_subset_fork_never_leaks_into_parent(self):
        snap = self._snap()
        sub = snap.subset(["n0", "n1"])
        sub.fork()
        # COW mutation inside the subset's fork
        assert sub.get_node_for_write("n0").update_geometry_for(
            {"2x2": 2})
        assert sub.cow_clones == 1
        # the parent saw nothing: no dirty set, no clones, original
        # object and geometry untouched
        assert not snap.forked
        assert snap.cow_clones == 0
        assert snap.get_node("n0").geometries() == {0: {"2x4": 1}}
        # ... and the parent can fork independently while the subset's
        # fork is live (disjoint dirty sets by construction)
        snap.fork()
        snap.get_node_for_write("n2").update_geometry_for({"2x2": 2})
        snap.revert()
        assert snap.get_node("n2").geometries() == {0: {"2x4": 1}}
        # subset revert restores the subset's own view from ITS dirty
        # set — and the restored object IS the shared pristine one
        sub.revert()
        assert sub.get_node("n0") is snap.get_node("n0")
        assert sub.get_node("n0").geometries() == {0: {"2x4": 1}}

    def test_subset_commit_stays_in_subset(self):
        snap = self._snap()
        sub = snap.subset(["n0"])
        sub.fork()
        sub.get_node_for_write("n0").update_geometry_for({"2x2": 2})
        sub.commit()
        assert sub.get_node("n0").geometries() == {0: {"2x2": 2}}
        # a committed COW clone belongs to the subset alone
        assert snap.get_node("n0").geometries() == {0: {"2x4": 1}}


class DefragHarness:
    """3 fragmented hosts + 1 busy host, one pending whole-host (2x4)
    pod that no carve can place: the canonical frag regime."""

    def __init__(self, n_fragged=3, progress=0.2, pending_shape="2x4",
                 serving_on=None):
        self.api = APIServer()
        self.clock_now = [0.0]
        self.nodes_with_pods = []
        for i in range(n_fragged):
            node, pods = fragged_host(f"h{i}", i, progress=progress)
            if serving_on == f"h{i}":
                pods[0].metadata.labels[C.LABEL_TIER] = C.TIER_SERVING
            self.nodes_with_pods.append((node, pods))
            self.api.create(KIND_NODE, node)
            for p in pods:
                self.api.create(KIND_POD, p)
        self.pending = make_slice_pod(pending_shape, 1, name="big",
                                      namespace="default")
        self.pending.mark_unschedulable("no fit")
        self.api.create(KIND_POD, self.pending)
        self.ledger = ChipSecondLedger(clock=lambda: self.clock_now[0])
        self.journal = DecisionJournal(clock=lambda: self.clock_now[0])

    def snapshot(self):
        return snapshot_for(self.nodes_with_pods)

    def proposer(self, **kw):
        kw.setdefault("interval_s", 5.0)
        kw.setdefault("payback_min", 1.0)
        return DefragProposer(
            self.api, "slice", SliceProfileCalculator(),
            clock=lambda: self.clock_now[0], **kw)

    def run_steps(self, proposer, steps=2, dt=10.0):
        applied = []
        with obs_scoped(journal=self.journal, ledger=self.ledger):
            for _ in range(steps):
                self.clock_now[0] += dt
                applied.append(
                    proposer.step(self.snapshot(), [self.pending]))
        return [a for a in applied if a]


class TestDefragProposer:
    def test_persistence_gate_then_apply(self):
        h = DefragHarness()
        p = h.proposer()
        with obs_scoped(journal=h.journal, ledger=h.ledger):
            h.clock_now[0] += 10.0
            # first sight: the unit is remembered, nothing moves
            assert p.step(h.snapshot(), [h.pending]) is None
            assert h.journal.events(category=J.DEFRAG_APPLIED) == []
            h.clock_now[0] += 10.0
            pid = p.step(h.snapshot(), [h.pending])
        assert pid is not None
        applied = h.journal.events(category=J.DEFRAG_APPLIED)
        assert len(applied) == 1
        rec = applied[0]
        assert rec.subject == pid
        hosts = rec.attrs["hosts"]
        assert len(hosts) == 1
        # the window host was annotated, its filler evicted, and the
        # ledger carries a DRAIN hold (never frag_stranded)
        node = h.api.get(KIND_NODE, hosts[0])
        assert node.metadata.annotations[C.ANNOT_DEFRAG_DRAIN] == pid
        assert h.ledger.holds()[hosts[0]]["drain"]["proposal"] == pid
        live = {pod.metadata.name for pod in h.api.list(KIND_POD)}
        assert f"{hosts[0]}-filler-0" not in live
        assert "big" in live            # the demand itself is untouched

    def test_cleanup_releases_drained_window(self):
        h = DefragHarness()
        p = h.proposer()
        (pid,) = h.run_steps(p)
        rec = h.journal.events(category=J.DEFRAG_APPLIED)[0]
        host = rec.attrs["hosts"][0]
        # the victim is gone from the store already (synchronous
        # delete), so the next step's cleanup releases the window
        with obs_scoped(journal=h.journal, ledger=h.ledger):
            h.clock_now[0] += 10.0
            p.step(h.snapshot(), [h.pending])
        node = h.api.get(KIND_NODE, host)
        assert C.ANNOT_DEFRAG_DRAIN not in node.metadata.annotations
        assert host not in h.ledger.holds()

    def test_payback_threshold_rejects(self):
        h = DefragHarness()
        p = h.proposer(payback_min=1e9)
        assert h.run_steps(p) == []
        assert h.journal.events(category=J.DEFRAG_APPLIED) == []
        rejected = h.journal.events(category=J.DEFRAG_REJECTED)
        assert rejected and rejected[0].attrs["reason"] == "payback"
        # propose-only mode moved NOTHING: store intact, no annotations
        assert len(h.api.list(KIND_POD)) == 4
        for node in h.api.list(KIND_NODE):
            assert C.ANNOT_DEFRAG_DRAIN not in node.metadata.annotations
        assert h.ledger.holds() == {}

    def test_serving_tier_is_never_touched(self):
        # every fragged host carries a serving pod: no window is
        # drainable, nothing is proposed
        h = DefragHarness(n_fragged=1, serving_on="h0")
        p = h.proposer()
        assert h.run_steps(p) == []
        assert h.journal.events(category=J.DEFRAG_PROPOSED) == []
        assert len(h.api.list(KIND_POD)) == 2

    def test_near_done_pods_pin_their_host(self):
        h = DefragHarness(n_fragged=1, progress=0.9)  # past spare 0.75
        p = h.proposer()
        assert h.run_steps(p) == []
        assert len(h.api.list(KIND_POD)) == 2

    def test_no_proposal_when_demand_exceeds_free(self):
        # a genuinely short cluster (pending 2x4 but only 1 host with
        # 7 fragged free chips + nothing else) is not a frag problem
        h = DefragHarness(n_fragged=1)
        p = h.proposer()
        assert h.run_steps(p) == []

    def test_rate_limit_one_in_flight(self):
        h = DefragHarness()
        # second pending whole-host pod: only one proposal may fly
        second = make_slice_pod("2x4", 1, name="big2",
                                namespace="default")
        second.mark_unschedulable("no fit")
        h.api.create(KIND_POD, second)
        p = h.proposer()
        with obs_scoped(journal=h.journal, ledger=h.ledger):
            h.clock_now[0] += 10.0
            p.step(h.snapshot(), [h.pending, second])
            h.clock_now[0] += 10.0
            first = p.step(h.snapshot(), [h.pending, second])
            # keep the drain outstanding: re-bind a pod onto the drained
            # host so cleanup cannot release it
            host = h.journal.events(
                category=J.DEFRAG_APPLIED)[0].attrs["hosts"][0]
            squatter = make_slice_pod("1x1", 1, name="squat",
                                      node_name=host, phase="Running")
            h.api.create(KIND_POD, squatter)
            h.clock_now[0] += 10.0
            again = p.step(h.snapshot(), [h.pending, second])
        assert first is not None and again is None
        assert len(h.journal.events(category=J.DEFRAG_APPLIED)) == 1

    def test_drain_timeout_aborts_and_heals(self):
        h = DefragHarness()
        p = h.proposer(drain_timeout_s=15.0, demand_cooldown_s=1000.0)
        (pid,) = h.run_steps(p)
        host = h.journal.events(
            category=J.DEFRAG_APPLIED)[0].attrs["hosts"][0]
        squatter = make_slice_pod("1x1", 1, name="squat",
                                  node_name=host, phase="Running")
        h.api.create(KIND_POD, squatter)
        with obs_scoped(journal=h.journal, ledger=h.ledger):
            h.clock_now[0] += 30.0      # past the drain deadline
            p.step(h.snapshot(), [h.pending])
        node = h.api.get(KIND_NODE, host)
        assert C.ANNOT_DEFRAG_DRAIN not in node.metadata.annotations
        assert host not in h.ledger.holds()
        rejected = h.journal.events(category=J.DEFRAG_REJECTED)
        assert any(r.attrs.get("reason") == "drain-timeout"
                   and r.subject == pid for r in rejected)


class TestConservationDuringDefrag:
    """Satellite: randomized conservation property — chip-seconds spent
    draining for a re-carve land in drain/actuation, never
    double-counted with frag_stranded."""

    def test_attribution_is_exclusive_and_bounded(self):
        from nos_tpu.scheduler.scheduler import attribute_free_chips

        rng = random.Random(1405)
        for _ in range(500):
            free = rng.uniform(0.0, 16.0)
            hold: dict | None = None
            if rng.random() < 0.5:
                hold = {k: {} for k in
                        rng.sample(["quarantine", "actuation", "drain"],
                                   rng.randint(1, 3))}
            reserved = rng.random() < 0.3
            demand = rng.random() < 0.7
            rejected = rng.random() < 0.5
            qb = rng.choice([0.0, rng.uniform(0.0, 20.0)])
            gb = rng.choice([0.0, rng.uniform(0.0, 20.0)])
            cat, take, qb2, gb2 = attribute_free_chips(
                free, hold, reserved, demand, rejected, qb, gb)
            # exactly one category, bounded take, budgets only shrink
            assert 0.0 <= take <= free + 1e-12
            assert 0.0 <= qb2 <= qb and 0.0 <= gb2 <= gb
            spent = (qb - qb2) + (gb - gb2)
            if cat == "quota_stranded":
                assert qb - qb2 == pytest.approx(take)
                assert gb2 == gb
            elif cat == "gang_wait" and hold is None and not reserved:
                assert gb - gb2 == pytest.approx(take)
                assert qb2 == qb
            else:
                assert spent == pytest.approx(0.0)
            # a defrag/drain hold can NEVER read frag_stranded —
            # the double-count the ledger's invariant forbids
            if hold is not None:
                assert cat in ("quarantine", "actuation", "drain")
                assert cat != "frag_stranded"
                if "drain" in hold and "quarantine" not in hold \
                        and "actuation" not in hold:
                    assert cat == "drain"
                assert take == pytest.approx(free)

    def test_randomized_ledger_conservation_with_drain_churn(self):
        """Drive the real ledger through randomized defrag-shaped
        waterfalls — holds toggling mid-trace, frag/drain flipping on
        the same nodes — and assert exact per-pool conservation."""
        from nos_tpu.scheduler.scheduler import attribute_free_chips

        rng = random.Random(77)
        now = [0.0]
        ledger = ChipSecondLedger(clock=lambda: now[0])
        nodes = [f"n{i}" for i in range(6)]
        cap = {n: 8.0 for n in nodes}
        for _ in range(200):
            now[0] += rng.uniform(0.1, 2.0)
            # defrag actuation churn: drain holds appear and resolve
            for n in nodes:
                if rng.random() < 0.2:
                    ledger.set_hold(n, "drain", owner="defrag-slice",
                                    proposal="dfrg-x")
                elif rng.random() < 0.2:
                    ledger.clear_hold(n, "drain", owner="defrag-slice")
            holds = ledger.holds()
            cats: dict[str, float] = {}
            qb = rng.uniform(0.0, 10.0)
            gb = rng.uniform(0.0, 10.0)
            used_total = 0.0
            for n in nodes:
                used = rng.uniform(0.0, cap[n])
                used_total += used
                free = cap[n] - used
                cat, take, qb, gb = attribute_free_chips(
                    free, holds.get(n), rng.random() < 0.2, True,
                    rng.random() < 0.5, qb, gb)
                cats[cat] = cats.get(cat, 0.0) + take
                if take < free:
                    cats["idle_no_demand"] = \
                        cats.get("idle_no_demand", 0.0) + (free - take)
            cats["productive"] = used_total
            ledger.observe({"pool-0": {
                "capacity": sum(cap.values()), "categories": cats}})
        now[0] += 1.0
        ledger.observe({})      # final accrual
        report = ledger.report()
        assert conservation_ok(report)
        assert report["overcommit_events"] == 0


class TestElasticGangs:
    def _gang_pod(self, name, gang="eg", node_name="", lo=1, hi=4,
                  namespace="default", phase="Pending"):
        return make_slice_pod(
            "1x2", 1, name=name, namespace=namespace,
            node_name=node_name, phase=phase,
            labels={C.LABEL_POD_GROUP: gang},
            annotations={C.ANNOT_ELASTIC: C.ELASTIC_DP,
                         C.ANNOT_MIN_REPLICAS: str(lo),
                         C.ANNOT_MAX_REPLICAS: str(hi)})

    def test_replica_bounds_parse_and_degrade(self):
        from nos_tpu.utils.pod_util import (
            elastic_replica_bounds, is_elastic_dp,
        )

        pod = self._gang_pod("m0")
        assert is_elastic_dp(pod)
        assert elastic_replica_bounds(pod) == (1, 4)
        pod.metadata.annotations[C.ANNOT_MAX_REPLICAS] = "garbage"
        assert elastic_replica_bounds(pod) is None      # rigid, not inf
        bare = make_slice_pod("1x2", 1, annotations={
            C.ANNOT_ELASTIC: C.ELASTIC_DP})
        assert not is_elastic_dp(bare)                  # no gang: rigid

    def test_grow_creates_one_member_and_stamps_resize(self):
        from nos_tpu.scheduler.elastic import maybe_grow
        from nos_tpu.scheduler.framework import (
            Framework, NodeInfo, NodeResourcesFit, SharedLister,
        )

        api = APIServer()
        node = make_tpu_node("g0", status_geometry={"free": {"1x2": 4}})
        api.create(KIND_NODE, node)
        members = [self._gang_pod(f"m{i}", node_name="g0",
                                  phase="Running") for i in range(2)]
        for m in members:
            api.create(KIND_POD, m)
        ni = NodeInfo(node=node)
        for m in members:
            ni.add_pod(m)
        lister = SharedLister([ni])
        journal = DecisionJournal()
        with obs_scoped(journal=journal):
            created = maybe_grow(api, Framework([NodeResourcesFit()]),
                                 lister, budget=1, clock=lambda: 42.0)
        assert created == 1
        clones = [p for p in api.list(KIND_POD)
                  if p.metadata.name.startswith("eg-e")]
        assert len(clones) == 1
        clone = clones[0]
        assert clone.status.phase == "Pending"
        assert not clone.spec.node_name
        assert clone.metadata.creation_timestamp == 42.0
        assert clone.metadata.labels[C.LABEL_POD_GROUP] == "eg"
        # survivors carry the dp-resize stamp with the NEW count
        for m in members:
            live = api.get(KIND_POD, m.metadata.name, "default")
            assert live.metadata.annotations[C.ANNOT_DP_RESIZE] == "3"
        recs = journal.events(category=J.GANG_RESIZED)
        assert recs and recs[0].attrs["direction"] == "grow"
        # at max: no further growth
        with obs_scoped(journal=journal):
            grown = maybe_grow(api, Framework([NodeResourcesFit()]),
                               lister, budget=5, clock=lambda: 43.0)
        assert grown == 0       # pending clone blocks regrowth

    def test_grow_respects_max_and_full_nodes(self):
        from nos_tpu.scheduler.elastic import maybe_grow
        from nos_tpu.scheduler.framework import (
            Framework, NodeInfo, NodeResourcesFit, SharedLister,
        )

        api = APIServer()
        node = make_tpu_node("g0", status_geometry={"used": {"1x2": 4}})
        api.create(KIND_NODE, node)
        members = [self._gang_pod(f"m{i}", node_name="g0", hi=2,
                                  phase="Running") for i in range(2)]
        for m in members:
            api.create(KIND_POD, m)
        lister = SharedLister([NodeInfo(node=node)])
        assert maybe_grow(api, Framework([NodeResourcesFit()]),
                          lister, budget=3) == 0

    def test_shrink_rung_in_victim_walk(self):
        """An elastic member above min is selected BEFORE a best-effort
        single, dies alone (no gang amplification), and the survivors
        get the resize stamp."""
        from nos_tpu.quota import TPUResourceCalculator
        from nos_tpu.scheduler.capacityscheduling import (
            CapacityScheduling, ELASTIC_QUOTA_SNAPSHOT_KEY,
            PRE_FILTER_STATE_KEY, PreFilterState,
        )
        from nos_tpu.quota import ElasticQuotaInfos
        from nos_tpu.scheduler.framework import (
            CycleState, Framework, NodeInfo, NodeResourcesFit,
        )

        api = APIServer()
        node = make_tpu_node("h0", status_geometry={"free": {"1x2": 4}})
        api.create(KIND_NODE, node)
        ni = NodeInfo(node=node)
        members = [self._gang_pod(f"m{i}", node_name="h0", lo=2, hi=4,
                                  phase="Running") for i in range(3)]
        be = make_slice_pod(
            "1x2", 1, name="scav", node_name="h0", phase="Running",
            labels={C.LABEL_TIER: C.TIER_BEST_EFFORT})
        for p in [*members, be]:
            api.create(KIND_POD, p)
            ni.add_pod(p)
        calc = TPUResourceCalculator()
        cs = CapacityScheduling(calc)
        cs.set_framework(Framework([NodeResourcesFit()]))
        cs._api = api
        preemptor = make_slice_pod("1x2", 1, name="pree", priority=10)
        state = CycleState()
        state[ELASTIC_QUOTA_SNAPSHOT_KEY] = ElasticQuotaInfos()
        state[PRE_FILTER_STATE_KEY] = PreFilterState(
            calc.compute_pod_request(preemptor))
        shrink: set[str] = set()
        victims, _, status = cs._select_victims_on_node(
            state, preemptor, ni, pdbs=[], shrink_out=shrink)
        assert status.is_success and victims
        # the first death is the shrinkable elastic member, not the
        # best-effort scavenger and not the whole gang
        assert victims[0].metadata.name.startswith("m")
        assert victims[0].metadata.uid in shrink
        # shrink never amplifies: the eviction set is the member alone
        assert [p.key for p in cs._eviction_set(
            victims[0], None, shrink)] == [victims[0].key]
        # at most (live - min) = 1 member shrinks; any further elastic
        # victims in the same walk would amplify
        assert sum(1 for v in victims if v.metadata.uid in shrink) <= 1
        # drive the actual eviction: one member deleted, gang survives
        journal = DecisionJournal()
        with obs_scoped(journal=journal):
            cs._evict_all([victims[0]], shrink)
        alive = [p for p in api.list(KIND_POD)
                 if p.metadata.labels.get(C.LABEL_POD_GROUP) == "eg"]
        assert len(alive) == 2
        for m in alive:
            assert m.metadata.annotations[C.ANNOT_DP_RESIZE] == "2"
        recs = journal.events(category=J.GANG_RESIZED)
        assert recs and recs[0].attrs["direction"] == "shrink"

    def test_train_checkpoint_honors_resize(self, tmp_path):
        from nos_tpu.cmd.train import (
            boot_world_size, read_resize_signal,
        )

        assert boot_world_size({}) == 1
        assert boot_world_size(
            {"TPU_WORKER_HOSTNAMES": "a,b,c"}) == 3
        api = APIServer()
        pod = self._gang_pod("m0", node_name="h0", phase="Running")
        api.create(KIND_POD, pod)
        assert read_resize_signal(api, "m0", "default") is None
        pod2 = api.get(KIND_POD, "m0", "default")
        pod2.metadata.annotations[C.ANNOT_DP_RESIZE] = "3"
        api.patch(KIND_POD, "m0", "default",
                  mutate=lambda p: p.metadata.annotations.update(
                      {C.ANNOT_DP_RESIZE: "3"}))
        assert read_resize_signal(api, "m0", "default") == 3
        api.patch(KIND_POD, "m0", "default",
                  mutate=lambda p: p.metadata.annotations.update(
                      {C.ANNOT_DP_RESIZE: "garbage"}))
        assert read_resize_signal(api, "m0", "default") is None


class TestFragCulpritRanking:
    """Satellite: when multiple classes strand the same pool, the
    culprit join ranks by stranded chip-seconds, not recency."""

    def test_evidence_ranked_by_stranded_chip_seconds(self):
        from nos_tpu.cmd.assembly import build_scheduler

        api = APIServer()
        api.create(KIND_NODE, make_tpu_node(
            "h0", status_geometry={"free": {"1x1": 8}}))
        ledger = ChipSecondLedger(clock=lambda: now[0])
        now = [0.0]
        sched = build_scheduler(api, clock=lambda: now[0])
        # two frag-blocked classes: slice-2x4 (8 chips) has waited with
        # far more blocked demand than slice-2x2 (4 chips), but 2x2's
        # rejection is NEWER every cycle
        with obs_scoped(ledger=ledger):
            for _ in range(5):
                now[0] += 1.0
                sched._waste_rejection_maps = [{"h0": "no fit"}]
                sched._waste_frag_counts = {"slice-2x4": 1,
                                            "slice-2x2": 1}
                sched._waste_frag_chips = {"slice-2x4": 8.0,
                                           "slice-2x2": 4.0}
                sched._observe_waste({"slice-2x4": 1, "slice-2x2": 1})
            now[0] += 1.0
            ledger.observe({})
        report = ledger.report()
        ev = report["pools"]["pod-0"]["evidence"]["frag_stranded"]
        assert ev["class"] == "slice-2x4"
        ranked = [row["class"] for row in ev["classes"]]
        assert ranked[0] == "slice-2x4"
        assert ev["classes"][0]["stranded_chip_seconds"] > \
            ev["classes"][1]["stranded_chip_seconds"]

    def test_waste_culprit_renders_ranking_and_defrag_join(self, capsys):
        from nos_tpu.obs.__main__ import _waste_culprit

        journal = [
            {"seq": 1, "category": J.POD_REJECTED, "subject": "ns/p1",
             "attrs": {"class": "slice-2x4", "message": "no fit"}},
            {"seq": 2, "category": J.DEFRAG_APPLIED, "subject": "dfrg-1",
             "attrs": {"demand_class": "slice-2x4", "hosts": ["h0"],
                       "unlocked_chips": 6.0, "payback": 3.2}},
        ]
        evidence = {
            "class": "slice-2x4", "rejected_nodes": 3,
            "classes": [
                {"class": "slice-2x4", "stranded_chip_seconds": 40.0},
                {"class": "slice-2x2", "stranded_chip_seconds": 5.0},
            ],
        }
        lines = _waste_culprit(journal, "frag_stranded", evidence)
        text = "\n".join(lines)
        assert "culprit class slice-2x4" in text
        assert "also stranding: class slice-2x2" in text
        assert "dfrg-1" in text and "applied" in text
