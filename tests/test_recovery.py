"""Self-healing node-loss recovery tests (ISSUE 15): the displaced
head-of-line contract, the rebind histogram, warm-spare promotion, the
missed-heartbeat failure detector, drain-then-migrate, the
restart-cost-aware victim walk, the disabled-path byte-identity, and a
chaos scenario that kills a node mid-handshake under lockcheck +
guard_state (docs/scheduler.md, "Self-healing node-loss recovery")."""

from __future__ import annotations

import pytest

from nos_tpu import obs
from nos_tpu.api import constants as C
from nos_tpu.cmd.assembly import build_scheduler
from nos_tpu.controllers.node_controller import NodeController
from nos_tpu.controllers.pod_controller import PodController
from nos_tpu.controllers.sliceagent.agent import SliceAgent
from nos_tpu.device import default_tpu_runtime
from nos_tpu.device.fake import FakePodResources, FakeTpuRuntime
from nos_tpu.exporter.metrics import REGISTRY
from nos_tpu.kube.client import APIServer, KIND_NODE, KIND_POD
from nos_tpu.kube.objects import PENDING, RUNNING
from nos_tpu.obs import journal as J
from nos_tpu.obs.journal import DecisionJournal
from nos_tpu.obs.ledger import ChipSecondLedger, DRAIN, conservation_ok
from nos_tpu.partitioning.core import (
    REASON_SUSPECT, SelfHealingPolicy, QuarantineList, is_warm_spare,
)
from nos_tpu.partitioning.slicepart import SliceNodeInitializer
from nos_tpu.partitioning.slicepart.factory import (
    new_slice_partitioner_controller,
)
from nos_tpu.partitioning.state import ClusterState
from nos_tpu.testing.chaos import ChaosAPIServer
from nos_tpu.testing.factory import (
    admit_all, make_slice_pod, make_tpu_node,
)
from nos_tpu.testing.lockcheck import LockGraph, guard_state, unguard_all
from nos_tpu.topology import V5E
from nos_tpu.utils.pod_util import (
    admission_rank, displaced_value, displacement, job_progress,
)


# ---------------------------------------------------------------------------
# The displacement contract (utils/pod_util)
# ---------------------------------------------------------------------------


class TestDisplacementContract:
    def test_value_roundtrip(self):
        pod = make_slice_pod("2x2", 1, name="p", annotations={
            C.ANNOT_DISPLACED: displaced_value("node-loss", 153.25)})
        assert displacement(pod) == ("node-loss", 153.25)

    @pytest.mark.parametrize("raw", [
        "", "node-loss", "node-loss@", "@1.0", "node-loss@nan",
        "node-loss@inf", "node-loss@abc",
    ])
    def test_garbage_degrades_to_not_displaced(self, raw):
        """A malformed stamp must read not-displaced (normal rank),
        never grant a permanent head-of-line boost."""
        pod = make_slice_pod("2x2", 1, name="p",
                             annotations={C.ANNOT_DISPLACED: raw})
        assert displacement(pod) is None
        assert admission_rank(pod, now=10.0, age_cap_s=300.0) == 2

    def test_rank_order_serving_displaced_batch_best_effort(self):
        serving = make_slice_pod(
            "2x2", 1, name="s",
            labels={C.LABEL_TIER: C.TIER_SERVING})
        displaced = make_slice_pod(
            "2x2", 1, name="d",
            annotations={C.ANNOT_DISPLACED:
                         displaced_value("node-loss", 5.0)})
        batch = make_slice_pod("2x2", 1, name="b")
        be = make_slice_pod(
            "2x2", 1, name="e",
            labels={C.LABEL_TIER: C.TIER_BEST_EFFORT})
        ranks = [admission_rank(p, now=6.0, age_cap_s=300.0)
                 for p in (serving, displaced, batch, be)]
        assert ranks == sorted(ranks)
        assert ranks[0] < ranks[1] < ranks[2] < ranks[3]

    def test_displaced_never_outranks_serving(self):
        serving_displaced = make_slice_pod(
            "2x2", 1, name="sd",
            labels={C.LABEL_TIER: C.TIER_SERVING},
            annotations={C.ANNOT_DISPLACED:
                         displaced_value("node-loss", 5.0)})
        assert admission_rank(serving_displaced, now=6.0,
                              age_cap_s=300.0) == 0

    def test_anti_starvation_age_cap(self):
        pod = make_slice_pod(
            "2x2", 1, name="d",
            annotations={C.ANNOT_DISPLACED:
                         displaced_value("node-loss", 0.0)})
        assert admission_rank(pod, now=299.0, age_cap_s=300.0) == 1
        # past the cap the boost expires: plain batch again
        assert admission_rank(pod, now=301.0, age_cap_s=300.0) == 2
        # cap 0 = no expiry
        assert admission_rank(pod, now=10_000.0, age_cap_s=0.0) == 1

    def test_job_progress_parses_and_degrades(self):
        pod = make_slice_pod("2x2", 1, name="p", annotations={
            C.ANNOT_JOB_PROGRESS: "0.4"})
        assert job_progress(pod) == pytest.approx(0.4)
        for raw, want in [("", 0.0), ("junk", 0.0), ("inf", 0.0),
                          ("-3", 0.0), ("7", 1.0)]:
            pod.metadata.annotations[C.ANNOT_JOB_PROGRESS] = raw
            assert job_progress(pod) == want


# ---------------------------------------------------------------------------
# Displaced head-of-line + rebind histogram (scheduler e2e)
# ---------------------------------------------------------------------------


def _one_slot_cluster(clock):
    """One host advertising exactly one free 2x2 slot."""
    api = APIServer()
    api.create(KIND_NODE, make_tpu_node(
        "h0", pod_id="pod-0", host_index=0,
        status_geometry={"free": {"2x2": 1}}))
    sched = build_scheduler(api, 16, clock=lambda: clock[0])
    return api, sched


class TestDisplacedHeadOfLine:
    def test_displaced_binds_before_older_batch(self):
        clock = [100.0]
        api, sched = _one_slot_cluster(clock)
        api.create(KIND_POD, make_slice_pod(
            "2x2", 1, name="old-batch", creation_timestamp=1.0))
        api.create(KIND_POD, make_slice_pod(
            "2x2", 1, name="victim", creation_timestamp=2.0,
            annotations={C.ANNOT_DISPLACED:
                         displaced_value("node-loss", 99.0)}))
        sched.run_cycle()
        assert api.get(KIND_POD, "victim", "default").spec.node_name == "h0"
        assert not api.get(KIND_POD, "old-batch", "default").spec.node_name

    def test_expired_boost_yields_to_fifo(self):
        """Past the age cap the displaced pod is plain batch again —
        the OLDER batch pod wins the slot (anti-starvation)."""
        clock = [1000.0]
        api, sched = _one_slot_cluster(clock)
        api.create(KIND_POD, make_slice_pod(
            "2x2", 1, name="old-batch", creation_timestamp=1.0))
        api.create(KIND_POD, make_slice_pod(
            "2x2", 1, name="victim", creation_timestamp=2.0,
            annotations={C.ANNOT_DISPLACED:
                         displaced_value("node-loss", 10.0)}))
        sched.run_cycle()
        assert api.get(KIND_POD, "old-batch", "default").spec.node_name == "h0"
        assert not api.get(KIND_POD, "victim", "default").spec.node_name

    def test_rebind_observed_journaled_and_stamp_cleared(self):
        clock = [50.0]
        api, sched = _one_slot_cluster(clock)
        journal = DecisionJournal(clock=lambda: clock[0])
        api.create(KIND_POD, make_slice_pod(
            "2x2", 1, name="victim", creation_timestamp=2.0,
            annotations={C.ANNOT_DISPLACED:
                         displaced_value("node-loss", 44.0)}))
        before = REGISTRY.snapshot().get(
            "nos_tpu_rebind_latency_seconds_count", {})
        with obs.scoped(journal=journal):
            sched.run_cycle()
        bound = api.get(KIND_POD, "victim", "default")
        assert bound.spec.node_name == "h0"
        # the stamp is consumed by the bind: a later requeue is a
        # fresh displacement event, not an inherited boost
        assert C.ANNOT_DISPLACED not in bound.metadata.annotations
        recs = journal.events(category=J.JOB_REBOUND)
        assert len(recs) == 1
        assert recs[0].subject == "default/victim"
        assert recs[0].attrs["cause"] == "node-loss"
        assert recs[0].attrs["latency_s"] == pytest.approx(6.0)
        # COUNT convention: a `members` attr is reserved for pod-key
        # lists (explain's membership match iterates it) — a count
        # there crashed `obs explain pod` for EVERY pod whenever any
        # job had rebound (found by the boundary drive)
        assert recs[0].attrs["members_total"] == 1
        assert "members" not in recs[0].attrs
        from nos_tpu.obs.explain import explain_pod

        lines = explain_pod(
            {"journal": [r.to_dict() for r in journal.events()]},
            "default/other")
        assert lines        # renders, never raises
        after = REGISTRY.snapshot().get(
            "nos_tpu_rebind_latency_seconds_count", {})
        key = "class=slice-2x2"
        assert after.get(key, 0) == before.get(key, 0) + 1

    def test_non_displaced_bind_observes_nothing(self):
        clock = [50.0]
        api, sched = _one_slot_cluster(clock)
        journal = DecisionJournal(clock=lambda: clock[0])
        api.create(KIND_POD, make_slice_pod(
            "2x2", 1, name="plain", creation_timestamp=2.0))
        with obs.scoped(journal=journal):
            sched.run_cycle()
        assert api.get(KIND_POD, "plain", "default").spec.node_name == "h0"
        assert journal.events(category=J.JOB_REBOUND) == []


# ---------------------------------------------------------------------------
# Restart-cost-aware victim walk (capacityscheduling)
# ---------------------------------------------------------------------------


class TestRestartCostVictims:
    def _walk(self, preemptor, ctx=None):
        from nos_tpu.quota import ElasticQuotaInfos, TPUResourceCalculator
        from nos_tpu.scheduler.capacityscheduling import (
            CapacityScheduling, DISPLACED_CONTEXT_KEY,
            ELASTIC_QUOTA_SNAPSHOT_KEY, PRE_FILTER_STATE_KEY,
            PreFilterState,
        )
        from nos_tpu.scheduler.framework import (
            CycleState, Framework, NodeInfo, NodeResourcesFit,
        )

        api = APIServer()
        node = make_tpu_node("h0", status_geometry={"free": {"2x2": 2}})
        api.create(KIND_NODE, node)
        ni = NodeInfo(node=node)
        # same priority, same tier; 'fresh' reported 10% progress and
        # is OLDER, 'done' reported 90% and is NEWER — the default walk
        # (newest first) picks 'done', the displaced walk must pick
        # 'fresh' (least restart cost)
        fresh = make_slice_pod(
            "2x2", 1, name="fresh", node_name="h0", phase="Running",
            creation_timestamp=1.0,
            annotations={C.ANNOT_JOB_PROGRESS: "0.1"})
        done = make_slice_pod(
            "2x2", 1, name="done", node_name="h0", phase="Running",
            creation_timestamp=9.0,
            annotations={C.ANNOT_JOB_PROGRESS: "0.9"})
        for p in (fresh, done):
            api.create(KIND_POD, p)
            ni.add_pod(p)
        calc = TPUResourceCalculator()
        cs = CapacityScheduling(calc)
        cs.set_framework(Framework([NodeResourcesFit()]))
        cs._api = api
        state = CycleState()
        state[ELASTIC_QUOTA_SNAPSHOT_KEY] = ElasticQuotaInfos()
        state[PRE_FILTER_STATE_KEY] = PreFilterState(
            calc.compute_pod_request(preemptor))
        if ctx is not None:
            state[DISPLACED_CONTEXT_KEY] = ctx
        victims, _, status = cs._select_victims_on_node(
            state, preemptor, ni, pdbs=[])
        assert status.is_success and victims
        return victims

    def test_displaced_preemptor_takes_least_progress_victim(self):
        preemptor = make_slice_pod(
            "2x2", 1, name="pree", priority=10,
            annotations={C.ANNOT_DISPLACED:
                         displaced_value("node-loss", 1.0)})
        victims = self._walk(preemptor)
        assert victims[0].metadata.name == "fresh"

    def test_plain_preemptor_order_unchanged(self):
        """Without a displacement stamp the walk's order is the
        historical one (newest first) — byte-identical decisions."""
        preemptor = make_slice_pod("2x2", 1, name="pree", priority=10)
        victims = self._walk(preemptor)
        assert victims[0].metadata.name == "done"

    def test_expired_stamp_loses_the_altered_order_too(self):
        """A stamp past displaced_age_cap_s reads plain batch in the
        admission queue — the victim walk must agree (the scheduler
        hands the walk its clock + cap via DISPLACED_CONTEXT_KEY)."""
        preemptor = make_slice_pod(
            "2x2", 1, name="pree", priority=10,
            annotations={C.ANNOT_DISPLACED:
                         displaced_value("node-loss", 1.0)})
        victims = self._walk(preemptor, ctx=(1000.0, 300.0))
        assert victims[0].metadata.name == "done"
        # the same stamp, still fresh: altered order applies
        victims = self._walk(preemptor, ctx=(100.0, 300.0))
        assert victims[0].metadata.name == "fresh"

    def test_serving_preemptor_stamp_alters_nothing(self):
        """Serving never had the displaced head-of-line slot, so a
        stamped serving preemptor keeps the historical walk order."""
        preemptor = make_slice_pod(
            "2x2", 1, name="pree", priority=10,
            labels={C.LABEL_TIER: C.TIER_SERVING},
            annotations={C.ANNOT_DISPLACED:
                         displaced_value("node-loss", 1.0)})
        victims = self._walk(preemptor, ctx=(2.0, 300.0))
        assert victims[0].metadata.name == "done"


# ---------------------------------------------------------------------------
# SpareGuard + MigrationDrainGuard (framework filters)
# ---------------------------------------------------------------------------


class TestHoldGuards:
    def test_pod_never_binds_to_warm_spare(self):
        clock = [0.0]
        api = APIServer()
        api.create(KIND_NODE, make_tpu_node(
            "spare", pod_id="pod-0", host_index=7,
            status_geometry={"free": {"2x2": 2}},
            extra_labels={C.LABEL_SPARE: C.SPARE_WARM}))
        sched = build_scheduler(api, 16, clock=lambda: clock[0])
        api.create(KIND_POD, make_slice_pod("2x2", 1, name="p",
                                            creation_timestamp=1.0))
        sched.run_cycle()
        pod = api.get(KIND_POD, "p", "default")
        assert not pod.spec.node_name
        # promotion = the label comes off; the SAME pod binds next cycle
        api.patch(KIND_NODE, "spare",
                  mutate=lambda n: n.metadata.labels.pop(
                      C.LABEL_SPARE, None))
        sched.run_cycle()
        assert api.get(KIND_POD, "p", "default").spec.node_name == "spare"

    def test_migration_drained_node_hard_rejected(self):
        clock = [0.0]
        api = APIServer()
        api.create(KIND_NODE, make_tpu_node(
            "dying", pod_id="pod-0", host_index=0,
            status_geometry={"free": {"2x2": 2}}))
        api.patch(KIND_NODE, "dying",
                  mutate=lambda n: n.metadata.annotations.update(
                      {C.ANNOT_DEFRAG_DRAIN: "migrate:node-suspect"}))
        sched = build_scheduler(api, 16, clock=lambda: clock[0])
        api.create(KIND_POD, make_slice_pod("2x2", 1, name="p",
                                            creation_timestamp=1.0))
        sched.run_cycle()
        assert not api.get(KIND_POD, "p", "default").spec.node_name

    def test_defrag_drain_stays_a_soft_avoidance(self):
        """A defrag proposal's drain (non-migrate value) must NOT hard-
        reject: the host is healthy — with no alternative the pod still
        binds (the score key only prefers elsewhere)."""
        clock = [0.0]
        api = APIServer()
        api.create(KIND_NODE, make_tpu_node(
            "defragged", pod_id="pod-0", host_index=0,
            status_geometry={"free": {"2x2": 2}}))
        api.patch(KIND_NODE, "defragged",
                  mutate=lambda n: n.metadata.annotations.update(
                      {C.ANNOT_DEFRAG_DRAIN: "dfrg-slice-7"}))
        sched = build_scheduler(api, 16, clock=lambda: clock[0])
        api.create(KIND_POD, make_slice_pod("2x2", 1, name="p",
                                            creation_timestamp=1.0))
        sched.run_cycle()
        assert api.get(KIND_POD, "p", "default").spec.node_name == "defragged"

    def test_spare_excluded_from_waste_waterfall(self):
        """A warm spare is reserve, not fleet capacity: its chips
        appear in no waterfall pool (its SpareGuard rejections must
        not read frag_stranded)."""
        clock = [0.0]
        ledger = ChipSecondLedger(clock=lambda: clock[0])
        api = APIServer()
        api.create(KIND_NODE, make_tpu_node(
            "spare", pod_id="pod-9", host_index=0,
            status_geometry={"free": {"2x2": 2}},
            extra_labels={C.LABEL_SPARE: C.SPARE_WARM}))
        sched = build_scheduler(api, 16, clock=lambda: clock[0])
        with obs.scoped(ledger=ledger):
            api.create(KIND_POD, make_slice_pod(
                "2x2", 1, name="p", creation_timestamp=1.0))
            clock[0] += 1.0
            sched.run_cycle()
            clock[0] += 1.0
            sched.run_cycle()
        assert "pod-9" not in ledger.report()["pools"]


# ---------------------------------------------------------------------------
# Failure detector + warm spares + drain-then-migrate (the policy)
# ---------------------------------------------------------------------------


def _policy_cluster(spares=1, suspect_after=5.0, grace=3.0):
    clock = [0.0]
    api = APIServer()
    quarantine = QuarantineList(kind="slice", clock=lambda: clock[0])
    policy = SelfHealingPolicy(
        api, "slice", quarantine, spare_hosts_per_pool=spares,
        suspect_after_s=suspect_after, migrate_grace_s=grace,
        clock=lambda: clock[0])
    nodes = {}
    for i in range(2):
        node = make_tpu_node(f"h{i}", pod_id="pod-0", host_index=i)
        node.metadata.annotations[C.heartbeat_annotation("slice")] = "1"
        api.create(KIND_NODE, node)
        nodes[f"h{i}"] = node
    for s in range(spares):
        node = make_tpu_node(
            f"spare{s}", pod_id="pod-0", host_index=100 + s,
            extra_labels={C.LABEL_SPARE: C.SPARE_WARM})
        api.create(KIND_NODE, node)
        nodes[f"spare{s}"] = node
    return clock, api, quarantine, policy, nodes


def _fresh_nodes(api):
    return {n.metadata.name: n for n in api.list(KIND_NODE)}


class TestFailureDetector:
    def test_frozen_heartbeat_suspects_and_resume_releases(self):
        clock, api, quarantine, policy, nodes = _policy_cluster()
        policy.step(nodes)              # baseline observation
        clock[0] = 4.0
        policy.step(nodes)
        assert not quarantine.is_quarantined("h0")
        clock[0] = 6.0                  # > suspect_after_s, value frozen
        policy.step(nodes)
        assert quarantine.reason("h0") == REASON_SUSPECT
        assert quarantine.reason("h1") == REASON_SUSPECT
        # the heartbeat moves again: released by the detector itself
        nodes["h0"].metadata.annotations[
            C.heartbeat_annotation("slice")] = "2"
        policy.step(nodes)
        assert not quarantine.is_quarantined("h0")
        assert quarantine.is_quarantined("h1")

    def test_node_without_heartbeat_is_never_suspected(self):
        clock, api, quarantine, policy, nodes = _policy_cluster()
        silent = make_tpu_node("mute", pod_id="pod-0", host_index=5)
        api.create(KIND_NODE, silent)
        nodes["mute"] = silent
        policy.step(nodes)
        clock[0] = 100.0
        policy.step(nodes)
        assert not quarantine.is_quarantined("mute")

    def test_heartbeat_stamp_is_gateable(self):
        """AgentConfig.heartbeat=False keeps the agent from stamping
        the liveness counter, so steady-state reports stay no-op
        status re-writes (no watch event per node per report interval
        fleet-wide) on deployments running without the detector."""
        api = APIServer()
        api.create(KIND_NODE,
                   make_tpu_node("h0", pod_id="pod-0", host_index=0))
        agent = SliceAgent(api, "h0", FakeTpuRuntime(V5E),
                           FakePodResources(), heartbeat=False)
        agent.start()
        agent.tick()
        annotations = api.get(KIND_NODE, "h0").metadata.annotations
        assert C.heartbeat_annotation("slice") not in annotations
        agent.stop()
        # default stays ON: every in-process sim/bench keeps the signal
        api.create(KIND_NODE,
                   make_tpu_node("h1", pod_id="pod-0", host_index=1))
        agent = SliceAgent(api, "h1", FakeTpuRuntime(V5E),
                           FakePodResources())
        agent.start()
        annotations = api.get(KIND_NODE, "h1").metadata.annotations
        assert C.heartbeat_annotation("slice") in annotations
        agent.stop()

    def test_guarded_by_contract(self):
        """The detector/spare/migration state is @guarded_by the policy
        lock — writes without it are convicted at runtime exactly like
        the static N010 rule."""
        graph = LockGraph(name="recovery-guard")
        try:
            with graph.install():
                clock, api, quarantine, policy, nodes = _policy_cluster()
            guard_state(policy, graph,
                        name="core.SelfHealingPolicy")
            policy.step(nodes)
            clock[0] = 6.0
            policy.step(nodes)
            graph.assert_clean()
        finally:
            graph.close()
            unguard_all()


class TestSparePromotion:
    def test_vanished_host_promotes_a_spare_into_its_index(self):
        clock, api, quarantine, policy, nodes = _policy_cluster()
        journal = DecisionJournal(clock=lambda: clock[0])
        with obs.scoped(journal=journal):
            policy.step(_fresh_nodes(api))      # baseline membership
            api.delete(KIND_NODE, "h0")         # the kill
            policy.step(_fresh_nodes(api))
        spare = api.get(KIND_NODE, "spare0")
        assert not is_warm_spare(spare)
        assert spare.metadata.labels[C.LABEL_HOST_INDEX] == "0"
        recs = journal.events(category=J.SPARE_PROMOTED)
        assert len(recs) == 1
        assert recs[0].subject == "spare0"
        assert recs[0].attrs["replaced"] == "h0"
        assert recs[0].attrs["host_index"] == 0

    def test_one_vacancy_consumes_one_spare(self):
        clock, api, quarantine, policy, nodes = _policy_cluster(spares=2)
        policy.step(_fresh_nodes(api))
        api.delete(KIND_NODE, "h0")
        policy.step(_fresh_nodes(api))
        policy.step(_fresh_nodes(api))
        policy.step(_fresh_nodes(api))
        promoted = [n for n in api.list(KIND_NODE)
                    if n.metadata.name.startswith("spare")
                    and not is_warm_spare(n)]
        assert len(promoted) == 1

    def test_unhealthy_spare_is_not_promoted(self):
        """A quarantined spare (its own agent died) or one marked for
        maintenance must not consume a vacancy — it would hold the
        gang window broken while a healthy spare sits idle (the
        no-replacement-while-present rule would never revisit it)."""
        clock, api, quarantine, policy, nodes = _policy_cluster(spares=2)
        policy.step(_fresh_nodes(api))
        quarantine.quarantine("spare0", REASON_SUSPECT)
        api.delete(KIND_NODE, "h0")
        policy.step(_fresh_nodes(api))
        assert is_warm_spare(api.get(KIND_NODE, "spare0"))
        promoted = api.get(KIND_NODE, "spare1")
        assert not is_warm_spare(promoted)
        assert promoted.metadata.labels[C.LABEL_HOST_INDEX] == "0"
        # inventory counts PROMOTABLE spares only: the dead spare is
        # not inventory, so the pool reads 0 held and warns short
        snap = REGISTRY.snapshot()["nos_tpu_spare_hosts"]
        assert snap["pool=pod-0"] == 0.0
        # maintenance-stamped spares are equally ineligible
        quarantine.unquarantine("spare0")
        api.patch(KIND_NODE, "spare0",
                  mutate=lambda n: n.metadata.annotations.update(
                      {C.ANNOT_MAINTENANCE: "planned"}))
        api.delete(KIND_NODE, "h1")
        policy.step(_fresh_nodes(api))
        assert is_warm_spare(api.get(KIND_NODE, "spare0"))

    def test_hybrid_pool_promotion_owned_by_slice_family(self):
        """Hybrid hosts are seen by BOTH families' policies; promotion
        is single-owner (slice by convention) or two concurrent
        reconciles could label two different spares with one vacated
        index — two live nodes sharing a host-index breaks the window
        convention for good."""
        clock = [0.0]
        api = APIServer()
        policies = {}
        for kind in ("slice", "timeshare"):
            policies[kind] = SelfHealingPolicy(
                api, kind, QuarantineList(kind=kind,
                                          clock=lambda: clock[0]),
                spare_hosts_per_pool=1, clock=lambda: clock[0])
        for i in range(2):
            api.create(KIND_NODE, make_tpu_node(
                f"y{i}", pod_id="pod-9", host_index=i,
                partitioning="hybrid"))
        for s in range(2):
            api.create(KIND_NODE, make_tpu_node(
                f"yspare{s}", pod_id="pod-9", host_index=200 + s,
                partitioning="hybrid",
                extra_labels={C.LABEL_SPARE: C.SPARE_WARM}))
        for p in policies.values():
            p.step(_fresh_nodes(api))       # both observe the baseline
        api.delete(KIND_NODE, "y0")
        for p in policies.values():
            p.step(_fresh_nodes(api))
        promoted = [n for n in api.list(KIND_NODE)
                    if n.metadata.name.startswith("yspare")
                    and not is_warm_spare(n)]
        assert len(promoted) == 1
        assert promoted[0].metadata.labels[C.LABEL_HOST_INDEX] == "0"

    def test_restart_lost_vacancy_inferred_from_index_gap(self):
        """A host that died BEFORE the policy's first poll (controller
        restart, leader failover) is in no in-memory baseline — but
        the window convention indexes a pool's hosts contiguously from
        0, so the first poll infers the vacancy from the index GAP and
        still promotes a spare."""
        clock, api, quarantine, policy, nodes = _policy_cluster()
        api.create(KIND_NODE,
                   make_tpu_node("h2", pod_id="pod-0", host_index=2))
        api.delete(KIND_NODE, "h1")     # dies while nobody is watching
        fresh = SelfHealingPolicy(
            api, "slice", quarantine, spare_hosts_per_pool=1,
            clock=lambda: clock[0])
        fresh.step(_fresh_nodes(api))   # FIRST poll of a fresh process
        spare = api.get(KIND_NODE, "spare0")
        assert not is_warm_spare(spare)
        assert spare.metadata.labels[C.LABEL_HOST_INDEX] == "1"

    def test_intact_pool_first_poll_promotes_nothing(self):
        """Gap inference must not fire on a healthy contiguous pool —
        and a dead HIGHEST index is indistinguishable from a smaller
        pool, so it stays invisible to a fresh process (the documented
        limitation)."""
        clock, api, quarantine, policy, nodes = _policy_cluster()
        policy.step(_fresh_nodes(api))
        assert is_warm_spare(api.get(KIND_NODE, "spare0"))
        api.delete(KIND_NODE, "h1")     # h1 holds the highest index
        fresh = SelfHealingPolicy(
            api, "slice", quarantine, spare_hosts_per_pool=1,
            clock=lambda: clock[0])
        fresh.step(_fresh_nodes(api))
        assert is_warm_spare(api.get(KIND_NODE, "spare0"))

    def test_quarantined_but_present_host_is_not_replaced(self):
        """Promotion is for VANISHED nodes only: a suspect host still
        holds its index (two nodes must never share one)."""
        clock, api, quarantine, policy, nodes = _policy_cluster()
        policy.step(_fresh_nodes(api))
        quarantine.quarantine("h0", REASON_SUSPECT)
        policy.step(_fresh_nodes(api))
        assert is_warm_spare(api.get(KIND_NODE, "spare0"))

    def test_spare_gauge_tracks_inventory(self):
        clock, api, quarantine, policy, nodes = _policy_cluster()
        policy.step(_fresh_nodes(api))
        snap = REGISTRY.snapshot()["nos_tpu_spare_hosts"]
        assert snap["pool=pod-0"] == 1.0
        api.delete(KIND_NODE, "h0")
        policy.step(_fresh_nodes(api))
        snap = REGISTRY.snapshot()["nos_tpu_spare_hosts"]
        assert snap["pool=pod-0"] == 0.0


class TestDrainMigrate:
    def _suspect_h0(self, clock, policy, api):
        policy.step(_fresh_nodes(api))
        clock[0] += 6.0
        policy.step(_fresh_nodes(api))

    def test_suspect_node_drains_stamps_and_evicts_after_grace(self):
        clock, api, quarantine, policy, nodes = _policy_cluster(
            spares=0, suspect_after=5.0, grace=3.0)
        resident = make_slice_pod("2x2", 1, name="r0", node_name="h0",
                                  phase="Running", namespace="work")
        api.create(KIND_POD, resident)
        ledger = ChipSecondLedger(clock=lambda: clock[0])
        journal = DecisionJournal(clock=lambda: clock[0])
        with obs.scoped(journal=journal, ledger=ledger):
            self._suspect_h0(clock, policy, api)
            node = api.get(KIND_NODE, "h0")
            assert node.metadata.annotations[
                C.ANNOT_DEFRAG_DRAIN] == "migrate:slice:node-suspect"
            assert ledger.holds()["h0"][DRAIN]["cause"] == "node-suspect"
            pod = api.get(KIND_POD, "r0", "work")
            assert pod.metadata.annotations[C.ANNOT_MIGRATE] \
                == "node-suspect"
            recs = journal.events(category=J.JOB_DISPLACED)
            assert recs and recs[0].subject == "work/r0"
            assert recs[0].attrs["cause"] == "node-suspect"
            # inside the grace nothing is evicted (the checkpoint exit
            # window)
            assert api.try_get(KIND_POD, "r0", "work") is not None
            clock[0] += 3.5
            policy.step(_fresh_nodes(api))
            assert api.try_get(KIND_POD, "r0", "work") is None

    def test_recovered_node_heals_drain_and_hold(self):
        clock, api, quarantine, policy, nodes = _policy_cluster(
            spares=0, suspect_after=5.0, grace=300.0)
        ledger = ChipSecondLedger(clock=lambda: clock[0])
        with obs.scoped(ledger=ledger):
            self._suspect_h0(clock, policy, api)
            assert "h0" in ledger.holds()
            # the heartbeat moves: suspect released, drain healed
            api.patch(KIND_NODE, "h0",
                      mutate=lambda n: n.metadata.annotations.update(
                          {C.heartbeat_annotation("slice"): "2"}))
            policy.step(_fresh_nodes(api))
            assert not quarantine.is_quarantined("h0")
            node = api.get(KIND_NODE, "h0")
            assert C.ANNOT_DEFRAG_DRAIN not in node.metadata.annotations
            assert "h0" not in ledger.holds()

    def test_recovered_node_unstamps_residents(self):
        """A retracted migration must retract the checkpoint-exit
        request too: residents lose nos.tpu/migrate when the node
        recovers, or every job on the healthy node would exit at its
        next landed checkpoint — a spurious restart wave."""
        clock, api, quarantine, policy, nodes = _policy_cluster(
            spares=0, suspect_after=5.0, grace=300.0)
        resident = make_slice_pod("2x2", 1, name="r0", node_name="h0",
                                  phase="Running", namespace="work")
        api.create(KIND_POD, resident)
        ledger = ChipSecondLedger(clock=lambda: clock[0])
        with obs.scoped(ledger=ledger):
            self._suspect_h0(clock, policy, api)
            pod = api.get(KIND_POD, "r0", "work")
            assert pod.metadata.annotations[C.ANNOT_MIGRATE] \
                == "node-suspect"
            # the heartbeat moves: migration retracted end to end
            api.patch(KIND_NODE, "h0",
                      mutate=lambda n: n.metadata.annotations.update(
                          {C.heartbeat_annotation("slice"): "2"}))
            policy.step(_fresh_nodes(api))
            pod = api.get(KIND_POD, "r0", "work")
            assert C.ANNOT_MIGRATE not in pod.metadata.annotations

    def test_other_family_migration_is_never_retracted(self):
        """Migration-drain ownership is exclusive per family
        (migrate:<kind>:<cause>): the slice policy must neither begin
        over nor retract a timeshare-owned drain on a hybrid host —
        clearing it would let the scheduler refill a still-dying host
        and strip the residents' checkpoint-exit request."""
        clock, api, quarantine, policy, nodes = _policy_cluster(
            spares=0, suspect_after=5.0, grace=300.0)
        ts_value = C.migration_drain_value("timeshare", "node-suspect")
        api.patch(KIND_NODE, "h0",
                  mutate=lambda n: n.metadata.annotations.update(
                      {C.ANNOT_DEFRAG_DRAIN: ts_value}))
        resident = make_slice_pod("2x2", 1, name="r0", node_name="h0",
                                  phase="Running", namespace="work",
                                  annotations={C.ANNOT_MIGRATE:
                                               "node-suspect"})
        api.create(KIND_POD, resident)
        ledger = ChipSecondLedger(clock=lambda: clock[0])
        with obs.scoped(ledger=ledger):
            # h0's SLICE agent goes suspect too: slice wants its own
            # migration but timeshare already owns the drain — defer
            self._suspect_h0(clock, policy, api)
            node = api.get(KIND_NODE, "h0")
            assert node.metadata.annotations[
                C.ANNOT_DEFRAG_DRAIN] == ts_value
            # slice's heartbeat resumes: nothing of timeshare's is
            # retracted (drain stays, resident stamp stays)
            api.patch(KIND_NODE, "h0",
                      mutate=lambda n: n.metadata.annotations.update(
                          {C.heartbeat_annotation("slice"): "2"}))
            policy.step(_fresh_nodes(api))
            node = api.get(KIND_NODE, "h0")
            assert node.metadata.annotations[
                C.ANNOT_DEFRAG_DRAIN] == ts_value
            pod = api.get(KIND_POD, "r0", "work")
            assert pod.metadata.annotations[C.ANNOT_MIGRATE] \
                == "node-suspect"

    def test_stray_drain_of_recovered_node_is_healed(self):
        """A predecessor died mid-migration, the node recovered during
        the downtime: the fresh policy (empty in-memory state) must
        retract the stray drain and the residents' migrate stamps —
        otherwise the host is hard-unschedulable forever.  The verdict
        needs the heartbeat to MOVE: on first sight a recovered node
        and a frozen-dead one look identical, so the stray is HELD
        (not retracted — a retraction would un-ask the residents of a
        genuinely dying host and re-journal the displacement on every
        failover) until the agent's next report proves life."""
        clock, api, quarantine, policy, nodes = _policy_cluster(
            spares=0, suspect_after=5.0, grace=300.0)
        api.patch(KIND_NODE, "h0",
                  mutate=lambda n: n.metadata.annotations.update(
                      {C.ANNOT_DEFRAG_DRAIN: C.migration_drain_value(
                          "slice", "node-suspect")}))
        resident = make_slice_pod("2x2", 1, name="r0", node_name="h0",
                                  phase="Running", namespace="work",
                                  annotations={C.ANNOT_MIGRATE:
                                               "node-suspect"})
        api.create(KIND_POD, resident)
        ledger = ChipSecondLedger(clock=lambda: clock[0])
        with obs.scoped(ledger=ledger):
            policy.step(_fresh_nodes(api))   # first sight: undecided
            node = api.get(KIND_NODE, "h0")
            assert C.ANNOT_DEFRAG_DRAIN in node.metadata.annotations
            clock[0] += 1.0                  # the agent reports again
            api.patch(KIND_NODE, "h0",
                      mutate=lambda n: n.metadata.annotations.update(
                          {C.heartbeat_annotation("slice"): "2"}))
            policy.step(_fresh_nodes(api))   # moved: alive — retract
            node = api.get(KIND_NODE, "h0")
            assert C.ANNOT_DEFRAG_DRAIN not in node.metadata.annotations
            pod = api.get(KIND_POD, "r0", "work")
            assert C.ANNOT_MIGRATE not in pod.metadata.annotations

    def test_stray_drain_of_still_dead_node_is_adopted(self):
        """The predecessor's migration target is STILL suspect at
        restart: the fresh policy adopts the stray (tracks it, keeps
        the drain, restores the ledger hold) instead of healing it —
        WITHOUT re-stamping already-asked residents or journaling a
        second displacement event for the same displacement."""
        clock, api, quarantine, policy, nodes = _policy_cluster(
            spares=0, suspect_after=5.0, grace=300.0)
        api.patch(KIND_NODE, "h0",
                  mutate=lambda n: n.metadata.annotations.update(
                      {C.ANNOT_DEFRAG_DRAIN: C.migration_drain_value(
                          "slice", "node-suspect")}))
        resident = make_slice_pod(
            "2x2", 1, name="r0", node_name="h0", phase="Running",
            namespace="work",
            annotations={C.ANNOT_MIGRATE: "node-suspect"})
        api.create(KIND_POD, resident)
        ledger = ChipSecondLedger(clock=lambda: clock[0])
        journal = DecisionJournal(clock=lambda: clock[0])
        with obs.scoped(journal=journal, ledger=ledger):
            self._suspect_h0(clock, policy, api)   # h0 still frozen
            node = api.get(KIND_NODE, "h0")
            assert node.metadata.annotations[C.ANNOT_DEFRAG_DRAIN] \
                == "migrate:slice:node-suspect"
            assert ledger.holds()["h0"][DRAIN]["cause"] == "node-suspect"
            # adoption is idempotent on the workload side
            assert journal.events(category=J.JOB_DISPLACED) == []

    def test_straggler_eviction_fires_once_per_pod(self, monkeypatch):
        """Graceful termination on a real apiserver keeps evicted pods
        in _residents for many polls — the straggler pass must not
        re-delete them (and re-count nos_tpu_drain_migrations_total by
        the gang size) every poll past the grace."""
        import nos_tpu.scheduler.gang as gang_mod

        calls: list[str] = []
        monkeypatch.setattr(
            gang_mod, "evict_gang",
            lambda api, pod: (calls.append(pod.key), [pod.key])[1])
        clock, api, quarantine, policy, nodes = _policy_cluster(
            spares=0, suspect_after=5.0, grace=3.0)
        api.create(KIND_POD, make_slice_pod(
            "2x2", 1, name="r0", node_name="h0", phase="Running",
            namespace="work"))
        self._suspect_h0(clock, policy, api)
        for _ in range(4):                  # polls past the grace;
            clock[0] += 2.0                 # the pod never leaves
            policy.step(_fresh_nodes(api))
        assert calls == ["work/r0"]

    def test_other_family_drain_defers_begin_inside_the_write(self):
        """ONE family owns a node's migration: when the other family's
        drain is already on the node, ours defers — judged INSIDE the
        retried mutate, so a hybrid host's two concurrent detectors
        cannot both read no-owner and double-run the migration."""
        clock, api, quarantine, policy, nodes = _policy_cluster(
            spares=0, suspect_after=5.0, grace=300.0)
        api.patch(KIND_NODE, "h0",
                  mutate=lambda n: n.metadata.annotations.update(
                      {C.ANNOT_DEFRAG_DRAIN: C.migration_drain_value(
                          "timeshare", "maintenance")}))
        resident = make_slice_pod("2x2", 1, name="r0", node_name="h0",
                                  phase="Running", namespace="work")
        api.create(KIND_POD, resident)
        ledger = ChipSecondLedger(clock=lambda: clock[0])
        journal = DecisionJournal(clock=lambda: clock[0])
        with obs.scoped(journal=journal, ledger=ledger):
            self._suspect_h0(clock, policy, api)
        node = api.get(KIND_NODE, "h0")
        assert node.metadata.annotations[C.ANNOT_DEFRAG_DRAIN] \
            == "migrate:timeshare:maintenance"      # never overwritten
        pod = api.get(KIND_POD, "r0", "work")
        assert C.ANNOT_MIGRATE not in pod.metadata.annotations
        assert DRAIN not in ledger.holds().get("h0", {})
        assert journal.events(category=J.JOB_DISPLACED) == []

    def test_defrag_cleanup_spares_a_superseding_migration_drain(self):
        """Defrag stamped a host, the host then started dying and the
        recovery plane overwrote the stamp with its migration drain:
        defrag's cleanup/heal must NOT pop the migration drain — the
        scheduler would refill a presumed-dying host."""
        from nos_tpu.partitioning.slicepart.calculators import (
            SliceProfileCalculator,
        )
        from nos_tpu.partitioning.core.defrag import DefragProposer

        api = APIServer()
        node = make_tpu_node("h0", pod_id="pod-0", host_index=0)
        migrate = C.migration_drain_value("slice", "node-suspect")
        node.metadata.annotations[C.ANNOT_DEFRAG_DRAIN] = migrate
        api.create(KIND_NODE, node)
        proposer = DefragProposer(api, "slice",
                                  SliceProfileCalculator(),
                                  clock=lambda: 0.0)
        # the direct clear path (cleanup's per-host call): the stamp
        # it owned was superseded, so nothing is popped
        proposer._clear_drain("h0", "defrag-proposal-123")
        assert api.get(KIND_NODE, "h0").metadata.annotations[
            C.ANNOT_DEFRAG_DRAIN] == migrate
        # the startup stray sweep also leaves migration drains alone
        proposer._heal_stray_drains()
        assert api.get(KIND_NODE, "h0").metadata.annotations[
            C.ANNOT_DEFRAG_DRAIN] == migrate

    def test_disabled_controller_heals_predecessor_stray_once(self):
        """A controller built WITHOUT the recovery plane heals a
        recovery-enabled predecessor's migration drains at its first
        poll (heal_stray_migration_drains) — nothing else ever would."""
        from nos_tpu.partitioning.core import heal_stray_migration_drains

        api = APIServer()
        node = make_tpu_node("h0", pod_id="pod-0", host_index=0)
        node.metadata.annotations[C.ANNOT_DEFRAG_DRAIN] = \
            C.migration_drain_value("slice", "maintenance")
        api.create(KIND_NODE, node)
        other = make_tpu_node("h1", pod_id="pod-0", host_index=1)
        other.metadata.annotations[C.ANNOT_DEFRAG_DRAIN] = \
            C.migration_drain_value("timeshare", "maintenance")
        api.create(KIND_NODE, other)
        resident = make_slice_pod("2x2", 1, name="r0", node_name="h0",
                                  phase="Running", namespace="work",
                                  annotations={C.ANNOT_MIGRATE:
                                               "maintenance"})
        api.create(KIND_POD, resident)
        assert heal_stray_migration_drains(api, "slice") == 1
        node = api.get(KIND_NODE, "h0")
        assert C.ANNOT_DEFRAG_DRAIN not in node.metadata.annotations
        pod = api.get(KIND_POD, "r0", "work")
        assert C.ANNOT_MIGRATE not in pod.metadata.annotations
        # the other family's drain is not ours to heal
        other = api.get(KIND_NODE, "h1")
        assert C.is_migration_drain(other.metadata.annotations)

    def test_maintenance_annotation_drains_without_suspicion(self):
        clock, api, quarantine, policy, nodes = _policy_cluster(
            spares=0, suspect_after=5.0, grace=300.0)
        api.patch(KIND_NODE, "h1",
                  mutate=lambda n: n.metadata.annotations.update(
                      {C.ANNOT_MAINTENANCE: "planned-reboot"}))
        policy.step(_fresh_nodes(api))
        node = api.get(KIND_NODE, "h1")
        assert node.metadata.annotations[
            C.ANNOT_DEFRAG_DRAIN] == "migrate:slice:maintenance"
        assert not quarantine.is_quarantined("h1")

    def test_train_reads_the_migrate_signal(self):
        from nos_tpu.cmd.train import read_migrate_signal

        api = APIServer()
        pod = make_slice_pod("2x2", 1, name="w0", namespace="work",
                             node_name="h0", phase="Running")
        api.create(KIND_POD, pod)
        assert read_migrate_signal(api, "w0", "work") is None
        api.patch(KIND_POD, "w0", "work",
                  mutate=lambda p: p.metadata.annotations.update(
                      {C.ANNOT_MIGRATE: "maintenance"}))
        assert read_migrate_signal(api, "w0", "work") == "maintenance"
        assert read_migrate_signal(api, "gone", "work") is None

    def test_signal_checker_one_read_serves_both(self, monkeypatch):
        """The default per-checkpoint probe serves BOTH the dp-resize
        and migrate annotations from ONE pod read on ONE client —
        separate probes would double the apiserver load fleet-wide."""
        from nos_tpu.cmd import _runtime
        from nos_tpu.cmd.train import TrainConfig, signal_checker

        api = APIServer()
        pod = make_slice_pod("2x2", 1, name="w0", namespace="work",
                             node_name="h0", phase="Running")
        pod.metadata.annotations[C.ANNOT_DP_RESIZE] = "3"
        pod.metadata.annotations[C.ANNOT_MIGRATE] = "maintenance"
        api.create(KIND_POD, pod)
        reads = [0]
        real_try_get = api.try_get

        def counting_try_get(kind, name, namespace=None):
            if kind == KIND_POD:
                reads[0] += 1
            return real_try_get(kind, name, namespace)

        monkeypatch.setattr(api, "try_get", counting_try_get)
        monkeypatch.setattr(_runtime, "build_api", lambda cfg: api)
        probe = signal_checker(
            TrainConfig(kubeconfig="in-memory"),
            environ={"POD_NAME": "w0", "POD_NAMESPACE": "work"})
        assert probe() == (3, "maintenance")
        assert reads[0] == 1
        # identity incomplete -> inert, never a guessed namespace
        assert signal_checker(TrainConfig(kubeconfig="in-memory"),
                              environ={"POD_NAME": "w0"}) is None


# ---------------------------------------------------------------------------
# Disabled-path byte-identity + end-to-end recovery
# ---------------------------------------------------------------------------


def _mini_cluster(recovery: bool, hosts=2, spares=1):
    """A small real control plane (controller + agents + scheduler) on
    a virtual clock, with the recovery plane on or off."""
    clock = [0.0]
    api = APIServer()
    state = ClusterState()
    NodeController(api, state, SliceNodeInitializer(api)).bind()
    PodController(api, state).bind()
    ctl = new_slice_partitioner_controller(
        api, state, batch_timeout_s=2.0, batch_idle_s=0.5,
        clock=lambda: clock[0],
        spare_hosts_per_pool=spares if recovery else 0,
        node_suspect_after_s=5.0 if recovery else 0.0,
        migrate_grace_s=2.0)
    ctl.bind()
    agents = {}
    for i in range(hosts):
        name = f"h{i}"
        api.create(KIND_NODE, make_tpu_node(
            name, pod_id="pod-0", host_index=i))
        agent = SliceAgent(api, name, default_tpu_runtime(V5E),
                           FakePodResources())
        agent.start()
        agents[name] = agent
    for s in range(spares):
        name = f"spare{s}"
        api.create(KIND_NODE, make_tpu_node(
            name, pod_id="pod-0", host_index=100 + s,
            extra_labels={C.LABEL_SPARE: C.SPARE_WARM}))
        agent = SliceAgent(api, name, default_tpu_runtime(V5E),
                           FakePodResources())
        agent.start()
        agents[name] = agent
    sched = build_scheduler(api, 16, clock=lambda: clock[0])
    return clock, api, ctl, agents, sched


def _drive(clock, ctl, agents, sched, ticks, dt=1.0):
    for _ in range(ticks):
        clock[0] += dt
        sched.run_cycle()
        ctl.process_if_ready()
        for a in agents.values():
            a.tick()


class TestByteIdentity:
    def test_disabled_plane_is_byte_identical(self):
        """Recovery constructed-but-unprovoked (spares held, detector
        armed, no failures) must journal the EXACT record sequence of
        the plane-off build."""
        traces = []
        for recovery in (False, True):
            clock, api, ctl, agents, sched = _mini_cluster(recovery)
            journal = DecisionJournal(clock=lambda: clock[0])
            with obs.scoped(journal=journal):
                for i in range(3):
                    api.create(KIND_POD, make_slice_pod(
                        "2x2", 1, name=f"p{i}",
                        creation_timestamp=0.5))
                _drive(clock, ctl, agents, sched, 12)
            traces.append([
                (r.category, r.subject, tuple(sorted(
                    (k, str(v)) for k, v in r.attrs.items()
                    if k != "plan_id")))
                for r in journal.events()])
        assert traces[0] == traces[1]


class TestEndToEndRecovery:
    def test_kill_promote_rebind_with_zero_never_rebound(self):
        """The seeded kill-trace regression pin: a killed busy host's
        job requeues displaced, a spare is promoted into the index,
        and the job rebinds — never_rebound == 0."""
        clock, api, ctl, agents, sched = _mini_cluster(recovery=True)
        journal = DecisionJournal(clock=lambda: clock[0])
        with obs.scoped(journal=journal):
            api.create(KIND_POD, make_slice_pod(
                "2x4", 1, name="job", namespace="work",
                creation_timestamp=0.5))
            _drive(clock, ctl, agents, sched, 10)
            pod = api.get(KIND_POD, "job", "work")
            killed_on = pod.spec.node_name
            assert killed_on and pod.status.phase == RUNNING
            # keep the other host busy so the rebind NEEDS the spare
            other = next(h for h in ("h0", "h1") if h != killed_on)
            filler = make_slice_pod("2x4", 1, name="filler",
                                    namespace="work", node_name=other,
                                    phase="Running")
            api.create(KIND_POD, filler)
            # the kill: agent dies, pods die, node object vanishes
            agents.pop(killed_on).stop()
            api.delete(KIND_POD, "job", "work")
            api.delete(KIND_NODE, killed_on)
            # the workload controller requeues the victim DISPLACED
            api.create(KIND_POD, make_slice_pod(
                "2x4", 1, name="job", namespace="work",
                creation_timestamp=0.5,
                annotations={C.ANNOT_DISPLACED: displaced_value(
                    "node-loss", clock[0])}))
            _drive(clock, ctl, agents, sched, 20)
        promoted = journal.events(category=J.SPARE_PROMOTED)
        assert promoted and promoted[0].attrs["replaced"] == killed_on
        pod = api.get(KIND_POD, "job", "work")
        assert pod.spec.node_name == "spare0"
        assert pod.status.phase == RUNNING          # never_rebound = 0
        rebound = journal.events(category=J.JOB_REBOUND)
        assert rebound and rebound[0].attrs["cause"] == "node-loss"


class TestConfigKnobs:
    def test_recovery_knobs_validate(self):
        from nos_tpu.api.config import (
            ConfigError, PartitionerConfig, SchedulerConfig,
        )

        PartitionerConfig().validate()          # defaults: plane off
        SchedulerConfig().validate()
        PartitionerConfig(spare_hosts_per_pool=2,
                          node_suspect_after_s=30.0,
                          migrate_grace_s=5.0).validate()
        for bad in (PartitionerConfig(spare_hosts_per_pool=-1),
                    PartitionerConfig(node_suspect_after_s=-1.0),
                    PartitionerConfig(migrate_grace_s=-1.0),
                    SchedulerConfig(displaced_age_cap_s=-1.0)):
            with pytest.raises(ConfigError):
                bad.validate()

    def test_agent_heartbeat_defaults_off(self):
        """Production agents stamp the liveness heartbeat only on
        opt-in (pair with node_suspect_after_s on the partitioner) —
        the stamp makes every steady-state report a real write."""
        from nos_tpu.api.config import AgentConfig

        assert AgentConfig(node_name="h0").heartbeat is False
        AgentConfig(node_name="h0", heartbeat=True).validate()


class TestWasteDisplacedRendering:
    def test_obs_waste_names_the_kill_cause(self, capsys):
        """The cookbook's promise (docs/troubleshooting.md): displaced
        wait is distinguishable in the waterfall — the gang_wait and
        frag culprit lines name the kill cause."""
        from nos_tpu.obs.__main__ import cmd_waste

        clock = [0.0]
        led = ChipSecondLedger(clock=lambda: clock[0])
        led.observe({"pod-0": {
            "capacity": 16.0,
            "categories": {"gang_wait": 10.0, "frag_stranded": 6.0},
            "evidence": {
                "gang_wait": {"gang": "work/gang-7",
                              "displaced_cause": "node-loss"},
                "frag_stranded": {"class": "gang-4x4",
                                  "rejected_nodes": 3,
                                  "displaced_cause": "drain-migrate"},
            }}})
        clock[0] = 5.0
        led.observe({"pod-0": {"capacity": 16.0, "categories": {}}})
        assert cmd_waste({"waste": led.report(), "journal": []}) == 0
        out = capsys.readouterr().out
        assert "culprit gang work/gang-7: assembly stalled " \
               "(displaced: node-loss)" in out
        assert "(displaced: drain-migrate)" in out


# ---------------------------------------------------------------------------
# Chaos: kill a node mid-handshake under lockcheck + guard_state
# ---------------------------------------------------------------------------


class TestChaosNodeKillMidHandshake:
    @pytest.mark.parametrize("seed", range(6))
    def test_kill_mid_handshake_recovers_under_lockdep(self, seed):
        """A node dies BETWEEN the plan write and its report (the spec
        plan id is ahead of status) while the chaos substrate injects
        conflicts/transients/watch drops: the handshake must not wedge,
        the spare must be promoted, demand must converge — with every
        lock constructed in the window checked for order inversions and
        the policy/quarantine/ledger state @guarded_by-convicted on any
        unlocked write."""
        from nos_tpu.utils import retry as retry_mod

        original_sleep = retry_mod.sleep
        retry_mod.sleep = lambda s: None
        graph = LockGraph(name=f"nodeloss-chaos-{seed}")
        try:
            with graph.install():
                api = ChaosAPIServer(seed, conflict_rate=0.15,
                                     transient_rate=0.10,
                                     drop_watch_rate=0.10,
                                     replay_after_ops=5)
                state = ClusterState()
            clock = [0.0]
            with graph.install():
                NodeController(api, state,
                               SliceNodeInitializer(api)).bind()
                PodController(api, state).bind()
                ctl = new_slice_partitioner_controller(
                    api, state, batch_timeout_s=60.0, batch_idle_s=10.0,
                    clock=lambda: clock[0],
                    spare_hosts_per_pool=1, node_suspect_after_s=300.0)
                ctl.bind()
                agents = {}
                for i in range(2):
                    name = f"host-{i}"
                    api.create(KIND_NODE, make_tpu_node(
                        name, pod_id="pod-0", host_index=i))
                    agent = SliceAgent(api, name, FakeTpuRuntime(V5E),
                                       FakePodResources())
                    agent.start()
                    agents[name] = agent
                api.create(KIND_NODE, make_tpu_node(
                    "spare-0", pod_id="pod-0", host_index=100,
                    extra_labels={C.LABEL_SPARE: C.SPARE_WARM}))
                spare_agent = SliceAgent(api, "spare-0",
                                         FakeTpuRuntime(V5E),
                                         FakePodResources())
                spare_agent.start()
                agents["spare-0"] = spare_agent
                sched = build_scheduler(api, clock=lambda: clock[0])
                journal = DecisionJournal(maxlen=256,
                                          clock=lambda: clock[0])
                ledger = ChipSecondLedger(clock=lambda: clock[0])
            guard_state(state, graph, name="partitioning.ClusterState")
            guard_state(ctl.quarantine, graph,
                        name="core.QuarantineList")
            guard_state(ctl._recovery, graph,
                        name="core.SelfHealingPolicy")
            guard_state(journal, graph, name="obs.DecisionJournal")
            guard_state(ledger, graph, name="obs.ChipSecondLedger")

            for i in range(3):
                api.create(KIND_POD, make_slice_pod(
                    "2x2", 1, name=f"c{i}"))
            errors = []

            def tick(name, fn):
                try:
                    fn()
                except Exception as e:  # noqa: BLE001
                    errors.append(f"seed={seed} {name}: {e!r}")

            killed = False
            with obs.scoped(journal=journal, ledger=ledger):
                for rnd in range(60):
                    clock[0] += 61.0
                    tick("scheduler", sched.run_cycle)
                    tick("partitioner", ctl.process_if_ready)
                    if not killed and rnd >= 2:
                        # mid-handshake: the controller just planned;
                        # kill host-0 BEFORE its agent can report, so
                        # its spec plan id dies ahead of its status
                        killed = True
                        agents.pop("host-0").stop()
                        victims = []
                        for p in api.pods_on_node("host-0"):
                            try:
                                api.delete(KIND_POD, p.metadata.name,
                                           p.metadata.namespace)
                                victims.append(p.metadata.name)
                            except Exception:  # noqa: BLE001
                                pass
                        try:
                            api.delete(KIND_NODE, "host-0")
                        except Exception:  # noqa: BLE001
                            pass
                        # the workload controller's duty: requeue the
                        # victims DISPLACED (the bench/production path)
                        for name in victims:
                            api.create(KIND_POD, make_slice_pod(
                                "2x2", 1, name=name,
                                annotations={
                                    C.ANNOT_DISPLACED: displaced_value(
                                        "node-loss", clock[0])}))
                    for name, a in list(agents.items()):
                        tick(f"agent-{name}", a.tick)
                    api.replay_dropped()
                    bound = [p for p in api.list(KIND_POD)
                             if p.spec.node_name
                             and p.status.phase == RUNNING]
                    if killed and len(bound) == 3:
                        break
                clock[0] += 61.0
                tick("scheduler-final", sched.run_cycle)

            assert not errors, errors
            bound = [p for p in api.list(KIND_POD)
                     if p.spec.node_name and p.status.phase == RUNNING]
            assert len(bound) == 3, [p.key for p in api.list(KIND_POD)]
            assert journal.events(category=J.SPARE_PROMOTED)
            assert conservation_ok(ledger.report())
            graph.assert_clean()
        finally:
            graph.close()
            unguard_all()
            retry_mod.sleep = original_sleep
