"""Integration test: timeshare (MPS-analog) fractional chip sharing.

BASELINE config #2: a single v5e-8 host shares chips between small
inference pods via HBM-sized timeshare profiles.  Exercises the full MPS
actuation path — planner -> device-plugin ConfigMap + node label ->
plugin re-advertisement -> chipagent report -> plan handshake -> schedule —
with the generation-stamped readiness that replaces the reference's blind
propagation sleep (mps/partitioner.go:99-100).
"""

from __future__ import annotations

import pytest

# every lock built by the harness is lockdep-checked (conftest fixture)
pytestmark = pytest.mark.usefixtures("lock_discipline")

from nos_tpu.api import constants as C  # noqa: E402
from nos_tpu.controllers.chipagent import ChipAgent
from nos_tpu.controllers.node_controller import NodeController
from nos_tpu.controllers.pod_controller import PodController
from nos_tpu.kube.client import APIServer, KIND_CONFIGMAP, KIND_NODE, KIND_POD
from nos_tpu.kube.objects import RUNNING
from nos_tpu.partitioning.state import ClusterState
from nos_tpu.partitioning.timeshare import (
    DEVICE_PLUGIN_CM_NAME, DEVICE_PLUGIN_CM_NAMESPACE, TimeshareNode,
    new_timeshare_partitioner_controller, plan_id_from_key, to_plugin_config,
)
from nos_tpu.partitioning.state import NodePartitioning, UnitPartitioning
from nos_tpu.scheduler.framework import Framework, NodeInfo
from nos_tpu.scheduler.scheduler import Scheduler
from nos_tpu.testing.factory import make_timeshare_pod, make_tpu_node
from nos_tpu.topology.annotations import parse_status_annotations, status_plan_id


class Harness:
    def __init__(self):
        self.api = APIServer()
        self.state = ClusterState()
        self.clock_now = [0.0]
        NodeController(self.api, self.state).bind()
        PodController(self.api, self.state).bind()
        self.partitioner = new_timeshare_partitioner_controller(
            self.api, self.state,
            batch_timeout_s=60.0, batch_idle_s=10.0,
            clock=lambda: self.clock_now[0],
        )
        self.partitioner.bind()
        self.api.create(KIND_NODE, make_tpu_node(
            "ts-0", partitioning="timeshare"))
        self.agent = ChipAgent(self.api, "ts-0")
        self.agent.start()
        self.scheduler = Scheduler(self.api, Framework())

    def advance(self, seconds: float):
        self.clock_now[0] += seconds

    def get_node(self):
        return self.api.get(KIND_NODE, "ts-0")


def test_fractional_sharing_end_to_end():
    h = Harness()
    # 4 small inference pods, each wanting 8 GB of a 16 GB chip
    for i in range(4):
        h.api.create(KIND_POD, make_timeshare_pod(8, 1, name=f"infer-{i}"))
    assert h.scheduler.run_cycle() == 0          # nothing advertised yet
    h.advance(11.0)
    assert h.partitioner.process_if_ready()

    # ConfigMap rendered under <node>-<planId>
    cm = h.api.get(KIND_CONFIGMAP, DEVICE_PLUGIN_CM_NAME,
                   DEVICE_PLUGIN_CM_NAMESPACE)
    keys = [k for k in cm.data if k.startswith("ts-0.")]
    assert len(keys) == 1
    node = h.get_node()
    # label holds the plan id alone (63-char label-value limit); the full
    # CM key is derived node-side
    label = node.metadata.labels[C.LABEL_DEVICE_PLUGIN_CONFIG]
    assert keys[0] == f"ts-0.{label}"
    assert len(label) <= 63

    # handshake: next batch deferred until the agent reports
    h.advance(61.0)
    h.api.create(KIND_POD, make_timeshare_pod(4, 1, name="late"))
    h.scheduler.run_cycle()
    assert not h.partitioner.process_if_ready()

    # device plugin applies + reporter closes the handshake
    h.agent.tick()
    node = h.get_node()
    assert node.status.allocatable.get(f"{C.RESOURCE_TIMESHARE_PREFIX}8gb") == 4.0
    assert status_plan_id(node.metadata.annotations, family="timeshare") == \
        plan_id_from_key("ts-0", keys[0])

    assert h.scheduler.run_cycle() >= 4
    h.agent.tick()  # kubelet-phase sim: the agent admits the bound pods
    for i in range(4):
        pod = h.api.get(KIND_POD, f"infer-{i}", "default")
        assert pod.spec.node_name == "ts-0"
        assert pod.status.phase == RUNNING

    # reporter attributes usage per chip (tick also re-reports)
    status = parse_status_annotations(h.get_node().metadata.annotations)
    used = sum(a.quantity for a in status if a.status == "used")
    assert used == 4


def test_repartition_sacrifices_free_profiles():
    h = Harness()
    h.api.create(KIND_POD, make_timeshare_pod(8, 1, name="first"))
    h.scheduler.run_cycle()
    h.advance(11.0)
    h.partitioner.process_if_ready()
    h.agent.tick()
    assert h.scheduler.run_cycle() == 1

    # now a 16gb pod: free 8gb profiles must be sacrificed on some chip
    h.advance(61.0)
    h.api.create(KIND_POD, make_timeshare_pod(16, 1, name="big"))
    h.scheduler.run_cycle()
    h.advance(11.0)
    assert h.partitioner.process_if_ready()
    h.agent.tick()
    assert h.scheduler.run_cycle() == 1
    assert h.api.get(KIND_POD, "big", "default").spec.node_name == "ts-0"
    # the used 8gb stays advertised
    node = h.get_node()
    assert node.status.allocatable.get(f"{C.RESOURCE_TIMESHARE_PREFIX}8gb", 0) >= 1


def test_plugin_config_render_roundtrip():
    np = NodePartitioning(units=[
        UnitPartitioning(index=0, resources={
            f"{C.RESOURCE_TIMESHARE_PREFIX}8gb": 2}),
        UnitPartitioning(index=3, resources={
            f"{C.RESOURCE_TIMESHARE_PREFIX}4gb": 1}),
    ])
    cfg = to_plugin_config(np)
    chips = cfg["sharing"]["timeshare"]["chips"]
    assert chips == {"0": {"8gb": 2}, "3": {"4gb": 1}}


def test_timeshare_node_respects_used_profiles():
    node = make_tpu_node(
        "ts-1", partitioning="timeshare",
        status_geometry={"used": {"8gb": 1}, "free": {"8gb": 1}})
    ni = NodeInfo(node=node)
    tn = TimeshareNode(node, ni)
    # used profile must survive any regeometry
    tn.update_geometry_for({"16gb": 8})
    total_used = sum(u.used.get(8, 0) for u in tn.units)
    assert total_used == 1


def test_hybrid_status_annotations_coexist():
    """On a hybrid node the two reporters must not clobber each other's
    status family (family-scoped stripping)."""
    from nos_tpu.topology.annotations import strip_status_annotations
    annots = {
        f"{C.ANNOT_STATUS_PREFIX}0-2x2-free": "1",
        f"{C.ANNOT_STATUS_PREFIX}1-8gb-used": "2",
    }
    strip_status_annotations(annots, family="timeshare")
    assert f"{C.ANNOT_STATUS_PREFIX}0-2x2-free" in annots
    assert f"{C.ANNOT_STATUS_PREFIX}1-8gb-used" not in annots
    annots[f"{C.ANNOT_STATUS_PREFIX}1-8gb-used"] = "2"
    strip_status_annotations(annots, family="slice")
    assert f"{C.ANNOT_STATUS_PREFIX}1-8gb-used" in annots
    assert f"{C.ANNOT_STATUS_PREFIX}0-2x2-free" not in annots


def test_chipagent_refuses_slice_node():
    api = APIServer()
    api.create(KIND_NODE, make_tpu_node("s-0", partitioning="slice"))
    import pytest
    with pytest.raises(RuntimeError):
        ChipAgent(api, "s-0").start()
