"""Device-plugin gRPC server tests: real grpc over unix sockets with a
kubelet-shaped stub (SURVEY.md §2.8 device data plane — the piece that
advertises carved slice profiles to the kubelet for real)."""

from __future__ import annotations

import concurrent.futures
import queue
import threading

import grpc
import pytest

from nos_tpu.device.deviceplugin import (
    API_VERSION, ENV_DEVICE_IDS, SliceDevicePlugin,
)
from nos_tpu.device.deviceplugin import deviceplugin_pb2 as api_pb2


@pytest.fixture
def kubelet(tmp_path):
    """A Registration-service stub recording RegisterRequests."""
    requests: queue.Queue = queue.Queue()

    def register(request, context):
        requests.put(request)
        return api_pb2.Empty()

    handler = grpc.method_handlers_generic_handler(
        "v1beta1.Registration",
        {"Register": grpc.unary_unary_rpc_method_handler(
            register,
            request_deserializer=api_pb2.RegisterRequest.FromString,
            response_serializer=api_pb2.Empty.SerializeToString)})
    server = grpc.server(
        concurrent.futures.ThreadPoolExecutor(max_workers=2))
    server.add_generic_rpc_handlers((handler,))
    sock = tmp_path / "kubelet.sock"
    server.add_insecure_port(f"unix://{sock}")
    server.start()
    yield str(sock), requests
    server.stop(0)


@pytest.fixture
def plugin(tmp_path, kubelet):
    kubelet_sock, _ = kubelet
    devices = {"ids": ["tpu-0-2x2-1", "tpu-0-2x2-2"]}
    p = SliceDevicePlugin(
        "nos.tpu/slice-2x2", lambda: list(devices["ids"]),
        plugins_dir=str(tmp_path), kubelet_socket=kubelet_sock)
    p.serve()
    yield p, devices
    p.stop()


def _plugin_channel(p: SliceDevicePlugin):
    return grpc.insecure_channel(f"unix://{p.socket_path}")


class TestDevicePlugin:
    def test_registers_with_kubelet(self, plugin, kubelet):
        p, _ = plugin
        _, requests = kubelet
        p.register()
        req = requests.get(timeout=5.0)
        assert req.version == API_VERSION
        assert req.resource_name == "nos.tpu/slice-2x2"
        assert req.endpoint == p.socket_path.rsplit("/", 1)[-1]

    def test_list_and_watch_streams_inventory_and_changes(self, plugin):
        p, devices = plugin
        channel = _plugin_channel(p)
        stream = channel.unary_stream(
            "/v1beta1.DevicePlugin/ListAndWatch",
            request_serializer=api_pb2.Empty.SerializeToString,
            response_deserializer=api_pb2.ListAndWatchResponse.FromString,
        )(api_pb2.Empty())
        first = next(stream)
        assert sorted(d.ID for d in first.devices) == [
            "tpu-0-2x2-1", "tpu-0-2x2-2"]
        assert all(d.health == "Healthy" for d in first.devices)

        # actuation changes the carved geometry -> re-advertise
        devices["ids"] = ["tpu-0-2x2-1"]
        got = queue.Queue()
        threading.Thread(target=lambda: got.put(next(stream)),
                         daemon=True).start()
        p.notify_changed()
        second = got.get(timeout=5.0)
        assert [d.ID for d in second.devices] == ["tpu-0-2x2-1"]
        channel.close()

    def test_allocate_returns_device_ids_env(self, plugin):
        p, _ = plugin
        channel = _plugin_channel(p)
        allocate = channel.unary_unary(
            "/v1beta1.DevicePlugin/Allocate",
            request_serializer=api_pb2.AllocateRequest.SerializeToString,
            response_deserializer=api_pb2.AllocateResponse.FromString)
        resp = allocate(api_pb2.AllocateRequest(container_requests=[
            api_pb2.ContainerAllocateRequest(
                devices_IDs=["tpu-0-2x2-2"])]), timeout=5.0)
        assert resp.container_responses[0].envs[ENV_DEVICE_IDS] == \
            "tpu-0-2x2-2"
        channel.close()


class TestTimesharePlugin:
    def test_replicas_and_hbm_grant_env(self, tmp_path, kubelet):
        from nos_tpu.device.deviceplugin import TimeshareReplicaPlugin

        kubelet_sock, _ = kubelet
        replicas = {"n": 3}
        p = TimeshareReplicaPlugin(
            "nos.tpu/tpu-8gb", gb=8, num_replicas=lambda: replicas["n"],
            plugins_dir=str(tmp_path), kubelet_socket=kubelet_sock)
        p.serve()
        try:
            channel = _plugin_channel(p)
            stream = channel.unary_stream(
                "/v1beta1.DevicePlugin/ListAndWatch",
                request_serializer=api_pb2.Empty.SerializeToString,
                response_deserializer=api_pb2.ListAndWatchResponse
                .FromString)(api_pb2.Empty())
            first = next(stream)
            assert len(first.devices) == 3
            assert all(d.ID.startswith("tpu-8gb::") for d in first.devices)

            allocate = channel.unary_unary(
                "/v1beta1.DevicePlugin/Allocate",
                request_serializer=api_pb2.AllocateRequest
                .SerializeToString,
                response_deserializer=api_pb2.AllocateResponse.FromString)
            # TWO replicas granted -> the env carries 2 x 8 GB
            resp = allocate(api_pb2.AllocateRequest(container_requests=[
                api_pb2.ContainerAllocateRequest(
                    devices_IDs=["tpu-8gb::1", "tpu-8gb::2"])]),
                timeout=5.0)
            envs = resp.container_responses[0].envs
            assert envs["NOS_TPU_TIMESHARE_GB_tpu_8gb"] == "16"
            channel.close()
        finally:
            p.stop()

    def test_grants_sum_into_workload_env_cap(self):
        """The full loop, mixed profiles: per-profile Allocate envs sum
        into one XLA HBM cap."""
        from nos_tpu.device import workload_env

        env = {"NOS_TPU_TIMESHARE_GB_tpu_8gb": "8",
               "NOS_TPU_TIMESHARE_GB_tpu_4gb": "4",
               "TPU_ACCELERATOR_TYPE": "v5litepod-8"}
        applied = workload_env.apply(env)
        assert float(applied["XLA_PYTHON_CLIENT_MEM_FRACTION"]) == \
            pytest.approx(12 / 16 * 0.9)


class TestTimesharePluginManager:
    def test_syncs_from_node_allocatable(self, tmp_path, kubelet):
        from nos_tpu.device.deviceplugin import TimesharePluginManager
        from nos_tpu.kube.client import APIServer, KIND_NODE
        from nos_tpu.testing.factory import make_tpu_node

        kubelet_sock, requests = kubelet
        api = APIServer()
        node = make_tpu_node("ts-0", partitioning="timeshare")
        node.status.allocatable["nos.tpu/tpu-8gb"] = 2.0
        node.status.allocatable["nos.tpu/tpu-4gb"] = 4.0
        api.create(KIND_NODE, node)

        mgr = TimesharePluginManager(
            api, "ts-0", plugins_dir=str(tmp_path),
            kubelet_socket=kubelet_sock)
        try:
            mgr.sync()
            assert set(mgr._plugins) == {"nos.tpu/tpu-8gb",
                                         "nos.tpu/tpu-4gb"}
            # both registered with the kubelet stub
            names = {requests.get(timeout=5.0).resource_name
                     for _ in range(2)}
            assert names == {"nos.tpu/tpu-8gb", "nos.tpu/tpu-4gb"}
            # replica counts follow the node
            p8 = mgr._plugins["nos.tpu/tpu-8gb"]
            assert len(p8._devices().devices) == 2

            def shrink(n):
                n.status.allocatable["nos.tpu/tpu-8gb"] = 1.0

            api.patch(KIND_NODE, "ts-0", mutate=shrink)
            mgr.sync()
            assert len(p8._devices().devices) == 1
        finally:
            mgr.stop()
