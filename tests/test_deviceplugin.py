"""Device-plugin gRPC server tests: real grpc over unix sockets with a
kubelet-shaped stub (SURVEY.md §2.8 device data plane — the piece that
advertises carved slice profiles to the kubelet for real)."""

from __future__ import annotations

import concurrent.futures
import queue
import threading

import grpc
import pytest

from nos_tpu.device.deviceplugin import (
    API_VERSION, ENV_DEVICE_IDS, SliceDevicePlugin,
)
from nos_tpu.device.deviceplugin import deviceplugin_pb2 as api_pb2


@pytest.fixture
def kubelet(tmp_path):
    """A Registration-service stub recording RegisterRequests."""
    requests: queue.Queue = queue.Queue()

    def register(request, context):
        requests.put(request)
        return api_pb2.Empty()

    handler = grpc.method_handlers_generic_handler(
        "v1beta1.Registration",
        {"Register": grpc.unary_unary_rpc_method_handler(
            register,
            request_deserializer=api_pb2.RegisterRequest.FromString,
            response_serializer=api_pb2.Empty.SerializeToString)})
    server = grpc.server(
        concurrent.futures.ThreadPoolExecutor(max_workers=2))
    server.add_generic_rpc_handlers((handler,))
    sock = tmp_path / "kubelet.sock"
    server.add_insecure_port(f"unix://{sock}")
    server.start()
    yield str(sock), requests
    server.stop(0)


@pytest.fixture
def plugin(tmp_path, kubelet):
    kubelet_sock, _ = kubelet
    devices = {"ids": ["tpu-0-2x2-1", "tpu-0-2x2-2"]}
    p = SliceDevicePlugin(
        "nos.tpu/slice-2x2", lambda: list(devices["ids"]),
        plugins_dir=str(tmp_path), kubelet_socket=kubelet_sock)
    p.serve()
    yield p, devices
    p.stop()


def _plugin_channel(p: SliceDevicePlugin):
    return grpc.insecure_channel(f"unix://{p.socket_path}")


class TestDevicePlugin:
    def test_registers_with_kubelet(self, plugin, kubelet):
        p, _ = plugin
        _, requests = kubelet
        p.register()
        req = requests.get(timeout=5.0)
        assert req.version == API_VERSION
        assert req.resource_name == "nos.tpu/slice-2x2"
        assert req.endpoint == p.socket_path.rsplit("/", 1)[-1]

    def test_list_and_watch_streams_inventory_and_changes(self, plugin):
        p, devices = plugin
        channel = _plugin_channel(p)
        stream = channel.unary_stream(
            "/v1beta1.DevicePlugin/ListAndWatch",
            request_serializer=api_pb2.Empty.SerializeToString,
            response_deserializer=api_pb2.ListAndWatchResponse.FromString,
        )(api_pb2.Empty())
        first = next(stream)
        assert sorted(d.ID for d in first.devices) == [
            "tpu-0-2x2-1", "tpu-0-2x2-2"]
        assert all(d.health == "Healthy" for d in first.devices)

        # actuation changes the carved geometry -> re-advertise
        devices["ids"] = ["tpu-0-2x2-1"]
        got = queue.Queue()
        threading.Thread(target=lambda: got.put(next(stream)),
                         daemon=True).start()
        p.notify_changed()
        second = got.get(timeout=5.0)
        assert [d.ID for d in second.devices] == ["tpu-0-2x2-1"]
        channel.close()

    def test_allocate_returns_device_ids_env(self, plugin):
        p, _ = plugin
        channel = _plugin_channel(p)
        allocate = channel.unary_unary(
            "/v1beta1.DevicePlugin/Allocate",
            request_serializer=api_pb2.AllocateRequest.SerializeToString,
            response_deserializer=api_pb2.AllocateResponse.FromString)
        resp = allocate(api_pb2.AllocateRequest(container_requests=[
            api_pb2.ContainerAllocateRequest(
                devices_IDs=["tpu-0-2x2-2"])]), timeout=5.0)
        assert resp.container_responses[0].envs[ENV_DEVICE_IDS] == \
            "tpu-0-2x2-2"
        channel.close()
