"""MoE family tests: routing math, dense equivalence, expert-parallel
sharding over the `ep` mesh axis (8 virtual CPU devices)."""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
import pytest

from nos_tpu.models.moe import MoEConfig, MoELlama, MoEMLP, TINY_MOE, moe_loss
from nos_tpu.parallel.mesh import DEFAULT_RULES, MeshSpec, make_mesh


@pytest.fixture
def tokens():
    return jax.random.randint(
        jax.random.PRNGKey(0), (2, 64), 0, TINY_MOE.vocab_size, jnp.int32)


class TestMoEMLP:
    def test_single_expert_equals_dense_swiglu(self):
        """E=1/k=1 with ample capacity routes everything through the one
        expert at gate weight 1.0 — exactly a dense SwiGLU."""
        cfg = dataclasses.replace(TINY_MOE, num_experts=1, top_k=1,
                                  capacity_factor=2.0)
        layer = MoEMLP(cfg)
        x = jax.random.normal(jax.random.PRNGKey(2), (2, 16, cfg.hidden_size),
                              jnp.float32)
        variables = layer.init(jax.random.PRNGKey(3), x)
        y, _ = layer.apply(variables, x, mutable=["losses"])

        p = nn.meta.unbox(variables)["params"]
        ref = jnp.einsum(
            "bsd,df->bsf", x, p["w_gate"][0])
        ref = nn.silu(ref) * jnp.einsum("bsd,df->bsf", x, p["w_up"][0])
        ref = jnp.einsum("bsf,fd->bsd", ref, p["w_down"][0])
        assert jnp.max(jnp.abs(y - ref)) < 1e-4

    def test_capacity_drops_overflow_tokens(self):
        """capacity_factor -> tiny: most tokens are dropped (output ~0
        for them), none crash, shapes stay static."""
        cfg = dataclasses.replace(TINY_MOE, capacity_factor=0.05)
        layer = MoEMLP(cfg)
        x = jax.random.normal(jax.random.PRNGKey(2), (1, 32, cfg.hidden_size),
                              jnp.float32)
        variables = layer.init(jax.random.PRNGKey(3), x)
        y, _ = layer.apply(variables, x, mutable=["losses"])
        assert y.shape == x.shape
        # with capacity 1 per expert, at most E tokens can produce output
        nonzero = jnp.sum(jnp.any(jnp.abs(y[0]) > 1e-9, axis=-1))
        assert int(nonzero) <= cfg.num_experts * cfg.top_k

    def test_router_aux_is_sown(self):
        layer = MoEMLP(TINY_MOE)
        x = jax.random.normal(jax.random.PRNGKey(2), (1, 16,
                                                      TINY_MOE.hidden_size),
                              jnp.float32)
        variables = layer.init(jax.random.PRNGKey(3), x)
        _, state = layer.apply(variables, x, mutable=["losses"])
        leaves = jax.tree_util.tree_leaves(state["losses"])
        assert leaves and all(jnp.isfinite(v).all() for v in leaves)


class TestMoELlama:
    def test_forward_and_loss_finite(self, tokens):
        model = MoELlama(TINY_MOE)
        params = model.init(jax.random.PRNGKey(1), tokens)["params"]
        logits = model.apply({"params": params}, tokens)
        assert logits.shape == (2, 64, TINY_MOE.vocab_size)
        loss = moe_loss(model, params, tokens)
        assert jnp.isfinite(loss)

    def test_grads_flow_to_every_expert_weight(self, tokens):
        model = MoELlama(TINY_MOE)
        params = model.init(jax.random.PRNGKey(1), tokens)["params"]
        grads = jax.grad(lambda p: moe_loss(model, p, tokens))(params)
        flat = jax.tree_util.tree_leaves_with_path(grads)
        moe_leaves = [(p, g) for p, g in flat if "w_gate" in str(p)]
        assert moe_leaves
        for path, g in moe_leaves:
            assert bool(jnp.any(g != 0)), path

    def test_expert_parallel_step_over_ep_mesh(self, tokens):
        """The ep-axis crown check: jit a full MoE train step over a mesh
        with ep=2, expert weights sharded on ep, one optimizer step, loss
        finite — the same harness dryrun_multichip drives."""
        from nos_tpu.models.moe import make_ep_trainer
        from nos_tpu.parallel.mesh import batch_sharding

        mesh = make_mesh(MeshSpec(fsdp=2, tp=1, sp=2, ep=2))
        model = MoELlama(TINY_MOE)
        params, opt_state, step = make_ep_trainer(model, mesh, tokens)

        # expert weights actually sharded over ep
        w_gate = nn.meta.unbox(params)["layer_0"]["moe"]["w_gate"]
        assert "ep" in str(w_gate.sharding.spec), w_gate.sharding.spec

        toks = jax.device_put(tokens, batch_sharding(mesh))
        params, opt_state, loss = step(params, opt_state, toks)
        assert jnp.isfinite(loss)
