"""Registry histogram semantics + Prometheus exposition validity.

A mini text-format parser asserts `render()` output round-trips:
HELP/TYPE placement, label escaping, bucket monotonicity, `le`
ordering, `+Inf` bucket == `_count` — the contract a real Prometheus
scraper enforces.  Plus the derived-series namespace guards (a scalar
named `foo_count` must not merge with histogram `foo`), windowed-max
semantics, and the in-process quantile estimator.
"""

from __future__ import annotations

import math

import pytest

from nos_tpu.exporter.metrics import (
    DEFAULT_BUCKETS, Registry, histogram_quantile,
)


# ---------------------------------------------------------------------------
# mini text-format parser
# ---------------------------------------------------------------------------

def _unescape(val: str) -> str:
    out = []
    i = 0
    while i < len(val):
        c = val[i]
        if c == "\\" and i + 1 < len(val):
            nxt = val[i + 1]
            if nxt == "n":
                out.append("\n")
            elif nxt == "\\":
                out.append("\\")
            elif nxt == '"':
                out.append('"')
            else:
                raise ValueError(f"bad escape \\{nxt} in label value")
            i += 2
            continue
        out.append(c)
        i += 1
    return "".join(out)


def _parse_labels(body: str) -> dict[str, str]:
    """Parse `k="v",k2="v2"` honouring escapes — a quote inside a value
    must be escaped or this raises (that IS the validity test)."""
    labels: dict[str, str] = {}
    i = 0
    while i < len(body):
        eq = body.index("=", i)
        key = body[i:eq]
        assert body[eq + 1] == '"', f"label {key}: unquoted value"
        j = eq + 2
        raw = []
        while True:
            c = body[j]
            if c == "\\":
                raw.append(body[j:j + 2])
                j += 2
                continue
            if c == '"':
                break
            assert c != "\n", "raw newline inside a label value"
            raw.append(c)
            j += 1
        labels[key] = _unescape("".join(raw))
        i = j + 1
        if i < len(body):
            assert body[i] == ",", f"junk after label {key}"
            i += 1
    return labels


class Exposition:
    """Parsed render() output: samples + per-metric HELP/TYPE metadata,
    with placement rules enforced while parsing."""

    def __init__(self, text: str) -> None:
        assert text.endswith("\n"), "exposition must end with a newline"
        self.samples: list[tuple[str, dict[str, str], float]] = []
        self.meta: dict[str, dict[str, str]] = {}
        samples_seen: set[str] = set()
        for line in text.splitlines():
            if not line:
                continue
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                kind = line[2:6].strip().lower()
                rest = line[7:]
                name, _, value = rest.partition(" ")
                meta = self.meta.setdefault(name, {})
                assert kind not in meta, f"duplicate {kind} for {name}"
                assert name not in samples_seen, \
                    f"{kind} for {name} after its samples"
                meta[kind] = value
                continue
            assert not line.startswith("#"), f"unknown comment: {line}"
            body, _, value_s = line.rpartition(" ")
            if "{" in body:
                name, _, labelpart = body.partition("{")
                assert labelpart.endswith("}"), line
                labels = _parse_labels(labelpart[:-1])
            else:
                name, labels = body, {}
            samples_seen.add(name)
            value = float(value_s)
            self.samples.append((name, labels, value))

    def series(self, name: str) -> list[tuple[dict[str, str], float]]:
        return [(lbl, v) for n, lbl, v in self.samples if n == name]

    def family_of(self, sample_name: str) -> str:
        """The metric family a sample belongs to: histogram children
        (`_bucket`/`_sum`/`_count`) roll up to the base name."""
        for suffix in ("_bucket", "_sum", "_count"):
            base = sample_name.removesuffix(suffix)
            if base != sample_name \
                    and self.meta.get(base, {}).get("type") == "histogram":
                return base
        return sample_name


# ---------------------------------------------------------------------------
# exposition validity
# ---------------------------------------------------------------------------

class TestExpositionValidity:
    def _registry(self) -> Registry:
        reg = Registry()
        reg.describe("nos_t_total", "a counter")
        reg.describe("nos_t_gauge", "a gauge")
        reg.describe("nos_t_seconds", "a histogram")
        reg.inc("nos_t_total", labels={"kind": "slice"})
        reg.set("nos_t_gauge", 7.5)
        for v in (0.003, 0.02, 0.02, 0.7, 3.0, 100.0):
            reg.observe("nos_t_seconds", v, labels={"class": "a"})
        reg.observe("nos_t_seconds", 0.04, labels={"class": "b"})
        return reg

    def test_every_sample_has_type_placed_before_it(self):
        exp = Exposition(self._registry().render())
        for name, _, _ in exp.samples:
            family = exp.family_of(name)
            assert "type" in exp.meta.get(family, {}), \
                f"sample {name} has no TYPE for family {family}"

    def test_help_precedes_type_for_described_metrics(self):
        text = self._registry().render()
        for base in ("nos_t_total", "nos_t_seconds"):
            help_i = text.index(f"# HELP {base} ")
            type_i = text.index(f"# TYPE {base} ")
            assert help_i < type_i

    def test_histogram_type_and_children(self):
        exp = Exposition(self._registry().render())
        assert exp.meta["nos_t_seconds"]["type"] == "histogram"
        assert exp.meta["nos_t_seconds_max"]["type"] == "gauge"
        for child in ("nos_t_seconds_bucket", "nos_t_seconds_sum",
                      "nos_t_seconds_count", "nos_t_seconds_max"):
            assert exp.series(child), f"missing {child}"

    def test_le_ordering_and_bucket_monotonicity(self):
        exp = Exposition(self._registry().render())
        for cls in ("a", "b"):
            buckets = [(lbl["le"], v) for lbl, v
                       in exp.series("nos_t_seconds_bucket")
                       if lbl["class"] == cls]
            les = [le for le, _ in buckets]
            assert les[-1] == "+Inf"
            finite = [float(le) for le in les[:-1]]
            assert finite == sorted(finite), "le not ascending"
            assert len(set(finite)) == len(finite), "duplicate le"
            counts = [v for _, v in buckets]
            assert counts == sorted(counts), "bucket counts not cumulative"

    def test_inf_bucket_equals_count(self):
        exp = Exposition(self._registry().render())
        for cls, expected in (("a", 6), ("b", 1)):
            inf = [v for lbl, v in exp.series("nos_t_seconds_bucket")
                   if lbl["class"] == cls and lbl["le"] == "+Inf"]
            cnt = [v for lbl, v in exp.series("nos_t_seconds_count")
                   if lbl["class"] == cls]
            assert inf == [expected] and cnt == [expected]

    def test_sum_present_and_plausible(self):
        exp = Exposition(self._registry().render())
        total = [v for lbl, v in exp.series("nos_t_seconds_sum")
                 if lbl["class"] == "a"]
        assert total == [pytest.approx(0.003 + 0.02 + 0.02 + 0.7
                                       + 3.0 + 100.0)]

    def test_label_escaping_round_trips(self):
        reg = Registry()
        nasty = 'a"b\\c\nd'
        reg.inc("nos_esc_total", labels={"v": nasty})
        exp = Exposition(reg.render())
        [(labels, value)] = exp.series("nos_esc_total")
        assert labels["v"] == nasty
        assert value == 1.0

    def test_observation_beyond_last_bound_lands_only_in_inf(self):
        reg = Registry()
        reg.observe("nos_t_seconds", 999.0)
        exp = Exposition(reg.render())
        buckets = exp.series("nos_t_seconds_bucket")
        for lbl, v in buckets:
            assert v == (1 if lbl["le"] == "+Inf" else 0)


# ---------------------------------------------------------------------------
# derived-series namespace (satellite: suffix collisions)
# ---------------------------------------------------------------------------

class TestDerivedSeriesNamespace:
    def test_scalar_colliding_with_histogram_derived_name_raises(self):
        reg = Registry()
        reg.observe("nos_t_seconds", 0.1)
        for suffix in ("_count", "_sum", "_max", "_bucket"):
            with pytest.raises(ValueError, match="collides"):
                reg.inc(f"nos_t_seconds{suffix}")
            with pytest.raises(ValueError, match="collides"):
                reg.set(f"nos_t_seconds{suffix}", 1.0)

    def test_histogram_colliding_with_existing_scalar_raises(self):
        reg = Registry()
        reg.inc("nos_t_seconds_count")      # user counter, odd name, legal
        with pytest.raises(ValueError, match="already a scalar"):
            reg.observe("nos_t_seconds", 0.1)

    def test_same_name_scalar_and_histogram_raises(self):
        reg = Registry()
        reg.observe("nos_x_seconds", 0.1)
        with pytest.raises(ValueError, match="histogram"):
            reg.inc("nos_x_seconds")
        reg2 = Registry()
        reg2.inc("nos_x_seconds")
        with pytest.raises(ValueError, match="counter/gauge"):
            reg2.observe("nos_x_seconds", 0.1)

    def test_scalar_genuinely_ending_in_sum_keeps_its_own_help(self):
        """Regression: the old render() removesuffix-chained base names,
        so `nos_t_burn_sum`'s HELP was looked up under `nos_t_burn` —
        a metric that never existed — and dropped."""
        reg = Registry()
        reg.describe("nos_t_burn_sum", "genuinely ends in _sum")
        reg.inc("nos_t_burn_sum", 2.0)
        text = reg.render()
        assert "# HELP nos_t_burn_sum genuinely ends in _sum" in text
        exp = Exposition(reg.render())
        assert exp.series("nos_t_burn_sum") == [({}, 2.0)]


# ---------------------------------------------------------------------------
# windowed max (satellite)
# ---------------------------------------------------------------------------

class TestWindowedMax:
    def test_max_resets_on_window_roll_counts_do_not(self):
        reg = Registry()
        reg.observe("nos_t_seconds", 5.0)
        reg.observe("nos_t_seconds", 1.0)
        snap = reg.snapshot()
        assert snap["nos_t_seconds_max"][""] == 5.0
        reg.reset_window()
        snap = reg.snapshot()
        assert snap["nos_t_seconds_max"][""] == 0.0
        assert snap["nos_t_seconds_count"][""] == 2      # cumulative
        assert snap["nos_t_seconds_sum"][""] == pytest.approx(6.0)
        reg.observe("nos_t_seconds", 0.5)
        assert reg.snapshot()["nos_t_seconds_max"][""] == 0.5

    def test_startup_spike_does_not_dominate_after_roll(self):
        reg = Registry()
        reg.observe("nos_t_seconds", 60.0)      # one-off startup spike
        reg.reset_window()
        reg.observe("nos_t_seconds", 0.01)
        assert reg.snapshot()["nos_t_seconds_max"][""] == 0.01


# ---------------------------------------------------------------------------
# buckets + quantiles
# ---------------------------------------------------------------------------

class TestBucketsAndQuantiles:
    def test_custom_buckets_render_and_conflicts_raise(self):
        reg = Registry()
        reg.observe("nos_t_seconds", 0.5, buckets=(0.1, 1.0, 10.0))
        exp = Exposition(reg.render())
        les = [lbl["le"] for lbl, _ in exp.series("nos_t_seconds_bucket")]
        assert les == ["0.1", "1", "10", "+Inf"]
        with pytest.raises(ValueError, match="conflicting"):
            reg.observe("nos_t_seconds", 0.5, buckets=(0.2, 2.0))
        # re-registering the SAME layout is idempotent
        reg.observe("nos_t_seconds", 0.5, buckets=(0.1, 1.0, 10.0))

    def test_describe_pins_buckets(self):
        reg = Registry()
        reg.describe("nos_t_seconds", "h", buckets=(1.0, 2.0))
        reg.observe("nos_t_seconds", 1.5)
        exp = Exposition(reg.render())
        les = [lbl["le"] for lbl, _ in exp.series("nos_t_seconds_bucket")]
        assert les == ["1", "2", "+Inf"]

    def test_invalid_buckets_raise(self):
        reg = Registry()
        with pytest.raises(ValueError, match="strictly increasing"):
            reg.observe("nos_t_seconds", 0.1, buckets=(1.0, 1.0))
        with pytest.raises(ValueError, match="strictly increasing"):
            reg.observe("nos_t_seconds", 0.1, buckets=())

    def test_quantile_interpolates_within_bucket(self):
        reg = Registry()
        for _ in range(100):
            reg.observe("nos_t_seconds", 0.3, buckets=(0.1, 0.5, 1.0))
        # all mass in (0.1, 0.5]: median interpolates to its midpoint
        assert reg.quantile("nos_t_seconds", 0.5) == pytest.approx(0.3)

    def test_quantile_none_without_samples(self):
        reg = Registry()
        assert reg.quantile("nos_t_nothing_seconds", 0.99) is None

    def test_quantile_inf_bucket_reports_observed_max(self):
        reg = Registry()
        reg.observe("nos_t_seconds", 500.0)
        q = reg.quantile("nos_t_seconds", 0.99)
        assert q == 500.0

    def test_quantile_tracks_distribution_tail(self):
        reg = Registry()
        for i in range(99):
            reg.observe("nos_t_seconds", 0.002)
        reg.observe("nos_t_seconds", 20.0)
        p50 = reg.quantile("nos_t_seconds", 0.50)
        p995 = reg.quantile("nos_t_seconds", 0.995)
        assert p50 < 0.01
        assert p995 > 10.0

    def test_histogram_quantile_helper_edge_cases(self):
        assert histogram_quantile((1.0,), [0], 0, 0.5) is None
        # rank exactly on a bucket boundary
        assert histogram_quantile((1.0, 2.0), [1, 1], 2, 0.5) \
            == pytest.approx(1.0)
        assert not math.isnan(
            histogram_quantile(DEFAULT_BUCKETS,
                               [0] * len(DEFAULT_BUCKETS), 3, 0.9))


# ---------------------------------------------------------------------------
# snapshot payload (metricsexporter contract)
# ---------------------------------------------------------------------------

class TestSnapshotPayload:
    def test_snapshot_carries_bucket_series_with_le(self):
        reg = Registry()
        reg.observe("nos_t_seconds", 0.003, labels={"class": "a"})
        snap = reg.snapshot()
        buckets = snap["nos_t_seconds_bucket"]
        assert "class=a,le=0.005" in buckets
        assert buckets["class=a,le=+Inf"] == 1
        assert snap["nos_t_seconds_count"]["class=a"] == 1
        assert snap["nos_t_seconds_max"]["class=a"] == 0.003

    def test_snapshot_counters_and_gauges_unchanged(self):
        reg = Registry()
        reg.inc("nos_t_total", 3.0, labels={"kind": "slice"})
        reg.set("nos_t_gauge", 7.0)
        snap = reg.snapshot()
        assert snap["nos_t_total"]["kind=slice"] == 3.0
        assert snap["nos_t_gauge"][""] == 7.0
