"""Sharded parallel planning: pools, merge determinism, equivalence.

The fleet-scale decision plane's correctness story, pinned:

- pool partitioning groups by machine class + failure domain and the
  pod split is deterministic, capacity-aware, and drops only
  cross-pool-infeasible pods;
- `ClusterSnapshot.subset` shares node objects but isolates fork/COW
  state, so concurrent shards over disjoint pools never write through
  to each other;
- the parallel planner is BYTE-IDENTICAL to the sequential planner on
  single-pool inputs (randomized property), and observationally
  equivalent on multi-pool snapshots whose pod geometry classes are
  pool-unique (the merge determinism contract, docs/performance.md) —
  including cross-pool-infeasible pods and quarantined nodes;
- a chaos-soak variant runs the worker pool under lockcheck
  instrumentation: any lock-order inversion or unguarded write across
  shard threads fails the seed;
- epoch-batched replans: ready batches inside the running epoch defer
  and accumulate into ONE plan cycle.
"""

from __future__ import annotations

import random

import pytest

from nos_tpu import obs
from nos_tpu.api import constants as C
from nos_tpu.kube.client import APIServer, KIND_NODE, KIND_POD
from nos_tpu.obs import journal as J
from nos_tpu.partitioning.core import (
    ParallelGeometryPlanner, SnapshotError, partition_pools, split_pods,
)
from nos_tpu.partitioning.slicepart import (
    SlicePartitionCalculator, SliceProfileCalculator, SliceSnapshotTaker,
)
from nos_tpu.partitioning.slicepart.group import MultiHostGeometryPlanner
from nos_tpu.partitioning.state import ClusterState
from nos_tpu.scheduler.framework import Framework
from nos_tpu.testing.factory import (
    make_pod, make_slice_pod, make_tpu_node,
)
from nos_tpu.testing.lockcheck import LockGraph
from nos_tpu.topology import V4, V5E

CALC = SliceProfileCalculator()


def make_sequential() -> MultiHostGeometryPlanner:
    return MultiHostGeometryPlanner(
        framework=Framework(), calculator=SliceProfileCalculator(),
        partition_calculator=SlicePartitionCalculator())


def make_parallel(**kw) -> ParallelGeometryPlanner:
    kw.setdefault("min_shard_hosts", 0)
    return ParallelGeometryPlanner(
        make_sequential, SliceProfileCalculator(), kind="slice", **kw)


def canon(state) -> dict:
    """PartitioningState -> comparable plain dict (byte-level canon)."""
    return {name: np._canon() for name, np in state.items()}


# v5e profiles vs v4 profiles: no spelling collides, so every pod's
# eligible pool is unique — the premise of the multi-pool equivalence
# property (see pools.py docstring / docs/performance.md).
V5E_PROFILES = ["1x1", "1x2", "2x2", "2x4", "4x4"]
V4_PROFILES = ["1x1x1", "1x1x2", "1x2x2", "2x2x2"]
GEOMETRIES = {
    V5E: [{"free": {"2x4": 1}}, {"free": {"2x2": 2}},
          {"free": {"1x1": 4, "1x2": 2}}, {"used": {"2x4": 1}},
          {"used": {"2x2": 1}, "free": {"2x2": 1}}],
    V4: [{"free": {"1x2x2": 1}}, {"free": {"1x1x2": 2}},
         {"used": {"1x2x2": 1}}, {"used": {"1x1x2": 1},
                                  "free": {"1x1x2": 1}}],
}


def random_state(rng: random.Random, gens, pools_per_gen: int = 2,
                 hosts_per_pool: int = 6) -> ClusterState:
    state = ClusterState()
    for gen in gens:
        for p in range(pools_per_gen):
            for h in range(hosts_per_pool):
                geo = rng.choice(GEOMETRIES[gen])
                state.update_node(make_tpu_node(
                    f"{gen.name}-{p}-h{h}", generation=gen,
                    pod_id=f"{gen.name}-pod-{p}", host_index=h,
                    status_geometry=dict(geo)), [])
    return state


def random_pods(rng: random.Random, gens, n: int,
                infeasible: int = 0) -> list:
    pods = []
    gang_i = 0
    for i in range(n):
        gen = rng.choice(gens)
        profiles = V5E_PROFILES if gen is V5E else V4_PROFILES
        profile = rng.choice(profiles)
        labels = None
        if profile == "4x4":            # v5e multi-host: gang-labeled
            labels = {C.LABEL_POD_GROUP: f"ppgang-{gang_i}"}
            gang_i += 1
        pods.append(make_slice_pod(profile, 1, name=f"pp-{i}",
                                   labels=labels,
                                   priority=rng.randrange(3)))
    for i in range(infeasible):
        # no present generation supports 7x7: cross-pool-infeasible
        pods.append(make_slice_pod("7x7", 1, name=f"pp-inf-{i}"))
    rng.shuffle(pods)
    return pods


class TestPools:
    def test_partition_groups_by_class_and_domain(self):
        state = random_state(random.Random(0), [V5E, V4])
        snap = SliceSnapshotTaker().take_snapshot(state)
        pools = partition_pools(snap)
        assert [p.key for p in pools] == sorted(p.key for p in pools)
        assert len(pools) == 4
        for pool in pools:
            for name in pool.nodes:
                assert name.startswith(f"{pool.accelerator}-")

    def test_split_is_deterministic_and_pool_unique(self):
        rng = random.Random(1)
        state = random_state(rng, [V5E, V4])
        snap = SliceSnapshotTaker().take_snapshot(state)
        pools = partition_pools(snap)
        pods = random_pods(random.Random(2), [V5E, V4], 20, infeasible=2)
        a, inf_a = split_pods(pools, pods, CALC)
        b, inf_b = split_pods(pools, pods, CALC)
        assert {k: [p.key for p in v] for k, v in a.items()} == \
            {k: [p.key for p in v] for k, v in b.items()}
        assert [p.key for p in inf_a] == [p.key for p in inf_b]
        assert len(inf_a) == 2
        # every feasible pod landed in exactly one pool of its generation
        assigned = [p.key for v in a.values() for p in v]
        assert len(assigned) == len(set(assigned)) == len(pods) - 2
        for key, members in a.items():
            accel = key.split("|")[0]
            for pod in members:
                profile = next(iter(CALC.requested_profiles(pod)))
                is_v5e = "x" in profile and profile.count("x") == 1
                assert (accel == "tpu-v5e") == is_v5e

    def test_split_demotes_fragmented_pools(self):
        """A pod is not deterministically starved on the freest-but-
        fragmented pool while a capable sibling pool exists: pools
        whose every host has fewer free chips than a requested single-
        host shape are demoted from assignment."""
        state = ClusterState()
        # pool-0: more TOTAL free chips, but fragmented (2 free 1x1 per
        # host, rest used) — no host could ever re-carve a 2x4
        for h in range(8):
            node = make_tpu_node(
                f"frag{h}", pod_id="pod-0", host_index=h,
                status_geometry={"free": {"1x1": 2},
                                 "used": {"1x1": 6}})
            filler = make_pod(name=f"fragfill{h}", node_name=f"frag{h}",
                              resources={"nos.tpu/slice-1x1": 6})
            state.update_node(node, [filler])
        # pool-1: one virgin host (8 free chips on one host)
        state.update_node(make_tpu_node(
            "virgin", pod_id="pod-1", host_index=0,
            status_geometry={"free": {"2x4": 1}}), [])
        snap = SliceSnapshotTaker().take_snapshot(state)
        pools = partition_pools(snap)
        assert pools[0].free_chips > pools[1].free_chips  # the trap
        by_pool, inf = split_pods(
            pools, [make_slice_pod("2x4", 1, name="whole")], CALC)
        assert not inf
        assert [p.metadata.name for p in by_pool[pools[1].key]] == ["whole"]
        # but a 1x1 pod (fits any host) still goes to the freest pool
        by_pool, _ = split_pods(
            pools, [make_slice_pod("1x1", 1, name="tiny")], CALC)
        assert [p.metadata.name for p in by_pool[pools[0].key]] == ["tiny"]

    def test_split_keeps_gangs_atomic(self):
        """All members of one pod group land in ONE pool — scattered
        members would make every shard carve a multi-host window for
        the same gang."""
        state = ClusterState()
        for p in range(2):
            for h in range(4):
                state.update_node(make_tpu_node(
                    f"g{p}{h}", pod_id=f"pod-{p}", host_index=h,
                    status_geometry={"free": {"2x4": 1}}), [])
        snap = SliceSnapshotTaker().take_snapshot(state)
        pools = partition_pools(snap)
        gang = [make_slice_pod("4x4", 1, name=f"m{i}",
                               labels={C.LABEL_POD_GROUP: "bigone"})
                for i in range(4)]
        # interleave singles so per-pod accounting WOULD have scattered
        # the gang across the two equal pools
        pods = [gang[0], make_slice_pod("1x1", 1, name="s0"), gang[1],
                make_slice_pod("1x1", 1, name="s1"), gang[2], gang[3]]
        by_pool, inf = split_pods(pools, pods, CALC)
        assert not inf
        homes = {k for k, v in by_pool.items()
                 if any(p.metadata.name.startswith("m") for p in v)}
        assert len(homes) == 1, by_pool

    def test_split_spreads_by_remaining_capacity(self):
        # two identical pools: pool-agnostic demand must alternate, not
        # pile onto one pool
        state = ClusterState()
        for p in range(2):
            for h in range(2):
                state.update_node(make_tpu_node(
                    f"n{p}{h}", pod_id=f"pod-{p}", host_index=h,
                    status_geometry={"free": {"2x4": 1}}), [])
        snap = SliceSnapshotTaker().take_snapshot(state)
        pools = partition_pools(snap)
        pods = [make_slice_pod("2x4", 1, name=f"s{i}") for i in range(4)]
        by_pool, _ = split_pods(pools, pods, CALC)
        sizes = sorted(len(v) for v in by_pool.values())
        assert sizes == [2, 2]


class TestSubset:
    def test_subset_shares_objects_but_isolates_forks(self):
        state = random_state(random.Random(3), [V5E])
        snap = SliceSnapshotTaker().take_snapshot(state)
        names = sorted(snap.nodes())[:3]
        sub = snap.subset(names)
        assert sub.get_node(names[0]) is snap.get_node(names[0])
        sub.fork()
        sub.get_node_for_write(names[0]).update_geometry_for({"1x1": 8})
        # the COW clone replaced the SUBSET's entry only
        assert sub.get_node(names[0]) is not snap.get_node(names[0])
        sub.revert()
        assert sub.get_node(names[0]) is snap.get_node(names[0])

    def test_subset_rejects_unknown_and_forked(self):
        state = random_state(random.Random(4), [V5E])
        snap = SliceSnapshotTaker().take_snapshot(state)
        with pytest.raises(SnapshotError):
            snap.subset(["nope"])
        snap.fork()
        with pytest.raises(SnapshotError):
            snap.subset(sorted(snap.nodes())[:1])


class TestEquivalence:
    @pytest.mark.parametrize("seed", range(12))
    def test_single_pool_byte_identical(self, seed):
        """One pool => the parallel planner IS the sequential planner."""
        rng = random.Random(1000 + seed)
        state = random_state(rng, [V5E], pools_per_gen=1, hosts_per_pool=8)
        taker = SliceSnapshotTaker()
        pods = random_pods(random.Random(2000 + seed), [V5E], 14)
        seq = make_sequential().plan(taker.take_snapshot(state), pods)
        parallel = make_parallel()
        par = parallel.plan(taker.take_snapshot(state), pods)
        parallel.close()
        assert canon(par) == canon(seq)

    @pytest.mark.parametrize("seed", range(14))
    def test_multi_pool_observational_equivalence(self, seed):
        """Pool-unique pod classes (one pool per machine class, V5E's
        2-D profile spellings disjoint from V4's 3-D ones): sharded ==
        sequential byte-for-byte, including cross-pool-infeasible pods
        and quarantined nodes.  Same-class multi-pool splits are a
        deliberate policy divergence — covered by the determinism test
        below, per the merge contract in docs/performance.md."""
        rng = random.Random(3000 + seed)
        state = random_state(rng, [V5E, V4], pools_per_gen=1,
                             hosts_per_pool=10)
        taker = SliceSnapshotTaker()
        pods = random_pods(random.Random(4000 + seed), [V5E, V4], 18,
                           infeasible=seed % 3)
        # quarantine a couple of nodes: excluded from BOTH snapshots,
        # exactly as the controller excludes them
        all_names = sorted(state.nodes())
        exclude = set(rng.sample(all_names, k=seed % 4))
        seq = make_sequential().plan(
            taker.take_snapshot(state, exclude=exclude), pods)
        parallel = make_parallel()
        par = parallel.plan(
            taker.take_snapshot(state, exclude=exclude), pods)
        parallel.close()
        assert canon(par) == canon(seq)

    @pytest.mark.parametrize("seed", range(6))
    def test_same_class_multi_pool_is_deterministic(self, seed):
        """Pools of one machine class share profile classes, so the
        capacity-aware split is a policy choice, not a replay of the
        sequential planner — but it must be DETERMINISTIC: same
        snapshot + batch => identical merged plan, across runs and
        worker counts."""
        rng = random.Random(5000 + seed)
        state = random_state(rng, [V5E, V4], pools_per_gen=2)
        taker = SliceSnapshotTaker()
        pods = random_pods(random.Random(6000 + seed), [V5E, V4], 16)
        results = []
        for workers in (1, 2, 4):
            parallel = make_parallel(max_workers=workers)
            results.append(canon(parallel.plan(
                taker.take_snapshot(state), pods)))
            parallel.close()
        assert results[0] == results[1] == results[2]

    def test_multi_pool_merge_covers_every_node(self):
        state = random_state(random.Random(7), [V5E, V4])
        snap = SliceSnapshotTaker().take_snapshot(state)
        n_nodes = len(snap.nodes())
        parallel = make_parallel()
        desired = parallel.plan(snap, random_pods(
            random.Random(8), [V5E, V4], 10))
        parallel.close()
        assert len(desired) == n_nodes

    def test_below_min_shard_hosts_stays_sequential(self):
        state = random_state(random.Random(9), [V5E, V4])
        parallel = make_parallel(min_shard_hosts=10_000)
        snap = SliceSnapshotTaker().take_snapshot(state)
        ring = obs.RingExporter(maxlen=64)
        with obs.scoped(obs.Tracer(ring=ring)):
            parallel.plan(snap, random_pods(random.Random(10),
                                            [V5E, V4], 6))
        parallel.close()
        assert not [s for s in ring.dump() if s["name"] == "plan_shard"]


class TestObservability:
    def test_shard_spans_journal_and_explain(self):
        state = random_state(random.Random(11), [V5E, V4])
        taker = SliceSnapshotTaker()
        pods = random_pods(random.Random(12), [V5E, V4], 12)
        ring = obs.RingExporter(maxlen=256)
        tracer = obs.Tracer(ring=ring)
        journal = obs.DecisionJournal(maxlen=256)
        parallel = make_parallel()
        with obs.scoped(tracer, journal):
            # the controller's root span: explain plan keys off it
            with tracer.span("partitioner.plan_cycle", kind="slice"):
                parallel.plan(taker.take_snapshot(state), pods)
        parallel.close()
        spans = ring.dump()
        shards = [s for s in spans if s["name"] == "plan_shard"]
        assert len(shards) == 4
        pools = {s["attrs"]["pool"] for s in shards}
        assert len(pools) == 4
        # worker-thread spans are parented INTO the cycle's trace
        roots = [s for s in spans
                 if s["name"] == "partitioner.plan_cycle"]
        assert all(s["trace_id"] == roots[0]["trace_id"] for s in shards)
        merged = journal.events(category=J.PLAN_SHARD_MERGED)
        assert len(merged) == 1
        assert merged[0].attrs["shards"] == 4
        assert merged[0].trace_id == roots[0]["trace_id"]

        from nos_tpu.obs.explain import explain_plan
        snapshot = {"spans": spans,
                    "journal": [r.to_dict() for r in journal.events()]}
        lines = explain_plan(snapshot)
        text = "\n".join(lines)
        assert "shard time by pool:" in text
        for key in pools:
            assert key in text
        assert "plan-shard-merged" in text

    def test_shard_histogram_observed_per_pool(self):
        from nos_tpu.exporter.metrics import REGISTRY

        state = random_state(random.Random(13), [V5E, V4])
        parallel = make_parallel()
        parallel.plan(SliceSnapshotTaker().take_snapshot(state),
                      random_pods(random.Random(14), [V5E, V4], 8))
        parallel.close()
        text = REGISTRY.render()
        assert "nos_tpu_plan_shard_seconds" in text
        assert 'pool="tpu-v5e|tpu-v5e-pod-0"' in text


@pytest.mark.chaos
class TestParallelChaosSoak:
    @pytest.mark.parametrize("seed", range(6))
    def test_worker_pool_under_lockcheck(self, seed):
        """The shard worker pool under lockdep: inversions or unguarded
        shared-state writes across shard threads fail the seed; the
        merged plan still matches the sequential planner."""
        lock_graph = LockGraph(name=f"parallel-plan-seed-{seed}")
        rng = random.Random(7000 + seed)
        with lock_graph.install():
            state = random_state(rng, [V5E, V4], pools_per_gen=2,
                                 hosts_per_pool=5)
            tracer = obs.Tracer(ring=obs.RingExporter(maxlen=256))
            journal = obs.DecisionJournal(maxlen=256)
            parallel = make_parallel(max_workers=4)
        taker = SliceSnapshotTaker()
        pods = random_pods(random.Random(8000 + seed), [V5E, V4], 16,
                           infeasible=1)
        try:
            with obs.scoped(tracer, journal):
                with lock_graph.install():
                    par = parallel.plan(taker.take_snapshot(state), pods)
                    par2 = parallel.plan(taker.take_snapshot(state), pods)
            # concurrent shards under lockdep are still deterministic
            assert canon(par) == canon(par2)
            lock_graph.assert_clean()
        finally:
            parallel.close()
            lock_graph.close()


class TestEpochBatching:
    def _cluster(self, replan_epoch_s=None):
        api = APIServer()
        clock = [100.0]
        state = ClusterState()
        from nos_tpu.controllers.node_controller import NodeController
        from nos_tpu.controllers.pod_controller import PodController
        from nos_tpu.partitioning.slicepart import SliceNodeInitializer

        NodeController(api, state, SliceNodeInitializer(api)).bind()
        PodController(api, state).bind()
        from nos_tpu.partitioning.slicepart.factory import (
            new_slice_partitioner_controller,
        )

        ctl = new_slice_partitioner_controller(
            api, state, batch_timeout_s=60.0, batch_idle_s=10.0,
            replan_epoch_s=replan_epoch_s, clock=lambda: clock[0])
        ctl.bind()
        api.create(KIND_NODE, make_tpu_node("host-0"))
        self._ack_plan(api)
        return api, ctl, clock

    @staticmethod
    def _ack_plan(api):
        """Stand-in agent: report status == spec so the handshake never
        blocks (this suite tests the epoch gate, not the handshake)."""
        from nos_tpu.api import constants as AC
        from nos_tpu.topology.annotations import spec_plan_id

        node = api.get(KIND_NODE, "host-0")
        pid = spec_plan_id(node.metadata.annotations, family="slice")
        if pid:
            def mutate(n):
                n.metadata.annotations[
                    AC.status_plan_annotation("slice")] = pid
            api.patch(KIND_NODE, "host-0", mutate=mutate)

    def _unschedulable(self, api, name):
        pod = make_slice_pod("2x2", 1, name=name)
        pod.mark_unschedulable("no fit")
        api.create(KIND_POD, pod)

    def test_ready_batch_defers_inside_epoch(self):
        api, ctl, clock = self._cluster(replan_epoch_s=30.0)
        self._unschedulable(api, "a")
        clock[0] += 61.0
        assert ctl.process_if_ready()          # first plan: never deferred
        # two more triggers, batch ready, but the epoch is still running
        self._unschedulable(api, "b")
        self._unschedulable(api, "c")
        self._ack_plan(api)
        clock[0] += 15.0                       # > idle window, < epoch
        assert not ctl.process_if_ready()
        assert len(ctl._batcher) == 2          # accumulating, not dropped
        clock[0] += 20.0                       # epoch elapsed
        assert ctl.process_if_ready()          # ONE replan takes both
        assert len(ctl._batcher) == 0

    def test_epoch_defaults_to_idle_window(self):
        _, ctl, _ = self._cluster()
        assert ctl._replan_epoch_s == 10.0

    @staticmethod
    def _deferred_total() -> float:
        from nos_tpu.exporter.metrics import REGISTRY

        for line in REGISTRY.render().splitlines():
            if line.startswith("nos_tpu_replan_epoch_deferred_total") \
                    and 'kind="slice"' in line:
                return float(line.rsplit(" ", 1)[1])
        return 0.0

    def test_deferral_metric_counts_transitions(self):
        api, ctl, clock = self._cluster(replan_epoch_s=30.0)
        self._unschedulable(api, "a")
        clock[0] += 61.0
        assert ctl.process_if_ready()
        self._ack_plan(api)
        self._unschedulable(api, "b")
        clock[0] += 15.0
        before = self._deferred_total()
        assert not ctl.process_if_ready()
        assert self._deferred_total() == before + 1   # one transition
        assert not ctl.process_if_ready()      # same epoch: no double count
        assert self._deferred_total() == before + 1
        clock[0] += 20.0
        assert ctl.process_if_ready()


class TestShardFailure:
    def test_failed_shard_drains_siblings_and_planner_is_reusable(self):
        """A raising shard must not leave sibling futures running when
        plan() propagates: the per-slot shard planners are reused, so a
        retrying caller would otherwise race a still-running thread."""
        class Boom(Exception):
            pass

        def mk():
            planner = make_sequential()
            orig = planner.plan

            def plan(snapshot, pods):
                if any(n.startswith("tpu-v4") for n in snapshot.nodes()):
                    raise Boom()
                return orig(snapshot, pods)

            planner.plan = plan  # type: ignore[method-assign]
            return planner

        par = ParallelGeometryPlanner(
            mk, SliceProfileCalculator(), kind="slice", min_shard_hosts=0)
        taker = SliceSnapshotTaker()
        bad = random_state(random.Random(42), [V5E, V4])
        with pytest.raises(Boom):
            par.plan(taker.take_snapshot(bad),
                     random_pods(random.Random(1), [V5E, V4], 6))
        good = random_state(random.Random(43), [V5E])   # 2 v5e pools
        desired = par.plan(taker.take_snapshot(good),
                           random_pods(random.Random(2), [V5E], 6))
        assert len(desired) == 12
        par.close()


class TestTimeshareEligibility:
    def test_gb_profile_skips_undersized_generation(self):
        """A timeshare profile bigger than a generation's per-CHIP HBM
        (timeshare units carve per chip: v5e 16 GB, v5p 95 GB) never
        lands on that generation's pools, even when they are freer."""
        from nos_tpu.partitioning.timeshare.calculators import (
            TimeshareProfileCalculator,
        )
        from nos_tpu.testing.factory import make_timeshare_pod
        from nos_tpu.topology import V5P

        state = ClusterState()
        for h in range(4):      # v5e pool: freer by chip-equivalents
            state.update_node(make_tpu_node(
                f"e{h}", pod_id="pe", host_index=h,
                status_geometry={"free": {"2x4": 1}}), [])
        state.update_node(make_tpu_node(
            "p0", generation=V5P, pod_id="pp", host_index=0,
            status_geometry={"free": {"1x2x2": 1}}), [])
        snap = SliceSnapshotTaker().take_snapshot(state)
        pools = partition_pools(snap)
        assert len(pools) == 2
        by_pool, inf = split_pods(
            pools, [make_timeshare_pod(30, 1, name="big")],
            TimeshareProfileCalculator())
        assert not inf
        v5p_key = next(p.key for p in pools if "v5p" in p.key)
        assert [p.metadata.name for p in by_pool[v5p_key]] == ["big"]
        # and one no generation's CHIP can hold is infeasible everywhere
        _, inf = split_pods(
            pools, [make_timeshare_pod(200, 1, name="huge")],
            TimeshareProfileCalculator())
        assert [p.metadata.name for p in inf] == ["huge"]
