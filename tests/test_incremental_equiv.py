"""Incremental vs full-rescan decision equivalence (ISSUE 18).

The dirty-set scheduler keeps the previous cycle's snapshot and derived
indexes (class scans, filter memos, busy map, feasibility indexes) and
re-levels only the watch-dirty node set; ``incremental=False`` rebuilds
everything per cycle.  The two modes must emit byte-identical decision
journals for the same event stream — one stale cross-cycle memo, one
node the dirty walk skipped but the full walk would have visited, shows
up as the first differing record.

nosdiff (analysis/determinism.py) certifies this on the benchmark trace
in child interpreters across PYTHONHASHSEED; these tests replay
BENCH-style event streams in-process where the interesting *schedules*
are easy to provoke: mid-stream unbinds, node churn, annotation-only
dirtying, the periodic full-rescan backstop, and a view-epoch /
per-node generation counter sitting at the int64 boundary.
"""

from __future__ import annotations

import itertools
import json
import random

import pytest

from nos_tpu.cmd.assembly import build_scheduler
from nos_tpu.kube.client import APIServer, KIND_NODE, KIND_POD
from nos_tpu.obs.journal import DecisionJournal, get_journal, set_journal
from nos_tpu.obs.trace import Tracer, get_tracer, set_tracer
from nos_tpu.testing.factory import make_slice_pod, make_tpu_node

HOSTS = 12
PER_DOMAIN = 4
SHAPES = ("1x1", "2x2", "2x4")


@pytest.fixture(autouse=True)
def _pinned_obs():
    """Fresh journal per run (installed by run_stream) and a disabled
    tracer: span-id assignment is a process-global counter, so two
    otherwise identical runs would differ in trace ids alone."""
    prev_journal = get_journal()
    prev_tracer = set_tracer(Tracer(enabled=False))
    yield
    set_journal(prev_journal)
    set_tracer(prev_tracer)


def journal_lines() -> list[str]:
    """The journal as canonical JSON lines — the nosdiff byte format."""
    return [json.dumps(r.to_dict(), sort_keys=True, separators=(",", ":"))
            for r in get_journal().events()]


def pod_assignments(api: APIServer) -> dict[str, str]:
    return {p.metadata.name: p.spec.node_name for p in api.list(KIND_POD)}


def run_stream(steps, *, incremental: bool, full_rescan_every: int = 512,
               prepare=None):
    """Drive one scheduler over `steps` (callables mutating the API,
    one cycle after each); returns (journal lines, scheduler, api).

    The journal gets a logical clock so ``ts`` is a step number — wall
    time is not a decision and must not enter the byte comparison."""
    ticks = itertools.count(1)
    set_journal(DecisionJournal(maxlen=1 << 16,
                                clock=lambda: float(next(ticks))))
    api = APIServer()
    scheduler = build_scheduler(api, incremental=incremental,
                                full_rescan_every=full_rescan_every,
                                clock=lambda: 0.0)
    if prepare is not None:
        prepare(scheduler)
    for step in steps:
        step(api)
        scheduler.run_cycle()
    return journal_lines(), scheduler, api


def assert_equivalent(steps_a, steps_b, **inc_kwargs):
    """The correctness anchor: identical journals AND identical final
    placements between incremental and full-rescan over one stream."""
    inc_lines, inc_sched, inc_api = run_stream(
        steps_a, incremental=True, **inc_kwargs)
    full_lines, full_sched, full_api = run_stream(
        steps_b, incremental=False)
    try:
        assert inc_lines, "stream produced an empty journal — vacuous test"
        assert inc_lines == full_lines
        assert pod_assignments(inc_api) == pod_assignments(full_api)
    finally:
        inc_sched.close()
        full_sched.close()
    return inc_sched, inc_api


# -- stream builders ---------------------------------------------------------

def make_fleet(api: APIServer) -> None:
    """BENCH-shaped fleet in miniature: domains of PER_DOMAIN hosts,
    every third host pre-filled (a bound whole-host pod), the rest free."""
    for i in range(HOSTS):
        full = i % 3 == 0
        geometry = {"used": {"2x4": 1}} if full else {"free": {"2x4": 1}}
        api.create(KIND_NODE, make_tpu_node(
            f"host-{i}", pod_id=f"dom-{i // PER_DOMAIN}",
            host_index=i % PER_DOMAIN, status_geometry=geometry))
        if full:
            api.create(KIND_POD, make_slice_pod(
                "2x4", 1, name=f"filler-{i}", node_name=f"host-{i}"))


def bench_style_steps(seed: int):
    """A deterministic pseudo-random event stream: pod arrivals of mixed
    shapes, mid-stream deletes (freeing capacity = dirtying a node),
    and annotation-only node touches (dirty without capacity change)."""
    rng = random.Random(seed)
    counter = itertools.count()
    created: list[str] = []
    steps = [make_fleet]

    def arrivals(api: APIServer) -> None:
        for _ in range(rng.randrange(1, 4)):
            name = f"p{next(counter)}"
            api.create(KIND_POD, make_slice_pod(
                rng.choice(SHAPES), 1, name=name))
            created.append(name)

    def churn(api: APIServer) -> None:
        if created and rng.random() < 0.5:
            victim = created.pop(rng.randrange(len(created)))
            api.delete(KIND_POD, victim, "default")
        host = f"host-{rng.randrange(HOSTS)}"
        api.patch(KIND_NODE, host,
                  mutate=lambda n: n.metadata.annotations.__setitem__(
                      "touch", str(rng.random())))

    for cycle in range(8):
        steps.append(arrivals if cycle % 2 == 0 else churn)
    return steps


# -- the tests ---------------------------------------------------------------

class TestJournalEquivalence:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_bench_style_streams(self, seed):
        # two independently built streams (same seed) because each run
        # consumes its own RNG/counters while mutating its own API
        assert_equivalent(bench_style_steps(seed), bench_style_steps(seed))

    def test_backstop_rescan_preserves_journal(self):
        # full_rescan_every=2 forces the periodic backstop to fire on
        # every other of the 9 cycles: the re-leveled indexes must not
        # change a single decision vs the never-incremental run
        sched, _ = assert_equivalent(
            bench_style_steps(7), bench_style_steps(7),
            full_rescan_every=2)
        assert sched._full_rescan_every == 2
        # the counter never accumulates past the period — the backstop
        # actually reset it (i.e. it fired, the test is not vacuous)
        assert sched._cycles_since_rescan < 2

    def test_generation_wraparound(self):
        # per-node generations and the fleet view epoch are unbounded
        # counters used as memo-key material; start them just below
        # 2**63 so the stream pushes them across the int64 boundary —
        # feasibility indexes keyed on the epoch must keep invalidating
        def age_counters(scheduler) -> None:
            cache = scheduler._cache
            assert cache is not None
            cache._epoch = 2**63 - 2
            for i in range(HOSTS):
                cache._gen[f"host-{i}"] = 2**63 - 2

        sched, _ = assert_equivalent(
            bench_style_steps(5), bench_style_steps(5),
            prepare=age_counters)
        assert sched._cache.view_epoch() > 2**63

    def test_incremental_defaults_on_with_watch_substrate(self):
        api = APIServer()
        sched = build_scheduler(api)
        try:
            assert sched._incremental
            assert sched._cache is not None
        finally:
            sched.close()
