"""Serving replica-autoscaler unit suite (nos_tpu/serving/autoscaler.py):
scale-up/down hysteresis, cooldowns, band clamps, victim choice on
scale-down, config validation, status publication, retry-on-conflict
under the chaos substrate, leader handoff, and a seeded chaos round
under lockcheck with the autoscaler's @guarded_by contract enforced.
"""

from __future__ import annotations

import threading
import time

import pytest

from nos_tpu.api import constants as C
from nos_tpu.api.config import AutoscalerConfig, ConfigError
from nos_tpu.kube.client import (
    APIServer, KIND_CONFIGMAP, KIND_POD,
)
from nos_tpu.serving.autoscaler import (
    ReplicaAutoscaler, ServingService, replica_load,
)
from nos_tpu.utils import retry as retry_mod


@pytest.fixture(autouse=True)
def fast_retry(monkeypatch):
    monkeypatch.setattr(retry_mod, "sleep", lambda s: None)


def make_service(**kw) -> ServingService:
    defaults = dict(name="chat", namespace="serve", slice_shape="1x1",
                    min_replicas=1, max_replicas=8,
                    target_load_per_replica=10.0,
                    scale_up_cooldown_s=0.0, scale_down_cooldown_s=0.0,
                    down_hysteresis=0.2)
    defaults.update(kw)
    return ServingService(**defaults)


class Harness:
    def __init__(self, svc: ServingService | None = None,
                 api: APIServer | None = None) -> None:
        self.now = [0.0]
        self.api = api or APIServer()
        self.svc = svc or make_service()
        self.autoscaler = ReplicaAutoscaler(
            self.api, [self.svc], clock=lambda: self.now[0])

    def replicas(self) -> list:
        return self.api.list(
            KIND_POD, namespace=self.svc.namespace,
            label_selector={C.LABEL_SERVICE: self.svc.name})

    def stamp(self, total_load: float) -> None:
        pods = self.replicas()
        assert pods, "stamp() needs at least one replica"
        share = total_load / len(pods)
        for p in pods:
            # retry-wrapped: the chaos harness injects conflicts on
            # patch, and the stamp is test plumbing, not the subject
            retry_mod.retry_on_conflict(
                self.api, KIND_POD, p.metadata.name,
                lambda q: q.metadata.annotations.__setitem__(
                    C.ANNOT_SERVING_LOAD, str(share)),
                p.metadata.namespace, component="test-stamp")


class TestScaling:
    def test_min_floor_is_enforced_immediately(self):
        h = Harness(make_service(min_replicas=3))
        out = h.autoscaler.reconcile()
        assert len(h.replicas()) == 3
        assert out["serve/chat"]["scaled"] == 3

    def test_scale_up_follows_load(self):
        h = Harness()
        h.autoscaler.reconcile()
        h.stamp(35.0)                      # ceil(35/10) = 4
        h.now[0] = 1.0
        h.autoscaler.reconcile()
        assert len(h.replicas()) == 4

    def test_max_clamp(self):
        h = Harness(make_service(max_replicas=5))
        h.autoscaler.reconcile()
        h.stamp(1000.0)
        h.now[0] = 1.0
        h.autoscaler.reconcile()
        assert len(h.replicas()) == 5

    def test_replica_pods_carry_the_tier_contract(self):
        h = Harness()
        h.autoscaler.reconcile()
        pod = h.replicas()[0]
        assert pod.metadata.labels[C.LABEL_TIER] == C.TIER_SERVING
        assert pod.metadata.labels[C.LABEL_SERVICE] == "chat"
        assert C.ANNOT_SERVING_LOAD in pod.metadata.annotations
        assert pod.metadata.creation_timestamp == 0.0
        assert "nos.tpu/slice-1x1" in \
            pod.spec.containers[0].resources

    def test_scale_down_hysteresis_blocks_the_boundary(self):
        """Load just under the shrunk fleet's capacity must NOT scale
        down: without the headroom requirement the boundary load
        re-adds the replica next tick (flap)."""
        h = Harness()
        h.autoscaler.reconcile()
        h.stamp(35.0)
        h.now[0] = 1.0
        h.autoscaler.reconcile()
        assert len(h.replicas()) == 4
        # desired at 29 is ceil(29/10)=3, but 29 > 3*10*(1-0.2)=24:
        # the shrunk fleet would lack headroom — stay at 4
        h.stamp(29.0)
        h.now[0] = 2.0
        h.autoscaler.reconcile()
        assert len(h.replicas()) == 4
        # desired at 22 is still 3, and 22 <= 24: the shrink is safe
        h.stamp(22.0)
        h.now[0] = 3.0
        h.autoscaler.reconcile()
        assert len(h.replicas()) == 3

    def test_scale_up_cooldown_defers_the_second_burst(self):
        h = Harness(make_service(scale_up_cooldown_s=10.0))
        h.autoscaler.reconcile()   # min floor: arms the up clock at t=0
        h.stamp(25.0)
        h.now[0] = 1.0
        h.autoscaler.reconcile()
        assert len(h.replicas()) == 1      # deferred: inside cooldown
        h.now[0] = 10.5
        h.autoscaler.reconcile()
        assert len(h.replicas()) == 3      # cooldown passed (re-arms)
        h.stamp(60.0)
        h.now[0] = 11.0
        h.autoscaler.reconcile()
        assert len(h.replicas()) == 3      # second burst deferred
        h.now[0] = 21.0
        h.autoscaler.reconcile()
        assert len(h.replicas()) == 6

    def test_scale_down_cooldown(self):
        h = Harness(make_service(scale_down_cooldown_s=30.0))
        h.autoscaler.reconcile()
        h.stamp(35.0)
        h.now[0] = 1.0
        h.autoscaler.reconcile()
        assert len(h.replicas()) == 4
        h.stamp(15.0)                      # desired 2, headroom ok
        h.now[0] = 2.0
        h.autoscaler.reconcile()           # first down: clock arms
        assert len(h.replicas()) == 2
        h.stamp(5.0)                       # desired 1
        h.now[0] = 3.0
        h.autoscaler.reconcile()
        assert len(h.replicas()) == 2      # inside down cooldown
        h.now[0] = 40.0
        h.autoscaler.reconcile()
        assert len(h.replicas()) == 1      # cooldown passed, min floor

    def test_scale_down_prefers_pending_then_least_loaded(self):
        h = Harness()
        h.autoscaler.reconcile()
        h.stamp(35.0)
        h.now[0] = 1.0
        h.autoscaler.reconcile()
        pods = h.replicas()
        assert len(pods) == 4
        # mark one RUNNING+loaded, one RUNNING+idle; two stay PENDING
        from nos_tpu.kube.objects import RUNNING

        def mark(name, load):
            def mutate(p):
                p.status.phase = RUNNING
                p.spec.node_name = "host-0"
                p.metadata.annotations[C.ANNOT_SERVING_LOAD] = str(load)
            h.api.patch(KIND_POD, name, "serve", mutate=mutate)
        names = sorted(p.metadata.name for p in pods)
        mark(names[0], 6.0)
        mark(names[1], 1.0)
        for p in h.replicas():      # drop the signal so desired = 1
            if p.metadata.name not in names[:2]:
                h.api.patch(
                    KIND_POD, p.metadata.name, "serve",
                    mutate=lambda q: q.metadata.annotations.
                    __setitem__(C.ANNOT_SERVING_LOAD, "0"))
        h.now[0] = 2.0
        h.autoscaler.reconcile()
        left = {p.metadata.name for p in h.replicas()}
        # survivors: the loaded running replica is shed LAST
        assert names[0] in left
        assert len(left) == 1

    def test_scale_down_prefers_drained_replicas_over_least_loaded(self):
        """Session-aware victim order: a RUNNING replica with ZERO
        router-published sessions is shed before a lighter-loaded one
        still carrying live streams — killing the drained replica cuts
        no stream (the router's ANNOT_SERVING_SESSIONS loop)."""
        h = Harness()
        h.autoscaler.reconcile()
        h.stamp(25.0)
        h.now[0] = 1.0
        h.autoscaler.reconcile()
        pods = sorted(p.metadata.name for p in h.replicas())
        assert len(pods) == 3
        from nos_tpu.kube.objects import RUNNING

        def mark(name, load, sessions):
            def mutate(p):
                p.status.phase = RUNNING
                p.spec.node_name = "host-0"
                p.metadata.annotations[C.ANNOT_SERVING_LOAD] = str(load)
                p.metadata.annotations[C.ANNOT_SERVING_SESSIONS] = \
                    str(sessions)
            h.api.patch(KIND_POD, name, "serve", mutate=mutate)
        mark(pods[0], 5.0, 3)       # streaming
        mark(pods[1], 1.0, 2)       # least loaded, still streaming
        mark(pods[2], 6.0, 0)       # drained: the right victim
        h.now[0] = 2.0
        # desired ceil(12/10) = 2, headroom 12 <= 2*10*0.8: shed ONE
        h.autoscaler.reconcile()
        left = {p.metadata.name for p in h.replicas()}
        assert pods[2] not in left, \
            "the drained replica must be shed first"
        assert pods[0] in left and pods[1] in left

    def test_status_configmap_published(self):
        h = Harness()
        h.autoscaler.reconcile()
        cm = h.api.get(KIND_CONFIGMAP, "nos-tpu-autoscaler-status",
                       "nos-tpu-system")
        assert "serve/chat" in cm.data

    def test_replica_load_parses_garbage_as_zero(self):
        from nos_tpu.testing.factory import make_pod

        assert replica_load(make_pod(
            annotations={C.ANNOT_SERVING_LOAD: "nan"})) == 0.0
        assert replica_load(make_pod(
            annotations={C.ANNOT_SERVING_LOAD: "-3"})) == 0.0
        assert replica_load(make_pod()) == 0.0
        assert replica_load(make_pod(
            annotations={C.ANNOT_SERVING_LOAD: "7.5"})) == 7.5


class TestServiceSpec:
    def test_exactly_one_shape(self):
        with pytest.raises(ValueError):
            ServingService(name="x", slice_shape="1x1", timeshare_gb=8)
        with pytest.raises(ValueError):
            ServingService(name="x")

    def test_band_and_knob_validation(self):
        with pytest.raises(ValueError):
            make_service(min_replicas=5, max_replicas=2)
        with pytest.raises(ValueError):
            make_service(target_load_per_replica=0.0)
        with pytest.raises(ValueError):
            make_service(down_hysteresis=1.0)

    def test_from_mapping_rejects_unknown_keys(self):
        with pytest.raises(ValueError, match="unknown"):
            ServingService.from_mapping(
                {"name": "x", "slice_shape": "1x1", "cooldown": 1})

    def test_autoscaler_config_validates_services(self):
        cfg = AutoscalerConfig(services=[
            {"name": "chat", "slice_shape": "1x1"}])
        cfg.validate()
        bad = AutoscalerConfig(services=[{"name": "chat"}])
        with pytest.raises(ConfigError):
            bad.validate()


class TestChaos:
    def test_status_write_retries_on_conflict(self):
        from nos_tpu.exporter.metrics import REGISTRY
        from nos_tpu.testing.chaos import ChaosAPIServer

        api = ChaosAPIServer(7, conflict_rate=0.5, transient_rate=0.2)
        h = Harness(api=api)
        before = REGISTRY.snapshot().get("nos_tpu_retry_total", {}).get(
            "component=autoscaler-status", 0.0)
        for i in range(30):
            h.now[0] = float(i)
            h.autoscaler.reconcile()
        cm = api.get(KIND_CONFIGMAP, "nos-tpu-autoscaler-status",
                     "nos-tpu-system")
        assert "serve/chat" in cm.data
        after = REGISTRY.snapshot().get("nos_tpu_retry_total", {}).get(
            "component=autoscaler-status", 0.0)
        assert after > before, "chaos injected no retried status write"

    @pytest.mark.usefixtures("lock_discipline")
    def test_seeded_chaos_round_under_lockcheck(self, lock_discipline):
        """One seeded chaos round with the @guarded_by contract
        enforced at runtime: reconcile through injected conflicts and
        transient write errors while the load signal swings; any write
        to declared shared state without the lock, or a lock-order
        inversion against the API store lock, fails at teardown."""
        from nos_tpu.testing.chaos import ChaosAPIServer
        from nos_tpu.testing.lockcheck import guard_state

        api = ChaosAPIServer(11, conflict_rate=0.3, transient_rate=0.1)
        h = Harness(api=api)
        guard_state(h.autoscaler, lock_discipline, name="autoscaler")
        loads = [0.0, 30.0, 75.0, 75.0, 20.0, 5.0, 90.0, 0.0]
        h.autoscaler.reconcile()
        for i, load in enumerate(loads):
            h.now[0] = float(i + 1)
            h.stamp(load)
            h.autoscaler.reconcile()
        assert 1 <= len(h.replicas()) <= h.svc.max_replicas


class TestLeaderHandoff:
    def test_standby_takes_over_the_reconcile_loop(self):
        """Two autoscaler mains on one substrate: the standby must not
        scale while blocked, and must take over after the leader
        releases the lease (the cmd/autoscaler wiring, with fast lease
        timings)."""
        from nos_tpu.cmd._runtime import Main
        from nos_tpu.kube.leaderelection import LeaderElector

        api = APIServer()
        svc = make_service(min_replicas=2)
        mains: list[Main] = []
        scalers = []
        for ident in ("a", "b"):
            autoscaler = ReplicaAutoscaler(api, [svc])
            scalers.append(autoscaler)
            m = Main(f"autoscaler-{ident}")
            m.attach_leader_election(LeaderElector(
                api, "nos-tpu-autoscaler-leader", identity=ident,
                lease_duration_s=0.6, renew_s=0.05, retry_s=0.05))
            m.add_loop("autoscaler", autoscaler.reconcile, 0.02)
            mains.append(m)
        try:
            mains[0].start()
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline and not api.list(
                    KIND_POD, namespace="serve"):
                time.sleep(0.01)
            assert len(api.list(KIND_POD, namespace="serve")) == 2
            mains[1].start()
            time.sleep(0.2)     # standby must stay gated
            assert not mains[1]._elector.is_leader.is_set()
            mains[0].shutdown()     # releases the lease
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline \
                    and not mains[1]._elector.is_leader.is_set():
                time.sleep(0.01)
            assert mains[1]._elector.is_leader.is_set(), \
                "standby never acquired the released lease"
            # the standby's loop now reconciles: scale-up lands
            for p in api.list(KIND_POD, namespace="serve"):
                api.patch(KIND_POD, p.metadata.name, "serve",
                          mutate=lambda q: q.metadata.annotations.
                          __setitem__(C.ANNOT_SERVING_LOAD, "40"))
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline and len(api.list(
                    KIND_POD, namespace="serve")) < 4:
                time.sleep(0.01)
            assert len(api.list(KIND_POD, namespace="serve")) >= 4
        finally:
            for m in mains:
                m.shutdown()
