"""Pipeline parallelism tests: the GPipe combinator must be EXACTLY
equivalent to running the stages sequentially, for any microbatch count,
and differentiable end to end (8 virtual CPU devices)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from nos_tpu.parallel.mesh import MeshSpec
from nos_tpu.parallel.pipeline import pipeline_apply, stack_stage_params


def make_pp_mesh(pp: int):
    import numpy as np
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:pp]), ("pp",))


def mlp_stage(params, x):
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    return h @ params["w2"] + params["b2"]


def init_stages(num_stages: int, width: int, key):
    stages = []
    for i in range(num_stages):
        k1, k2, key = jax.random.split(key, 3)
        stages.append({
            "w1": jax.random.normal(k1, (width, width)) / width ** 0.5,
            "b1": jnp.zeros(width),
            "w2": jax.random.normal(k2, (width, width)) / width ** 0.5,
            "b2": jnp.zeros(width),
        })
    return stack_stage_params(stages)


def sequential(stacked, x):
    num_stages = jax.tree_util.tree_leaves(stacked)[0].shape[0]
    for i in range(num_stages):
        params = jax.tree_util.tree_map(lambda p: p[i], stacked)
        x = mlp_stage(params, x)
    return x


class TestPipelineEquivalence:
    @pytest.mark.parametrize("pp,microbatches", [(2, 2), (2, 4), (4, 4),
                                                 (4, 8), (2, 1)])
    def test_matches_sequential(self, pp, microbatches):
        mesh = make_pp_mesh(pp)
        stacked = init_stages(pp, width=16, key=jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 16))
        want = sequential(stacked, x)
        got = pipeline_apply(mesh, mlp_stage, stacked, x,
                             num_microbatches=microbatches)
        assert jnp.max(jnp.abs(got - want)) < 1e-5

    def test_jit_and_grad_flow_through_every_stage(self):
        mesh = make_pp_mesh(4)
        stacked = init_stages(4, width=16, key=jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 16))

        @jax.jit
        def loss(stacked, x):
            y = pipeline_apply(mesh, mlp_stage, stacked, x,
                               num_microbatches=4)
            return jnp.sum(y ** 2)

        ref = jax.grad(lambda s: jnp.sum(sequential(s, x) ** 2))(stacked)
        got = jax.grad(loss)(stacked, x)
        for g, r in zip(jax.tree_util.tree_leaves(got),
                        jax.tree_util.tree_leaves(ref)):
            assert jnp.max(jnp.abs(g - r)) < 1e-4
            # every stage slice received gradient
            flat = g.reshape(g.shape[0], -1)
            assert bool(jnp.all(jnp.any(flat != 0, axis=1)))

    def test_indivisible_batch_rejected(self):
        mesh = make_pp_mesh(2)
        stacked = init_stages(2, width=8, key=jax.random.PRNGKey(0))
        x = jnp.zeros((6, 8))
        with pytest.raises(ValueError, match="divisible"):
            pipeline_apply(mesh, mlp_stage, stacked, x, num_microbatches=4)


class TestTransformerPipeline:
    """The pp story on real transformer blocks: 4 Llama blocks split
    into 2 stages of 2 layers each must reproduce the sequential
    forward exactly."""

    def test_llama_blocks_pipeline_matches_sequential(self):
        import dataclasses

        from nos_tpu.models.llama import Block, TINY, rope_tables

        cfg = dataclasses.replace(TINY, remat=False, num_layers=4)
        block = Block(cfg)
        bsz, seq = 2, 32
        x = jax.random.normal(jax.random.PRNGKey(0),
                              (bsz, seq, cfg.hidden_size), jnp.float32)
        # batch-1 rope broadcasts over any microbatch size inside stages
        positions = jnp.arange(seq, dtype=jnp.int32)[None]
        rope = rope_tables(positions, cfg.head_dim, cfg.rope_theta)

        keys = jax.random.split(jax.random.PRNGKey(1), 4)
        layer_params = [block.init(k, x, rope)["params"] for k in keys]

        # two stages of two layers: stage params are stacked per stage
        def stage_fn(params, act):
            for i in range(2):
                layer = jax.tree_util.tree_map(lambda p: p[i], params)
                act = block.apply({"params": layer}, act, rope)
            return act

        stages = [
            stack_stage_params(layer_params[0:2]),
            stack_stage_params(layer_params[2:4]),
        ]
        stacked = stack_stage_params(stages)

        want = x
        for p in layer_params:
            want = block.apply({"params": p}, want, rope)

        mesh = make_pp_mesh(2)
        got = pipeline_apply(mesh, stage_fn, stacked, x,
                             num_microbatches=2)
        assert jnp.max(jnp.abs(got - want)) < 2e-5
