"""Unit tests for the partitioning engine core.

Mirrors reference internal/partitioning/core/{planner,tracker,snapshot}_test.go
coverage: snapshot fork/commit/revert, lacking-slice math, tracker
bookkeeping, and planner behavior against fake v5e nodes (the SURVEY.md §7
step-3 milestone gate).
"""

import pytest

from nos_tpu.api import constants as C
from nos_tpu.kube.client import APIServer, KIND_NODE
from nos_tpu.partitioning.core import (
    ClusterSnapshot, GeometryActuator, GeometryPlanner, QuarantineList,
    REASON_ACTUATION, SliceTracker, SnapshotError,
)
from nos_tpu.partitioning.slicepart import (
    SliceNodeInitializer, SlicePartitionCalculator, SlicePartitioner,
    SliceProfileCalculator, SliceProfileFilter, SliceSnapshotTaker,
    is_node_initialized,
)
from nos_tpu.partitioning.state import (
    ClusterState, NodePartitioning, PartitioningState, UnitPartitioning,
)
from nos_tpu.scheduler.framework import Framework
from nos_tpu.testing.factory import make_slice_pod, make_tpu_node
from nos_tpu.topology.annotations import parse_spec_annotations


def snapshot_for(nodes):
    state = ClusterState()
    for n in nodes:
        state.update_node(n, [])
    return SliceSnapshotTaker().take_snapshot(state), state


def virgin_v5e(name="n1", **kw):
    return make_tpu_node(name, status_geometry={"free": {"2x4": 1}}, **kw)


class TestSnapshot:
    def test_fork_commit_revert(self):
        snap, _ = snapshot_for([virgin_v5e()])
        snap.fork()
        # write access goes through get_node_for_write: the COW fork
        # clones the node lazily on this first mutation
        assert snap.get_node_for_write("n1").update_geometry_for({"2x2": 2})
        snap.revert()
        geo = snap.get_node("n1").geometries()
        assert geo == {0: {"2x4": 1}}
        snap.fork()
        snap.get_node_for_write("n1").update_geometry_for({"2x2": 2})
        snap.commit()
        assert snap.get_node("n1").geometries() == {0: {"2x2": 2}}

    def test_fork_is_lazy(self):
        # the tentpole contract: fork() copies nothing up front
        snap, _ = snapshot_for([virgin_v5e("a"), virgin_v5e("b")])
        snap.fork()
        snap.commit()
        assert snap.cow_clones == 0
        snap.fork()
        snap.get_node_for_write("a").update_geometry_for({"2x2": 2})
        snap.get_node_for_write("a")        # same fork: no second clone
        snap.revert()
        assert snap.cow_clones == 1
        assert snap.get_node("a").geometries() == {0: {"2x4": 1}}

    def test_double_fork_rejected(self):
        snap, _ = snapshot_for([virgin_v5e()])
        snap.fork()
        with pytest.raises(SnapshotError):
            snap.fork()

    def test_lacking_slices(self):
        snap, _ = snapshot_for([virgin_v5e()])
        pod = make_slice_pod("2x2", 2)
        # node only advertises one free 2x4 -> lacking two 2x2
        assert snap.get_lacking_slices(pod) == {"2x2": 2}
        pod2 = make_slice_pod("2x4", 1)
        assert snap.get_lacking_slices(pod2) == {}

    def test_candidate_nodes_sorted_with_free_capacity(self):
        snap, _ = snapshot_for([virgin_v5e("b"), virgin_v5e("a")])
        names = [n.name for n in snap.get_candidate_nodes()]
        assert names == ["a", "b"]


class TestTracker:
    def test_tracks_and_removes(self):
        snap, _ = snapshot_for([virgin_v5e()])
        pods = [make_slice_pod("2x2", 2, name="p1"),
                make_slice_pod("1x1", 1, name="p2")]
        tracker = SliceTracker(snap, SliceProfileCalculator(), pods)
        assert tracker.lacking == {"2x2": 2, "1x1": 1}
        assert not tracker.empty
        tracker.remove(pods[0])
        assert tracker.lacking == {"1x1": 1}
        tracker.remove(pods[1])
        assert tracker.empty

    def test_non_tpu_pods_ignored(self):
        snap, _ = snapshot_for([virgin_v5e()])
        from nos_tpu.testing.factory import make_pod
        tracker = SliceTracker(
            snap, SliceProfileCalculator(), [make_pod(resources={"cpu": 1})]
        )
        assert tracker.empty


def make_planner():
    return GeometryPlanner(
        framework=Framework(),
        calculator=SliceProfileCalculator(),
        partition_calculator=SlicePartitionCalculator(),
    )


class TestPlanner:
    def test_recarve_for_pending_pod(self):
        snap, _ = snapshot_for([virgin_v5e()])
        pods = [make_slice_pod("2x2", 1, name="p1")]
        state = make_planner().plan(snap, pods)
        resources = state["n1"].units[0].resources
        assert resources.get("nos.tpu/slice-2x2", 0) >= 1

    def test_no_pending_no_change(self):
        snap, _ = snapshot_for([virgin_v5e()])
        state = make_planner().plan(snap, [])
        assert state["n1"].units[0].resources == {"nos.tpu/slice-2x4": 1}

    def test_plan_packs_multiple_pods_one_node(self):
        snap, _ = snapshot_for([virgin_v5e()])
        pods = [make_slice_pod("2x2", 1, name=f"p{i}") for i in range(2)]
        state = make_planner().plan(snap, pods)
        assert state["n1"].units[0].resources == {"nos.tpu/slice-2x2": 2}

    def test_plan_spreads_over_nodes_when_needed(self):
        snap, _ = snapshot_for([virgin_v5e("n1"), virgin_v5e("n2")])
        pods = [make_slice_pod("2x4", 1, name=f"p{i}", priority=10 - i)
                for i in range(2)]
        # both nodes already offer 2x4; no geometry change needed, no lack
        state = make_planner().plan(snap, pods)
        assert state["n1"].units[0].resources == {"nos.tpu/slice-2x4": 1}
        assert state["n2"].units[0].resources == {"nos.tpu/slice-2x4": 1}

    def test_mixed_profiles_carved_on_one_host(self):
        snap, _ = snapshot_for([virgin_v5e()])
        pods = [make_slice_pod("2x2", 1, name="big"),
                make_slice_pod("1x1", 4, name="small")]
        state = make_planner().plan(snap, pods)
        res = state["n1"].units[0].resources
        assert res.get("nos.tpu/slice-2x2") == 1
        assert res.get("nos.tpu/slice-1x1") == 4

    def test_unsatisfiable_keeps_geometry(self):
        snap, _ = snapshot_for([virgin_v5e()])
        pods = [make_slice_pod("4x4", 1, name="toolarge")]
        state = make_planner().plan(snap, pods)
        assert state["n1"].units[0].resources == {"nos.tpu/slice-2x4": 1}

    def test_priority_order_wins_contention(self):
        # one host (8 chips), three pods each lacking a 2x2 — only two fit;
        # the higher-priority pods must win (reference core/util.go:34-71)
        snap, _ = snapshot_for([virgin_v5e()])
        pods = [make_slice_pod("2x2", 1, name="lo", priority=1),
                make_slice_pod("2x2", 1, name="hi", priority=100),
                make_slice_pod("2x2", 1, name="mid", priority=50)]
        state = make_planner().plan(snap, pods)
        node = snap.get_node("n1")
        placed = {p.metadata.name for p in node.node_info().pods}
        assert placed == {"hi", "mid"}
        assert state["n1"].units[0].resources == {"nos.tpu/slice-2x2": 2}

    def test_pods_lacking_nothing_are_not_planned(self):
        # the node already advertises the needed profile: the planner leaves
        # placement to the scheduler (tracker empty -> unchanged state)
        snap, _ = snapshot_for([virgin_v5e()])
        state = make_planner().plan(snap, [make_slice_pod("2x4", 1)])
        assert state["n1"].units[0].resources == {"nos.tpu/slice-2x4": 1}
        assert snap.get_node("n1").node_info().pods == []


class TestReviewRegressions:
    def test_later_candidate_recarves_after_earlier_revert(self):
        # review regression: revert() swaps snapshot node objects; the
        # planner must re-fetch candidates by name or later re-carves are
        # lost on detached objects
        n1 = make_tpu_node("n1", status_geometry={
            "used": {"1x1": 7}, "free": {"1x1": 1}})
        n2 = make_tpu_node("n2", status_geometry={"free": {"2x4": 1}})
        snap, _ = snapshot_for([n1, n2])
        desired = make_planner().plan(snap, [make_slice_pod("2x2", 1)])
        assert desired["n2"].units[0].resources.get("nos.tpu/slice-2x2", 0) >= 1

    def test_snapshot_does_not_mutate_cluster_state(self):
        # review regression: SliceNode syncs allocatable on construction;
        # that must happen on deep copies, not the live ClusterState node
        node = make_tpu_node("n1", status_geometry={"free": {"2x4": 1}})
        node.status.allocatable["nos.tpu/slice-2x2"] = 2.0
        state = ClusterState()
        state.update_node(node, [])
        SliceSnapshotTaker().take_snapshot(state)
        assert state.nodes()["n1"].status.allocatable.get(
            "nos.tpu/slice-2x2") == 2.0

    def test_completed_pods_do_not_consume_capacity(self):
        # review regression: NodeController must drop Succeeded/Failed pods
        from nos_tpu.controllers.node_controller import NodeController
        from nos_tpu.kube.client import KIND_POD
        from nos_tpu.kube.objects import SUCCEEDED
        from nos_tpu.testing.factory import make_pod
        api = APIServer()
        state = ClusterState()
        node = make_tpu_node("n1", status_geometry={"free": {"2x4": 1}})
        api.create(KIND_NODE, node)
        dead = make_pod(name="done", resources={"nos.tpu/slice-2x4": 1},
                        node_name="n1", phase=SUCCEEDED)
        api.create(KIND_POD, dead)
        NodeController(api, state).reconcile("MODIFIED", node)
        ni = state.node_infos()["n1"]
        assert ni.requested.get("nos.tpu/slice-2x4", 0) == 0

    def test_hybrid_nodes_enable_slice_partitioning(self):
        state = ClusterState()
        state.update_node(make_tpu_node("h", partitioning="hybrid"), [])
        assert state.is_partitioning_enabled("slice")
        assert state.is_partitioning_enabled("timeshare")


class TestActuatorAndPartitioner:
    def setup_method(self):
        self.api = APIServer()
        self.node = virgin_v5e("n1")
        self.api.create(KIND_NODE, self.node)
        self.partitioner = SlicePartitioner(self.api)
        self.actuator = GeometryActuator(
            self.partitioner, SlicePartitionCalculator()
        )

    def test_apply_writes_spec_annotations(self):
        snap, _ = snapshot_for([self.node])
        desired = PartitioningState({
            "n1": NodePartitioning(units=[
                UnitPartitioning(0, {"nos.tpu/slice-2x2": 2})
            ])
        })
        assert self.actuator.apply(snap, desired)
        node = self.api.get(KIND_NODE, "n1")
        parsed = parse_spec_annotations(node.metadata.annotations)
        assert [(a.index, a.profile, a.quantity) for a in parsed] == [(0, "2x2", 2)]
        assert node.metadata.annotations[C.spec_plan_annotation("slice")]

    def test_apply_skips_when_equal(self):
        snap, _ = snapshot_for([self.node])
        desired = PartitioningState({
            "n1": NodePartitioning(units=[
                UnitPartitioning(0, {"nos.tpu/slice-2x4": 1})
            ])
        })
        assert not self.actuator.apply(snap, desired)
        node = self.api.get(KIND_NODE, "n1")
        assert C.spec_plan_annotation("slice") not in node.metadata.annotations

    def test_apply_skips_empty(self):
        snap, _ = snapshot_for([self.node])
        assert not self.actuator.apply(snap, PartitioningState())


class _FailingForNode:
    """Partitioner stub failing every apply for one node."""

    def __init__(self, inner, bad_node):
        self.inner = inner
        self.bad_node = bad_node
        self.failures = 0

    def apply_partitioning(self, node_name, plan_id, partitioning):
        if node_name == self.bad_node:
            self.failures += 1
            raise RuntimeError("injected: apply rejected")
        self.inner.apply_partitioning(node_name, plan_id, partitioning)


class TestActuatorFailureIsolation:
    """Regression: one node's apply_partitioning raising used to abort
    the remaining nodes of the plan."""

    def _desired(self, names):
        return PartitioningState({
            n: NodePartitioning(units=[
                UnitPartitioning(0, {"nos.tpu/slice-2x2": 2})
            ]) for n in names
        })

    def test_one_failing_node_does_not_abort_the_rest(self):
        api = APIServer()
        nodes = [virgin_v5e("bad"), virgin_v5e("good")]
        for n in nodes:
            api.create(KIND_NODE, n)
        quarantine = QuarantineList(kind="slice")
        actuator = GeometryActuator(
            _FailingForNode(SlicePartitioner(api), "bad"),
            SlicePartitionCalculator(), quarantine=quarantine)
        snap, _ = snapshot_for(nodes)

        assert actuator.apply(snap, self._desired(["bad", "good"]))
        good = api.get(KIND_NODE, "good")
        parsed = parse_spec_annotations(good.metadata.annotations)
        assert [(a.profile, a.quantity) for a in parsed] == [("2x2", 2)]
        bad = api.get(KIND_NODE, "bad")
        assert not parse_spec_annotations(bad.metadata.annotations)
        assert not quarantine.is_quarantined("bad")  # streak 1 of 3

    def test_failure_streak_opens_the_breaker(self):
        api = APIServer()
        nodes = [virgin_v5e("bad"), virgin_v5e("good")]
        for n in nodes:
            api.create(KIND_NODE, n)
        quarantine = QuarantineList(kind="slice", failure_threshold=3)
        failing = _FailingForNode(SlicePartitioner(api), "bad")
        actuator = GeometryActuator(
            failing, SlicePartitionCalculator(), quarantine=quarantine)
        for _ in range(3):
            snap, _ = snapshot_for(nodes)
            actuator.apply(snap, self._desired(["bad"]))
        assert failing.failures == 3
        assert quarantine.is_quarantined("bad")
        assert quarantine.reason("bad") == REASON_ACTUATION

        # a later success (after the controller's half-open probe put
        # the node back in the snapshot) closes the breaker
        failing.bad_node = "nobody"
        snap, _ = snapshot_for(nodes)
        assert actuator.apply(snap, self._desired(["bad"]))
        assert not quarantine.is_quarantined("bad")

    def test_half_open_probe_reopens_on_first_failure(self):
        """A failure inside the probe window re-opens the breaker at
        once: a permanently failing node gets ONE doomed plan cycle per
        cool-down, not threshold-many.  Outside the window the
        N-consecutive contract is back in force."""
        now = [0.0]
        quarantine = QuarantineList(kind="slice", failure_threshold=3,
                                    clock=lambda: now[0])
        for _ in range(3):
            quarantine.record_failure("bad")
        assert quarantine.is_quarantined("bad")
        assert quarantine.release_for_probe("bad", window_s=10.0)
        assert not quarantine.is_quarantined("bad")
        now[0] += 5.0
        quarantine.record_failure("bad")        # failed probe, in window
        assert quarantine.is_quarantined("bad")

        # a success during the probe clears everything
        assert quarantine.release_for_probe("bad", window_s=10.0)
        quarantine.record_success("bad")
        assert quarantine.record_failure("bad") == 1

        # an EXPIRED probe window must not turn one isolated failure
        # weeks later into an instant quarantine
        quarantine.record_failure("bad")
        quarantine.record_failure("bad")
        assert quarantine.is_quarantined("bad")
        assert quarantine.release_for_probe("bad", window_s=10.0)
        now[0] += 100.0
        assert quarantine.record_failure("bad") == 1
        assert not quarantine.is_quarantined("bad")


class TestInitializer:
    def test_init_virgin_node(self):
        api = APIServer()
        node = make_tpu_node("n1")          # no status annotations at all
        api.create(KIND_NODE, node)
        assert not is_node_initialized(node)
        SliceNodeInitializer(api).init_node_partitioning("n1")
        node = api.get(KIND_NODE, "n1")
        assert is_node_initialized(node)
        parsed = parse_spec_annotations(node.metadata.annotations)
        assert [(a.index, a.profile, a.quantity) for a in parsed] == [(0, "2x4", 1)]


class TestPartitioningState:
    def test_order_insensitive_equality(self):
        a = PartitioningState({
            "n1": NodePartitioning(units=[
                UnitPartitioning(0, {"r": 1}), UnitPartitioning(1, {"s": 2}),
            ])
        })
        b = PartitioningState({
            "n1": NodePartitioning(units=[
                UnitPartitioning(1, {"s": 2}), UnitPartitioning(0, {"r": 1}),
            ])
        })
        assert a.equal(b)
        b["n1"].units[0].resources["s"] = 3
        assert not a.equal(b)
