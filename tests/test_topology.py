"""Unit tests for the TPU topology domain model.

Mirrors the coverage of reference pkg/gpu/mig/gpu_test.go (geometry algebra),
profile_test.go, annotation tests, and slicing/gpu_test.go (timeshare),
table-driven where the reference is.
"""

import pytest

from nos_tpu.api import constants as C
from nos_tpu.topology import (
    DEFAULT_REGISTRY, Shape, SliceUnit, TimeshareUnit, V4, V5E,
    annotations as ann, enumerate_tilings, extend, feasible,
    fewest_slices_geometry, named_geometry, pack, profile,
)
from nos_tpu.topology.errors import InvalidGeometryError


# ---------------------------------------------------------------------------
# Shape
# ---------------------------------------------------------------------------

class TestShape:
    def test_parse_and_chips(self):
        s = Shape.parse("2x4")
        assert s.dims == (2, 4)
        assert s.chips == 8
        assert s.name == "2x4"
        assert Shape.parse("2x2x4").chips == 16

    def test_ordering_smaller_first(self):
        shapes = [Shape.parse(x) for x in ["2x4", "1x1", "2x2", "1x2"]]
        assert [s.name for s in sorted(shapes)] == ["1x1", "1x2", "2x2", "2x4"]

    def test_canonical(self):
        assert Shape((4, 2)).canonical().name == "2x4"

    def test_fits_in_any_orientation(self):
        assert Shape.parse("1x2").fits_in(Shape.parse("2x1"))
        assert Shape.parse("2x2").fits_in(Shape.parse("2x4"))
        assert not Shape.parse("4x4").fits_in(Shape.parse("2x4"))

    def test_invalid(self):
        with pytest.raises(ValueError):
            Shape.parse("2xh")
        with pytest.raises(ValueError):
            Shape((0, 2))


# ---------------------------------------------------------------------------
# Known topologies
# ---------------------------------------------------------------------------

class TestGenerations:
    def test_v5e_parameters(self):
        assert V5E.chips_per_host == 8
        assert V5E.hbm_gb_per_chip == 16
        assert {s.name for s in V5E.subhost_shapes()} == {"1x1", "1x2", "2x2", "2x4"}
        assert Shape.parse("4x4") in V5E.multihost_shapes()

    def test_v6e_parameters(self):
        from nos_tpu.topology import V6E

        assert V6E.chips_per_host == 4
        assert V6E.hbm_gb_per_chip == 32
        assert {s.name for s in V6E.subhost_shapes()} == {"1x1", "1x2", "2x2"}
        assert Shape.parse("2x4") in V6E.multihost_shapes()
        assert V6E.hosts_for(Shape.parse("2x4")) == 2
        assert V6E.hosts_for(Shape.parse("16x16")) == 64
        assert V6E.host_grid(Shape.parse("16x16")).dims == (8, 8)
        # the derived geometry table exists and is non-trivial
        unit = SliceUnit(generation=V6E)
        assert len(unit.allowed_geometries()) >= 3

    def test_hosts_for(self):
        assert V5E.hosts_for(Shape.parse("2x2")) == 1
        assert V5E.hosts_for(Shape.parse("4x4")) == 2
        assert V5E.hosts_for(Shape.parse("8x8")) == 8
        assert V5E.hosts_for(Shape.parse("16x16")) == 32
        assert V4.hosts_for(Shape.parse("2x2x4")) == 4

    def test_host_grid(self):
        assert V5E.host_grid(Shape.parse("8x8")).dims == (4, 2)
        assert V5E.host_grid(Shape.parse("16x16")).dims == (8, 4)
        with pytest.raises(ValueError):
            V5E.host_grid(Shape.parse("3x5"))

    def test_registry_lookup(self):
        assert DEFAULT_REGISTRY.get("tpu-v5e") is V5E
        with pytest.raises(KeyError):
            DEFAULT_REGISTRY.get("tpu-v9")


# ---------------------------------------------------------------------------
# Packing
# ---------------------------------------------------------------------------

class TestPacking:
    def test_exact_tiling_v5e_host(self):
        block = V5E.host_block
        assert pack(block, {Shape.parse("2x2"): 2}, require_full=True) is not None
        assert pack(block, {Shape.parse("1x1"): 8}, require_full=True) is not None
        assert pack(block, {Shape.parse("2x4"): 1}, require_full=True) is not None
        mixed = {Shape.parse("2x2"): 1, Shape.parse("1x2"): 2}
        assert pack(block, mixed, require_full=True) is not None

    def test_infeasible(self):
        block = V5E.host_block
        assert pack(block, {Shape.parse("2x2"): 3}) is None          # 12 > 8 chips
        assert not feasible(block, {Shape.parse("2x4"): 2})

    def test_partial_pack(self):
        block = V5E.host_block
        res = pack(block, {Shape.parse("2x2"): 1})
        assert res is not None and len(res) == 1

    def test_extend_around_used(self):
        block = V5E.host_block
        fixed = pack(block, {Shape.parse("2x2"): 1})
        assert fixed is not None
        more = extend(block, fixed, {Shape.parse("2x2"): 1, Shape.parse("1x1"): 0})
        assert more is not None and len(more) == 1
        assert extend(block, fixed, {Shape.parse("2x4"): 1}) is None

    def test_enumerate_tilings_derived_table(self):
        tilings = enumerate_tilings(
            V5E.host_block, tuple(V5E.subhost_shapes())
        )
        as_named = [dict((s.name, c) for s, c in t) for t in tilings]
        assert {"2x4": 1} in as_named
        assert {"2x2": 2} in as_named
        assert {"1x1": 8} in as_named
        assert {"2x2": 1, "1x2": 2} in as_named
        # every tiling covers exactly 8 chips
        for t in tilings:
            assert sum(Shape(s.dims).chips * c for s, c in t) == 8

    def test_enumerate_tilings_v4_host(self):
        tilings = enumerate_tilings(V4.host_block, tuple(V4.subhost_shapes()))
        as_named = [dict((s.name, c) for s, c in t) for t in tilings]
        assert {"1x2x2": 1} in as_named
        assert {"1x1x1": 4} in as_named


# ---------------------------------------------------------------------------
# SliceUnit geometry state machine
# ---------------------------------------------------------------------------

class TestSliceUnit:
    def unit(self):
        return SliceUnit(generation=V5E)

    def test_init_geometry_is_fewest_slices(self):
        u = self.unit()
        u.init_geometry()
        assert u.geometry_names() == {"2x4": 1}

    def test_apply_and_allocate(self):
        u = self.unit()
        u.apply_geometry({Shape.parse("2x2"): 2})
        assert u.free_names() == {"2x2": 2}
        assert u.allocate(Shape.parse("2x2"))
        assert u.used_names() == {"2x2": 1}
        assert not u.allocate(Shape.parse("1x1"))

    def test_cannot_delete_used(self):
        u = self.unit()
        u.apply_geometry({Shape.parse("2x2"): 2})
        u.allocate(Shape.parse("2x2"))
        with pytest.raises(InvalidGeometryError):
            u.apply_geometry({Shape.parse("2x4"): 1})
        # but refining the free half is fine
        u.apply_geometry({Shape.parse("2x2"): 1, Shape.parse("1x1"): 4})
        assert u.used_names() == {"2x2": 1}
        assert u.free_names() == {"1x1": 4}

    def test_update_geometry_for_lacking(self):
        u = self.unit()
        u.init_geometry()                      # one 2x4, nothing used
        changed = u.update_geometry_for({Shape.parse("2x2"): 2})
        assert changed
        assert u.free_names() == {"2x2": 2}

    def test_update_geometry_respects_used(self):
        u = self.unit()
        u.apply_geometry({Shape.parse("2x2"): 2})
        u.allocate(Shape.parse("2x2"))
        changed = u.update_geometry_for({Shape.parse("1x1"): 4})
        assert changed
        assert u.used_names() == {"2x2": 1}
        assert u.free_names() == {"1x1": 4}

    def test_update_noop_when_no_improvement(self):
        u = self.unit()
        u.apply_geometry({Shape.parse("2x2"): 2})
        assert not u.update_geometry_for({Shape.parse("2x2"): 1})

    def test_non_canonical_shapes_are_canonicalised(self):
        # review regression: apply/allocate/profile paths must canonicalise
        u = self.unit()
        u.apply_geometry({Shape((4, 2)): 1})
        assert u.allocate(Shape.parse("2x4"))
        assert u.used_names() == {"2x4": 1}
        assert profile.slice_resource_name(Shape((4, 2))) == "nos.tpu/slice-2x4"
        assert profile.extract_slice_requests({"nos.tpu/slice-4x2": 1}) == {
            Shape.parse("2x4"): 1
        }

    def test_fewest_slices_helper(self):
        best = fewest_slices_geometry([{"1x1": 8}, {"2x4": 1}, {"2x2": 2}])
        assert best == {"2x4": 1}


# ---------------------------------------------------------------------------
# TimeshareUnit
# ---------------------------------------------------------------------------

class TestTimeshareUnit:
    def test_create_from_spare(self):
        u = TimeshareUnit(hbm_gb=16)
        assert u.update_geometry_for({8: 2})
        assert u.free_names() == {"8gb": 2}
        assert u.spare_gb == 0

    def test_sacrifice_free_and_restore(self):
        u = TimeshareUnit(hbm_gb=16)
        u.update_geometry_for({16: 1})
        assert u.free_names() == {"16gb": 1}
        # need two 8gb: must sacrifice the free 16gb
        assert u.update_geometry_for({8: 2})
        assert u.free_names() == {"8gb": 2}

    def test_used_never_sacrificed(self):
        u = TimeshareUnit(hbm_gb=16)
        u.update_geometry_for({8: 1})
        u.allocate(8)
        assert u.update_geometry_for({4: 2})
        assert u.used_names() == {"8gb": 1}
        assert u.free_names() == {"4gb": 2}
        # free slices may be sacrificed for new requests...
        assert u.update_geometry_for({8: 1})
        assert u.free_names() == {"8gb": 1}
        assert u.used_names() == {"8gb": 1}
        # ...but a request exceeding hbm minus used capacity cannot be met
        assert not u.update_geometry_for({16: 1})
        assert u.used_names() == {"8gb": 1}

    def test_apply_geometry_bounds(self):
        u = TimeshareUnit(hbm_gb=16)
        with pytest.raises(ValueError):
            u.apply_geometry({16: 2})

    def test_no_oscillating_sacrifice(self):
        # review regression: a sacrifice plan that lowers overall lacking
        # satisfaction must be rejected, else reconciles flip-flop forever
        u = TimeshareUnit(hbm_gb=16)
        u.free = {8: 2}
        assert not u.update_geometry_for({8: 2, 16: 1})
        assert u.free_names() == {"8gb": 2}


# ---------------------------------------------------------------------------
# Profiles / resource names
# ---------------------------------------------------------------------------

class TestProfiles:
    def test_slice_roundtrip(self):
        name = profile.slice_resource_name(Shape.parse("2x2"))
        assert name == "nos.tpu/slice-2x2"
        assert profile.shape_from_resource(name) == Shape.parse("2x2")
        assert profile.shape_from_resource("nvidia.com/mig-1g.5gb") is None

    def test_timeshare_roundtrip(self):
        name = profile.timeshare_resource_name(8)
        assert name == "nos.tpu/tpu-8gb"
        assert profile.gb_from_resource(name) == 8
        assert profile.gb_from_resource("nos.tpu/slice-2x2") is None

    def test_extract_requests(self):
        req = {"cpu": 1.0, "nos.tpu/slice-2x2": 2, "nos.tpu/tpu-8gb": 1}
        assert profile.extract_slice_requests(req) == {Shape.parse("2x2"): 2}
        assert profile.extract_timeshare_requests(req) == {8: 1}


# ---------------------------------------------------------------------------
# Annotation codec
# ---------------------------------------------------------------------------

class TestAnnotations:
    def test_spec_roundtrip(self):
        annots = ann.spec_from_geometries({0: {"2x2": 2}, 1: {"8gb": 3}})
        assert annots == {
            "nos.tpu/spec-tpu-0-2x2": "2",
            "nos.tpu/spec-tpu-1-8gb": "3",
        }
        parsed = ann.parse_spec_annotations(annots)
        assert [(a.index, a.profile, a.quantity) for a in parsed] == [
            (0, "2x2", 2), (1, "8gb", 3),
        ]

    def test_status_from_units(self):
        u = SliceUnit(generation=V5E)
        u.apply_geometry({Shape.parse("2x2"): 2})
        u.allocate(Shape.parse("2x2"))
        annots = ann.status_from_units([u])
        assert annots == {
            "nos.tpu/status-tpu-0-2x2-used": "1",
            "nos.tpu/status-tpu-0-2x2-free": "1",
        }

    def test_corrupt_and_zero_annotations(self):
        # review regressions: corrupt values are skipped; zero-quantity spec
        # entries do not block convergence
        assert ann.parse_spec_annotations({"nos.tpu/spec-tpu-0-2x2": "banana"}) == []
        assert ann.spec_matches_status({"nos.tpu/spec-tpu-0-2x2": "0"})

    def test_spec_matches_status(self):
        annots = {
            "nos.tpu/spec-tpu-0-2x2": "2",
            "nos.tpu/status-tpu-0-2x2-used": "1",
            "nos.tpu/status-tpu-0-2x2-free": "1",
        }
        assert ann.spec_matches_status(annots)
        annots["nos.tpu/spec-tpu-0-2x2"] = "1"
        assert not ann.spec_matches_status(annots)
        assert ann.spec_matches_status({})

    def test_ignores_foreign_annotations(self):
        annots = {"foo/bar": "1", C.spec_plan_annotation("slice"): "abc"}
        assert ann.parse_spec_annotations(annots) == []
        assert ann.spec_plan_id(annots) == "abc"
