"""SLO telemetry plane: sampler windows, burn-rate engine, journal
transitions, surfaces (/debug/slo, obs slo / obs top), and the
acceptance chain — an injected latency regression flips a journaled
SLO_BREACH that the CLI links to the breaching class and its rejecting
plugin.
"""

from __future__ import annotations

import json

import pytest

from nos_tpu import obs
from nos_tpu.exporter.metrics import Registry
from nos_tpu.obs import journal as J
from nos_tpu.obs.__main__ import main as obs_main
from nos_tpu.obs.journal import DecisionJournal
from nos_tpu.obs.slo import (
    GAUGE_FLOOR, LATENCY, RATE_CEILING, SLOEngine, SLOObjective,
)
from nos_tpu.obs.timeseries import TimeSeriesSampler
from nos_tpu.obs.trace import RingExporter, Tracer


class Clock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t


def make_engine(reg: Registry, clock: Clock,
                objectives: list[SLOObjective],
                fast: float = 10.0, slow: float = 30.0,
                threshold: float = 2.0) -> SLOEngine:
    return SLOEngine(TimeSeriesSampler(registry=reg, clock=clock),
                     objectives, fast_window_s=fast, slow_window_s=slow,
                     burn_threshold=threshold, clock=clock)


LAT = "nos_tpu_schedule_latency_seconds"


def latency_objective(**kw) -> SLOObjective:
    defaults = dict(name="lat", kind=LATENCY, metric=LAT, target=0.1,
                    each_label="class", compliance=0.9)
    defaults.update(kw)
    return SLOObjective(**defaults)


# ---------------------------------------------------------------------------
# sampler
# ---------------------------------------------------------------------------

class TestSampler:
    def test_bounded_with_eviction_counter(self):
        clock = Clock()
        reg = Registry()
        sampler = TimeSeriesSampler(registry=reg, maxlen=3, clock=clock)
        for i in range(7):
            clock.t += 1.0
            sampler.tick()
        assert len(sampler) == 3
        pts = sampler.points()
        assert [p.ts for p in pts] == [5.0, 6.0, 7.0]
        snap = reg.snapshot()
        assert snap["nos_tpu_timeseries_points_dropped_total"][""] == 4

    def test_tick_rolls_the_max_window(self):
        clock = Clock()
        reg = Registry()
        sampler = TimeSeriesSampler(registry=reg, clock=clock)
        reg.observe("nos_t_seconds", 9.0)
        clock.t = 1.0
        first = sampler.tick()
        assert first.get("nos_t_seconds_max") == 9.0
        clock.t = 2.0
        second = sampler.tick()
        assert second.get("nos_t_seconds_max") == 0.0

    def test_bracket_requires_full_window_coverage(self):
        """Cold-start rule: a half-filled window is 'not yet
        observable', never a verdict."""
        clock = Clock()
        sampler = TimeSeriesSampler(registry=Registry(), clock=clock)
        assert sampler.bracket(5.0) is None
        for t in (1.0, 2.0, 3.0):
            clock.t = t
            sampler.tick()
        assert sampler.bracket(5.0) is None     # only 2 s covered
        clock.t = 7.0
        sampler.tick()
        start, end = sampler.bracket(5.0)
        assert (start.ts, end.ts) == (2.0, 7.0)

    def test_bracket_picks_newest_point_at_or_before_cutoff(self):
        clock = Clock()
        sampler = TimeSeriesSampler(registry=Registry(), clock=clock)
        for t in (1.0, 2.0, 3.0, 10.0):
            clock.t = t
            sampler.tick()
        start, end = sampler.bracket(8.0)
        assert (start.ts, end.ts) == (2.0, 10.0)


# ---------------------------------------------------------------------------
# engine verdicts
# ---------------------------------------------------------------------------

class TestEngineLatency:
    def _drive(self, engine: SLOEngine, reg: Registry, clock: Clock,
               ticks: int, latency: float, cls: str = "serve") -> list:
        verdicts = []
        for _ in range(ticks):
            clock.t += 1.0
            reg.observe(LAT, latency, labels={"class": cls})
            verdicts = engine.tick()
        return verdicts

    def test_breach_and_recovery_journal_with_class_and_trace(self):
        clock = Clock()
        reg = Registry()
        engine = make_engine(reg, clock, [latency_objective()])
        journal = DecisionJournal(maxlen=64, clock=clock)
        tracer = Tracer(clock=clock, ring=RingExporter(maxlen=64))
        with obs.scoped(tracer, journal):
            self._drive(engine, reg, clock, 40, 0.01)
            assert not [r for r in journal.events()
                        if r.category == J.SLO_BREACH]
            v = self._drive(engine, reg, clock, 40, 5.0)
            assert [x["breached"] for x in v] == [True]
            v = self._drive(engine, reg, clock, 80, 0.01)
            assert [x["breached"] for x in v] == [False]
        transitions = [r for r in journal.events()
                       if r.category in (J.SLO_BREACH, J.SLO_RECOVERED)]
        assert [r.category for r in transitions] == \
            [J.SLO_BREACH, J.SLO_RECOVERED]
        breach = transitions[0]
        assert breach.subject == "lat/serve"
        assert breach.attrs["slo_class"] == "serve"
        assert breach.attrs["burn_slow"] >= 2.0
        assert breach.attrs["budget_remaining"] < 0
        # the ambient slo.evaluate span linked the record into a trace
        assert breach.trace_id
        spans = {s["name"] for s in tracer.ring.dump()}
        assert "slo.evaluate" in spans

    def test_fast_burst_alone_does_not_breach(self):
        """Multi-window rule: a burst that burns the fast window but is
        invisible at the slow window's scale is not a breach."""
        clock = Clock()
        reg = Registry()
        engine = make_engine(reg, clock, [latency_objective()],
                             fast=5.0, slow=200.0)
        self._drive(engine, reg, clock, 210, 0.01)
        v = self._drive(engine, reg, clock, 5, 5.0)
        [verdict] = v
        assert verdict["burn_fast"] >= 2.0
        assert verdict["burn_slow"] < 2.0
        assert not verdict["breached"]

    def test_min_events_guards_low_traffic_classes(self):
        clock = Clock()
        reg = Registry()
        engine = make_engine(
            reg, clock, [latency_objective(min_events=10)])
        # 2 slow events in the whole window: 100% bad, but unjudgeable
        verdicts = []
        for i in range(40):
            clock.t += 1.0
            if i in (20, 21):
                reg.observe(LAT, 9.0, labels={"class": "rare"})
            verdicts = engine.tick()
        [v] = verdicts
        assert v["burn_slow"] is None
        assert not v["breached"]

    def test_each_label_fans_out_per_class(self):
        clock = Clock()
        reg = Registry()
        engine = make_engine(reg, clock, [latency_objective()])
        for _ in range(40):
            clock.t += 1.0
            reg.observe(LAT, 0.01, labels={"class": "serve"})
            reg.observe(LAT, 5.0, labels={"class": "batch"})
            verdicts = engine.tick()
        by_class = {v["class"]: v for v in verdicts}
        assert set(by_class) == {"serve", "batch"}
        assert not by_class["serve"]["breached"]
        assert by_class["batch"]["breached"]
        assert by_class["batch"]["value"] > 1.0
        assert by_class["serve"]["value"] < 0.1

    def test_quantile_and_budget_fields_populated(self):
        clock = Clock()
        reg = Registry()
        engine = make_engine(reg, clock, [latency_objective()])
        v = self._drive(engine, reg, clock, 40, 0.01)
        [verdict] = v
        assert verdict["value"] == pytest.approx(0.01, abs=0.02)
        assert verdict["budget_remaining"] == pytest.approx(1.0)
        assert verdict["burn_fast"] == 0.0


class TestEngineGaugeAndRate:
    def test_gauge_floor_breach(self):
        clock = Clock()
        reg = Registry()
        obj = SLOObjective(name="util", kind=GAUGE_FLOOR,
                           metric="nos_tpu_cluster_utilization",
                           target=0.9, compliance=0.9)
        engine = make_engine(reg, clock, [obj])
        for _ in range(40):
            clock.t += 1.0
            reg.set("nos_tpu_cluster_utilization", 0.97)
            verdicts = engine.tick()
        [v] = verdicts
        assert not v["breached"]
        for _ in range(40):
            clock.t += 1.0
            reg.set("nos_tpu_cluster_utilization", 0.5)
            verdicts = engine.tick()
        [v] = verdicts
        assert v["breached"]
        assert v["value"] == 0.5

    def test_rate_ceiling_breach(self):
        clock = Clock()
        reg = Registry()
        obj = SLOObjective(name="rebind", kind=RATE_CEILING,
                           metric="nos_tpu_drain_preemptions_total",
                           target=0.5)
        engine = make_engine(reg, clock, [obj])
        for _ in range(40):
            clock.t += 1.0
            verdicts = engine.tick()
        [v] = verdicts
        assert not v["breached"] and v["value"] == 0.0
        for _ in range(40):
            clock.t += 1.0
            reg.inc("nos_tpu_drain_preemptions_total", 2.0,
                    labels={"gang": "ns/g"})
            verdicts = engine.tick()
        [v] = verdicts
        assert v["breached"]
        assert v["value"] == pytest.approx(2.0, rel=0.2)

    def test_zero_target_rejected_no_infinity_in_json(self):
        """A zero ceiling would make burn = inf, which json.dumps
        renders as the non-JSON token Infinity — rejected up front."""
        with pytest.raises(ValueError, match="target must be > 0"):
            SLOObjective(name="evict", kind=RATE_CEILING,
                         metric="nos_tpu_drain_preemptions_total",
                         target=0.0)
        # every verdict a real engine produces stays JSON-strict
        clock = Clock()
        reg = Registry()
        engine = make_engine(reg, clock, [SLOObjective(
            name="evict", kind=RATE_CEILING,
            metric="nos_tpu_drain_preemptions_total", target=0.001)])
        for _ in range(40):
            clock.t += 1.0
            reg.inc("nos_tpu_drain_preemptions_total",
                    labels={"gang": "g"})
            engine.tick()
        text = json.dumps(engine.report())
        assert "Infinity" not in text and "NaN" not in text

    def test_vanished_breached_class_recovers_instead_of_latching(self):
        """A fanned-out class that breaches and then disappears from
        the sampled series (registry reset) must close its episode:
        SLO_RECOVERED journaled, latch cleared — not a breach that
        silently drops out of report() forever."""
        clock = Clock()
        reg = Registry()
        engine = make_engine(reg, clock, [latency_objective()])
        journal = DecisionJournal(maxlen=64, clock=clock)
        with obs.scoped(journal=journal):
            for _ in range(40):
                clock.t += 1.0
                reg.observe(LAT, 9.0, labels={"class": "doomed"})
                engine.tick()
            assert [r.category for r in journal.events()
                    if r.category in (J.SLO_BREACH, J.SLO_RECOVERED)] \
                == [J.SLO_BREACH]
            reg.reset()     # the class's series vanish entirely
            for _ in range(5):
                clock.t += 1.0
                verdicts = engine.tick()
        cats = [r.category for r in journal.events()
                if r.category in (J.SLO_BREACH, J.SLO_RECOVERED)]
        assert cats == [J.SLO_BREACH, J.SLO_RECOVERED]
        # ...and the closing verdict was visible in the report
        assert not any(v["breached"] for v in verdicts)

    def test_counter_reset_resyncs_instead_of_negative_delta(self):
        clock = Clock()
        reg = Registry()
        obj = SLOObjective(name="rebind", kind=RATE_CEILING,
                           metric="nos_tpu_drain_preemptions_total",
                           target=1000.0)
        engine = make_engine(reg, clock, [obj])
        for _ in range(35):
            clock.t += 1.0
            reg.inc("nos_tpu_drain_preemptions_total", labels={"gang": "g"})
            engine.tick()
        reg.reset()     # process restart analog
        reg.inc("nos_tpu_drain_preemptions_total", labels={"gang": "g"})
        clock.t += 1.0
        [v] = engine.tick()
        assert v["value"] is not None and v["value"] >= 0.0


# ---------------------------------------------------------------------------
# surfaces
# ---------------------------------------------------------------------------

class TestSurfaces:
    def _fed_engine(self, clock: Clock) -> SLOEngine:
        reg = Registry()
        engine = make_engine(reg, clock, [latency_objective()])
        for _ in range(40):
            clock.t += 1.0
            reg.observe(LAT, 0.01, labels={"class": "serve"})
            engine.tick()
        return engine

    def test_flight_snapshot_includes_slo_block(self):
        clock = Clock()
        engine = self._fed_engine(clock)
        with obs.scoped(Tracer(clock=clock), DecisionJournal(clock=clock),
                        engine=engine):
            snap = obs.flight_snapshot()
        assert snap["slo"]["verdicts"]
        assert snap["slo"]["verdicts"][0]["class"] == "serve"

    def test_debug_slo_endpoint_serves_report(self):
        import urllib.request

        from nos_tpu.cmd._runtime import Main

        clock = Clock()
        engine = self._fed_engine(clock)
        prev = obs.set_engine(engine)
        main = Main("slo-test", health_addr="127.0.0.1:0")
        main.start()
        try:
            url = f"http://{main.health_address}/debug/slo"
            with urllib.request.urlopen(url, timeout=5.0) as resp:
                payload = json.load(resp)
        finally:
            main.shutdown()
            obs.set_engine(prev)
        assert payload["verdicts"][0]["objective"] == "lat"
        assert payload["burn_threshold"] == 2.0

    def test_debug_slo_404_without_engine(self):
        import urllib.error
        import urllib.request

        from nos_tpu.cmd._runtime import Main

        prev = obs.set_engine(None)
        main = Main("slo-test", health_addr="127.0.0.1:0")
        main.start()
        try:
            url = f"http://{main.health_address}/debug/slo"
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(url, timeout=5.0)
            assert exc.value.code == 404
        finally:
            main.shutdown()
            obs.set_engine(prev)

    def test_snapshot_endpoint_carries_slo_and_buckets(self):
        import urllib.request

        from nos_tpu.cmd._runtime import Main
        from nos_tpu.kube.client import APIServer, KIND_NODE
        from nos_tpu.testing.factory import make_tpu_node

        clock = Clock()
        engine = self._fed_engine(clock)
        api = APIServer()
        api.create(KIND_NODE, make_tpu_node("host-0", pod_id="pod-0"))
        prev = obs.set_engine(engine)
        main = Main("slo-test", health_addr="127.0.0.1:0", api=api)
        main.start()
        try:
            url = f"http://{main.health_address}/snapshot"
            with urllib.request.urlopen(url, timeout=5.0) as resp:
                payload = json.load(resp)
        finally:
            main.shutdown()
            obs.set_engine(prev)
        assert payload["slo"]["verdicts"]
        # histogram buckets ride in the metrics series (metricsexporter
        # payload contract): some _bucket series with an le= label
        assert any(name.endswith("_bucket") and
                   any("le=" in s for s in series)
                   for name, series in payload["metrics"].items())

    def test_metrics_endpoint_serves_per_class_bucket_series(self):
        """Acceptance: /metrics serves
        nos_tpu_schedule_latency_seconds_bucket{class=...,le=...}."""
        import urllib.request

        import nos_tpu.scheduler.scheduler  # noqa: F401 — the owning
        # module's describe() pins the metric's bucket layout first
        from nos_tpu.cmd._runtime import Main
        from nos_tpu.exporter.metrics import REGISTRY as GLOBAL

        GLOBAL.observe("nos_tpu_schedule_latency_seconds", 0.02,
                       labels={"class": "slice-1x1"})
        main = Main("slo-test", health_addr="127.0.0.1:0")
        main.start()
        try:
            url = f"http://{main.health_address}/metrics"
            with urllib.request.urlopen(url, timeout=5.0) as resp:
                text = resp.read().decode()
        finally:
            main.shutdown()
        assert "# TYPE nos_tpu_schedule_latency_seconds histogram" in text
        assert 'nos_tpu_schedule_latency_seconds_bucket{class="slice-1x1"' \
            in text
        line = next(ln for ln in text.splitlines()
                    if ln.startswith("nos_tpu_schedule_latency_seconds_"
                                     "bucket")
                    and 'class="slice-1x1"' in ln)
        assert 'le="' in line

    def test_attach_slo_ticks_the_engine(self):
        import time as _time

        from nos_tpu.cmd._runtime import Main

        main = Main("slo-test")
        main.attach_slo(interval_s=0.01)
        engine = obs.get_engine()
        assert engine is not None
        main.start()
        try:
            deadline = _time.time() + 5.0
            while _time.time() < deadline and len(engine.sampler) < 3:
                _time.sleep(0.01)
        finally:
            main.shutdown()
            obs.set_engine(None)
        assert len(engine.sampler) >= 3

    def test_sampler_eviction_counts_in_its_own_registry(self):
        """A sampler over a private registry surfaces its truncation in
        THAT registry's exposition, not the process-global one."""
        clock = Clock()
        reg = Registry()
        sampler = TimeSeriesSampler(registry=reg, maxlen=2, clock=clock)
        for _ in range(5):
            clock.t += 1.0
            sampler.tick()
        snap = reg.snapshot()
        assert snap["nos_tpu_timeseries_points_dropped_total"][""] == 3

    def test_obs_slo_url_path_joins_journal_to_plugin(self, capsys):
        """Live-URL acceptance: `obs slo --url` must print the
        rejecting-plugin join, which requires fetching the flight
        snapshot (report + journal), not the bare /debug/slo body."""
        import urllib.request  # noqa: F401 — exercised via obs_main

        from nos_tpu.cmd._runtime import Main

        clock = Clock()
        reg = Registry()
        engine = make_engine(reg, clock, [latency_objective()])
        journal = DecisionJournal(maxlen=64, clock=clock)
        for _ in range(40):
            clock.t += 1.0
            reg.observe(LAT, 9.0, labels={"class": "slice-2x2"})
            engine.tick()
        journal.record(
            J.POD_REJECTED, "default/stuck", reason="", message="no fit",
            **{"class": "slice-2x2"},
            reason_counts={"NodeResourcesFit: insufficient": 3})
        prev_e = obs.set_engine(engine)
        prev_j = obs.set_journal(journal)
        main = Main("slo-test", health_addr="127.0.0.1:0")
        main.start()
        try:
            rc = obs_main(["slo", "--url",
                           f"http://{main.health_address}"])
        finally:
            main.shutdown()
            obs.set_engine(prev_e)
            obs.set_journal(prev_j)
        out = capsys.readouterr().out
        assert rc == 0
        assert "BREACH" in out
        assert "rejecting plugin for class slice-2x2: NodeResourcesFit" \
            in out

    def test_obs_top_scoreboard(self, tmp_path, capsys):
        from nos_tpu.kube.client import APIServer, KIND_NODE, KIND_POD
        from nos_tpu.kube.serialize import dump_state
        from nos_tpu.testing.factory import make_slice_pod, make_tpu_node

        api = APIServer()
        for i in range(4):
            api.create(KIND_NODE, make_tpu_node(
                f"host-{i}", pod_id="pod-0", host_index=i))
        bound = make_slice_pod("2x2", 1, name="bound")
        bound.spec.node_name = "host-0"
        api.create(KIND_POD, bound)
        api.create(KIND_POD, make_slice_pod("2x4", 1, name="waiting"))
        clock = Clock()
        engine = self._fed_engine(clock)
        payload = {"state": dump_state(api), "metrics": {},
                   "slo": engine.report()}
        path = tmp_path / "snap.json"
        path.write_text(json.dumps(payload))
        rc = obs_main(["top", "--snapshot", str(path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "pod-0" in out
        assert "slice-2x4" in out               # pending by class
        assert "utilization" in out
        assert "budget remaining" in out.lower()

    def test_obs_top_rejects_flightrecorder_payload(self, tmp_path,
                                                    capsys):
        path = tmp_path / "flight.json"
        path.write_text(json.dumps({"spans": [], "journal": []}))
        rc = obs_main(["top", "--snapshot", str(path)])
        assert rc == 1
        assert "/snapshot" in capsys.readouterr().err

    def test_obs_slo_reports_from_bench_shaped_payload(self, tmp_path,
                                                       capsys):
        clock = Clock()
        engine = self._fed_engine(clock)
        bench = {"utilization_pct": 0.97, "slo": engine.report()}
        path = tmp_path / "bench.json"
        path.write_text(json.dumps(bench))
        rc = obs_main(["slo", "--snapshot", str(path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "class=serve" in out
        assert "budget remaining=1.00" in out
        assert "0 breached / 1 verdict(s)" in out

    def test_obs_slo_errors_without_slo_block(self, tmp_path, capsys):
        path = tmp_path / "empty.json"
        path.write_text(json.dumps({"spans": []}))
        rc = obs_main(["slo", "--snapshot", str(path)])
        assert rc == 1
        assert "no SLO report" in capsys.readouterr().err

    def test_metricsexporter_passes_slo_through(self, tmp_path, capsys):
        from nos_tpu.cmd.metricsexporter import main as exporter_main
        from nos_tpu.kube.client import APIServer
        from nos_tpu.kube.serialize import dump_state

        clock = Clock()
        engine = self._fed_engine(clock)
        src = tmp_path / "snap.json"
        src.write_text(json.dumps({"state": dump_state(APIServer()),
                                   "metrics": {"nos_tpu_x_total": {"": 1}},
                                   "slo": engine.report()}))
        out = tmp_path / "payload.json"
        rc = exporter_main(["--source", str(src), "--out", str(out)])
        assert rc == 0
        payload = json.loads(out.read_text())
        assert payload["metrics"] == {"nos_tpu_x_total": {"": 1}}
        assert payload["slo"]["verdicts"][0]["class"] == "serve"


# ---------------------------------------------------------------------------
# acceptance: injected latency regression → journaled breach → CLI names
# the class and the rejecting plugin
# ---------------------------------------------------------------------------

class TestRegressionToExplainChain:
    def test_injected_latency_regression_flips_breach_cli_names_plugin(
            self, tmp_path, capsys):
        from nos_tpu.kube.client import APIServer, KIND_NODE, KIND_POD
        from nos_tpu.scheduler.framework import Framework
        from nos_tpu.scheduler.scheduler import Scheduler
        from nos_tpu.testing.factory import make_slice_pod, make_tpu_node

        clock = Clock(100.0)
        reg = Registry()
        # the scheduler emits into the process registry; sample THAT one
        from nos_tpu.exporter.metrics import REGISTRY as GLOBAL

        engine = SLOEngine(
            TimeSeriesSampler(registry=GLOBAL, clock=clock),
            [latency_objective(target=0.1, min_events=3)],
            fast_window_s=5.0, slow_window_s=20.0, clock=clock)
        journal = DecisionJournal(maxlen=256, clock=clock)
        tracer = Tracer(clock=clock, ring=RingExporter(maxlen=256))
        del reg

        with obs.scoped(tracer, journal, engine=engine):
            api = APIServer()
            api.create(KIND_NODE, make_tpu_node(
                "host-0", status_geometry={"free": {"2x2": 1}}))
            sched = Scheduler(api, Framework(), clock=clock)
            # one permanently-stuck pod of the SAME class: its per-cycle
            # rejection is the journal's plugin provenance
            api.create(KIND_POD, make_slice_pod(
                "2x2", 1, name="stuck", creation_timestamp=1.0))

            def drive(ticks: int, queue_wait: float) -> None:
                # priority above the stuck pod: the driver pod takes the
                # one free slice each cycle (observing its injected
                # queue wait), the stuck pod re-rejects behind it
                for i in range(ticks):
                    clock.t += 1.0
                    name = f"p-{clock.t:.0f}"
                    api.create(KIND_POD, make_slice_pod(
                        "2x2", 1, name=name, priority=10,
                        creation_timestamp=clock.t - queue_wait))
                    sched.run_cycle()
                    engine.tick()
                    api.delete(KIND_POD, name, "default")

            drive(30, queue_wait=0.01)      # healthy: binds in ~10 ms
            assert not [r for r in journal.events()
                        if r.category == J.SLO_BREACH]
            drive(30, queue_wait=30.0)      # regression: 30 s queue waits

            breaches = [r for r in journal.events()
                        if r.category == J.SLO_BREACH]
            assert breaches, "latency regression did not flip SLO_BREACH"
            assert breaches[0].attrs["slo_class"] == "slice-2x2"
            assert breaches[0].trace_id       # linked into the trace tree
            snap = obs.flight_snapshot()

        # ... and the one-command join: obs slo names class AND plugin
        path = tmp_path / "flight.json"
        path.write_text(json.dumps(snap))
        rc = obs_main(["slo", "--snapshot", str(path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "BREACH" in out
        assert "slice-2x2" in out
        assert "NodeResourcesFit" in out
        # the rejection chain itself is one more command away
        rc = obs_main(["explain", "pod", "default/stuck",
                       "--snapshot", str(path)])
        out = capsys.readouterr().out
        assert rc == 0 and "NodeResourcesFit" in out
