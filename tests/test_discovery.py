"""Topology discovery tests (nos_tpu/device/discovery.py).

The NVML-enumeration analog (reference pkg/gpu/nvml/client.go:31-518) must
attribute generations from PJRT device kinds and Cloud TPU env metadata,
and fall back to the configured generation off-TPU.  The real-hardware
closure of the loop lives in tests/test_e2e_device.py.
"""

import pytest

from nos_tpu.device import discovery
from nos_tpu.topology import Shape, V4, V5E, V5P


class TestKindAttribution:
    def test_v5e_lite(self):
        assert discovery._match("TPU v5 lite", discovery._KIND_PATTERNS) is V5E

    def test_v5p(self):
        assert discovery._match("TPU v5p", discovery._KIND_PATTERNS) is V5P

    def test_plain_v5_is_v5p(self):
        assert discovery._match("TPU v5", discovery._KIND_PATTERNS) is V5P

    def test_v4(self):
        assert discovery._match("TPU v4", discovery._KIND_PATTERNS) is V4

    def test_unknown(self):
        assert discovery._match("TPU v99", discovery._KIND_PATTERNS) is None


class TestBoundingBlock:
    def test_single_chip_3d_coords_clipped_to_2d(self):
        block, origin = discovery._bounding_block([(0, 0, 0)], 2)
        assert block == Shape((1, 1))
        assert origin == (0, 0)

    def test_full_v5e_host(self):
        coords = [(x, y, 0) for x in range(2) for y in range(4)]
        block, origin = discovery._bounding_block(coords, 2)
        assert block == Shape((2, 4))
        assert origin == (0, 0)

    def test_offset_origin(self):
        coords = [(4, 4, 0), (4, 5, 0), (5, 4, 0), (5, 5, 0)]
        block, origin = discovery._bounding_block(coords, 2)
        assert block == Shape((2, 2))
        assert origin == (4, 4)

    def test_v4_keeps_three_dims(self):
        coords = [(0, 0, 0), (0, 1, 0), (0, 0, 1), (0, 1, 1)]
        block, origin = discovery._bounding_block(coords, 3)
        assert block == Shape((1, 2, 2))
        assert origin == (0, 0, 0)


class TestEnvDiscovery:
    def test_single_worker_uses_advertised_topology(self):
        env = {"TPU_ACCELERATOR_TYPE": "v5litepod-8",
               "TPU_TOPOLOGY": "2x4",
               "TPU_WORKER_HOSTNAMES": "localhost"}
        d = discovery._discover_from_env(env)
        assert d.generation is V5E
        assert d.host_block == Shape((2, 4))
        assert d.num_hosts == 1
        assert d.source == discovery.SOURCE_ENV
        assert d.accelerator_type == "v5litepod-8"

    def test_multi_worker_falls_back_to_generation_host_block(self):
        env = {"TPU_ACCELERATOR_TYPE": "v5litepod-16",
               "TPU_TOPOLOGY": "4x4",
               "TPU_WORKER_HOSTNAMES": "h0,h1"}
        d = discovery._discover_from_env(env)
        assert d.num_hosts == 2
        assert d.host_block == V5E.host_block  # 4x4 spans hosts, not local

    def test_v4(self):
        d = discovery._discover_from_env({"TPU_ACCELERATOR_TYPE": "v4-8"})
        assert d.generation is V4

    def test_v5p(self):
        d = discovery._discover_from_env({"TPU_ACCELERATOR_TYPE": "v5p-16"})
        assert d.generation is V5P

    def test_v6e(self):
        from nos_tpu.topology import V6E

        d = discovery._discover_from_env({"TPU_ACCELERATOR_TYPE": "v6e-8"})
        assert d.generation is V6E

    def test_unknown_type(self):
        assert discovery._discover_from_env(
            {"TPU_ACCELERATOR_TYPE": "v99-8"}) is None

    def test_absent(self):
        assert discovery._discover_from_env({}) is None

    def test_bad_topology_string_tolerated(self):
        d = discovery._discover_from_env(
            {"TPU_ACCELERATOR_TYPE": "v5litepod-8", "TPU_TOPOLOGY": "zzz"})
        assert d.host_block == V5E.host_block


class TestDiscoverFallback:
    def test_configured_fallback_with_empty_env(self):
        d = discovery.discover(configured=V4, allow_jax=False, environ={})
        assert d.generation is V4
        assert d.host_block == V4.host_block
        assert d.source == discovery.SOURCE_CONFIGURED

    def test_default_configured_is_v5e(self):
        d = discovery.discover(allow_jax=False, environ={})
        assert d.generation is V5E

    def test_env_beats_configured(self):
        d = discovery.discover(
            configured=V4, allow_jax=False,
            environ={"TPU_ACCELERATOR_TYPE": "v5litepod-4"})
        assert d.generation is V5E
        assert d.source == discovery.SOURCE_ENV


class TestFakeFallbackTopology:
    def test_fake_runtime_keeps_observed_host_block(self, monkeypatch):
        """default_tpu_runtime(None) with the native shim unavailable must
        advertise the discovered block, not the generation default."""
        from nos_tpu import device as device_pkg
        from nos_tpu.device import fake, native

        monkeypatch.setattr(native, "available", lambda build=True: False)
        observed = discovery.DiscoveredTopology(
            generation=V5E, host_block=Shape((2, 2)), num_local_chips=4,
            num_hosts=1, source=discovery.SOURCE_ENV,
            accelerator_type="v5litepod-4", origin=(0, 0))
        monkeypatch.setattr(discovery, "discover",
                            lambda *a, **k: observed)
        rt = device_pkg.default_tpu_runtime(None)
        assert isinstance(rt, fake.FakeTpuRuntime)
        name, block = rt.topology()
        assert name == "tpu-v5e"
        assert block == Shape((2, 2))


class TestWorkloadEnv:
    def test_timeshare_grant_caps_hbm_fraction(self):
        from nos_tpu.device import workload_env

        env = {"NOS_TPU_TIMESHARE_GB": "8"}
        applied = workload_env.apply(env, hbm_gb_per_chip=16)
        assert float(applied["XLA_PYTHON_CLIENT_MEM_FRACTION"]) == \
            pytest.approx(0.45)  # 8/16 * 0.9 safety
        assert env["XLA_PYTHON_CLIENT_PREALLOCATE"] == "false"

    def test_hbm_size_discovered_per_generation(self):
        """An 8 GB grant on a v5p host (95 GB HBM) must cap ~8/95, not
        8/16 — the discovery env path supplies the generation."""
        from nos_tpu.device import workload_env

        env = {"NOS_TPU_TIMESHARE_GB": "8",
               "TPU_ACCELERATOR_TYPE": "v5p-16"}
        applied = workload_env.apply(env)  # hbm from discovery
        assert float(applied["XLA_PYTHON_CLIENT_MEM_FRACTION"]) == \
            pytest.approx(8 / 95 * 0.9, abs=1e-3)

    def test_existing_settings_not_clobbered(self):
        from nos_tpu.device import workload_env

        env = {"NOS_TPU_TIMESHARE_GB": "4",
               "XLA_PYTHON_CLIENT_MEM_FRACTION": "0.10"}
        workload_env.apply(env, hbm_gb_per_chip=16)
        assert env["XLA_PYTHON_CLIENT_MEM_FRACTION"] == "0.10"

    def test_garbage_and_absent_grants_are_noops(self):
        from nos_tpu.device import workload_env

        assert workload_env.apply({}, 16) == {}
        env = {"NOS_TPU_TIMESHARE_GB": "banana"}
        assert workload_env.apply(env, 16) == {}

    def test_slice_ids_passed_through(self):
        from nos_tpu.device import workload_env

        env = {"NOS_TPU_SLICE_IDS": "tpu-0-2x2-1"}
        applied = workload_env.apply(env, 16)
        assert applied["TPU_VISIBLE_SLICE_IDS"] == "tpu-0-2x2-1"
