"""Multi-host slice tests (SURVEY.md §7 hard part 4, BASELINE config #3):
slices spanning hosts are carved as whole-host shards by the planner's
group pass, consumed by gangs pinned to the matching host window.
"""

from __future__ import annotations

import pytest

from nos_tpu.api import constants as C
from nos_tpu.api.podgroup import PodGroup, PodGroupSpec
from nos_tpu.controllers.node_controller import NodeController
from nos_tpu.controllers.pod_controller import PodController
from nos_tpu.controllers.sliceagent.agent import SliceAgent
from nos_tpu.device.fake import FakePodResources, FakeTpuRuntime
from nos_tpu.kube.client import APIServer, KIND_NODE, KIND_POD, KIND_POD_GROUP
from nos_tpu.kube.objects import ObjectMeta, RUNNING
from nos_tpu.partitioning.slicepart import SliceNodeInitializer
from nos_tpu.partitioning.slicepart.factory import new_slice_partitioner_controller
from nos_tpu.partitioning.slicepart.group import aligned_windows
from nos_tpu.partitioning.slicepart.node import SliceNode
from nos_tpu.partitioning.state import ClusterState
from nos_tpu.scheduler.framework import Framework, NodeInfo, NodeResourcesFit
from nos_tpu.scheduler.gang import TopologyFilter
from nos_tpu.scheduler.scheduler import Scheduler
from nos_tpu.testing.factory import make_slice_pod, make_tpu_node
from nos_tpu.topology import Shape, V5E
from nos_tpu.topology.annotations import parse_spec_annotations


class Harness:
    """8 v5e hosts in one physical pod (a v5e-64)."""

    def __init__(self, hosts: int = 8):
        self.api = APIServer()
        self.state = ClusterState()
        self.now = [0.0]
        NodeController(self.api, self.state, SliceNodeInitializer(self.api)).bind()
        PodController(self.api, self.state).bind()
        self.partitioner = new_slice_partitioner_controller(
            self.api, self.state, batch_idle_s=10.0,
            clock=lambda: self.now[0])
        self.partitioner.bind()
        self.agents = []
        for i in range(hosts):
            self.api.create(KIND_NODE, make_tpu_node(
                f"host-{i}", pod_id="pod-a", host_index=i))
            a = SliceAgent(self.api, f"host-{i}", FakeTpuRuntime(V5E),
                           FakePodResources())
            a.start()
            a.tick()
            self.agents.append(a)
        self.scheduler = Scheduler(
            self.api, Framework([NodeResourcesFit(), TopologyFilter(self.api)]))

    def converge(self, cycles: int = 4) -> int:
        bound = 0
        for _ in range(cycles):
            bound += self.scheduler.run_cycle()
            self.now[0] += 11.0
            self.partitioner.process_if_ready()
            for a in self.agents:
                a.tick()
        return bound

    def gang(self, name: str, members: int, shape: str):
        self.api.create(KIND_POD_GROUP, PodGroup(
            metadata=ObjectMeta(name=name, namespace="default"),
            spec=PodGroupSpec(min_member=members)))
        for i in range(members):
            self.api.create(KIND_POD, make_slice_pod(
                shape, 1, name=f"{name}-{i}",
                labels={C.LABEL_POD_GROUP: name}))


def test_aligned_windows_helper():
    nodes = []
    for i in (0, 1, 2, 3, 5):
        n = make_tpu_node(f"h{i}", pod_id="p", host_index=i)
        nodes.append(SliceNode(n, NodeInfo(node=n)))
    wins = aligned_windows(nodes, 2)
    names = [[n.name for n in w] for w in wins]
    assert names == [["h0", "h1"], ["h2", "h3"]]  # 5 has no partner at 4


def test_baseline_reshape_v5e64():
    """BASELINE config #3: v5e-64 -> {4 x v5e-8, 2 x v5e-16} under
    pending-pod pressure."""
    h = Harness(8)
    # 4 single-host jobs (v5e-8 = one 2x4 block each)
    for i in range(4):
        h.api.create(KIND_POD, make_slice_pod("2x4", 1, name=f"single-{i}"))
    # 2 multi-host jobs (v5e-16 = 4x4 over 2 hosts), each a 2-pod gang
    h.gang("job-a", 2, "4x4")
    h.gang("job-b", 2, "4x4")

    assert h.converge() == 8
    # every pod is running
    for p in h.api.list(KIND_POD):
        assert p.status.phase == RUNNING, p.metadata.name

    # each gang occupies one aligned 2-host window
    for job in ("job-a", "job-b"):
        idxs = sorted(
            int(h.api.get(KIND_NODE, h.api.get(
                KIND_POD, f"{job}-{i}", "default").spec.node_name
            ).metadata.labels[C.LABEL_HOST_INDEX])
            for i in range(2)
        )
        assert idxs[1] == idxs[0] + 1 and idxs[0] % 2 == 0, (job, idxs)

    # shard spec annotations on member hosts
    member = h.api.get(KIND_POD, "job-a-0", "default").spec.node_name
    node = h.api.get(KIND_NODE, member)
    spec = {(a.index, a.profile): a.quantity
            for a in parse_spec_annotations(node.metadata.annotations)}
    assert spec.get((0, "4x4")) == 1


def test_reclaim_free_multihost_for_small_pods():
    """Free multi-host instances are broken up when sub-host profiles are
    lacking (the v5e-16 -> small-slices direction of the reshape)."""
    h = Harness(2)
    h.gang("big", 2, "4x4")
    assert h.converge() == 2
    # the job finishes: pods deleted, shards become free
    for i in range(2):
        h.api.delete(KIND_POD, f"big-{i}", "default")
    for a in h.agents:
        a.tick()
    # now 4 quarter-host pods arrive
    for i in range(4):
        h.api.create(KIND_POD, make_slice_pod("2x2", 1, name=f"small-{i}"))
    assert h.converge() == 4


def test_used_shards_never_destroyed():
    """A running multi-host job's shards survive any repartition pressure."""
    h = Harness(2)
    h.gang("big", 2, "4x4")
    assert h.converge() == 2
    # register device usage with the fake kubelet so reports mark them used
    for i, a in enumerate(h.agents):
        node = h.api.get(KIND_NODE, f"host-{i}")
        devs = a.runtime.list_devices()
        assert len(devs) == 1
        a.pod_resources.allocate(f"default/big-{i}", {devs[0].device_id})
        a.tick()
    # heavy small-slice pressure cannot break up the used instance
    for i in range(4):
        h.api.create(KIND_POD, make_slice_pod("2x2", 1, name=f"small-{i}"))
    h.converge()
    for i, a in enumerate(h.agents):
        ids = [d.device_id for d in a.runtime.list_devices()]
        assert any("4x4" in d for d in ids), f"host-{i} lost its shard"
    for i in range(4):
        assert h.api.get(KIND_POD, f"small-{i}", "default").spec.node_name == ""


def test_gang_rejects_misaligned_window():
    """With host 0 occupied, a 2-host slice gang must not land on the
    unaligned pair (1,2); it fits the aligned window (2,3)."""
    h = Harness(4)
    h.api.create(KIND_POD, make_slice_pod("2x4", 1, name="holder"))
    assert h.converge(1) >= 1
    holder_node = h.api.get(KIND_POD, "holder", "default").spec.node_name
    h.gang("big", 2, "4x4")
    assert h.converge() == 2
    idxs = sorted(
        int(h.api.get(KIND_NODE, h.api.get(
            KIND_POD, f"big-{i}", "default").spec.node_name
        ).metadata.labels[C.LABEL_HOST_INDEX])
        for i in range(2)
    )
    assert idxs[0] % 2 == 0 and idxs[1] == idxs[0] + 1
    assert holder_node not in {
        h.api.get(KIND_POD, f"big-{i}", "default").spec.node_name
        for i in range(2)
    }
