"""Native shim tests: the C++ TpuRuntimeClient must be a drop-in for the
fake — same placement semantics, same agent e2e behavior (the analog of the
reference's nvml-tagged client conforming to the mocked interface)."""

from __future__ import annotations

import pytest

from nos_tpu.device import native
from nos_tpu.device.fake import FakePodResources, FakeTpuRuntime
from nos_tpu.topology.errors import PlacementInfeasibleError
from nos_tpu.topology import Shape, V4, V5E

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native shim not buildable (no g++?)")


def shapes(*names):
    return [Shape.parse(n) for n in names]


class TestNativeRuntime:
    def test_create_list_delete(self):
        rt = native.NativeTpuRuntime(V5E)
        ids = rt.create_slices(0, shapes("2x2", "1x1"))
        assert len(ids) == 2
        assert len(rt.list_devices()) == 2
        rt.delete_slice(ids[0])
        assert len(rt.list_devices()) == 1
        with pytest.raises(Exception):
            rt.delete_slice("nope")

    def test_exact_fill_and_overfull(self):
        rt = native.NativeTpuRuntime(V5E)   # 2x4 block = 8 chips
        rt.create_slices(0, shapes("2x2", "2x2"))
        with pytest.raises(PlacementInfeasibleError):
            rt.create_slices(0, shapes("1x1"))

    def test_all_or_nothing_on_failure(self):
        rt = native.NativeTpuRuntime(V5E)
        rt.create_slices(0, shapes("2x2"))
        before = len(rt.list_devices())
        with pytest.raises(PlacementInfeasibleError):
            rt.create_slices(0, shapes("1x1", "2x2"))  # 2nd 2x2 can't fit
        assert len(rt.list_devices()) == before

    def test_joint_placement_beats_greedy(self):
        """2x2 + 4x1x1 on a 2x4 block only fits if placed jointly."""
        rt = native.NativeTpuRuntime(V5E)
        ids = rt.create_slices(0, shapes("1x1", "1x1", "1x1", "1x1", "2x2"))
        assert len(ids) == 5

    def test_3d_generation(self):
        rt = native.NativeTpuRuntime(V4)    # 1x2x2 block = 4 chips
        ids = rt.create_slices(0, shapes("1x1x2", "1x1x2"))
        assert len(ids) == 2
        with pytest.raises(PlacementInfeasibleError):
            rt.create_slices(0, shapes("1x1x1"))

    def test_multihost_shard(self):
        rt = native.NativeTpuRuntime(V5E)
        ids = rt.create_slices(0, shapes("4x4"))
        assert len(ids) == 1
        assert rt.list_devices()[0].resource_name == "nos.tpu/slice-4x4"
        with pytest.raises(PlacementInfeasibleError):
            rt.create_slices(0, shapes("1x1"))

    def test_startup_cleanup(self):
        rt = native.NativeTpuRuntime(V5E)
        ids = rt.create_slices(0, shapes("2x2", "2x2"))
        doomed = rt.delete_all_except({ids[0]})
        assert doomed == [ids[1]]
        assert [d.device_id for d in rt.list_devices()] == [ids[0]]


class TestConformanceWithFake:
    """Same operation sequence -> same resulting device multiset."""

    SEQUENCES = [
        [("create", 0, ("2x2", "1x1", "1x1")), ("create", 0, ("1x2",))],
        [("create", 0, ("2x4",)), ("delete_first", 0), ("create", 0, ("2x2", "2x2"))],
        [("create", 0, ("1x1",) * 8)],
        [("create", 0, ("4x4",))],
        [("create", 1, ("2x2",)), ("create", 0, ("2x4",))],
    ]

    @pytest.mark.parametrize("seq", SEQUENCES)
    def test_sequence(self, seq):
        fake, nat = FakeTpuRuntime(V5E), native.NativeTpuRuntime(V5E)
        for rt in (fake, nat):
            for op in seq:
                if op[0] == "create":
                    rt.create_slices(op[1], shapes(*op[2]))
                elif op[0] == "delete_first":
                    first = sorted(d.device_id for d in rt.list_devices()
                                   if d.unit_index == op[1])[0]
                    rt.delete_slice(first)
        summarize = lambda rt: sorted(  # noqa: E731
            (d.unit_index, d.resource_name) for d in rt.list_devices())
        assert summarize(fake) == summarize(nat)

    @pytest.mark.parametrize("reqs", [
        ("2x2", "2x2", "1x1"),        # 9 chips > 8: both must refuse
        ("2x4", "1x1"),
    ])
    def test_both_reject_overfull(self, reqs):
        fake, nat = FakeTpuRuntime(V5E), native.NativeTpuRuntime(V5E)
        with pytest.raises(PlacementInfeasibleError):
            fake.create_slices(0, shapes(*reqs))
        with pytest.raises(PlacementInfeasibleError):
            nat.create_slices(0, shapes(*reqs))


class TestNativeEndToEnd:
    def test_agent_e2e_on_native_runtime(self):
        """The full decision-plane loop with the C++ runtime actuating."""
        from nos_tpu.controllers.node_controller import NodeController
        from nos_tpu.controllers.pod_controller import PodController
        from nos_tpu.controllers.sliceagent.agent import SliceAgent
        from nos_tpu.kube.client import APIServer, KIND_NODE, KIND_POD
        from nos_tpu.kube.objects import RUNNING
        from nos_tpu.partitioning.slicepart import SliceNodeInitializer
        from nos_tpu.partitioning.slicepart.factory import (
            new_slice_partitioner_controller,
        )
        from nos_tpu.partitioning.state import ClusterState
        from nos_tpu.scheduler.framework import Framework
        from nos_tpu.scheduler.scheduler import Scheduler
        from nos_tpu.testing.factory import make_slice_pod, make_tpu_node

        api = APIServer()
        state = ClusterState()
        now = [0.0]
        NodeController(api, state, SliceNodeInitializer(api)).bind()
        PodController(api, state).bind()
        pc = new_slice_partitioner_controller(
            api, state, batch_idle_s=10.0, clock=lambda: now[0])
        pc.bind()
        api.create(KIND_NODE, make_tpu_node("host-0"))
        agent = SliceAgent(api, "host-0", native.NativeTpuRuntime(V5E),
                           FakePodResources())
        agent.start()
        agent.tick()
        sched = Scheduler(api, Framework())

        for i in range(2):
            api.create(KIND_POD, make_slice_pod("2x2", 1, name=f"p-{i}"))
        sched.run_cycle()
        now[0] += 11.0
        assert pc.process_if_ready()
        agent.tick()
        assert sched.run_cycle() == 2
        agent.tick()  # kubelet-phase sim: the agent admits the bound pods
        for i in range(2):
            assert api.get(KIND_POD, f"p-{i}", "default").status.phase == RUNNING


class TestNativePacker:
    """The C++ exact packer (nos_pack) must agree with the Python search
    on feasibility and produce valid aligned placements — it backs
    topology.packing's hot loops via set_native_packer."""

    def test_installed_at_import(self):
        from nos_tpu.topology import packing

        # nos_tpu/__init__ auto-installs when the shim builds (it does
        # here, per the skipif guard on this module)
        assert packing._native_packer is native.native_packer

    @pytest.mark.parametrize("block_name,pool", [
        ("2x4", ["1x1", "1x2", "2x2", "1x4", "2x4"]),
        ("1x2x2", ["1x1x1", "1x1x2", "1x2x2"]),
    ])
    def test_matches_python_search(self, block_name, pool):
        import itertools
        import random

        from nos_tpu.topology import packing

        rng = random.Random(1234)
        block = Shape.parse(block_name)
        pool = [Shape.parse(s) for s in pool]
        for _ in range(200):
            counts = {s: rng.randint(1, 3)
                      for s in rng.sample(pool, rng.randint(1, len(pool)))}
            occ = rng.getrandbits(block.chips) if rng.random() < 0.5 else 0
            require_full = occ == 0 and rng.random() < 0.3
            key = packing._counts_key(counts)
            got = native.native_packer(block, key, occ, require_full)
            want = packing._pack_masks(block, key, occupied=occ,
                                       require_full=require_full)
            assert (got is None) == (want is None), (counts, occ,
                                                     require_full)
            if got is None:
                continue
            used = occ
            for pl in got:
                assert all(o % d == 0 for o, d in zip(pl.offset, pl.dims))
                for cell in itertools.product(
                        *[range(o, o + d)
                          for o, d in zip(pl.offset, pl.dims)]):
                    bit = 1 << packing._cell_id(cell, block.dims)
                    assert not used & bit, "overlapping placement"
                    used |= bit
            if require_full:
                assert used == (1 << block.chips) - 1

    def test_pack_uses_native_and_agrees(self):
        """pack() through the installed seam equals the pure-Python result
        for the exact-tiling geometry derivation path."""
        from nos_tpu.topology import packing

        block = V5E.host_block
        counts = {Shape.parse("2x2"): 1, Shape.parse("1x2"): 2}
        via_seam = packing.pack(block, counts)
        direct = packing._pack_masks(
            block, packing._counts_key(counts), occupied=0,
            require_full=False)
        assert (via_seam is None) == (direct is None)
        assert via_seam is not None


class TestNativeFitBatch:
    """nos_fit_batch: the Filter prescreen's C half (native_filter.py)."""

    def _py_verdict(self, request, free, cap, used, pod_chips):
        """NodeResourcesFit.filter's math, straight from framework.py."""
        from nos_tpu.kube.resources import fits

        if not fits(request, free):
            return False
        if pod_chips and used + pod_chips > cap:
            return False
        return True

    @pytest.mark.parametrize("seed", range(12))
    def test_matches_python_fit_semantics(self, seed):
        """Randomized equivalence: the native verdict replays fits() +
        the chip guard bit-for-bit — the superset contract's foundation."""
        import random

        rng = random.Random(seed)
        names = [f"res-{i}" for i in range(rng.randrange(1, 6))]
        request = {n: float(rng.choice([0, 1, 2, 4])) for n in names}
        pod_chips = rng.choice([0, 2, 8])
        nodes = []
        for _ in range(20):
            free = {n: float(rng.choice([0, 1, 2, 3, 8])) for n in names}
            cap = rng.choice([8, 16])
            used = rng.choice([0, 4, 8, 16])
            nodes.append((free, cap, used))
        universe = sorted(n for n, v in request.items() if v > 0)
        free_flat = [f.get(n, 0.0) for f, _, _ in nodes for n in universe]
        result = native.fit_batch(
            free_flat, [request[n] for n in universe],
            [float(c) for _, c, _ in nodes],
            [float(u) for _, _, u in nodes],
            [float(pod_chips)], len(nodes), 1, len(universe))
        assert result is not None
        verdicts, miss = result
        for i, (free, cap, used) in enumerate(nodes):
            want = self._py_verdict(request, free, cap, used, pod_chips)
            assert (verdicts[i] == 1) == want, (i, request, free)
            if verdicts[i] == 0:
                mask = miss[i]
                if mask & ~native.FIT_MISS_CHIP_GUARD:
                    missing = {universe[r] for r in range(len(universe))
                               if mask & (1 << r)}
                    expect = {n for n, v in request.items()
                              if v > 0 and free.get(n, 0.0) < v}
                    assert missing == expect

    def test_prescreen_messages_are_byte_identical(self):
        """screen_nodes reconstructs NodeResourcesFit's exact strings."""
        from nos_tpu.scheduler.framework import (
            CycleState, Framework, NodeInfo, NodeResourcesFit,
        )
        from nos_tpu.scheduler.native_filter import FitPrescreen
        from nos_tpu.kube.resources import pod_request
        from nos_tpu.scheduler.framework import _slice_chips
        from nos_tpu.testing.factory import make_slice_pod, make_tpu_node

        fw = Framework([NodeResourcesFit()])
        screen = FitPrescreen(fw)
        assert screen.verdict_sound and screen.message_exact
        # one node that fails on resources, one on the chip guard, one ok
        n_missing = NodeInfo(node=make_tpu_node(
            "missing", status_geometry={"free": {"1x1": 1}}))
        n_guard = NodeInfo(node=make_tpu_node(
            "guard", status_geometry={"free": {"2x2": 2}}))
        # bound usage hides behind a re-carve: free looks ok, chips don't
        n_guard.requested = {"nos.tpu/slice-2x4": 1.0}
        n_ok = NodeInfo(node=make_tpu_node(
            "ok", status_geometry={"free": {"2x2": 2}}))
        pod = make_slice_pod("2x2", 2)
        req = pod_request(pod)
        msgs = screen.screen_nodes([n_missing, n_guard, n_ok], req,
                                   _slice_chips(req))
        assert msgs is not None
        for ni, msg in zip([n_missing, n_guard, n_ok], msgs):
            st = fw.run_filter_plugins(CycleState(), pod, ni)
            if st.is_success:
                assert msg is None
            else:
                assert msg == f"{st.plugin}: {st.message}"

    def test_two_thread_native_overlap(self):
        """Every shim entry point goes through ctypes' CDLL, which
        RELEASES the GIL for the duration of the call — so two threads
        inside long native calls (the fleet prescreen's batch fit, the
        exact packer) genuinely overlap instead of serializing, which
        is what lets concurrent plan shards' native filtering run in
        parallel.

        Pinned via an event-based in-kernel handshake, not a wall-clock
        speedup threshold (the old form flaked on loaded CI boxes):
        each thread enters `nos_gil_handshake`, atomically announces
        itself in a shared cell, and spin-waits for its partner.  Both
        see the partner IFF the binding released the GIL — a PyDLL-style
        binding would wedge thread B outside while thread A spins to the
        timeout, and the handshake reports 0.  The only timing constant
        is a generous deadline a genuine regression exhausts but machine
        noise cannot."""
        import ctypes
        import threading

        lib = native._load()
        # the binding really is the GIL-dropping loader class (PyDLL
        # would keep the GIL held through every call)
        assert type(lib) is ctypes.CDLL

        cell = (ctypes.c_longlong * 1)()
        results: list[int | None] = [None, None]

        def work(i: int) -> None:
            results[i] = lib.nos_gil_handshake(cell, 30.0)

        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results == [1, 1], (
            f"no GIL overlap: handshake verdicts {results} "
            "(0 = partner never entered native code concurrently; "
            "is the shim bound via a GIL-holding loader?)")


class TestNativeHotLoops:
    """ISSUE 18 decision-plane hot loops: each native form and its
    Python fallback must be interchangeable bit-for-bit — the scheduler
    journals DECISIONS, so a single comparator divergence breaks the
    incremental-vs-full byte-identity certification (nosdiff)."""

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_window_busy_sort_matches_python_sort(self, seed):
        import ctypes
        import random

        rng = random.Random(seed)
        # unique (gid, host-index) keys — the busy dict guarantees that
        keys = list({(rng.randrange(6), rng.randrange(16))
                     for _ in range(rng.randrange(0, 40))})
        rng.shuffle(keys)
        triples = [(g, i, rng.randrange(2)) for g, i in keys]
        n = len(triples)
        gid_a = (ctypes.c_longlong * max(1, n))(*[t[0] for t in triples])
        idx_a = (ctypes.c_longlong * max(1, n))(*[t[1] for t in triples])
        val_a = (ctypes.c_uint8 * max(1, n))(*[t[2] for t in triples])
        assert native.window_busy_sort(gid_a, idx_a, val_a, n)
        got = [(gid_a[i], idx_a[i], val_a[i]) for i in range(n)]
        assert got == sorted(triples)

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_victim_prescreen_matches_python_screen(self, seed):
        import random

        rng = random.Random(seed)
        n_res = rng.randrange(1, 5)
        req = [round(rng.uniform(0.5, 4.0), 3) for _ in range(n_res)]
        rows = [[round(rng.uniform(0.0, 5.0), 3) for _ in range(n_res)]
                for _ in range(30)]
        rows.append(list(req))                  # exact-equality edge
        rows.append([v - 1e-9 for v in req])    # just-below edge
        caps = [rng.randrange(0, 9) for _ in rows]
        for pod_chips in (0, rng.randrange(1, 9)):
            got = native.victim_prescreen(rows, req, caps, pod_chips)
            assert got is not None
            # the Python fallback in capacityscheduling._victim_screen:
            # fits(req, allocatable) and the chip-capacity guard
            want = [all(row[j] >= req[j] for j in range(n_res))
                    and (pod_chips == 0 or pod_chips <= caps[i])
                    for i, row in enumerate(rows)]
            assert got == want

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_choose_node_native_matches_python_argmin(self, seed):
        import random

        from nos_tpu.kube.client import APIServer, KIND_NODE, KIND_POD
        from nos_tpu.scheduler.framework import Framework
        from nos_tpu.scheduler.scheduler import Scheduler
        from nos_tpu.testing.factory import make_slice_pod, make_tpu_node

        rng = random.Random(seed)
        api = APIServer()
        hosts, per_domain = 16, 4
        for i in range(hosts):
            free = rng.choice([{"2x2": 1}, {"2x2": 2}, {"2x4": 1}])
            api.create(KIND_NODE, make_tpu_node(
                f"h{i:02d}", pod_id=f"dom-{i // per_domain}",
                host_index=i % per_domain, status_geometry={"free": free}))
        for i in rng.sample(range(hosts), 5):    # busy windows
            api.create(KIND_POD, make_slice_pod(
                "2x2", 1, name=f"b{i}", node_name=f"h{i:02d}"))
        scheduler = Scheduler(api, Framework())
        scheduler._reserved_hosts = frozenset(   # avoided-host axis
            f"h{i:02d}" for i in rng.sample(range(hosts), 2))
        api.create(KIND_POD, make_slice_pod("2x2", 1, name="target"))
        pod = api.get(KIND_POD, "target", "default")
        lister = scheduler._cycle_lister()
        nis = list(lister.list())
        picked = scheduler._native_choose(pod, nis, lister)
        assert picked is not None, "native scorer fell back unexpectedly"
        want = min(nis, key=scheduler._score_key(pod, lister))
        assert picked.name == want.name
