"""DPOR-lite interleaving explorer (nos_tpu/testing/interleave.py).

Three layers:

- **regression corpus**: the seeded critical pairs explore to the
  verdicts the determinism gate requires — the buggy ``replay_dropped``
  model rediscovered (inversion AND realized deadlock) in well under
  the 5 000-schedule budget, every fixed model clean to completion;
- **explorer mechanics**: exhaustiveness (a deadlock that only exists
  in one interleaving of a 3-cycle is found), sleep-set pruning
  (independent lock sets don't explode the schedule count), gate-set
  reuse (a common outer lock makes an AB/BA pair safe, exactly like
  lockcheck), reentrancy;
- **failure surfaces**: scenario exceptions and lock misuse become
  result errors, not hangs.
"""

from __future__ import annotations

import pytest

from nos_tpu.testing.interleave import (
    REGRESSION_CORPUS, ExplorationError, ExploreResult, explore,
    replay_dropped_scenario,
)

pytestmark = pytest.mark.interleave

# The ISSUE/check.sh acceptance budget for rediscovering the PR 2
# replay_dropped inversion.
REPLAY_BUDGET = 5000


# ---------------------------------------------------------------------------
# The regression corpus (the determinism gate's dynamic half)
# ---------------------------------------------------------------------------

class TestRegressionCorpus:
    @pytest.mark.parametrize(
        "name,build,expect_clean",
        REGRESSION_CORPUS,
        ids=[name for name, _, _ in REGRESSION_CORPUS])
    def test_corpus_verdicts(self, name, build, expect_clean):
        result = explore(name, build, max_schedules=REPLAY_BUDGET)
        assert result.complete, (
            f"{name}: budget exhausted after {result.schedules} schedules")
        assert result.clean == expect_clean, (
            f"{name}: expected clean={expect_clean}, got "
            f"{result.inversions + result.deadlocks + result.errors}")

    def test_buggy_replay_rediscovered_within_budget(self):
        result = explore("replay-dropped-buggy",
                         replay_dropped_scenario(buggy=True),
                         max_schedules=REPLAY_BUDGET)
        # the inversion (lockcheck's graph verdict) AND the schedule
        # where it actually bites (a realized deadlock) must both be
        # found, well inside the budget
        assert result.first_violation_schedule is not None
        assert result.first_violation_schedule <= REPLAY_BUDGET
        assert result.inversions, "gate-set inversion not rediscovered"
        assert result.deadlocks, "deadlocking schedule not rediscovered"
        assert any("SchedulerCache._lock" in d and "APIServer._lock" in d
                   for d in result.deadlocks)

    def test_fixed_replay_is_certified_clean(self):
        result = explore("replay-dropped-fixed",
                         replay_dropped_scenario(buggy=False),
                         max_schedules=REPLAY_BUDGET)
        assert result.complete and result.clean

    def test_stop_on_first_short_circuits(self):
        result = explore("replay-dropped-buggy",
                         replay_dropped_scenario(buggy=True),
                         max_schedules=REPLAY_BUDGET, stop_on_first=True)
        assert not result.clean
        assert result.schedules == result.first_violation_schedule


# ---------------------------------------------------------------------------
# Explorer mechanics
# ---------------------------------------------------------------------------

def _ring_scenario(env):
    """3-thread dining-philosophers ring: deadlock exists only in the
    interleavings where each thread grabs its first lock before any
    grabs its second — exhaustiveness is what finds it."""
    a = env.lock("A")
    b = env.lock("B")
    c = env.lock("C")

    def t0():
        with a:
            with b:
                pass

    def t1():
        with b:
            with c:
                pass

    def t2():
        with c:
            with a:
                pass

    return [t0, t1, t2]


def _gated_scenario(env):
    """Both nesting orders of A/B exist, but every chain runs under one
    common outer gate G — lockcheck's gate-set semantics say no
    deadlock is reachable, and the explorer (which reuses them, and
    explores every schedule) must agree on both counts."""
    g = env.lock("G")
    a = env.lock("A")
    b = env.lock("B")

    def t0():
        with g:
            with a:
                with b:
                    pass

    def t1():
        with g:
            with b:
                with a:
                    pass

    return [t0, t1]


def _independent_scenario(env):
    """Two threads over disjoint locks: every interleaving commutes, so
    sleep sets should collapse the tree to a handful of schedules."""
    a = env.lock("A")
    b = env.lock("B")

    def t0():
        with a:
            pass
        with a:
            pass

    def t1():
        with b:
            pass
        with b:
            pass

    return [t0, t1]


class TestExplorerMechanics:
    def test_three_thread_ring_deadlock_found(self):
        result = explore("ring", _ring_scenario)
        assert result.complete
        assert result.deadlocks, "the ring's one deadlock interleaving missed"
        assert any("T0" in d and "T1" in d and "T2" in d
                   for d in result.deadlocks)

    def test_gate_set_blesses_common_outer_lock(self):
        result = explore("gated", _gated_scenario)
        assert result.complete
        assert result.clean, (result.inversions + result.deadlocks)

    def test_sleep_sets_prune_independent_interleavings(self):
        result = explore("independent", _independent_scenario)
        assert result.complete and result.clean
        # 2 threads x (spawn + 4 lock ops): naive DFS visits dozens of
        # schedules; with every pair of cross-thread ops independent,
        # sleep sets must collapse to single digits
        assert result.schedules < 10, result.schedules

    def test_reentrant_reacquire_is_not_a_self_deadlock(self):
        def build(env):
            r = env.lock("R", reentrant=True)

            def t0():
                with r:
                    with r:
                        pass

            def t1():
                with r:
                    pass

            return [t0, t1]

        result = explore("reentrant", build)
        assert result.complete and result.clean

    def test_non_reentrant_self_acquire_is_a_deadlock(self):
        def build(env):
            lk = env.lock("L")

            def t0():
                with lk:
                    with lk:
                        pass

            def t1():
                pass

            return [t0, t1]

        result = explore("self-deadlock", build)
        assert result.deadlocks
        assert any("itself" in d for d in result.deadlocks)


# ---------------------------------------------------------------------------
# Failure surfaces
# ---------------------------------------------------------------------------

class TestFailureSurfaces:
    def test_scenario_exception_becomes_result_error(self):
        def build(env):
            a = env.lock("A")

            def t0():
                with a:
                    raise ValueError("boom")

            def t1():
                with a:
                    pass

            return [t0, t1]

        result = explore("raises", build)
        assert not result.clean
        assert any("ValueError" in e for e in result.errors)

    def test_foreign_release_is_convicted(self):
        def build(env):
            a = env.lock("A")

            def t0():
                a.release()     # never acquired

            def t1():
                pass

            return [t0, t1]

        result = explore("foreign-release", build)
        assert any("without owning" in e for e in result.errors)

    def test_wrong_thread_count_rejected(self):
        with pytest.raises(ExplorationError):
            explore("solo", lambda env: [lambda: None])

    def test_assert_clean_raises_with_detail(self):
        result = ExploreResult(scenario="x", schedules=1,
                               deadlocks=["deadlock: T0 waits"])
        with pytest.raises(AssertionError, match="T0 waits"):
            result.assert_clean()
