"""Mesh-aware slice normalization (SURVEY.md §2.8: the slice shape
chooser must know which JAX mesh shapes a workload requests).

A pod requesting `google.com/tpu: N` with `nos.tpu/mesh: AxB` is
rewritten at admission into `nos.tpu/slice-AxB: 1`, end-to-end on both
substrates: the in-memory hook mutates the object, the webhook path
returns RFC 6902 ops the kube-apiserver applies."""

from __future__ import annotations

import json

import pytest

from nos_tpu.api import constants as C
from nos_tpu.api.mesh import (
    install_mesh_normalization, mesh_patch_ops, normalize_mesh_request,
)
from nos_tpu.kube.client import APIServer, KIND_POD
from nos_tpu.testing.factory import make_pod, make_slice_pod


def tpu_pod(n: int, mesh: str | None = None, name: str = "p", **kw):
    annotations = {C.ANNOT_MESH: mesh} if mesh else {}
    return make_pod(name=name, resources={C.RESOURCE_TPU: n, "cpu": 1.0},
                    annotations=annotations, **kw)


class TestNormalizeObject:
    def test_rewrites_matching_mesh(self):
        pod = tpu_pod(8, mesh="2x4")
        assert normalize_mesh_request(pod)
        res = pod.spec.containers[0].resources
        assert C.RESOURCE_TPU not in res
        assert res["nos.tpu/slice-2x4"] == 1

    def test_canonicalizes_shape(self):
        pod = tpu_pod(8, mesh="4x2")
        assert normalize_mesh_request(pod)
        assert "nos.tpu/slice-2x4" in pod.spec.containers[0].resources

    @pytest.mark.parametrize("mesh,n", [
        ("2x4", 4),        # chip-count mismatch
        ("banana", 8),     # unparseable
        (None, 8),         # no annotation
    ])
    def test_ineligible_left_alone(self, mesh, n):
        pod = tpu_pod(n, mesh=mesh)
        assert not normalize_mesh_request(pod)
        assert pod.spec.containers[0].resources[C.RESOURCE_TPU] == n

    def test_explicit_slice_request_wins(self):
        pod = make_slice_pod("2x2", 1, name="explicit",
                             annotations={C.ANNOT_MESH: "2x2"})
        assert not normalize_mesh_request(pod)

    def test_admission_hook_applies_on_create(self):
        api = APIServer()
        install_mesh_normalization(api)
        api.create(KIND_POD, tpu_pod(8, mesh="2x4"))
        stored = api.get(KIND_POD, "p", "default")
        assert stored.spec.containers[0].resources.get(
            "nos.tpu/slice-2x4") == 1


class TestPatchOps:
    def raw(self, n=8, mesh="2x4", sections=("limits", "requests")):
        res = {s: {C.RESOURCE_TPU: str(n), "cpu": "1"} for s in sections}
        return {
            "metadata": {"name": "p", "namespace": "default",
                         "annotations": {C.ANNOT_MESH: mesh}},
            "spec": {"containers": [
                {"name": "main", "resources": res,
                 "volumeMounts": [{"name": "x", "mountPath": "/x"}]},
            ], "nodeSelector": {"pool": "tpu"}},
        }

    @staticmethod
    def apply(ops, doc):
        """Minimal RFC 6902 evaluator for the op shapes we emit."""
        doc = json.loads(json.dumps(doc))
        for op in ops:
            parts = [p.replace("~1", "/").replace("~0", "~")
                     for p in op["path"].split("/")[1:]]
            cur = doc
            for p in parts[:-1]:
                cur = cur[int(p)] if isinstance(cur, list) else cur[p]
            if op["op"] == "remove":
                del cur[parts[-1]]
            elif op["op"] == "add":
                cur[parts[-1]] = op["value"]
        return doc

    def test_ops_rewrite_both_sections_only(self):
        raw = self.raw()
        ops = mesh_patch_ops(raw)
        assert ops and len(ops) == 4     # remove+add x limits+requests
        out = self.apply(ops, raw)
        for section in ("limits", "requests"):
            sec = out["spec"]["containers"][0]["resources"][section]
            assert C.RESOURCE_TPU not in sec
            assert sec["nos.tpu/slice-2x4"] == "1"
            assert sec["cpu"] == "1"     # untouched
        # unmodeled fields never touched
        assert out["spec"]["nodeSelector"] == {"pool": "tpu"}
        assert out["spec"]["containers"][0]["volumeMounts"]

    def test_limits_only_pod(self):
        raw = self.raw(sections=("limits",))
        ops = mesh_patch_ops(raw)
        assert len(ops) == 2
        out = self.apply(ops, raw)
        lim = out["spec"]["containers"][0]["resources"]["limits"]
        assert lim["nos.tpu/slice-2x4"] == "1"

    def test_mismatch_returns_none(self):
        assert mesh_patch_ops(self.raw(n=4)) is None
        assert mesh_patch_ops(self.raw(mesh="3x3")) is None
        raw = self.raw()
        raw["spec"]["containers"][0]["resources"]["limits"][
            "nos.tpu/slice-1x1"] = "1"
        assert mesh_patch_ops(raw) is None   # explicit slice wins

    def test_webhook_returns_jsonpatch(self):
        import base64

        from nos_tpu.kube.webhook import AdmissionHandler

        h = AdmissionHandler(APIServer())
        h.register_mutating("Pod", mesh_patch_ops)
        review = json.dumps({
            "request": {"uid": "u1", "operation": "CREATE",
                        "kind": {"kind": "Pod"},
                        "object": self.raw()},
        }).encode()
        resp = h.handle(review)["response"]
        assert resp["allowed"] is True
        assert resp["patchType"] == "JSONPatch"
        ops = json.loads(base64.b64decode(resp["patch"]))
        assert {o["op"] for o in ops} == {"remove", "add"}

    def test_init_container_tpu_disqualifies(self):
        raw = self.raw()
        raw["spec"]["initContainers"] = [
            {"name": "warm", "resources": {
                "limits": {C.RESOURCE_TPU: "8"}}}]
        assert mesh_patch_ops(raw) is None

    def test_undecodable_pod_passes_mutate_only_path(self):
        """The cluster-wide pod mutating webhook must be fail-open: a
        pod whose quantities the subset codec cannot parse (e.g. 1Pi
        memory) is passed through unmutated, never denied.  Kinds with
        VALIDATORS stay fail-closed."""
        from nos_tpu.kube.webhook import AdmissionHandler

        raw = self.raw()
        raw["spec"]["containers"][0]["resources"]["limits"]["memory"] = "1Pi"
        h = AdmissionHandler(APIServer())
        h.register_mutating("Pod", mesh_patch_ops)
        resp = h.handle(json.dumps({
            "request": {"uid": "u", "kind": {"kind": "Pod"},
                        "object": raw},
        }).encode())["response"]
        assert resp["allowed"] is True

        from nos_tpu.api.elasticquota import validate_elastic_quota
        h2 = AdmissionHandler(APIServer())
        h2.register("ElasticQuota", validate_elastic_quota)
        resp2 = h2.handle(json.dumps({
            "request": {"uid": "u", "kind": {"kind": "ElasticQuota"},
                        "object": {"metadata": {"name": "q"},
                                   "spec": {"min": {"memory": "1Xi"}}}},
        }).encode())["response"]
        assert resp2["allowed"] is False

    def test_broken_mutator_does_not_block_the_write(self):
        from nos_tpu.kube.webhook import AdmissionHandler

        h = AdmissionHandler(APIServer())
        h.register_mutating("Pod", lambda raw: 1 / 0)
        resp = h.handle(json.dumps({
            "request": {"uid": "u", "kind": {"kind": "Pod"},
                        "object": self.raw()},
        }).encode())["response"]
        assert resp["allowed"] is True
        assert "patch" not in resp


class TestEndToEnd:
    def test_mesh_pod_gets_carved_and_binds(self):
        """The whole point: a chips+mesh pod on the in-memory substrate
        is normalized at create, the partitioner carves the shape, and
        the pod binds to the carved slice."""
        from test_e2e_slice import Harness

        h = Harness()
        install_mesh_normalization(h.api)
        h.agent.tick()

        h.api.create(KIND_POD, tpu_pod(4, mesh="2x2", name="meshy"))
        stored = h.api.get(KIND_POD, "meshy", "default")
        assert stored.spec.containers[0].resources.get(
            "nos.tpu/slice-2x2") == 1

        assert h.scheduler.run_cycle() == 0     # no 2x2 advertised yet
        h.advance(11.0)
        assert h.partitioner.process_if_ready()
        h.agent.tick()
        assert h.scheduler.run_cycle() == 1
        bound = h.api.get(KIND_POD, "meshy", "default")
        assert bound.spec.node_name == "host-0"
