"""Compute-side checkpoint/resume (orbax over sharded TrainState).

The scenario the capacity scheduler creates: a gang is preempted
(whole-gang eviction), the partitioner re-carves, and the job must
resume from its last step on a fresh process with a fresh mesh — the
restored state continues EXACTLY as the original would have."""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from nos_tpu.models.checkpoint import TrainCheckpointer
from nos_tpu.models.llama import TINY
from nos_tpu.models.train import ShardedTrainer
from nos_tpu.parallel.mesh import MeshSpec, make_mesh


@pytest.fixture
def trained():
    mesh = make_mesh(MeshSpec(fsdp=2, tp=2, sp=2))
    cfg = dataclasses.replace(TINY, attn_impl="ring")
    trainer = ShardedTrainer(cfg, mesh, batch_size=4, seq_len=64)
    state = trainer.init_state(0)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (4, 64), 0, cfg.vocab_size, jnp.int32)
    state, _ = trainer.train_step()(state, tokens)
    return cfg, trainer, state, tokens


class TestTrainCheckpointer:
    def test_resume_continues_identically(self, trained, tmp_path):
        cfg, trainer, state, tokens = trained
        ck = TrainCheckpointer(tmp_path)
        try:
            ck.save(int(state.step), state)
            assert ck.latest_step() == int(state.step)

            # a fresh process: new trainer, new mesh object, restore into
            # the ABSTRACT state (no materialized init paid at resume)
            trainer2 = ShardedTrainer(
                cfg, make_mesh(MeshSpec(fsdp=2, tp=2, sp=2)),
                batch_size=4, seq_len=64)
            restored = ck.restore(trainer2.abstract_state())
            assert int(restored.step) == int(state.step)

            # every leaf restored bit-identically
            import flax.linen as nn

            orig_leaves = jax.tree_util.tree_leaves(nn.meta.unbox(state))
            rest_leaves = jax.tree_util.tree_leaves(restored)
            assert len(orig_leaves) == len(rest_leaves)
            for a, b in zip(orig_leaves, rest_leaves):
                if hasattr(a, "shape"):
                    assert bool(jnp.array_equal(a, b))

            _, loss_orig = trainer.train_step()(state, tokens)
            _, loss_resumed = trainer2.train_step()(restored, tokens)
            assert float(loss_orig) == pytest.approx(
                float(loss_resumed), abs=1e-5)
        finally:
            ck.close()

    def test_restore_into_concrete_state_also_works(self, trained,
                                                     tmp_path):
        cfg, _, state, _ = trained
        ck = TrainCheckpointer(tmp_path)
        try:
            ck.save(int(state.step), state)
            trainer2 = ShardedTrainer(
                cfg, make_mesh(MeshSpec(fsdp=2, tp=2, sp=2)),
                batch_size=4, seq_len=64)
            restored = ck.restore(trainer2.init_state(seed=9))
            assert int(restored.step) == int(state.step)
        finally:
            ck.close()

    def test_restore_without_checkpoint_raises(self, tmp_path):
        ck = TrainCheckpointer(tmp_path)
        try:
            with pytest.raises(FileNotFoundError):
                ck.restore(state_like={"x": jnp.zeros(3)})
        finally:
            ck.close()

    def test_max_to_keep_prunes_old_steps(self, trained, tmp_path):
        _, _, state, _ = trained
        ck = TrainCheckpointer(tmp_path, max_to_keep=2)
        try:
            for step in (1, 2, 3):
                ck.save(step, state)
            assert ck.latest_step() == 3
            steps = set(ck._mngr.all_steps())
            assert 1 not in steps and {2, 3} <= steps
        finally:
            ck.close()
