"""Kubelet-phase split: the scheduler binds, the node agent admits.

In Kubernetes the scheduler writes only the binding; the kubelet reports
phase=Running.  Round-2 review flagged that conflating the two in the
scheduler would inflate PDB current_healthy / gang liveness against a real
substrate — these tests pin the split (nos_tpu/controllers/kubelet.py).
"""

from __future__ import annotations

from nos_tpu.api import constants as C
from nos_tpu.controllers.kubelet import admit_bound_pods
from nos_tpu.kube.client import APIServer, KIND_NODE, KIND_POD
from nos_tpu.kube.objects import PENDING, RUNNING
from nos_tpu.scheduler.framework import Framework, NodeResourcesFit
from nos_tpu.scheduler.scheduler import Scheduler
from nos_tpu.testing.factory import make_node, make_pod


def make_cluster():
    api = APIServer()
    api.create(KIND_NODE, make_node(
        "node-0", allocatable={"cpu": 8.0, C.RESOURCE_TPU: 8.0}))
    return api, Scheduler(api, Framework([NodeResourcesFit()]))


def test_scheduler_binds_without_claiming_running():
    api, sched = make_cluster()
    api.create(KIND_POD, make_pod(name="p", resources={C.RESOURCE_TPU: 4}))
    assert sched.run_cycle() == 1
    pod = api.get(KIND_POD, "p", "default")
    assert pod.spec.node_name == "node-0"
    assert pod.status.phase == PENDING   # kubelet's claim, not ours


def test_admit_transitions_only_bound_pods_on_node():
    api, sched = make_cluster()
    api.create(KIND_POD, make_pod(name="p", resources={C.RESOURCE_TPU: 4}))
    api.create(KIND_POD, make_pod(name="q", resources={C.RESOURCE_TPU: 16}))
    sched.run_cycle()
    assert admit_bound_pods(api, "node-0") == 1
    assert api.get(KIND_POD, "p", "default").status.phase == RUNNING
    # unbound pod untouched; second admit is a no-op
    assert api.get(KIND_POD, "q", "default").status.phase == PENDING
    assert admit_bound_pods(api, "node-0") == 0


def test_admit_declines_on_non_sim_substrate():
    class NotTheSim:  # a real-substrate client is not an APIServer
        pass

    assert admit_bound_pods(NotTheSim(), "node-0") == 0
