"""Kubelet-phase split: the scheduler binds, the node agent admits.

In Kubernetes the scheduler writes only the binding; the kubelet reports
phase=Running.  Round-2 review flagged that conflating the two in the
scheduler would inflate PDB current_healthy / gang liveness against a real
substrate — these tests pin the split (nos_tpu/controllers/kubelet.py).
"""

from __future__ import annotations

from nos_tpu.api import constants as C
from nos_tpu.controllers.kubelet import admit_bound_pods
from nos_tpu.kube.client import APIServer, KIND_NODE, KIND_POD
from nos_tpu.kube.objects import PENDING, RUNNING
from nos_tpu.scheduler.framework import Framework, NodeResourcesFit
from nos_tpu.scheduler.scheduler import Scheduler
from nos_tpu.testing.factory import make_node, make_pod


def make_cluster():
    api = APIServer()
    api.create(KIND_NODE, make_node(
        "node-0", allocatable={"cpu": 8.0, C.RESOURCE_TPU: 8.0}))
    return api, Scheduler(api, Framework([NodeResourcesFit()]))


def test_scheduler_binds_without_claiming_running():
    api, sched = make_cluster()
    api.create(KIND_POD, make_pod(name="p", resources={C.RESOURCE_TPU: 4}))
    assert sched.run_cycle() == 1
    pod = api.get(KIND_POD, "p", "default")
    assert pod.spec.node_name == "node-0"
    assert pod.status.phase == PENDING   # kubelet's claim, not ours


def test_admit_transitions_only_bound_pods_on_node():
    api, sched = make_cluster()
    api.create(KIND_POD, make_pod(name="p", resources={C.RESOURCE_TPU: 4}))
    api.create(KIND_POD, make_pod(name="q", resources={C.RESOURCE_TPU: 16}))
    sched.run_cycle()
    assert admit_bound_pods(api, "node-0") == 1
    assert api.get(KIND_POD, "p", "default").status.phase == RUNNING
    # unbound pod untouched; second admit is a no-op
    assert api.get(KIND_POD, "q", "default").status.phase == PENDING
    assert admit_bound_pods(api, "node-0") == 0


def test_admit_declines_on_non_sim_substrate():
    class NotTheSim:  # a real-substrate client is not an APIServer
        pass

    assert admit_bound_pods(NotTheSim(), "node-0") == 0


def test_admit_skips_slice_pods_when_asked():
    """Hybrid nodes: the ChipAgent's bare phase transition must leave
    slice pods to the sliceagent's device-backed admission (ADVICE r3)."""
    from nos_tpu.testing.factory import make_slice_pod

    api, sched = make_cluster()
    api.create(KIND_POD, make_pod(name="ts", resources={C.RESOURCE_TPU: 1},
                                  node_name="node-0"))
    api.create(KIND_POD, make_slice_pod("2x2", 1, name="sl",
                                        node_name="node-0"))
    assert admit_bound_pods(api, "node-0", skip_slice_pods=True) == 1
    assert api.get(KIND_POD, "ts", "default").status.phase == RUNNING
    assert api.get(KIND_POD, "sl", "default").status.phase == PENDING


def test_watch_events_deliver_in_store_commit_order():
    """A watch callback that writes back (KubeletSim's phase patch) must
    not let later-registered watchers see the nested event before the
    one that caused it — the FIFO bus (ADVICE r3): every watcher
    observes the same store-commit order."""
    from nos_tpu.kube.client import APIServer

    api = APIServer()

    def reactor(event, pod):
        # first watcher: on seeing a bound Pending pod, immediately
        # patch it Running (a nested write from inside the callback)
        if event != "DELETED" and pod.status.phase == PENDING \
                and pod.spec.node_name:
            def mutate(p):
                p.status.phase = RUNNING
            api.patch(KIND_POD, pod.metadata.name, pod.metadata.namespace,
                      mutate=mutate)

    seen: list[tuple[str, str]] = []
    api.watch(KIND_POD, reactor)
    api.watch(KIND_POD, lambda ev, p: seen.append((ev, p.status.phase)))

    api.create(KIND_POD, make_pod(name="w", node_name="node-0"))
    # the later watcher must see ADDED(Pending) BEFORE MODIFIED(Running):
    # out-of-order delivery would let a cache overwrite new state with
    # the stale outer payload
    assert seen == [("ADDED", PENDING), ("MODIFIED", RUNNING)]
