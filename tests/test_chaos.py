"""Chaos soak + failure-domain tests (the robustness acceptance gate).

Seeded `ChaosAPIServer` runs drive the full slice e2e path — node init,
repartition plan, agent actuation, scheduling, kubelet admission — while
conflicts, transient write errors, and watch-event drops are injected on
every update/patch.  Each run must converge to spec==status on every
node with all pods Running and ZERO exceptions escaping the (simulated)
run loops; a failure prints the seed for one-command repro via
`python scripts/diag_chaos.py --seed N`.

Also here: the plan-deadline quarantine state machine (kill an agent
mid-plan, assert the controller quarantines it and still replans the
surviving nodes — docs/protocol.md, "Plan deadline and quarantine")
and retry exhaustion.
"""

from __future__ import annotations

import random
from types import SimpleNamespace

import pytest

from nos_tpu import obs
from nos_tpu.obs import slo as slo_mod
from nos_tpu.controllers.node_controller import NodeController
from nos_tpu.controllers.pod_controller import PodController
from nos_tpu.controllers.sliceagent.agent import SliceAgent
from nos_tpu.device.fake import FakePodResources, FakeTpuRuntime
from nos_tpu.exporter.metrics import REGISTRY
from nos_tpu.kube.client import Conflict, KIND_NODE, KIND_POD
from nos_tpu.kube.objects import RUNNING
from nos_tpu.partitioning.core import REASON_PLAN_DEADLINE
from nos_tpu.partitioning.slicepart import SliceNodeInitializer
from nos_tpu.partitioning.slicepart.factory import (
    new_slice_partitioner_controller,
)
from nos_tpu.partitioning.state import ClusterState
from nos_tpu.scheduler.framework import Framework, NodeResourcesFit
from nos_tpu.scheduler.gang import TopologyFilter
from nos_tpu.scheduler.scheduler import Scheduler
from nos_tpu.testing.chaos import ChaosAPIServer
from nos_tpu.testing.lockcheck import LockGraph, guard_state, unguard_all
from nos_tpu.testing.factory import make_slice_pod, make_tpu_node
from nos_tpu.topology import V5E
from nos_tpu.topology.annotations import (
    spec_matches_status, spec_plan_id, status_plan_id,
)
from nos_tpu.utils import retry as retry_mod


@pytest.fixture(autouse=True)
def fast_retry(monkeypatch):
    """Injected faults retry instantly — the soak exercises the retry
    *logic* hundreds of times; real backoff sleeps belong in prod."""
    monkeypatch.setattr(retry_mod, "sleep", lambda s: None)


# The acceptance gate: 25+ seeded runs, all faults on, all converge.
TIER1_SEEDS = range(25)
DEEP_SEEDS = range(25, 125)

BATCH_TIMEOUT_S = 60.0


def run_slice_soak(seed: int, hosts: int = 2, pods: int = 3,
                   max_rounds: int = 80,
                   conflict_rate: float = 0.15,
                   transient_rate: float = 0.10,
                   drop_watch_rate: float = 0.10) -> SimpleNamespace:
    """One seeded chaos run over the full slice e2e path.  Single
    thread, injected clock: deterministic per seed."""
    # Every lock constructed below (APIServer bus, agents' SharedState,
    # kubelet sims) is lockdep-instrumented: a lock-order inversion or an
    # unguarded SharedState write anywhere in the soak fails the seed
    # (nos_tpu/testing/lockcheck.py; docs/static-analysis.md).
    lock_graph = LockGraph(name=f"soak-seed-{seed}")
    with lock_graph.install():
        api = ChaosAPIServer(seed, conflict_rate=conflict_rate,
                             transient_rate=transient_rate,
                             drop_watch_rate=drop_watch_rate,
                             replay_after_ops=5)
        state = ClusterState()
    clock = [0.0]
    errors: list[str] = []

    def tick(name, fn):
        """RunLoop analog: a raising tick is THE failure being hunted."""
        try:
            fn()
        except Exception as e:  # noqa: BLE001 — recorded, then asserted on
            errors.append(f"seed={seed} round={round_no} {name}: {e!r}")

    # The whole control plane is constructed under install() so every
    # lock it builds (quarantine list, framework, cluster-state, agents'
    # KubeletSim/SharedState) joins the acquisition graph — not just the
    # APIServer bus.
    with lock_graph.install():
        NodeController(api, state, SliceNodeInitializer(api)).bind()
        PodController(api, state).bind()
        partitioner = new_slice_partitioner_controller(
            api, state, batch_timeout_s=BATCH_TIMEOUT_S, batch_idle_s=10.0,
            clock=lambda: clock[0])
        partitioner.bind()
        agents = []
        round_no = -1  # node creation fires watch callbacks via tick-less paths
        for i in range(hosts):
            api.create(KIND_NODE, make_tpu_node(
                f"host-{i}", pod_id="pod-0", host_index=i))
            agent = SliceAgent(api, f"host-{i}", FakeTpuRuntime(V5E),
                               FakePodResources())
            # guard the handshake state: any field write without _lock
            # held is a soak failure
            guard_state(agent.shared, lock_graph,
                        name="sliceagent.SharedState")
            agent.start()
            agents.append(agent)
        scheduler = Scheduler(
            api, Framework([NodeResourcesFit(), TopologyFilter(api)]))
        # Observability instrumented under the SAME lockdep install
        # window: the tracer ring's and journal's locks join the
        # acquisition graph, so tracing/journaling adding a lock-order
        # edge anywhere in the decision plane fails the seed.
        tracer = obs.Tracer(clock=lambda: clock[0],
                            ring=obs.RingExporter(maxlen=256))
        journal = obs.DecisionJournal(maxlen=256, clock=lambda: clock[0])
        # @guarded_by contracts, dynamically: guard_state reads each
        # class's __guarded_by__ table (nos_tpu/utils/guards.py) — the
        # SAME declaration noslint N010 proves statically — and convicts
        # any runtime write to a declared field without its lock held.
        guard_state(state, lock_graph, name="partitioning.ClusterState")
        guard_state(partitioner.quarantine, lock_graph,
                    name="core.QuarantineList")
        guard_state(journal, lock_graph, name="obs.DecisionJournal")
        if scheduler._cache is not None:
            guard_state(scheduler._cache, lock_graph,
                        name="scheduler.SchedulerCache")
        # SLO plane under the same window: the sampler's ring lock joins
        # the graph, so its leaf-lock contract (tick computes the
        # registry snapshot BEFORE its own lock) is verified, not
        # assumed — a sampler that nested the registry lock under its
        # ring lock would fail every seed here.
        sampler = obs.TimeSeriesSampler(maxlen=64,
                                        clock=lambda: clock[0])
        slo_engine = obs.SLOEngine(
            sampler, slo_mod.default_objectives(),
            fast_window_s=BATCH_TIMEOUT_S,
            slow_window_s=3 * BATCH_TIMEOUT_S,
            clock=lambda: clock[0])
        guard_state(sampler, lock_graph, name="obs.TimeSeriesSampler")
        # Chip-second ledger under the same window: its leaf-lock
        # contract (holds/observe touch only its own lock) is verified
        # like the sampler's, and every seed asserts the conservation
        # invariant over the chaotic run afterwards.
        ledger = obs.ChipSecondLedger(clock=lambda: clock[0])
        guard_state(ledger, lock_graph, name="obs.ChipSecondLedger")

    # 2x2 pods: hosts*2 fit, demand stays below capacity so convergence
    # is always feasible
    assert pods <= hosts * 2
    for i in range(pods):
        api.create(KIND_POD, make_slice_pod("2x2", 1, name=f"soak-{i}"))

    def converged() -> bool:
        for p in api.list(KIND_POD):
            if not p.spec.node_name or p.status.phase != RUNNING:
                return False
        return all(
            spec_matches_status(n.metadata.annotations)
            for n in api.list(KIND_NODE))

    done = False
    with obs.scoped(tracer, journal, engine=slo_engine, ledger=ledger):
        for round_no in range(max_rounds):
            clock[0] += BATCH_TIMEOUT_S + 1.0
            tick("scheduler", scheduler.run_cycle)
            tick("partitioner", partitioner.process_if_ready)
            for i, agent in enumerate(agents):
                tick(f"agent-{i}", agent.tick)
            tick("slo", slo_engine.tick)
            api.replay_dropped()        # the round's watch "reconnect"
            if converged():
                done = True
                break
        # one more cycle after convergence: the ledger accrues between
        # observes, so the final (all-productive) waterfall needs a
        # successor observation or the converged interval never lands
        # in the integrals the conservation assert reads
        clock[0] += BATCH_TIMEOUT_S + 1.0
        tick("scheduler-final", scheduler.run_cycle)
    return SimpleNamespace(api=api, errors=errors, converged=done,
                           rounds=round_no + 1, seed=seed, hosts=hosts,
                           quarantined=partitioner.quarantine.names(),
                           lock_graph=lock_graph,
                           tracer=tracer, journal=journal,
                           sampler=sampler, slo_engine=slo_engine,
                           ledger=ledger)


def _assert_soak_ok(result) -> None:
    repro = f"repro: python scripts/diag_chaos.py --seed {result.seed}"
    assert not result.errors, (result.errors[:3], repro)
    # lockdep verdict: order inversions / unguarded SharedState writes
    # observed anywhere in the run fail the seed
    try:
        result.lock_graph.assert_clean()
    finally:
        result.lock_graph.close()
        unguard_all()   # restore SharedState's patched __setattr__
    assert result.converged, (
        f"seed {result.seed} did not converge in {result.rounds} rounds "
        f"(stats {result.api.stats}, quarantined {result.quarantined}); "
        + repro)
    # Journal/tracing invariants under chaos: bounded memory, a strictly
    # increasing total order, and a flight recording that actually
    # captured the run (every converged soak binds pods and runs plans).
    journal = result.journal
    assert len(journal) <= journal.maxlen, repro
    assert len(result.tracer.ring) <= result.tracer.ring.maxlen, repro
    seqs = [r.seq for r in journal.events()]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs), repro
    from nos_tpu.obs import journal as J
    cats = {r.category for r in journal.events()}
    # eviction may have dropped early categories on busy seeds; bounds
    # are the invariant — but a converged run must at least have bound
    # pods or have everything evicted (dropped > 0)
    assert (J.POD_BOUND in cats) or journal.dropped > 0, (cats, repro)
    span_names = {s["name"] for s in result.tracer.ring.dump()}
    assert "scheduler.run_cycle" in span_names, repro
    # SLO sampler invariants under chaos: bounded ring, one point per
    # soak round (the engine ticked every round without raising)
    assert len(result.sampler) <= result.sampler.maxlen, repro
    assert len(result.sampler) == min(result.rounds,
                                      result.sampler.maxlen), repro
    # Chip-second ledger invariants under chaos: the scheduler observed
    # the fleet every cycle, per-pool conservation holds over the whole
    # chaotic run (Σ categories == ∫ capacity dt within ε), and the
    # hold map stays bounded by the cluster (two planes x a few hold
    # kinds per node, never growth with rounds).
    from nos_tpu.obs.ledger import conservation_ok
    waste = result.ledger.report()
    assert waste["pools"], ("ledger observed no pools", repro)
    assert conservation_ok(waste), (
        {p: v["conservation_delta"]
         for p, v in waste["pools"].items()}, repro)
    assert waste["fleet"]["chip_seconds"].get("productive", 0.0) > 0.0, \
        (waste["fleet"], repro)
    assert result.ledger.hold_count() <= result.hosts * 6, repro


class TestChaosSoak:
    @pytest.mark.parametrize("seed", TIER1_SEEDS)
    def test_soak_converges(self, seed):
        result = run_slice_soak(seed)
        _assert_soak_ok(result)
        # the run must actually have been chaotic, not a lucky no-fault
        # walk — otherwise the gate asserts nothing
        stats = result.api.stats
        assert stats["conflicts"] + stats["transients"] + stats["drops"] > 0

    @pytest.mark.chaos
    @pytest.mark.slow
    @pytest.mark.parametrize("seed", DEEP_SEEDS)
    def test_soak_deep(self, seed):
        _assert_soak_ok(run_slice_soak(seed, hosts=3, pods=5,
                                       drop_watch_rate=0.2))

    def test_replay_mid_drain_is_deferred(self):
        """White-box: replay_dropped landing inside an active _notify
        drain must keep events withheld — direct delivery would hand
        the dropped watcher newer state before older queued events."""
        api = ChaosAPIServer(0, drop_watch_rate=1.0, replay_after_ops=1000)
        seen = []
        api.watch(KIND_NODE, lambda ev, obj: seen.append(ev))
        from nos_tpu.testing.factory import make_tpu_node as mk
        api.create(KIND_NODE, mk("h0"))      # ADDED dropped (rate=1.0)
        assert api._dropped and not seen
        api._delivering = True               # simulate an active drain
        api.replay_dropped()
        assert api._dropped and not seen     # deferred, still withheld
        api._delivering = False
        api.replay_dropped()
        assert not api._dropped and seen == ["MODIFIED"]

    def test_same_seed_same_fault_sequence(self):
        a = run_slice_soak(7)
        b = run_slice_soak(7)
        assert a.api.stats == b.api.stats
        assert a.rounds == b.rounds


class _Cluster:
    """Fault-free control plane over N hosts with individually killable
    agents (chaos rates zero: these tests inject failure by *silence*)."""

    def __init__(self, hosts=2):
        self.api = ChaosAPIServer(0)
        self.state = ClusterState()
        self.clock = [0.0]
        NodeController(self.api, self.state,
                       SliceNodeInitializer(self.api)).bind()
        PodController(self.api, self.state).bind()
        self.partitioner = new_slice_partitioner_controller(
            self.api, self.state, batch_timeout_s=BATCH_TIMEOUT_S,
            batch_idle_s=10.0, clock=lambda: self.clock[0])
        self.partitioner.bind()
        self.agents = {}
        for i in range(hosts):
            name = f"host-{i}"
            self.api.create(KIND_NODE, make_tpu_node(
                name, pod_id="pod-0", host_index=i))
            self.agents[name] = SliceAgent(
                self.api, name, FakeTpuRuntime(V5E), FakePodResources())
            self.agents[name].start()
            self.agents[name].tick()
        self.scheduler = Scheduler(self.api, Framework())

    def demand(self, shape, qty, name):
        self.api.create(KIND_POD, make_slice_pod(shape, qty, name=name))
        self.scheduler.run_cycle()

    def plan_cycle(self):
        self.clock[0] += BATCH_TIMEOUT_S + 1.0
        return self.partitioner.process_if_ready()

    def node(self, name):
        return self.api.get(KIND_NODE, name)

    def planned_nodes(self):
        """Nodes with an open handshake (spec plan != status plan)."""
        out = []
        for n in self.api.list(KIND_NODE):
            annots = n.metadata.annotations
            if spec_plan_id(annots) and \
                    status_plan_id(annots) != spec_plan_id(annots):
                out.append(n.metadata.name)
        return out


class TestHandshakeDeadline:
    def test_dead_agent_is_quarantined_and_survivors_replan(self):
        c = _Cluster(hosts=2)
        quarantine = c.partitioner.quarantine

        # a plan lands on one host; its agent dies before actuating
        c.demand("2x2", 1, "want-a")
        assert c.plan_cycle()
        dead = c.planned_nodes()
        assert len(dead) == 1
        dead = dead[0]
        alive = next(n for n in c.agents if n != dead)
        dead_plan = spec_plan_id(c.node(dead).metadata.annotations)
        alive_plan = spec_plan_id(c.node(alive).metadata.annotations)

        # new demand: the handshake is open, so the first ready batch
        # only arms the deadline...
        c.demand("2x2", 1, "want-b")
        assert not c.plan_cycle()
        assert not quarantine.is_quarantined(dead)

        # ...and once the deadline passes, the laggard is quarantined
        # and the SAME call replans the surviving node
        c.clock[0] += 3 * BATCH_TIMEOUT_S + 1.0
        assert c.plan_cycle()
        assert quarantine.is_quarantined(dead)
        assert quarantine.reason(dead) == REASON_PLAN_DEADLINE
        # survivor got a fresh plan; the dead node's spec is untouched
        new_alive_plan = spec_plan_id(c.node(alive).metadata.annotations)
        assert new_alive_plan and new_alive_plan != alive_plan
        assert alive in c.planned_nodes()
        assert spec_plan_id(c.node(dead).metadata.annotations) == dead_plan
        snap = REGISTRY.snapshot()
        assert snap["nos_tpu_plan_deadline_exceeded_total"]["kind=slice"] >= 1

        # the quarantined node is OUT of the snapshot until it reports
        assert dead in c.partitioner.quarantine.names()

        # the agent comes back and reports: auto-unquarantine on the
        # next poll, node rejoins planning
        c.agents[dead].tick()
        assert spec_matches_status(c.node(dead).metadata.annotations)
        c.partitioner.process_if_ready()
        assert not quarantine.is_quarantined(dead)
        assert snap_gauge("nos_tpu_quarantined_nodes", "kind=slice") == 0.0

    def test_demand_survives_total_quarantine(self):
        """Regression: quarantining the LAST node of a kind used to
        drain the batch into an empty snapshot, stranding the pending
        pods until unrelated pod churn re-fed the batcher."""
        c = _Cluster(hosts=1)
        c.demand("2x2", 1, "a")
        assert c.plan_cycle()           # plan lands; agent never ticks
        c.demand("1x1", 1, "b")
        assert not c.plan_cycle()       # handshake open: arms deadline
        c.clock[0] += 3 * BATCH_TIMEOUT_S + 1.0
        # quarantined -> snapshot empty -> batch must be KEPT
        assert not c.partitioner.process_if_ready()
        assert c.partitioner.quarantine.is_quarantined("host-0")

        # the agent recovers and reports; with NO new pod events the
        # restored batch must replan the recovered node (its window
        # restarted at the restore, so advance past it again)
        old_plan = spec_plan_id(c.node("host-0").metadata.annotations)
        c.agents["host-0"].tick()
        assert c.plan_cycle()
        assert not c.partitioner.quarantine.is_quarantined("host-0")
        assert spec_plan_id(
            c.node("host-0").metadata.annotations) != old_plan

    def test_deadline_rearms_per_plan(self):
        """A node lagging on plan A, then reporting, then lagging on
        plan B gets a FRESH deadline for B — the timer is per-plan, not
        cumulative."""
        c = _Cluster(hosts=1)
        c.demand("2x2", 1, "a")
        assert c.plan_cycle()
        assert c.planned_nodes() == ["host-0"]
        # lag half a deadline, then report
        c.demand("1x1", 1, "b")
        assert not c.plan_cycle()       # arms deadline for plan A
        c.clock[0] += 1.5 * BATCH_TIMEOUT_S
        c.agents["host-0"].tick()       # reports plan A
        # plan B lands; half a deadline later the node must NOT be
        # quarantined (fresh timer), a full deadline later it must be
        assert c.plan_cycle()
        c.demand("1x2", 1, "c")
        assert not c.plan_cycle()       # arms deadline for plan B
        c.clock[0] += 1.5 * BATCH_TIMEOUT_S
        c.partitioner.process_if_ready()
        assert not c.partitioner.quarantine.is_quarantined("host-0")
        c.clock[0] += 2.0 * BATCH_TIMEOUT_S
        c.partitioner.process_if_ready()
        assert c.partitioner.quarantine.is_quarantined("host-0")

    def test_handshake_wait_journal_records_transitions_only(self):
        """The handshake-wait journal records the lagging-set
        TRANSITIONS — including the empty one (the operator reading the
        newest record must see the wait resolved, not a stale node
        list), and a node quarantined this tick is excluded (it no
        longer blocks the handshake)."""
        from nos_tpu.obs import journal as J

        c = _Cluster(hosts=2)
        journal = obs.DecisionJournal(maxlen=64,
                                      clock=lambda: c.clock[0])
        with obs.scoped(journal=journal):
            c.demand("2x2", 1, "want-a")
            assert c.plan_cycle()           # plan lands; agents dead
            lagging = sorted(c.planned_nodes())
            c.demand("2x2", 1, "want-b")
            assert not c.plan_cycle()       # handshake open: arms
            waits = journal.events(category=J.HANDSHAKE_WAIT)
            assert waits, "open handshake did not journal a transition"
            assert waits[-1].attrs["lagging"] == lagging
            assert waits[-1].attrs["lagging_count"] == len(lagging)
            n_waits = len(waits)
            # steady state: another blocked tick is NOT a new decision
            assert not c.plan_cycle()
            assert len(journal.events(
                category=J.HANDSHAKE_WAIT)) == n_waits
            # deadline passes: the laggards are quarantined and stop
            # blocking — the SAME tick journals the empty transition
            c.clock[0] += 3 * BATCH_TIMEOUT_S + 1.0
            c.partitioner.process_if_ready()
            waits = journal.events(category=J.HANDSHAKE_WAIT)
            assert waits[-1].attrs["lagging"] == []
            assert waits[-1].attrs["lagging_count"] == 0
            for name in lagging:
                assert c.partitioner.quarantine.is_quarantined(name)


class TestRescanBackstop:
    def test_lost_trigger_is_replanned_by_rescan(self):
        """Against a real apiserver a pod's repeated unschedulable
        re-mark is a no-op write emitting NO watch event: if the batch
        carrying the pod's only event is consumed by a plan that could
        not help it, only the level-triggered rescan can save it."""
        c = _Cluster(hosts=1)
        before = spec_plan_id(c.node("host-0").metadata.annotations)
        c.demand("2x2", 1, "a")
        # simulate the trigger loss: the batch vanishes unconsummated
        c.partitioner._batcher.drain()
        assert not c.partitioner._batcher.ready()
        c.clock[0] += BATCH_TIMEOUT_S + 1.0
        assert c.partitioner.process_if_ready()   # rescan plans anyway
        after = spec_plan_id(c.node("host-0").metadata.annotations)
        assert after and after != before

    def test_rescan_is_idle_without_pending_demand(self):
        c = _Cluster(hosts=1)
        c.clock[0] += 10 * BATCH_TIMEOUT_S
        assert not c.partitioner.process_if_ready()

    def test_rescan_defers_to_an_accumulating_batch(self):
        """A fresh not-yet-ready batch already carries a live trigger:
        the rescan must not preempt its idle/timeout accumulation
        windows and plan with half a demand wave."""
        c = _Cluster(hosts=1)
        c.clock[0] += 10 * BATCH_TIMEOUT_S      # rescan long overdue
        c.demand("2x2", 1, "a")                 # batch starts filling
        c.clock[0] += 1.0                       # inside idle window
        assert not c.partitioner.process_if_ready()
        c.clock[0] += 11.0                      # idle window elapses
        assert c.partitioner.process_if_ready()


def snap_gauge(name: str, series: str) -> float:
    return REGISTRY.snapshot().get(name, {}).get(series, 0.0)


class TestRetrySubstrate:
    def test_retry_recovers_from_conflicts(self):
        api = ChaosAPIServer(3, conflict_rate=0.5, transient_rate=0.2)
        api.create(KIND_NODE, make_tpu_node("n1"))
        retry_mod.retry_on_conflict(
            api, KIND_NODE, "n1",
            lambda n: n.metadata.annotations.__setitem__("x", "1"),
            component="test", attempts=100)
        assert api.get(KIND_NODE, "n1").metadata.annotations["x"] == "1"

    def test_retry_exhausted_raises_and_counts(self):
        api = ChaosAPIServer(4, conflict_rate=1.0)
        api.create(KIND_NODE, make_tpu_node("n1"))
        before = snap_gauge("nos_tpu_retry_exhausted_total",
                            "component=exhaust-test")
        with pytest.raises(Conflict):
            retry_mod.retry_on_conflict(
                api, KIND_NODE, "n1",
                lambda n: n.metadata.annotations.__setitem__("x", "1"),
                component="exhaust-test", attempts=4)
        assert "x" not in api.get(KIND_NODE, "n1").metadata.annotations
        assert snap_gauge("nos_tpu_retry_exhausted_total",
                          "component=exhaust-test") == before + 1
        assert snap_gauge("nos_tpu_retry_total",
                          "component=exhaust-test") >= 4

    def test_transient_api_errors_are_retried(self):
        """5xx/429 from a real apiserver arrive as TransientAPIError
        (kube/rest.py) and must ride the same retry path as Conflict."""
        from nos_tpu.kube.client import TransientAPIError

        calls = []

        class _FlakyApi:
            def patch(self, kind, name, namespace="", *, mutate):
                calls.append(name)
                if len(calls) < 3:
                    raise TransientAPIError("HTTP 503: apiserver rolling")
                return "ok"

        assert retry_mod.retry_on_conflict(
            _FlakyApi(), KIND_NODE, "n1", lambda n: None,
            component="t503") == "ok"
        assert len(calls) == 3

    def test_backoff_caps_and_resets(self):
        b = retry_mod.Backoff(base_s=0.1, cap_s=1.0, jitter=0.0)
        delays = [b.next_delay() for _ in range(8)]
        assert delays[0] == pytest.approx(0.1)
        assert delays[-1] == 1.0 and max(delays) == 1.0
        b.reset()
        assert b.next_delay() == pytest.approx(0.1)

    def test_jitter_stays_below_raw_delay(self):
        b = retry_mod.Backoff(base_s=1.0, cap_s=1.0, jitter=0.5,
                              rng=random.Random(1))
        for _ in range(50):
            assert 0.5 <= b.next_delay() <= 1.0


class TestChaosMechanics:
    def test_dropped_watch_event_is_replayed_at_current_state(self):
        api = ChaosAPIServer(1, drop_watch_rate=1.0, replay_after_ops=1000)
        seen = []
        api.watch(KIND_NODE, lambda ev, o: seen.append(
            (ev, o.metadata.name, dict(o.metadata.annotations))))
        api.create(KIND_NODE, make_tpu_node("n1"))
        api.patch(KIND_NODE, "n1",
                  mutate=lambda n: n.metadata.annotations.__setitem__(
                      "k", "v2"))
        assert seen == []               # everything withheld
        api.replay_dropped()
        # replay delivers the CURRENT state once per drop, not the
        # stale intermediates
        assert all(ann.get("k") == "v2" for _, _, ann in seen)

    def test_dropped_delete_replays_as_deleted(self):
        api = ChaosAPIServer(1, drop_watch_rate=1.0, replay_after_ops=1000)
        api.create(KIND_NODE, make_tpu_node("n1"))
        seen = []
        api.watch(KIND_NODE, lambda ev, o: seen.append((ev, o.metadata.name)))
        api.delete(KIND_NODE, "n1")
        api.replay_dropped()
        assert ("DELETED", "n1") in seen

    def test_faults_are_scoped_to_fault_kinds(self):
        api = ChaosAPIServer(2, conflict_rate=1.0, fault_kinds={"Pod"})
        api.create(KIND_NODE, make_tpu_node("n1"))
        api.patch(KIND_NODE, "n1",
                  mutate=lambda n: n.metadata.labels.__setitem__("a", "b"))
        pod = make_slice_pod("2x2", 1, name="p")
        api.create(KIND_POD, pod)
        with pytest.raises(Conflict):
            api.patch(KIND_POD, "p", pod.metadata.namespace,
                      mutate=lambda p: None)
