"""Input pipeline tests: deterministic epoch coverage, resume addressing,
memmap loading, sharded device feeding."""

from __future__ import annotations

import numpy as np
import pytest

from nos_tpu.models.data import TokenLoader
from nos_tpu.parallel.mesh import MeshSpec, make_mesh


class TestTokenLoader:
    def test_epoch_covers_every_window_exactly_once(self):
        # collision-free stream: token value == position, so a row's
        # first token identifies its window uniquely
        tokens = np.arange(16 * 64, dtype=np.int32)
        loader = TokenLoader(tokens, batch_size=4, seq_len=16)
        assert loader.steps_per_epoch == 16
        seen = []
        for step in range(loader.steps_per_epoch):
            batch = loader.batch_at(step)
            assert batch.shape == (4, 16)
            assert batch.dtype == np.int32
            seen.extend((batch[:, 0] // 16).tolist())
        # exactly-once: the multiset of window indices IS the full range
        assert sorted(seen) == list(range(loader.windows_per_epoch))

    def test_deterministic_and_epochs_differ(self):
        a = TokenLoader.synthetic(97, 2048, batch_size=4, seq_len=16, seed=3)
        b = TokenLoader.synthetic(97, 2048, batch_size=4, seq_len=16, seed=3)
        assert np.array_equal(a.batch_at(5), b.batch_at(5))
        e0 = [a.batch_at(s) for s in range(a.steps_per_epoch)]
        e1 = [a.batch_at(s + a.steps_per_epoch)
              for s in range(a.steps_per_epoch)]
        assert not all(np.array_equal(x, y) for x, y in zip(e0, e1))

    def test_resume_addressing_matches_uninterrupted(self):
        loader = TokenLoader.synthetic(97, 4096, batch_size=2, seq_len=32)
        full = [b for _, b in zip(range(10), loader.batches(0))]
        resumed = [b for _, b in zip(range(4), loader.batches(6))]
        for want, got in zip(full[6:], resumed):
            assert np.array_equal(want, got)

    def test_memmap_round_trip(self, tmp_path):
        tokens = np.arange(1024, dtype=np.uint16)
        path = tmp_path / "corpus.bin"
        tokens.tofile(path)
        loader = TokenLoader.from_memmap(path, batch_size=2, seq_len=64)
        batch = loader.batch_at(0)
        assert batch.shape == (2, 64)
        # rows are contiguous 64-token windows of the arange stream
        for row in batch:
            assert np.array_equal(row, np.arange(row[0], row[0] + 64))

    def test_too_small_stream_rejected(self):
        with pytest.raises(ValueError, match="fewer"):
            TokenLoader.synthetic(7, 100, batch_size=8, seq_len=64)

    def test_device_iter_sharded_and_prefetched(self):
        import jax

        mesh = make_mesh(MeshSpec(fsdp=2, tp=2, sp=2))
        loader = TokenLoader.synthetic(97, 8192, batch_size=4, seq_len=64)
        got = list(loader.device_iter(mesh=mesh, num_steps=3))
        assert len(got) == 3
        for i, batch in enumerate(got):
            assert isinstance(batch, jax.Array)
            assert batch.shape == (4, 64)
            assert "fsdp" in str(batch.sharding.spec)
            assert np.array_equal(np.asarray(batch), loader.batch_at(i))


    def test_feeds_the_sharded_trainer_end_to_end(self):
        import dataclasses

        import jax.numpy as jnp

        from nos_tpu.models.llama import TINY
        from nos_tpu.models.train import ShardedTrainer

        mesh = make_mesh(MeshSpec(fsdp=2, tp=2, sp=2))
        cfg = dataclasses.replace(TINY, attn_impl="ring")
        trainer = ShardedTrainer(cfg, mesh, batch_size=4, seq_len=64)
        state = trainer.init_state(0)
        step = trainer.train_step()
        loader = TokenLoader.synthetic(
            cfg.vocab_size, 64 * 64, batch_size=4, seq_len=64)
        for batch in loader.device_iter(mesh=mesh, num_steps=2):
            state, loss = step(state, batch)
            assert bool(jnp.isfinite(loss))
