"""A kube-apiserver-shaped HTTP stub for substrate contract tests.

Implements the REST subset KubeClient (nos_tpu/kube/rest.py) speaks:
typed collection/object paths, POST/GET/PUT/DELETE, labelSelector on
list, optimistic concurrency (409 on stale resourceVersion), and
?watch=true streaming of JSON-line events.  State is a plain dict of raw
k8s JSON objects — deliberately NOT the nos_tpu object model, so the
codec is exercised for real.

Real-apiserver awkwardness deliberately simulated (the informer must
survive all of it — VERDICT r3 missing #3 / weak #5):
- resourceVersions advance NON-contiguously (one shared rv space across
  all resources; the stub bumps by a stride > 1) — numeric-gap tolerance
  is exercised by every test, not a special case;
- 410 Gone: `state.fire_gone(plural)` ends every open watch stream with
  an ERROR event (watch-cache compaction), and a ?resourceVersion older
  than `state.min_rv` (set via `state.compact()`) is answered with an
  immediate 410 ERROR event;
- dropped connections: `state.drop_watches(plural)` severs open streams
  abruptly — no ERROR event, no clean end-of-list.
"""

from __future__ import annotations

import json
import queue
import re
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_PATH = re.compile(
    r"^/(?:api|apis)/(?P<gv>v1|[\w.]+/v1alpha1|policy/v1)"
    r"(?:/namespaces/(?P<ns>[\w.-]+))?"
    r"/(?P<plural>[a-z]+)"
    r"(?:/(?P<name>[\w.-]+))?"
    r"(?:/(?P<sub>binding|status))?$")


def merge_apply(target: dict, patch: dict) -> dict:
    """RFC 7386 JSON merge patch."""
    for k, v in patch.items():
        if v is None:
            target.pop(k, None)
        elif isinstance(v, dict) and isinstance(target.get(k), dict):
            merge_apply(target[k], v)
        else:
            target[k] = v
    return target


class _State:
    def __init__(self) -> None:
        self.lock = threading.RLock()
        self.store: dict[str, dict[str, dict]] = {}   # plural -> key -> obj
        self.rv = 0
        self.rv_stride = 7      # shared rv space: versions skip numbers
        self.min_rv = 0         # watch-cache compaction horizon
        self.watchers: dict[str, list[queue.Queue]] = {}

    def key(self, ns: str | None, name: str) -> str:
        return f"{ns}/{name}" if ns else name

    def bump(self, obj: dict) -> None:
        self.rv += self.rv_stride
        obj.setdefault("metadata", {})["resourceVersion"] = str(self.rv)

    def notify(self, plural: str, event: str, obj: dict) -> None:
        for q in self.watchers.get(plural, []):
            q.put({"type": event, "object": obj})

    # -- fault injection ---------------------------------------------------
    def fire_gone(self, plural: str) -> None:
        """End every open stream for `plural` with a 410 Gone ERROR event
        (what a real apiserver does when its watch cache is compacted)."""
        with self.lock:
            for q in self.watchers.get(plural, []):
                q.put({"__end__": "gone"})

    def drop_watches(self, plural: str) -> None:
        """Sever open streams for `plural` abruptly — no ERROR event (a
        mid-flight LB reset / network partition)."""
        with self.lock:
            for q in self.watchers.get(plural, []):
                q.put({"__end__": "drop"})

    def compact(self) -> None:
        """Advance the compaction horizon: any future watch asking for a
        resourceVersion older than now is answered 410."""
        with self.lock:
            self.min_rv = self.rv


class _Handler(BaseHTTPRequestHandler):
    state: _State = None  # type: ignore[assignment]
    protocol_version = "HTTP/1.0"

    def log_message(self, *args) -> None:
        pass

    def _send(self, code: int, body: dict | None = None) -> None:
        data = json.dumps(body or {}).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _parse(self):
        parsed = urllib.parse.urlparse(self.path)
        m = _PATH.match(parsed.path)
        if not m:
            self._send(404, {"message": f"bad path {parsed.path}"})
            return None
        return m.group("ns"), m.group("plural"), m.group("name"), \
            urllib.parse.parse_qs(parsed.query), m.group("sub")

    def _body(self) -> dict:
        return json.loads(self.rfile.read(
            int(self.headers.get("Content-Length", 0))))

    def do_GET(self):  # noqa: N802
        parsed = self._parse()
        if parsed is None:
            return
        ns, plural, name, query, _sub = parsed
        st = self.state
        with st.lock:
            coll = st.store.setdefault(plural, {})
            if name:
                key = st.key(ns, name)
                if key not in coll:
                    return self._send(404, {"message": "not found"})
                return self._send(200, coll[key])
            items = list(coll.values())
        if ns:
            items = [o for o in items
                     if (o.get("metadata") or {}).get("namespace") == ns]
        sel = query.get("labelSelector", [""])[0]
        if sel:
            want = dict(kv.split("=", 1) for kv in sel.split(","))
            items = [o for o in items
                     if all(((o.get("metadata") or {}).get("labels") or {})
                            .get(k) == v for k, v in want.items())]
        if query.get("watch", ["false"])[0] == "true":
            return self._watch(plural, query)
        self._send(200, {"kind": "List",
                         "metadata": {"resourceVersion": str(st.rv)},
                         "items": items})

    _GONE = {"type": "ERROR",
             "object": {"kind": "Status", "code": 410, "reason": "Gone",
                        "message": "too old resource version"}}

    def _watch(self, plural: str, query: dict) -> None:
        st = self.state
        rv_param = query.get("resourceVersion", [""])[0]
        q: queue.Queue = queue.Queue()
        with st.lock:
            stale = False
            try:
                stale = bool(rv_param) and int(rv_param) < st.min_rv
            except ValueError:
                pass
            if not stale:
                st.watchers.setdefault(plural, []).append(q)
        try:
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            if stale:
                # compacted away: the real server answers the request
                # with a single 410 ERROR event
                self.wfile.write((json.dumps(self._GONE) + "\n").encode())
                self.wfile.flush()
                return
            while True:
                try:
                    evt = q.get(timeout=10.0)
                except queue.Empty:
                    return
                if evt.get("__end__") == "drop":
                    raise BrokenPipeError("injected connection drop")
                if evt.get("__end__") == "gone":
                    self.wfile.write(
                        (json.dumps(self._GONE) + "\n").encode())
                    self.wfile.flush()
                    return
                self.wfile.write((json.dumps(evt) + "\n").encode())
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            pass
        finally:
            with st.lock:
                if q in st.watchers.get(plural, []):
                    st.watchers[plural].remove(q)

    def do_POST(self):  # noqa: N802
        parsed = self._parse()
        if parsed is None:
            return
        ns, plural, name, _, sub = parsed
        obj = self._body()
        st = self.state
        if sub == "binding":
            # POST pods/{name}/binding: the ONLY way to set nodeName
            # (spec.nodeName is immutable through PUT/PATCH)
            with st.lock:
                coll = st.store.setdefault(plural, {})
                key = st.key(ns, name)
                if key not in coll:
                    return self._send(404, {"message": "not found"})
                target = (obj.get("target") or {}).get("name", "")
                coll[key].setdefault("spec", {})["nodeName"] = target
                # The cluster side of the split: once bound, the node's
                # kubelet starts the containers and reports Running.  The
                # stub plays that kubelet (the scheduler/agents must NOT —
                # controllers/kubelet.py declines on real substrates).
                coll[key].setdefault("status", {})["phase"] = "Running"
                st.bump(coll[key])
                st.notify(plural, "MODIFIED", coll[key])
            return self._send(201, {"status": "Success"})
        with st.lock:
            coll = st.store.setdefault(plural, {})
            name = (obj.get("metadata") or {}).get("name", "")
            key = st.key(ns, name)
            if key in coll:
                return self._send(409, {"message": "already exists"})
            meta = obj.setdefault("metadata", {})
            meta.setdefault("uid", f"stub-uid-{st.rv + 1}")
            meta.setdefault("creationTimestamp",
                            "2026-01-01T00:00:00Z")
            if ns:
                meta["namespace"] = ns
            st.bump(obj)
            coll[key] = obj
            st.notify(plural, "ADDED", obj)
        self._send(201, obj)

    def do_PUT(self):  # noqa: N802
        parsed = self._parse()
        if parsed is None:
            return
        ns, plural, name, _, _sub = parsed
        obj = self._body()
        st = self.state
        with st.lock:
            coll = st.store.setdefault(plural, {})
            key = st.key(ns, name)
            if key not in coll:
                return self._send(404, {"message": "not found"})
            current = coll[key]
            current_rv = (current.get("metadata") or {}) \
                .get("resourceVersion")
            sent_rv = (obj.get("metadata") or {}).get("resourceVersion")
            if sent_rv and sent_rv != current_rv:
                return self._send(409, {"message": "conflict"})
            if plural == "pods":
                old_nn = (current.get("spec") or {}).get("nodeName", "")
                new_nn = (obj.get("spec") or {}).get("nodeName", "")
                if new_nn != old_nn:
                    return self._send(422, {
                        "message": "spec.nodeName is immutable; "
                                   "use the binding subresource"})
            meta = obj.setdefault("metadata", {})
            meta.setdefault("uid", (current["metadata"]).get("uid"))
            if ns:
                meta["namespace"] = ns
            st.bump(obj)
            coll[key] = obj
            st.notify(plural, "MODIFIED", obj)
        self._send(200, obj)

    def do_PATCH(self):  # noqa: N802
        parsed = self._parse()
        if parsed is None:
            return
        ns, plural, name, _, sub = parsed
        patch = self._body()
        st = self.state
        with st.lock:
            coll = st.store.setdefault(plural, {})
            key = st.key(ns, name)
            if key not in coll:
                return self._send(404, {"message": "not found"})
            current = coll[key]
            if sub == "status":
                # only the status stanza applies through /status
                merge_apply(current.setdefault("status", {}),
                            (patch.get("status") or {}))
            else:
                if plural == "pods":
                    nn = (patch.get("spec") or {}).get("nodeName")
                    old_nn = (current.get("spec") or {}) \
                        .get("nodeName", "")
                    if nn is not None and nn != old_nn:
                        return self._send(422, {
                            "message": "spec.nodeName is immutable; "
                                       "use the binding subresource"})
                patch.pop("status", None)  # status via /status only
                merge_apply(current, patch)
            st.bump(current)
            st.notify(plural, "MODIFIED", current)
        self._send(200, current)

    def do_DELETE(self):  # noqa: N802
        parsed = self._parse()
        if parsed is None:
            return
        ns, plural, name, _, _sub = parsed
        st = self.state
        with st.lock:
            coll = st.store.setdefault(plural, {})
            key = st.key(ns, name)
            if key not in coll:
                return self._send(404, {"message": "not found"})
            obj = coll.pop(key)
            st.notify(plural, "DELETED", obj)
        self._send(200, {"status": "Success"})


class StubApiServer:
    """Context manager exposing the stub's base URL."""

    def __init__(self) -> None:
        self.state = _State()
        handler = type("Handler", (_Handler,), {"state": self.state})
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True)

    def __enter__(self) -> "StubApiServer":
        self._thread.start()
        return self

    def __exit__(self, *exc) -> None:
        self.httpd.shutdown()
