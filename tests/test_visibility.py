"""Chip-visibility enforcement end-to-end (VERDICT r3 missing #1).

A slice grant used to be advisory: the device plugin handed the workload
NOS_TPU_SLICE_IDS but nothing confined the jax process to the granted
chips.  Now the plugin's Allocate response derives the granted chips'
local ids from the carved placements, and device/workload_env.apply turns
them into libtpu visibility env (TPU_VISIBLE_CHIPS / TPU_PROCESS_BOUNDS /
TPU_CHIPS_PER_PROCESS_BOUNDS) before the first jax import — the TPU
analog of MIG device visibility (reference pkg/gpu/nvml/client.go:286-340
creates hard per-partition devices).
"""

from __future__ import annotations

import pytest

from nos_tpu.device import workload_env
from nos_tpu.device.deviceplugin import (
    DevicePluginManager, ENV_DEVICE_IDS, ENV_HOST_BOUNDS, ENV_VISIBLE_CHIPS,
)
from nos_tpu.device.fake import FakeTpuRuntime
from nos_tpu.topology import Shape, V4, V5E
from nos_tpu.topology.packing import Placement, placement_cells


def shapes(*names):
    return [Shape.parse(n) for n in names]


class TestPlacementCells:
    def test_row_major_ids(self):
        # 2x2 at origin of the 2x4 block: rows 0-1, cols 0-1
        pl = Placement(Shape.parse("2x2"), (0, 0), (2, 2))
        assert placement_cells(V5E.host_block, pl) == (0, 1, 4, 5)

    def test_offset_placement(self):
        pl = Placement(Shape.parse("2x2"), (0, 2), (2, 2))
        assert placement_cells(V5E.host_block, pl) == (2, 3, 6, 7)

    def test_3d(self):
        pl = Placement(Shape.parse("1x1x2"), (0, 1, 0), (1, 1, 2))
        assert placement_cells(V4.host_block, pl) == (2, 3)


class TestAllocateEnvs:
    def _manager(self):
        rt = FakeTpuRuntime(V5E)
        mgr = DevicePluginManager(rt, plugins_dir="/nonexistent",
                                  kubelet_socket="/nonexistent")
        return rt, mgr

    def test_visibility_env_from_placements(self):
        rt, mgr = self._manager()
        ids = rt.create_slices(0, shapes("2x2", "2x2"))
        envs = mgr._slice_allocate_envs("nos.tpu/slice-2x2", [ids[0]])
        assert envs[ENV_DEVICE_IDS] == ids[0]
        assert envs[f"{ENV_VISIBLE_CHIPS}_slice_2x2"] == "0,1,4,5"
        assert envs[ENV_HOST_BOUNDS] == "2x4"

    def test_unknown_device_grants_no_visibility(self):
        rt, mgr = self._manager()
        envs = mgr._slice_allocate_envs("nos.tpu/slice-2x2", ["ghost"])
        assert envs == {ENV_DEVICE_IDS: "ghost"}

    def test_cross_unit_grant_falls_back_to_ids_only(self):
        # local chip ids are per partition root: a grant spanning units
        # cannot be expressed as one visibility set
        rt, mgr = self._manager()
        a = rt.create_slices(0, shapes("2x2"))
        b = rt.create_slices(1, shapes("2x2"))
        envs = mgr._slice_allocate_envs("nos.tpu/slice-2x2", a + b)
        assert f"{ENV_VISIBLE_CHIPS}_slice_2x2" not in envs
        assert envs[ENV_DEVICE_IDS] == ",".join(a + b)


class TestWorkloadEnvVisibility:
    def test_contiguous_grant_sets_bounds(self):
        env = {f"{ENV_VISIBLE_CHIPS}_slice_2x2": "0,1,4,5",
               ENV_HOST_BOUNDS: "2x4"}
        applied = workload_env.apply(env, hbm_gb_per_chip=16)
        assert applied["TPU_VISIBLE_CHIPS"] == "0,1,4,5"
        assert applied["TPU_PROCESS_BOUNDS"] == "1,1,1"
        assert applied["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,2,1"
        assert env["TPU_VISIBLE_CHIPS"] == "0,1,4,5"

    def test_multi_profile_grants_union(self):
        env = {f"{ENV_VISIBLE_CHIPS}_slice_2x2": "0,1,4,5",
               f"{ENV_VISIBLE_CHIPS}_slice_1x2": "2,6",
               ENV_HOST_BOUNDS: "2x4"}
        applied = workload_env.apply(env, hbm_gb_per_chip=16)
        assert applied["TPU_VISIBLE_CHIPS"] == "0,1,2,4,5,6"
        # union (2x3 box has 6 cells = chip count): still contiguous
        assert applied["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,3,1"

    def test_non_contiguous_grant_sets_chips_only(self):
        env = {f"{ENV_VISIBLE_CHIPS}_slice_1x1": "0,3",
               ENV_HOST_BOUNDS: "2x4"}
        applied = workload_env.apply(env, hbm_gb_per_chip=16)
        assert applied["TPU_VISIBLE_CHIPS"] == "0,3"
        assert "TPU_PROCESS_BOUNDS" not in applied
        assert "TPU_CHIPS_PER_PROCESS_BOUNDS" not in applied

    def test_garbage_grants_ignored(self):
        env = {f"{ENV_VISIBLE_CHIPS}_slice_1x1": "banana"}
        assert "TPU_VISIBLE_CHIPS" not in workload_env.apply(env, 16)
        env = {f"{ENV_VISIBLE_CHIPS}_slice_1x1": "1,2",
               ENV_HOST_BOUNDS: "0x0"}
        applied = workload_env.apply(env, 16)
        assert applied["TPU_VISIBLE_CHIPS"] == "1,2"
        assert "TPU_CHIPS_PER_PROCESS_BOUNDS" not in applied


class _Dev:
    def __init__(self, coords=None):
        if coords is not None:
            self.coords = coords


class TestConfinementCheck:
    """check_confinement: the chip-numbering convention is asserted after
    jax init, not assumed (ADVICE r4 medium — a host whose libtpu
    enumeration disagrees with row-major placement cells must fail loudly
    before work runs on another slice's chips)."""

    def test_count_mismatch_raises(self):
        import pytest

        with pytest.raises(workload_env.ConfinementError,
                           match="promised 2"):
            workload_env.check_confinement(
                [0, 1], [_Dev((0, 0, 0))], "2x4")

    def test_matching_coords_pass(self):
        # granted cells 0,1 of a 2x4 block = local coords (0,0),(0,1);
        # PJRT reports global coords with an arbitrary host origin
        workload_env.check_confinement(
            [0, 1], [_Dev((4, 2, 0)), _Dev((4, 3, 0))], "2x4")

    def test_interior_subblock_passes(self):
        # cells 2,3 (row 0, cols 2-3): devices renumbered from their own
        # origin still match after rebasing both sides
        workload_env.check_confinement(
            [2, 3], [_Dev((0, 0)), _Dev((0, 1))], "2x4")

    def test_wrong_shape_raises(self):
        import pytest

        # granted a 1x2 row pair but the visible devices form a column
        with pytest.raises(workload_env.ConfinementError,
                           match="numbering disagrees"):
            workload_env.check_confinement(
                [0, 1], [_Dev((0, 0)), _Dev((1, 0))], "2x4")

    def test_no_coords_degrades_to_count(self):
        workload_env.check_confinement([0, 1], [_Dev(), _Dev()], "2x4")

    def test_one_corrupt_token_voids_the_whole_grant(self):
        # confining to a silently under-sized subset is worse than not
        # confining at all
        env = {f"{ENV_VISIBLE_CHIPS}_slice_2x2": "0,1,4,x5",
               ENV_HOST_BOUNDS: "2x4"}
        applied = workload_env.apply(env, 16)
        assert "TPU_VISIBLE_CHIPS" not in applied
        assert "TPU_CHIPS_PER_PROCESS_BOUNDS" not in applied

    def test_existing_visibility_env_withholds_all_keys(self):
        # mixing a grant's bounds with pre-existing operator visibility
        # settings would describe a contradictory topology: all-or-none
        env = {f"{ENV_VISIBLE_CHIPS}_slice_2x2": "0,1,4,5",
               ENV_HOST_BOUNDS: "2x4",
               "TPU_VISIBLE_CHIPS": "0,1"}
        applied = workload_env.apply(env, 16)
        assert env["TPU_VISIBLE_CHIPS"] == "0,1"
        assert "TPU_PROCESS_BOUNDS" not in applied
        assert "TPU_CHIPS_PER_PROCESS_BOUNDS" not in applied
        env2 = {f"{ENV_VISIBLE_CHIPS}_slice_2x2": "0,1,4,5",
                ENV_HOST_BOUNDS: "2x4",
                "TPU_PROCESS_BOUNDS": "2,2,1"}
        applied2 = workload_env.apply(env2, 16)
        assert "TPU_VISIBLE_CHIPS" not in applied2


class TestFullChain:
    def test_plugin_grant_to_workload_env(self):
        """Carve -> Allocate envs -> workload env: the whole cooperative
        enforcement path on the fake substrate."""
        rt = FakeTpuRuntime(V5E)
        mgr = DevicePluginManager(rt, plugins_dir="/nonexistent",
                                  kubelet_socket="/nonexistent")
        ids = rt.create_slices(0, shapes("2x2", "1x2", "1x2"))
        granted = [i for i in ids if "2x2" in i]
        env = dict(mgr._slice_allocate_envs("nos.tpu/slice-2x2", granted))
        applied = workload_env.apply(env, hbm_gb_per_chip=16)
        chips = [int(c) for c in applied["TPU_VISIBLE_CHIPS"].split(",")]
        assert len(chips) == 4
        pl = rt.placements()[granted[0]]
        assert tuple(chips) == placement_cells(V5E.host_block, pl)
        assert applied["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,2,1"


def _on_real_tpu() -> bool:
    try:
        import jax

        return any(d.platform == "tpu" for d in jax.local_devices())
    except Exception:
        return False


@pytest.mark.skipif(not _on_real_tpu(),
                    reason="no real TPU visible (set NOS_TPU_TEST_REAL=1)")
def test_visibility_confines_jax_process_e2e():
    """Real hardware: a workload granted a sub-block sees ONLY those chips
    in jax.local_devices().  Must run jax in a SUBPROCESS — visibility env
    binds at backend init.  On a 1-chip tunnel this carves a 1x1 from a
    1x1 block (degenerate but real: the env is honored end-to-end)."""
    import json
    import os
    import subprocess
    import sys

    from nos_tpu.device import discovery, native

    if not native.available():
        pytest.skip("native shim not buildable")
    rt = native.NativeTpuRuntime(None)   # discover, don't assert
    assert rt.topology_source == discovery.SOURCE_DEVICE
    _, block = rt.topology()
    disc = rt.discovered
    fitting = [s for s in disc.generation.subhost_shapes()
               if s.fits_in(block)]
    if not fitting:  # observed block smaller than any profile: carve it all
        fitting = [block.canonical()]
    sub = min(fitting, key=lambda s: s.chips)
    ids = rt.create_slices(0, [sub])
    mgr = DevicePluginManager(rt, plugins_dir="/nonexistent",
                              kubelet_socket="/nonexistent")
    envs = mgr._slice_allocate_envs("nos.tpu/slice-" + sub.name, ids)
    child_env = dict(os.environ)
    child_env.pop("JAX_PLATFORMS", None)
    child_env.update({k: str(v) for k, v in envs.items()})
    code = (
        "from nos_tpu.device import workload_env\n"
        "applied = workload_env.apply()\n"
        "import jax, json\n"
        "print(json.dumps({'applied': applied,"
        " 'n': len(jax.local_devices())}))\n"
    )
    try:
        out = subprocess.run([sys.executable, "-c", code], env=child_env,
                             capture_output=True, text=True, timeout=300)
        if out.returncode != 0 and (
                "already in use" in out.stderr.lower()
                or "unable to initialize backend" in out.stderr.lower()):
            pytest.skip("platform does not allow a second TPU process "
                        "while the test runner holds the chip(s)")
        assert out.returncode == 0, out.stderr[-2000:]
        result = json.loads(out.stdout.strip().splitlines()[-1])
        assert "TPU_VISIBLE_CHIPS" in result["applied"]
        assert result["n"] == sub.chips
    finally:
        for did in ids:
            rt.delete_slice(did)
