"""Elastic-quota subsystem tests.

Modeled on reference test strategy (SURVEY.md §4): quota arithmetic
(elasticquotainfo_test.go), plugin behavior driven through the real framework
(capacity_scheduling_test.go), and reconciler behavior against the API
substrate (elasticquota_controller_int_test.go).
"""

from __future__ import annotations

import pytest

from nos_tpu.api import constants as C
from nos_tpu.api.elasticquota import (
    AdmissionError, CompositeElasticQuota, CompositeElasticQuotaSpec,
    ElasticQuota, ElasticQuotaSpec, validate_composite_elastic_quota,
    validate_elastic_quota,
)
from nos_tpu.controllers.elasticquota import (
    CompositeElasticQuotaReconciler, ElasticQuotaReconciler,
)
from nos_tpu.kube.client import (
    APIServer, KIND_COMPOSITE_ELASTIC_QUOTA, KIND_ELASTIC_QUOTA, KIND_NODE,
    KIND_POD, NotFound,
)
from nos_tpu.kube.objects import ObjectMeta, RUNNING
from nos_tpu.quota import ElasticQuotaInfo, ElasticQuotaInfos, TPUResourceCalculator
from nos_tpu.scheduler.capacityscheduling import CapacityScheduling
from nos_tpu.scheduler.framework import CycleState, Framework, NodeResourcesFit, SharedLister
from nos_tpu.scheduler.scheduler import Scheduler
from nos_tpu.testing.factory import admit_all, make_node, make_pod

TPU_MEM = C.RESOURCE_TPU_MEMORY
CALC = TPUResourceCalculator(hbm_gb_per_chip=16)


def make_eq(name: str, namespace: str, min: dict, max: dict | None = None) -> ElasticQuota:
    return ElasticQuota(
        metadata=ObjectMeta(name=name, namespace=namespace),
        spec=ElasticQuotaSpec(min=dict(min), max=dict(max or {})),
    )


def make_info(name: str, ns: str, min: dict, max: dict | None = None,
              used: dict | None = None) -> ElasticQuotaInfo:
    info = ElasticQuotaInfo(name, ns, [ns], min, max, CALC)
    info.used = dict(used or {})
    return info


# ---------------------------------------------------------------------------
# Resource calculator
# ---------------------------------------------------------------------------


class TestTPUResourceCalculator:
    def test_whole_chips(self):
        pod = make_pod(resources={C.RESOURCE_TPU: 4, "cpu": 2})
        req = CALC.compute_pod_request(pod)
        assert req[TPU_MEM] == 4 * 16

    def test_slice_profile(self):
        pod = make_pod(resources={f"{C.RESOURCE_SLICE_PREFIX}2x2": 1})
        req = CALC.compute_pod_request(pod)
        assert req[TPU_MEM] == 4 * 16

    def test_timeshare_profile(self):
        pod = make_pod(resources={f"{C.RESOURCE_TIMESHARE_PREFIX}8gb": 2})
        req = CALC.compute_pod_request(pod)
        assert req[TPU_MEM] == 16

    def test_mixed(self):
        pod = make_pod(resources={
            C.RESOURCE_TPU: 1,
            f"{C.RESOURCE_SLICE_PREFIX}1x1": 1,
            f"{C.RESOURCE_TIMESHARE_PREFIX}4gb": 1,
        })
        assert CALC.compute_pod_request(pod)[TPU_MEM] == 16 + 16 + 4

    def test_multihost_shard_accounting(self):
        """With chips_per_host set, one unit of a multi-host slice is
        charged as one host-shard (the chips the member physically owns,
        quota/calculator.py); sub-host shapes are unaffected, and the
        default (0) keeps full-shape charging."""
        from nos_tpu.quota import TPUResourceCalculator

        shard_calc = TPUResourceCalculator(16, chips_per_host=8)
        gang_member = make_pod(resources={f"{C.RESOURCE_SLICE_PREFIX}4x8": 1})
        assert shard_calc.compute_pod_request(gang_member)[TPU_MEM] == 8 * 16
        assert CALC.compute_pod_request(gang_member)[TPU_MEM] == 32 * 16
        small = make_pod(resources={f"{C.RESOURCE_SLICE_PREFIX}2x2": 1})
        assert shard_calc.compute_pod_request(small)[TPU_MEM] == 4 * 16


# ---------------------------------------------------------------------------
# Quota ledger arithmetic (reference elasticquotainfo_test.go)
# ---------------------------------------------------------------------------


class TestElasticQuotaInfo:
    def test_used_over_min_with(self):
        info = make_info("eq", "ns", {TPU_MEM: 100}, used={TPU_MEM: 90})
        assert not info.used_over_min_with({TPU_MEM: 10})
        assert info.used_over_min_with({TPU_MEM: 11})

    def test_max_not_enforced_when_absent(self):
        info = make_info("eq", "ns", {TPU_MEM: 100})
        assert not info.used_over_max_with({TPU_MEM: 10**9})

    def test_max_enforced(self):
        info = make_info("eq", "ns", {TPU_MEM: 100}, max={TPU_MEM: 200},
                         used={TPU_MEM: 150})
        assert not info.used_over_max_with({TPU_MEM: 50})
        assert info.used_over_max_with({TPU_MEM: 51})

    def test_unenforced_resources_ignored(self):
        # A resource absent from min does not bound usage — including cpu
        # (deliberate divergence from the reference scheduler plugin, whose
        # always-on cpu/memory comparison contradicts its own reconciler;
        # see nos_tpu/quota/info.py module docstring).
        info = make_info("eq", "ns", {TPU_MEM: 100},
                         used={"google.com/tpu": 999, "cpu": 4})
        assert not info.used_over_min()
        info2 = make_info("eq", "ns", {TPU_MEM: 100, "cpu": 2},
                          used={"cpu": 4})
        assert info2.used_over_min()

    def test_add_delete_pod_idempotent(self):
        info = make_info("eq", "ns", {TPU_MEM: 100})
        pod = make_pod(namespace="ns", resources={C.RESOURCE_TPU: 2})
        info.add_pod_if_not_present(pod)
        info.add_pod_if_not_present(pod)
        assert info.used[TPU_MEM] == 32
        info.delete_pod_if_present(pod)
        info.delete_pod_if_present(pod)
        assert info.used[TPU_MEM] == 0

    def test_guaranteed_overquotas_proportional_to_min(self):
        # The worked example in reference elasticquotainfo.go:121-152:
        # A(min=100, used=350), B(min=50, used=0), C(min=200, used=50)
        # -> aggregate overquotas = 0 + 50 + 150 = 200.
        infos = ElasticQuotaInfos()
        infos.add(make_info("a", "ns-a", {"cpu": 100}, used={"cpu": 350}))
        infos.add(make_info("b", "ns-b", {"cpu": 50}, used={"cpu": 0}))
        infos.add(make_info("c", "ns-c", {"cpu": 200}, used={"cpu": 50}))
        assert infos.aggregated_overquotas() == {"cpu": 200}
        # Guaranteed shares are proportional to min (100:50:200 of 350).
        assert infos.get_guaranteed_overquotas("ns-a") == {"cpu": 57.0}
        assert infos.get_guaranteed_overquotas("ns-b") == {"cpu": 28.0}
        assert infos.get_guaranteed_overquotas("ns-c") == {"cpu": 114.0}

    def test_aggregated_used_over_min_with(self):
        infos = ElasticQuotaInfos()
        infos.add(make_info("a", "ns-a", {TPU_MEM: 64}, used={TPU_MEM: 64}))
        infos.add(make_info("b", "ns-b", {TPU_MEM: 64}, used={TPU_MEM: 32}))
        assert not infos.aggregated_used_over_min_with({TPU_MEM: 32})
        assert infos.aggregated_used_over_min_with({TPU_MEM: 33})

    def test_composite_counted_once_in_aggregates(self):
        infos = ElasticQuotaInfos()
        ceq = ElasticQuotaInfo("team", "default", ["ns-1", "ns-2"],
                               {TPU_MEM: 100}, None, CALC, composite=True)
        infos.add(ceq)
        assert infos.aggregated_min() == {TPU_MEM: 100}

    def test_clone_preserves_composite_identity(self):
        infos = ElasticQuotaInfos()
        ceq = ElasticQuotaInfo("team", "default", ["ns-1", "ns-2"],
                               {TPU_MEM: 100}, None, CALC, composite=True)
        infos.add(ceq)
        cloned = infos.clone()
        assert cloned["ns-1"] is cloned["ns-2"]
        pod = make_pod(namespace="ns-1", resources={C.RESOURCE_TPU: 1})
        cloned["ns-1"].add_pod_if_not_present(pod)
        assert cloned["ns-2"].used[TPU_MEM] == 16
        assert ceq.used == {}  # original untouched


# ---------------------------------------------------------------------------
# Plugin through the framework
# ---------------------------------------------------------------------------


def quota_cluster(*, nodes=2, chips_per_node=8):
    """API + scheduler wiring with CapacityScheduling registered."""
    api = APIServer()
    plugin = CapacityScheduling(CALC)
    fw = Framework([NodeResourcesFit(), plugin])
    plugin.set_framework(fw)
    plugin.attach(api)
    for i in range(nodes):
        api.create(KIND_NODE, make_node(
            f"node-{i}",
            allocatable={"cpu": 64.0, C.RESOURCE_TPU: float(chips_per_node),
                         TPU_MEM: chips_per_node * 16.0},
        ))
    sched = Scheduler(api, fw)
    return api, plugin, fw, sched


class TestCapacitySchedulingPreFilter:
    def test_no_quota_passes(self):
        api, plugin, fw, sched = quota_cluster()
        pod = make_pod(namespace="free", resources={C.RESOURCE_TPU: 2})
        st = plugin.pre_filter(CycleState(), pod, SharedLister())
        assert st.is_success

    def test_rejects_over_max(self):
        api, plugin, fw, sched = quota_cluster()
        api.create(KIND_ELASTIC_QUOTA, make_eq(
            "eq-a", "ns-a", min={TPU_MEM: 32}, max={TPU_MEM: 48}))
        pod = make_pod(namespace="ns-a", resources={C.RESOURCE_TPU: 4})  # 64GB
        st = plugin.pre_filter(CycleState(), pod, SharedLister())
        assert not st.is_success and "max" in st.message

    def test_allows_borrowing_within_aggregate_min(self):
        api, plugin, fw, sched = quota_cluster()
        api.create(KIND_ELASTIC_QUOTA, make_eq("eq-a", "ns-a", min={TPU_MEM: 32}))
        api.create(KIND_ELASTIC_QUOTA, make_eq("eq-b", "ns-b", min={TPU_MEM: 96}))
        # ns-a requests 64GB > its own min 32, but aggregate min 128 has room.
        pod = make_pod(namespace="ns-a", resources={C.RESOURCE_TPU: 4})
        st = plugin.pre_filter(CycleState(), pod, SharedLister())
        assert st.is_success

    def test_rejects_when_aggregate_min_exhausted(self):
        api, plugin, fw, sched = quota_cluster()
        api.create(KIND_ELASTIC_QUOTA, make_eq("eq-a", "ns-a", min={TPU_MEM: 32}))
        api.create(KIND_ELASTIC_QUOTA, make_eq("eq-b", "ns-b", min={TPU_MEM: 32}))
        # ns-b is using its whole min.
        api.create(KIND_POD, make_pod(
            name="b-1", namespace="ns-b", resources={C.RESOURCE_TPU: 2},
            node_name="node-0", phase=RUNNING))
        pod = make_pod(namespace="ns-a", resources={C.RESOURCE_TPU: 3})  # 48GB
        st = plugin.pre_filter(CycleState(), pod, SharedLister())
        assert not st.is_success and "min" in st.message

    def test_reserve_unreserve_bookkeeping(self):
        api, plugin, fw, sched = quota_cluster()
        api.create(KIND_ELASTIC_QUOTA, make_eq("eq-a", "ns-a", min={TPU_MEM: 64}))
        pod = make_pod(namespace="ns-a", resources={C.RESOURCE_TPU: 2})
        plugin.reserve(CycleState(), pod, "node-0")
        assert plugin.elastic_quota_infos["ns-a"].used[TPU_MEM] == 32
        plugin.unreserve(CycleState(), pod, "node-0")
        assert plugin.elastic_quota_infos["ns-a"].used[TPU_MEM] == 0


class TestEndToEndSchedulingWithQuota:
    def test_borrow_then_preempt_over_quota_pod(self):
        """BASELINE config #5 shape: ns-b borrows ns-a's unused quota; when
        ns-a claims its min back, the scheduler preempts ns-b's over-quota
        pod (reference SelectVictimsOnNode :566-581)."""
        api, plugin, fw, sched = quota_cluster(nodes=1, chips_per_node=8)
        api.create(KIND_ELASTIC_QUOTA, make_eq("eq-a", "ns-a", min={TPU_MEM: 64}))
        api.create(KIND_ELASTIC_QUOTA, make_eq("eq-b", "ns-b", min={TPU_MEM: 64}))
        eq_rec = ElasticQuotaReconciler(api, CALC)

        # ns-b fills the whole node (8 chips = 128GB), borrowing 64GB.
        for i in range(2):
            api.create(KIND_POD, make_pod(
                name=f"b-{i}", namespace="ns-b",
                resources={C.RESOURCE_TPU: 4}, creation_timestamp=float(i)))
        assert sched.run_cycle() == 2
        admit_all(api)  # kubelet-phase sim: victims must be Running
        eq_rec.reconcile_all()
        labels = {p.metadata.name: p.metadata.labels.get(C.LABEL_CAPACITY)
                  for p in api.list(KIND_POD, namespace="ns-b")}
        assert sorted(labels.values()) == ["in-quota", "over-quota"]

        # ns-a now claims its guaranteed min: 4 chips = 64GB.
        a_pod = make_pod(name="a-0", namespace="ns-a",
                         resources={C.RESOURCE_TPU: 4})
        api.create(KIND_POD, a_pod)
        # One cycle: preempts, then binds into the synchronously freed
        # capacity (the post-preemption retry — scheduler.py
        # _preempt_then_retry; on a real apiserver victims terminate
        # gracefully and this would nominate instead).
        assert sched.run_cycle() == 1
        remaining_b = api.list(KIND_POD, namespace="ns-b")
        assert len(remaining_b) == 1  # over-quota borrower evicted
        assert remaining_b[0].metadata.labels[C.LABEL_CAPACITY] == "in-quota"
        assert api.get(KIND_POD, "a-0", "ns-a").spec.node_name == "node-0"

    def test_same_namespace_priority_preemption(self):
        """Over-min preemptor evicts same-namespace lower-priority pods
        (reference :529-541)."""
        api, plugin, fw, sched = quota_cluster(nodes=1, chips_per_node=8)
        api.create(KIND_ELASTIC_QUOTA, make_eq(
            "eq-a", "ns-a", min={TPU_MEM: 64}))
        # Idle quota providing the aggregate-min headroom ns-a borrows.
        api.create(KIND_ELASTIC_QUOTA, make_eq(
            "eq-b", "ns-b", min={TPU_MEM: 64}))
        # Low-priority pod fills the node, running over-quota.
        api.create(KIND_POD, make_pod(
            name="low", namespace="ns-a", priority=0,
            resources={C.RESOURCE_TPU: 8}))
        assert sched.run_cycle() == 1
        ElasticQuotaReconciler(api, CALC).reconcile_all()
        # High-priority pod displaces it.
        api.create(KIND_POD, make_pod(
            name="high", namespace="ns-a", priority=100,
            resources={C.RESOURCE_TPU: 4}))
        sched.run_cycle()
        assert api.try_get(KIND_POD, "low", "ns-a") is None
        sched.run_cycle()
        assert api.get(KIND_POD, "high", "ns-a").spec.node_name == "node-0"

    def test_no_preemption_of_in_quota_pods(self):
        """A borrower cannot evict pods that are within their own min."""
        api, plugin, fw, sched = quota_cluster(nodes=1, chips_per_node=8)
        api.create(KIND_ELASTIC_QUOTA, make_eq("eq-a", "ns-a", min={TPU_MEM: 32}))
        api.create(KIND_ELASTIC_QUOTA, make_eq("eq-b", "ns-b", min={TPU_MEM: 96}))
        api.create(KIND_POD, make_pod(
            name="b-0", namespace="ns-b", resources={C.RESOURCE_TPU: 6}))
        assert sched.run_cycle() == 1
        ElasticQuotaReconciler(api, CALC).reconcile_all()
        # ns-a wants 4 chips: 2 over its min — no over-quota victims exist.
        api.create(KIND_POD, make_pod(
            name="a-0", namespace="ns-a", resources={C.RESOURCE_TPU: 4}))
        sched.run_cycle()
        assert api.try_get(KIND_POD, "b-0", "ns-b") is not None
        assert api.get(KIND_POD, "a-0", "ns-a").spec.node_name == ""


class TestPDBGangPreemption:
    """Gang eviction is all-or-nothing (evict_gang), so its amplification
    set must be charged against PodDisruptionBudgets at victim-selection
    time — not discovered at deletion time."""

    @staticmethod
    def _pdb(api, ns, selector, min_available):
        from nos_tpu.api.pdb import (
            KIND_POD_DISRUPTION_BUDGET, PodDisruptionBudget,
            PodDisruptionBudgetSpec,
        )

        api.create(KIND_POD_DISRUPTION_BUDGET, PodDisruptionBudget(
            metadata=ObjectMeta(name=f"pdb-{ns}", namespace=ns),
            spec=PodDisruptionBudgetSpec(min_available=min_available,
                                         selector=dict(selector))))

    def test_split_counts_gang_amplification(self):
        api, plugin, fw, sched = quota_cluster()
        # 2-member gang; the PDB allows ONE disruption.  Evicting one
        # member amplifies to both, so the candidate must be violating
        # even though it alone is within budget.
        for i, node in enumerate(["node-0", "node-1"]):
            api.create(KIND_POD, make_pod(
                name=f"g-{i}", namespace="work",
                labels={C.LABEL_POD_GROUP: "job-g"},
                resources={C.RESOURCE_TPU: 4}, node_name=node,
                phase=RUNNING))
        self._pdb(api, "work", {C.LABEL_POD_GROUP: "job-g"}, 1)
        member = api.get(KIND_POD, "g-0", "work")
        violating, non_violating = plugin._split_pdb_violation(
            [member], None)
        assert [p.metadata.name for p in violating] == ["g-0"]
        assert non_violating == []

    def test_split_charges_each_member_once(self):
        api, plugin, fw, sched = quota_cluster()
        for i, node in enumerate(["node-0", "node-1"]):
            api.create(KIND_POD, make_pod(
                name=f"g-{i}", namespace="work",
                labels={C.LABEL_POD_GROUP: "job-g"},
                resources={C.RESOURCE_TPU: 4}, node_name=node,
                phase=RUNNING))
        self._pdb(api, "work", {C.LABEL_POD_GROUP: "job-g"}, 0)  # allow 2
        members = [api.get(KIND_POD, f"g-{i}", "work") for i in range(2)]
        # Both members as candidates: the first charges the whole gang (2),
        # the second is already fully charged — still non-violating.
        violating, non_violating = plugin._split_pdb_violation(members, None)
        assert violating == []
        assert [p.metadata.name for p in non_violating] == ["g-0", "g-1"]

    def test_pdb_protected_gang_survives_preemption(self):
        """VERDICT r2 #5: a candidate whose gang-mates are PDB-protected is
        marked violating, so the scheduler prefers a violation-free node —
        the gang survives a preemption that previously killed it."""
        api, plugin, fw, sched = quota_cluster(nodes=3, chips_per_node=8)
        # node-0: a plain victim, HIGHER priority than the gang members —
        # without PDB amplification the (cheaper) gang member would win.
        api.create(KIND_POD, make_pod(
            name="plain", namespace="work", priority=10,
            resources={C.RESOURCE_TPU: 8}, node_name="node-0",
            phase=RUNNING))
        # node-1/node-2: a 2-member gang, priority 0.
        for i in (1, 2):
            api.create(KIND_POD, make_pod(
                name=f"g-{i}", namespace="work", priority=0,
                labels={C.LABEL_POD_GROUP: "job-g"},
                resources={C.RESOURCE_TPU: 8}, node_name=f"node-{i}",
                phase=RUNNING))
        # Budget tolerates one gang disruption — but eviction would take 2.
        self._pdb(api, "work", {C.LABEL_POD_GROUP: "job-g"}, 1)

        api.create(KIND_POD, make_pod(
            name="pre", namespace="work", priority=100,
            resources={C.RESOURCE_TPU: 8}))
        sched.run_cycle()
        # The plain pod was evicted; the PDB-protected gang survived;
        # the preemptor bound straight into the synchronously freed
        # node (post-preemption retry).
        assert api.try_get(KIND_POD, "plain", "work") is None
        assert api.try_get(KIND_POD, "g-1", "work") is not None
        assert api.try_get(KIND_POD, "g-2", "work") is not None
        assert api.get(KIND_POD, "pre", "work") \
            .spec.node_name == "node-0"

    def test_pending_gang_member_consumes_no_budget(self):
        """Only RUNNING (healthy) members consume disruption budget —
        matching refresh_pdb_status's healthy accounting."""
        api, plugin, fw, sched = quota_cluster()
        api.create(KIND_POD, make_pod(
            name="g-0", namespace="work",
            labels={C.LABEL_POD_GROUP: "job-g"},
            resources={C.RESOURCE_TPU: 4}, node_name="node-0",
            phase=RUNNING))
        api.create(KIND_POD, make_pod(
            name="g-1", namespace="work",
            labels={C.LABEL_POD_GROUP: "job-g"},
            resources={C.RESOURCE_TPU: 4}))  # pending, unbound
        self._pdb(api, "work", {C.LABEL_POD_GROUP: "job-g"}, 0)
        # healthy=1, allowed=1: the running member alone is within budget;
        # the pending mate must not inflate the charge to 2.
        member = api.get(KIND_POD, "g-0", "work")
        violating, non_violating = plugin._split_pdb_violation(
            [member], None)
        assert violating == []
        assert [p.metadata.name for p in non_violating] == ["g-0"]

    def test_cross_node_gang_amplification_in_scoring(self):
        """The fewest-victims tiebreak must see the cluster-wide eviction
        set: one on-node gang member whose mates span other nodes is a
        3-pod eviction, not a 1-pod one."""
        api, plugin, fw, sched = quota_cluster(nodes=4, chips_per_node=8)
        for i in range(2):
            api.create(KIND_POD, make_pod(
                name=f"plain-{i}", namespace="work", priority=0,
                resources={C.RESOURCE_TPU: 4}, node_name="node-0",
                phase=RUNNING))
        for i in (1, 2, 3):
            api.create(KIND_POD, make_pod(
                name=f"g-{i}", namespace="work", priority=0,
                labels={C.LABEL_POD_GROUP: "job-g"},
                resources={C.RESOURCE_TPU: 8}, node_name=f"node-{i}",
                phase=RUNNING))
        api.create(KIND_POD, make_pod(
            name="pre", namespace="work", priority=100,
            resources={C.RESOURCE_TPU: 8}))
        sched.run_cycle()
        # Evicting two plain pods beats evicting a 3-member gang.
        assert api.try_get(KIND_POD, "plain-0", "work") is None
        assert api.try_get(KIND_POD, "plain-1", "work") is None
        for i in (1, 2, 3):
            assert api.try_get(KIND_POD, f"g-{i}", "work") is not None

    def test_gang_coherent_victim_accounting(self):
        """A reprieved candidate whose gang-mate stays a victim dies anyway
        at eviction — it must be folded back into the victim set so the
        accounting matches what evict_gang actually deletes."""
        from nos_tpu.exporter.metrics import REGISTRY

        api, plugin, fw, sched = quota_cluster(nodes=1, chips_per_node=8)
        # Two same-gang members on one node; preemptor needs only 4 chips,
        # so the reprieve pass would keep one member — but gang eviction
        # takes both.
        for i, prio in enumerate([0, 5]):
            api.create(KIND_POD, make_pod(
                name=f"g-{i}", namespace="work", priority=prio,
                labels={C.LABEL_POD_GROUP: "job-g"},
                resources={C.RESOURCE_TPU: 4}, node_name="node-0",
                phase=RUNNING))
        before = REGISTRY.snapshot().get(
            "nos_tpu_preemption_victims_total", {}).get("", 0)
        api.create(KIND_POD, make_pod(
            name="pre", namespace="work", priority=100,
            resources={C.RESOURCE_TPU: 4}))
        sched.run_cycle()
        assert api.try_get(KIND_POD, "g-0", "work") is None
        assert api.try_get(KIND_POD, "g-1", "work") is None
        after = REGISTRY.snapshot().get(
            "nos_tpu_preemption_victims_total", {}).get("", 0)
        assert after - before == 2  # both members accounted, not one


# ---------------------------------------------------------------------------
# Reconcilers
# ---------------------------------------------------------------------------


class TestElasticQuotaReconciler:
    def test_status_used_and_labels(self):
        api = APIServer()
        api.create(KIND_ELASTIC_QUOTA, make_eq(
            "eq-a", "ns-a", min={TPU_MEM: 64}))
        # Three running pods of 2 chips (32GB) each: first two in-quota.
        for i in range(3):
            api.create(KIND_POD, make_pod(
                name=f"p-{i}", namespace="ns-a",
                resources={C.RESOURCE_TPU: 2}, node_name="node-0",
                phase=RUNNING, creation_timestamp=float(i)))
        rec = ElasticQuotaReconciler(api, CALC)
        rec.reconcile("eq-a", "ns-a")
        eq = api.get(KIND_ELASTIC_QUOTA, "eq-a", "ns-a")
        assert eq.status.used == {TPU_MEM: 96.0}
        labels = [api.get(KIND_POD, f"p-{i}", "ns-a").metadata.labels[C.LABEL_CAPACITY]
                  for i in range(3)]
        assert labels == ["in-quota", "in-quota", "over-quota"]

    def test_labeling_ignores_resources_absent_from_min(self):
        """Regression: a pod requesting cpu under a quota whose min omits cpu
        must stay in-quota (labeling enforces only min's named resources,
        unlike the scheduler plugin's cpu/memory-always comparison)."""
        api = APIServer()
        api.create(KIND_ELASTIC_QUOTA, make_eq("eq-a", "ns-a", min={TPU_MEM: 64}))
        api.create(KIND_POD, make_pod(
            name="p", namespace="ns-a",
            resources={"cpu": 4, C.RESOURCE_TPU: 1},
            node_name="n", phase=RUNNING))
        ElasticQuotaReconciler(api, CALC).reconcile("eq-a", "ns-a")
        pod = api.get(KIND_POD, "p", "ns-a")
        assert pod.metadata.labels[C.LABEL_CAPACITY] == C.CAPACITY_IN_QUOTA

    def test_drops_non_enforced_resources(self):
        api = APIServer()
        api.create(KIND_ELASTIC_QUOTA, make_eq("eq-a", "ns-a", min={TPU_MEM: 64}))
        api.create(KIND_POD, make_pod(
            name="p", namespace="ns-a",
            resources={"cpu": 4, C.RESOURCE_TPU: 1},
            node_name="n", phase=RUNNING))
        rec = ElasticQuotaReconciler(api, CALC)
        rec.reconcile("eq-a", "ns-a")
        eq = api.get(KIND_ELASTIC_QUOTA, "eq-a", "ns-a")
        assert "cpu" not in eq.status.used
        assert eq.status.used[TPU_MEM] == 16.0


class TestCompositeElasticQuota:
    def test_spans_namespaces_and_deletes_overlapping_eq(self):
        api = APIServer()
        api.create(KIND_ELASTIC_QUOTA, make_eq("eq-1", "ns-1", min={TPU_MEM: 16}))
        ceq = CompositeElasticQuota(
            metadata=ObjectMeta(name="team", namespace="default"),
            spec=CompositeElasticQuotaSpec(
                namespaces=["ns-1", "ns-2"], min={TPU_MEM: 64}),
        )
        api.create(KIND_COMPOSITE_ELASTIC_QUOTA, ceq)
        for ns in ("ns-1", "ns-2"):
            api.create(KIND_POD, make_pod(
                name=f"p-{ns}", namespace=ns,
                resources={C.RESOURCE_TPU: 1}, node_name="n", phase=RUNNING))
        rec = CompositeElasticQuotaReconciler(api, CALC)
        rec.reconcile("team", "default")
        assert api.try_get(KIND_ELASTIC_QUOTA, "eq-1", "ns-1") is None
        out = api.get(KIND_COMPOSITE_ELASTIC_QUOTA, "team", "default")
        assert out.status.used == {TPU_MEM: 32.0}


    def test_ceq_namespace_growth_keeps_ledger(self):
        """Regression: expanding a CompositeElasticQuota over a namespace
        that had its own ElasticQuota must keep the CEQ's tracked usage and
        absorb the newly covered namespace's assigned pods."""
        api = APIServer()
        plugin = CapacityScheduling(CALC)
        plugin.attach(api)
        api.create(KIND_COMPOSITE_ELASTIC_QUOTA, CompositeElasticQuota(
            metadata=ObjectMeta(name="team", namespace="default"),
            spec=CompositeElasticQuotaSpec(
                namespaces=["ns-1", "ns-2"], min={TPU_MEM: 128})))
        api.create(KIND_ELASTIC_QUOTA, make_eq("eq-3", "ns-3", min={TPU_MEM: 32}))
        api.create(KIND_POD, make_pod(
            name="a", namespace="ns-1", resources={C.RESOURCE_TPU: 4},
            node_name="n", phase=RUNNING))
        api.create(KIND_POD, make_pod(
            name="b", namespace="ns-3", resources={C.RESOURCE_TPU: 1},
            node_name="n", phase=RUNNING))
        assert plugin.elastic_quota_infos["ns-1"].used[TPU_MEM] == 64
        # Expand the CEQ to also cover ns-3.
        api.patch(KIND_COMPOSITE_ELASTIC_QUOTA, "team", "default",
                  mutate=lambda o: o.spec.namespaces.append("ns-3"))
        info = plugin.elastic_quota_infos["ns-3"]
        assert info.composite
        assert info is plugin.elastic_quota_infos["ns-1"]
        # 64GB carried + 16GB from ns-3's pod recounted.
        assert info.used[TPU_MEM] == 80
        assert set(info.pods) == {"ns-1/a", "ns-3/b"}

    def test_ceq_namespace_shrink_releases_usage(self):
        """Regression: dropping a namespace from a CompositeElasticQuota
        must release the booked usage of that namespace's pods."""
        api = APIServer()
        plugin = CapacityScheduling(CALC)
        plugin.attach(api)
        api.create(KIND_COMPOSITE_ELASTIC_QUOTA, CompositeElasticQuota(
            metadata=ObjectMeta(name="team", namespace="default"),
            spec=CompositeElasticQuotaSpec(
                namespaces=["ns-1", "ns-2"], min={TPU_MEM: 128})))
        api.create(KIND_POD, make_pod(
            name="a", namespace="ns-1", resources={C.RESOURCE_TPU: 2},
            node_name="n", phase=RUNNING))
        api.create(KIND_POD, make_pod(
            name="b", namespace="ns-2", resources={C.RESOURCE_TPU: 4},
            node_name="n", phase=RUNNING))
        assert plugin.elastic_quota_infos["ns-1"].used[TPU_MEM] == 96
        api.patch(KIND_COMPOSITE_ELASTIC_QUOTA, "team", "default",
                  mutate=lambda o: setattr(o.spec, "namespaces", ["ns-1"]))
        info = plugin.elastic_quota_infos["ns-1"]
        assert info.used[TPU_MEM] == 32
        assert set(info.pods) == {"ns-1/a"}
        assert "ns-2" not in plugin.elastic_quota_infos


# ---------------------------------------------------------------------------
# Webhooks
# ---------------------------------------------------------------------------


class TestWebhooks:
    def test_one_eq_per_namespace(self):
        api = APIServer()
        api.create(KIND_ELASTIC_QUOTA, make_eq("eq-1", "ns-1", min={}))
        with pytest.raises(AdmissionError):
            validate_elastic_quota(api, make_eq("eq-2", "ns-1", min={}))
        # update of the same EQ passes
        validate_elastic_quota(api, make_eq("eq-1", "ns-1", min={TPU_MEM: 1}))

    def test_eq_rejected_in_ceq_namespace(self):
        api = APIServer()
        api.create(KIND_COMPOSITE_ELASTIC_QUOTA, CompositeElasticQuota(
            metadata=ObjectMeta(name="team", namespace="default"),
            spec=CompositeElasticQuotaSpec(namespaces=["ns-1"], min={})))
        with pytest.raises(AdmissionError):
            validate_elastic_quota(api, make_eq("eq-1", "ns-1", min={}))

    def test_ceq_overlap_rejected(self):
        api = APIServer()
        api.create(KIND_COMPOSITE_ELASTIC_QUOTA, CompositeElasticQuota(
            metadata=ObjectMeta(name="team-a", namespace="default"),
            spec=CompositeElasticQuotaSpec(namespaces=["ns-1", "ns-2"], min={})))
        with pytest.raises(AdmissionError):
            validate_composite_elastic_quota(api, CompositeElasticQuota(
                metadata=ObjectMeta(name="team-b", namespace="default"),
                spec=CompositeElasticQuotaSpec(namespaces=["ns-2"], min={})))

    def test_ceq_requires_namespaces(self):
        with pytest.raises(AdmissionError):
            validate_composite_elastic_quota(APIServer(), CompositeElasticQuota(
                metadata=ObjectMeta(name="x", namespace="default"),
                spec=CompositeElasticQuotaSpec(namespaces=[], min={})))

    def test_webhooks_enforced_at_api_level(self):
        """install_quota_webhooks makes the API substrate itself reject
        invalid quota writes — the runtime admission path."""
        from nos_tpu.api.elasticquota import install_quota_webhooks
        api = APIServer()
        install_quota_webhooks(api)
        api.create(KIND_ELASTIC_QUOTA, make_eq("eq-1", "ns-1", min={}))
        with pytest.raises(AdmissionError):
            api.create(KIND_ELASTIC_QUOTA, make_eq("eq-2", "ns-1", min={}))


class TestReconcileReentrancy:
    def test_many_pods_label_flip_no_recursion(self):
        """Regression: with watches bound, relabeling many pods must not
        recurse through the synchronous watch fan-out."""
        api = APIServer()
        api.create(KIND_ELASTIC_QUOTA, make_eq("eq-a", "ns-a", min={TPU_MEM: 16}))
        rec = ElasticQuotaReconciler(api, CALC)
        rec.bind()
        import sys
        limit = sys.getrecursionlimit()
        n = limit // 3  # enough pods that naive recursion would blow the stack
        for i in range(n):
            api.create(KIND_POD, make_pod(
                name=f"p-{i}", namespace="ns-a",
                resources={C.RESOURCE_TPU: 1}, node_name="n",
                phase=RUNNING, creation_timestamp=float(i)))
        labels = [p.metadata.labels.get(C.LABEL_CAPACITY)
                  for p in api.list(KIND_POD, namespace="ns-a")]
        assert labels.count(C.CAPACITY_IN_QUOTA) == 1
        assert labels.count(C.CAPACITY_OVER_QUOTA) == n - 1
