"""Hardening tests (round-2 VERDICT #8): the envtest-discipline gaps.

- kill/restart a controller and an agent mid-plan: all durable state
  lives in node annotations (SURVEY.md §5 checkpoint/resume), so fresh
  processes must resume the handshake where the dead ones left it;
- native-shim fault injection through the actuator: the REAL C++ error
  paths (rc=-1 infeasible create, unknown-device delete) plus a runtime
  that fails transiently, asserting the duplicate-plan guard does not
  wedge the retry;
- packer property tests on random multisets: Python and native searches
  agree on feasibility, placements actually tile (in-bounds, aligned,
  non-overlapping), and feasibility is monotone under taking subsets;
- a 64-host scale point bounding the scheduler cycle wall time.
"""

from __future__ import annotations

import random
import time

import pytest

from nos_tpu.controllers.node_controller import NodeController
from nos_tpu.controllers.pod_controller import PodController
from nos_tpu.controllers.sliceagent.agent import SliceAgent
from nos_tpu.device.fake import FakePodResources, FakeTpuRuntime
from nos_tpu.kube.client import APIServer, KIND_NODE, KIND_POD
from nos_tpu.partitioning.slicepart import SliceNodeInitializer
from nos_tpu.partitioning.slicepart.factory import (
    new_slice_partitioner_controller,
)
from nos_tpu.partitioning.state import ClusterState
from nos_tpu.scheduler.framework import Framework, NodeResourcesFit
from nos_tpu.scheduler.gang import TopologyFilter
from nos_tpu.scheduler.scheduler import Scheduler
from nos_tpu.testing.factory import make_slice_pod, make_tpu_node
from nos_tpu.topology import Shape, V5E
from nos_tpu.topology.annotations import (
    spec_matches_status, spec_plan_id, status_plan_id,
)


class Cluster:
    """Minimal decision plane over one fake host, with the ability to
    'kill' (drop) and recreate each component."""

    def __init__(self):
        self.api = APIServer()
        self.clock = [0.0]
        self.state = ClusterState()
        NodeController(self.api, self.state,
                       SliceNodeInitializer(self.api)).bind()
        PodController(self.api, self.state).bind()
        self.partitioner = self._new_partitioner()
        self.api.create(KIND_NODE, make_tpu_node("host-0"))
        self.runtime = FakeTpuRuntime(V5E)
        self.agent = self._new_agent()
        self.scheduler = Scheduler(self.api, Framework())

    def demand(self, shape: str, qty: int, name: str) -> None:
        """Submit a pod and let the scheduler mark it unschedulable —
        the partitioner only considers pods the scheduler gave up on
        (ExtraResourcesCouldHelpScheduling)."""
        self.api.create(KIND_POD, make_slice_pod(shape, qty, name=name))
        self.scheduler.run_cycle()

    def _new_partitioner(self):
        ctl = new_slice_partitioner_controller(
            self.api, self.state, batch_timeout_s=60.0, batch_idle_s=10.0,
            clock=lambda: self.clock[0])
        ctl.bind()
        return ctl

    def _new_agent(self) -> SliceAgent:
        # same runtime (the hardware keeps its carved slices across an
        # agent restart), fresh in-process state
        return SliceAgent(self.api, "host-0", self.runtime,
                          FakePodResources())

    def node(self):
        return self.api.get(KIND_NODE, "host-0")


class TestKillRestartMidPlan:
    def test_agent_restart_resumes_plan_from_annotations(self):
        c = Cluster()
        c.agent.start()
        c.agent.tick()  # init geometry reported
        # demand forces a repartition plan onto the node
        c.demand("2x2", 2, "want")
        c.clock[0] += 61.0
        c.partitioner.process_if_ready()
        node = c.node()
        plan_id = spec_plan_id(node.metadata.annotations, family="slice")
        assert plan_id, "partitioner wrote no plan"
        assert not spec_matches_status(node.metadata.annotations)

        # the agent dies before actuating; a FRESH agent (fresh
        # SharedState, same hardware) must pick the plan up purely from
        # the annotations.  The dead process's watches die with it:
        c.agent.stop()
        c.agent = c.agent2 = c._new_agent()
        c.agent.start()
        c.agent.tick()
        node = c.node()
        assert spec_matches_status(node.metadata.annotations)
        assert status_plan_id(
            node.metadata.annotations, family="slice") == plan_id

    def test_partitioner_restart_honors_inflight_handshake(self):
        c = Cluster()
        c.agent.start()
        c.agent.tick()
        c.demand("2x2", 2, "want")
        c.clock[0] += 61.0
        c.partitioner.process_if_ready()
        node = c.node()
        plan_id = spec_plan_id(node.metadata.annotations, family="slice")
        assert plan_id

        # partitioner dies; its replacement sees the unreported plan and
        # must NOT write a second plan while the handshake is open
        c.partitioner = c._new_partitioner()
        c.demand("1x1", 1, "more")
        c.clock[0] += 61.0
        c.partitioner.process_if_ready()
        node = c.node()
        assert spec_plan_id(
            node.metadata.annotations, family="slice") == plan_id

        # agent reports -> handshake closes -> the new partitioner may
        # now plan for the extra demand
        c.agent.tick()
        c.clock[0] += 61.0
        c.partitioner.process_if_ready()
        node = c.node()
        new_plan = spec_plan_id(node.metadata.annotations, family="slice")
        assert new_plan and new_plan != plan_id


class _FlakyRuntime:
    """Delegating runtime whose create_slices fails until `heal()`."""

    def __init__(self, inner):
        self._inner = inner
        self.fail = True

    def heal(self):
        self.fail = False

    def create_slices(self, unit_index, shapes):
        if self.fail:
            raise RuntimeError("injected: create_slices rc=-2")
        return self._inner.create_slices(unit_index, shapes)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class TestNativeFaultInjection:
    def test_real_shim_error_paths(self):
        """The C++ shim's rc<0 paths surface as typed exceptions."""
        from nos_tpu.device import native
        from nos_tpu.topology.errors import DeviceNotFoundError

        if not native.available():
            pytest.skip("native shim did not build")
        rt = native.NativeTpuRuntime(V5E)
        with pytest.raises(DeviceNotFoundError):
            rt.delete_slice("no-such-device")          # rc != 0
        from nos_tpu.topology.errors import PlacementInfeasibleError
        with pytest.raises(PlacementInfeasibleError):
            rt.create_slices(0, [Shape.parse("2x4")] * 2)   # rc=-1

    def test_actuator_retries_after_transient_create_failure(self):
        c = Cluster()
        flaky = _FlakyRuntime(c.runtime)
        c.agent.stop()
        c.agent = SliceAgent(c.api, "host-0", flaky, FakePodResources())
        c.agent.start()
        c.agent.tick()
        c.demand("2x2", 2, "want")
        c.clock[0] += 61.0
        c.partitioner.process_if_ready()

        c.agent.tick()  # create fails (injected); must not wedge
        node = c.node()
        assert not spec_matches_status(node.metadata.annotations)

        flaky.heal()
        c.agent.tick()  # the SAME plan must be retried, not deduped away
        node = c.node()
        assert spec_matches_status(node.metadata.annotations)

    def test_reporter_survives_listing_failure(self):
        c = Cluster()

        class BrokenList(_FlakyRuntime):
            def list_devices(self):
                if self.fail:
                    raise RuntimeError("injected: truncated list output")
                return self._inner.list_devices()

        broken = BrokenList(c.runtime)
        broken.fail = False
        c.agent.stop()
        c.agent = SliceAgent(c.api, "host-0", broken, FakePodResources())
        c.agent.start()
        c.agent.tick()
        broken.fail = True
        with pytest.raises(RuntimeError):
            c.agent.tick()   # the run loop logs this in production
        broken.fail = False
        c.agent.tick()       # recovery needs no restart
        assert spec_matches_status(c.node().metadata.annotations)


def _occupancy(placements, block: Shape) -> int:
    bdims = tuple(block.dims) + (1,) * (3 - len(block.dims))
    mask = 0
    for pl in placements:
        dims = tuple(pl.dims) + (1,) * (3 - len(pl.dims))
        off = tuple(pl.offset) + (0,) * (3 - len(pl.offset))
        for x in range(dims[0]):
            for y in range(dims[1]):
                for z in range(dims[2]):
                    px, py, pz = off[0] + x, off[1] + y, off[2] + z
                    assert px < bdims[0] and py < bdims[1] and pz < bdims[2]
                    bit = 1 << (px * bdims[1] * bdims[2] + py * bdims[2] + pz)
                    assert not (mask & bit), "overlapping placements"
                    mask |= bit
    return mask


class TestPackerProperties:
    SHAPES = [Shape.parse(s) for s in ("1x1", "1x2", "2x2", "1x4", "2x4")]

    def _random_multiset(self, rng) -> dict:
        counts: dict = {}
        budget = V5E.host_block.chips + rng.randrange(0, 5)  # may overflow
        while budget > 0:
            s = rng.choice(self.SHAPES)
            counts[s] = counts.get(s, 0) + 1
            budget -= s.chips
        return counts

    def test_python_and_native_agree_and_tile(self):
        from nos_tpu.device import native
        from nos_tpu.topology import packing

        rng = random.Random(7)
        block = V5E.host_block
        checked_native = 0
        for _ in range(60):
            counts = self._random_multiset(rng)
            key = packing._counts_key(counts)
            py = packing._pack_masks(block, key, occupied=0,
                                     require_full=False)
            if py is not None:
                _occupancy(py, block)  # in-bounds, non-overlapping
                placed = sorted(p.shape.canonical() for p in py)
                want = sorted(s.canonical() for s, n in counts.items()
                              for _ in range(n))
                assert placed == want
            if native.available():
                nat = native.native_packer(block, key, 0, False)
                if nat is not NotImplemented:
                    checked_native += 1
                    assert (nat is None) == (py is None), counts
                    if nat is not None:
                        _occupancy(nat, block)
        if native.available():
            assert checked_native >= 50

    def test_feasibility_monotone_under_subsets(self):
        from nos_tpu.topology import packing

        rng = random.Random(11)
        block = V5E.host_block
        for _ in range(40):
            counts = self._random_multiset(rng)
            if not packing.feasible(block, counts):
                continue
            sub = dict(counts)
            victim = rng.choice(list(sub))
            sub[victim] -= 1
            if not sub[victim]:
                del sub[victim]
            assert packing.feasible(block, sub), (counts, sub)

    def test_require_full_is_an_exact_tiling(self):
        from nos_tpu.topology import packing

        block = V5E.host_block
        res = packing.pack(block, {Shape.parse("2x2"): 2}, require_full=True)
        assert res is not None
        assert sum(p.shape.chips for p in res) == block.chips
        assert packing.pack(block, {Shape.parse("2x2"): 1},
                            require_full=True) is None


class TestSchedulerScale64Hosts:
    def test_cycle_p99_stays_sub_second(self):
        api = APIServer()
        for i in range(64):
            node = make_tpu_node(
                f"host-{i}", pod_id=f"pod-{i // 16}", host_index=i % 16,
                status_geometry={"free": {"2x4": 1}, "used": {}})
            api.create(KIND_NODE, node)
        scheduler = Scheduler(
            api, Framework([NodeResourcesFit(), TopologyFilter(api)]))

        rng = random.Random(3)
        for i in range(96):
            shape = rng.choice(["1x1", "2x2", "2x4"])
            api.create(KIND_POD, make_slice_pod(shape, 1, name=f"p{i}"))

        cycles = []
        for _ in range(12):
            # process CPU time, not wall time: the bound is about the
            # scheduler's own cost at 64-host scale, and wall time
            # starves under parallel load (benchmarks, CI neighbors)
            t0 = time.process_time()
            scheduler.run_cycle()
            cycles.append(time.process_time() - t0)
        cycles.sort()
        # median bounds the steady-state cost robustly;
        # the max is a gross-regression tripwire only
        p50, worst = cycles[len(cycles) // 2], cycles[-1]
        assert p50 < 1.0, f"64-host cycle p50 {p50:.3f}s CPU"
        assert worst < 10.0, f"64-host cycle worst {worst:.3f}s CPU"
        bound = sum(1 for p in api.list(KIND_POD) if p.spec.node_name)
        assert bound > 0



class TestOversubscriptionGuard:
    def test_bind_rejected_when_bound_profile_was_recarved_away(self):
        """Mid-repartition race: a bound pod whose slice profile was
        re-carved away subtracts from NO advertised profile, so the
        per-profile fit sees free capacity that is physically spoken
        for.  The chip-equivalent guard must refuse the bind."""
        from nos_tpu.scheduler.framework import (
            CycleState, NodeInfo, NodeResourcesFit,
        )

        node = make_tpu_node(
            "n1", status_geometry={"free": {"1x1": 4}, "used": {}})
        # the node now advertises 4x 1x1 (4 chips carved)...
        ni = NodeInfo(node=node)
        # ...but a pod bound under the PREVIOUS geometry holds a 2x2
        # the carve dropped: it subtracts from no advertised profile
        ni.add_pod(make_slice_pod("2x2", 1, name="stale", node_name="n1"))
        fit = NodeResourcesFit()
        verdict = fit.filter(CycleState(),
                             make_slice_pod("1x1", 1, name="new"), ni)
        assert not verdict.is_success
        assert "chips" in verdict.message

    def test_guard_allows_full_use_of_consistent_geometry(self):
        from nos_tpu.scheduler.framework import (
            CycleState, NodeInfo, NodeResourcesFit,
        )

        node = make_tpu_node(
            "n1", status_geometry={"free": {"2x2": 2}, "used": {}})
        ni = NodeInfo(node=node)
        fit = NodeResourcesFit()
        for i in range(2):
            pod = make_slice_pod("2x2", 1, name=f"p{i}", node_name="n1")
            assert fit.filter(CycleState(), pod, ni).is_success
            ni.add_pod(pod)
        assert not fit.filter(
            CycleState(), make_slice_pod("2x2", 1, name="p2"), ni).is_success


class TestConcurrentChurn:
    def test_threaded_control_plane_survives_churn(self):
        """Race hunt at the process-model level: submitter and deleter
        threads churn pods for a fixed window while the
        partitioner/scheduler/agent run loops are live.  The live
        invariant is falsifiable: no host may ever be oversubscribed
        (bound chips > its 8-chip block).  Demand is capped below
        cluster capacity so afterwards EVERY surviving pod must converge
        to bound + Running — a stuck pod fails the test."""
        import threading

        from nos_tpu.api.config import PartitionerConfig
        from nos_tpu.cmd.assembly import build_partitioner_main, build_scheduler
        from nos_tpu.device import default_tpu_runtime
        from nos_tpu.kube.client import NotFound
        from nos_tpu.kube.objects import RUNNING
        from nos_tpu.kube.resources import pod_request
        from nos_tpu.topology.profile import extract_slice_requests

        def pod_chips(p) -> int:
            return sum(s.chips * q for s, q in
                       extract_slice_requests(pod_request(p)).items())

        api = APIServer()
        state = ClusterState()
        cfg = PartitionerConfig(batch_timeout_s=0.2, batch_idle_s=0.05,
                                poll_interval_s=0.01)
        main, _ = build_partitioner_main(api, state, cfg)
        for i in range(2):
            api.create(KIND_NODE, make_tpu_node(
                f"host-{i}", pod_id="pod-0", host_index=i))
            agent = SliceAgent(api, f"host-{i}", default_tpu_runtime(V5E),
                               FakePodResources())
            agent.start()
            main.add_loop(f"agent-{i}", agent.tick, 0.01)
        main.add_loop("sched", build_scheduler(api).run_cycle, 0.01)
        main.start()

        stop = threading.Event()
        errors: list[str] = []
        DEMAND_CAP = 14        # always below the 16-chip capacity:
        cap_lock = threading.Lock()   # convergence stays feasible

        def submitter(tid: int) -> None:
            n = 0
            while not stop.is_set():
                # check-then-create under a lock: three submitters racing
                # past the cap together could strand unbindable pods
                with cap_lock:
                    live = sum(pod_chips(p) for p in api.list(KIND_POD))
                    if live <= DEMAND_CAP - 4:  # worst new pod is 4 chips
                        n += 1
                        try:
                            api.create(KIND_POD, make_slice_pod(
                                random.choice(["1x1", "1x2", "2x2"]), 1,
                                name=f"churn-{tid}-{n}"))
                        except Exception as e:  # noqa: BLE001
                            errors.append(f"submit: {e}")
                time.sleep(0.004)

        def deleter() -> None:
            while not stop.is_set():
                for p in api.list(KIND_POD):
                    if p.spec.node_name and random.random() < 0.3:
                        try:
                            api.delete(KIND_POD, p.metadata.name,
                                       p.metadata.namespace)
                        except NotFound:
                            pass
                        except Exception as e:  # noqa: BLE001
                            errors.append(f"delete: {e}")
                time.sleep(0.01)

        threads = [threading.Thread(target=submitter, args=(t,))
                   for t in range(3)] + [threading.Thread(target=deleter)]
        try:
            for t in threads:
                t.start()
            # Live-churn window: submit/bind/delete races overlap the
            # scheduler + repartitioner + agents the whole time.
            churn_until = time.monotonic() + 4.0
            while time.monotonic() < churn_until:
                per_node: dict[str, int] = {}
                for p in api.list(KIND_POD):
                    if p.spec.node_name:
                        per_node[p.spec.node_name] = \
                            per_node.get(p.spec.node_name, 0) + pod_chips(p)
                for node, chips in per_node.items():
                    assert chips <= 8, (
                        f"{node} oversubscribed: {chips} chips bound")
                time.sleep(0.02)
            stop.set()
            for t in threads:
                t.join(timeout=5.0)
            assert not any(t.is_alive() for t in threads)
            assert not errors, errors[:3]

            # Post-churn: demand was capped below capacity, so EVERY
            # surviving pod must converge to bound + Running.  60 s:
            # the fixed-period run loops contend for this process's GIL
            # with the checker thread, and a loaded CI box stretches the
            # standalone few-second convergence substantially.
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                pods = api.list(KIND_POD)
                if pods and all(p.spec.node_name
                                and p.status.phase == RUNNING
                                for p in pods):
                    break
                time.sleep(0.05)
            else:
                stuck = [(p.metadata.name, p.status.phase)
                         for p in api.list(KIND_POD)
                         if not (p.spec.node_name
                                 and p.status.phase == RUNNING)]
                pytest.fail(f"pods stuck after churn: {stuck[:5]}")
        finally:
            stop.set()
            main.shutdown()
