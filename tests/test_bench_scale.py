"""Structure tests for the ISSUE 18 scale bench tier (bench_fleet).

Tier-1 runs the scale tier's exact code path on a toy fleet and pins
the report SHAPE — the keys CI's perf gate and the acceptance JSON
consume.  No wall-clock assertions here (this box's timing noise is
±40%); the perf bounds live in the slow-marked smoke/scale runs.
"""

from __future__ import annotations

import sys

import pytest

sys.path.insert(0, ".")  # bench_fleet lives at the repo root

import bench_fleet  # noqa: E402


@pytest.fixture(scope="module")
def scale_report():
    # tiny fleet: the multi-generation pool split needs a few dozen
    # hosts; 2 cycles + 1 plan repeat keeps this inside tier-1 budget
    return bench_fleet.run_scale_bench(
        hosts=48, pods=96, steady_cycles=2, warmup_cycles=1,
        plan_repeats=1)


class TestScaleBenchReport:
    def test_acceptance_keys_present(self, scale_report):
        for key in ("hosts", "pods", "resident_pending", "incremental",
                    "warmup_cycle_wall_ms", "scheduler_cycle_wall_ms",
                    "backstop_cycle_ms", "plan_delta_pods",
                    "plan_wall_ms", "scale_targets"):
            assert key in scale_report, f"scale report lost key {key!r}"

    def test_named_targets_shape(self, scale_report):
        targets = scale_report["scale_targets"]
        assert set(targets) == {"cycle_p99_ms", "plan_p50_ms"}
        assert targets["cycle_p99_ms"]["target"] == \
            bench_fleet.SCALE_CYCLE_P99_MS
        assert targets["plan_p50_ms"]["target"] == \
            bench_fleet.SCALE_PLAN_P50_MS
        for gate in targets.values():
            assert set(gate) == {"target", "value", "ok"}
            assert gate["value"] > 0
            assert gate["ok"] == (gate["value"] < gate["target"])

    def test_wall_summaries_have_percentiles(self, scale_report):
        for key in ("warmup_cycle_wall_ms", "scheduler_cycle_wall_ms",
                    "plan_wall_ms"):
            summary = scale_report[key]
            assert {"p50", "p99"} <= set(summary)
            assert summary["p50"] <= summary["p99"]

    def test_backstop_measured_when_incremental(self, scale_report):
        # the forced full-rescan recovery cycle is the honesty metric
        # for the dirty-set fast path: it must be measured (not None)
        # whenever the bench ran incrementally
        assert scale_report["incremental"] is True
        assert scale_report["backstop_cycle_ms"] is not None
        assert scale_report["backstop_cycle_ms"] > 0

    def test_full_rescan_mode_skips_backstop_metric(self):
        report = bench_fleet.run_scale_bench(
            hosts=48, pods=96, steady_cycles=1, warmup_cycles=1,
            plan_repeats=1, incremental=False)
        assert report["incremental"] is False
        assert report["backstop_cycle_ms"] is None
