"""noslint + lockcheck acceptance (docs/static-analysis.md).

Two halves:

- the **gate**: rules N001–N006 run over the whole ``nos_tpu/`` tree and
  any unsuppressed violation fails tier-1 — the analyzer ships with the
  tree clean, so a regression in any invariant is a test failure with
  the file:line in the message;
- **per-rule fixtures**: for each rule a violating snippet, a clean
  snippet, and a pragma-suppressed snippet run through ``lint_source``,
  so rule semantics are pinned independently of the tree's current
  state.  Plus unit tests for the dynamic lock-order checker (a real
  A→B/B→A inversion, reentrancy, Condition compatibility, guarded
  shared-state writes).
"""

from __future__ import annotations

import os
import threading

import pytest

from nos_tpu.analysis import default_rules, lint_source, run
from nos_tpu.analysis.__main__ import main as noslint_main
from nos_tpu.analysis.rules import (
    InjectableClock, MetricDiscipline, NameHygiene, NoBlockingUnderLock,
    NoSwallowedExceptions, RetryWrappedWrites,
)
from nos_tpu.testing.lockcheck import LockGraph, guard_state

pytestmark = pytest.mark.analysis

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO_ROOT, "nos_tpu")


def rules_of(v):
    return [x.rule for x in v]


# ---------------------------------------------------------------------------
# The gate: the tree is clean.
# ---------------------------------------------------------------------------

class TestTreeIsClean:
    def test_noslint_zero_violations_on_nos_tpu(self):
        report = run(default_rules(), [PACKAGE], root=REPO_ROOT)
        assert report.files > 100      # the sweep actually saw the tree
        rendered = "\n".join(v.render() for v in report.violations)
        assert report.ok, f"noslint violations:\n{rendered}"

    def test_cli_exits_zero_and_lists_rules(self, capsys):
        assert noslint_main([PACKAGE, "--no-cache"]) == 0
        assert noslint_main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule_id in ("N001", "N002", "N003", "N004", "N005", "N006",
                        "N007", "N008", "N009", "N010", "N011", "N012"):
            assert rule_id in out

    def test_every_suppression_carries_a_reason(self):
        report = run(default_rules(), [PACKAGE], root=REPO_ROOT)
        # N000 findings are pragmas without reasons; the gate above
        # already fails on them — this pins the contract explicitly
        assert not [v for v in report.violations if v.rule == "N000"]


# ---------------------------------------------------------------------------
# N001: retry-wrapped writes
# ---------------------------------------------------------------------------

class TestN001:
    def test_flags_raw_patch_and_update(self):
        src = (
            "def f(api, cm):\n"
            "    api.patch('Node', 'n', mutate=lambda o: None)\n"
            "    api.update(KIND_CONFIGMAP, cm)\n"
        )
        v = lint_source(src, [RetryWrappedWrites()])
        assert rules_of(v) == ["N001", "N001"]

    def test_wrapped_and_dict_update_are_clean(self):
        src = (
            "from nos_tpu.utils.retry import retry_on_conflict\n"
            "def f(api, d):\n"
            "    retry_on_conflict(api, 'Node', 'n', lambda o: None)\n"
            "    d.update({'a': 1})\n"          # dict.update: not an API write
            "    obj.metadata.annotations.update(extra)\n"
        )
        assert lint_source(src, [RetryWrappedWrites()]) == []

    def test_substrate_and_super_calls_exempt(self):
        src = (
            "class Chaos:\n"
            "    def patch(self, kind, name, ns='', *, mutate=None):\n"
            "        return super().patch(kind, name, ns, mutate=mutate)\n"
        )
        assert lint_source(src, [RetryWrappedWrites()]) == []
        raw = "api.patch('Node', 'n', mutate=m)\n"
        assert lint_source(
            raw, [RetryWrappedWrites()],
            relpath="nos_tpu/kube/rest.py") == []    # substrate file

    def test_pragma_suppresses_with_reason(self):
        src = (
            "def f(api, cm):\n"
            "    # noslint: N001 — CAS loss is semantically a lost election\n"
            "    api.update(KIND_CONFIGMAP, cm)\n"
        )
        assert lint_source(src, [RetryWrappedWrites()]) == []

    def test_pragma_without_reason_is_flagged(self):
        src = (
            "def f(api, cm):\n"
            "    api.update(KIND_CONFIGMAP, cm)  # noslint: N001\n"
        )
        v = lint_source(src, [RetryWrappedWrites()])
        # N001 suppressed, but the naked pragma itself is an N000
        assert rules_of(v) == ["N000"]


# ---------------------------------------------------------------------------
# N002: injectable clock
# ---------------------------------------------------------------------------

class TestN002:
    REL = "nos_tpu/controllers/foo.py"

    def test_flags_raw_time_calls(self):
        src = (
            "import time\n"
            "from time import sleep\n"
            "def tick():\n"
            "    t = time.time()\n"
            "    sleep(1)\n"
            "    time.monotonic()\n"
        )
        v = lint_source(src, [InjectableClock()], relpath=self.REL)
        assert rules_of(v) == ["N002", "N002", "N002"]

    def test_injectable_default_reference_is_clean(self):
        src = (
            "import time\n"
            "from typing import Callable\n"
            "class C:\n"
            "    def __init__(self, clock: Callable[[], float]"
            " = time.monotonic):\n"
            "        self._clock = clock\n"
            "    def now(self):\n"
            "        return self._clock()\n"
        )
        assert lint_source(src, [InjectableClock()], relpath=self.REL) == []

    def test_out_of_scope_paths_unflagged(self):
        src = "import time\nt = time.time()\n"
        assert lint_source(src, [InjectableClock()],
                           relpath="nos_tpu/exporter/__init__.py") == []

    def test_pragma_suppressed(self):
        src = (
            "import time\n"
            "# noslint: N002 — wall-clock timestamp for a log payload only\n"
            "t = time.time()\n"
        )
        assert lint_source(src, [InjectableClock()], relpath=self.REL) == []


# ---------------------------------------------------------------------------
# N003: metric discipline
# ---------------------------------------------------------------------------

class TestN003:
    def test_unregistered_and_bad_name_flagged(self):
        src = (
            "REGISTRY.inc('nos_tpu_good_total')\n"
            "REGISTRY.inc('bad_prefix_total')\n"
        )
        v = lint_source(src, [MetricDiscipline()])
        msgs = [x.message for x in v]
        assert any("never registered" in m for m in msgs)
        assert any("nos_tpu_[a-z0-9_]+" in m for m in msgs)

    def test_double_describe_flagged(self):
        src = (
            "REGISTRY.describe('nos_tpu_x_total', 'a')\n"
            "REGISTRY.describe('nos_tpu_x_total', 'b')\n"
        )
        v = lint_source(src, [MetricDiscipline()])
        assert any("more than once" in x.message for x in v)

    def test_inconsistent_label_keys_flagged(self):
        src = (
            "REGISTRY.describe('nos_tpu_x_total', 'help')\n"
            "REGISTRY.inc('nos_tpu_x_total', labels={'kind': 'a'})\n"
            "REGISTRY.inc('nos_tpu_x_total', labels={'node': 'b'})\n"
        )
        v = lint_source(src, [MetricDiscipline()])
        assert any("label keys" in x.message for x in v)

    def test_consistent_usage_clean(self):
        src = (
            "REGISTRY.describe('nos_tpu_x_total', 'help')\n"
            "REGISTRY.inc('nos_tpu_x_total', labels={'kind': 'a'})\n"
            "REGISTRY.inc('nos_tpu_x_total', 2.0, labels={'kind': 'b'})\n"
        )
        assert lint_source(src, [MetricDiscipline()]) == []

    def test_non_literal_name_flagged(self):
        src = "REGISTRY.inc(name_var)\n"
        v = lint_source(src, [MetricDiscipline()])
        assert any("string literal" in x.message for x in v)

    # -- histogram bucket extension -----------------------------------------
    def test_literal_buckets_clean(self):
        src = (
            "REGISTRY.describe('nos_tpu_x_seconds', 'h',\n"
            "                  buckets=(0.1, 1.0, 10.0))\n"
            "REGISTRY.observe('nos_tpu_x_seconds', 0.2,\n"
            "                 labels={'class': 'a'})\n"
        )
        assert lint_source(src, [MetricDiscipline()]) == []

    def test_non_literal_buckets_flagged(self):
        src = (
            "REGISTRY.describe('nos_tpu_x_seconds', 'h')\n"
            "REGISTRY.observe('nos_tpu_x_seconds', 0.2, buckets=BOUNDS)\n"
        )
        v = lint_source(src, [MetricDiscipline()])
        assert any("literal tuple/list" in x.message for x in v)

    def test_non_increasing_buckets_flagged(self):
        src = (
            "REGISTRY.describe('nos_tpu_x_seconds', 'h',\n"
            "                  buckets=(1.0, 1.0, 2.0))\n"
        )
        v = lint_source(src, [MetricDiscipline()])
        assert any("strictly increasing" in x.message for x in v)

    def test_conflicting_bucket_layouts_flagged(self):
        src = (
            "REGISTRY.describe('nos_tpu_x_seconds', 'h',\n"
            "                  buckets=(0.1, 1.0))\n"
            "REGISTRY.observe('nos_tpu_x_seconds', 0.2,\n"
            "                 buckets=(0.5, 5.0))\n"
        )
        v = lint_source(src, [MetricDiscipline()])
        assert any("bucket layout" in x.message for x in v)

    def test_quantile_requires_literal_name(self):
        src = "REGISTRY.quantile(metric_var, 0.99)\n"
        v = lint_source(src, [MetricDiscipline()])
        assert any("string literal" in x.message for x in v)

    def test_exclude_list_does_not_exempt_obs_modules(self):
        """The rule's exclusions name the Registry implementation and
        the analyzer ONLY — a future exclude entry silently exempting
        nos_tpu/obs/ (timeseries, slo: heavy emitters) would turn the
        rule off exactly where the new series are minted."""
        for entry in MetricDiscipline.exclude:
            assert not entry.startswith("nos_tpu/obs"), entry
        assert MetricDiscipline.exclude == (
            "nos_tpu/exporter/metrics.py", "nos_tpu/analysis/")
        # and a violation planted under an obs-like path fires
        v = lint_source("REGISTRY.inc('nos_tpu_obs_only_total')\n",
                        [MetricDiscipline()],
                        relpath="nos_tpu/obs/fixture.py")
        assert any("never registered" in x.message for x in v)


# ---------------------------------------------------------------------------
# N004: no blocking under lock
# ---------------------------------------------------------------------------

class TestN004:
    def test_sleep_network_result_log_flagged(self):
        src = (
            "def f(self):\n"
            "    with self._lock:\n"
            "        time.sleep(0.1)\n"
            "        fut.result()\n"
            "        logger.warning('x')\n"
            "        subprocess.run(['ls'])\n"
        )
        v = lint_source(src, [NoBlockingUnderLock()])
        assert rules_of(v) == ["N004"] * 4

    def test_debug_log_and_nested_def_clean(self):
        src = (
            "def f(self):\n"
            "    with self._lock:\n"
            "        logger.debug('cheap when disabled')\n"
            "        x = compute()\n"
            "        def later():\n"
            "            time.sleep(1)\n"       # deferred: runs unlocked
            "    time.sleep(1)\n"               # outside the with
        )
        assert lint_source(src, [NoBlockingUnderLock()]) == []

    def test_api_locked_call_is_a_lock(self):
        src = (
            "def f(self):\n"
            "    with self._api.locked(), self._lock:\n"
            "        retry_on_conflict(self._api, 'Pod', 'p', m)\n"
        )
        v = lint_source(src, [NoBlockingUnderLock()])
        assert rules_of(v) == ["N004"]

    def test_pragma_suppressed(self):
        src = (
            "def f(self):\n"
            "    with _BUILD_LOCK:\n"
            "        # noslint: N004 — the lock exists to serialize this\n"
            "        subprocess.run(['make'])\n"
        )
        assert lint_source(src, [NoBlockingUnderLock()]) == []


# ---------------------------------------------------------------------------
# N005: swallowed exceptions
# ---------------------------------------------------------------------------

class TestN005:
    def test_bare_and_swallowed_flagged(self):
        src = (
            "def f():\n"
            "    try:\n"
            "        g()\n"
            "    except:\n"
            "        pass\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:\n"
            "        return False\n"
        )
        v = lint_source(src, [NoSwallowedExceptions()])
        assert rules_of(v) == ["N005", "N005"]

    def test_logged_recorded_narrow_clean(self):
        src = (
            "def f():\n"
            "    first = None\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:\n"
            "        logger.exception('tick failed')\n"
            "    try:\n"
            "        g()\n"
            "    except BaseException as e:\n"
            "        if first is None:\n"
            "            first = e\n"           # recorded: not swallowed
            "    try:\n"
            "        g()\n"
            "    except (ValueError, KeyError):\n"
            "        pass\n"                    # narrow: caller's policy
        )
        assert lint_source(src, [NoSwallowedExceptions()]) == []

    def test_pragma_suppressed(self):
        src = (
            "def f():\n"
            "    try:\n"
            "        g()\n"
            "    # noslint: N005 — best-effort import hook, see module doc\n"
            "    except Exception:\n"
            "        pass\n"
        )
        assert lint_source(src, [NoSwallowedExceptions()]) == []


# ---------------------------------------------------------------------------
# N006: name hygiene
# ---------------------------------------------------------------------------

class TestN006:
    def test_undefined_name_flagged(self):
        src = (
            "def main(cfg):\n"
            "    api = build_api(cfg)\n"        # the seed's NameError class
            "    return api\n"
        )
        v = lint_source(src, [NameHygiene()])
        assert rules_of(v) == ["N006"]
        assert "build_api" in v[0].message

    def test_unused_import_flagged(self):
        src = "import os\nimport sys\nprint(sys.argv)\n"
        v = lint_source(src, [NameHygiene()])
        assert rules_of(v) == ["N006"]
        assert "'os'" in v[0].message

    def test_quoted_annotation_and_all_are_uses(self):
        src = (
            "from typing import TYPE_CHECKING\n"
            "if TYPE_CHECKING:\n"
            "    from nos_tpu.partitioning.state import PartitioningState\n"
            "from nos_tpu.kube.objects import Pod\n"
            "__all__ = ['Pod']\n"
            "def plan(p) -> 'PartitioningState': ...\n"
        )
        assert lint_source(src, [NameHygiene()]) == []

    def test_init_py_reexports_exempt(self):
        src = "from .core import Thing\n"
        assert lint_source(
            src, [NameHygiene()],
            relpath="nos_tpu/foo/__init__.py") == []

    def test_pragma_suppressed(self):
        src = (
            "from .state import NodePartitioning"
            "  # noslint: N006 — re-export for readers\n"
        )
        assert lint_source(src, [NameHygiene()]) == []


# ---------------------------------------------------------------------------
# --fix: mechanical autofixes (fix.py)
# ---------------------------------------------------------------------------

class TestAutofix:
    FIXTURE = (
        "import os\n"
        "import sys, json\n"
        "from typing import (\n"
        "    Any,\n"
        "    Callable,\n"
        ")\n"
        "\n"
        "def f(api, cm):\n"
        "    # noslint: N001\n"
        "    api.update('ConfigMap', cm)\n"
        "    print(sys.argv, json.dumps({}))\n"
        "    x: Any = 1\n"
        "    return x\n"
    )

    def _write(self, tmp_path):
        pkg = tmp_path / "nos_tpu"
        pkg.mkdir()
        target = pkg / "mod.py"
        target.write_text(self.FIXTURE)
        return target

    def test_fixes_unused_imports_and_naked_pragmas(self, tmp_path):
        from nos_tpu.analysis.fix import fix_file

        target = self._write(tmp_path)
        fixes = fix_file(str(target), str(tmp_path))
        text = target.read_text()
        assert "import os" not in text
        assert "Callable" not in text
        assert "import sys, json" in text          # used names survive
        assert "from typing import Any" in text
        assert "noslint" not in text               # naked pragma removed
        assert len(fixes) == 3
        # the fixed file still parses and the suppressed finding
        # re-surfaced (the pragma was hiding a real N001)
        import ast as _ast
        _ast.parse(text)
        v = lint_source(text, [RetryWrappedWrites()])
        assert rules_of(v) == ["N001"]

    def test_idempotent(self, tmp_path):
        from nos_tpu.analysis.fix import fix_file

        target = self._write(tmp_path)
        fix_file(str(target), str(tmp_path))
        once = target.read_text()
        assert fix_file(str(target), str(tmp_path)) == []
        assert target.read_text() == once

    def test_suppressed_unused_import_not_fixed(self, tmp_path):
        from nos_tpu.analysis.fix import fix_file

        pkg = tmp_path / "nos_tpu"
        pkg.mkdir()
        target = pkg / "mod.py"
        target.write_text(
            "from .state import Thing"
            "  # noslint: N006 — re-export for readers\n")
        assert fix_file(str(target), str(tmp_path)) == []
        assert "Thing" in target.read_text()

    def test_naked_pragma_over_unused_import_converges_in_one_run(
            self, tmp_path):
        """A naked pragma suppressing an auto-fixable N006: the pragma
        fixer runs first, so the re-surfaced unused import is removed in
        the SAME run — the opposite order needed two runs, breaking the
        idempotency contract."""
        from nos_tpu.analysis.fix import fix_file

        pkg = tmp_path / "nos_tpu"
        pkg.mkdir()
        target = pkg / "mod.py"
        target.write_text("import os  # noslint: N006\nx = 1\n")
        fixes = fix_file(str(target), str(tmp_path))
        assert len(fixes) == 2            # pragma gone AND import gone
        assert "import os" not in target.read_text()
        assert fix_file(str(target), str(tmp_path)) == []

    def test_partial_rewrite_never_destroys_comments(self, tmp_path):
        """A partial import rewrite goes through ast.unparse, which
        would erase comments on the SURVIVING aliases — including an
        audited `# noslint` pragma for another rule.  Such statements
        are skipped (the N006 finding stays for a human); an import
        removed WHOLE still goes, comments and all."""
        from nos_tpu.analysis.fix import fix_file

        pkg = tmp_path / "nos_tpu"
        pkg.mkdir()
        target = pkg / "mod.py"
        original = (
            "from typing import (\n"
            "    Any,  # load-bearing comment about Any\n"
            "    Callable,\n"
            ")\n"
            "import os  # goes with the whole statement\n"
            "\n"
            "x: Any = 1\n"
        )
        target.write_text(original)
        fixes = fix_file(str(target), str(tmp_path))
        text = target.read_text()
        # Callable is still unused but untouchable without eating the
        # comment; os was removed whole, its trailing comment with it
        assert "load-bearing comment" in text
        assert "Callable" in text
        assert "import os" not in text
        assert len(fixes) == 1
        # skipping is stable: a second run changes nothing
        assert fix_file(str(target), str(tmp_path)) == []
        assert target.read_text() == text

    def test_cli_fix_skips_unparsable_file_and_keeps_sweeping(
            self, tmp_path, capsys):
        """fix_file raises SyntaxError on an unparsable file; the CLI
        loop must skip-and-report it (the lint pass downgrades it to an
        N000 finding) instead of dying with a traceback mid-sweep."""
        from nos_tpu.analysis.__main__ import main
        from nos_tpu.analysis.fix import fix_file

        pkg = tmp_path / "nos_tpu"
        pkg.mkdir()
        broken = pkg / "broken.py"
        broken.write_text("def oops(:\n")
        with pytest.raises(SyntaxError):
            fix_file(str(broken), str(tmp_path))
        rc = main(["--fix", "--no-cache", str(broken)])
        captured = capsys.readouterr()
        assert rc == 1                      # reported as a finding...
        assert "syntax error" in captured.out
        assert "skip (syntax error)" in captured.err   # ...not a crash
        assert "Traceback" not in captured.err


# ---------------------------------------------------------------------------
# .noslint_cache/: the per-file result cache (cache.py)
# ---------------------------------------------------------------------------

class TestResultCache:
    def _cache(self, tmp_path):
        from nos_tpu.analysis.cache import ResultCache, rules_signature

        return ResultCache(
            str(tmp_path),
            rules_signature([r.id for r in default_rules()]))

    def _tree(self, tmp_path):
        pkg = tmp_path / "nos_tpu"
        pkg.mkdir(exist_ok=True)
        a = pkg / "a.py"
        b = pkg / "b.py"
        a.write_text("import os\n")                # N006 unused import
        b.write_text("x = 1\n")
        return a, b

    def test_hit_serves_identical_results(self, tmp_path):
        a, b = self._tree(tmp_path)
        cache = self._cache(tmp_path)
        cold = run(default_rules(), [str(tmp_path / "nos_tpu")],
                   root=str(tmp_path), cache=cache)
        assert cache.misses == 2 and cache.hits == 0
        cache2 = self._cache(tmp_path)
        warm = run(default_rules(), [str(tmp_path / "nos_tpu")],
                   root=str(tmp_path), cache=cache2)
        assert cache2.hits == 2 and cache2.misses == 0
        assert [v.render() for v in warm.violations] == \
            [v.render() for v in cold.violations]
        assert rules_of(cold.violations) == ["N006"]

    def test_content_change_invalidates_that_file_only(self, tmp_path):
        a, b = self._tree(tmp_path)
        run(default_rules(), [str(tmp_path / "nos_tpu")],
            root=str(tmp_path), cache=self._cache(tmp_path))
        a.write_text("import os\nprint(os.sep)\n")     # now used
        cache = self._cache(tmp_path)
        rep = run(default_rules(), [str(tmp_path / "nos_tpu")],
                  root=str(tmp_path), cache=cache)
        assert cache.hits == 1 and cache.misses == 1   # only a.py re-ran
        assert rep.ok

    def test_readonly_checkout_degrades_to_cacheless(self, tmp_path,
                                                     monkeypatch):
        """A checkout where .noslint_cache/ cannot be created must lint
        normally, not die — put() swallows the makedirs failure too."""
        import os as _os

        self._tree(tmp_path)

        def deny(*a, **k):
            raise PermissionError("read-only filesystem")

        monkeypatch.setattr(_os, "makedirs", deny)
        cache = self._cache(tmp_path)
        rep = run(default_rules(), [str(tmp_path / "nos_tpu")],
                  root=str(tmp_path), cache=cache)
        assert rules_of(rep.violations) == ["N006"]   # linted fine
        assert cache.hits == 0                        # and cached nothing

    def test_rules_signature_change_invalidates_everything(self, tmp_path):
        from nos_tpu.analysis.cache import ResultCache

        self._tree(tmp_path)
        run(default_rules(), [str(tmp_path / "nos_tpu")],
            root=str(tmp_path), cache=self._cache(tmp_path))
        stale = ResultCache(str(tmp_path), "different-signature")
        run(default_rules(), [str(tmp_path / "nos_tpu")],
            root=str(tmp_path), cache=stale)
        assert stale.misses == 2 and stale.hits == 0

    def test_cross_file_rules_bypass_the_cache(self, tmp_path):
        """N003's verdict about b.py moves when a.py changes — a cached
        b.py entry must not pin the stale verdict."""
        pkg = tmp_path / "nos_tpu"
        pkg.mkdir()
        a = pkg / "a.py"
        b = pkg / "b.py"
        a.write_text("REGISTRY = object()\n"
                     "REGISTRY.describe('nos_tpu_x_total', 'help')\n")
        b.write_text("from .a import REGISTRY\n"
                     "REGISTRY.inc('nos_tpu_x_total')\n")
        rep = run(default_rules(), [str(pkg)], root=str(tmp_path),
                  cache=self._cache(tmp_path))
        assert rep.ok
        a.write_text("y = 1\n")                    # describe vanishes
        rep = run(default_rules(), [str(pkg)], root=str(tmp_path),
                  cache=self._cache(tmp_path))
        assert [v.rule for v in rep.violations] == ["N003"]
        assert rep.violations[0].path.endswith("b.py")   # though b cached


# ---------------------------------------------------------------------------
# lockcheck: the dynamic half
# ---------------------------------------------------------------------------

class TestLockcheck:
    def test_ab_ba_inversion_detected(self):
        g = LockGraph(name="inv")
        a, b = g.lock("A"), g.lock("B")
        with a:
            with b:
                pass
        with b:
            with a:                      # reverse of the witnessed order
                pass
        assert len(g.inversions) == 1
        text = g.inversions[0].render()
        assert "A" in text and "B" in text
        with pytest.raises(AssertionError):
            g.assert_clean()

    def test_transitive_inversion_detected(self):
        g = LockGraph(name="trans")
        a, b, c = g.lock("A"), g.lock("B"), g.lock("C")
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with c:
            with a:                      # A->B->C established, C->A closes it
                pass
        assert g.inversions

    def test_consistent_order_clean(self):
        g = LockGraph(name="ok")
        a, b = g.lock("A"), g.lock("B")
        for _ in range(5):
            with a:
                with b:
                    pass
        g.assert_clean()

    def test_reentrant_reacquire_is_not_an_inversion(self):
        g = LockGraph(name="re")
        r = g.lock("R", reentrant=True)
        with r:
            with r:
                pass
        g.assert_clean()

    def test_cross_thread_order_is_convicted(self):
        """The inversion need not deadlock THIS run: thread 1 witnesses
        A->B, thread 2 later does B->A and is convicted (lockdep)."""
        g = LockGraph(name="xthread")
        a, b = g.lock("A"), g.lock("B")

        def t1():
            with a:
                with b:
                    pass

        th = threading.Thread(target=t1)
        th.start()
        th.join()
        with b:
            with a:
                pass
        assert g.inversions

    def test_common_gate_lock_is_not_an_inversion(self):
        """Both orders of A/B witnessed — but every chain runs under
        gate G, so the chains can never reach their blocking points
        concurrently: safe (the APIServer-store-lock-over-nested-watch-
        delivery pattern, derived rather than annotated)."""
        g = LockGraph(name="gated")
        gate, a, b = g.lock("G"), g.lock("A"), g.lock("B")
        with gate:
            with a:
                with b:
                    pass
        with gate:
            with b:
                with a:
                    pass
        g.assert_clean()
        # ...but the same reversal WITHOUT the gate is convicted
        with b:
            with a:
                pass
        assert g.inversions

    def test_install_instruments_new_locks_and_condition_works(self):
        g = LockGraph(name="inst")
        with g.install():
            lk = threading.Lock()
            cond = threading.Condition()     # RLock-backed
            ev = threading.Event()

            def worker():
                with lk:
                    pass
                with cond:
                    cond.notify_all()
                ev.set()

            th = threading.Thread(target=worker)
            with cond:
                th.start()
                cond.wait(timeout=2.0)
            assert ev.wait(timeout=2.0)
            th.join()
        # restored after the with-block
        assert threading.Lock is not type(lk)
        g.assert_clean()

    def test_guard_state_unlocked_write_detected(self):
        class Shared:
            def __init__(self):
                self._lock = threading.RLock()
                self.field = 0

        g = LockGraph(name="guard")
        s = Shared()
        guard_state(s, g)
        with s._lock:
            s.field = 1                  # locked: fine
        g.assert_clean()
        s.field = 2                      # unlocked: convicted
        assert len(g.unguarded_writes) == 1
        assert "field" in g.unguarded_writes[0]

    def test_closed_graph_records_nothing(self):
        g = LockGraph(name="closed")
        a, b = g.lock("A"), g.lock("B")
        with a:
            with b:
                pass
        g.close()
        with b:
            with a:                          # would be an inversion
                pass
        g.assert_clean()                     # closed: nothing recorded

    def test_registry_describe_guard(self):
        """Satellite of N003: the dynamic double-registration guard.
        Same help re-describe is idempotent (re-import, double
        build_api); a conflicting one raises."""
        from nos_tpu.exporter.metrics import Registry

        reg = Registry()
        reg.describe("nos_tpu_x_total", "help")
        reg.describe("nos_tpu_x_total", "help")          # idempotent
        with pytest.raises(ValueError, match="already registered"):
            reg.describe("nos_tpu_x_total", "different")

    def test_guard_state_property_setter_judged_by_inner_write(self):
        class Shared:
            def __init__(self):
                self._lock = threading.RLock()
                self._x = ""

            @property
            def x(self):
                with self._lock:
                    return self._x

            @x.setter
            def x(self, v):
                with self._lock:
                    self._x = v

        g = LockGraph(name="prop")
        s = Shared()
        guard_state(s, g)
        s.x = "plan-1"                   # setter takes the lock itself
        g.assert_clean()
