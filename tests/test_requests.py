"""Request data-plane suite (nos_tpu/requests/): the roofline-derived
cost split, the continuous-batching replica (bounded admission,
reserve-ahead KV, prefill/decode split, disaggregation handoff), the
serving router (session affinity, shed-with-retry, session migration,
the downward-API publish loop), config validation, the obs joins,
journal determinism across arrival-source installation order and
worker counts, and the burst e2e: KV-pressure scale-up with zero
serving preemption victims.
"""

from __future__ import annotations

import random

import pytest

from nos_tpu.api import constants as C
from nos_tpu.api.config import ConfigError, RouterConfig
from nos_tpu.kube.client import APIServer, KIND_NODE, KIND_POD
from nos_tpu.kube.objects import RUNNING
from nos_tpu.obs import journal as J
from nos_tpu.obs import scoped as obs_scoped
from nos_tpu.obs.journal import DecisionJournal
from nos_tpu.requests import (
    ContinuousBatchingReplica, ModelProfile, Request, RequestCostModel,
    RouterService, ServingRouter, hbm_bandwidth_for,
)
from nos_tpu.sim.engine import SimEngine
from nos_tpu.sim.trace import ArrivalSource
from nos_tpu.testing.factory import make_pod, make_tpu_node


# A deliberately KV-heavy profile: 128 KB/token, so one 1-chip 1 GB-HBM
# replica holds ~819 KV tokens and a handful of requests saturates it.
def make_profile(**kw) -> ModelProfile:
    defaults = dict(name="tiny", num_layers=16, num_heads=16,
                    num_kv_heads=16, head_dim=128,
                    intermediate_size=1024, vocab_size=1000,
                    weights_gb=0.9)
    defaults.update(kw)
    return ModelProfile(**defaults)


def make_costs(**kw) -> RequestCostModel:
    defaults = dict(profile=make_profile(), device_kind="v5e",
                    chips=1, hbm_gb=1.0)
    defaults.update(kw)
    return RequestCostModel(**defaults)


def make_request(rid: str = "r0", session: str = "s0",
                 prompt: int = 80, output: int = 20,
                 created: float = 0.0) -> Request:
    return Request("chat", rid, session, prompt, output, created)


def make_router_service(**kw) -> RouterService:
    costs = kw.pop("prefill_costs", make_costs())
    defaults = dict(name="chat", model=costs.profile,
                    prefill_costs=costs, max_queue_per_replica=4,
                    max_retries=1, retry_backoff_s=0.1,
                    session_idle_s=10.0)
    defaults.update(kw)
    return RouterService(**defaults)


def replica_pod(name: str, service: str = "chat") -> object:
    return make_pod(name=name, namespace="serve", node_name="host-0",
                    phase=RUNNING,
                    labels={C.LABEL_SERVICE: service,
                            C.LABEL_TIER: C.TIER_SERVING})


class TestCosts:
    def test_kv_bytes_per_token_arithmetic(self):
        # 2 tensors x layers x kv_heads x head_dim x dtype bytes
        assert make_profile().kv_bytes_per_token() == \
            2 * 16 * 16 * 128 * 2

    def test_kv_capacity_is_free_hbm_over_footprint(self):
        costs = make_costs()
        free = (1.0 - 0.9) * 2**30
        assert costs.kv_capacity_tokens() == \
            int(free // costs.profile.kv_bytes_per_token())

    def test_prefill_is_compute_bound_and_linear(self):
        costs = make_costs()
        one = costs.prefill_seconds(100)
        assert one > 0.0
        assert costs.prefill_seconds(200) == pytest.approx(2 * one)
        # a bigger slice is proportionally faster compute
        assert make_costs(chips=2).prefill_seconds(100) == \
            pytest.approx(one / 2)

    def test_decode_step_grows_with_resident_kv(self):
        costs = make_costs()
        empty = costs.decode_step_seconds(0)
        assert empty > 0.0          # the weights pass alone costs time
        assert costs.decode_step_seconds(800) > empty

    def test_bandwidth_substring_match(self):
        assert hbm_bandwidth_for("tpu-v5p-podslice") == 2765e9
        assert hbm_bandwidth_for("v5e") == 819e9
        assert hbm_bandwidth_for("Trillium") == 1640e9
        assert hbm_bandwidth_for("tpu-v6e") == 1640e9
        assert hbm_bandwidth_for("mystery") == 819e9   # default

    def test_validation(self):
        with pytest.raises(ValueError):
            make_costs(mfu=0.0)
        with pytest.raises(ValueError):
            make_costs(hbm_efficiency=1.5)
        with pytest.raises(ValueError):
            make_costs(hbm_gb=0.5)      # weights don't fit
        with pytest.raises(ValueError):
            make_profile(num_kv_heads=0)


class TestReplica:
    def test_admission_queue_is_bounded(self):
        rep = ContinuousBatchingReplica("r0", make_costs(), max_queue=2)
        assert rep.admit(make_request("a"), 0.0)
        assert rep.admit(make_request("b"), 0.0)
        assert not rep.admit(make_request("c"), 0.0)
        assert rep.queue_depth() == 2

    def test_reserve_ahead_blocks_the_head_of_line(self):
        # the head's WHOLE stream (prompt+output) exceeds KV capacity:
        # nothing behind it may start — that back-pressure IS the
        # scaling signal, never a silent drop
        rep = ContinuousBatchingReplica("r0", make_costs())
        cap = rep.kv_capacity
        assert rep.admit(make_request("big", prompt=cap, output=cap), 0.0)
        assert rep.admit(make_request("small", prompt=10, output=2), 0.0)
        rep.step(1.0, 1.0)
        assert rep.queue_depth() == 2
        assert rep.kv_occupancy() == 0.0

    def test_decode_completes_and_releases_kv(self):
        rep = ContinuousBatchingReplica("r0", make_costs())
        req = make_request(prompt=50, output=4)
        assert rep.admit(req, 0.0)
        for i in range(200):
            if rep.step(float(i), 0.05)[1]:
                break
        assert req.finished is not None
        assert req.generated == 4
        assert rep.kv_occupancy() == 0.0
        assert rep.in_flight() == 0

    def test_output_of_one_completes_at_prefill(self):
        # embeddings/scoring: the one "output" token is the prefill's
        # own logits — no decode phase at all
        rep = ContinuousBatchingReplica("r0", make_costs())
        req = make_request(prompt=64, output=1)
        assert rep.admit(req, 0.0)
        completed: list[Request] = []
        for i in range(100):
            completed = rep.step(float(i), 0.05)[1]
            if completed:
                break
        assert completed == [req]
        assert req.finished is not None and req.generated == 1
        assert rep.kv_occupancy() == 0.0

    def test_prefill_only_hands_off_and_releases_kv(self):
        rep = ContinuousBatchingReplica("r0", make_costs(),
                                        prefill_only=True)
        req = make_request(prompt=64, output=20)
        assert rep.admit(req, 0.0)
        handoffs: list[Request] = []
        for i in range(100):
            handoffs = rep.step(float(i), 0.05)[0]
            if handoffs:
                break
        assert handoffs == [req]
        assert not req.needs_prefill and req.prefill_done is not None
        assert req.finished is None         # decode happens elsewhere
        assert rep.kv_occupancy() == 0.0    # prompt scratch released

    def test_drain_resets_requests_for_a_fresh_start(self):
        rep = ContinuousBatchingReplica("r0", make_costs())
        a = make_request("a", output=300)
        b = make_request("b", output=300)
        assert rep.admit(a, 0.0) and rep.admit(b, 0.0)
        for i in range(5):                  # partway into decode
            rep.step(float(i), 0.01)
        assert a.generated > 0 or b.generated > 0
        orphans = rep.drain()
        assert sorted(r.rid for r in orphans) == ["a", "b"]
        for r in orphans:
            assert r.needs_prefill and r.generated == 0
            assert r.prefill_done is None
        assert rep.in_flight() == 0 and rep.kv_occupancy() == 0.0

    def test_admit_decode_needs_kv_room_not_queue_room(self):
        rep = ContinuousBatchingReplica("r0", make_costs(), max_queue=1)
        cap = rep.kv_capacity
        big = make_request("big", prompt=cap - 10, output=5)
        big.needs_prefill = False
        assert rep.admit_decode(big, 0.0)
        small = make_request("small", prompt=20, output=5)
        small.needs_prefill = False
        assert not rep.admit_decode(small, 0.0)   # KV full


class RouterHarness:
    def __init__(self, svc: RouterService | None = None,
                 replicas: int = 2, **router_kw):
        self.now = [0.0]
        self.api = APIServer()
        self.svc = svc or make_router_service()
        label = self.svc.prefill_label
        for i in range(replicas):
            self.api.create(KIND_POD, replica_pod(f"{label}-r{i}", label))
        self.router = ServingRouter(
            self.api, [self.svc], clock=lambda: self.now[0],
            publish_every_ticks=1, **router_kw)

    def run(self, ticks: int, dt: float = 0.05) -> None:
        for _ in range(ticks):
            self.now[0] += dt
            self.router.tick(dt)


class TestRouter:
    def test_session_affinity_sticks_to_the_kv_holder(self):
        h = RouterHarness()
        h.router.submit("serve/chat", make_request("a", "s1", output=400))
        h.run(3)
        occ = h.router.kv_occupancies("serve/chat")
        holder = max(occ, key=lambda k: occ[k])
        assert occ[holder] > 0.0
        # the second request of the session lands on the SAME replica
        # even though the other one is emptier
        h.router.submit("serve/chat",
                        make_request("b", "s1", output=400,
                                     created=h.now[0]))
        h.run(3)
        occ = h.router.kv_occupancies("serve/chat")
        others = [v for k, v in occ.items() if k != holder]
        assert all(v == 0.0 for v in others)
        assert h.router.session_count("serve/chat") == 1

    def test_new_sessions_spread_by_kv_occupancy(self):
        h = RouterHarness()
        h.router.submit("serve/chat", make_request("a", "s1", output=400))
        h.run(3)
        h.router.submit("serve/chat",
                        make_request("b", "s2", output=400,
                                     created=h.now[0]))
        h.run(3)
        occ = h.router.kv_occupancies("serve/chat")
        assert sum(1 for v in occ.values() if v > 0.0) == 2

    def test_shed_after_max_retries_is_journaled(self):
        h = RouterHarness(make_router_service(max_queue_per_replica=1,
                                              max_retries=0),
                          replicas=1)
        h.router.tick(0.0)          # discover the replica; no progress
        journal = DecisionJournal(clock=lambda: h.now[0])
        with obs_scoped(journal=journal):
            h.router.submit("serve/chat", make_request("a", "s1"))
            h.router.submit("serve/chat", make_request("b", "s2"))
        stats = h.router.stats()["serve/chat"]
        assert stats["shed"] == 1 and stats["submitted"] == 2
        shed = journal.events(J.REQUEST_SHED)
        assert len(shed) == 1
        assert shed[0].subject == "serve/chat"
        assert shed[0].attrs["rid"] == "b"
        assert shed[0].attrs["phase"] == "prefill"

    def test_retry_admits_once_capacity_frees(self):
        h = RouterHarness(make_router_service(max_queue_per_replica=1,
                                              max_retries=3,
                                              retry_backoff_s=0.05),
                          replicas=1)
        h.router.tick(0.0)
        h.router.submit("serve/chat", make_request("a", "s1", output=2))
        h.router.submit("serve/chat", make_request("b", "s2", output=2))
        stats = h.router.stats()["serve/chat"]
        assert stats["retried"] == 1 and stats["shed"] == 0
        h.run(40)                   # a drains; b's retry lands
        stats = h.router.stats()["serve/chat"]
        assert stats["completed"] == 2 and stats["shed"] == 0

    def test_replica_vanish_migrates_sessions_and_reroutes(self):
        h = RouterHarness()
        h.router.submit("serve/chat", make_request("a", "s1", output=400))
        h.run(3)
        occ = h.router.kv_occupancies("serve/chat")
        holder = max(occ, key=lambda k: occ[k])
        journal = DecisionJournal(clock=lambda: h.now[0])
        with obs_scoped(journal=journal):
            h.api.delete(KIND_POD, holder, "serve")
            h.run(3)
        moved = journal.events(J.SESSION_MIGRATED)
        assert len(moved) == 1
        assert moved[0].attrs["session"] == "s1"
        assert moved[0].attrs["from_replica"] == holder
        assert moved[0].attrs["was_affine"] is True
        assert h.router.stats()["serve/chat"]["migrated"] == 1
        # the orphan restarted on the survivor
        occ = h.router.kv_occupancies("serve/chat")
        assert holder not in occ and max(occ.values()) > 0.0

    def test_publish_stamps_load_and_sessions(self):
        h = RouterHarness()
        h.router.submit("serve/chat", make_request("a", "s1", output=400))
        h.run(2)
        pods = {p.metadata.name: p for p in h.api.list(
            KIND_POD, namespace="serve")}
        occ = h.router.kv_occupancies("serve/chat")
        holder = max(occ, key=lambda k: occ[k])
        ann = pods[holder].metadata.annotations
        assert float(ann[C.ANNOT_SERVING_LOAD]) == \
            pytest.approx(occ[holder], abs=1e-3)
        assert ann[C.ANNOT_SERVING_SESSIONS] == "1"
        idle = next(n for n in pods if n != holder)
        assert pods[idle].metadata.annotations[
            C.ANNOT_SERVING_SESSIONS] == "0"

    def test_disaggregated_prefill_hands_off_to_decode_pool(self):
        svc = make_router_service(
            prefill_service="chat-prefill",
            decode_service="chat-decode",
            decode_costs=make_costs())
        h = RouterHarness(svc, replicas=0)
        h.api.create(KIND_POD, replica_pod("pf-0", "chat-prefill"))
        h.api.create(KIND_POD, replica_pod("dec-0", "chat-decode"))
        req = make_request("a", "s1", prompt=64, output=8)
        h.router.submit("serve/chat", req)
        h.run(40)
        assert h.router.stats()["serve/chat"]["completed"] == 1
        assert req.prefill_done is not None
        assert req.finished is not None
        assert req.finished >= req.prefill_done
        # the decode-side KV was released on completion
        assert h.router.kv_occupancies("serve/chat")["dec-0"] == 0.0

    def test_session_expiry_forgets_idle_sessions(self):
        h = RouterHarness(make_router_service(session_idle_s=1.0))
        h.router.submit("serve/chat", make_request("a", "s1", output=2))
        h.run(4)
        assert h.router.session_count("serve/chat") == 1
        h.run(30)                   # > 1 s idle
        assert h.router.session_count("serve/chat") == 0

    def test_duplicate_service_rejected(self):
        api = APIServer()
        svc = make_router_service()
        with pytest.raises(ValueError, match="duplicate"):
            ServingRouter(api, [svc, svc], clock=lambda: 0.0)


class TestRouterConfig:
    SERVICE = {
        "name": "chat",
        "model": {"name": "m", "num_layers": 2, "num_heads": 2,
                  "num_kv_heads": 2, "head_dim": 8,
                  "intermediate_size": 16, "weights_gb": 0.5},
        "prefill": {"device_kind": "v5e", "hbm_gb": 1.0},
    }

    def test_round_trip(self):
        cfg = RouterConfig(enabled=True, services=[dict(self.SERVICE)])
        cfg.validate()
        svc = RouterService.from_mapping(self.SERVICE)
        assert svc.key == "serve/chat" and not svc.disaggregated

    def test_unknown_key_fails_the_config_load(self):
        bad = dict(self.SERVICE)
        bad["max_qeue"] = 3
        with pytest.raises(ConfigError, match="max_qeue"):
            RouterConfig(services=[bad]).validate()

    def test_disaggregated_decode_needs_costs(self):
        bad = dict(self.SERVICE)
        bad["decode_service"] = "chat-decode"
        with pytest.raises(ConfigError, match="decode_costs"):
            RouterConfig(services=[bad]).validate()


class TestObsJoins:
    def test_request_breach_joins_shed_and_scale_up(self):
        from nos_tpu.obs.__main__ import _request_breach_cause

        journal = [
            {"category": J.AUTOSCALE, "subject": "serve/chat-decode",
             "attrs": {"direction": "up", "count": 2}},
            {"category": J.REQUEST_SHED, "subject": "serve/chat",
             "attrs": {"rid": "r9", "phase": "decode", "retries": 5}},
        ]
        lines = _request_breach_cause(journal, "chat")
        assert any("router saturation" in ln for ln in lines)
        assert any("scale-up in flight" in ln for ln in lines)
        lines = _request_breach_cause([], "chat")
        assert any("scheduler" in ln for ln in lines)

    def test_find_requests_block_shapes(self):
        from nos_tpu.obs.__main__ import _find_requests_block

        rows = {"serve/chat": {"submitted": 1}}
        assert _find_requests_block({"requests": rows}) == rows
        assert _find_requests_block(
            {"utilization": {"requests": rows}}) == rows
        assert _find_requests_block({"requests": {}}) is None
        assert _find_requests_block({}) is None


def _deterministic_run(*, install_order: tuple[int, ...],
                       workers: int) -> list:
    """One router-only sim: two seeded arrival streams over two fixed
    replicas plus a scheduled replica loss; returns the normalized
    journal (category, subject, sorted attrs) — the byte-identity
    basis."""
    eng = SimEngine()
    api = APIServer()
    for i in range(2):
        api.create(KIND_POD, replica_pod(f"chat-r{i}"))
    svc = make_router_service(max_queue_per_replica=2, max_retries=1,
                              retry_backoff_s=0.05)
    router = ServingRouter(api, [svc], clock=eng.clock, workers=workers,
                           publish_every_ticks=2)
    journal = DecisionJournal(maxlen=50_000, clock=eng.now)

    def make_source(idx: int) -> ArrivalSource:
        shapes = random.Random(100 + idx)
        counter = [0]

        def fire(t: float) -> None:
            counter[0] += 1
            router.submit("serve/chat", Request(
                "chat", f"src{idx}-{counter[0]}",
                f"s{shapes.randrange(6)}",
                shapes.randrange(20, 120), shapes.randrange(2, 30), t))

        return ArrivalSource(7 + idx, lambda t: 30.0, fire,
                             peak_rate=30.0, until=4.0,
                             label=f"arrivals-{idx}")

    sources = [make_source(0), make_source(1)]
    with obs_scoped(journal=journal):
        for idx in install_order:
            sources[idx].install(eng)
        eng.tick_loop(0.05, lambda: router.tick(0.05), until=6.0,
                      label="router-tick")
        eng.at(2.0, lambda: api.delete(KIND_POD, "chat-r0", "serve"),
               label="replica-loss")
        eng.run()
    return [(r.category, r.subject,
             tuple(sorted((k, str(v)) for k, v in r.attrs.items())))
            for r in journal.events()]


class TestDeterminism:
    def test_journal_identical_across_install_order_and_workers(self):
        base = _deterministic_run(install_order=(0, 1), workers=0)
        assert base, "the run journaled nothing — it exercises no path"
        assert any(r[0] == J.SESSION_MIGRATED for r in base)
        shuffled = _deterministic_run(install_order=(1, 0), workers=0)
        assert shuffled == base
        threaded = _deterministic_run(install_order=(0, 1), workers=4)
        assert threaded == base


class TestBurstE2E:
    def test_kv_pressure_scales_up_with_zero_serving_preemptions(self):
        """The tentpole loop end to end on a carved host: a request
        burst drives KV occupancy up, the router's published load makes
        the autoscaler add replicas, the scheduler binds them onto free
        slices — and no serving pod is ever a preemption victim."""
        from nos_tpu.scheduler.framework import Framework
        from nos_tpu.scheduler.scheduler import Scheduler
        from nos_tpu.serving.autoscaler import (
            ReplicaAutoscaler, ServingService,
        )
        from nos_tpu.testing.factory import admit_all

        api = APIServer()
        api.create(KIND_NODE, make_tpu_node(
            "host-0", status_geometry={"free": {"1x1": 8}}))
        now = [0.0]
        autoscaler = ReplicaAutoscaler(api, [ServingService(
            name="chat", namespace="serve", slice_shape="1x1",
            min_replicas=1, max_replicas=8,
            target_load_per_replica=0.55, scale_up_cooldown_s=0.0,
            scale_down_cooldown_s=60.0, down_hysteresis=0.2)],
            clock=lambda: now[0])
        router = ServingRouter(
            api, [make_router_service(max_queue_per_replica=8,
                                      max_retries=6,
                                      retry_backoff_s=0.2)],
            clock=lambda: now[0], publish_every_ticks=1)
        scheduler = Scheduler(api, Framework())
        journal = DecisionJournal(maxlen=50_000, clock=lambda: now[0])
        rng = random.Random(3)
        rid = 0
        with obs_scoped(journal=journal):
            for step in range(400):
                now[0] = step * 0.05
                burst = 6 if 2.0 <= now[0] < 10.0 else \
                    (1 if step % 4 == 0 else 0)
                for _ in range(burst):
                    rid += 1
                    router.submit("serve/chat", Request(
                        "chat", f"r{rid}", f"s{rng.randrange(40)}",
                        rng.randrange(40, 120), rng.randrange(32, 96),
                        now[0]))
                router.tick(0.05)
                autoscaler.reconcile()
                scheduler.run_cycle()
                admit_all(api)
        stats = router.stats()["serve/chat"]
        assert stats["completed"] > 100
        assert stats["shed"] == 0, \
            "the retry ladder plus scale-up must absorb the burst"
        assert len(api.list(KIND_POD, namespace="serve")) > 1, \
            "KV pressure never scaled the service up"
        for rec in journal.events(J.PREEMPTION):
            victims = rec.attrs.get("victims", [])
            assert not [v for v in victims
                        if str(v).startswith("serve/")], \
                f"serving pod preempted: {rec}"
