"""End-to-end native boundary on real hardware (round-2 VERDICT item #1).

Closes the loop the reference closes with NVML (pkg/gpu/nvml/client.go:
create a MIG device, kubelet hands it to a pod, CUDA runs on it): discover
the topology from the device (PJRT attributes), carve a slice from the
*observed* block through the native C++ shim, map the carved placement back
to a live PJRT device at those physical coordinates, and run a JAX program
on exactly that chip.

Skipped off-TPU: run with NOS_TPU_TEST_REAL=1 on a TPU host.  The observed
block may be smaller than a full v5e host (a tunnel can expose one chip);
the test carves the largest shape that fits whatever was observed.
"""

import pytest

# every lock built by the plugin stack is lockdep-checked (conftest)
pytestmark = pytest.mark.usefixtures("lock_discipline")

from nos_tpu.device import discovery  # noqa: E402


def _on_real_tpu() -> bool:
    try:
        import jax

        return any(d.platform == "tpu" for d in jax.local_devices())
    except Exception:
        return False


requires_tpu = pytest.mark.skipif(
    not _on_real_tpu(),
    reason="no real TPU visible (set NOS_TPU_TEST_REAL=1 on a TPU host)")


@requires_tpu
def test_discovery_observes_device():
    d = discovery.discover()
    assert d.source == discovery.SOURCE_DEVICE
    assert d.num_local_chips >= 1
    assert d.accelerator_type  # a real device_kind string
    assert len(d.chip_coords) == d.num_local_chips


@requires_tpu
def test_carve_slice_and_run_jax_on_it():
    import jax
    import jax.numpy as jnp

    from nos_tpu.device import native

    if not native.available():
        pytest.skip("native shim did not build")
    rt = native.NativeTpuRuntime(None)  # discover, don't assert
    assert rt.topology_source == discovery.SOURCE_DEVICE
    _, block = rt.topology()
    disc = rt.discovered
    assert block.chips == disc.num_local_chips

    fitting = [s for s in disc.generation.subhost_shapes()
               if s.fits_in(block)]
    if not fitting:  # observed block smaller than any profile: carve it all
        fitting = [block.canonical()]
    target = max(fitting, key=lambda s: s.chips)

    ids = rt.create_slices(0, [target])
    assert len(ids) == 1
    try:
        placement = rt.placements()[ids[0]]
        dev = disc.jax_device_for(placement.offset)
        assert dev.platform == "tpu"

        x = jax.device_put(jnp.ones((256, 256), jnp.bfloat16), dev)
        y = jax.jit(lambda a: jnp.sum(a @ a))(x)
        assert list(y.devices()) == [dev]
        assert float(y) == pytest.approx(256.0 * 256 * 256, rel=1e-2)
    finally:
        rt.delete_slice(ids[0])
    assert ids[0] not in rt.placements()
