"""Flash block autotuner tests: device-class normalization, candidate
legality, cache roundtrip + precedence, and the attention._plan
consultation path — interpret mode on CPU, so an autotuner that picks a
new block can never pick a wrong one (the numerics checks run at tuned
blocks, not just the defaults)."""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import pytest

from nos_tpu.ops import attention as A
from nos_tpu.ops import autotune
from nos_tpu.parallel.ring import dense_attention


@pytest.fixture(autouse=True)
def tmp_cache(tmp_path, monkeypatch):
    """Point the persistent cache at a per-test file and reset the
    in-memory view on both sides.  Autouse: NO test in this module may
    read the host's real ~/.cache entries — a single
    `python -m nos_tpu.ops.autotune` run on the dev box would otherwise
    change what PRETUNED-expectation tests observe."""
    path = tmp_path / "flash_autotune.json"
    monkeypatch.setenv(autotune._CACHE_ENV, str(path))
    autotune.reload_cache()
    yield path
    autotune.reload_cache()


@pytest.fixture
def qkv():
    key = jax.random.PRNGKey(1)
    return tuple(
        jax.random.normal(kk, (1, 256, 2, 128), jnp.float32)
        for kk in jax.random.split(key, 3)
    )


class TestDeviceClass:
    @pytest.mark.parametrize("kind,cls", [
        ("TPU v5 lite", "v5e"),
        ("v5litepod-16", "v5e"),
        ("TPU v5e", "v5e"),
        ("TPU v5p", "v5p"),
        ("TPU v6e", "v6e"),
        ("trillium", "v6e"),
        ("TPU v4", "v4"),
        ("cpu", "cpu"),
        ("", "unknown"),
    ])
    def test_normalization(self, kind, cls):
        assert autotune.device_class(kind) == cls


class TestCandidates:
    def test_all_candidates_kernel_legal(self):
        for pass_ in ("fwd", "bwd"):
            cands = autotune.candidates(pass_, 2048, 2048, 128, 2)
            assert cands, pass_
            for bq, bk in cands:
                assert 2048 % bq == 0 and 2048 % bk == 0
                assert bk % 128 == 0
                assert autotune._vmem_estimate(
                    pass_, bq, bk, 128, 2) <= autotune.VMEM_BUDGET

    def test_short_sequences_shrink_the_space(self):
        cands = autotune.candidates("fwd", 256, 256, 128, 4)
        assert all(bq <= 256 and bk <= 256 for bq, bk in cands)
        assert (256, 256) in cands

    def test_vmem_budget_excludes_fat_bwd_blocks(self):
        # the fused backward's 4 fp32 score-tile intermediates push
        # 1024x1024 past the budget; the forward still admits it
        assert (1024, 1024) not in autotune.candidates(
            "bwd", 8192, 8192, 128, 2)
        assert (1024, 1024) in autotune.candidates(
            "fwd", 8192, 8192, 128, 2)

    def test_v6e_budget_admits_its_pretuned_bwd_blocks(self):
        """The search budget must agree with the shipped v6e table, or
        a tuning run on v6e would record a smaller-block winner that
        permanently outranks the better PRETUNED entry."""
        for seq in (2048, 8192):
            pretuned = autotune.lookup("v6e", "bwd", seq, 128,
                                       "bfloat16", True)
            assert pretuned in autotune.candidates(
                "bwd", seq, seq, 128, 2,
                budget=autotune.vmem_budget("v6e"))


class TestPretuned:
    def test_v5e_ships_the_measured_sweep_optima(self):
        assert autotune.lookup("TPU v5 lite", "fwd", 2048, 128,
                               "bfloat16", True) == (512, 512)
        assert autotune.lookup("TPU v5 lite", "bwd", 2048, 128,
                               "bfloat16", True) == (512, 1024)

    def test_all_families_cover_the_training_shapes(self):
        for dev in ("v5e", "v5p", "v6e"):
            for seq in (1024, 2048, 4096, 8192):
                for pass_ in ("fwd", "bwd"):
                    blocks = autotune.lookup(dev, pass_, seq, 128,
                                             "bfloat16", True)
                    assert blocks is not None, (dev, pass_, seq)
                    bq, bk = blocks
                    assert seq % bq == 0 and seq % bk == 0, \
                        (dev, pass_, seq, blocks)

    def test_unknown_device_and_shape_miss(self):
        assert autotune.lookup("cpu", "fwd", 2048, 128,
                               "bfloat16", True) is None
        assert autotune.lookup("TPU v5e", "fwd", 2048, 64,
                               "bfloat16", True) is None


class TestCache:
    def test_record_roundtrip_through_the_file(self, tmp_cache):
        key = autotune.record("TPU v5e", "fwd", 2048, 128, "bfloat16",
                              True, (256, 512))
        raw = json.loads(tmp_cache.read_text())
        assert raw["entries"][key] == [256, 512]
        autotune.reload_cache()   # force the file read path
        assert autotune.lookup("TPU v5e", "fwd", 2048, 128, "bfloat16",
                               True) == (256, 512)

    def test_measured_beats_pretuned(self, tmp_cache):
        assert autotune.lookup("TPU v5e", "fwd", 2048, 128, "bfloat16",
                               True) == (512, 512)
        autotune.record("TPU v5e", "fwd", 2048, 128, "bfloat16", True,
                        (256, 1024))
        assert autotune.lookup("TPU v5e", "fwd", 2048, 128, "bfloat16",
                               True) == (256, 1024)

    def test_corrupt_cache_degrades_to_pretuned(self, tmp_cache):
        tmp_cache.write_text("{not json")
        autotune.reload_cache()
        assert autotune.lookup("TPU v5e", "fwd", 2048, 128, "bfloat16",
                               True) == (512, 512)

    def test_bad_pass_rejected(self, tmp_cache):
        with pytest.raises(ValueError):
            autotune.record("TPU v5e", "sideways", 2048, 128,
                            "bfloat16", True, (128, 128))


class TestPlanConsultation:
    """A recorded entry must flow through attention._resolve_plan into
    the kernel, and a bad entry must fall through to the defaults —
    never disable the kernel or change the math."""

    def test_tuned_blocks_drive_the_kernel(self, tmp_cache, qkv):
        q, k, v = qkv
        kind = jax.devices()[0].device_kind
        autotune.record(kind, "fwd", 256, 128, "float32", True,
                        (128, 256))
        autotune.record(kind, "bwd", 256, 128, "float32", True,
                        (256, 128))
        ref = dense_attention(q, k, v, True)
        out = A.flash_attention(q, k, v, True, None, None, True)
        assert jnp.max(jnp.abs(out - ref)) < 1e-4

        def loss(fn):
            return lambda q, k, v: (fn(q, k, v) ** 2).sum()
        g = jax.grad(loss(lambda q, k, v: A.flash_attention(
            q, k, v, True, None, None, True)), (0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss(lambda q, k, v: dense_attention(
            q, k, v, True)), (0, 1, 2))(q, k, v)
        for got, want in zip(g, g_ref):
            scale = float(jnp.max(jnp.abs(want))) + 1e-9
            assert float(jnp.max(jnp.abs(got - want))) / scale < 2e-2

    def test_invalid_tuned_entry_falls_through_to_defaults(
            self, tmp_cache, qkv):
        q, k, v = qkv
        kind = jax.devices()[0].device_kind
        # 384 divides nothing here: _resolve_plan must reject it and
        # use the defaults, NOT route to the XLA fallback
        autotune.record(kind, "fwd", 256, 128, "float32", True,
                        (384, 384))
        plan = A._resolve_plan(q, k, True, None, None, "fwd",
                               A.DEFAULT_BLOCK_Q, A.DEFAULT_BLOCK_K)
        assert plan == (min(A.DEFAULT_BLOCK_Q, 256),
                        min(A.DEFAULT_BLOCK_K, 256))
        out = A.flash_attention(q, k, v, True, None, None, True)
        assert jnp.max(jnp.abs(out - dense_attention(q, k, v, True))) \
            < 1e-4

    def test_explicit_blocks_beat_the_cache(self, tmp_cache, qkv):
        q, k, _ = qkv
        kind = jax.devices()[0].device_kind
        autotune.record(kind, "fwd", 256, 128, "float32", True,
                        (128, 128))
        plan = A._resolve_plan(q, k, True, 256, 256, "fwd",
                               A.DEFAULT_BLOCK_Q, A.DEFAULT_BLOCK_K)
        assert plan == (256, 256)

    def test_unaligned_bwd_override_drops_to_fwd_blocks(self):
        """A bwd_block override that divides nothing at these shapes
        (384 at seq 512) must fall back to the forward's validated
        blocks, not crash the backward with plan=None."""
        key = jax.random.PRNGKey(9)
        q, k, v = (jax.random.normal(kk, (1, 512, 1, 128), jnp.float32)
                   for kk in jax.random.split(key, 3))

        def loss(q, k, v):
            return (A.flash_attention(q, k, v, True, 128, 128, True,
                                      384, 384) ** 2).sum()
        g = jax.grad(loss, (0, 1, 2))(q, k, v)
        g_ref = jax.grad(lambda q, k, v: (dense_attention(
            q, k, v, True) ** 2).sum(), (0, 1, 2))(q, k, v)
        for got, want in zip(g, g_ref):
            scale = float(jnp.max(jnp.abs(want))) + 1e-9
            assert float(jnp.max(jnp.abs(got - want))) / scale < 2e-2

    def test_bwd_blocks_pin_the_backward_separately(self, qkv):
        """bwd_block_q/bwd_block_k (the autotuner's isolation knob)
        override the shared explicit blocks for the backward only."""
        q, k, v = qkv

        def loss(q, k, v):
            return (A.flash_attention(q, k, v, True, 256, 256, True,
                                      128, 128) ** 2).sum()
        g = jax.grad(loss, (0, 1, 2))(q, k, v)
        g_ref = jax.grad(lambda q, k, v: (dense_attention(
            q, k, v, True) ** 2).sum(), (0, 1, 2))(q, k, v)
        for got, want in zip(g, g_ref):
            scale = float(jnp.max(jnp.abs(want))) + 1e-9
            assert float(jnp.max(jnp.abs(got - want))) / scale < 2e-2


class TestSearch:
    @pytest.mark.slow
    def test_interpret_search_picks_a_legal_candidate(self, tmp_cache):
        key = jax.random.PRNGKey(0)
        q, k, v = (jax.random.normal(kk, (1, 256, 1, 128), jnp.float32)
                   for kk in jax.random.split(key, 3))
        best, timings = autotune.search(
            "fwd", q, k, v, True, interpret=True, n1=1, n2=2, reps=1)
        assert best in timings
        assert best in autotune.candidates("fwd", 256, 256, 128, 4)
        assert all(t > 0 for t in timings.values())

    def test_search_rejects_unknown_pass(self, qkv):
        q, k, v = qkv
        with pytest.raises(ValueError):
            autotune.search("sideways", q, k, v)
