"""Simulator contract tests: the engine's deterministic tie-break, the
event-vs-tick equivalence the bench ports stand on, injector
composition, the worst-week smoke (ledger conservation + SLO verdicts),
and the one-JSON-document stdout contract every bench main shares.

The tie-break contract ``(time, priority, label, seq)`` is pinned HERE
(nosdiff/N011 discipline): shuffling the order sources are installed in
must never change a byte of the fired stream.
"""

from __future__ import annotations

import json
import random

import pytest

from nos_tpu.kube.client import KIND_POD
from nos_tpu.kube.objects import RUNNING
from nos_tpu.obs.ledger import conservation_ok
from nos_tpu.sim import (
    APIChaosInjector, ArrivalSource, AtSource, CloudChaosInjector,
    PRIO_FAULT, PRIO_SAMPLE, PRIO_TICK, PoolSpec, QuotaSpec, SamplerSource,
    Scenario, SimEngine, TickSource, WindowSource, WorstWeek,
    WorstWeekConfig, assemble_control_plane, compose, emit, install_all,
    stdout_to_stderr,
)
from nos_tpu.testing.chaos import ChaosAPIServer, ChaosCloudTPUAPI
from nos_tpu.testing.factory import make_slice_pod


# ---------------------------------------------------------------------------
# Engine: clock, ordering, tick_loop semantics
# ---------------------------------------------------------------------------


def test_engine_orders_by_time_then_priority_then_label():
    eng = SimEngine()
    fired = []
    eng.at(2.0, lambda: fired.append("tick@2"), priority=PRIO_TICK,
           label="tick")
    eng.at(1.0, lambda: fired.append("late-label@1"), priority=PRIO_FAULT,
           label="zz")
    eng.at(2.0, lambda: fired.append("fault@2"), priority=PRIO_FAULT,
           label="fault")
    eng.at(1.0, lambda: fired.append("early-label@1"), priority=PRIO_FAULT,
           label="aa")
    eng.at(2.0, lambda: fired.append("sample@2"), priority=PRIO_SAMPLE,
           label="sample")
    eng.run()
    assert fired == ["early-label@1", "late-label@1",
                     "fault@2", "tick@2", "sample@2"]
    assert eng.now() == 2.0
    assert eng.events_fired == 5


def test_engine_rejects_scheduling_into_the_past():
    eng = SimEngine()
    eng.at(1.0, lambda: None, label="a")
    eng.run()
    with pytest.raises(ValueError):
        eng.at(0.5, lambda: None, label="b")


def test_engine_run_until_stops_clock_on_boundary():
    eng = SimEngine()
    fired = []
    eng.at(1.0, lambda: fired.append(1.0), label="a")
    eng.at(5.0, lambda: fired.append(5.0), label="a")
    assert eng.run(until=3.0) == 1
    assert fired == [1.0]
    assert eng.now() == 3.0          # clock lands on the horizon
    eng.run()
    assert fired == [1.0, 5.0]


def test_tick_loop_replicates_while_loop_float_accumulation():
    """The ported bench loop must keep its float-accumulation sequence
    bit-identical to ``while now < until: now += period``."""
    period, until = 0.25, 10.0
    expect = []
    now = 0.0
    while now < until:
        now += period
        expect.append(now)
    eng = SimEngine()
    got = []
    eng.tick_loop(period, lambda: got.append(eng.now()), until=until)
    eng.run()
    assert got == expect             # exact float equality, by design


def test_tick_loop_while_fn_stops_like_a_while_loop():
    eng = SimEngine()
    count = [0]

    def body():
        count[0] += 1

    eng.tick_loop(1.0, body, until=100.0,
                  while_fn=lambda: count[0] < 7)
    eng.run()
    assert count[0] == 7


# ---------------------------------------------------------------------------
# Tie-break determinism: shuffled installation, byte-identical stream
# ---------------------------------------------------------------------------


def _build_sources(log):
    def mk(label, kind):
        if kind == "at":
            return AtSource([1.0, 2.0, 3.0],
                            lambda t, lab=label: log.append((t, lab)),
                            label=label)
        if kind == "window":
            return WindowSource(
                [(1.0, 2.0)],
                lambda t, lab=label: log.append((t, lab + "/open")),
                lambda t, lab=label: log.append((t, lab + "/close")),
                label=label)
        if kind == "tick":
            return TickSource(1.0,
                              lambda lab=label: log.append(("tick", lab)),
                              until=3.0, label=label)
        return SamplerSource(1.0,
                             lambda t, lab=label: log.append((t, lab)),
                             until=3.0, label=label)

    return [mk("kill", "at"), mk("storm", "window"), mk("ctl", "tick"),
            mk("slo", "sample"), mk("drain", "window"),
            mk("arrive", "at")]


def test_shuffled_installation_is_byte_identical():
    """The N011 discipline for scenarios: composition order must never
    change the fired stream.  Install the same six sources in ten
    shuffled orders and byte-compare the journals."""
    journals = []
    for trial in range(10):
        log: list = []
        sources = _build_sources(log)
        random.Random(trial).shuffle(sources)
        eng = SimEngine()
        compose(*sources).install(eng)
        eng.run()
        journals.append(json.dumps(log).encode())
    assert len(set(journals)) == 1


def test_arrival_source_is_a_pure_function_of_seed():
    def run_once():
        times: list = []
        eng = SimEngine()
        ArrivalSource(7, lambda t: 0.5 + 0.4 * (t % 2.0),
                      times.append, peak_rate=1.0,
                      until=200.0).install(eng)
        eng.run()
        return times

    a, b = run_once(), run_once()
    assert a == b
    assert len(a) > 20


# ---------------------------------------------------------------------------
# Event-vs-tick equivalence: the bench-port discipline
# ---------------------------------------------------------------------------


def _small_scenario(name: str) -> Scenario:
    return Scenario(
        name=name, horizon_s=8.0, tick_s=0.25, seed=3,
        pools=(PoolSpec("pod-0", hosts=2),),
        quotas=(QuotaSpec("work", min_gb=256.0, max_gb=1024.0),))


def _journal_trace(plane):
    """(category, subject, attrs) with run-unique plan ids normalized —
    the same byte-identity basis the benches gate on."""
    return [(r.category, r.subject, tuple(sorted(
        (k, str(v)) for k, v in r.attrs.items() if k != "plan_id")))
        for r in plane.journal.events()]


def _submit_pods(plane, n=2):
    for i in range(n):
        plane.api.create(KIND_POD, make_slice_pod(
            "2x4", 1, name=f"job-{i}", namespace="work"))


def test_event_fault_equals_in_tick_fault_check():
    """A PRIO_FAULT one-shot at T fires before the same-timestamp tick
    — exactly the old in-tick ``if now >= T`` idiom.  Both stylings of
    the same scenario must journal identically and converge the same
    pods (equivalence holds whenever T is on the tick grid)."""
    kill_t = 4.0

    # event-styled: the kill is a first-class one-shot
    ev_eng = SimEngine()
    ev = assemble_control_plane(_small_scenario("ev"), ev_eng)
    _submit_pods(ev)
    compose(*ev.sources()).install(ev_eng)
    ev_eng.at(kill_t, lambda: ev.kill_host("pod-0-h1"),
              priority=PRIO_FAULT, label="node-kill")
    ev_eng.run(until=8.0)

    # tick-styled: the kill hides inside the tick body (the old idiom)
    tk_eng = SimEngine()
    tk = assemble_control_plane(_small_scenario("ev"), tk_eng)
    _submit_pods(tk)
    killed = [False]

    def tick_with_fault_check():
        if not killed[0] and tk_eng.now() >= kill_t:
            killed[0] = True
            tk.kill_host("pod-0-h1")
        tk.tick()

    tk_eng.tick_loop(0.25, tick_with_fault_check, until=8.0,
                     label="ctl-tick")
    tk_eng.run(until=8.0)

    assert _journal_trace(ev) == _journal_trace(tk)

    def phases(plane):
        return sorted((p.metadata.name, p.status.phase,
                       p.spec.node_name or "")
                      for p in plane.api.list(KIND_POD))

    assert phases(ev) == phases(tk)


def test_assembled_control_plane_schedules_and_runs_pods():
    eng = SimEngine()
    plane = assemble_control_plane(_small_scenario("basic"), eng)
    _submit_pods(plane)
    compose(*plane.sources()).install(eng)
    eng.run(until=8.0)
    pods = plane.api.list(KIND_POD)
    assert len(pods) == 2
    assert all(p.status.phase == RUNNING and p.spec.node_name
               for p in pods)


# ---------------------------------------------------------------------------
# Injector composition
# ---------------------------------------------------------------------------


def test_two_injectors_compose_on_one_run():
    api = ChaosAPIServer(seed=5)
    eng = SimEngine()
    cloud = ChaosCloudTPUAPI(5, clock=eng.now)
    api_chaos = APIChaosInjector(api, [(2.0, 3.0)], conflict_rate=0.5,
                                 transient_rate=0.25)
    cloud_chaos = CloudChaosInjector(cloud, [(2.0, 4.0), (8.0, 1.0)],
                                     machine_class="tpu-v5e", zone="z0")
    install_all(eng, [api_chaos, cloud_chaos])

    probes = {}

    def stockout_open() -> bool:
        return (cloud._stockout_until.get(("tpu-v5e", "z0"), 0.0)
                > eng.now())

    def probe(label, t):
        probes[(label, t)] = (api._conflict_rate, stockout_open())

    for t in (1.0, 2.5, 3.5, 4.5, 5.5, 8.5, 9.5):
        eng.at(t, (lambda when=t: probe("probe", when)),
               priority=PRIO_SAMPLE, label="probe")
    eng.run()

    assert probes[("probe", 1.0)] == (0.0, False)
    assert probes[("probe", 2.5)] == (0.5, True)     # both windows open
    assert probes[("probe", 3.5)] == (0.5, True)
    assert probes[("probe", 4.5)] == (0.5, True)
    assert probes[("probe", 5.5)] == (0.0, True)     # api closed at 5.0
    assert probes[("probe", 8.5)] == (0.0, True)     # second cloud window
    assert probes[("probe", 9.5)] == (0.0, False)
    assert cloud_chaos.opened == 2 and cloud_chaos.closed == 2


# ---------------------------------------------------------------------------
# Worst-week smoke: conservation + SLO verdicts
# ---------------------------------------------------------------------------


def test_worst_week_smoke_conserves_and_explains():
    cfg = WorstWeekConfig(seed=0).smoke()
    report = WorstWeek(cfg).run(wall_clock=lambda: 0.0)
    assert report["ledger"]["conservation_ok"]
    assert report["ledger"]["conservation_delta"] == 0.0
    # every registered objective must be judged — a missing verdict
    # means an SLO silently fell out of the evaluation loop
    judged = {v["objective"] for v in report["slo"]["verdicts"]}
    assert judged == {"sim_fleet_util_floor", "sim_serve_wait_p99",
                      "sim_train_wait_p99", "sim_research_wait_p99",
                      "sim_node_kill_rate"}
    assert report["unexplained_breaches"] == 0
    assert report["jobs"]["completed"] > 0
    assert report["events"] > 0


def test_worst_week_is_deterministic_per_seed():
    cfg = WorstWeekConfig(seed=1).smoke()
    a = WorstWeek(cfg).run(wall_clock=lambda: 0.0)
    b = WorstWeek(cfg).run(wall_clock=lambda: 0.0)
    for k in ("events", "jobs", "kills", "utilization", "wait_p99_s",
              "ledger", "slo", "breaches"):
        assert a[k] == b[k], k


def test_what_if_hosts_forecast_reports_deltas():
    from nos_tpu.sim.worstweek import parse_what_if, run_what_if

    assert parse_what_if("hosts=+120") == {"hosts_delta": 120}
    assert parse_what_if("hosts=-60") == {"hosts_delta": -60}
    with pytest.raises(ValueError):
        parse_what_if("quota=train:0.9,serve:0.3")   # fracs must sum to 1
    with pytest.raises(ValueError):
        parse_what_if("chips=+8")                    # unknown knob

    cfg = WorstWeekConfig(seed=0).smoke()
    base = WorstWeek(cfg).run(wall_clock=lambda: 0.0)
    out = run_what_if(cfg, "hosts=+120", base_report=base,
                      wall_clock=lambda: 0.0)
    assert out["delta"]["hosts"] == 120
    assert set(out["delta"]["wait_p99_s"]) == {"train", "serve",
                                               "research"}


# ---------------------------------------------------------------------------
# The bench stdout contract: ONE JSON document
# ---------------------------------------------------------------------------


def test_stdout_contract_one_json_document(capsys):
    """Everything printed under the swap lands on stderr; exactly one
    JSON document reaches the real stdout — the contract every bench
    main and ``python -m nos_tpu.sim`` are parsed under."""
    with stdout_to_stderr() as real_stdout:
        print("library noise")            # must NOT reach stdout
        print("progress: 50%")
        emit({"ok": True, "n": 3}, real_stdout)
    captured = capsys.readouterr()
    assert "library noise" in captured.err
    assert "progress: 50%" in captured.err
    lines = [ln for ln in captured.out.splitlines() if ln.strip()]
    assert len(lines) == 1
    assert json.loads(lines[0]) == {"ok": True, "n": 3}


def test_sim_cli_smoke_emits_one_json_and_gates(capsys, tmp_path):
    from nos_tpu.sim.__main__ import main

    report_path = tmp_path / "sim-report.json"
    rc = main(["--smoke", "--report", str(report_path)],
              wall_clock=lambda: 0.0)
    captured = capsys.readouterr()
    lines = [ln for ln in captured.out.splitlines() if ln.strip()]
    assert len(lines) == 1               # the one-document contract
    report = json.loads(lines[0])
    assert rc == 0
    assert report["ledger"]["conservation_ok"]
    assert conservation_ok is not None   # re-exported invariant exists
    artifact = json.loads(report_path.read_text())
    assert artifact["scenario"] == report["scenario"]
