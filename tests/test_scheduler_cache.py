"""Incremental scheduler cluster view + per-cycle Filter memo.

The watch-driven SchedulerCache must (a) mirror what the full-scan
snapshot computed, (b) rebuild NodeInfos for exactly the nodes events
touched (bind / evict / geometry change) and reuse the rest by object
identity, and (c) the per-cycle pod-equivalence Filter cache must skip
re-running the pipeline for identical requests while invalidating the
node a pod was just assumed onto.
"""

from nos_tpu.api import constants as C
from nos_tpu.kube.client import APIServer, Informer, KIND_NODE, KIND_POD
from nos_tpu.kube.objects import RUNNING, SUCCEEDED
from nos_tpu.scheduler.cache import SchedulerCache
from nos_tpu.scheduler.framework import (
    CycleState, Framework, NodeInfo, NodeResourcesFit, Status,
)
from nos_tpu.scheduler.scheduler import Scheduler
from nos_tpu.testing.factory import make_pod, make_slice_pod, make_tpu_node


def infos_by_name(lister):
    return {ni.name: ni for ni in lister.list()}


class TestInformer:
    def test_initial_sync_and_updates(self):
        api = APIServer()
        api.create(KIND_NODE, make_tpu_node("n1"))
        informer = Informer(api, KIND_NODE)
        assert set(informer.items()) == {"n1"}
        api.create(KIND_NODE, make_tpu_node("n2"))
        api.delete(KIND_NODE, "n1")
        assert set(informer.items()) == {"n2"}
        assert informer.get("n2") is not None
        assert len(informer) == 1

    def test_close_stops_delivery(self):
        api = APIServer()
        informer = Informer(api, KIND_NODE)
        informer.close()
        api.create(KIND_NODE, make_tpu_node("n1"))
        assert len(informer) == 0

    def test_namespaced_keys(self):
        api = APIServer()
        events = []
        informer = Informer(api, KIND_POD,
                            on_event=lambda ev, o: events.append(ev))
        api.create(KIND_POD, make_pod(name="p", namespace="ns"))
        assert set(informer.items()) == {"ns/p"}
        assert events == ["ADDED"]


class TestSchedulerCache:
    def test_matches_full_scan_snapshot(self):
        api = APIServer()
        cache = SchedulerCache(api)
        api.create(KIND_NODE, make_tpu_node("n1"))
        api.create(KIND_NODE, make_tpu_node("n2"))
        api.create(KIND_POD, make_slice_pod(
            "2x2", 1, name="bound", node_name="n1"))
        api.create(KIND_POD, make_slice_pod("2x2", 1, name="pending"))
        api.create(KIND_POD, make_slice_pod(
            "2x2", 1, name="done", node_name="n1", phase=SUCCEEDED))
        view = infos_by_name(cache.snapshot())
        assert set(view) == {"n1", "n2"}
        assert [p.metadata.name for p in view["n1"].pods] == ["bound"]
        assert view["n2"].pods == []

    def test_generation_gated_rebuild(self):
        api = APIServer()
        cache = SchedulerCache(api)
        api.create(KIND_NODE, make_tpu_node("n1"))
        api.create(KIND_NODE, make_tpu_node("n2"))
        first = infos_by_name(cache.snapshot())
        second = infos_by_name(cache.snapshot())
        # nothing changed: identical NodeInfo objects, no rebuild
        assert first["n1"] is second["n1"]
        assert first["n2"] is second["n2"]
        # touching n1 (geometry annotation write) rebuilds ONLY n1
        api.patch(KIND_NODE, "n1",
                  mutate=lambda n: n.metadata.annotations.__setitem__(
                      "k", "v"))
        third = infos_by_name(cache.snapshot())
        assert third["n1"] is not second["n1"]
        assert third["n1"].node.metadata.annotations["k"] == "v"
        assert third["n2"] is second["n2"]

    def test_bind_and_evict_invalidate_the_node(self):
        api = APIServer()
        cache = SchedulerCache(api)
        api.create(KIND_NODE, make_tpu_node("n1"))
        api.create(KIND_POD, make_slice_pod("2x2", 1, name="p"))
        before = infos_by_name(cache.snapshot())["n1"]
        assert before.pods == []
        api.patch(KIND_POD, "p", "default",
                  mutate=lambda p: setattr(p.spec, "node_name", "n1"))
        bound = infos_by_name(cache.snapshot())["n1"]
        assert bound is not before
        assert [p.metadata.name for p in bound.pods] == ["p"]
        api.delete(KIND_POD, "p", "default")
        evicted = infos_by_name(cache.snapshot())["n1"]
        assert evicted is not bound
        assert evicted.pods == []
        assert evicted.requested == {}

    def test_pod_bound_before_node_appears(self):
        # replacement hosts: the pod index is node-existence independent
        api = APIServer()
        cache = SchedulerCache(api)
        api.create(KIND_POD, make_slice_pod(
            "2x2", 1, name="p", node_name="late"))
        assert infos_by_name(cache.snapshot()) == {}
        api.create(KIND_NODE, make_tpu_node("late"))
        view = infos_by_name(cache.snapshot())
        assert [p.metadata.name for p in view["late"].pods] == ["p"]

    def test_completed_pod_releases_capacity(self):
        api = APIServer()
        cache = SchedulerCache(api)
        api.create(KIND_NODE, make_tpu_node("n1"))
        api.create(KIND_POD, make_slice_pod(
            "2x2", 1, name="p", node_name="n1", phase=RUNNING))
        assert infos_by_name(cache.snapshot())["n1"].pods
        api.patch(KIND_POD, "p", "default",
                  mutate=lambda p: setattr(p.status, "phase", SUCCEEDED))
        assert infos_by_name(cache.snapshot())["n1"].pods == []

    def test_node_delete_drops_view(self):
        api = APIServer()
        cache = SchedulerCache(api)
        api.create(KIND_NODE, make_tpu_node("n1"))
        assert set(infos_by_name(cache.snapshot())) == {"n1"}
        api.delete(KIND_NODE, "n1")
        assert infos_by_name(cache.snapshot()) == {}


class _CountingFit:
    """NodeResourcesFit wrapper counting Filter invocations."""

    name = "CountingFit"

    def __init__(self):
        self.calls = 0
        self._inner = NodeResourcesFit()

    def filter(self, state: CycleState, pod, node_info) -> Status:
        self.calls += 1
        return self._inner.filter(state, pod, node_info)


class TestFilterEquivalenceCache:
    def _cluster(self, nodes=4):
        api = APIServer()
        for i in range(nodes):
            api.create(KIND_NODE, make_tpu_node(
                f"n{i}", host_index=i,
                status_geometry={"free": {"2x2": 2}}))
        return api

    def test_identical_requests_share_verdicts(self):
        api = self._cluster(nodes=4)
        plugin = _CountingFit()
        scheduler = Scheduler(api, Framework([plugin]))
        for i in range(3):
            api.create(KIND_POD, make_slice_pod("2x2", 1, name=f"p{i}"))
        bound = scheduler.run_cycle()
        assert bound == 3
        # pod 0: 4 fresh verdicts; pods 1-2: only the node the previous
        # pod was assumed onto is re-filtered (its verdicts died), the
        # other 3 come from the memo.
        assert plugin.calls == 4 + 1 + 1

    def test_gang_members_are_never_cached(self):
        api = self._cluster(nodes=2)
        plugin = _CountingFit()
        scheduler = Scheduler(api, Framework([plugin]))
        api.create(KIND_POD, make_slice_pod(
            "2x2", 1, name="g0",
            labels={C.LABEL_POD_GROUP: "g"}))
        api.create(KIND_POD, make_slice_pod(
            "2x2", 1, name="solo"))
        assert scheduler._filter_equiv_key(
            api.get(KIND_POD, "g0", "default")) is None
        assert scheduler._filter_equiv_key(
            api.get(KIND_POD, "solo", "default")) is not None

    def test_cache_respects_consumed_capacity(self):
        # one node with room for exactly one pod: the second identical
        # pod must NOT reuse the stale "fits" verdict after the assume
        api = APIServer()
        api.create(KIND_NODE, make_tpu_node(
            "n0", status_geometry={"free": {"2x4": 1}}))
        scheduler = Scheduler(api, Framework([NodeResourcesFit()]))
        api.create(KIND_POD, make_slice_pod("2x4", 1, name="first"))
        api.create(KIND_POD, make_slice_pod("2x4", 1, name="second"))
        assert scheduler.run_cycle() == 1
        second = api.get(KIND_POD, "second", "default")
        assert not second.spec.node_name
        assert second.is_unschedulable()


class TestReviewRegressions:
    def test_vanished_pod_bind_is_not_assumed(self):
        # a pod deleted between the cycle's LIST and the bind patch
        # produces no write (NotFound swallowed), so no watch event and
        # no generation bump: assuming it would pollute the cached
        # NodeInfo with phantom capacity FOREVER (the old full-rebuild
        # snapshot self-healed next cycle; the incremental cache cannot)
        api = APIServer()
        api.create(KIND_NODE, make_tpu_node(
            "n0", status_geometry={"free": {"2x4": 1}}))
        scheduler = Scheduler(api, Framework([NodeResourcesFit()]))
        ghost = make_slice_pod("2x4", 1, name="ghost")   # never created
        # bind hits NotFound: nothing was placed, nothing is reported
        assert scheduler.schedule_one(ghost) is None
        view = infos_by_name(scheduler.snapshot())
        assert view["n0"].pods == []
        assert view["n0"].requested == {}
        # and the freed capacity is actually usable by a real pod
        api.create(KIND_POD, make_slice_pod("2x4", 1, name="real"))
        assert scheduler.run_cycle() == 1

    def test_close_detaches_the_cache(self):
        api = APIServer()
        scheduler = Scheduler(api, Framework())
        scheduler.close()
        api.create(KIND_NODE, make_tpu_node("n0"))
        assert infos_by_name(scheduler._cache.snapshot()) == {}

    def test_vanished_pod_reservation_rolled_back(self):
        # reserve books the pod into the LIVE quota ledger; when the
        # bind then hits NotFound (pod deleted mid-cycle, its DELETED
        # event long gone) the reservation must be unwound or the
        # namespace's `used` stays inflated forever
        from nos_tpu.api import constants as C
        from nos_tpu.api.elasticquota import ElasticQuota, ElasticQuotaSpec
        from nos_tpu.cmd.assembly import build_scheduler
        from nos_tpu.kube.client import KIND_ELASTIC_QUOTA
        from nos_tpu.kube.objects import ObjectMeta

        api = APIServer()
        api.create(KIND_NODE, make_tpu_node(
            "n0", status_geometry={"free": {"2x4": 1}}))
        scheduler = build_scheduler(api)
        api.create(KIND_ELASTIC_QUOTA, ElasticQuota(
            metadata=ObjectMeta(name="q", namespace="default"),
            spec=ElasticQuotaSpec(min={C.RESOURCE_TPU_MEMORY: 1000.0})))
        ghost = make_slice_pod("2x4", 1, name="ghost")   # never created
        assert scheduler.schedule_one(ghost) is None
        cap = next(p for p in scheduler._framework.plugins
                   if hasattr(p, "elastic_quota_infos"))
        info = cap.elastic_quota_infos.get("default")
        assert info.used.get(C.RESOURCE_TPU_MEMORY, 0.0) == 0.0

    def test_assume_survives_node_event_rebuild(self):
        # async-substrate coherence: the assumed pod is booked into the
        # cache indexes, so a node-event rebuild cannot resurrect the
        # pre-bind view while the pod's own watch event lags
        api = APIServer()
        cache = SchedulerCache(api)
        api.create(KIND_NODE, make_tpu_node(
            "n0", status_geometry={"free": {"2x4": 1}}))
        assumed = make_slice_pod("2x4", 1, name="p", node_name="n0")
        cache.assume(assumed)
        api.patch(KIND_NODE, "n0",
                  mutate=lambda n: n.metadata.annotations.__setitem__(
                      "k", "v"))
        view = infos_by_name(cache.snapshot())
        assert [p.metadata.name for p in view["n0"].pods] == ["p"]


class TestSchedulerEndToEnd:
    def test_run_cycle_binds_through_the_cache(self):
        api = APIServer()
        api.create(KIND_NODE, make_tpu_node(
            "n0", status_geometry={"free": {"2x2": 2}}))
        scheduler = Scheduler(api, Framework())
        assert scheduler._cache is not None
        api.create(KIND_POD, make_slice_pod("2x2", 1, name="p"))
        assert scheduler.run_cycle() == 1
        assert api.get(KIND_POD, "p", "default").spec.node_name == "n0"

    def test_watchless_substrate_falls_back_to_full_scan(self):
        class NoWatchAPI:
            def __init__(self, api):
                self._api = api

            def __getattr__(self, name):
                if name == "watch":
                    raise AttributeError(name)
                return getattr(self._api, name)

        api = APIServer()
        api.create(KIND_NODE, make_tpu_node(
            "n0", status_geometry={"free": {"2x2": 2}}))
        wrapped = NoWatchAPI(api)
        scheduler = Scheduler(wrapped, Framework())
        assert scheduler._cache is None
        api.create(KIND_POD, make_slice_pod("2x2", 1, name="p"))
        assert scheduler.run_cycle() == 1
        view = infos_by_name(scheduler.snapshot())
        assert isinstance(view["n0"], NodeInfo)
        assert [p.metadata.name for p in view["n0"].pods] == ["p"]


class TestPrescreenDisablePath:
    """Runtime disable of the native fit prescreen (shim-less latch, a
    test, an operator toggle) must be a benign fallback to the pure
    Filter pipeline — never a crashed cycle.  The old code asserted on
    ``self._prescreen`` inside the seed call, so a drop landing between
    the caller's None check and the dereference took the whole cycle
    down (ISSUE 18 satellite; scheduler._seed_filter_memo_native)."""

    def _cluster(self):
        api = APIServer()
        for i in range(3):
            api.create(KIND_NODE, make_tpu_node(
                f"n{i}", host_index=i,
                status_geometry={"free": {"2x2": 1}}))
        return api, Scheduler(api, Framework([NodeResourcesFit()]))

    def test_seed_with_screen_already_dropped_is_noop(self):
        api, scheduler = self._cluster()
        scheduler._prescreen = None
        api.create(KIND_POD, make_slice_pod("2x2", 1, name="p"))
        pod = api.get(KIND_POD, "p", "default")
        equiv = scheduler._filter_equiv_key(pod)
        assert equiv is not None
        # the old assert crashed exactly here; now: quiet no-op
        scheduler._seed_filter_memo_native(
            pod, equiv, scheduler._cycle_lister())
        assert scheduler._filter_cache == {}
        # and the pure pipeline still schedules
        assert scheduler.run_cycle() == 1

    def test_screen_dropped_mid_call_finishes_on_snapshot(self, monkeypatch):
        # simulate the race: the screen is dropped AFTER the caller's
        # check, while the seed call is in flight — the local snapshot
        # must keep this call self-consistent (seed or no-op, no crash)
        from nos_tpu.device import native
        api, scheduler = self._cluster()
        assert scheduler._prescreen is not None

        def dropping_probe(build=False):
            scheduler._prescreen = None
            return True

        monkeypatch.setattr(native, "fit_batch_available", dropping_probe)
        api.create(KIND_POD, make_slice_pod("2x2", 1, name="p"))
        pod = api.get(KIND_POD, "p", "default")
        scheduler._seed_filter_memo_native(
            pod, scheduler._filter_equiv_key(pod), scheduler._cycle_lister())
        assert scheduler._prescreen is None
        assert scheduler.run_cycle() == 1

    def test_shimless_deployment_latches_screen_off(self, monkeypatch):
        from nos_tpu.device import native
        api, scheduler = self._cluster()
        assert scheduler._prescreen is not None
        monkeypatch.setattr(native, "fit_batch_available",
                            lambda build=False: False)
        api.create(KIND_POD, make_slice_pod("2x2", 1, name="p"))
        assert scheduler.run_cycle() == 1
        # decided once, at the first cycle: the screen is latched off
        # so later cycles skip even the availability probe
        assert scheduler._prescreen is None
        api.create(KIND_POD, make_slice_pod("2x2", 1, name="q"))
        assert scheduler.run_cycle() == 1


class TestDirectEntryPointSnapshotHygiene:
    """schedule_one/schedule_gang are public entry points: a direct call
    (outside run_cycle) must never let an external mutation between
    calls go unseen (ADVICE round 5; scheduler.py `_in_cycle`).
    Full-rescan mode guarantees that by dropping the per-cycle snapshot
    at exit; incremental mode deliberately RETAINS it and re-levels it
    from the watch cache's dirty set on the next entry (ISSUE 18) —
    identical visible behavior, both contracts pinned here."""

    def test_direct_schedule_one_drops_cycle_snapshot(self):
        api = APIServer()
        api.create(KIND_NODE, make_tpu_node(
            "n0", status_geometry={"free": {"2x2": 1}}))
        scheduler = Scheduler(api, Framework(), incremental=False)
        blocker = make_slice_pod("2x2", 1, name="blocker")
        api.create(KIND_POD, blocker)
        assert scheduler.schedule_one(
            api.get(KIND_POD, "blocker", "default")) == "n0"
        # the direct call must not retain the snapshot it built
        assert scheduler._cycle_lister_cache is None
        assert scheduler._filter_cache == {}
        # external mutation between direct calls: the blocker vanishes
        api.delete(KIND_POD, "blocker", "default")
        late = make_slice_pod("2x2", 1, name="late")
        api.create(KIND_POD, late)
        # a stale snapshot would still count the blocker's capacity and
        # reject; a fresh one sees the freed slice
        assert scheduler.schedule_one(
            api.get(KIND_POD, "late", "default")) == "n0"

    def test_incremental_direct_calls_see_external_mutations(self):
        """Incremental mode keeps the snapshot across direct calls but
        the dirty-set re-level on entry makes every external mutation
        visible — same observable contract as the drop."""
        api = APIServer()
        api.create(KIND_NODE, make_tpu_node(
            "n0", status_geometry={"free": {"2x2": 1}}))
        scheduler = Scheduler(api, Framework())
        blocker = make_slice_pod("2x2", 1, name="blocker")
        api.create(KIND_POD, blocker)
        assert scheduler.schedule_one(
            api.get(KIND_POD, "blocker", "default")) == "n0"
        # retained on purpose: the next entry re-levels it
        assert scheduler._cycle_lister_cache is not None
        api.delete(KIND_POD, "blocker", "default")
        late = make_slice_pod("2x2", 1, name="late")
        api.create(KIND_POD, late)
        assert scheduler.schedule_one(
            api.get(KIND_POD, "late", "default")) == "n0"

    def test_direct_schedule_one_failure_also_drops_snapshot(self):
        api = APIServer()
        api.create(KIND_NODE, make_tpu_node(
            "n0", status_geometry={"free": {"2x4": 1}}))
        scheduler = Scheduler(api, Framework(), incremental=False)
        stuck = make_slice_pod("2x2", 1, name="stuck")
        api.create(KIND_POD, stuck)
        assert scheduler.schedule_one(
            api.get(KIND_POD, "stuck", "default")) is None
        assert scheduler._cycle_lister_cache is None

    def test_run_cycle_keeps_snapshot_across_its_own_pods(self, monkeypatch):
        api = APIServer()
        api.create(KIND_NODE, make_tpu_node(
            "n0", status_geometry={"free": {"2x2": 2}}))
        scheduler = Scheduler(api, Framework())
        rebuilds = []
        orig = Scheduler.snapshot

        def counting(self):
            rebuilds.append(1)
            return orig(self)

        monkeypatch.setattr(Scheduler, "snapshot", counting)
        for i in range(2):
            api.create(KIND_POD, make_slice_pod("2x2", 1, name=f"p{i}"))
        assert scheduler.run_cycle() == 2
        # one snapshot for the whole cycle, not one per pod
        assert len(rebuilds) == 1
