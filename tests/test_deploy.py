"""Deploy-artifact tests: the chart must render and its rendered
ConfigMaps must satisfy the typed config loaders (`helm template`-level
validation without helm in the image).

The renderer (nos_tpu/testing/helm.py, shared with the dev-cluster
harness) implements exactly the template subset the chart commits to
(_helpers.tpl documents it).  Straying outside the subset fails the
test, which is the point — the chart stays mechanically verifiable in
CI.
"""

from __future__ import annotations

import pathlib
import re

import pytest
import yaml

from nos_tpu.api.config import (
    AgentConfig, AutoscalerConfig, OperatorConfig, PartitionerConfig,
    SchedulerConfig, load_config,
)
from nos_tpu.testing.helm import default_context, render

CHART = pathlib.Path(__file__).resolve().parent.parent / "deploy/helm/nos-tpu"
BUILD = CHART.parent.parent.parent / "build"


@pytest.fixture(scope="module")
def ctx():
    return default_context(CHART)


def _templates():
    return sorted(p for p in CHART.glob("templates/**/*.yaml"))


class TestDevClusterHarness:
    def test_render_mode_runs_clean(self):
        """hack/dev-cluster.sh's CI-enforced half: render-and-validate
        must work with no cluster binaries in the image (the kind `up`
        path applies exactly these manifests)."""
        import subprocess
        import sys

        proc = subprocess.run(
            [sys.executable, str(CHART.parent.parent.parent
                                 / "hack/render-chart.py")],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
        assert "validated 6 ConfigMaps" in proc.stdout
        assert "3 CRDs" in proc.stdout


class TestChartRenders:
    def test_every_template_renders_to_valid_yaml(self, ctx):
        rendered = 0
        for path in _templates():
            out = render(path.read_text(), ctx)
            for doc in yaml.safe_load_all(out):
                if doc is None:
                    continue
                assert "kind" in doc and "apiVersion" in doc, path.name
                rendered += 1
        assert rendered >= 15  # a complete install, not a stub

    def test_disabled_component_renders_empty(self, ctx):
        import copy

        c = copy.deepcopy(ctx)
        c["Values"]["partitioner"]["enabled"] = False
        out = render(
            (CHART / "templates/partitioner/deployment.yaml").read_text(), c)
        assert all(d is None for d in yaml.safe_load_all(out))

    def test_webhook_disabled_renders_cleanly(self, ctx):
        """operator.webhook.enabled=false alone must fully disable the
        webhook: no VWC/certgen manifests, no cert mount or webhook port
        in the Deployment, and a ConfigMap without webhook_port (so the
        operator neither serves nor crashloops on missing certs)."""
        import copy

        c = copy.deepcopy(ctx)
        c["Values"]["operator"]["webhook"]["enabled"] = False
        for rel in ("templates/operator/webhook.yaml",
                    "templates/operator/webhook-certgen.yaml"):
            out = render((CHART / rel).read_text(), c)
            assert all(d is None for d in yaml.safe_load_all(out)), rel
        dep = yaml.safe_load(render(
            (CHART / "templates/operator/deployment.yaml").read_text(), c))
        spec = dep["spec"]["template"]["spec"]
        assert [v["name"] for v in spec["volumes"]] == ["config"]
        container = spec["containers"][0]
        assert [p["name"] for p in container["ports"]] == ["health"]
        cm = yaml.safe_load(render(
            (CHART / "templates/operator/configmap.yaml").read_text(), c))
        assert "webhook_port" not in cm["data"]["config.yaml"]

    def test_webhook_enabled_renders_vwc_and_jobs(self, ctx):
        out = render(
            (CHART / "templates/operator/webhook.yaml").read_text(), ctx)
        docs = [d for d in yaml.safe_load_all(out) if d]
        kinds = sorted(d["kind"] for d in docs)
        assert kinds == ["MutatingWebhookConfiguration", "Service",
                         "ValidatingWebhookConfiguration"]
        mwc = next(d for d in docs
                   if d["kind"] == "MutatingWebhookConfiguration")
        assert mwc["webhooks"][0]["failurePolicy"] == "Ignore"
        assert mwc["webhooks"][0]["rules"][0]["resources"] == ["pods"]
        vwc = next(d for d in docs
                   if d["kind"] == "ValidatingWebhookConfiguration")
        rules = [w["rules"][0]["resources"][0] for w in vwc["webhooks"]]
        assert sorted(rules) == ["compositeelasticquotas", "elasticquotas"]
        out2 = render(
            (CHART / "templates/operator/webhook-certgen.yaml").read_text(),
            ctx)
        kinds2 = [d["kind"] for d in yaml.safe_load_all(out2) if d]
        assert kinds2.count("Job") == 3  # create + 2 patch

    def test_crds_are_valid_yaml(self):
        names = set()
        for path in sorted(CHART.glob("crds/*.yaml")):
            doc = yaml.safe_load(path.read_text())
            assert doc["kind"] == "CustomResourceDefinition"
            assert doc["spec"]["group"] == "nos.tpu"
            names.add(doc["spec"]["names"]["kind"])
        assert names == {"ElasticQuota", "CompositeElasticQuota", "PodGroup"}


class TestRenderedConfigsLoad:
    """The chart's ConfigMaps must round-trip through the typed config
    loaders — chart and code cannot drift apart silently."""

    def test_every_config_configmap_is_wired(self, ctx):
        """The shared CONFIG_KINDS table (testing/helm.py) must cover
        every rendered config.yaml ConfigMap — validate_configmaps
        raises on an unknown one, so a seventh component cannot ship a
        config that nothing validates."""
        from nos_tpu.testing.helm import render_chart, validate_configmaps

        assert validate_configmaps(render_chart(CHART, ctx)) == 6

    @pytest.mark.parametrize("component,cls", [
        ("partitioner", PartitionerConfig),
        ("operator", OperatorConfig),
        ("scheduler", SchedulerConfig),
        ("autoscaler", AutoscalerConfig),
    ])
    def test_component_config(self, ctx, tmp_path, component, cls):
        out = render(
            (CHART / f"templates/{component}/configmap.yaml").read_text(),
            ctx)
        cm = yaml.safe_load(out)
        cfg_file = tmp_path / "config.yaml"
        cfg_file.write_text(cm["data"]["config.yaml"])
        cfg = load_config(str(cfg_file), cls)
        cfg.validate()

    def test_provisioner_config(self, ctx, tmp_path):
        """The capacity plane is off by default (nothing renders —
        off means off at the chart layer too); flipping it on must
        produce a ProvisionerConfig the loader accepts, with the
        plane's own `enabled` gate set."""
        import copy

        from nos_tpu.api.config import ProvisionerConfig

        out = render(
            (CHART / "templates/provisioner/configmap.yaml").read_text(),
            ctx)
        assert all(d is None for d in yaml.safe_load_all(out))
        c = copy.deepcopy(ctx)
        c["Values"]["provisioner"]["enabled"] = True
        out = render(
            (CHART / "templates/provisioner/configmap.yaml").read_text(), c)
        cm = yaml.safe_load(out)
        cfg_file = tmp_path / "config.yaml"
        cfg_file.write_text(cm["data"]["config.yaml"])
        cfg = load_config(str(cfg_file), ProvisionerConfig)
        cfg.validate()
        assert cfg.enabled is True
        kinds = []
        for rel in ("templates/provisioner/deployment.yaml",
                    "templates/provisioner/rbac.yaml"):
            kinds += [d["kind"] for d in yaml.safe_load_all(
                render((CHART / rel).read_text(), c)) if d]
        assert sorted(kinds) == ["ClusterRole", "ClusterRoleBinding",
                                 "Deployment", "ServiceAccount"]

    @pytest.mark.parametrize("component", ["sliceagent", "chipagent"])
    def test_agent_config(self, ctx, tmp_path, component):
        out = render(
            (CHART / f"templates/{component}/configmap.yaml").read_text(),
            ctx)
        cm = yaml.safe_load(out)
        cfg_file = tmp_path / "config.yaml"
        cfg_file.write_text(cm["data"]["config.yaml"])
        # node identity arrives via --node at runtime (downward API)
        from nos_tpu.api.config import load_agent_config

        cfg = load_agent_config(str(cfg_file), "host-0")
        assert isinstance(cfg, AgentConfig)
        assert cfg.node_name == "host-0"


class TestDockerfiles:
    def test_one_dockerfile_per_component(self):
        components = {"operator", "partitioner", "scheduler", "sliceagent",
                      "chipagent", "metricsexporter", "train",
                      "autoscaler", "provisioner"}
        found = {p.parent.name for p in BUILD.glob("*/Dockerfile")}
        assert found == components
        assert (BUILD / "Dockerfile.base").exists()

    def test_entrypoints_match_cmd_mains(self):
        import importlib

        for p in BUILD.glob("*/Dockerfile"):
            text = p.read_text()
            m = re.search(r'ENTRYPOINT \["python", "-m", "([\w.]+)"\]', text)
            assert m, f"{p}: no python -m entrypoint"
            mod = importlib.import_module(m.group(1))
            assert hasattr(mod, "main"), m.group(1)
