"""Admission webhook tests (VERDICT r3 missing #2): the HTTPS
AdmissionReview endpoint must enforce the same quota rules the in-memory
substrate enforces in-process — duplicate ElasticQuota per namespace and
EQ/CEQ overlap are rejected server-side on a real cluster."""

from __future__ import annotations

import json
import ssl
import subprocess
import urllib.request

import pytest

from nos_tpu.api.elasticquota import (
    validate_composite_elastic_quota, validate_elastic_quota,
)
from nos_tpu.kube.client import (
    APIServer, KIND_COMPOSITE_ELASTIC_QUOTA, KIND_ELASTIC_QUOTA,
)
from nos_tpu.kube.webhook import AdmissionHandler, WebhookServer


def review(kind: str, obj: dict, uid: str = "uid-1",
           operation: str = "CREATE") -> bytes:
    return json.dumps({
        "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
        "request": {"uid": uid, "operation": operation,
                    "kind": {"group": "nos.tpu", "version": "v1alpha1",
                             "kind": kind},
                    "object": obj},
    }).encode()


def eq_json(name: str, namespace: str, tpus: int = 4) -> dict:
    return {"metadata": {"name": name, "namespace": namespace},
            "spec": {"min": {"google.com/tpu": tpus}}}


def ceq_json(name: str, namespaces: list[str]) -> dict:
    return {"metadata": {"name": name, "namespace": "default"},
            "spec": {"namespaces": namespaces,
                     "min": {"google.com/tpu": 8}}}


@pytest.fixture
def handler():
    """Handler over a pre-populated store: team-a has an EQ; team-c and
    team-d are governed by a CompositeElasticQuota."""
    from nos_tpu.kube.k8s_codec import from_k8s

    api = APIServer()
    api.create(KIND_ELASTIC_QUOTA,
               from_k8s(KIND_ELASTIC_QUOTA, eq_json("quota-a", "team-a")))
    api.create(KIND_COMPOSITE_ELASTIC_QUOTA,
               from_k8s(KIND_COMPOSITE_ELASTIC_QUOTA,
                        ceq_json("comp-cd", ["team-c", "team-d"])))
    h = AdmissionHandler(api)
    h.register(KIND_ELASTIC_QUOTA, validate_elastic_quota)
    h.register(KIND_COMPOSITE_ELASTIC_QUOTA, validate_composite_elastic_quota)
    return h


class TestAdmissionHandler:
    def test_fresh_namespace_allowed(self, handler):
        resp = handler.handle(review(
            "ElasticQuota", eq_json("quota-b", "team-b")))
        assert resp["response"] == {"uid": "uid-1", "allowed": True}

    def test_duplicate_eq_denied(self, handler):
        resp = handler.handle(review(
            "ElasticQuota", eq_json("quota-a2", "team-a")))
        assert resp["response"]["allowed"] is False
        assert "quota-a" in resp["response"]["status"]["message"]

    def test_eq_update_of_itself_allowed(self, handler):
        resp = handler.handle(review(
            "ElasticQuota", eq_json("quota-a", "team-a", tpus=8),
            operation="UPDATE"))
        assert resp["response"]["allowed"] is True

    def test_eq_overlapping_ceq_denied(self, handler):
        resp = handler.handle(review(
            "ElasticQuota", eq_json("quota-c", "team-c")))
        assert resp["response"]["allowed"] is False
        assert "comp-cd" in resp["response"]["status"]["message"]

    def test_ceq_overlap_denied(self, handler):
        resp = handler.handle(review(
            "CompositeElasticQuota", ceq_json("comp-2", ["team-d", "team-e"])))
        assert resp["response"]["allowed"] is False

    def test_delete_passes_through(self, handler):
        resp = handler.handle(review(
            "ElasticQuota", eq_json("quota-a", "team-a"),
            operation="DELETE"))
        assert resp["response"]["allowed"] is True

    def test_malformed_review_denied_not_crashed(self, handler):
        assert handler.handle(b"not json")["response"]["allowed"] is False
        assert handler.handle(b"{}")["response"]["allowed"] is False
        resp = handler.handle(review("ElasticQuota", "banana"))
        assert resp["response"]["allowed"] is False
        assert resp["response"]["uid"] == "uid-1"   # uid still echoed


def _post(url: str, body: bytes, ctx=None) -> dict:
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10, context=ctx) as r:
        return json.loads(r.read())


class TestWebhookServerHTTPS:
    """The transport the kube-apiserver actually speaks: TLS, POST,
    AdmissionReview v1 in and out."""

    @pytest.fixture
    def certs(self, tmp_path):
        crt, key = tmp_path / "tls.crt", tmp_path / "tls.key"
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(key), "-out", str(crt), "-days", "1",
             "-subj", "/CN=localhost",
             "-addext", "subjectAltName=DNS:localhost"],
            check=True, capture_output=True)
        return str(crt), str(key)

    def test_https_post_enforces_rules(self, handler, certs):
        crt, key = certs
        server = WebhookServer(handler, host="127.0.0.1", port=0,
                               cert_file=crt, key_file=key)
        server.start()
        try:
            ctx = ssl.create_default_context(cafile=crt)
            ctx.check_hostname = False
            url = f"https://127.0.0.1:{server.port}/validate-elasticquota"
            ok = _post(url, review("ElasticQuota",
                                   eq_json("quota-b", "team-b")), ctx)
            assert ok["response"]["allowed"] is True
            dup = _post(url, review("ElasticQuota",
                                    eq_json("dup", "team-a")), ctx)
            assert dup["response"]["allowed"] is False
            assert dup["response"]["status"]["code"] == 403
            overlap = _post(
                f"https://127.0.0.1:{server.port}/validate-compositeelasticquota",
                review("CompositeElasticQuota",
                       ceq_json("comp-2", ["team-c"])), ctx)
            assert overlap["response"]["allowed"] is False
        finally:
            server.stop()

    def test_health_endpoints(self, handler, certs):
        crt, key = certs
        server = WebhookServer(handler, host="127.0.0.1", port=0,
                               cert_file=crt, key_file=key)
        server.start()
        try:
            ctx = ssl.create_default_context(cafile=crt)
            ctx.check_hostname = False
            with urllib.request.urlopen(
                    f"https://127.0.0.1:{server.port}/healthz",
                    timeout=10, context=ctx) as r:
                assert r.read() == b"ok"
        finally:
            server.stop()


class TestOperatorServesWebhook:
    def test_operator_main_serves_admission(self):
        """build_operator_main with webhook_port wires the endpoint with
        the production validators (HTTP here; TLS is chart-provisioned)."""
        from nos_tpu.api.config import OperatorConfig
        from nos_tpu.cmd.operator import build_operator_main
        from nos_tpu.kube.k8s_codec import from_k8s

        api = APIServer()
        api.create(KIND_ELASTIC_QUOTA,
                   from_k8s(KIND_ELASTIC_QUOTA, eq_json("held", "team-a")))
        cfg = OperatorConfig(leader_election=False, webhook_port=0)
        main = build_operator_main(api, cfg)
        assert not hasattr(main, "webhook")

        # WebhookServer(port=0) binds an ephemeral port; the operator
        # main requires port>0, so drive its helper directly
        from nos_tpu.cmd.operator import _serve_admission_webhook
        cfg2 = OperatorConfig(leader_election=False, webhook_port=0)
        server = None
        try:
            server = _serve_admission_webhook(api, cfg2)
            url = f"http://127.0.0.1:{server.port}/validate-elasticquota"
            dup = _post(url, review("ElasticQuota",
                                    eq_json("dup", "team-a")))
            assert dup["response"]["allowed"] is False
        finally:
            if server is not None:
                server.stop()

    def test_kubeclient_collects_validators(self):
        """register_admission on the REST substrate feeds the webhook
        handler instead of warning it away (r3 missing #2)."""
        from nos_tpu.kube.rest import KubeClient, KubeConfig

        client = KubeClient(KubeConfig("http://127.0.0.1:1"))
        client.register_admission(KIND_ELASTIC_QUOTA, validate_elastic_quota)
        assert client.admission.kinds == [KIND_ELASTIC_QUOTA]
