"""Leader election tests (the reference runs controller-runtime leader
election on operator/gpupartitioner/scheduler — helm values.yaml:57,121,
285; round-2 VERDICT flagged our config field as dead)."""

from __future__ import annotations

import threading
import time

from nos_tpu.kube.client import APIServer
from nos_tpu.kube.leaderelection import LeaderElector


class FakeClock:
    def __init__(self) -> None:
        self.now = 1000.0

    def __call__(self) -> float:
        return self.now


class TestElector:
    def test_first_candidate_acquires(self):
        api = APIServer()
        e = LeaderElector(api, "lease", identity="a")
        assert e.try_acquire_or_renew() == e.LEADING
        assert e.try_acquire_or_renew() == e.LEADING  # renew keeps it

    def test_second_candidate_blocked_until_expiry(self):
        api = APIServer()
        clock = FakeClock()
        a = LeaderElector(api, "lease", identity="a", clock=clock,
                          lease_duration_s=15.0)
        b = LeaderElector(api, "lease", identity="b", clock=clock,
                          lease_duration_s=15.0)
        assert a.try_acquire_or_renew() == a.LEADING
        assert b.try_acquire_or_renew() == b.BLOCKED
        clock.now += 16.0  # a's lease expires un-renewed
        assert b.try_acquire_or_renew() == b.LEADING
        assert a.try_acquire_or_renew() == a.BLOCKED  # takeover sticks

    def test_release_hands_over_immediately(self):
        api = APIServer()
        clock = FakeClock()
        a = LeaderElector(api, "lease", identity="a", clock=clock)
        b = LeaderElector(api, "lease", identity="b", clock=clock)
        assert a.try_acquire_or_renew() == a.LEADING
        a._release()
        assert b.try_acquire_or_renew() == b.LEADING  # no wait for expiry

    def test_run_loop_failover(self):
        api = APIServer()
        a = LeaderElector(api, "lease", identity="a",
                          lease_duration_s=0.6, renew_s=0.1, retry_s=0.05)
        b = LeaderElector(api, "lease", identity="b",
                          lease_duration_s=0.6, renew_s=0.1, retry_s=0.05)
        stop_a, stop_b = threading.Event(), threading.Event()
        ta = threading.Thread(target=a.run, args=(stop_a,), daemon=True)
        tb = threading.Thread(target=b.run, args=(stop_b,), daemon=True)
        ta.start()
        assert a.is_leader.wait(2.0)
        tb.start()
        time.sleep(0.3)
        assert not b.is_leader.is_set()
        stop_a.set()          # leader dies; releases on exit
        ta.join(2.0)
        assert b.is_leader.wait(3.0), "standby never took over"
        stop_b.set()
        tb.join(2.0)


    def test_transient_error_does_not_demote_a_valid_leader(self):
        """One failed renew while the lease is still live must not fire
        the fatal demotion (controller-runtime retries until the renew
        deadline actually passes)."""
        api = APIServer()
        died = threading.Event()
        a = LeaderElector(api, "lease", identity="a",
                          lease_duration_s=8.0, renew_s=0.05, retry_s=0.05,
                          on_stopped_leading=died.set)
        stop = threading.Event()
        t = threading.Thread(target=a.run, args=(stop,), daemon=True)
        t.start()
        assert a.is_leader.wait(2.0)

        real_update = api.update
        fails = {"n": 3}

        def flaky_update(kind, obj):
            if fails["n"] > 0:
                fails["n"] -= 1
                raise OSError("injected apiserver blip")
            return real_update(kind, obj)

        api.update = flaky_update
        time.sleep(0.5)  # several failed renews, lease still valid
        assert not died.is_set(), "a blip demoted a valid leader"
        assert a.is_leader.is_set()
        stop.set()
        t.join(2.0)


    def test_losing_acquired_lease_is_fatal(self):
        """A running leader whose lease is stolen must fire
        on_stopped_leading and end its loop (controller-runtime
        semantics: demotion = process restart)."""
        import time as _time

        from nos_tpu.kube.client import KIND_CONFIGMAP
        from nos_tpu.kube.leaderelection import ANN_DEADLINE, ANN_HOLDER

        api = APIServer()
        died = threading.Event()
        a = LeaderElector(api, "lease", identity="a",
                          lease_duration_s=5.0, renew_s=0.05,
                          on_stopped_leading=died.set)
        stop = threading.Event()
        t = threading.Thread(target=a.run, args=(stop,), daemon=True)
        t.start()
        assert a.is_leader.wait(2.0)

        def steal(cm):
            cm.metadata.annotations[ANN_HOLDER] = "b"
            cm.metadata.annotations[ANN_DEADLINE] = str(
                _time.time() + 100.0)

        api.patch(KIND_CONFIGMAP, "lease", "nos-tpu-system", mutate=steal)
        assert died.wait(3.0), "demotion callback never fired"
        t.join(2.0)
        assert not t.is_alive()
        assert not a.is_leader.is_set()
        stop.set()


class TestMainGating:
    def test_only_leader_ticks_and_failover_promotes_standby(self):
        from nos_tpu.cmd._runtime import Main

        api = APIServer()
        counts = {"a": 0, "b": 0}

        def build(name: str) -> Main:
            m = Main(f"m-{name}", api=api)
            m.attach_leader_election(LeaderElector(
                api, "cm-lease", identity=name,
                lease_duration_s=0.6, renew_s=0.1, retry_s=0.05))

            def tick(name=name):
                counts[name] += 1

            m.add_loop("tick", tick, 0.02)
            return m

        ma, mb = build("a"), build("b")
        ma.start()
        time.sleep(0.4)
        mb.start()
        time.sleep(0.4)
        assert counts["a"] > 0
        b_before = counts["b"]
        assert b_before == 0, "standby ticked while not leading"
        ma.shutdown()        # releases the lease
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline and counts["b"] == 0:
            time.sleep(0.05)
        assert counts["b"] > 0, "standby never promoted after failover"
        mb.shutdown()
