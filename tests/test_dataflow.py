"""Dataflow engine + N007–N010 rule acceptance (docs/static-analysis.md).

Three layers, mirroring the engine's structure:

- **CFG/def-use facts** on synthetic functions: branches, loops with
  break/continue, try/except/finally (including return-through-finally
  inlining), with-blocks, match, and nested closures — asserting the
  reaching-definition and inevitability verdicts the rules stand on;
- **escape facts**: each way a tainted value can outlive the frame,
  and the local-use shapes that must NOT count;
- **rule fixtures**: one firing and one silent fixture per rule
  N007–N010 (plus the pragma path), pinned via ``lint_source`` exactly
  like the N001–N006 suites in test_analysis.py.

Plus the ``@guarded_by`` runtime carrier and the lockcheck integration
(guard_state reading the annotation table).
"""

from __future__ import annotations

import ast
import threading

import pytest

from nos_tpu.analysis import lint_source
from nos_tpu.analysis.dataflow import (
    FunctionFlow, SymbolIndex, build_cfg, escapes, iter_functions,
    module_name_of, unit_defs, unit_uses,
)
from nos_tpu.analysis.rules_flow import (
    CacheInvalidation, CowEscape, GuardedByDiscipline, LeafLockContract,
)
from nos_tpu.testing.lockcheck import LockGraph, guard_state, unguard_all
from nos_tpu.utils.guards import guarded_by, guarded_fields

pytestmark = pytest.mark.analysis


def fn_of(src: str, name: str = None) -> ast.FunctionDef:
    tree = ast.parse(src)
    fns = [f for f in iter_functions(tree)
           if name is None or f.name == name]
    return fns[0]


def stmt_at(flow: FunctionFlow, line: int) -> ast.AST:
    for unit in flow.cfg.units():
        if getattr(unit, "lineno", None) == line:
            return unit
    raise AssertionError(f"no unit at line {line}")


def rules_of(v):
    return [x.rule for x in v]


# ---------------------------------------------------------------------------
# CFG + reaching definitions
# ---------------------------------------------------------------------------

class TestDefUse:
    def test_straightline_reaching_def(self):
        src = (
            "def f(a):\n"
            "    x = a\n"        # line 2
            "    y = x\n"        # line 3
            "    return y\n"     # line 4
        )
        flow = FunctionFlow(fn_of(src))
        use = stmt_at(flow, 3)
        defs = flow.defs_of(use, "x")
        assert len(defs) == 1
        # the argument def of `a` reaches line 2
        assert flow.defs_of(stmt_at(flow, 2), "a")

    def test_branch_merges_both_defs(self):
        src = (
            "def f(c):\n"
            "    if c:\n"
            "        x = 1\n"    # line 3
            "    else:\n"
            "        x = 2\n"    # line 5
            "    return x\n"     # line 6
        )
        flow = FunctionFlow(fn_of(src))
        ret = stmt_at(flow, 6)
        assert len(flow.defs_of(ret, "x")) == 2

    def test_branch_kill_is_per_path(self):
        src = (
            "def f(c):\n"
            "    x = 0\n"        # line 2
            "    if c:\n"
            "        x = 1\n"    # line 4: kills line 2 on this path only
            "    return x\n"     # line 5
        )
        flow = FunctionFlow(fn_of(src))
        assert len(flow.defs_of(stmt_at(flow, 5), "x")) == 2

    def test_loop_back_edge_carries_defs(self):
        src = (
            "def f(items):\n"
            "    acc = 0\n"
            "    for i in items:\n"   # line 3: defines i
            "        acc = acc + i\n"  # line 4: sees line-2 AND line-4 defs
            "    return acc\n"
        )
        flow = FunctionFlow(fn_of(src))
        body = stmt_at(flow, 4)
        assert len(flow.defs_of(body, "acc")) == 2
        assert flow.defs_of(body, "i")

    def test_while_break_continue_edges(self):
        src = (
            "def f(c):\n"
            "    x = 0\n"
            "    while c:\n"
            "        if x:\n"
            "            break\n"
            "        x = 1\n"
            "        continue\n"
            "    return x\n"          # line 8
        )
        flow = FunctionFlow(fn_of(src))
        # both the pre-loop and in-loop defs reach the return (break
        # after x=1? no — break precedes it; the back edge carries it)
        assert len(flow.defs_of(stmt_at(flow, 8), "x")) == 2

    def test_with_block_is_straightline(self):
        src = (
            "def f(self):\n"
            "    with self._lock:\n"
            "        x = 1\n"
            "    return x\n"          # line 4
        )
        flow = FunctionFlow(fn_of(src))
        assert len(flow.defs_of(stmt_at(flow, 4), "x")) == 1

    def test_except_handler_sees_pre_raise_defs(self):
        src = (
            "def f():\n"
            "    x = 1\n"
            "    try:\n"
            "        x = 2\n"
            "        g()\n"
            "    except ValueError as e:\n"
            "        y = x\n"          # line 7: both defs may reach
            "    return x\n"
        )
        flow = FunctionFlow(fn_of(src))
        assert len(flow.defs_of(stmt_at(flow, 7), "x")) == 2
        assert flow.defs_of(stmt_at(flow, 7), "e")

    def test_unit_defs_and_uses_primitives(self):
        tree = ast.parse("a, b = q\nc += a\n")
        assign, aug = tree.body
        assert unit_defs(assign) == {"a", "b"}
        assert unit_uses(assign) == {"q"}
        assert unit_defs(aug) == {"c"}
        assert "a" in unit_uses(aug)

    def test_nested_def_binds_only_its_name(self):
        src = (
            "def f(p):\n"
            "    p = get()\n"          # line 2
            "    def mutate(p):\n"     # line 3: binds `mutate`, NOT p
            "        return p\n"
            "    return p\n"           # line 5: still sees line-2 def
        )
        flow = FunctionFlow(fn_of(src, "f"))
        ret = stmt_at(flow, 5)
        assert flow.defs_of(ret, "p") == {
            id(stmt_at(flow, 2))}
        assert flow.defs_of(ret, "mutate")


# ---------------------------------------------------------------------------
# Inevitability (the N008 post-domination read)
# ---------------------------------------------------------------------------

def _is_bump(unit: ast.AST) -> bool:
    return any(isinstance(s, ast.Call)
               and isinstance(s.func, ast.Attribute)
               and s.func.attr == "bump"
               for s in ast.walk(unit)
               if not isinstance(unit, (ast.If, ast.While, ast.For))
               or s in ast.walk(unit.test if hasattr(unit, "test")
                                else unit))


class TestInevitability:
    def check(self, src, line, expect):
        flow = FunctionFlow(fn_of(src))

        def pred(u):
            return isinstance(u, ast.Expr) \
                and isinstance(u.value, ast.Call) \
                and isinstance(u.value.func, ast.Attribute) \
                and u.value.func.attr == "bump"

        assert flow.always_reaches_after(stmt_at(flow, line), pred) \
            is expect

    def test_same_block_later_bump(self):
        self.check("def f(s):\n    s.write()\n    s.bump()\n", 2, True)

    def test_branch_skips_bump(self):
        self.check(
            "def f(s, c):\n"
            "    s.write()\n"          # line 2
            "    if c:\n"
            "        s.bump()\n",
            2, False)

    def test_both_branches_bump(self):
        self.check(
            "def f(s, c):\n"
            "    s.write()\n"
            "    if c:\n"
            "        s.bump()\n"
            "    else:\n"
            "        s.bump()\n",
            2, True)

    def test_finally_always_bumps_even_past_return(self):
        self.check(
            "def f(s, c):\n"
            "    try:\n"
            "        s.write()\n"      # line 3
            "        if c:\n"
            "            return 1\n"
            "    finally:\n"
            "        s.bump()\n",
            3, True)

    def test_loop_zero_iterations_skips_bump(self):
        self.check(
            "def f(s, items):\n"
            "    s.write()\n"          # line 2
            "    for i in items:\n"
            "        s.bump()\n",
            2, False)

    def test_bump_after_loop_is_inevitable(self):
        self.check(
            "def f(s, items):\n"
            "    s.write()\n"
            "    for i in items:\n"
            "        pass\n"
            "    s.bump()\n",
            2, True)

    def test_early_return_before_bump(self):
        self.check(
            "def f(s, c):\n"
            "    s.write()\n"          # line 2
            "    if c:\n"
            "        return None\n"    # escapes without bumping
            "    s.bump()\n",
            2, False)


# ---------------------------------------------------------------------------
# Escape facts
# ---------------------------------------------------------------------------

def _src_fork(call: ast.Call) -> bool:
    return isinstance(call.func, ast.Attribute) \
        and call.func.attr in ("fork", "get_node_for_write")


class TestEscapes:
    def kinds(self, src):
        return sorted(e.kind for e in escapes(fn_of(src), _src_fork))

    def test_local_use_does_not_escape(self):
        assert self.kinds(
            "def f(snap):\n"
            "    n = snap.get_node_for_write('x')\n"
            "    changed = n.update()\n"
            "    return changed\n") == []

    def test_stored_on_self_escapes(self):
        assert self.kinds(
            "def f(self, snap):\n"
            "    n = snap.get_node_for_write('x')\n"
            "    self._n = n\n") == ["stored-on-self"]

    def test_copy_chain_then_return_escapes(self):
        assert self.kinds(
            "def f(snap):\n"
            "    n = snap.get_node_for_write('x')\n"
            "    alias = n\n"
            "    return alias\n") == ["returned"]

    def test_yield_escapes(self):
        assert self.kinds(
            "def f(snap):\n"
            "    n = snap.get_node_for_write('x')\n"
            "    yield n\n") == ["yielded"]

    def test_append_to_self_container_escapes(self):
        assert self.kinds(
            "def f(self, snap):\n"
            "    n = snap.get_node_for_write('x')\n"
            "    self._all.append(n)\n") == ["stored-on-self"]

    def test_append_to_local_container_is_fine(self):
        assert self.kinds(
            "def f(snap):\n"
            "    n = snap.get_node_for_write('x')\n"
            "    out = []\n"
            "    out.append(n)\n"
            "    n.mutate()\n") == []

    def test_escaping_closure_capture_convicted(self):
        assert self.kinds(
            "def f(self, snap):\n"
            "    n = snap.get_node_for_write('x')\n"
            "    def later():\n"
            "        return n.free()\n"
            "    self._cb = later\n") == ["closure"]

    def test_returned_closure_capture_convicted(self):
        assert self.kinds(
            "def f(snap):\n"
            "    n = snap.get_node_for_write('x')\n"
            "    def later():\n"
            "        return n.free()\n"
            "    return later\n") == ["closure"]

    def test_lambda_appended_to_self_container_escapes(self):
        assert self.kinds(
            "def f(self, snap, p):\n"
            "    node = snap.get_node_for_write('x')\n"
            "    self._callbacks.append(lambda: node.add_pod(p))\n"
        ) == ["closure"]

    def test_yielded_closures_escape(self):
        assert self.kinds(
            "def f(self, snap, names):\n"
            "    for n in names:\n"
            "        node = snap.get_node_for_write(n)\n"
            "        def handler():\n"
            "            node.add_pod(None)\n"
            "        yield handler\n") == ["closure"]
        assert self.kinds(
            "def f(snap):\n"
            "    node = snap.get_node_for_write('x')\n"
            "    yield lambda: node.free()\n") == ["closure"]

    def test_named_closure_appended_to_self_container_escapes(self):
        assert self.kinds(
            "def f(self, snap):\n"
            "    node = snap.get_node_for_write('x')\n"
            "    def cb():\n"
            "        return node\n"
            "    self._cbs.append(cb)\n") == ["closure"]

    def test_local_lambda_is_fine(self):
        assert self.kinds(
            "def f(snap, xs):\n"
            "    n = snap.get_node_for_write('x')\n"
            "    return sorted(xs, key=lambda t: n.rank(t))[0]\n") == []

    def test_container_indirection_return_escapes(self):
        """`out[n] = node; return out` carries every element past the
        fork scope — the container becomes a carrier."""
        assert self.kinds(
            "def f(snap, names):\n"
            "    out = {}\n"
            "    for n in names:\n"
            "        node = snap.get_node_for_write(n)\n"
            "        out[n] = node\n"
            "    return out\n") == ["returned"]
        assert self.kinds(
            "def f(self, snap):\n"
            "    acc = []\n"
            "    acc.append(snap.get_node_for_write('x'))\n"
            "    self._acc = acc\n") == ["stored-on-self"]

    def test_local_container_that_stays_local_is_fine(self):
        assert self.kinds(
            "def f(snap, names):\n"
            "    out = {}\n"
            "    for n in names:\n"
            "        out[n] = snap.get_node_for_write(n)\n"
            "    count = 0\n"
            "    return count\n") == []

    def test_augassign_store_on_self_escapes(self):
        """`self._dirty += [n]` / `self._seen |= {n}` store the alias
        exactly like the plain-assign and .append forms."""
        assert self.kinds(
            "def f(self, snap):\n"
            "    n = snap.get_node_for_write('x')\n"
            "    self._dirty += [n]\n") == ["stored-on-self"]
        assert self.kinds(
            "def f(self, snap):\n"
            "    self._seen |= {snap.get_node_for_write('x')}\n"
        ) == ["stored-on-self"]

    def test_augassign_to_local_is_fine(self):
        assert self.kinds(
            "def f(snap):\n"
            "    out = []\n"
            "    out += [snap.get_node_for_write('x')]\n"
            "    out[0].mutate()\n") == []

    def test_rebound_name_clears_taint(self):
        assert self.kinds(
            "def f(snap):\n"
            "    n = snap.get_node_for_write('x')\n"
            "    n.mutate()\n"
            "    n = 'clean'\n"
            "    return n\n") == []


# ---------------------------------------------------------------------------
# Symbol index
# ---------------------------------------------------------------------------

class TestSymbolIndex:
    def test_module_name_of(self):
        assert module_name_of("nos_tpu/obs/journal.py") == \
            "nos_tpu.obs.journal"
        assert module_name_of("nos_tpu/obs/__init__.py") == "nos_tpu.obs"

    def test_resolution_self_base_alias_singleton(self):
        idx = SymbolIndex()
        idx.add_module("pkg/base.py", ast.parse(
            "class Base:\n"
            "    def helper(self):\n"
            "        pass\n"))
        idx.add_module("pkg/mod.py", ast.parse(
            "from pkg.base import Base\n"
            "import pkg.util as U\n"
            "class C(Base):\n"
            "    def m(self):\n"
            "        self.helper()\n"
            "        U.work()\n"
            "        REG.inc()\n"
            "class Reg:\n"
            "    def inc(self):\n"
            "        pass\n"
            "REG = Reg()\n"))
        idx.add_module("pkg/util.py", ast.parse("def work():\n    pass\n"))
        resolved = {r for _, r in idx.callees(("pkg.mod", "C.m"))}
        assert ("pkg.base", "Base.helper") in resolved   # via base class
        assert ("pkg.util", "work") in resolved          # module alias
        assert ("pkg.mod", "Reg.inc") in resolved        # singleton


# ---------------------------------------------------------------------------
# Rule fixtures: N007–N010
# ---------------------------------------------------------------------------

class TestN007:
    def test_fires_on_stored_returned_yielded(self):
        src = (
            "class P:\n"
            "    def plan(self, snapshot):\n"
            "        snapshot.fork()\n"
            "        node = snapshot.get_node_for_write('n')\n"
            "        self._last = node\n"            # stored
            "        snapshot.commit()\n"
            "    def gen(self, snapshot):\n"
            "        n = snapshot.get_node_for_write('x')\n"
            "        yield n\n"                      # yielded
            "    def ret(self, snapshot):\n"
            "        n = snapshot.get_node_for_write('x')\n"
            "        alias = n\n"
            "        return alias\n"                 # returned via copy
        )
        assert rules_of(lint_source(src, [CowEscape()])) == ["N007"] * 3

    def test_silent_on_fork_scoped_use(self):
        src = (
            "def plan(snapshot, pods):\n"
            "    snapshot.fork()\n"
            "    node = snapshot.get_node_for_write('n')\n"
            "    changed = node.update_geometry_for({})\n"
            "    if changed:\n"
            "        snapshot.commit()\n"
            "    else:\n"
            "        snapshot.revert()\n"
            "    return changed\n"
        )
        assert lint_source(src, [CowEscape()]) == []

    def test_snapshot_substrate_exempt(self):
        src = (
            "class ClusterSnapshot:\n"
            "    def get_node_for_write(self, name):\n"
            "        n = self._writable(name)\n"
            "        return n\n"
        )
        assert lint_source(
            src, [CowEscape()],
            relpath="nos_tpu/partitioning/core/snapshot.py") == []

    def test_pragma_suppressed(self):
        src = (
            "def f(snap):\n"
            "    n = snap.get_node_for_write('x')\n"
            "    # noslint: N007 — handed to the caller which owns the fork\n"
            "    return n\n"
        )
        assert lint_source(src, [CowEscape()]) == []

    def test_fires_on_direct_store_without_intermediate_name(self):
        """The headline hazard needs no intermediate name: the source
        call can sit directly in the escaping position."""
        src = (
            "class P:\n"
            "    def direct_store(self, snap, name):\n"
            "        self._last = snap.get_node_for_write(name)\n"
            "    def direct_return(self, snap):\n"
            "        return snap.fork()\n"
            "    def direct_yield(self, snap):\n"
            "        yield snap.get_node_for_write('x')\n"
            "    def annotated(self, snap):\n"
            "        node: Node = snap.get_node_for_write('x')\n"
            "        return node\n"
            "    def tuple_elem(self, snap, x):\n"
            "        self._n, other = snap.fork(), x\n"
        )
        v = lint_source(src, [CowEscape()])
        assert rules_of(v) == ["N007"] * 5
        # ...and consuming the result inside the frame stays silent
        src_ok = (
            "def f(snap):\n"
            "    count = len(snap.fork().nodes())\n"
            "    return count\n"
        )
        assert lint_source(src_ok, [CowEscape()]) == []

    def test_fires_on_module_global_store(self):
        src = (
            "_LAST = None\n"
            "def f(snap):\n"
            "    global _LAST\n"
            "    n = snap.get_node_for_write('x')\n"
            "    _LAST = n\n"                         # module-global escape
        )
        v = lint_source(src, [CowEscape()])
        assert rules_of(v) == ["N007"]
        assert "_LAST" in v[0].message
        # a plain local rebinding of the same shape stays silent
        src_local = (
            "def f(snap):\n"
            "    n = snap.get_node_for_write('x')\n"
            "    last = n\n"
            "    last.add_pod('p')\n"
        )
        assert lint_source(src_local, [CowEscape()]) == []


class TestN008:
    REL = "nos_tpu/scheduler/foo.py"

    def test_fires_on_branch_skipping_bump(self):
        src = (
            "class S:\n"
            "    def handle(self, name):\n"
            "        node = self._api.get('Node', name)\n"
            "        node.status.phase = 'Running'\n"
            "        if name:\n"
            "            self._bump_locked(name)\n"
        )
        v = lint_source(src, [CacheInvalidation()], relpath=self.REL)
        assert rules_of(v) == ["N008"]
        assert "status.phase" in v[0].message

    def test_silent_when_bump_post_dominates(self):
        src = (
            "class S:\n"
            "    def handle(self, name):\n"
            "        node = self._api.get('Node', name)\n"
            "        node.metadata.annotations['k'] = 'v'\n"
            "        self._bump_locked(name)\n"
            "    def loop(self):\n"
            "        for node in self._api.list('Node'):\n"
            "            node.metadata.labels['k'] = 'v'\n"
            "            self._api.update('Node', node)\n"
        )
        assert lint_source(src, [CacheInvalidation()],
                           relpath=self.REL) == []

    def test_silent_on_copies_and_mutate_callbacks(self):
        src = (
            "class S:\n"
            "    def copy(self, name):\n"
            "        node = clone(self._api.get('Node', name))\n"
            "        node.status.phase = 'Running'\n"
            "    def cb(self, name):\n"
            "        def mutate(p):\n"
            "            p.spec.node_name = name\n"
            "        retry_on_conflict(self._api, 'Pod', name, mutate)\n"
        )
        assert lint_source(src, [CacheInvalidation()],
                           relpath=self.REL) == []

    def test_out_of_scope_path_unflagged(self):
        src = (
            "def f(api):\n"
            "    p = api.get('Pod', 'x')\n"
            "    p.status.phase = 'Running'\n"
        )
        assert lint_source(src, [CacheInvalidation()],
                           relpath="nos_tpu/models/foo.py") == []

    def test_dict_mutator_does_not_self_invalidate(self):
        """`labels.update(...)` shares its NAME with the api-verb
        invalidator `api.update` — the write itself must not count as
        its own invalidation (same for pop/clear/setdefault)."""
        src = (
            "class S:\n"
            "    def bad(self, name):\n"
            "        pod = self._api.get('Pod', name)\n"
            "        pod.metadata.labels.update({'k': 'v'})\n"
            "    def ok(self, name):\n"
            "        pod = self._api.get('Pod', name)\n"
            "        pod.metadata.labels.update({'k': 'v'})\n"
            "        self._api.update('Pod', pod)\n"   # real write-back
        )
        v = lint_source(src, [CacheInvalidation()], relpath=self.REL)
        assert rules_of(v) == ["N008"]
        assert v[0].line == 4

    def test_other_units_dict_mutator_is_not_an_invalidation(self):
        """A SECOND watched-dict write must not silence the first: the
        api-verb invalidators require an api receiver."""
        src = (
            "class S:\n"
            "    def bad(self, name):\n"
            "        pod = self._api.get('Pod', name)\n"
            "        pod.status.phase = 'Failed'\n"
            "        pod.metadata.labels.update({'k': 'v'})\n"
        )
        v = lint_source(src, [CacheInvalidation()], relpath=self.REL)
        assert [x.line for x in v] == [4, 5]
        assert rules_of(v) == ["N008", "N008"]

    def test_whole_dict_replacement_fires(self):
        """`pod.metadata.labels = {...}` is the most drastic watched-dict
        write — it must convict like the per-key form."""
        src = (
            "class S:\n"
            "    def bad(self, name):\n"
            "        pod = self._api.get('Pod', name)\n"
            "        pod.metadata.labels = {'a': 'b'}\n"
            "    def bad_aug(self, name):\n"
            "        pod = self._api.get('Pod', name)\n"
            "        pod.metadata.labels |= {'a': 'b'}\n"
            "    def ok(self, name):\n"
            "        pod = self._api.get('Pod', name)\n"
            "        pod.metadata.labels = {'a': 'b'}\n"
            "        self._api.patch('Pod', name, pod)\n"
        )
        v = lint_source(src, [CacheInvalidation()], relpath=self.REL)
        assert rules_of(v) == ["N008", "N008"]
        assert [x.line for x in v] == [4, 7]

    def test_header_lambda_body_not_walked_for_calls(self):
        """A lambda inside a compound-statement HEADER is deferred
        execution: its body must neither convict N010 nor count as an
        N008 invalidation."""
        src = (
            "import threading\n"
            "from nos_tpu.utils.guards import guarded_by\n"
            "@guarded_by('_lock', '_items')\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._items = []\n"
            "    def ok(self):\n"
            "        if self.check(lambda: self._items.append(1)):\n"
            "            pass\n"
        )
        assert lint_source(src, [GuardedByDiscipline()]) == []

    def test_annotated_assign_from_api_is_live(self):
        """mypy strict pushes scheduler code toward `pod: Pod =
        api.get(...)` — the annotation must not launder liveness."""
        src = (
            "class S:\n"
            "    def bad(self, name):\n"
            "        pod: object = self._api.get('Pod', name)\n"
            "        pod.status.phase = 'Failed'\n"
            "    def bad_tuple(self, name):\n"
            "        pods, n = self._api.list('Pod'), 0\n"
            "        pods[0].status.phase = 'Failed'\n"
        )
        v = lint_source(src, [CacheInvalidation()], relpath=self.REL)
        assert rules_of(v) == ["N008", "N008"]
        assert [x.line for x in v] == [4, 7]

    def test_subscript_element_of_live_list_is_live(self):
        """`pods[0]` is the same object the cache watches — indexing
        instead of iterating must not launder liveness."""
        src = (
            "class S:\n"
            "    def bad(self, name):\n"
            "        pods = self._api.list('Pod')\n"
            "        pod = pods[0]\n"
            "        pod.status.phase = 'Failed'\n"
            "    def ok(self, name):\n"
            "        pods = self._api.list('Pod')\n"
            "        pod = pods[0]\n"
            "        pod.status.phase = 'Failed'\n"
            "        self._api.patch('Pod', name, pod)\n"
        )
        v = lint_source(src, [CacheInvalidation()], relpath=self.REL)
        assert rules_of(v) == ["N008"]
        assert v[0].line == 5

    def test_finally_write_escaping_on_early_return_path_fires_once(self):
        """The finally body runs on BOTH the normal path (bump follows)
        and the early-return path (nothing follows).  Each inlined copy
        gets its own identity, so inevitability judges the return path
        separately — and identical findings from multiple copies
        collapse to one."""
        src = (
            "class S:\n"
            "    def bad(self, name, flag):\n"
            "        pod = self._api.get('Pod', name)\n"
            "        try:\n"
            "            if flag:\n"
            "                return None\n"
            "            self._work()\n"
            "        finally:\n"
            "            pod.status.phase = 'Failed'\n"
            "        self._gen[name] = 1\n"
            "    def ok(self, name):\n"
            "        pod = self._api.get('Pod', name)\n"
            "        try:\n"
            "            self._work()\n"
            "        finally:\n"
            "            pod.status.phase = 'Failed'\n"
            "            self._gen[name] = 1\n"
        )
        v = lint_source(src, [CacheInvalidation()], relpath=self.REL)
        assert rules_of(v) == ["N008"]
        assert v[0].line == 9

    def test_del_watched_dict_entry_fires_and_writeback_silences(self):
        """`del pod.metadata.annotations[k]` is the same stale-cache
        hazard as `.pop(k)` — the Delete statement form must convict."""
        src = (
            "class S:\n"
            "    def bad(self, name):\n"
            "        pod = self._api.get('Pod', name)\n"
            "        del pod.metadata.annotations['k']\n"
            "    def ok(self, name):\n"
            "        pod = self._api.get('Pod', name)\n"
            "        del pod.metadata.annotations['k']\n"
            "        self._api.patch('Pod', name, pod)\n"
        )
        v = lint_source(src, [CacheInvalidation()], relpath=self.REL)
        assert rules_of(v) == ["N008"]
        assert v[0].line == 4

    def test_gen_substring_lookalikes_are_not_bumps(self):
        src = (
            "class S:\n"
            "    def bad(self, name):\n"
            "        node = self._api.get('Node', name)\n"
            "        node.status.phase = 'Ready'\n"
            "        self.agenda[name] = 1\n"          # not a gen bump
            "    def ok(self, name):\n"
            "        node = self._api.get('Node', name)\n"
            "        node.status.phase = 'Ready'\n"
            "        self._gen[name] = 1\n"            # a real one
        )
        v = lint_source(src, [CacheInvalidation()], relpath=self.REL)
        assert rules_of(v) == ["N008"]
        assert v[0].line == 4


class TestN009:
    REL = "nos_tpu/obs/journal.py"

    def _lint(self, src):
        return lint_source(src, [LeafLockContract()], relpath=self.REL)

    def test_fires_on_api_reach_and_reentry(self):
        src = (
            "class DecisionJournal:\n"
            "    def record(self, category):\n"
            "        self._api.patch('Pod', 'p', mutate=None)\n"
            "        self._other.record(category)\n"
        )
        v = self._lint(src)
        assert rules_of(v) == ["N009", "N009"]

    def test_fires_transitively_through_helper(self):
        src = (
            "class DecisionJournal:\n"
            "    def record(self, category):\n"
            "        self._flush()\n"
            "    def _flush(self):\n"
            "        import threading\n"
            "        threading.Event().wait()\n"
        )
        v = self._lint(src)
        assert rules_of(v) == ["N009"]
        assert "reached via" in v[0].message

    def test_fires_on_nontrivial_call_under_lock(self):
        src = (
            "class DecisionJournal:\n"
            "    def record(self, category):\n"
            "        with self._lock:\n"
            "            self._seq += 1\n"
            "            self._rebuild_index()\n"
        )
        v = self._lint(src)
        assert rules_of(v) == ["N009"]
        assert "under" in v[0].message

    def test_silent_on_the_leaf_shape(self):
        src = (
            "class DecisionJournal:\n"
            "    def record(self, category):\n"
            "        rec = object()\n"
            "        with self._lock:\n"
            "            self._seq += 1\n"
            "            evicted = self._push_locked(rec)\n"
            "        REGISTRY.inc('nos_tpu_journal_records_total')\n"
            "        return rec\n"
        )
        assert self._lint(src) == []

    def test_renamed_root_is_itself_a_violation(self):
        """If record() is renamed/moved, the certification must not
        silently check nothing — the unresolved root is the finding."""
        src = (
            "class DecisionJournal:\n"
            "    def record_decision(self, category):\n"   # renamed
            "        pass\n"
        )
        v = self._lint(src)
        assert rules_of(v) == ["N009"]
        assert "no longer resolves" in v[0].message
        assert "DecisionJournal.record" in v[0].message

    def test_real_tree_roots_resolve(self):
        """The rule is inert if its roots vanish in a refactor — pin
        that the real modules still define them."""
        import os

        from nos_tpu.analysis.core import run as nrun
        rule = LeafLockContract()
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        nrun([rule], [os.path.join(root, "nos_tpu", "obs")], root=root)
        assert all(k in rule.index.functions for k in rule.ROOTS)


class TestN010:
    def test_fires_on_unlocked_writes_and_unlocked_locked_call(self):
        src = (
            "import threading\n"
            "from nos_tpu.utils.guards import guarded_by\n"
            "@guarded_by('_lock', '_items', '_gen')\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._items = []\n"
            "        self._gen = {}\n"
            "    def bad(self):\n"
            "        self._items.append(1)\n"       # unlocked mutator
            "        self._gen['a'] = 2\n"          # unlocked subscript
            "    def caller(self):\n"
            "        self._touch_locked()\n"        # lock not held
            "    def _touch_locked(self):\n"
            "        self._gen['a'] = 3\n"          # exempt (_locked)
        )
        v = lint_source(src, [GuardedByDiscipline()])
        assert rules_of(v) == ["N010"] * 3

    def test_silent_on_locked_writes_and_init(self):
        src = (
            "import threading\n"
            "from nos_tpu.utils.guards import guarded_by\n"
            "@guarded_by('_lock', '_items', '_gen')\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._items = []\n"
            "        self._gen = {}\n"              # init: pre-publication
            "    def ok(self):\n"
            "        with self._lock:\n"
            "            self._items.append(1)\n"
            "            self._gen['a'] = 2\n"
            "            self._touch_locked()\n"
            "        return len(self._items)\n"     # reads stay free
            "    def _touch_locked(self):\n"
            "        del self._gen['a']\n"
        )
        assert lint_source(src, [GuardedByDiscipline()]) == []

    def test_missing_lock_and_nonliteral_args_flagged(self):
        src = (
            "from nos_tpu.utils.guards import guarded_by\n"
            "@guarded_by('_lock', '_x')\n"
            "class NoLock:\n"
            "    def __init__(self):\n"
            "        self._x = 1\n"
            "\n"
            "NAME = '_y'\n"
            "@guarded_by('_lock', NAME)\n"
            "class Computed:\n"
            "    pass\n"
        )
        v = lint_source(src, [GuardedByDiscipline()])
        msgs = " | ".join(x.message for x in v)
        assert "never creates it" in msgs
        assert "string literals" in msgs

    def test_try_wrapped_locked_write_not_convicted(self):
        """The common `try: with self._lock: ...` idiom must stay
        clean, and an unlocked write inside a try body is reported
        exactly once (not re-walked at the Try statement level)."""
        src = (
            "import threading\n"
            "from nos_tpu.utils.guards import guarded_by\n"
            "@guarded_by('_lock', '_items')\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._items = []\n"
            "    def ok(self, x):\n"
            "        try:\n"
            "            with self._lock:\n"
            "                self._items.append(x)\n"
            "                self._push_locked(x)\n"
            "        except ValueError:\n"
            "            raise\n"
            "    def bad(self, x):\n"
            "        try:\n"
            "            self._items.append(x)\n"    # unlocked, once
            "        except ValueError:\n"
            "            raise\n"
        )
        v = lint_source(src, [GuardedByDiscipline()])
        assert rules_of(v) == ["N010"]
        assert v[0].line == 17

    def test_tuple_destructuring_write_flagged(self):
        src = (
            "import threading\n"
            "from nos_tpu.utils.guards import guarded_by\n"
            "@guarded_by('_lock', '_a', '_b')\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._a, self._b = 0, 0\n"
            "    def bad(self, x):\n"
            "        self._a, self._b = x, x\n"       # both unlocked
            "    def ok(self, x):\n"
            "        with self._lock:\n"
            "            self._a, self._b = x, x\n"
        )
        v = lint_source(src, [GuardedByDiscipline()])
        assert rules_of(v) == ["N010"] * 2
        assert {x.line for x in v} == {9}

    def test_class_level_annotated_lock_counts_as_created(self):
        """`_lock: ClassVar[Lock] = Lock()` at class level IS a created
        lock; a bare annotation with no value is not."""
        src = (
            "import threading\n"
            "from typing import ClassVar\n"
            "from nos_tpu.utils.guards import guarded_by\n"
            "@guarded_by('_lock', '_n')\n"
            "class Annotated:\n"
            "    _lock: ClassVar[threading.Lock] = threading.Lock()\n"
            "    def __init__(self):\n"
            "        self._n = 0\n"
            "\n"
            "@guarded_by('_lock', '_n')\n"
            "class BareAnnotation:\n"
            "    _lock: threading.Lock\n"       # declared, never created
            "    def __init__(self):\n"
            "        self._n = 0\n"
        )
        v = lint_source(src, [GuardedByDiscipline()])
        assert rules_of(v) == ["N010"]
        assert "BareAnnotation" in v[0].message

    def test_zero_field_decorator_flagged(self):
        """@guarded_by('_lock') with no fields is a vacuous contract —
        the static half flags what guards.guarded_by raises on at
        import time, so a never-imported module can't carry one."""
        src = (
            "import threading\n"
            "from nos_tpu.utils.guards import guarded_by\n"
            "@guarded_by('_lock')\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
        )
        v = lint_source(src, [GuardedByDiscipline()])
        assert rules_of(v) == ["N010"]
        assert "no fields" in v[0].message

    def test_external_locked_caller_needs_receiver_lock(self):
        """`other._bump_locked()` from outside the owning class is the
        exact parallel-shard merge shape — it must hold a lock on that
        same receiver, or be a *_locked method itself."""
        src = (
            "class Merger:\n"
            "    def bad(self, k):\n"
            "        self._cache._bump_locked(k)\n"
            "    def ok(self, k):\n"
            "        with self._cache._lock:\n"
            "            self._cache._bump_locked(k)\n"
            "    def _merge_locked(self, k):\n"
            "        self._cache._bump_locked(k)\n"   # carries convention
            "\n"
            "def free_bad(cache, k):\n"
            "    cache._bump_locked(k)\n"
            "\n"
            "def free_ok(cache, k):\n"
            "    with cache._lock:\n"
            "        cache._bump_locked(k)\n"
        )
        v = lint_source(src, [GuardedByDiscipline()])
        assert rules_of(v) == ["N010"] * 2
        assert {x.line for x in v} == {3, 11}

    def test_subclass_with_base_skips_lock_existence(self):
        src = (
            "from nos_tpu.utils.guards import guarded_by\n"
            "from other import Base\n"
            "@guarded_by('_lock', '_seq')\n"
            "class Derived(Base):\n"
            "    def bump(self):\n"
            "        with self._lock:\n"
            "            self._seq += 1\n"
        )
        assert lint_source(src, [GuardedByDiscipline()]) == []


# ---------------------------------------------------------------------------
# @guarded_by runtime carrier + lockcheck integration
# ---------------------------------------------------------------------------

class TestGuardedByRuntime:
    def test_table_merges_and_inherits(self):
        @guarded_by("_lock", "_a", "_b")
        class Base:
            pass

        @guarded_by("_lock", "_c")
        class Child(Base):
            pass

        assert guarded_fields(Base) == {"_a": "_lock", "_b": "_lock"}
        assert guarded_fields(Child) == {
            "_a": "_lock", "_b": "_lock", "_c": "_lock"}
        # extending the child never mutated the base's table
        assert "_c" not in guarded_fields(Base)

    def test_conflicting_redeclaration_raises(self):
        with pytest.raises(ValueError, match="one lock per field"):
            @guarded_by("_other", "_a")
            @guarded_by("_lock", "_a")
            class Bad:
                pass

    def test_zero_fields_raises(self):
        """@guarded_by('_lock') with no fields would be a silent no-op
        contract — {} table, nothing checked by either half."""
        with pytest.raises(ValueError, match="fields it guards"):
            guarded_by("_lock")

    def test_guard_state_reads_annotations(self):
        @guarded_by("_lock", "_guarded")
        class Shared:
            def __init__(self):
                self._lock = threading.Lock()
                self._guarded = 0
                self._free = 0

        g = LockGraph(name="annot")
        s = Shared()
        try:
            guard_state(s, g)
            with s._lock:
                s._guarded = 1           # locked: fine
            s._free = 2                  # undeclared field: not judged
            g.assert_clean()
            s._guarded = 3               # unlocked declared write
            assert len(g.unguarded_writes) == 1
            assert "_guarded" in g.unguarded_writes[0]
        finally:
            g.close()
            unguard_all()

    def test_guard_state_legacy_mode_still_guards_everything(self):
        class Plain:
            def __init__(self):
                self._lock = threading.Lock()
                self.field = 0

        g = LockGraph(name="legacy")
        p = Plain()
        try:
            guard_state(p, g)
            p.field = 1                  # every field judged (PR 2 mode)
            assert len(g.unguarded_writes) == 1
        finally:
            g.close()
            unguard_all()

    def test_annotated_decision_plane_classes_carry_tables(self):
        from nos_tpu.obs.journal import DecisionJournal
        from nos_tpu.partitioning.core.quarantine import QuarantineList
        from nos_tpu.partitioning.state import ClusterState
        from nos_tpu.scheduler.cache import SchedulerCache

        for cls in (DecisionJournal, QuarantineList, ClusterState,
                    SchedulerCache):
            table = guarded_fields(cls)
            assert table, f"{cls.__name__} lost its @guarded_by table"
            assert set(table.values()) == {"_lock"}
        # the journal inherits the ring's fields and adds its own
        assert "_items" in guarded_fields(DecisionJournal)
        assert "_seq" in guarded_fields(DecisionJournal)


class TestCfgShapes:
    """The builder handles the syntax zoo without falling over."""

    @pytest.mark.parametrize("src", [
        "def f():\n    match x:\n        case 1:\n            a = 1\n"
        "        case _:\n            a = 2\n    return a\n",
        "def f():\n    while True:\n        if q():\n            break\n"
        "    return 1\n",
        "def f():\n    try:\n        a = 1\n    except (ValueError,"
        " KeyError) as e:\n        a = 2\n    except Exception:\n"
        "        raise\n    else:\n        a = 3\n    finally:\n"
        "        b = a\n    return b\n",
        "def f():\n    for i in range(3):\n        try:\n"
        "            continue\n        finally:\n            cleanup()\n",
        "def f():\n    with open('x') as fh, lock:\n        return fh\n",
    ])
    def test_builds_and_flows(self, src):
        fn = fn_of(src)
        cfg = build_cfg(fn)
        assert cfg.blocks[cfg.entry].units
        FunctionFlow(fn, cfg)      # fixpoint terminates
