"""Tests for nos_tpu/obs: spans, journal, explain, flight recorder —
plus the victim-prescreen superset contract (ADVICE round 5) and the
public-entry-point snapshot hygiene regression.
"""

from __future__ import annotations

import json
import threading

from nos_tpu import obs
from nos_tpu.obs import journal as J
from nos_tpu.obs.__main__ import main as obs_main, selftest
from nos_tpu.obs.journal import DecisionJournal
from nos_tpu.obs.trace import RingExporter, Tracer, current_span


class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self) -> float:
        self.now += 1.0
        return self.now


# ---------------------------------------------------------------------------
# Spans
# ---------------------------------------------------------------------------


class TestSpans:
    def test_nesting_links_parent_and_trace(self):
        t = Tracer(clock=FakeClock(), ring=RingExporter(maxlen=16))
        with t.span("outer", kind="slice") as outer:
            assert current_span() is outer
            with t.span("inner") as inner:
                assert inner.trace_id == outer.trace_id
                assert inner.parent_id == outer.span_id
                assert current_span() is inner
            assert current_span() is outer
        assert current_span() is None
        dumped = {s["name"]: s for s in t.ring.dump()}
        assert dumped["inner"]["end"] is not None
        # injected clock: inner opened after outer, closed before it
        assert dumped["inner"]["start"] > dumped["outer"]["start"]
        assert dumped["inner"]["end"] < dumped["outer"]["end"]
        assert dumped["outer"]["attrs"] == {"kind": "slice"}

    def test_propagation_through_calls_and_bumps(self):
        t = Tracer(clock=FakeClock(), ring=RingExporter(maxlen=16))
        prev = obs.set_tracer(t)
        try:
            def hot_path():
                obs.bump("filter_runs")
                obs.bump("filter_runs", 2)

            with obs.span("cycle") as sp:
                hot_path()
            assert sp.counts == {"filter_runs": 3}
        finally:
            obs.set_tracer(prev)

    def test_threads_do_not_inherit_ambient_span(self):
        t = Tracer(clock=FakeClock(), ring=RingExporter(maxlen=16))
        seen = {}

        def worker():
            with t.span("in-thread") as sp:
                seen["parent"] = sp.parent_id

        with t.span("outer"):
            th = threading.Thread(target=worker)
            th.start()
            th.join()
        assert seen["parent"] == ""    # fresh trace root per thread

    def test_exception_marks_status_and_still_exports(self):
        t = Tracer(clock=FakeClock(), ring=RingExporter(maxlen=16))
        try:
            with t.span("boom"):
                raise ValueError("x")
        except ValueError:
            pass
        (span,) = t.ring.dump()
        assert span["status"] == "error:ValueError"
        assert span["end"] is not None

    def test_ring_bounded_and_counts_drops(self):
        t = Tracer(clock=FakeClock(), ring=RingExporter(maxlen=3))
        for i in range(7):
            with t.span(f"s{i}"):
                pass
        assert len(t.ring) == 3
        assert t.ring.dropped == 4
        assert [s["name"] for s in t.ring.dump()] == ["s4", "s5", "s6"]

    def test_detail_span_bumps_by_default_and_opens_when_detailed(self):
        t = Tracer(clock=FakeClock(), ring=RingExporter(maxlen=8))
        with t.span("outer") as outer:
            with t.detail_span("framework.filter"):
                pass
        assert outer.counts == {"framework.filter": 1}
        assert len(t.ring) == 1    # no child span exported

        t2 = Tracer(clock=FakeClock(), ring=RingExporter(maxlen=8),
                    detailed=True)
        with t2.span("outer"):
            with t2.detail_span("framework.filter") as child:
                assert child is not None
        assert {s["name"] for s in t2.ring.dump()} == \
            {"outer", "framework.filter"}

    def test_framework_filter_materializes_span_in_detailed_mode(self):
        """The doc contract: Tracer(detailed=True) turns the hot
        per-pod x node Filter pipeline's counter bump into a real
        `framework.filter` child span carrying the rejecting plugin
        (review regression: the doc promised it, nothing emitted it)."""
        from nos_tpu.scheduler.framework import (
            CycleState, Framework, NodeInfo, Status)
        from nos_tpu.testing.factory import make_slice_pod, make_tpu_node

        class Rejector:
            name = "Rejector"

            def filter(self, state, pod, node_info):
                return Status.unschedulable("no room")

        fw = Framework([Rejector()])
        pod = make_slice_pod("2x2", 1, name="stuck")
        ni = NodeInfo(node=make_tpu_node("host-0"))

        # default tracer: no child span, one counter bump on the parent
        t = Tracer(clock=FakeClock(), ring=RingExporter(maxlen=8))
        with obs.scoped(t, DecisionJournal(maxlen=8, clock=FakeClock())):
            with t.span("outer") as outer:
                st = fw.run_filter_plugins(CycleState(), pod, ni)
        assert not st.is_success
        assert outer.counts.get("filter_runs") == 1
        assert [s["name"] for s in t.ring.dump()] == ["outer"]

        # detailed tracer: a real framework.filter span with provenance,
        # AND the filter_runs counter still lands on the enclosing span
        # (troubleshooting's reverts/filter_runs ratio must not vanish
        # in detailed captures — review regression)
        t2 = Tracer(clock=FakeClock(), ring=RingExporter(maxlen=8),
                    detailed=True)
        with obs.scoped(t2, DecisionJournal(maxlen=8, clock=FakeClock())):
            with t2.span("outer") as outer2:
                st = fw.run_filter_plugins(CycleState(), pod, ni)
        assert not st.is_success
        assert outer2.counts.get("filter_runs") == 1
        spans = {s["name"]: s for s in t2.ring.dump()}
        assert set(spans) == {"outer", "framework.filter"}
        child = spans["framework.filter"]
        assert child["attrs"]["plugin"] == "Rejector"
        assert child["attrs"]["reason"] == "no room"
        assert child["attrs"]["node"] == "host-0"

    def test_fresh_tracers_replay_byte_identical(self):
        """Span/trace ids are per-tracer: the same driven sequence on a
        fresh Tracer + injected clock yields a byte-identical recording
        — the chaos-seed replay contract (review regression: a module-
        global id counter made second runs diverge)."""
        def drive():
            t = Tracer(clock=FakeClock(), ring=RingExporter(maxlen=16))
            with t.span("cycle", pods=2):
                with t.span("inner"):
                    pass
            with t.span("cycle", pods=0):
                pass
            return t.ring.to_json()

        assert drive() == drive()

    def test_disabled_tracer_is_inert(self):
        t = Tracer(clock=FakeClock(), ring=RingExporter(maxlen=8),
                   enabled=False)
        with t.span("x") as sp:
            assert sp is None
            assert current_span() is None
        assert len(t.ring) == 0

    def test_span_latency_histogram_lands_in_registry(self):
        from nos_tpu.exporter.metrics import REGISTRY

        t = Tracer(clock=FakeClock(), ring=RingExporter(maxlen=8))
        with t.span("obs-test-histogram"):
            pass
        snap = REGISTRY.snapshot()
        assert snap["nos_tpu_span_seconds_count"][
            "span=obs-test-histogram"] >= 1


# ---------------------------------------------------------------------------
# Journal
# ---------------------------------------------------------------------------


class TestJournal:
    def test_bounded_ordering_and_drop_count(self):
        j = DecisionJournal(maxlen=5, clock=FakeClock())
        for i in range(12):
            j.record(J.POD_BOUND, f"ns/p{i}", node="h0")
        assert len(j) == 5
        assert j.dropped == 7
        seqs = [r.seq for r in j.events()]
        assert seqs == sorted(seqs) and len(set(seqs)) == 5
        assert seqs[-1] == 12    # seq is total appends, not ring position
        ts = [r.ts for r in j.events()]
        assert ts == sorted(ts)

    def test_records_capture_trace_context(self):
        clock = FakeClock()
        t = Tracer(clock=clock, ring=RingExporter(maxlen=8))
        j = DecisionJournal(maxlen=8, clock=clock)
        with obs.scoped(t, j):
            with obs.span("cycle") as sp:
                obs.record(J.POD_REJECTED, "ns/p", reason="r", message="m")
            obs.record(J.POD_BOUND, "ns/p", node="h0")
        inside, outside = j.events()
        assert inside.trace_id == sp.trace_id
        assert inside.span_id == sp.span_id
        assert outside.trace_id == ""

    def test_event_filtering(self):
        j = DecisionJournal(maxlen=16, clock=FakeClock())
        j.record(J.POD_BOUND, "ns/a", node="h0")
        j.record(J.POD_REJECTED, "ns/b", reason="", message="no fit")
        j.record(J.POD_BOUND, "ns/b", node="h1")
        assert [r.subject for r in j.events(category=J.POD_BOUND)] == \
            ["ns/a", "ns/b"]
        assert [r.category for r in j.events(subject="ns/b")] == \
            [J.POD_REJECTED, J.POD_BOUND]
        assert len(j.events(limit=2)) == 2

    def test_scoped_restores_globals(self):
        base_j, base_t = obs.get_journal(), obs.get_tracer()
        j = DecisionJournal(maxlen=4, clock=FakeClock())
        t = Tracer(clock=FakeClock(), ring=RingExporter(maxlen=4))
        with obs.scoped(t, j):
            assert obs.get_journal() is j
            assert obs.get_tracer() is t
        assert obs.get_journal() is base_j
        assert obs.get_tracer() is base_t


# ---------------------------------------------------------------------------
# Explain (unit: fabricated snapshots)
# ---------------------------------------------------------------------------


def _rec(seq, category, subject, **attrs):
    return {"seq": seq, "ts": float(seq), "category": category,
            "subject": subject, "attrs": attrs, "trace_id": "",
            "span_id": ""}


class TestExplainUnit:
    def test_rejection_chain_names_plugin_per_node(self):
        snap = {"spans": [], "journal": [_rec(
            1, J.POD_REJECTED, "ns/stuck", reason="", message="no fit",
            nodes={"host-0": "NodeResourcesFit: insufficient "
                             "nos.tpu/slice-2x2",
                   "host-1": "TopologyFilter: outside pinned domain"},
            reason_counts={"NodeResourcesFit: insufficient "
                           "nos.tpu/slice-2x2": 40})]}
        text = "\n".join(obs.explain_pod(snap, "ns/stuck"))
        assert "NodeResourcesFit" in text
        assert "host-0" in text and "host-1" in text
        # 40 counted for the NodeResourcesFit reason, 2 listed verbatim
        assert "38 more node(s)" in text

    def test_bound_pod_reports_bound(self):
        snap = {"spans": [], "journal": [
            _rec(1, J.POD_REJECTED, "ns/p", reason="", message="no fit"),
            _rec(2, J.POD_BOUND, "ns/p", node="host-3"),
        ]}
        text = "\n".join(obs.explain_pod(snap, "ns/p"))
        assert "BOUND to node host-3" in text

    def test_node_total_survives_capped_reason_counts(self):
        """Per-node messages embed per-node numbers, so reason_counts
        can hold one entry per node — the record caps them and carries
        the complete node total separately (review regression)."""
        snap = {"spans": [], "journal": [_rec(
            1, J.POD_REJECTED, "ns/stuck", reason="", message="no fit",
            nodes={"host-0": "NodeResourcesFit: 0+4 over 2"},
            reason_counts={"NodeResourcesFit: 0+4 over 2": 1,
                           "NodeResourcesFit: 1+4 over 2": 1},
            nodes_total=200)]}
        text = "\n".join(obs.explain_pod(snap, "ns/stuck"))
        assert "199 more node(s)" in text

    def test_gang_bound_pod_reports_bound(self):
        """Gang binds journal gang-admitted AFTER every member's
        pod-bound, so the bind must stay definitive even when it is not
        the newest record (review regression)."""
        snap = {"spans": [], "journal": [
            _rec(1, J.POD_BOUND, "ns/g-0", node="host-1"),
            _rec(2, J.GANG_ADMITTED, "ns/gang-1", message="gang admitted",
                 bound=2, members=["ns/g-0", "ns/g-1"]),
        ]}
        text = "\n".join(obs.explain_pod(snap, "ns/g-0"))
        assert "BOUND to node host-1" in text

    def test_rejection_after_bind_is_pending_again(self):
        """An evicted-and-requeued pod (rejected AFTER its bind) is
        pending — the old bind must not mask the fresh rejection."""
        snap = {"spans": [], "journal": [
            _rec(1, J.POD_BOUND, "ns/p", node="host-1"),
            _rec(2, J.POD_REJECTED, "ns/p", reason="", message="no fit",
                 nodes={"host-1": "NodeResourcesFit: insufficient"},
                 reason_counts={}),
        ]}
        text = "\n".join(obs.explain_pod(snap, "ns/p"))
        assert "BOUND" not in text
        assert "NodeResourcesFit" in text

    def test_quota_hol_and_gang_causes_surface(self):
        snap = {"spans": [], "journal": [
            _rec(1, J.QUOTA_HOL_CLAIM, "ns/big", namespace="ns",
                 priority=10),
            _rec(2, J.GANG_REJECTED, "ns/gang-1",
                 message="gang does not fit as a whole",
                 members=["ns/big", "ns/big-2"]),
        ]}
        text = "\n".join(obs.explain_pod(snap, "ns/big"))
        assert "head-of-line" in text
        assert "gang does not fit as a whole" in text

    def test_gang_member_beyond_member_cap_keeps_gang_context(self):
        """The gang record's member list is capped, so member 33+ is
        associated through its own rejection's `gang` attr; the member
        count shown is the complete members_total (review regression)."""
        snap = {"spans": [], "journal": [
            _rec(1, J.POD_REJECTED, "ns/g-39", reason="",
                 message="gang does not fit as a whole",
                 gang="ns/gang-1"),
            _rec(2, J.GANG_REJECTED, "ns/gang-1",
                 message="gang does not fit as a whole",
                 members=[f"ns/g-{i}" for i in range(32)],
                 members_total=40),
        ]}
        text = "\n".join(obs.explain_pod(snap, "ns/g-39"))
        assert "gang ns/gang-1" in text
        assert "members: 40" in text

    def test_stale_quota_hol_not_blamed_for_later_capacity_rejection(self):
        """Present-tense context must come from the LATEST scheduling
        attempt: a pod that was the quota head-of-line claimant cycles
        ago but is now rejected on pure capacity must not send the
        operator to debug quota (review regression)."""
        snap = {"spans": [], "journal": [
            _rec(1, J.QUOTA_HOL_CLAIM, "ns/p", namespace="ns", priority=10),
            _rec(2, J.POD_REJECTED, "ns/p", reason="quota",
                 message="no headroom"),
            _rec(3, J.POD_REJECTED, "ns/p", reason="", message="no fit",
                 nodes={"host-0": "NodeResourcesFit: insufficient"},
                 reason_counts={}),
        ]}
        text = "\n".join(obs.explain_pod(snap, "ns/p"))
        assert "head-of-line" not in text
        assert "NodeResourcesFit" in text

    def test_same_attempt_quota_hol_still_surfaces(self):
        """The claim journaled just before its own cycle's rejection is
        current context and must survive the recency bound."""
        snap = {"spans": [], "journal": [
            _rec(1, J.POD_REJECTED, "ns/p", reason="", message="no fit"),
            _rec(2, J.QUOTA_HOL_CLAIM, "ns/p", namespace="ns", priority=10),
            _rec(3, J.POD_REJECTED, "ns/p", reason="quota",
                 message="no headroom"),
        ]}
        text = "\n".join(obs.explain_pod(snap, "ns/p"))
        assert "head-of-line" in text

    def test_stale_preemption_not_reported_as_pending_retry(self):
        """'retry expected next cycle' from a preemption two attempts
        ago is a lie once a later rejection landed without one."""
        snap = {"spans": [], "journal": [
            _rec(1, J.PREEMPTION, "ns/p", node="host-0",
                 victims=["ns/v0"], victim_count=1),
            _rec(2, J.POD_REJECTED, "ns/p", reason="", message="no fit"),
            _rec(3, J.POD_REJECTED, "ns/p", reason="", message="no fit"),
        ]}
        text = "\n".join(obs.explain_pod(snap, "ns/p"))
        assert "retry expected next cycle" not in text

    def test_preemption_count_uses_complete_victim_count(self):
        snap = {"spans": [], "journal": [
            _rec(1, J.POD_REJECTED, "ns/p", reason="", message="no fit"),
            _rec(2, J.PREEMPTION, "ns/p", node="host-0",
                 victims=["ns/v0", "ns/v1"], victim_count=40),
        ]}
        text = "\n".join(obs.explain_pod(snap, "ns/p"))
        assert "evicted 40 victim(s)" in text

    def test_unknown_pod_explains_eviction_possibility(self):
        text = "\n".join(obs.explain_pod({"spans": [], "journal": []},
                                         "ns/ghost"))
        assert "no journaled decisions" in text

    def test_plan_breakdown_tree_and_decisions(self):
        spans = [
            {"name": "partitioner.plan_cycle", "trace_id": "t1",
             "span_id": "s1", "parent_id": "", "start": 0.0, "end": 10.0,
             "duration": 10.0, "status": "ok",
             "attrs": {"kind": "slice", "pods": 7}, "counts": {}},
            {"name": "planner.plan", "trace_id": "t1", "span_id": "s2",
             "parent_id": "s1", "start": 0.5, "end": 8.0, "duration": 7.5,
             "status": "ok", "attrs": {},
             "counts": {"forks": 4, "commits": 2, "filter_runs": 90}},
            {"name": "actuator.apply", "trace_id": "t1", "span_id": "s3",
             "parent_id": "s1", "start": 8.0, "end": 9.5, "duration": 1.5,
             "status": "ok", "attrs": {"plan_id": "abc"}, "counts": {}},
        ]
        journal = [_rec(1, J.PLAN_NODE_COMMITTED, "host-0", placed=2,
                        changed=True)]
        journal[0]["trace_id"] = "t1"
        lines = obs.explain_plan({"spans": spans, "journal": journal})
        text = "\n".join(lines)
        assert "partitioner.plan_cycle: 10000.0 ms" in text
        assert "planner.plan: 7500.0 ms (75%)" in text
        assert "forks: 4" in text
        assert "plan-node-committed host-0" in text

    def test_plan_kind_filter(self):
        lines = obs.explain_plan({"spans": [], "journal": []},
                                 kind="slice")
        assert "no completed plan cycle" in lines[0]


# ---------------------------------------------------------------------------
# End-to-end: real scheduler -> journal -> CLI explain
# ---------------------------------------------------------------------------


class TestExplainEndToEnd:
    def test_scheduler_rejection_explained_through_cli(self, tmp_path,
                                                       capsys):
        from nos_tpu.kube.client import APIServer, KIND_NODE, KIND_POD
        from nos_tpu.scheduler.framework import Framework
        from nos_tpu.scheduler.scheduler import Scheduler
        from nos_tpu.testing.factory import make_slice_pod, make_tpu_node

        clock = FakeClock()
        tracer = Tracer(clock=clock, ring=RingExporter(maxlen=256))
        journal = DecisionJournal(maxlen=256, clock=clock)
        with obs.scoped(tracer, journal):
            api = APIServer()
            api.create(KIND_NODE, make_tpu_node(
                "host-0", status_geometry={"free": {"2x4": 1}}))
            sched = Scheduler(api, Framework())
            api.create(KIND_POD, make_slice_pod("2x2", 1, name="stuck"))
            assert sched.run_cycle() == 0
            snap = obs.flight_snapshot()

        path = tmp_path / "flight.json"
        path.write_text(json.dumps(snap))
        rc = obs_main(["explain", "pod", "default/stuck",
                       "--snapshot", str(path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "NodeResourcesFit" in out
        assert "host-0" in out
        assert "nos.tpu/slice-2x2" in out
        # the run_cycle span made it to the ring with the bind count
        cycle = [s for s in snap["spans"]
                 if s["name"] == "scheduler.run_cycle"]
        assert cycle and cycle[-1]["attrs"]["bound"] == 0
        # the rejection record carries the complete node total (the
        # capped nodes/reason_counts views are NOT the size source)
        rej = [r for r in snap["journal"]
               if r["category"] == J.POD_REJECTED][-1]
        assert rej["attrs"]["nodes_total"] == 1

    def test_bound_pod_round_trip(self):
        from nos_tpu.kube.client import APIServer, KIND_NODE, KIND_POD
        from nos_tpu.scheduler.framework import Framework
        from nos_tpu.scheduler.scheduler import Scheduler
        from nos_tpu.testing.factory import make_slice_pod, make_tpu_node

        clock = FakeClock()
        with obs.scoped(Tracer(clock=clock, ring=RingExporter(maxlen=64)),
                        DecisionJournal(maxlen=64, clock=clock)):
            api = APIServer()
            api.create(KIND_NODE, make_tpu_node(
                "host-0", status_geometry={"free": {"2x2": 2}}))
            sched = Scheduler(api, Framework())
            api.create(KIND_POD, make_slice_pod("2x2", 1, name="ok"))
            assert sched.run_cycle() == 1
            text = "\n".join(obs.explain_pod(obs.flight_snapshot(),
                                             "default/ok"))
        assert "BOUND to node host-0" in text


# ---------------------------------------------------------------------------
# Flight recorder endpoint + selftest
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_health_server_serves_snapshot(self):
        import urllib.request

        from nos_tpu.cmd._runtime import Main

        clock = FakeClock()
        with obs.scoped(Tracer(clock=clock, ring=RingExporter(maxlen=8)),
                        DecisionJournal(maxlen=8, clock=clock)):
            with obs.span("flight-test"):
                obs.record(J.POD_BOUND, "ns/p", node="h0")
            main = Main("obs-test", health_addr="127.0.0.1:0")
            main.start()
            try:
                url = f"http://{main.health_address}/debug/flightrecorder"
                with urllib.request.urlopen(url, timeout=5.0) as resp:
                    payload = json.load(resp)
            finally:
                main.shutdown()
        assert [s["name"] for s in payload["spans"]] == ["flight-test"]
        assert payload["journal"][0]["subject"] == "ns/p"
        assert payload["journal"][0]["trace_id"] == \
            payload["spans"][0]["trace_id"]
        assert payload["journal_dropped"] == 0

    def test_selftest_green(self, capsys):
        assert selftest() == 0
        assert "ok" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# Victim-prescreen superset contract (ADVICE round 5)
# ---------------------------------------------------------------------------


class TestVictimPrescreen:
    """`victim_prescreen` must stay a SUPERSET of the victim walk's
    selection branches: a node screened out must be one the walk could
    never pick victims from.  The grid below runs the REAL
    `_select_victims_on_node` for every preemptor class and asserts
    every selected victim also passes the prescreen."""

    def _setup(self):
        from nos_tpu.api import constants as C
        from nos_tpu.quota import (
            ElasticQuotaInfo, ElasticQuotaInfos, TPUResourceCalculator,
        )
        from nos_tpu.scheduler.capacityscheduling import CapacityScheduling
        from nos_tpu.scheduler.framework import (
            Framework, NodeInfo, NodeResourcesFit,
        )
        from nos_tpu.testing.factory import make_slice_pod, make_tpu_node

        calc = TPUResourceCalculator()
        infos = ElasticQuotaInfos()
        # one 2x2 slice = 64 GB tpu-memory on v5e: team-a's min holds two
        # of them, team-b's one — so team-b (running two) borrows over min
        for ns, mn in (("team-a", 128.0), ("team-b", 64.0)):
            infos.add(ElasticQuotaInfo(
                resource_name=f"q-{ns}", resource_namespace=ns,
                namespaces=[ns], min={C.RESOURCE_TPU_MEMORY: mn},
                max=None, calculator=calc))
        cs = CapacityScheduling(calc)
        cs.elastic_quota_infos = infos
        cs.set_framework(Framework([NodeResourcesFit()]))

        node = make_tpu_node("host-0", status_geometry={"free": {"2x2": 4}})
        ni = NodeInfo(node=node)
        victims = [
            make_slice_pod("2x2", 1, name="free-lo", namespace="freens",
                           priority=0, node_name="host-0"),
            make_slice_pod("2x2", 1, name="a-lo", namespace="team-a",
                           priority=0, node_name="host-0"),
            make_slice_pod("2x2", 1, name="b-over", namespace="team-b",
                           priority=0, node_name="host-0",
                           labels={C.LABEL_CAPACITY:
                                   C.CAPACITY_OVER_QUOTA}),
            make_slice_pod("2x2", 1, name="b-in", namespace="team-b",
                           priority=0, node_name="host-0",
                           labels={C.LABEL_CAPACITY:
                                   C.CAPACITY_IN_QUOTA}),
        ]
        for v in victims:
            ni.add_pod(v)
            info = infos.get(v.metadata.namespace)
            if info is not None:
                info.add_pod_if_not_present(v)
        return cs, ni, infos, calc

    def _run(self, cs, ni, infos, calc, preemptor):
        from nos_tpu.scheduler.capacityscheduling import (
            ELASTIC_QUOTA_SNAPSHOT_KEY, PRE_FILTER_STATE_KEY,
            PreFilterState,
        )
        from nos_tpu.scheduler.framework import CycleState

        state = CycleState()
        state[ELASTIC_QUOTA_SNAPSHOT_KEY] = infos.clone()
        state[PRE_FILTER_STATE_KEY] = PreFilterState(
            calc.compute_pod_request(preemptor))
        victims, _, status = cs._select_victims_on_node(
            state, preemptor, ni, pdbs=[])
        return victims

    def test_walk_selection_is_subset_of_prescreen(self):
        from nos_tpu.scheduler.capacityscheduling import victim_prescreen
        from nos_tpu.testing.factory import make_slice_pod

        preemptors = [
            # quota-less preemptor: branch (a) — quota-less victims
            make_slice_pod("2x2", 1, name="p-free", namespace="freens",
                           priority=10),
            # governed, WITHIN min: branch (c) only — cross-namespace
            # over-quota victims from borrowing quotas
            make_slice_pod("2x2", 1, name="p-a", namespace="team-a",
                           priority=10),
            # governed, OVER min with this request: branches (b) + (c)
            make_slice_pod("2x2", 2, name="p-a2", namespace="team-a",
                           priority=10),
        ]
        selected_any = 0
        for preemptor in preemptors:
            cs, ni, infos, calc = self._setup()
            victims = self._run(cs, ni, infos, calc, preemptor)
            selected_any += len(victims)
            for v in victims:
                assert victim_prescreen(
                    preemptor, v, cs.elastic_quota_infos), (
                    f"walk selected {v.key} for {preemptor.key} but the "
                    "prescreen refuses it — the screen is no longer a "
                    "superset of the walk (see victim_prescreen contract)")
        assert selected_any > 0     # the grid actually exercised the walk

    def test_prescreen_skips_only_victimless_nodes(self):
        """A node whose pods ALL fail the prescreen yields no victims
        from the walk either (the screen's soundness direction)."""
        from nos_tpu.scheduler.capacityscheduling import victim_prescreen
        from nos_tpu.testing.factory import make_slice_pod

        cs, ni, infos, calc = self._setup()
        # high-priority governed preemptor from team-a: the only
        # prescreen-refused pod is b-in (cross-ns, in-quota) and free-lo
        # (ungoverned)
        preemptor = make_slice_pod("2x2", 2, name="p", namespace="team-a",
                                   priority=10)
        refused = [p for p in ni.pods
                   if not victim_prescreen(preemptor, p,
                                           cs.elastic_quota_infos)]
        assert {p.metadata.name for p in refused} == {"free-lo", "b-in"}
        victims = self._run(cs, ni, infos, calc, preemptor)
        assert {v.metadata.name for v in victims}.isdisjoint(
            {p.metadata.name for p in refused})


class TestVictimNodeScreen:
    """`_victim_screen` (ISSUE 18): the persistent per-request node mask
    for the preemption walk — epoch-keyed caching, correctness of the
    empty-node fit verdicts, and the empty-mask short-circuit that must
    emit the exact journal line the full walk would."""

    class _Lister:
        def __init__(self, nis):
            self._nis = nis

        def list(self):
            return list(self._nis)

    def _setup(self):
        from nos_tpu.quota import TPUResourceCalculator
        from nos_tpu.scheduler.capacityscheduling import CapacityScheduling
        from nos_tpu.scheduler.framework import (
            Framework, NodeInfo, NodeResourcesFit,
        )
        from nos_tpu.testing.factory import make_tpu_node

        cs = CapacityScheduling(TPUResourceCalculator())
        cs.set_framework(Framework([NodeResourcesFit()]))
        nis = [NodeInfo(node=make_tpu_node(
                   "big", status_geometry={"free": {"2x4": 1}})),
               NodeInfo(node=make_tpu_node(
                   "small", status_geometry={"free": {"2x2": 1}}))]
        return cs, self._Lister(nis)

    def _state(self, epoch=1):
        from nos_tpu.scheduler.capacityscheduling import (
            VIEW_EPOCH_CONTEXT_KEY,
        )
        from nos_tpu.scheduler.framework import CycleState

        state = CycleState()
        if epoch is not None:
            state[VIEW_EPOCH_CONTEXT_KEY] = epoch
        return state

    def test_mask_is_the_empty_node_fit_set(self):
        from nos_tpu.testing.factory import make_slice_pod

        cs, lister = self._setup()
        # a 2x4 preemptor fits an empty "big" (slice resource + 8 chips)
        # but never "small" (no 2x4 resource, only 4 chips of capacity)
        mask = cs._victim_screen(
            self._state(), make_slice_pod("2x4", 1, name="p"), lister)
        assert mask == frozenset({"big"})
        # a 2x2 preemptor only fits where the 2x2 slice resource exists
        # (the screen is NodeResourcesFit at zero occupancy: exact
        # resource names, not chip arithmetic)
        mask = cs._victim_screen(
            self._state(), make_slice_pod("2x2", 1, name="q"), lister)
        assert mask == frozenset({"small"})

    def test_no_epoch_means_no_screening(self):
        # detached plugin use / gang what-if domains carry no view
        # epoch: the walk must stay unscreened (None), not masked-empty
        from nos_tpu.testing.factory import make_slice_pod

        cs, lister = self._setup()
        assert cs._victim_screen(
            self._state(epoch=None),
            make_slice_pod("2x4", 1, name="p"), lister) is None

    def test_mask_persists_under_epoch_and_refreshes_past_it(self):
        from nos_tpu.scheduler.framework import NodeInfo
        from nos_tpu.testing.factory import make_slice_pod, make_tpu_node

        cs, lister = self._setup()
        pod = make_slice_pod("2x4", 1, name="p")
        first = cs._victim_screen(self._state(epoch=7), pod, lister)
        # unchanged epoch: the cached frozenset comes back by identity
        # (no node re-walk — that is the cross-cycle win)
        assert cs._victim_screen(self._state(epoch=7), pod, lister) \
            is first
        # fleet change bumps the epoch: the mask must see the new node
        lister._nis.append(NodeInfo(node=make_tpu_node(
            "big2", status_geometry={"free": {"2x4": 1}})))
        refreshed = cs._victim_screen(self._state(epoch=8), pod, lister)
        assert refreshed == frozenset({"big", "big2"})

    def test_empty_mask_short_circuits_with_exact_journal_line(self):
        from nos_tpu.scheduler.capacityscheduling import (
            ELASTIC_QUOTA_SNAPSHOT_KEY, PRE_FILTER_STATE_KEY,
            PreFilterState,
        )
        from nos_tpu.testing.factory import make_slice_pod

        cs, lister = self._setup()
        state = self._state()
        state[ELASTIC_QUOTA_SNAPSHOT_KEY] = cs.elastic_quota_infos.clone()
        # 4x4 fits neither node even fully drained -> empty mask
        preemptor = make_slice_pod("4x4", 1, name="p", priority=10)
        state[PRE_FILTER_STATE_KEY] = PreFilterState(
            cs.calculator.compute_pod_request(preemptor))
        journal = DecisionJournal(maxlen=8, clock=FakeClock())
        with obs.scoped(journal=journal):
            node, status = cs.post_filter(state, preemptor, lister)
        assert node == ""
        assert not status.is_success
        assert status.message == "preemption found no candidates"
        # byte-identical journal contract: the short-circuit emits the
        # same record the exhausted walk would
        [rec] = journal.events()
        assert rec.category == J.PREEMPTION_NONE
        assert rec.subject == preemptor.key
        assert rec.attrs["message"] == "preemption found no candidates"


# ---------------------------------------------------------------------------
# Journal call-site regressions
# ---------------------------------------------------------------------------


class TestQuotaLabelJournal:
    """The quota journal records label FLIPS — the first-ever labeling
    of a fresh in-quota pod is not a reclaim (review regression: every
    ordinary pod creation used to journal quota-reclaim)."""

    def _setup(self):
        from nos_tpu.controllers.elasticquota.controller import (
            _PodsReconciler,
        )
        from nos_tpu.kube.client import APIServer, KIND_POD
        from nos_tpu.quota import TPUResourceCalculator
        from nos_tpu.testing.factory import make_pod

        api = APIServer()
        api.create(KIND_POD, make_pod(name="p", namespace="team"))
        journal = DecisionJournal(maxlen=64, clock=FakeClock())
        return api, _PodsReconciler(api, TPUResourceCalculator()), journal

    def _pod(self, api):
        from nos_tpu.kube.client import KIND_POD

        return api.get(KIND_POD, "p", "team")

    def test_first_in_quota_labeling_is_silent_then_flips_journal(self):
        from nos_tpu.api import constants as C

        api, reconciler, journal = self._setup()
        with obs.scoped(journal=journal):
            reconciler._patch_capacity_label(
                self._pod(api), C.CAPACITY_IN_QUOTA)
            assert journal.events() == []       # not a flip
            reconciler._patch_capacity_label(
                self._pod(api), C.CAPACITY_OVER_QUOTA)
            reconciler._patch_capacity_label(
                self._pod(api), C.CAPACITY_IN_QUOTA)
        assert [r.category for r in journal.events()] == \
            [J.QUOTA_BORROW, J.QUOTA_RECLAIM]

    def test_fresh_pod_straight_to_over_quota_is_a_borrow(self):
        from nos_tpu.api import constants as C

        api, reconciler, journal = self._setup()
        with obs.scoped(journal=journal):
            reconciler._patch_capacity_label(
                self._pod(api), C.CAPACITY_OVER_QUOTA)
        assert [r.category for r in journal.events()] == [J.QUOTA_BORROW]
