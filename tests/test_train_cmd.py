"""Training main tests: config-driven loop, checkpoint cadence, resume."""

from __future__ import annotations

import math

import pytest

from nos_tpu.api.config import ConfigError
from nos_tpu.cmd.train import TrainConfig, build, train


def tiny_cfg(**kw) -> TrainConfig:
    base = dict(model="tiny", attn_impl="ring", batch_size=4, seq_len=64,
                steps=6, mesh="fsdp=2,tp=2,sp=2", log_every=3,
                checkpoint_every=3)
    base.update(kw)
    cfg = TrainConfig(**base)
    cfg.validate()
    return cfg


class TestTrainMain:
    def test_loop_runs_and_checkpoints(self, tmp_path):
        cfg = tiny_cfg(checkpoint_dir=str(tmp_path / "ck"))
        loss = train(cfg)
        assert math.isfinite(loss)
        from nos_tpu.models.checkpoint import TrainCheckpointer

        ck = TrainCheckpointer(cfg.checkpoint_dir)
        try:
            assert ck.latest_step() == cfg.steps
        finally:
            ck.close()

    def test_resume_picks_up_from_latest(self, tmp_path):
        ckdir = str(tmp_path / "ck")
        train(tiny_cfg(checkpoint_dir=ckdir, steps=6))
        # a "restarted pod": same config, more steps — must resume at 6
        cfg2 = tiny_cfg(checkpoint_dir=ckdir, steps=9)
        _, _, _, state, start_step = build(cfg2)
        assert start_step == 6
        assert int(state.step) == 6
        loss = train(cfg2)
        assert math.isfinite(loss)

    def test_already_complete_returns_none(self, tmp_path):
        ckdir = str(tmp_path / "ck")
        train(tiny_cfg(checkpoint_dir=ckdir, steps=6))
        assert train(tiny_cfg(checkpoint_dir=ckdir, steps=6)) is None
        assert train(tiny_cfg(checkpoint_dir=ckdir, steps=3)) is None

    def test_fresh_run_into_used_dir_rejected(self, tmp_path):
        ckdir = str(tmp_path / "ck")
        train(tiny_cfg(checkpoint_dir=ckdir, steps=6))
        with pytest.raises(ConfigError, match="resume"):
            build(tiny_cfg(checkpoint_dir=ckdir, steps=6, resume=False))

    def test_invalid_model_rejected(self):
        with pytest.raises(ConfigError, match="model"):
            tiny_cfg(model="gpt17")

    def test_missing_data_path_rejected(self):
        with pytest.raises(ConfigError, match="data_path"):
            tiny_cfg(data_path="/nonexistent/corpus.bin")


    def test_health_addr_validated_like_other_mains(self):
        with pytest.raises(ConfigError, match="host:port"):
            tiny_cfg(health_probe_addr="8080")

    def test_bad_worker_id_env_fails_fast(self, monkeypatch):
        from nos_tpu.cmd.train import maybe_init_distributed

        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "h0,h1")
        monkeypatch.delenv("TPU_WORKER_ID", raising=False)
        with pytest.raises(RuntimeError, match="unset"):
            maybe_init_distributed()
        monkeypatch.setenv("TPU_WORKER_ID", "worker-1")
        with pytest.raises(RuntimeError, match="not an integer"):
            maybe_init_distributed()
        monkeypatch.setenv("TPU_WORKER_ID", "5")
        with pytest.raises(RuntimeError, match="out of range"):
            maybe_init_distributed()


class TestJobProgressAnnotation:
    """The checkpoint hook's `nos.tpu/job-progress` write — the
    production source of the scheduler's drain-preemption spare-progress
    filter (docs/scheduler.md; ADVICE round 5)."""

    def _pod_api(self):
        from nos_tpu.kube.client import APIServer, KIND_POD
        from nos_tpu.testing.factory import make_pod

        api = APIServer()
        api.create(KIND_POD, make_pod(name="trainer", namespace="jobs"))
        return api

    def test_report_writes_clamped_annotation(self):
        from nos_tpu.api.constants import ANNOT_JOB_PROGRESS
        from nos_tpu.cmd.train import report_job_progress
        from nos_tpu.kube.client import KIND_POD

        api = self._pod_api()
        assert report_job_progress(api, "trainer", "jobs", 0.5)
        pod = api.get(KIND_POD, "trainer", "jobs")
        assert pod.metadata.annotations[ANNOT_JOB_PROGRESS] == "0.5000"
        # clamped into [0, 1] — a buggy fraction must not poison the
        # scheduler's float parse
        assert report_job_progress(api, "trainer", "jobs", 7.3)
        pod = api.get(KIND_POD, "trainer", "jobs")
        assert pod.metadata.annotations[ANNOT_JOB_PROGRESS] == "1.0000"

    def test_report_is_best_effort_on_vanished_pod(self):
        from nos_tpu.cmd.train import report_job_progress
        from nos_tpu.kube.client import APIServer

        # no such pod: the reporter logs and returns False, never raises
        assert not report_job_progress(APIServer(), "ghost", "jobs", 0.2)

    def test_reporter_inert_without_downward_api_identity(self):
        from nos_tpu.cmd.train import progress_reporter

        cfg = TrainConfig()
        assert progress_reporter(cfg, environ={}) is None
        # partial projection (POD_NAME without POD_NAMESPACE, or the
        # reverse) must stay inert, not guess a namespace — annotating
        # a same-named pod elsewhere would wrongly spare it from drain
        # preemption
        assert progress_reporter(cfg, environ={"POD_NAME": "t"}) is None
        assert progress_reporter(
            cfg, environ={"POD_NAMESPACE": "jobs"}) is None
        # identity present but no kubeconfig: nothing to annotate against
        assert progress_reporter(
            cfg, environ={"POD_NAME": "t", "POD_NAMESPACE": "jobs"}) is None

    def test_reporter_survives_malformed_kubeconfig(self, tmp_path):
        from nos_tpu.cmd.train import progress_reporter

        # the hook is advisory: a kubeconfig that exists but cannot be
        # loaded must disable the reporter, not kill train() at startup
        bad = tmp_path / "kubeconfig"
        bad.write_text("banana: [unclosed")
        cfg = TrainConfig(kubeconfig=str(bad))
        env = {"POD_NAME": "t", "POD_NAMESPACE": "jobs"}
        assert progress_reporter(cfg, environ=env) is None

    def test_scheduler_reads_reported_progress(self):
        from nos_tpu.api.constants import ANNOT_JOB_PROGRESS
        from nos_tpu.cmd.train import report_job_progress
        from nos_tpu.kube.client import KIND_POD
        from nos_tpu.scheduler.scheduler import _annotation_progress

        api = self._pod_api()
        report_job_progress(api, "trainer", "jobs", 0.8)
        pod = api.get(KIND_POD, "trainer", "jobs")
        assert _annotation_progress(pod) == 0.8
