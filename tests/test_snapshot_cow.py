"""COW snapshot correctness and clone-count regression gates.

The copy-on-write ClusterSnapshot must be observationally identical to
the seed's eager-clone fork semantics (every node cloned up front) on
arbitrary mutate/commit/revert sequences — the property test drives both
implementations through randomized op sequences (including nested
geometry re-carves and the SnapshotError paths) and compares the visible
state after every op.  The regression tests pin the tentpole's cost
contract: a plan clones only the nodes it dirties, never the cluster.
"""

import random

import pytest

from nos_tpu.kube.objects import Pod
from nos_tpu.partitioning.core import (
    ClusterSnapshot, GeometryPlanner, SnapshotError,
)
from nos_tpu.partitioning.core.snapshot import SnapshotLister
from nos_tpu.partitioning.slicepart import (
    SlicePartitionCalculator, SliceProfileCalculator, SliceSnapshotTaker,
)
from nos_tpu.partitioning.state import ClusterState
from nos_tpu.scheduler.framework import Framework
from nos_tpu.testing.factory import make_pod, make_slice_pod, make_tpu_node


class EagerForkSnapshot(ClusterSnapshot):
    """The seed's fork semantics, rebuilt on the COW machinery: every
    node is dirtied (and therefore cloned) up front, so revert restores
    everything — the reference model for the equivalence property."""

    def fork(self):
        super().fork()
        for name in list(self._nodes):
            self.get_node_for_write(name)


PROFILES = ["1x1", "1x2", "2x2", "2x4"]


def build_snapshot(cls, node_specs):
    state = ClusterState()
    for name, geometry in node_specs:
        state.update_node(make_tpu_node(name, status_geometry=geometry), [])
    base = SliceSnapshotTaker().take_snapshot(state)
    if cls is ClusterSnapshot:
        return base
    return cls(base.nodes(), base._filter)


def observe(snap):
    """Everything a consumer can see through the snapshot API."""
    out = {}
    for name, node in snap.nodes().items():
        ni = node.node_info()
        out[name] = (
            node.geometries(),
            tuple(sorted(ni.free().items())),
            tuple(sorted(p.metadata.name for p in ni.pods)),
            tuple(sorted(ni.requested.items())),
        )
    out["candidates"] = [n.name for n in snap.get_candidate_nodes()]
    probe = make_slice_pod("2x2", 2, name="probe")
    out["lacking"] = snap.get_lacking_slices(probe)
    return out


class TestCowEquivalenceProperty:
    @pytest.mark.parametrize("seed", range(12))
    def test_randomized_sequences_match_eager_semantics(self, seed):
        rng = random.Random(seed)
        specs = []
        for i in range(5):
            profile = rng.choice(PROFILES)
            status = rng.choice(["free", "used"])
            specs.append((f"n{i}", {status: {profile: 1}}))
        cow = build_snapshot(ClusterSnapshot, specs)
        eager = build_snapshot(EagerForkSnapshot, specs)
        pod_seq = [0]

        def op_fork(s):
            s.fork()

        def op_commit(s):
            s.commit()

        def op_revert(s):
            s.revert()

        def op_recarve(s, name=None, lacking=None):
            s.get_node_for_write(name).update_geometry_for(lacking)

        def op_add_pod(s, name=None, pod=None):
            s.add_pod(name, pod)

        def op_double_fork(s):
            s.fork()        # raises when already forked

        def op_add_unknown(s, pod=None):
            s.add_pod("no-such-node", pod)

        for step in range(40):
            roll = rng.random()
            kwargs = {}
            if not cow.forked:
                op = op_fork
            elif roll < 0.15:
                op = op_commit
            elif roll < 0.30:
                op = op_revert
            elif roll < 0.40:
                op = rng.choice([op_double_fork, op_add_unknown])
                if op is op_add_unknown:
                    kwargs["pod"] = make_slice_pod("1x1", 1, name="ghost")
            elif roll < 0.75:
                # nested re-carves: several geometry updates in one fork
                kwargs["name"] = f"n{rng.randrange(5)}"
                kwargs["lacking"] = {
                    rng.choice(PROFILES): rng.randrange(1, 3)}
                op = op_recarve
            else:
                pod_seq[0] += 1
                kwargs["name"] = f"n{rng.randrange(5)}"
                kwargs["pod"] = make_slice_pod(
                    rng.choice(PROFILES), 1, name=f"p{pod_seq[0]}")
                op = op_add_pod

            results = []
            for snap in (cow, eager):
                try:
                    op(snap, **kwargs)
                    results.append(("ok", None))
                except SnapshotError as e:
                    results.append(("err", type(e).__name__))
            assert results[0] == results[1], \
                f"seed={seed} step={step} op={op.__name__}: {results}"
            assert observe(cow) == observe(eager), \
                f"seed={seed} step={step} op={op.__name__}: state diverged"

    def test_error_paths_match(self):
        cow = build_snapshot(ClusterSnapshot, [("n0", {"free": {"2x4": 1}})])
        with pytest.raises(SnapshotError):
            cow.revert()                    # not forked
        cow.fork()
        with pytest.raises(SnapshotError):
            cow.fork()                      # double fork
        with pytest.raises(SnapshotError):
            cow.add_pod("n0", make_slice_pod("4x4", 1, name="toobig"))
        # a failed hypothetical bind still dirtied the node (the clone
        # happened before the fit check); revert must restore it
        cow.revert()
        assert cow.get_node("n0").geometries() == {0: {"2x4": 1}}


class TestCloneCountRegression:
    def _cluster_state(self, hosts=64, free_hosts=1):
        """`hosts - free_hosts` genuinely full hosts (a bound pod consumes
        every resource, so they are not candidates) + free hosts."""
        state = ClusterState()
        for i in range(hosts):
            if i >= hosts - free_hosts:
                state.update_node(make_tpu_node(
                    f"host-{i}", host_index=i,
                    status_geometry={"free": {"2x4": 1}}), [])
                continue
            node = make_tpu_node(f"host-{i}", host_index=i,
                                 status_geometry={"used": {"2x4": 1}})
            filler = make_pod(
                name=f"filler-{i}", node_name=f"host-{i}",
                resources=dict(node.status.allocatable))
            state.update_node(node, [filler])
        return state

    def _planner(self):
        return GeometryPlanner(
            framework=Framework(),
            calculator=SliceProfileCalculator(),
            partition_calculator=SlicePartitionCalculator(),
        )

    def test_plan_over_64_hosts_clones_only_dirty_nodes(self):
        # 63 fully-used hosts + 1 free host; demand re-carves the free
        # one.  The acceptance contract: clones per plan <= dirty + 1 —
        # the eager seed paid 64 clones per candidate visited.
        snap = SliceSnapshotTaker().take_snapshot(self._cluster_state())
        state = self._planner().plan(
            snap, [make_slice_pod("2x2", 1, name="p0")])
        assert state["host-63"].units[0].resources.get(
            "nos.tpu/slice-2x2", 0) >= 1
        assert snap.cow_clones <= 2
        assert snap.cow_clones < 64

    def test_reverted_candidates_cost_one_clone_each(self):
        # 4 free hosts, demand that fits nowhere: every candidate is
        # forked, dirtied once and reverted — 1 clone per candidate, not
        # N per fork.
        snap = SliceSnapshotTaker().take_snapshot(
            self._cluster_state(hosts=8, free_hosts=4))
        self._planner().plan(snap, [make_slice_pod("4x8", 1, name="big")])
        assert snap.cow_clones <= 4

    def test_snapshot_lister_tracks_fork_lifecycle(self):
        snap = SliceSnapshotTaker().take_snapshot(
            self._cluster_state(hosts=3, free_hosts=3))
        lister = SnapshotLister(snap)
        before = lister.get("host-0")
        assert before is snap.get_node("host-0").node_info()
        snap.fork()
        snap.get_node_for_write("host-0").update_geometry_for({"2x2": 2})
        # the COW clone replaced the node object: the lister re-reads it
        assert lister.get("host-0") is snap.get_node("host-0").node_info()
        assert lister.get("host-0") is not before
        # untouched nodes keep NodeInfo identity (no rebuild)
        assert lister.get("host-1") is snap.get_node("host-1").node_info()
        snap.revert()
        assert lister.get("host-0") is snap.get_node("host-0").node_info()
        assert dict(lister.get("host-0").free()).get(
            "nos.tpu/slice-2x4", 0) == 1


class TestDerivedViewCaches:
    def test_candidate_list_memoised_until_mutation(self):
        state = ClusterState()
        for i in range(4):
            state.update_node(make_tpu_node(
                f"n{i}", host_index=i,
                status_geometry={"free": {"2x4": 1}}), [])
        snap = SliceSnapshotTaker().take_snapshot(state)
        first = [n.name for n in snap.get_candidate_nodes()]
        epoch = snap._candidate_cache[0]
        assert [n.name for n in snap.get_candidate_nodes()] == first
        assert snap._candidate_cache[0] == epoch     # served from memo
        # a write access invalidates the memo: the next call re-sorts
        # (n0 lost its chips, so best-fit order puts it first)
        snap.add_pod("n0", make_slice_pod("2x4", 1, name="filler"))
        assert [n.name for n in snap.get_candidate_nodes()][0] == "n0"
        assert snap._candidate_cache[0] != epoch

    def test_lacking_slices_sees_writes(self):
        state = ClusterState()
        state.update_node(make_tpu_node(
            "n0", status_geometry={"free": {"2x4": 1}}), [])
        snap = SliceSnapshotTaker().take_snapshot(state)
        pod: Pod = make_slice_pod("2x4", 1, name="w")
        assert snap.get_lacking_slices(pod) == {}
        snap.add_pod("n0", make_slice_pod("2x4", 1, name="eater"))
        assert snap.get_lacking_slices(pod) == {"2x4": 1}
