"""Capacity plane tests: cloud API contract, stockout breaker, the
provisioner's level-triggered reconcile, and seeded chaos soaks with
cloud faults under lockcheck.

The regression test the satellite demands is here too: killing a pool's
HIGHEST-index host while no controller was watching (the blind spot
docs/scheduler.md documents for the purely observational spare policy)
and asserting a freshly restarted provisioner still closes the vacancy
from its durable pool-size record.
"""

from __future__ import annotations

import pytest

from nos_tpu import obs
from nos_tpu.api import constants as C
from nos_tpu.api.config import ConfigError, ProvisionerConfig
from nos_tpu.capacity import (
    AlreadyExistsError, BREAKER_CLOSED, BREAKER_HALF_OPEN, BREAKER_OPEN,
    CapacityProvisioner, CloudNotFoundError, CloudTPUAPI, RateLimitedError,
    StockoutBreaker, StockoutError,
)
from nos_tpu.capacity.cloudapi import OP_DONE, OP_PENDING
from nos_tpu.cmd.assembly import build_scheduler
from nos_tpu.kube.client import APIServer, KIND_CONFIGMAP, KIND_NODE, KIND_POD
from nos_tpu.obs import journal as J
from nos_tpu.obs import ledger as L
from nos_tpu.obs.journal import DecisionJournal
from nos_tpu.obs.ledger import ChipSecondLedger, conservation_ok
from nos_tpu.testing.chaos import ChaosAPIServer, ChaosCloudTPUAPI
from nos_tpu.testing.factory import admit_all, make_slice_pod, make_tpu_node
from nos_tpu.testing.lockcheck import LockGraph, guard_state, unguard_all
from nos_tpu.utils import retry as retry_mod


@pytest.fixture(autouse=True)
def fast_retry(monkeypatch):
    monkeypatch.setattr(retry_mod, "sleep", lambda s: None)


def make_joiner(api):
    """The kubelet-join analog the harness wires into the cloud: a
    landed cloud node becomes an API-server Node carrying the create's
    labels, with free geometry already reported (agentless)."""
    def join(cloud_node):
        labels = dict(cloud_node.labels)
        pool = labels.pop(C.LABEL_POD_ID, "pod-0")
        idx = int(labels.pop(C.LABEL_HOST_INDEX, "0"))
        for managed in (C.LABEL_ACCELERATOR, C.LABEL_PARTITIONING,
                        C.LABEL_CHIP_COUNT):
            labels.pop(managed, None)
        api.create(KIND_NODE, make_tpu_node(
            cloud_node.name, pod_id=pool, host_index=idx,
            status_geometry={"free": {"2x2": 2}}, extra_labels=labels))
    return join


class Harness:
    """Virtual-clock provisioner rig: APIServer + CloudTPUAPI with the
    join callback wired, obs scoped per test."""

    def __init__(self, cloud=None, provision_delay_s: float = 5.0,
                 **prov_kw):
        self.clock = [0.0]
        self.api = APIServer()
        self.cloud = cloud if cloud is not None else CloudTPUAPI(
            clock=lambda: self.clock[0],
            provision_delay_s=provision_delay_s)
        self.cloud.set_joiner(make_joiner(self.api))
        self.journal = DecisionJournal(maxlen=4096,
                                       clock=lambda: self.clock[0])
        self.ledger = ChipSecondLedger(clock=lambda: self.clock[0])
        self.prov = CapacityProvisioner(
            self.api, self.cloud, clock=lambda: self.clock[0], **prov_kw)

    def add_host(self, pool: str, idx: int, zone: str = "-",
                 spare: bool = False, park: int | None = None):
        extra = {C.LABEL_ZONE: zone}
        if spare:
            extra[C.LABEL_SPARE] = C.SPARE_WARM
        name = f"{pool}-h{idx}" if not spare else f"{pool}-sp{idx}"
        self.api.create(KIND_NODE, make_tpu_node(
            name, pod_id=pool, host_index=park if park is not None
            else idx, status_geometry={"free": {"2x2": 2}},
            extra_labels=extra))
        return name

    def scoped(self):
        return obs.scoped(journal=self.journal, ledger=self.ledger)

    def events(self, category):
        return self.journal.events(category)


# ---------------------------------------------------------------------------
# cloud API contract
# ---------------------------------------------------------------------------

class TestCloudAPI:
    def test_create_lands_async_and_joins(self):
        h = Harness(provision_delay_s=10.0)
        op = h.cloud.create_node("pod-0-h2", "tpu-v5e", "us-a",
                                 {C.LABEL_POD_ID: "pod-0",
                                  C.LABEL_HOST_INDEX: "2"})
        assert h.cloud.get_operation(op)["status"] == OP_PENDING
        assert h.api.try_get(KIND_NODE, "pod-0-h2") is None
        h.clock[0] = 11.0
        assert h.cloud.get_operation(op)["status"] == OP_DONE
        node = h.api.try_get(KIND_NODE, "pod-0-h2")
        assert node is not None
        assert node.metadata.labels[C.LABEL_HOST_INDEX] == "2"
        assert [n["name"] for n in h.cloud.list_nodes()] == ["pod-0-h2"]

    def test_duplicate_create_is_already_exists(self):
        h = Harness()
        h.cloud.create_node("n1", "tpu-v5e")
        with pytest.raises(AlreadyExistsError):
            h.cloud.create_node("n1", "tpu-v5e")
        h.clock[0] = 6.0
        h.cloud.list_nodes()
        with pytest.raises(AlreadyExistsError):
            h.cloud.create_node("n1", "tpu-v5e")

    def test_delete_cancels_pending_create(self):
        h = Harness()
        op = h.cloud.create_node("n1", "tpu-v5e")
        h.cloud.delete_node("n1")
        assert h.cloud.get_operation(op)["status"] == "FAILED"
        h.clock[0] = 60.0
        assert h.cloud.list_nodes() == []       # never lands
        with pytest.raises(CloudNotFoundError):
            h.cloud.delete_node("n1")

    def test_ack_gc_and_quota(self):
        h = Harness(cloud=None)
        cloud = CloudTPUAPI(clock=lambda: h.clock[0],
                            provision_delay_s=1.0, quota_nodes=1)
        cloud.create_node("n1", "tpu-v5e")
        from nos_tpu.capacity import QuotaExceededError
        with pytest.raises(QuotaExceededError):
            cloud.create_node("n2", "tpu-v5e")
        h.clock[0] = 2.0
        ops = cloud.list_operations()
        assert len(ops) == 1 and ops[0]["status"] == OP_DONE
        cloud.ack_operation(str(ops[0]["op_id"]))
        assert cloud.list_operations() == []

    def test_chaos_zombie_never_joins(self):
        h = Harness(cloud=ChaosCloudTPUAPI(
            seed=7, zombie_rate=1.0, clock=None))
        # rebuild with the virtual clock (ctor order quirk)
        h.cloud = ChaosCloudTPUAPI(seed=7, zombie_rate=1.0,
                                   clock=lambda: h.clock[0],
                                   provision_delay_s=1.0)
        h.cloud.set_joiner(make_joiner(h.api))
        h.cloud.create_node("z1", "tpu-v5e")
        h.clock[0] = 5.0
        assert [n["name"] for n in h.cloud.list_nodes()] == ["z1"]
        assert h.api.try_get(KIND_NODE, "z1") is None
        assert h.cloud.cloud_stats["zombies"] == 1

    def test_chaos_stockout_window_is_a_state(self):
        clock = [0.0]
        cloud = ChaosCloudTPUAPI(seed=1, clock=lambda: clock[0],
                                 stockout_window_s=30.0)
        cloud.inject_stockout("tpu-v5e", "us-a")
        for _ in range(3):
            with pytest.raises(StockoutError):
                cloud.create_node("x", "tpu-v5e", "us-a")
        # other zones unaffected; window expiry clears the state
        cloud.create_node("y", "tpu-v5e", "us-b")
        clock[0] = 31.0
        cloud.create_node("x", "tpu-v5e", "us-a")


# ---------------------------------------------------------------------------
# stockout breaker
# ---------------------------------------------------------------------------

class TestStockoutBreaker:
    def test_threshold_opens_and_half_open_probe(self):
        clock = [0.0]
        b = StockoutBreaker(threshold=3, open_s=60.0,
                            clock=lambda: clock[0])
        key = ("tpu-v5e", "us-a")
        assert b.record_stockout(key) is None
        assert b.record_stockout(key) is None
        assert b.state(key) == BREAKER_CLOSED and b.allow(key)
        assert b.record_stockout(key) == BREAKER_OPEN
        assert b.state(key) == BREAKER_OPEN and not b.allow(key)
        clock[0] = 61.0
        assert b.state(key) == BREAKER_HALF_OPEN
        assert b.allow(key)             # the single probe slot
        assert not b.allow(key)         # second caller stays blocked
        # failed probe: full window again
        assert b.record_stockout(key) == BREAKER_OPEN
        assert not b.allow(key)
        clock[0] = 122.0
        assert b.allow(key)
        assert b.record_success(key) == BREAKER_CLOSED
        assert b.state(key) == BREAKER_CLOSED and b.allow(key)
        assert b.open_count() == 0

    def test_keys_are_independent(self):
        b = StockoutBreaker(threshold=1, open_s=10.0, clock=lambda: 0.0)
        assert b.record_stockout(("v5e", "us-a")) == BREAKER_OPEN
        assert not b.allow(("v5e", "us-a"))
        assert b.allow(("v5e", "us-b"))
        assert b.allow(("v6e", "us-a"))
        snap = b.snapshot()
        assert snap["v5e/us-a"]["state"] == BREAKER_OPEN
        assert b.open_count() == 1

    def test_success_resets_streak(self):
        b = StockoutBreaker(threshold=2, open_s=10.0, clock=lambda: 0.0)
        key = ("v5e", "-")
        assert b.record_stockout(key) is None
        assert b.record_success(key) is None    # closed stays closed
        assert b.record_stockout(key) is None   # streak restarted
        assert b.record_stockout(key) == BREAKER_OPEN


# ---------------------------------------------------------------------------
# provisioner reconcile
# ---------------------------------------------------------------------------

def pump(h: Harness, until: float, step: float = 1.0):
    while h.clock[0] < until:
        h.clock[0] = min(until, h.clock[0] + step)
        h.prov.reconcile()


class TestScaleUp:
    def test_sustained_deficit_provisions_and_lands(self):
        h = Harness(scale_up_after_s=3.0, scale_up_cooldown_s=1.0,
                    vacancy_grace_s=1.0)
        h.add_host("pod-0", 0, zone="us-a")
        h.add_host("pod-0", 1, zone="us-a")
        for i in range(7):      # 28 chips demand vs 16 free
            h.api.create(KIND_POD, make_slice_pod("2x2", 1,
                                                  name=f"p{i}"))
        with h.scoped():
            h.prov.reconcile()                  # starts the sustain timer
            assert h.events(J.PROVISION_REQUESTED) == []
            pump(h, 4.0)
            reqs = h.events(J.PROVISION_REQUESTED)
            assert reqs, "sustained deficit must provision"
            name = reqs[0].subject
            assert name.startswith("pod-0-h")
            # the gap rides as a PROVISIONING hold, not idle_no_demand
            assert L.PROVISIONING in h.ledger.holds()[name]
            pump(h, 12.0)
            landed = h.events(J.PROVISION_LANDED)
            assert [r.subject for r in landed][:1] == [name]
            assert name not in h.ledger.holds()
            assert h.api.try_get(KIND_NODE, name) is not None
        report = h.prov.report()
        assert report["counters"]["landed"] >= 1
        assert report["pools"]["pod-0"]["recorded_size"] >= 3

    def test_no_demand_no_action(self):
        h = Harness()
        h.add_host("pod-0", 0)
        with h.scoped():
            pump(h, 30.0)
        assert h.cloud.list_operations() == []
        assert h.journal.events() == []
        assert h.prov.report()["deficit_chips"] <= 0.0

    def test_arriving_capacity_damps_further_creates(self):
        h = Harness(scale_up_after_s=1.0, scale_up_cooldown_s=0.0,
                    provision_delay_s=100.0, max_pending_creates=8)
        h.add_host("pod-0", 0)
        for i in range(4):      # 16 chips vs 8 free -> one host's worth
            h.api.create(KIND_POD, make_slice_pod("2x2", 1,
                                                  name=f"p{i}"))
        with h.scoped():
            pump(h, 10.0)
        # deficit was 8 = one host: exactly one create, then the
        # arriving capacity keeps the deficit below threshold
        assert len(h.cloud.list_operations()) == 1

    def test_restart_is_idempotent(self):
        h = Harness(scale_up_after_s=1.0, scale_up_cooldown_s=0.0,
                    provision_delay_s=100.0)
        h.add_host("pod-0", 0)
        for i in range(4):
            h.api.create(KIND_POD, make_slice_pod("2x2", 1,
                                                  name=f"p{i}"))
        with h.scoped():
            pump(h, 5.0)
            assert len(h.cloud.list_operations()) == 1
            # crash + new leader: same api, same cloud, fresh memory
            fresh = CapacityProvisioner(
                h.api, h.cloud, clock=lambda: h.clock[0],
                scale_up_after_s=1.0, scale_up_cooldown_s=0.0)
            for _ in range(6):
                h.clock[0] += 1.0
                fresh.reconcile()
        ops = h.cloud.list_operations()
        assert len(ops) == 1, "restart must not duplicate the create"
        # durable inventory survived and matches
        cm = h.api.try_get(KIND_CONFIGMAP, "nos-tpu-capacity-inventory",
                           "nos-tpu-system")
        assert cm is not None and '"pod-0": 2' in cm.data["pools"]


class TestVacancyAndBlindSpot:
    def test_dead_top_index_closed_from_durable_record(self):
        """THE regression: top-index host dies while NO controller is
        watching; the observational baseline can't see it, the durable
        record can."""
        h = Harness(vacancy_grace_s=2.0)
        for i in range(3):
            h.add_host("pod-0", i)
        spare = h.add_host("pod-0", 0, spare=True, park=100)
        with h.scoped():
            h.prov.reconcile()      # seeds the durable record: size 3
        cm = h.api.try_get(KIND_CONFIGMAP, "nos-tpu-capacity-inventory",
                           "nos-tpu-system")
        assert cm is not None and '"pod-0": 3' in cm.data["pools"]
        # the kill, unwatched: nothing running, nothing in memory
        h.api.delete(KIND_NODE, "pod-0-h2")
        fresh = CapacityProvisioner(h.api, h.cloud,
                                    clock=lambda: h.clock[0],
                                    vacancy_grace_s=2.0)
        with h.scoped():
            h.clock[0] += 1.0
            fresh.reconcile()       # sees the vacancy, grace pending
            node = h.api.get(KIND_NODE, spare)
            assert C.LABEL_SPARE in node.metadata.labels
            h.clock[0] += 3.0
            fresh.reconcile()       # grace over: spare takes index 2
        node = h.api.get(KIND_NODE, spare)
        assert C.LABEL_SPARE not in node.metadata.labels
        assert node.metadata.labels[C.LABEL_HOST_INDEX] == "2"
        assert [r.subject for r in h.events(J.SPARE_PROMOTED)] == [spare]

    def test_vacancy_without_spare_provisions(self):
        h = Harness(vacancy_grace_s=1.0, provision_delay_s=2.0)
        for i in range(2):
            h.add_host("pod-0", i, zone="us-a")
        with h.scoped():
            h.prov.reconcile()
            h.api.delete(KIND_NODE, "pod-0-h1")
            pump(h, 10.0)
        node = h.api.try_get(KIND_NODE, "pod-0-h1")
        assert node is not None, "vacancy must be re-provisioned"
        assert h.events(J.PROVISION_LANDED)


class TestStockoutDegradation:
    def _rig(self, **kw):
        h = Harness(cloud=None)
        h.cloud = ChaosCloudTPUAPI(seed=3, clock=lambda: h.clock[0],
                                   provision_delay_s=5.0)
        h.cloud.set_joiner(make_joiner(h.api))
        h.prov = CapacityProvisioner(
            h.api, h.cloud, clock=lambda: h.clock[0],
            scale_up_after_s=1.0, scale_up_cooldown_s=0.0,
            breaker_threshold=2, breaker_open_s=50.0, **kw)
        return h

    def test_breaker_opens_then_borrowing_covers(self):
        h = self._rig()
        h.add_host("pod-0", 0, zone="us-a")
        h.add_host("pod-1", 0, zone="us-b")
        h.add_host("pod-1", 1, zone="us-b")
        borrowable = h.add_host("pod-1", 0, spare=True, park=100)
        h.cloud.inject_stockout("tpu-v5e", "us-a", duration_s=1000.0)
        # deficit deep enough that one borrow doesn't erase it — the
        # retries after the borrow push the streak past the threshold
        for i in range(12):
            h.api.create(KIND_POD, make_slice_pod(
                "2x2", 1, name=f"p{i}"))
        with h.scoped():
            pump(h, 8.0)
        stock = h.events(J.PROVISION_STOCKOUT)
        assert any(r.attrs.get("state") == BREAKER_OPEN for r in stock)
        assert h.prov.breaker.state(("tpu-v5e", "us-a")) == BREAKER_OPEN
        borrows = h.events(J.SPARE_BORROWED)
        assert [r.subject for r in borrows] == [borrowable]
        node = h.api.get(KIND_NODE, borrowable)
        assert node.metadata.labels[C.LABEL_POD_ID] == "pod-0"
        assert C.LABEL_SPARE not in node.metadata.labels
        assert h.prov.report()["counters"]["borrows"] == 1

    def test_half_open_probe_recloses_after_recovery(self):
        h = self._rig()
        h.add_host("pod-0", 0, zone="us-a")
        h.cloud.inject_stockout("tpu-v5e", "us-a", duration_s=20.0)
        for i in range(6):
            h.api.create(KIND_POD, make_slice_pod(
                "2x2", 1, name=f"p{i}"))
        with h.scoped():
            pump(h, 8.0)        # stockouts open the breaker
            assert h.prov.breaker.state(
                ("tpu-v5e", "us-a")) == BREAKER_OPEN
            pump(h, 80.0)       # window expires, probe succeeds
        assert h.prov.breaker.state(("tpu-v5e", "us-a")) == BREAKER_CLOSED
        states = [r.attrs.get("state")
                  for r in h.events(J.PROVISION_STOCKOUT)]
        assert BREAKER_CLOSED in states


class TestZombieReap:
    def test_zombie_reaped_after_deadline(self):
        h = Harness(cloud=None)
        h.cloud = ChaosCloudTPUAPI(seed=5, zombie_rate=1.0,
                                   clock=lambda: h.clock[0],
                                   provision_delay_s=2.0)
        h.cloud.set_joiner(make_joiner(h.api))
        h.prov = CapacityProvisioner(
            h.api, h.cloud, clock=lambda: h.clock[0],
            scale_up_after_s=1.0, scale_up_cooldown_s=0.0,
            provision_deadline_s=10.0)
        h.add_host("pod-0", 0)
        for i in range(4):
            h.api.create(KIND_POD, make_slice_pod(
                "2x2", 1, name=f"p{i}"))
        with h.scoped():
            pump(h, 5.0)
            assert h.events(J.PROVISION_REQUESTED)
            name = h.events(J.PROVISION_REQUESTED)[0].subject
            assert L.PROVISIONING in h.ledger.holds().get(name, {})
            pump(h, 30.0)
        failed = h.events(J.PROVISION_FAILED)
        assert any(r.attrs.get("reason") == "zombie" for r in failed)
        assert name not in h.ledger.holds()     # hold reaped with it
        assert name not in [n["name"] for n in h.cloud.list_nodes()]
        assert not [op for op in h.cloud.list_operations()
                    if op["status"] != OP_PENDING], "reaped ops are acked"

    def test_stuck_pending_create_cancelled_at_deadline(self):
        h = Harness(cloud=None)
        h.cloud = ChaosCloudTPUAPI(seed=5, slow_rate=1.0,
                                   slow_extra_s=500.0,
                                   clock=lambda: h.clock[0],
                                   provision_delay_s=2.0)
        h.cloud.set_joiner(make_joiner(h.api))
        h.prov = CapacityProvisioner(
            h.api, h.cloud, clock=lambda: h.clock[0],
            scale_up_after_s=1.0, scale_up_cooldown_s=1000.0,
            provision_deadline_s=10.0)
        h.add_host("pod-0", 0)
        for i in range(4):
            h.api.create(KIND_POD, make_slice_pod(
                "2x2", 1, name=f"p{i}"))
        with h.scoped():
            pump(h, 30.0)
        failed = h.events(J.PROVISION_FAILED)
        assert any(r.attrs.get("reason") in ("deadline", "cancelled")
                   for r in failed)


class TestScaleDown:
    def _rig(self):
        h = Harness(scale_down_idle_s=5.0, scale_down_cooldown_s=0.0,
                    min_hosts_per_pool=1)
        h.add_host("pod-0", 0)
        h.add_host("pod-0", 1)
        return h

    def test_never_deletes_host_with_residents(self):
        h = self._rig()
        h.api.create(KIND_POD, make_slice_pod(
            "2x2", 1, name="r0", node_name="pod-0-h1", phase="Running"))
        with h.scoped():
            pump(h, 60.0)
        assert h.api.try_get(KIND_NODE, "pod-0-h1") is not None

    def test_never_deletes_held_host(self):
        h = self._rig()
        with h.scoped():
            h.ledger.set_hold("pod-0-h1", L.DRAIN, owner="t",
                              gang="g1")
            pump(h, 60.0)
        assert h.api.try_get(KIND_NODE, "pod-0-h1") is not None
        assert h.events(J.SCALE_DOWN) == []

    def test_never_deletes_while_demand_needs_the_host(self):
        # 16 pending chips against 16 free: releasing a host would
        # leave the demand unservable — the release must not happen
        h = self._rig()
        for i in range(2):
            h.api.create(KIND_POD, make_slice_pod("2x4", 1,
                                                  name=f"q{i}"))
        with h.scoped():
            pump(h, 60.0)
        assert h.api.try_get(KIND_NODE, "pod-0-h1") is not None
        assert h.events(J.SCALE_DOWN) == []

    def test_absorbable_pending_demand_does_not_block_release(self):
        # a churn-transient 4-chip pod fits the remaining host; it must
        # not reset the idle timer (that would ratchet the fleet up)
        h = self._rig()
        h.api.create(KIND_POD, make_slice_pod("2x2", 1, name="q0"))
        with h.scoped():
            pump(h, 60.0)
        assert h.api.try_get(KIND_NODE, "pod-0-h1") is None
        assert [r.subject for r in h.events(J.SCALE_DOWN)] \
            == ["pod-0-h1"]

    def test_busy_shrink_candidate_is_cordoned_then_released(self):
        # drain-then-release: a resident on the top host must not stall
        # the shrink forever — the host is cordoned with a capacity-
        # owned migration drain so the scheduler stops refilling it,
        # and released once the resident finishes
        h = self._rig()
        h.api.create(KIND_POD, make_slice_pod(
            "2x2", 1, name="r0", node_name="pod-0-h1", phase="Running"))
        with h.scoped():
            pump(h, 30.0)
            node = h.api.get(KIND_NODE, "pod-0-h1")
            assert node.metadata.annotations.get(C.ANNOT_DEFRAG_DRAIN) \
                == C.migration_drain_value("capacity", "scale-down")
            assert h.prov.report()["counters"]["cordons"] == 1
            h.api.delete(KIND_POD, "r0", "default")
            pump(h, 60.0)
        assert h.api.try_get(KIND_NODE, "pod-0-h1") is None
        assert [r.subject for r in h.events(J.SCALE_DOWN)] \
            == ["pod-0-h1"]

    def test_cordon_retracted_when_demand_returns(self):
        # level-triggered healing: the surplus evaporates (pending
        # demand needs the host) — the stamped cordon must come off
        # the same reconcile, not linger and starve placement
        h = self._rig()
        h.api.create(KIND_POD, make_slice_pod(
            "2x2", 1, name="r0", node_name="pod-0-h1", phase="Running"))
        with h.scoped():
            pump(h, 30.0)
            node = h.api.get(KIND_NODE, "pod-0-h1")
            assert C.ANNOT_DEFRAG_DRAIN in node.metadata.annotations
            for i in range(3):      # 12 pending chips > 12 free
                h.api.create(KIND_POD, make_slice_pod(
                    "2x2", 1, name=f"q{i}"))
            pump(h, 40.0)
        node = h.api.get(KIND_NODE, "pod-0-h1")
        assert C.ANNOT_DEFRAG_DRAIN not in node.metadata.annotations
        assert h.events(J.SCALE_DOWN) == []

    def test_cordon_never_touches_foreign_drains(self):
        # a defrag/recovery-owned migration drain on the shrink
        # candidate is someone else's state: the provisioner neither
        # overwrites it nor retracts it
        h = self._rig()
        foreign = C.migration_drain_value("defrag", "plan-7")
        h.api.patch(KIND_NODE, "pod-0-h1", mutate=lambda n: n.metadata
                    .annotations.__setitem__(C.ANNOT_DEFRAG_DRAIN, foreign))
        h.api.create(KIND_POD, make_slice_pod(
            "2x2", 1, name="r0", node_name="pod-0-h1", phase="Running"))
        with h.scoped():
            pump(h, 30.0)
        node = h.api.get(KIND_NODE, "pod-0-h1")
        assert node.metadata.annotations[C.ANNOT_DEFRAG_DRAIN] == foreign
        assert h.prov.report()["counters"]["cordons"] == 0

    def test_sustained_surplus_releases_top_index_only(self):
        h = self._rig()
        with h.scoped():
            pump(h, 60.0)
        assert h.api.try_get(KIND_NODE, "pod-0-h1") is None
        assert h.api.try_get(KIND_NODE, "pod-0-h0") is not None, \
            "min_hosts_per_pool floor holds"
        downs = h.events(J.SCALE_DOWN)
        assert [r.subject for r in downs] == ["pod-0-h1"]
        cm = h.api.try_get(KIND_CONFIGMAP, "nos-tpu-capacity-inventory",
                           "nos-tpu-system")
        assert '"pod-0": 1' in cm.data["pools"]


class TestSpareReplacement:
    def test_dead_spare_is_replaced(self):
        h = Harness(spare_target_per_pool=1, provision_delay_s=2.0,
                    provision_deadline_s=6.0, join_grace_s=1.0)
        h.add_host("pod-0", 0)
        with h.scoped():
            pump(h, 10.0)
        spares = [n for n in h.api.list(KIND_NODE)
                  if C.LABEL_SPARE in n.metadata.labels]
        assert len(spares) == 1, "missing warm spare gets provisioned"
        with h.scoped():
            h.api.delete(KIND_NODE, spares[0].metadata.name)
            pump(h, 20.0)
        spares = [n for n in h.api.list(KIND_NODE)
                  if C.LABEL_SPARE in n.metadata.labels]
        assert len(spares) == 1, "dead spare is auto-replaced"

    def test_quarantined_spare_not_counted_healthy(self):
        h = Harness(spare_target_per_pool=1, provision_delay_s=2.0)
        h.add_host("pod-0", 0)
        sick = h.add_host("pod-0", 0, spare=True, park=100)
        with h.scoped():
            h.ledger.set_hold(sick, L.QUARANTINE, owner="t",
                              reason="plan-deadline")
            pump(h, 10.0)
        spares = [n.metadata.name for n in h.api.list(KIND_NODE)
                  if C.LABEL_SPARE in n.metadata.labels]
        assert len(spares) == 2, "replacement provisioned alongside"


class TestWasteAttribution:
    def test_provisioning_hold_is_not_idle_no_demand(self):
        from nos_tpu.scheduler.scheduler import attribute_free_chips
        cat, take, q, g = attribute_free_chips(
            4.0, {L.PROVISIONING: {"pool": "pod-0"}}, False, 0.0, {},
            0.0, 0.0)
        assert cat == L.PROVISIONING and take == 4.0

    def test_conservation_holds_with_provisioning(self):
        h = Harness(scale_up_after_s=1.0, scale_up_cooldown_s=0.0,
                    provision_delay_s=50.0)
        h.add_host("pod-0", 0)
        for i in range(4):
            h.api.create(KIND_POD, make_slice_pod(
                "2x2", 1, name=f"p{i}"))
        sched = build_scheduler(h.api, 16, clock=lambda: h.clock[0])
        with h.scoped():
            for _ in range(6):
                h.clock[0] += 2.0
                sched.run_cycle()
                h.prov.reconcile()
            report = h.ledger.report()
        assert conservation_ok(report)
        # the in-flight host is NOT a pool member yet, so its hold must
        # stay inert in the waterfall (off-snapshot holds never accrue)
        assert h.events(J.PROVISION_REQUESTED)


class TestRetryPath:
    def test_rate_limits_are_retried_with_backoff(self, monkeypatch):
        h = Harness()
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise RateLimitedError("429")
            return "op-1"

        slept: list[float] = []
        monkeypatch.setattr(retry_mod, "sleep", slept.append)
        assert h.prov._call_cloud("create", flaky) == "op-1"
        assert calls["n"] == 3 and len(slept) == 2
        assert all(s > 0.0 for s in slept)

    def test_exhausted_retries_raise(self, monkeypatch):
        h = Harness(cloud_attempts=2)
        monkeypatch.setattr(retry_mod, "sleep", lambda s: None)

        def always():
            raise RateLimitedError("429")

        with pytest.raises(RateLimitedError):
            h.prov._call_cloud("create", always)


class TestProvisionerConfig:
    def test_defaults_validate_and_are_off(self):
        cfg = ProvisionerConfig()
        cfg.validate()
        assert cfg.enabled is False

    @pytest.mark.parametrize("field,value", [
        ("poll_interval_s", 0.0),
        ("scale_up_deficit_chips", -1.0),
        ("max_pending_creates", 0),
        ("provision_deadline_s", 0.0),
        ("breaker_threshold", 0),
        ("spare_target_per_pool", -1),
        ("inventory_configmap", ""),
        ("chips_per_host_cap", 0.0),
        ("hbm_gb_per_chip", 0.0),
        ("cloud_attempts", 0),
        ("quota_nodes", -1),
        ("breaker_open_s", -1.0),
    ])
    def test_rejects_bad_values(self, field, value):
        cfg = ProvisionerConfig(enabled=True)
        setattr(cfg, field, value)
        with pytest.raises(ConfigError):
            cfg.validate()

    def test_disabled_build_refuses_construction(self):
        from nos_tpu.cmd.assembly import build_provisioner_main
        with pytest.raises(ValueError):
            build_provisioner_main(APIServer(), ProvisionerConfig())


# ---------------------------------------------------------------------------
# chaos soak: provisioner + scheduler under cloud + apiserver faults,
# lockcheck-instrumented
# ---------------------------------------------------------------------------

def run_capacity_soak(seed: int, rounds: int = 60):
    lock_graph = LockGraph(name=f"capacity-soak-{seed}")
    clock = [0.0]
    errors: list[str] = []
    with lock_graph.install():
        api = ChaosAPIServer(seed, conflict_rate=0.10,
                             transient_rate=0.05, replay_after_ops=7)
        cloud = ChaosCloudTPUAPI(seed, stockout_rate=0.15,
                                 stockout_window_s=20.0,
                                 rate_limit_rate=0.15, slow_rate=0.3,
                                 slow_extra_s=10.0, zombie_rate=0.2,
                                 delete_fail_rate=0.3,
                                 clock=lambda: clock[0],
                                 provision_delay_s=4.0)
        cloud.set_joiner(make_joiner(api))
        prov = CapacityProvisioner(
            api, cloud, clock=lambda: clock[0],
            scale_up_after_s=2.0, scale_up_cooldown_s=4.0,
            scale_down_idle_s=20.0, scale_down_cooldown_s=10.0,
            provision_deadline_s=15.0, vacancy_grace_s=2.0,
            breaker_threshold=2, breaker_open_s=15.0,
            spare_target_per_pool=1)
        scheduler = build_scheduler(api, 16, clock=lambda: clock[0])
        journal = DecisionJournal(maxlen=8192, clock=lambda: clock[0])
        ledger = ChipSecondLedger(clock=lambda: clock[0])
        guard_state(journal, lock_graph, name="obs.DecisionJournal")
        guard_state(ledger, lock_graph, name="obs.ChipSecondLedger")
        guard_state(prov, lock_graph, name="capacity.CapacityProvisioner")
        guard_state(prov.breaker, lock_graph,
                    name="capacity.StockoutBreaker")
        guard_state(cloud, lock_graph, name="capacity.CloudTPUAPI")

    for i in range(2):
        api.create(KIND_NODE, make_tpu_node(
            f"pod-0-h{i}", pod_id="pod-0", host_index=i,
            status_geometry={"free": {"2x2": 2}},
            extra_labels={C.LABEL_ZONE: "us-a"}))

    def tick(name, fn):
        try:
            fn()
        except Exception as e:  # noqa: BLE001 — recorded, then asserted
            errors.append(f"seed={seed} round={rnd} {name}: {e!r}")

    rng = __import__("random").Random(seed)
    with obs.scoped(journal=journal, ledger=ledger):
        for rnd in range(rounds):
            clock[0] += 2.0
            if rnd == 5:
                for i in range(6):
                    api.create(KIND_POD, make_slice_pod(
                        "2x2", 1, name=f"soak-{seed}-{i}"))
            if rnd == 20:       # node loss mid-run, provisioner watching
                names = sorted(n.metadata.name
                               for n in api.list(KIND_NODE))
                if names:
                    tick("kill", lambda: api.delete(
                        KIND_NODE, rng.choice(names)))
            if rnd == 30:       # mid-reconcile controller kill/restart
                prov = CapacityProvisioner(
                    api, cloud, clock=lambda: clock[0],
                    scale_up_after_s=2.0, scale_up_cooldown_s=4.0,
                    scale_down_idle_s=20.0, scale_down_cooldown_s=10.0,
                    provision_deadline_s=15.0, vacancy_grace_s=2.0,
                    breaker_threshold=2, breaker_open_s=15.0,
                    spare_target_per_pool=1)
                with lock_graph.install():
                    guard_state(prov, lock_graph,
                                name="capacity.CapacityProvisioner-2")
            tick("scheduler", scheduler.run_cycle)
            tick("provisioner", prov.reconcile)
            tick("admit", lambda: admit_all(api))
            api.replay_dropped()
        clock[0] += 2.0
        tick("scheduler-final", scheduler.run_cycle)
    from types import SimpleNamespace
    return SimpleNamespace(seed=seed, errors=errors, api=api,
                           cloud=cloud, prov=prov, journal=journal,
                           ledger=ledger, lock_graph=lock_graph)


class TestChaosSoak:
    @pytest.mark.parametrize("seed", range(4))
    def test_soak_converges_clean(self, seed):
        r = run_capacity_soak(seed)
        try:
            assert not r.errors, r.errors[:3]
            r.lock_graph.assert_clean()
        finally:
            r.lock_graph.close()
            unguard_all()
        assert conservation_ok(r.ledger.report()), \
            f"seed={seed}: conservation violated under cloud faults"
        # every create either landed, was reaped, or is still within
        # its deadline — nothing leaks forever
        for op in r.cloud.list_operations():
            assert op["status"] in (OP_PENDING, OP_DONE)
