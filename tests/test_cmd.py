"""Process-model tests: typed configs, run loops, health/metrics
endpoints, graceful shutdown, and the end-to-end sim demo — the analog of
the reference's main-wiring coverage (cmd/gpupartitioner etc.)."""

from __future__ import annotations

import json
import time
import urllib.request

import pytest

from nos_tpu.api.config import (
    AgentConfig, ConfigError, OperatorConfig, PartitionerConfig,
    SchedulerConfig, load_config,
)
from nos_tpu.cmd._runtime import Main
from nos_tpu.cmd.assembly import build_partitioner_main, build_scheduler
from nos_tpu.exporter.metrics import Registry
from nos_tpu.kube.client import APIServer, KIND_NODE, KIND_POD
from nos_tpu.kube.objects import RUNNING
from nos_tpu.partitioning.state import ClusterState
from nos_tpu.testing.factory import make_slice_pod, make_tpu_node


class TestConfig:
    def test_defaults_valid(self):
        for cls in (PartitionerConfig, SchedulerConfig, OperatorConfig):
            load_config(None, cls)

    def test_yaml_round_trip(self, tmp_path):
        p = tmp_path / "cfg.yaml"
        p.write_text("kind: hybrid\nbatch_timeout_s: 5\nbatch_idle_s: 1\n")
        cfg = load_config(p, PartitionerConfig)
        assert cfg.kind == "hybrid"
        assert cfg.batch_timeout_s == 5.0  # int coerced to float

    def test_json_also_accepted(self, tmp_path):
        p = tmp_path / "cfg.json"
        p.write_text(json.dumps({"tpu_memory_gb_per_chip": 32}))
        assert load_config(p, SchedulerConfig).tpu_memory_gb_per_chip == 32


class TestConfigVersioning:
    """Versioned config API (api/config.py): apiVersion routing, logged
    v1beta1 -> v1beta2 conversion, defaulting, hard error on unknown
    versions — the pkg/api/scheduler/v1beta3 analog."""

    def test_v1beta2_nested_drain_block(self, tmp_path):
        p = tmp_path / "cfg.yaml"
        p.write_text(
            "apiVersion: nos.tpu/v1beta2\n"
            "drain_preemption:\n"
            "  after_cycles: 40\n"
            "  max_busy_fraction: 0.3\n"
            "  spare_progress: 0.6\n")
        cfg = load_config(p, SchedulerConfig)
        assert cfg.drain_preempt_after_cycles == 40
        assert cfg.drain_preempt_max_busy_fraction == 0.3
        assert cfg.drain_preempt_spare_progress == 0.6

    def test_v1beta1_flat_keys_convert_with_log(self, tmp_path, caplog):
        import logging

        p = tmp_path / "cfg.yaml"
        p.write_text(
            "apiVersion: nos.tpu/v1beta1\n"
            "drain_preempt_after_cycles: 25\n"
            "drain_preempt_max_busy_fraction: 0.4\n")
        with caplog.at_level(logging.INFO, logger="nos_tpu.api.config"):
            cfg = load_config(p, SchedulerConfig)
        assert cfg.drain_preempt_after_cycles == 25
        assert cfg.drain_preempt_max_busy_fraction == 0.4
        # defaulting pass: fields the old version never had
        assert cfg.drain_preempt_spare_progress == 0.75
        assert any("converted" in r.message for r in caplog.records)

    def test_unversioned_file_warns_and_loads_as_v1beta1(
            self, tmp_path, caplog):
        import logging

        p = tmp_path / "cfg.yaml"
        p.write_text("drain_preempt_after_cycles: 10\n")
        with caplog.at_level(logging.WARNING,
                             logger="nos_tpu.api.config"):
            cfg = load_config(p, SchedulerConfig)
        assert cfg.drain_preempt_after_cycles == 10
        assert any("no apiVersion" in r.message for r in caplog.records)

    def test_unknown_version_is_hard_error(self, tmp_path):
        p = tmp_path / "cfg.yaml"
        p.write_text("apiVersion: nos.tpu/v9\n")
        with pytest.raises(ConfigError, match="unsupported config"):
            load_config(p, SchedulerConfig)

    def test_mixed_forms_rejected(self, tmp_path):
        p = tmp_path / "cfg.yaml"
        p.write_text(
            "apiVersion: nos.tpu/v1beta1\n"
            "drain_preempt_after_cycles: 10\n"
            "drain_preemption:\n"
            "  after_cycles: 20\n")
        with pytest.raises(ConfigError, match="migrate fully"):
            load_config(p, SchedulerConfig)

    def test_unknown_nested_key_rejected(self, tmp_path):
        p = tmp_path / "cfg.yaml"
        p.write_text(
            "apiVersion: nos.tpu/v1beta2\n"
            "drain_preemption:\n"
            "  banana: 1\n")
        with pytest.raises(ConfigError, match="unknown drain_preemption"):
            load_config(p, SchedulerConfig)

    def test_version_accepted_on_all_kinds(self, tmp_path):
        for cls in (PartitionerConfig, OperatorConfig):
            p = tmp_path / "cfg.yaml"
            p.write_text("apiVersion: nos.tpu/v1beta2\n")
            load_config(p, cls)

    @pytest.mark.parametrize("body,err", [
        ("kind: banana", "slice|timeshare|hybrid"),
        ("batch_idle_s: 10\nbatch_timeout_s: 2", "must not exceed"),
        ("batch_timeout_s: -1", "positive"),
        ("frobnicate: 1", "unknown config key"),
        ("health_probe_addr: nocolon", "host:port"),
        ("known_geometries_file: /nope/missing.json", "does not exist"),
    ])
    def test_partitioner_validation(self, tmp_path, body, err):
        p = tmp_path / "bad.yaml"
        p.write_text(body)
        with pytest.raises(ConfigError, match=err):
            load_config(p, PartitionerConfig)

    def test_agent_requires_node_name(self):
        with pytest.raises(ConfigError, match="node_name"):
            AgentConfig().validate()

    def test_geometry_override_file_accepted(self, tmp_path):
        f = tmp_path / "geo.json"
        f.write_text("{}")
        cfg = load_config(None, PartitionerConfig)
        cfg.known_geometries_file = str(f)
        cfg.validate()

    def test_string_for_numeric_field_is_config_error(self, tmp_path):
        p = tmp_path / "bad.yaml"
        p.write_text("batch_timeout_s: 'two'\n")
        with pytest.raises(ConfigError, match="must be float"):
            load_config(p, PartitionerConfig)
        p.write_text("tpu_memory_gb_per_chip: '32'\n")
        with pytest.raises(ConfigError, match="must be int"):
            load_config(p, SchedulerConfig)
        p.write_text("leader_election: 'yes'\n")
        with pytest.raises(ConfigError, match="must be bool"):
            load_config(p, OperatorConfig)

    def test_bool_for_numeric_field_is_config_error(self, tmp_path):
        p = tmp_path / "bad.yaml"
        p.write_text("tpu_memory_gb_per_chip: true\n")
        with pytest.raises(ConfigError, match="must be int"):
            load_config(p, SchedulerConfig)

    def test_node_override_applies_before_validation(self, tmp_path):
        # ADVICE r2: shared config file without node_name + per-node
        # --node flag must not fail validation at load time.
        from nos_tpu.api.config import load_agent_config

        p = tmp_path / "agent.yaml"
        p.write_text("report_interval_s: 5\n")
        cfg = load_agent_config(p, "host-7")
        assert cfg.node_name == "host-7"
        assert cfg.report_interval_s == 5.0
        with pytest.raises(ConfigError, match="node_name"):
            load_agent_config(p, None)

    def test_yaml_bare_key_means_default(self, tmp_path):
        p = tmp_path / "cfg.yaml"
        p.write_text("metrics_addr:\nbatch_timeout_s: 3\n")
        cfg = load_config(p, PartitionerConfig)
        assert cfg.metrics_addr == ""
        assert cfg.batch_timeout_s == 3.0


class TestMetricsRegistry:
    def test_counter_gauge_timer_and_render(self):
        reg = Registry()
        reg.describe("nos_test_total", "a test counter")
        reg.inc("nos_test_total", labels={"kind": "slice"})
        reg.inc("nos_test_total", 2.0, labels={"kind": "slice"})
        reg.set("nos_test_gauge", 7.0)
        with reg.time("nos_test_op_seconds"):
            pass
        text = reg.render()
        assert 'nos_test_total{kind="slice"} 3.0' in text
        assert "# HELP nos_test_total a test counter" in text
        assert "nos_test_gauge 7.0" in text
        assert "nos_test_op_seconds_count 1" in text
        snap = reg.snapshot()
        assert snap["nos_test_total"]["kind=slice"] == 3.0

    def test_label_values_escaped(self):
        reg = Registry()
        reg.inc("nos_esc_total", labels={"v": 'a"b\\c\nd'})
        text = reg.render()
        assert 'v="a\\"b\\\\c\\nd"' in text


class TestRunLoops:
    def test_loop_survives_exceptions_and_stops(self):
        main = Main("t")
        calls = []

        def boom():
            calls.append(1)
            raise RuntimeError("tick failed")

        main.add_loop("boom", boom, 0.01)
        main.start()
        time.sleep(0.1)
        main.shutdown()
        assert len(calls) >= 2  # kept running after the exception
        n = len(calls)
        time.sleep(0.05)
        assert len(calls) == n  # actually stopped

    def test_slow_tick_does_not_stretch_the_period(self):
        """Regression: the loop used to sleep the FULL interval after
        every tick, so a tick taking ~interval doubled the effective
        reconcile period.  Asserted on the WAIT the loop requests (not
        on wall-clock tick counts, which flake on loaded CI): a ~0.03 s
        tick against a 0.05 s interval must wait ~0.02 s, never the
        full interval."""
        import threading

        from nos_tpu.cmd._runtime import RunLoop

        waits: list[float] = []

        class _Stop(threading.Event):
            def wait(self, timeout=None):
                waits.append(timeout)
                return len(waits) >= 3

            def is_set(self):
                return len(waits) >= 3

        loop = RunLoop("t", lambda: time.sleep(0.03), 0.05, _Stop())
        loop.run()          # synchronous: 3 ticks, then the stub stops it
        # tick duration only GROWS under load, so the requested wait
        # only shrinks — this bound holds on any machine
        assert len(waits) == 3
        assert all(w < 0.045 for w in waits), waits

    def test_health_respond_swallows_client_disconnect(self):
        from nos_tpu.cmd._runtime import _HealthHandler

        h = _HealthHandler.__new__(_HealthHandler)
        h.request_version = "HTTP/1.1"
        h.requestline = "GET /metrics HTTP/1.1"

        class _BrokenPipe:
            def write(self, data):
                raise BrokenPipeError("client went away")

        h.wfile = _BrokenPipe()
        h._respond(200, "payload")      # must not raise off the thread

    def test_health_endpoints(self):
        main = Main("t", health_addr="127.0.0.1:0")
        main.add_loop("noop", lambda: None, 0.05)
        main.start()
        try:
            base = f"http://{main.health_address}"
            for path, want in (("/healthz", 200), ("/readyz", 200),
                               ("/metrics", 200)):
                with urllib.request.urlopen(base + path) as resp:
                    assert resp.status == want
            with urllib.request.urlopen(base + "/metrics") as resp:
                assert b"nos_tpu_runloop" in resp.read()
        finally:
            main.shutdown()
        # after shutdown readiness is cleared
        assert not main.ready.is_set()


class TestMetricsExporter:
    def test_collect_and_export(self, tmp_path):
        from nos_tpu.cmd.metricsexporter import export
        from nos_tpu.exporter import collect
        from nos_tpu.exporter.metrics import Registry

        api = APIServer()
        api.create(KIND_NODE, make_tpu_node("h0", pod_id="p0"))
        api.create(KIND_NODE, make_tpu_node(
            "t0", partitioning="timeshare", pod_id=""))
        reg = Registry()
        reg.inc("nos_tpu_plans_total", labels={"kind": "slice"})
        payload = collect(api, components={"partitioner": True},
                          registry=reg)
        assert payload["cluster"]["nodes_total"] == 2
        assert payload["cluster"]["partitioning"]["slice"]["chips"] == 8.0
        assert payload["cluster"]["partitioning"]["timeshare"]["nodes"] == 1
        assert payload["metrics"]["nos_tpu_plans_total"]["kind=slice"] == 1.0
        out = tmp_path / "m.json"
        assert export(payload, out=str(out)) == 0
        assert json.loads(out.read_text())["components"]["partitioner"]

    def test_export_pos_failure_is_nonfatal_rc(self):
        from nos_tpu.cmd.metricsexporter import export

        # unreachable endpoint: rc 1, no exception
        assert export({"x": 1},
                      endpoint="http://127.0.0.1:1/ingest") == 1


class TestProcessModelEndToEnd:
    def test_threaded_control_plane_converges(self):
        """The bench path: partitioner + scheduler + agents as run loops
        bind a slice pod with no hand-cranking."""
        from nos_tpu.controllers.sliceagent.agent import SliceAgent
        from nos_tpu.device.fake import FakePodResources, FakeTpuRuntime
        from nos_tpu.topology import V5E

        api = APIServer()
        state = ClusterState()
        cfg = PartitionerConfig(batch_timeout_s=0.3, batch_idle_s=0.05,
                                poll_interval_s=0.01)
        main, _ = build_partitioner_main(api, state, cfg)
        api.create(KIND_NODE, make_tpu_node("host-0", pod_id="pod-0"))
        agent = SliceAgent(api, "host-0", FakeTpuRuntime(V5E),
                           FakePodResources())
        agent.start()
        main.add_loop("agent", agent.tick, 0.01)
        main.add_loop("sched", build_scheduler(api).run_cycle, 0.01)
        main.start()
        try:
            api.create(KIND_POD, make_slice_pod("2x2", 1, name="w"))
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                pod = api.get(KIND_POD, "w", "default")
                if pod.spec.node_name and pod.status.phase == RUNNING:
                    break
                time.sleep(0.02)
            else:
                pytest.fail("pod did not bind via threaded control plane")
        finally:
            main.shutdown()

    def test_partitioner_sim_demo(self):
        """`--sim` assembly converges (the standalone demo the main runs)."""
        from nos_tpu.cmd.partitioner import add_sim

        api = APIServer()
        state = ClusterState()
        cfg = PartitionerConfig(batch_timeout_s=0.3, batch_idle_s=0.05,
                                poll_interval_s=0.01)
        main, _ = build_partitioner_main(api, state, cfg)
        add_sim(main, api, hosts=2)
        main.start()
        try:
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                bound = sum(1 for p in api.list(KIND_POD)
                            if p.spec.node_name
                            and p.status.phase == RUNNING)
                if bound == 2:
                    break
                time.sleep(0.02)
            else:
                pytest.fail("sim demo did not converge")
        finally:
            main.shutdown()

class TestSnapshotAndExporterSource:
    """The one-shot exporter must observe real state (round-2 VERDICT #6):
    a live main's /snapshot endpoint or a dumped state file, never an
    empty APIServer by accident."""

    def test_serialize_round_trip(self):
        import dataclasses

        from nos_tpu.kube.client import APIServer, KIND_NODE, KIND_POD
        from nos_tpu.kube.serialize import dump_state, load_state
        from nos_tpu.testing.factory import make_slice_pod, make_tpu_node

        api = APIServer()
        api.create(KIND_NODE, make_tpu_node("host-0"))
        api.create(KIND_POD, make_slice_pod("2x2", 1, name="p0"))
        data = dump_state(api)
        api2 = load_state(json.loads(json.dumps(data)))
        n = api2.get(KIND_NODE, "host-0")
        assert n.metadata.labels == api.get(KIND_NODE, "host-0").metadata.labels
        p = api2.list(KIND_POD)[0]
        assert p.metadata.name == "p0"
        assert dataclasses.asdict(p) == dataclasses.asdict(
            api.list(KIND_POD)[0])

    def test_snapshot_endpoint_serves_live_state(self):
        import urllib.request

        from nos_tpu.cmd._runtime import Main
        from nos_tpu.kube.client import APIServer, KIND_NODE
        from nos_tpu.testing.factory import make_tpu_node

        api = APIServer()
        api.create(KIND_NODE, make_tpu_node("host-0"))
        main = Main("t", health_addr="127.0.0.1:0", api=api)
        main.start()
        try:
            url = f"http://{main.health_address}/snapshot"
            with urllib.request.urlopen(url, timeout=5) as resp:
                data = json.load(resp)
            assert "Node" in data["state"]
            assert data["state"]["Node"][0]["metadata"]["name"] == "host-0"
            assert "metrics" in data
        finally:
            main.shutdown()

    def test_exporter_source_url_yields_nonzero_nodes(self, tmp_path):
        from nos_tpu.cmd import metricsexporter
        from nos_tpu.cmd._runtime import Main
        from nos_tpu.kube.client import APIServer, KIND_NODE
        from nos_tpu.testing.factory import make_tpu_node

        api = APIServer()
        for i in range(4):
            api.create(KIND_NODE, make_tpu_node(f"host-{i}"))
        main = Main("t", health_addr="127.0.0.1:0", api=api)
        main.start()
        try:
            out = tmp_path / "payload.json"
            rc = metricsexporter.main([
                "--source", f"http://{main.health_address}",
                "--out", str(out)])
            assert rc == 0
            payload = json.loads(out.read_text())
            assert payload["cluster"]["nodes_total"] == 4
        finally:
            main.shutdown()

    def test_exporter_source_state_file(self, tmp_path):
        from nos_tpu.cmd import metricsexporter
        from nos_tpu.kube.client import APIServer, KIND_NODE
        from nos_tpu.kube.serialize import dump_state
        from nos_tpu.testing.factory import make_tpu_node

        api = APIServer()
        api.create(KIND_NODE, make_tpu_node("host-0"))
        src = tmp_path / "state.json"
        src.write_text(json.dumps(dump_state(api)))
        out = tmp_path / "payload.json"
        rc = metricsexporter.main(["--source", str(src), "--out", str(out)])
        assert rc == 0
        payload = json.loads(out.read_text())
        assert payload["cluster"]["nodes_total"] == 1

    def test_exporter_unknown_kind_skipped(self, tmp_path):
        from nos_tpu.cmd import metricsexporter

        src = tmp_path / "state.json"
        src.write_text(json.dumps({
            "Lease": [{"metadata": {"name": "l0"}}],
            "Node": [],
        }))
        out = tmp_path / "payload.json"
        rc = metricsexporter.main(["--source", str(src), "--out", str(out)])
        assert rc == 0  # unknown kind skipped, known kinds loaded

    def test_exporter_non_object_json_fails_cleanly(self, tmp_path):
        from nos_tpu.cmd import metricsexporter

        src = tmp_path / "state.json"
        src.write_text("[1, 2, 3]")
        rc = metricsexporter.main(["--source", str(src)])
        assert rc == 1

    def test_exporter_bad_source_fails_cleanly(self):
        from nos_tpu.cmd import metricsexporter

        rc = metricsexporter.main(["--source", "/nonexistent/state.json"])
        assert rc == 1

class TestAgentAutoGeneration:
    """--generation auto: agents observe topology (discovery) and the
    self-registered node advertises the OBSERVED block, not the
    generation default (a 4-chip VM must not offer 8 chips)."""

    def _observed(self):
        from nos_tpu.device import discovery
        from nos_tpu.topology import Shape, V5E

        return discovery.DiscoveredTopology(
            generation=V5E, host_block=Shape((2, 2)), num_local_chips=4,
            num_hosts=1, source=discovery.SOURCE_ENV,
            accelerator_type="v5litepod-4", origin=(0, 0))

    def test_sliceagent_auto_advertises_observed_block(self, monkeypatch):
        from nos_tpu.api import constants as C
        from nos_tpu.api.config import AgentConfig
        from nos_tpu.cmd.sliceagent import build_agent_main
        from nos_tpu.device import discovery
        from nos_tpu.kube.client import APIServer, KIND_NODE

        monkeypatch.setattr(discovery, "discover",
                            lambda *a, **k: self._observed())
        api = APIServer()
        cfg = AgentConfig(node_name="auto-0", generation="auto")
        build_agent_main(api, cfg)
        node = api.get(KIND_NODE, "auto-0")
        assert node.metadata.labels[C.LABEL_CHIP_COUNT] == "4"

    def test_chipagent_auto_advertises_observed_block(self, monkeypatch):
        from nos_tpu.api import constants as C
        from nos_tpu.api.config import AgentConfig
        from nos_tpu.cmd.chipagent import build_chipagent_main
        from nos_tpu.device import discovery
        from nos_tpu.kube.client import APIServer, KIND_NODE

        monkeypatch.setattr(discovery, "discover",
                            lambda *a, **k: self._observed())
        api = APIServer()
        cfg = AgentConfig(node_name="auto-ts-0", generation="auto")
        build_chipagent_main(api, cfg)
        node = api.get(KIND_NODE, "auto-ts-0")
        assert node.metadata.labels[C.LABEL_CHIP_COUNT] == "4"
