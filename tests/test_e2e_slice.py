"""Integration test: the minimum end-to-end slice (SURVEY.md §7).

A pending pod requesting `nos.tpu/slice-2x2` causes the partitioner to
annotate a fake v5e host, the (fake-runtime) slice agent actuates and flips
status annotations, the device plugin re-advertises, and the pod schedules —
the full decision-plane ↔ actuation-plane loop with no hardware, the analog
of the reference's envtest + mocked-NVML integration suites (SURVEY.md §4).
"""

import pytest

# every lock built by the harness is lockdep-checked (conftest fixture)
pytestmark = pytest.mark.usefixtures("lock_discipline")

from nos_tpu.api import constants as C  # noqa: E402
from nos_tpu.controllers.node_controller import NodeController
from nos_tpu.controllers.pod_controller import PodController
from nos_tpu.controllers.sliceagent.agent import SliceAgent
from nos_tpu.device.fake import FakePodResources, FakeTpuRuntime
from nos_tpu.kube.client import APIServer, KIND_NODE, KIND_POD
from nos_tpu.kube.objects import RUNNING
from nos_tpu.partitioning.slicepart import SliceNodeInitializer
from nos_tpu.partitioning.slicepart.factory import new_slice_partitioner_controller
from nos_tpu.partitioning.state import ClusterState
from nos_tpu.scheduler.framework import Framework
from nos_tpu.scheduler.scheduler import Scheduler
from nos_tpu.testing.factory import make_slice_pod, make_tpu_node
from nos_tpu.topology import V5E
from nos_tpu.topology.annotations import (
    parse_spec_annotations, parse_status_annotations, spec_matches_status,
)


class Harness:
    """Wires the full control plane against one fake v5e host."""

    def __init__(self):
        self.api = APIServer()
        self.state = ClusterState()
        self.clock_now = [0.0]
        self.node = make_tpu_node("host-0")     # virgin: no status annotations
        # decision plane
        self.node_ctrl = NodeController(
            self.api, self.state, SliceNodeInitializer(self.api)
        )
        self.pod_ctrl = PodController(self.api, self.state)
        self.partitioner = new_slice_partitioner_controller(
            self.api, self.state,
            batch_timeout_s=60.0, batch_idle_s=10.0,
            clock=lambda: self.clock_now[0],
        )
        self.node_ctrl.bind()
        self.pod_ctrl.bind()
        self.partitioner.bind()
        # node joins
        self.api.create(KIND_NODE, self.node)
        # actuation plane
        self.runtime = FakeTpuRuntime(V5E)
        self.pod_resources = FakePodResources()
        self.agent = SliceAgent(self.api, "host-0", self.runtime,
                                self.pod_resources)
        self.agent.start()
        # scheduler
        self.scheduler = Scheduler(self.api, Framework())

    def advance(self, seconds: float):
        self.clock_now[0] += seconds

    def get_node(self):
        return self.api.get(KIND_NODE, "host-0")


def test_node_bootstrap_initializes_virgin_host():
    h = Harness()
    node = h.get_node()
    parsed = parse_spec_annotations(node.metadata.annotations)
    assert [(a.index, a.profile, a.quantity) for a in parsed] == [(0, "2x4", 1)]
    # agent actuates the init spec
    h.agent.tick()
    node = h.get_node()
    assert spec_matches_status(node.metadata.annotations)
    assert node.status.allocatable.get("nos.tpu/slice-2x4") == 1.0
    status = parse_status_annotations(node.metadata.annotations)
    assert [(a.profile, a.status, a.quantity) for a in status] == [
        ("2x4", "free", 1)
    ]


def test_pending_pod_triggers_repartition_and_schedules():
    h = Harness()
    h.agent.tick()                        # actuate init geometry

    pod = make_slice_pod("2x2", 1, name="train-1")
    h.api.create(KIND_POD, pod)
    # first scheduling attempt fails: no 2x2 resource advertised
    assert h.scheduler.run_cycle() == 0
    # the unschedulable mark flows through the watch into the batcher
    h.advance(11.0)                       # idle window elapses
    assert h.partitioner.process_if_ready()

    node = h.get_node()
    spec = {(a.index, a.profile): a.quantity
            for a in parse_spec_annotations(node.metadata.annotations)}
    assert spec[(0, "2x2")] == 2          # host re-carved into 2x2 slices

    # plan handshake: a second batch is deferred until the agent reports
    h.advance(61.0)
    pod2 = make_slice_pod("1x1", 1, name="train-2")
    h.api.create(KIND_POD, pod2)
    h.scheduler.run_cycle()
    assert not h.partitioner.process_if_ready()   # waiting on plan report

    # actuation plane converges
    h.agent.tick()
    node = h.get_node()
    assert spec_matches_status(node.metadata.annotations)
    assert node.status.allocatable.get("nos.tpu/slice-2x2") == 2.0

    # now the pod schedules; the agent (kubelet sim) admits it
    assert h.scheduler.run_cycle() >= 1
    h.agent.tick()
    bound = h.api.get(KIND_POD, "train-1", "default")
    assert bound.spec.node_name == "host-0"
    assert bound.status.phase == RUNNING


def test_mixed_profile_creation_is_jointly_placed():
    # verify regression: creates must be grouped per unit so the packer
    # places 2x2 + 4x1x1 jointly (per-profile calls let 1x1s fragment the
    # block first and the 2x2 create fails)
    h = Harness()
    h.agent.tick()
    h.api.create(KIND_POD, make_slice_pod("2x2", 1, name="mid"))
    for i in range(4):
        h.api.create(KIND_POD, make_slice_pod("1x1", 1, name=f"small-{i}"))
    h.scheduler.run_cycle()
    h.advance(11.0)
    assert h.partitioner.process_if_ready()
    h.agent.tick()
    node = h.get_node()
    assert spec_matches_status(node.metadata.annotations)
    assert node.status.allocatable.get("nos.tpu/slice-2x2") == 1.0
    assert node.status.allocatable.get("nos.tpu/slice-1x1") == 4.0
    assert h.scheduler.run_cycle() == 5


def test_actuator_retries_after_create_failure():
    # verify regression: a failed plan must not be recorded as applied, or
    # the duplicate-skip guard blocks the retry forever
    h = Harness()
    h.runtime.fail_creates = True
    h.agent.tick()
    assert len(h.runtime.list_devices()) == 0
    h.runtime.fail_creates = False
    h.agent.tick()
    assert len(h.runtime.list_devices()) == 1
    assert spec_matches_status(h.get_node().metadata.annotations)


def test_repartition_preserves_used_devices():
    h = Harness()
    h.agent.tick()
    # a pod occupies a 2x4 slice
    pod = make_slice_pod("2x4", 1, name="holder")
    h.api.create(KIND_POD, pod)
    assert h.scheduler.run_cycle() == 1
    # kubelet allocates the device
    dev = h.runtime.list_devices()[0]
    h.pod_resources.allocate("default/holder", {dev.device_id})
    h.agent.tick()

    # now a 2x2 pod arrives; host is full — no repartition possible
    pod2 = make_slice_pod("2x2", 1, name="want-2x2")
    h.api.create(KIND_POD, pod2)
    assert h.scheduler.run_cycle() == 0
    h.advance(11.0)
    h.partitioner.process_if_ready()
    h.agent.tick()
    # the used 2x4 must still exist
    ids = [d.device_id for d in h.runtime.list_devices()]
    assert dev.device_id in ids
    node = h.get_node()
    status = {(a.profile, a.status): a.quantity
              for a in parse_status_annotations(node.metadata.annotations)}
    assert status.get(("2x4", "used")) == 1


def test_explain_names_rejecting_plugin_for_pending_pod(tmp_path, capsys):
    """Acceptance: `python -m nos_tpu.obs explain pod <ns>/<name>`
    reconstructs the rejection chain — plugin + reason per node — for a
    deliberately-unschedulable pod, end to end through the real
    scheduler, the flight snapshot, and the CLI."""
    import json

    from nos_tpu import obs
    from nos_tpu.obs.__main__ import main as obs_main

    h = Harness()
    h.agent.tick()                        # actuate init geometry (2x4)

    clock = [0.0]

    def tick():
        clock[0] += 1.0
        return clock[0]

    tracer = obs.Tracer(clock=tick, ring=obs.RingExporter(maxlen=256))
    journal = obs.DecisionJournal(maxlen=256, clock=tick)
    with obs.scoped(tracer, journal):
        # three 2x2 slices = 12 chips: can never fit the 8-chip host, no
        # matter how the partitioner re-carves — deliberately stuck
        h.api.create(KIND_POD, make_slice_pod("2x2", 3, name="impossible"))
        assert h.scheduler.run_cycle() == 0
        # partitioner tries (and fails) to help: the plan cycle lands in
        # the flight recorder too
        h.advance(11.0)
        h.partitioner.process_if_ready()
        assert h.scheduler.run_cycle() == 0
        snap = obs.flight_snapshot()

    path = tmp_path / "flight.json"
    path.write_text(json.dumps(snap))
    rc = obs_main(["explain", "pod", "default/impossible",
                   "--snapshot", str(path)])
    out = capsys.readouterr().out
    assert rc == 0
    # the rejection chain names the plugin and the node it rejected on
    assert "NodeResourcesFit" in out
    assert "host-0" in out
    # and the plan cycle the partitioner ran is explainable as well
    rc = obs_main(["explain", "plan", "--kind", "slice",
                   "--snapshot", str(path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "partitioner.plan_cycle" in out
    assert "planner.plan" in out
