"""Substrate contract tests: the in-memory APIServer and the production
KubeClient (REST over a kube-apiserver-shaped stub) must satisfy the
SAME assertions — the controllers cannot tell them apart (round-2
VERDICT #4; reference analog: envtest running the real API machinery).

Also covers the kubelet pod-resources gRPC client against a real grpc
server on a unix socket (pkg/resource/lister.go:28-38 analog).
"""

from __future__ import annotations

import threading
import time

import pytest

from nos_tpu.api import constants as C
from nos_tpu.api.elasticquota import ElasticQuota, ElasticQuotaSpec
from nos_tpu.api.podgroup import PodGroup, PodGroupSpec
from nos_tpu.kube.client import APIServer, Conflict, NotFound
from nos_tpu.kube.objects import ObjectMeta, PENDING, RUNNING
from nos_tpu.testing.factory import make_slice_pod, make_tpu_node

from k8s_stub import StubApiServer


@pytest.fixture(params=["memory", "rest"])
def api(request):
    if request.param == "memory":
        yield APIServer()
        return
    from nos_tpu.kube.rest import KubeClient, KubeConfig

    with StubApiServer() as stub:
        client = KubeClient(KubeConfig(server=stub.url))
        yield client
        client.close()


class TestContract:
    def test_create_get_round_trip(self, api):
        pod = make_slice_pod("2x2", 1, name="p0")
        pod.metadata.labels["team"] = "a"
        api.create("Pod", pod)
        got = api.get("Pod", "p0", "default")
        assert got.metadata.labels["team"] == "a"
        assert got.spec.containers[0].resources == \
            pod.spec.containers[0].resources
        assert got.status.phase == PENDING

    def test_create_duplicate_conflicts(self, api):
        api.create("Pod", make_slice_pod("1x1", 1, name="dup"))
        with pytest.raises(Conflict):
            api.create("Pod", make_slice_pod("1x1", 1, name="dup"))

    def test_get_missing_raises_not_found(self, api):
        with pytest.raises(NotFound):
            api.get("Pod", "ghost", "default")
        assert api.try_get("Pod", "ghost", "default") is None

    def test_patch_mutate_persists(self, api):
        api.create("Pod", make_slice_pod("1x1", 1, name="p1"))

        def mutate(p):
            p.spec.node_name = "host-3"
            p.status.phase = RUNNING

        api.patch("Pod", "p1", "default", mutate=mutate)
        got = api.get("Pod", "p1", "default")
        assert got.spec.node_name == "host-3"
        assert got.status.phase == RUNNING

    def test_delete_bumps_resource_version(self, api):
        """Deletions are mutations: rv-memoized views (the scheduler's
        cycle snapshot, the capacity plugin's nominated-pods cache) must
        invalidate on them.  Only the in-memory substrate exposes the
        counter; the REST substrate has no equivalent (callers fall back
        to listing)."""
        if not hasattr(api, "resource_version"):
            pytest.skip("REST substrate exposes no global counter")
        api.create("Pod", make_slice_pod("1x1", 1, name="rv-pod"))
        before = api.resource_version
        api.delete("Pod", "rv-pod", "default")
        assert api.resource_version > before

    def test_delete_then_not_found(self, api):
        api.create("Pod", make_slice_pod("1x1", 1, name="p2"))
        api.delete("Pod", "p2", "default")
        with pytest.raises(NotFound):
            api.get("Pod", "p2", "default")
        with pytest.raises(NotFound):
            api.delete("Pod", "p2", "default")

    def test_list_filters(self, api):
        for i in range(3):
            p = make_slice_pod("1x1", 1, name=f"l{i}")
            if i == 0:
                p.metadata.labels["pick"] = "yes"
            api.create("Pod", p)
        assert len(api.list("Pod")) == 3
        assert len(api.list("Pod", label_selector={"pick": "yes"})) == 1
        assert len(api.list("Pod", namespace="other")) == 0
        assert len(api.pods_by_phase(PENDING)) == 3

    def test_node_annotations_round_trip(self, api):
        node = make_tpu_node("host-0")
        api.create("Node", node)

        def mutate(n):
            n.metadata.annotations["nos.tpu/spec-partitioning-plan"] = "42"

        api.patch("Node", "host-0", mutate=mutate)
        got = api.get("Node", "host-0")
        assert got.metadata.annotations[
            "nos.tpu/spec-partitioning-plan"] == "42"
        assert got.metadata.labels[C.LABEL_ACCELERATOR] == "tpu-v5e"
        # quantities survive the string round trip
        assert got.status.allocatable == node.status.allocatable

    def test_crd_kinds_round_trip(self, api):
        api.create("ElasticQuota", ElasticQuota(
            metadata=ObjectMeta(name="eq", namespace="team-a"),
            spec=ElasticQuotaSpec(min={"nos.tpu/tpu-memory": 256.0},
                                  max={"nos.tpu/tpu-memory": 512.0})))
        eq = api.get("ElasticQuota", "eq", "team-a")
        assert eq.spec.min == {"nos.tpu/tpu-memory": 256.0}
        assert eq.spec.max == {"nos.tpu/tpu-memory": 512.0}

        api.create("PodGroup", PodGroup(
            metadata=ObjectMeta(name="gang", namespace="team-a"),
            spec=PodGroupSpec(min_member=4, mesh="4x8")))
        pg = api.get("PodGroup", "gang", "team-a")
        assert pg.spec.min_member == 4
        assert pg.spec.mesh == "4x8"

    def test_watch_replays_and_streams(self, api):
        api.create("Pod", make_slice_pod("1x1", 1, name="w0"))
        events: list[tuple[str, str]] = []
        seen = threading.Event()

        def fn(event, obj):
            events.append((event, obj.metadata.name))
            if ("ADDED", "w1") in events:
                seen.set()

        unsubscribe = api.watch("Pod", fn)
        try:
            # replay of the existing object is synchronous in both
            # implementations
            assert ("ADDED", "w0") in events
            api.create("Pod", make_slice_pod("1x1", 1, name="w1"))
            assert seen.wait(5.0), f"no streamed event; got {events}"
        finally:
            unsubscribe()


class TestInformerResilience:
    """The KubeClient informer against the stub's real-apiserver fault
    modes: 410 Gone mid-stream, compacted resourceVersions on reconnect,
    and abrupt connection drops.  Exactly-once per (object, rv): the
    re-list diff must recover anything missed without re-delivering what
    was already seen (VERDICT r3 missing #3 / weak #5)."""

    @pytest.fixture
    def rig(self):
        from nos_tpu.kube.rest import KubeClient, KubeConfig

        with StubApiServer() as stub:
            client = KubeClient(KubeConfig(server=stub.url))
            yield client, stub
            client.close()

    @staticmethod
    def _tracker():
        events: list[tuple[str, str, int]] = []
        cv = threading.Condition()

        def fn(event, obj):
            with cv:
                events.append((event, obj.metadata.name,
                               obj.metadata.resource_version))
                cv.notify_all()

        def wait_for(pred, timeout=8.0):
            deadline = time.monotonic() + timeout
            with cv:
                while not pred(events):
                    left = deadline - time.monotonic()
                    assert left > 0, f"timeout; events={events}"
                    cv.wait(left)
        return events, fn, wait_for

    @staticmethod
    def _assert_exactly_once(events):
        keys = [(name, rv) for _, name, rv in events]
        assert len(keys) == len(set(keys)), f"duplicate delivery: {events}"

    def test_rvs_are_non_contiguous_and_tolerated(self, rig):
        client, stub = rig
        assert stub.state.rv_stride > 1     # the stub enforces gaps
        events, fn, wait_for = self._tracker()
        client.watch("Pod", fn)
        client.create("Pod", make_slice_pod("1x1", 1, name="gap0"))
        for _ in range(3):
            client.patch("Pod", "gap0", "default",
                         mutate=lambda p: p.metadata.annotations.update(
                             {"nos.tpu/poke": str(time.monotonic())}))
        final = client.get("Pod", "gap0", "default")
        wait_for(lambda ev: any(rv == final.metadata.resource_version
                                for _, n, rv in ev if n == "gap0"))
        self._assert_exactly_once(events)

    def test_watch_survives_410_gone(self, rig):
        client, stub = rig
        events, fn, wait_for = self._tracker()
        client.create("Pod", make_slice_pod("1x1", 1, name="g0"))
        client.watch("Pod", fn)
        wait_for(lambda ev: any(n == "g0" for _, n, _ in ev))
        stub.state.compact()
        stub.state.fire_gone("pods")        # ERROR event ends the stream
        client.create("Pod", make_slice_pod("1x1", 1, name="g1"))
        wait_for(lambda ev: any(n == "g1" for _, n, _ in ev))
        self._assert_exactly_once(events)
        assert ("ADDED", "g0") == events[0][:2]

    def test_watch_survives_dropped_connection(self, rig):
        client, stub = rig
        events, fn, wait_for = self._tracker()
        client.create("Pod", make_slice_pod("1x1", 1, name="d0"))
        client.watch("Pod", fn)
        wait_for(lambda ev: any(n == "d0" for _, n, _ in ev))
        stub.state.drop_watches("pods")     # abrupt: no ERROR, no goodbye
        # mutate + add + delete while the informer is disconnected
        client.patch("Pod", "d0", "default",
                     mutate=lambda p: p.metadata.annotations.update(
                         {"nos.tpu/while-down": "1"}))
        client.create("Pod", make_slice_pod("1x1", 1, name="d1"))
        d0rv = client.get("Pod", "d0", "default").metadata.resource_version
        wait_for(lambda ev: any(n == "d1" for _, n, _ in ev)
                 and any(n == "d0" and rv == d0rv for _, n, rv in ev))
        self._assert_exactly_once(events)

    def test_watch_recovers_delete_across_drop(self, rig):
        client, stub = rig
        events, fn, wait_for = self._tracker()
        client.create("Pod", make_slice_pod("1x1", 1, name="x0"))
        client.create("Pod", make_slice_pod("1x1", 1, name="x1"))
        client.watch("Pod", fn)
        wait_for(lambda ev: len([1 for e, _, _ in ev if e == "ADDED"]) >= 2)
        stub.state.drop_watches("pods")
        client.delete("Pod", "x1", "default")
        wait_for(lambda ev: any(e == "DELETED" and n == "x1"
                                for e, n, _ in ev))
        self._assert_exactly_once(
            [e for e in events if e[0] != "DELETED"])


class TestPodResourcesClient:
    @pytest.fixture
    def kubelet(self, tmp_path):
        import grpc

        from nos_tpu.device.podresources import podresources_pb2 as api_pb2

        class Lister:
            def List(self, request, context):  # noqa: N802 — kubelet API
                return api_pb2.ListPodResourcesResponse(pod_resources=[
                    api_pb2.PodResources(
                        name="train-0", namespace="default",
                        containers=[api_pb2.ContainerResources(
                            name="main",
                            devices=[
                                api_pb2.ContainerDevices(
                                    resource_name="nos.tpu/tpu-2x2",
                                    device_ids=["tpu-0-2x2-1"]),
                                api_pb2.ContainerDevices(
                                    resource_name="google.com/tpu",
                                    device_ids=["tpu-chip-3"]),
                                api_pb2.ContainerDevices(
                                    resource_name="nvidia.com/gpu",
                                    device_ids=["gpu-9"]),
                            ])]),
                ])

        server = grpc.server(
            __import__("concurrent.futures", fromlist=["futures"])
            .ThreadPoolExecutor(max_workers=2))
        handler = grpc.method_handlers_generic_handler(
            "v1.PodResourcesLister",
            {"List": grpc.unary_unary_rpc_method_handler(
                Lister().List,
                request_deserializer=api_pb2.ListPodResourcesRequest
                .FromString,
                response_serializer=api_pb2.ListPodResourcesResponse
                .SerializeToString)})
        server.add_generic_rpc_handlers((handler,))
        sock = tmp_path / "kubelet.sock"
        server.add_insecure_port(f"unix://{sock}")
        server.start()
        yield str(sock)
        server.stop(0)

    def test_used_device_ids_filters_tpu_resources(self, kubelet):
        from nos_tpu.device.podresources import KubeletPodResourcesClient

        client = KubeletPodResourcesClient(socket_path=kubelet)
        try:
            ids = client.used_device_ids()
        finally:
            client.close()
        assert ids == {"tpu-0-2x2-1", "tpu-chip-3"}  # gpu-9 filtered

    def test_unreachable_socket_raises(self, tmp_path):
        import grpc

        from nos_tpu.device.podresources import KubeletPodResourcesClient

        client = KubeletPodResourcesClient(
            socket_path=str(tmp_path / "missing.sock"), timeout_s=0.5)
        try:
            with pytest.raises(grpc.RpcError):
                client.used_device_ids()
        finally:
            client.close()

class TestFullStackQuotaFlow:
    """VERDICT r4 #5 — the envtest analog for the quota path, in ONE
    flow on the kube-shaped stub: ElasticQuotas created through
    kube/rest.py after consulting the admission webhook over REAL TLS
    (denied duplicate never created), the scheduler rejecting an
    over-max pod, over-quota preemption evicting borrowers for a
    guaranteed-min claimant, a 410 Gone fired on the pods watch
    mid-flow, and the final bind landing via the /binding subresource
    (the stub rejects any other nodeName write)."""

    def _make_certs(self, tmp_path):
        import subprocess

        crt, key = tmp_path / "tls.crt", tmp_path / "tls.key"
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(key), "-out", str(crt), "-days", "1",
             "-subj", "/CN=localhost",
             "-addext", "subjectAltName=DNS:localhost"],
            check=True, capture_output=True)
        return str(crt), str(key)

    def test_eq_tls_admission_quota_preempt_bind(self, tmp_path):
        import json as _json
        import ssl
        import urllib.request

        from nos_tpu.api.config import PartitionerConfig
        from nos_tpu.api.elasticquota import validate_elastic_quota
        from nos_tpu.cmd.assembly import (
            build_partitioner_main, build_scheduler,
        )
        from nos_tpu.controllers.elasticquota.controller import (
            ElasticQuotaReconciler,
        )
        from nos_tpu.controllers.sliceagent.agent import SliceAgent
        from nos_tpu.device.fake import FakePodResources, FakeTpuRuntime
        from nos_tpu.kube.k8s_codec import from_k8s
        from nos_tpu.kube.rest import KubeClient, KubeConfig
        from nos_tpu.kube.webhook import AdmissionHandler, WebhookServer
        from nos_tpu.partitioning.state import ClusterState

        crt, key = self._make_certs(tmp_path)
        with StubApiServer() as stub:
            api = KubeClient(KubeConfig(server=stub.url))
            handler = AdmissionHandler(api)
            handler.register("ElasticQuota", validate_elastic_quota)
            webhook = WebhookServer(handler, host="127.0.0.1", port=0,
                                    cert_file=crt, key_file=key)
            webhook.start()
            ctx = ssl.create_default_context(cafile=crt)
            ctx.check_hostname = False

            def consult_then_create(raw: dict) -> bool:
                """What the kube-apiserver does: POST the AdmissionReview
                to the TLS endpoint; persist only when allowed."""
                review = _json.dumps({
                    "apiVersion": "admission.k8s.io/v1",
                    "kind": "AdmissionReview",
                    "request": {"uid": "u", "operation": "CREATE",
                                "kind": {"kind": "ElasticQuota"},
                                "object": raw}}).encode()
                req = urllib.request.Request(
                    f"https://127.0.0.1:{webhook.port}/validate-elasticquota",
                    data=review,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=10,
                                            context=ctx) as r:
                    allowed = _json.loads(
                        r.read())["response"]["allowed"]
                if allowed:
                    api.create("ElasticQuota",
                               from_k8s("ElasticQuota", raw))
                return allowed

            def eq_raw(name, ns, min_gb, max_gb):
                return {"metadata": {"name": name, "namespace": ns},
                        "spec": {"min": {C.RESOURCE_TPU_MEMORY: min_gb},
                                 "max": {C.RESOURCE_TPU_MEMORY: max_gb}}}

            # quotas in through the TLS-validated path
            assert consult_then_create(eq_raw("qa", "team-a", 32, 128))
            assert consult_then_create(eq_raw("qb", "team-b", 64, 128))
            # duplicate in team-a: DENIED over TLS, never persisted
            assert not consult_then_create(eq_raw("qa2", "team-a", 8, 8))
            assert len(api.list("ElasticQuota", namespace="team-a")) == 1

            cfg = PartitionerConfig(batch_timeout_s=0.4, batch_idle_s=0.1,
                                    poll_interval_s=0.02)
            main, _ = build_partitioner_main(api, ClusterState(), cfg)
            api.create("Node", make_tpu_node("host-0"))
            agent = SliceAgent(api, "host-0", FakeTpuRuntime(),
                               FakePodResources())
            agent.start()
            main.add_loop("sliceagent", agent.tick, 0.02)
            scheduler = build_scheduler(api)
            main.add_loop("scheduler", scheduler.run_cycle, 0.02)
            eq_rec = ElasticQuotaReconciler(api)
            main.add_loop("eq-reconciler", eq_rec.reconcile_all, 0.05)
            main.start()
            try:
                # 90 s envelope: standalone convergence is ~3 s, but
                # the whole control plane, the TLS webhook, AND the
                # apiserver stub share this process's GIL, so a loaded
                # CI box stretches it substantially
                def wait(pred, what, timeout=90.0):
                    deadline = time.monotonic() + timeout
                    while time.monotonic() < deadline:
                        if pred():
                            return
                        time.sleep(0.05)
                    raise AssertionError(f"timeout waiting for {what}")

                # team-a floods: 3 x 1x2 = 96 GB used — exactly the
                # aggregate min (32+64), the borrowing ceiling.  min-a is
                # 32, so the reconciler labels the tail over-quota.
                for i in range(3):
                    api.create("Pod", make_slice_pod(
                        "1x2", 1, name=f"a-{i}", namespace="team-a"))
                wait(lambda: sum(
                    1 for p in api.list("Pod", namespace="team-a")
                    if p.status.phase == RUNNING) == 3,
                    "team-a flood to run")
                wait(lambda: any(
                    p.metadata.labels.get(C.LABEL_CAPACITY)
                    == "over-quota"
                    for p in api.list("Pod", namespace="team-a")),
                    "over-quota labels")

                # scheduler quota REJECT: a-3 would push the aggregate
                # past the summed min — no preemption can help a
                # borrower, it just stays pending with the quota verdict
                api.create("Pod", make_slice_pod(
                    "1x2", 1, name="a-3", namespace="team-a"))
                wait(lambda: (lambda p: p is not None
                              and p.is_unschedulable())(
                        api.try_get("Pod", "a-3", "team-a")),
                     "quota rejection")
                p = api.try_get("Pod", "a-3", "team-a")
                msgs = " ".join(c.message or "" for c in
                                p.status.conditions)
                assert "quota" in msgs, msgs

                # real-apiserver fault mid-flow: pods watch gets 410
                # Gone; informers must re-list and carry on
                stub.state.fire_gone("pods")

                # team-b claims its guaranteed min: over-quota borrowers
                # are preempted, b-0 eventually binds via /binding
                api.create("Pod", make_slice_pod(
                    "1x2", 1, name="b-0", namespace="team-b"))
                wait(lambda: (lambda p: p is not None
                              and p.spec.node_name
                              and p.status.phase == RUNNING)(
                        api.try_get("Pod", "b-0", "team-b")),
                     "preemption + bind of b-0")
                survivors = [p.metadata.name for p in
                             api.list("Pod", namespace="team-a")
                             if p.status.phase == RUNNING
                             and p.spec.node_name]
                assert len(survivors) < 3, \
                    "no borrower was evicted for the min claimant"
            finally:
                main.shutdown()
                webhook.stop()
                api.close()


class TestControlPlaneOverRest:
    """The crown-jewel contract: the full control plane (partitioner +
    scheduler + sliceagent) converges a pending pod to bound while every
    interaction crosses the REST substrate — the envtest analog
    (reference internal/controllers/*/suite_int_test.go)."""

    def test_pending_pod_binds_over_rest(self):
        import time as _time

        from nos_tpu.api.config import PartitionerConfig
        from nos_tpu.cmd.assembly import build_partitioner_main, \
            build_scheduler
        from nos_tpu.controllers.sliceagent.agent import SliceAgent
        from nos_tpu.device.fake import FakePodResources, FakeTpuRuntime
        from nos_tpu.kube.rest import KubeClient, KubeConfig
        from nos_tpu.partitioning.state import ClusterState

        with StubApiServer() as stub:
            api = KubeClient(KubeConfig(server=stub.url))
            cfg = PartitionerConfig(batch_timeout_s=0.4, batch_idle_s=0.1,
                                    poll_interval_s=0.02)
            main, _ = build_partitioner_main(api, ClusterState(), cfg)
            api.create("Node", make_tpu_node("host-0"))
            agent = SliceAgent(api, "host-0", FakeTpuRuntime(),
                               FakePodResources())
            agent.start()
            main.add_loop("sliceagent", agent.tick, 0.02)
            scheduler = build_scheduler(api)
            main.add_loop("scheduler", scheduler.run_cycle, 0.02)
            main.start()
            try:
                api.create("Pod", make_slice_pod("2x2", 1, name="job-0"))
                deadline = _time.monotonic() + 30.0
                while _time.monotonic() < deadline:
                    p = api.get("Pod", "job-0", "default")
                    if p.spec.node_name and p.status.phase == RUNNING:
                        break
                    _time.sleep(0.05)
                else:
                    raise AssertionError(
                        "pod did not bind over the REST substrate")
                node = api.get("Node", "host-0")
                status_anns = [k for k in node.metadata.annotations
                               if "status-tpu" in k]
                assert status_anns, "agent never reported over REST"
            finally:
                main.shutdown()
                api.close()
