#!/usr/bin/env bash
# Dev-cluster on-ramp (the reference ships hack/kind as the contributor
# entry point; this is the nos-tpu analog).  Two modes:
#
#   ./hack/dev-cluster.sh up       create a 3-node kind cluster
#                                  (hack/kind/cluster.yaml), install the
#                                  CRDs and the rendered chart, wait for
#                                  the control plane.  Needs kind+kubectl.
#   ./hack/dev-cluster.sh render   render-and-validate only: produce the
#                                  manifests `up` would apply and check
#                                  every ConfigMap through the typed
#                                  config loaders.  Needs only python3 —
#                                  works in this repo's CI image, which
#                                  has no cluster binaries.
#   ./hack/dev-cluster.sh down     delete the kind cluster.
#
# `render` is the CI-enforced half: it runs in environments without
# kind, so the manifests stay valid even where `up` cannot execute.
set -euo pipefail
cd "$(dirname "$0")/.."

CLUSTER=nos-tpu-dev
OUT="${OUT:-/tmp/nos-tpu-rendered}"

render() {
    python3 hack/render-chart.py --out "$OUT"
}

case "${1:-render}" in
  render)
    render
    ;;
  up)
    command -v kind >/dev/null || {
        echo "kind not found — run './hack/dev-cluster.sh render' for the \
no-binaries mode" >&2; exit 1; }
    command -v kubectl >/dev/null || { echo "kubectl not found" >&2; exit 1; }
    command -v docker >/dev/null || { echo "docker not found" >&2; exit 1; }
    render
    # Build the component images under the chart's default names and
    # side-load them into kind (nothing is published at the default
    # registry; imagePullPolicy IfNotPresent then uses the loaded
    # copies).  SKIP_BUILD=1 reuses images from a previous run.
    REGISTRY=ghcr.io/nos-tpu
    TAG=0.3.0
    COMPONENTS="operator partitioner scheduler sliceagent chipagent \
metricsexporter"
    if [ -z "${SKIP_BUILD:-}" ]; then
        docker build -f build/Dockerfile.base -t nos-tpu-base:latest .
        for c in $COMPONENTS; do
            docker build -f "build/$c/Dockerfile" \
                -t "$REGISTRY/nos-tpu-$c:$TAG" \
                --build-arg BASE_IMAGE=nos-tpu-base:latest .
        done
    fi
    kind create cluster --name "$CLUSTER" --config hack/kind/cluster.yaml
    for c in $COMPONENTS; do
        kind load docker-image --name "$CLUSTER" "$REGISTRY/nos-tpu-$c:$TAG"
    done
    kubectl apply -f deploy/helm/nos-tpu/crds/
    kubectl apply -f "$OUT/nos-tpu.yaml"
    kubectl -n nos-tpu-system wait --for=condition=Available deployment \
        --all --timeout=300s
    echo "nos-tpu dev cluster '$CLUSTER' is up; try:"
    echo "  kubectl -n nos-tpu-system get pods"
    echo "  # then create the example ElasticQuotas from docs/quotas.md"
    ;;
  down)
    kind delete cluster --name "$CLUSTER"
    ;;
  *)
    echo "usage: $0 {up|render|down}" >&2
    exit 2
    ;;
esac
