#!/usr/bin/env python3
"""Render the nos-tpu helm chart WITHOUT helm and validate the output:
every manifest parses, every rendered ConfigMap round-trips through the
typed config loaders (a config the binaries would reject fails the
render), and the CRDs are well-formed.

    python3 hack/render-chart.py            # validate, print summary
    python3 hack/render-chart.py --out DIR  # also write manifests

Shares the renderer with tests/test_deploy.py
(nos_tpu/testing/helm.py) so hack and CI can never disagree about what
the chart renders to.
"""

from __future__ import annotations

import argparse
import pathlib
import sys
import tempfile

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

CHART = ROOT / "deploy/helm/nos-tpu"
CRD_DIR = CHART / "crds"

CONFIG_KINDS = {
    "nos-tpu-scheduler-config": "SchedulerConfig",
    "nos-tpu-operator-config": "OperatorConfig",
    "nos-tpu-partitioner-config": "PartitionerConfig",
    "nos-tpu-sliceagent-config": "AgentConfig",
    "nos-tpu-chipagent-config": "AgentConfig",
}


def main() -> int:
    import yaml

    from nos_tpu.api import config as cfg_mod
    from nos_tpu.api.config import load_config
    from nos_tpu.testing.helm import render_chart

    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--out", default=None,
                    help="directory to write rendered manifests into")
    args = ap.parse_args()

    docs = render_chart(CHART)
    crds = [yaml.safe_load(p.read_text())
            for p in sorted(CRD_DIR.glob("*.yaml"))]
    configs_checked = 0
    for doc in docs:
        if doc.get("kind") != "ConfigMap":
            continue
        name = doc["metadata"]["name"]
        cls_name = CONFIG_KINDS.get(name)
        if cls_name is None or "config.yaml" not in doc.get("data", {}):
            continue
        cls = getattr(cfg_mod, cls_name)
        with tempfile.NamedTemporaryFile("w", suffix=".yaml") as f:
            f.write(doc["data"]["config.yaml"])
            f.flush()
            # agent configs validate node_name at runtime (--node)
            load_config(f.name, cls, validate=cls_name != "AgentConfig")
        configs_checked += 1

    if args.out:
        out = pathlib.Path(args.out)
        out.mkdir(parents=True, exist_ok=True)
        with open(out / "nos-tpu.yaml", "w") as f:
            yaml.safe_dump_all(docs, f, sort_keys=False)
        with open(out / "crds.yaml", "w") as f:
            yaml.safe_dump_all(crds, f, sort_keys=False)
        print(f"wrote {out}/nos-tpu.yaml + {out}/crds.yaml")

    kinds: dict[str, int] = {}
    for doc in docs:
        kinds[doc["kind"]] = kinds.get(doc["kind"], 0) + 1
    print(f"rendered {len(docs)} manifests from {CHART.name}: "
          + ", ".join(f"{k} x{v}" for k, v in sorted(kinds.items())))
    print(f"validated {configs_checked} ConfigMaps through the typed "
          f"loaders; {len(crds)} CRDs parsed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
