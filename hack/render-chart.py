#!/usr/bin/env python3
"""Render the nos-tpu helm chart WITHOUT helm and validate the output:
every manifest parses, every rendered ConfigMap round-trips through the
typed config loaders (a config the binaries would reject fails the
render), and the CRDs are well-formed.

    python3 hack/render-chart.py            # validate, print summary
    python3 hack/render-chart.py --out DIR  # also write manifests

Shares the renderer with tests/test_deploy.py
(nos_tpu/testing/helm.py) so hack and CI can never disagree about what
the chart renders to.
"""

from __future__ import annotations

import argparse
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

CHART = ROOT / "deploy/helm/nos-tpu"
CRD_DIR = CHART / "crds"


def main() -> int:
    import yaml

    from nos_tpu.testing.helm import render_chart, validate_configmaps

    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--out", default=None,
                    help="directory to write rendered manifests into")
    args = ap.parse_args()

    docs = render_chart(CHART)
    crds = [yaml.safe_load(p.read_text())
            for p in sorted(CRD_DIR.glob("*.yaml"))]
    configs_checked = validate_configmaps(docs)

    if args.out:
        out = pathlib.Path(args.out)
        out.mkdir(parents=True, exist_ok=True)
        with open(out / "nos-tpu.yaml", "w") as f:
            yaml.safe_dump_all(docs, f, sort_keys=False)
        with open(out / "crds.yaml", "w") as f:
            yaml.safe_dump_all(crds, f, sort_keys=False)
        print(f"wrote {out}/nos-tpu.yaml + {out}/crds.yaml")

    kinds: dict[str, int] = {}
    for doc in docs:
        kinds[doc["kind"]] = kinds.get(doc["kind"], 0) + 1
    print(f"rendered {len(docs)} manifests from {CHART.name}: "
          + ", ".join(f"{k} x{v}" for k, v in sorted(kinds.items())))
    print(f"validated {configs_checked} ConfigMaps through the typed "
          f"loaders; {len(crds)} CRDs parsed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
