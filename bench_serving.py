"""Serving bench: the latency-SLO inference tier under bursty diurnal
load, with batch soaking every idle chip (ROADMAP item 2, docs/serving.md).

Cluster: 12 slice hosts in one v5e ICI domain (pod-0) plus 2 timeshare
hosts — 112 chips, 1792 GB HBM.  Two inference services run through the
REAL control plane (scheduler built by cmd/assembly.build_scheduler,
slice + timeshare partitioners, node agents, EQ reconcilers, the
serving replica autoscaler):

    chat    slice-1x1 replicas, band [2, 12], 8 requests-in-flight each
    embed   tpu-8gb timeshare replicas, band [1, 8], 16 each

Load is a deterministic bursty diurnal request stream
(nos_tpu/serving/trace.py): each tick the trace's requests-in-flight is
split across the live replicas and self-reported on the replica pods
via the nos.tpu/serving-load annotation (retry-wrapped writes); the
autoscaler reconciles against that signal with hysteresis + cooldown.

Batch (namespace `batch`, tier label absent = batch) trains 2x4/2x2
jobs inside its quota min; a best-effort FILLER namespace keeps a
backlog of single-chip and 8gb time-share scavengers — sized exactly
like the serving units — that soak every idle chip while running far
over their small min.  Those fillers are permanently over-quota-labeled
and first in the tier-ordered victim walk, so a serving burst always
reclaims units of the right shape in milliseconds.  Quota mins sum to
cluster HBM (borrowing redistributes real headroom); `serve`'s min is
its guaranteed scale-out share, larger than its typical footprint:

    serve       min  640  max  896
    batch       min  768  max 1024
    besteffort  min  384  max 1792

Falsifiable serving invariants, judged by the PR 8 SLO engine plus
direct counters:

  - schedule_latency{class=serving} p99 < 100 ms (SLOObjective target
    0.1 s, compliance 0.99) — a serving replica binds within 1-2
    scheduler cycles because over-quota batch is preempted on its
    behalf (tier-aware victim ordering) onto ALREADY-CARVED units;
  - ZERO serving pods preempted: no preemption victim ever carries the
    serving tier (capacityscheduling excludes them; the on_preempt
    observer convicts any exception);
  - autoscaler tracking: replicas follow clamp(ceil(load/target))
    within one replica for >= 90% of post-warmup samples, without
    flapping (cooldown-bounded scale events);
  - utilization >= 0.95 held while all of the above holds.

Time is virtual (0.04 s ticks — two scheduler cycles fit under the
100 ms serving budget); the 240 s trace runs in well under a minute of
wall clock.
"""

from __future__ import annotations

import argparse
import math
import random
import time

from nos_tpu.api import constants as C
from nos_tpu.api.elasticquota import (
    ElasticQuota, ElasticQuotaSpec, install_quota_webhooks,
)
from nos_tpu.cmd.assembly import build_scheduler
from nos_tpu.controllers.chipagent import ChipAgent
from nos_tpu.controllers.elasticquota.controller import (
    ElasticQuotaReconciler,
)
from nos_tpu.controllers.node_controller import NodeController
from nos_tpu.controllers.pod_controller import PodController
from nos_tpu.controllers.sliceagent.agent import SliceAgent
from nos_tpu.device import default_tpu_runtime
from nos_tpu.device.fake import FakePodResources
from nos_tpu.exporter.metrics import REGISTRY
from nos_tpu.kube.client import (
    APIServer, KIND_ELASTIC_QUOTA, KIND_NODE, KIND_POD, NotFound,
)
from nos_tpu.kube.objects import ObjectMeta, PENDING, RUNNING
from nos_tpu.kube.resources import pod_request
from nos_tpu.obs.slo import GAUGE_FLOOR, LATENCY, SLOEngine, SLOObjective
from nos_tpu.obs.timeseries import TimeSeriesSampler
from nos_tpu.partitioning.slicepart import SliceNodeInitializer
from nos_tpu.partitioning.slicepart.factory import new_slice_partitioner_controller
from nos_tpu.partitioning.state import ClusterState
from nos_tpu.sim import SimEngine, emit, write_report
from nos_tpu.partitioning.timeshare.factory import new_timeshare_partitioner_controller
from nos_tpu.quota import TPUResourceCalculator
from nos_tpu.scheduler.capacityscheduling import CapacityScheduling
from nos_tpu.serving import DiurnalTrace, ReplicaAutoscaler, ServingService
from nos_tpu.testing.factory import make_slice_pod, make_timeshare_pod, make_tpu_node
from nos_tpu.topology import V5E
from nos_tpu.topology.profile import extract_slice_requests, extract_timeshare_requests
from nos_tpu.utils.pod_util import workload_tier
from nos_tpu.utils.retry import retry_on_conflict

SLICE_HOSTS = 12
TS_HOSTS = 2
CHIPS_PER_HOST = V5E.chips_per_host          # 8
HBM_GB = 16
TOTAL_CHIPS = (SLICE_HOSTS + TS_HOSTS) * CHIPS_PER_HOST

TICK_S = 0.04
WARMUP_S = 40.0
TRACE_S = 240.0
BATCH_IDLE_S = 0.5
BATCH_TIMEOUT_S = 2.0
STAMP_EVERY_TICKS = 5       # load-signal refresh period (0.2 s)
UTILIZATION_TARGET = 0.95
SERVING_P99_TARGET_S = 0.1

# Quota layout: mins sum to cluster HBM capacity (the aggregate-min
# PreFilter gate equals physical capacity, so borrowing redistributes
# real headroom).  `serve`'s min is its guaranteed SCALE-OUT share —
# deliberately larger than its typical footprint; the `besteffort`
# FILLER namespace (tier best-effort, units sized like the serving
# replicas) soaks everything idle while running far over its small min,
# so its pods are PERMANENTLY over-quota-labeled: a serving burst
# always finds reclaimable victims of the right shape, first in the
# tier-ordered victim walk (the PAPER.md ElasticQuota borrow/reclaim
# posture, pointed at a scavenger tier).  `batch` proper (2x4/2x2
# training jobs) sits inside its min and is rarely touched.
QUOTAS = {
    "serve": {"min": 640.0, "max": 896.0},
    "batch": {"min": 768.0, "max": 1024.0},
    "besteffort": {"min": 384.0, "max": 1792.0},
}

SERVICES = (
    ServingService(name="chat", namespace="serve", slice_shape="1x1",
                   min_replicas=2, max_replicas=12,
                   target_load_per_replica=8.0,
                   scale_up_cooldown_s=0.2, scale_down_cooldown_s=10.0,
                   down_hysteresis=0.2),
    ServingService(name="embed", namespace="serve", timeshare_gb=8,
                   min_replicas=1, max_replicas=8,
                   target_load_per_replica=16.0,
                   scale_up_cooldown_s=0.2, scale_down_cooldown_s=12.0,
                   down_hysteresis=0.2),
)


def make_traces(seed: int) -> dict[str, DiurnalTrace]:
    """Per-service load curves: compressed diurnal period, millions of
    users at peak, seeded bursts (distinct sub-seeds so the services'
    bursts are uncorrelated, like real fleets)."""
    return {
        "serve/chat": DiurnalTrace(
            seed=seed * 7 + 1, period_s=120.0,
            base_users=400_000.0, peak_users=3_200_000.0,
            requests_per_user_per_s=2e-5, service_time_s=0.5,
            burst_rate_per_s=1.0 / 40.0, burst_multiplier=3.0,
            burst_duration_s=8.0),
        "serve/embed": DiurnalTrace(
            seed=seed * 7 + 2, period_s=150.0, phase_s=60.0,
            base_users=800_000.0, peak_users=4_800_000.0,
            requests_per_user_per_s=1e-5, service_time_s=1.0,
            burst_rate_per_s=1.0 / 55.0, burst_multiplier=2.5,
            burst_duration_s=10.0),
    }


# Workload mixes.  Batch proper trains on 2x4/2x2 slices inside its
# quota min; the best-effort FILLERS are sized exactly like the serving
# units (1x1 slices, 8gb time-share — ONE unit economy, so no
# device-plugin re-provision ever sits on the serving hot path) and
# their namespace runs far over its min: always labeled over-quota,
# always reclaimable, first in the tier-ordered victim walk.
BATCH_SLICE_MIX = [("2x4", 2.0), ("2x2", 2.0)]
BESTEFFORT_MIX = [("1x1", 1.0)]
BESTEFFORT_TS_MIX = [(8, 1.0)]
BATCH_TARGET_CHIPS = 20.0       # pending batch chip-equivalents
BESTEFFORT_TARGET = 28.0        # pending filler chip-equivalents
BESTEFFORT_TS_TARGET = 8.0
DURATION_S = {"batch": (20.0, 45.0), "besteffort": (8.0, 20.0)}
TS_DURATION_S = (12.0, 30.0)

SLO_FAST_WINDOW_S = 10.0
SLO_SLOW_WINDOW_S = 40.0
# the smoke run drops this to 1: its shortened trace sees only a
# handful of serving binds per window, and the gate must judge a REAL
# verdict (value populated), not a vacuous not-yet-observable one
SERVING_MIN_EVENTS = 5


def slo_objectives() -> list[SLOObjective]:
    return [
        # THE serving promise: p99 schedule latency in milliseconds.
        SLOObjective(name="serving-schedule-latency", kind=LATENCY,
                     metric="nos_tpu_schedule_latency_seconds",
                     target=SERVING_P99_TARGET_S,
                     labels={"class": "serving"},
                     compliance=0.99, quantile=0.99,
                     min_events=SERVING_MIN_EVENTS),
        # batch classes keep their (much looser) per-class envelope
        SLOObjective(name="schedule-latency", kind=LATENCY,
                     metric="nos_tpu_schedule_latency_seconds",
                     target=60.0, each_label="class", compliance=0.9,
                     min_events=5),
        SLOObjective(name="utilization-floor", kind=GAUGE_FLOOR,
                     metric="nos_tpu_cluster_utilization",
                     target=0.5, compliance=0.9),
    ]


def percentile(xs, q: float, digits: int):
    if not xs:
        return None
    xs = sorted(xs)
    return round(xs[min(len(xs) - 1, int(q * len(xs)))], digits)


def chip_equiv(pod) -> float:
    req = pod_request(pod)
    chips = sum(min(s.chips, CHIPS_PER_HOST) * q
                for s, q in extract_slice_requests(req).items())
    gb = sum(g * q for g, q in extract_timeshare_requests(req).items())
    return chips + gb / HBM_GB


class Job:
    def __init__(self, name: str, namespace: str, pod: str,
                 duration: float, created: float) -> None:
        self.name = name
        self.namespace = namespace
        self.pod = pod
        self.duration = duration
        self.created = created
        self.bound_at: float | None = None


class Sim:
    def __init__(self, seed: int = 0) -> None:
        self.rng = random.Random(seed)
        self.seed = seed
        self.eng = SimEngine()
        clock = self.eng.now
        api = self.api = APIServer()
        state = ClusterState()
        install_quota_webhooks(api)
        NodeController(api, state, SliceNodeInitializer(api)).bind()
        PodController(api, state).bind()
        self.slice_ctl = new_slice_partitioner_controller(
            api, state, batch_timeout_s=BATCH_TIMEOUT_S,
            batch_idle_s=BATCH_IDLE_S, clock=clock)
        self.slice_ctl.bind()
        self.ts_ctl = new_timeshare_partitioner_controller(
            api, state, batch_timeout_s=BATCH_TIMEOUT_S,
            batch_idle_s=BATCH_IDLE_S, clock=clock)
        self.ts_ctl.bind()

        self.calculator = TPUResourceCalculator(
            HBM_GB, chips_per_host=CHIPS_PER_HOST)
        for ns, q in QUOTAS.items():
            api.create(KIND_ELASTIC_QUOTA, ElasticQuota(
                metadata=ObjectMeta(name=ns, namespace=ns),
                spec=ElasticQuotaSpec(
                    min={C.RESOURCE_TPU_MEMORY: q["min"]},
                    max={C.RESOURCE_TPU_MEMORY: q["max"]})))
        self.eq_reconciler = ElasticQuotaReconciler(api, self.calculator)

        self.agents: dict[str, object] = {}
        for h in range(SLICE_HOSTS):
            name = f"host-{h}"
            api.create(KIND_NODE, make_tpu_node(
                name, pod_id="pod-0", host_index=h))
            agent = SliceAgent(api, name, default_tpu_runtime(V5E),
                               FakePodResources())
            agent.start()
            self.agents[name] = agent
        for t in range(TS_HOSTS):
            name = f"ts-{t}"
            api.create(KIND_NODE, make_tpu_node(
                name, partitioning="timeshare", pod_id="", host_index=t))
            agent = ChipAgent(api, name)
            agent.start()
            self.agents[name] = agent

        # preempt budget 4: a burst can ask for several replicas in one
        # cycle, and each unschedulable replica spends one PostFilter
        self.scheduler = build_scheduler(
            api, HBM_GB, shard_chips_per_host=CHIPS_PER_HOST,
            preempt_budget_per_cycle=4, clock=clock)
        self.autoscaler = ReplicaAutoscaler(api, SERVICES, clock=clock)
        self.traces = make_traces(seed)
        self.slo_engine = SLOEngine(
            TimeSeriesSampler(clock=clock, maxlen=4096),
            slo_objectives(),
            fast_window_s=SLO_FAST_WINDOW_S,
            slow_window_s=SLO_SLOW_WINDOW_S, clock=clock)
        self.capacity: CapacityScheduling = next(
            p for p in self.scheduler._framework.plugins
            if isinstance(p, CapacityScheduling))
        self.capacity.on_preempt = self._on_preempt

        self.jobs: dict[str, Job] = {}
        self._job_seq = 0
        self._pod_job: dict[str, Job] = {}
        # serving bookkeeping
        self.serving_latencies: list[float] = []
        self._serving_seen: set[str] = set()
        self.serving_preempted = 0
        self.preemptions = 0
        self.preempted_pods = 0
        self.replica_series: dict[str, list[tuple[float, float, int, int]]] = {
            svc.key: [] for svc in SERVICES}
        self.batch_latencies: list[float] = []
        self.cycle_wall_ms: list[float] = []
        self._util_area = 0.0
        self._util_time = 0.0
        self._batch_util_area = 0.0
        self.completed = 0
        # every spawned batch pod's request, cached for honest requeue
        self._job_requests: dict[str, dict] = {}
        self.api.watch(KIND_POD, self._cache_request)

    # -- observers ----------------------------------------------------------
    def _on_preempt(self, preemptor, victims) -> None:
        self.preemptions += 1
        self.preempted_pods += len(victims)
        for v in victims:
            if workload_tier(v) == C.TIER_SERVING:
                self.serving_preempted += 1

    # -- batch trace --------------------------------------------------------
    def _spawn_job(self, ns: str, kind: str, arg, lo: float, hi: float,
                   tier: str = "") -> float:
        self._job_seq += 1
        name = f"{ns}-j{self._job_seq}"
        labels = {C.LABEL_TIER: tier} if tier else None
        if kind == "ts":
            pod = make_timeshare_pod(arg, 1, name=name, namespace=ns,
                                     labels=labels,
                                     creation_timestamp=self.eng.now())
        else:
            pod = make_slice_pod(arg, 1, name=name, namespace=ns,
                                 labels=labels,
                                 creation_timestamp=self.eng.now())
        self.api.create(KIND_POD, pod)
        job = Job(name, ns, name, self.rng.uniform(lo, hi), self.eng.now())
        self.jobs[name] = job
        self._pod_job[name] = job
        return chip_equiv(pod)

    def _spawn(self) -> None:
        backlog = {"batch": 0.0, "besteffort": 0.0, "besteffort-ts": 0.0}
        for p in self.api.list(KIND_POD):
            if p.spec.node_name or p.metadata.namespace not in (
                    "batch", "besteffort"):
                continue
            req = pod_request(p)
            key = p.metadata.namespace
            if key == "besteffort" and extract_timeshare_requests(req):
                key = "besteffort-ts"
            backlog[key] += chip_equiv(p)
        lo, hi = DURATION_S["batch"]
        while backlog["batch"] < BATCH_TARGET_CHIPS:
            shape = self.rng.choices(
                [m[0] for m in BATCH_SLICE_MIX],
                [m[1] for m in BATCH_SLICE_MIX])[0]
            backlog["batch"] += self._spawn_job(
                "batch", "slice", shape, lo, hi)
        be_lo, be_hi = DURATION_S["besteffort"]
        while backlog["besteffort"] < BESTEFFORT_TARGET:
            shape = self.rng.choices(
                [m[0] for m in BESTEFFORT_MIX],
                [m[1] for m in BESTEFFORT_MIX])[0]
            backlog["besteffort"] += self._spawn_job(
                "besteffort", "slice", shape, be_lo, be_hi,
                tier=C.TIER_BEST_EFFORT)
        ts_lo, ts_hi = TS_DURATION_S
        while backlog["besteffort-ts"] < BESTEFFORT_TS_TARGET:
            gb = self.rng.choices(
                [m[0] for m in BESTEFFORT_TS_MIX],
                [m[1] for m in BESTEFFORT_TS_MIX])[0]
            backlog["besteffort-ts"] += self._spawn_job(
                "besteffort", "ts", gb, ts_lo, ts_hi,
                tier=C.TIER_BEST_EFFORT)

    def _complete_finished(self) -> None:
        for job in list(self.jobs.values()):
            if job.bound_at is None \
                    or self.eng.now() < job.bound_at + job.duration:
                continue
            try:
                self.api.delete(KIND_POD, job.pod, job.namespace)
            except NotFound:
                pass
            self._pod_job.pop(job.pod, None)
            del self.jobs[job.name]
            self.completed += 1

    def _requeue_evicted(self) -> None:
        """Preempted batch/best-effort jobs requeue from scratch with
        their ORIGINAL creation timestamps (honest latency accounting,
        exactly as bench_utilization does)."""
        live = {p.metadata.name for p in self.api.list(KIND_POD)}
        for job in self.jobs.values():
            if job.pod in live:
                continue
            job.bound_at = None
            pod = self._requeued_pod(job)
            if pod is not None:
                self.api.create(KIND_POD, pod)

    def _requeued_pod(self, job: Job):
        """Rebuild a victim's pod from the request cached at spawn
        (same name/namespace/ORIGINAL timestamp: its eventual latency
        includes the wasted run)."""
        req = self._job_requests.get(job.pod)
        if req is None:
            return None
        from nos_tpu.kube.objects import Container, Pod, PodSpec, PodStatus

        labels = ({C.LABEL_TIER: C.TIER_BEST_EFFORT}
                  if job.namespace == "besteffort" else {})
        return Pod(
            metadata=ObjectMeta(name=job.pod, namespace=job.namespace,
                                labels=labels,
                                creation_timestamp=job.created),
            spec=PodSpec(containers=[Container(resources=dict(req))]),
            status=PodStatus(phase=PENDING))

    # -- serving ------------------------------------------------------------
    def _stamp_loads(self) -> None:
        """Split each service's requests-in-flight across its live
        replicas and self-report via the load annotation (retry-wrapped
        writes — the downward-API pattern)."""
        for svc in SERVICES:
            demand = self.traces[svc.key].load_at(self.eng.now())
            replicas = self.api.list(
                KIND_POD, namespace=svc.namespace,
                label_selector={C.LABEL_SERVICE: svc.name},
                filter_fn=lambda p: p.status.phase in (PENDING, RUNNING))
            if not replicas:
                continue
            share = demand / len(replicas)

            def mutate(p) -> None:
                p.metadata.annotations[C.ANNOT_SERVING_LOAD] = \
                    f"{share:.3f}"
            for p in replicas:
                try:
                    retry_on_conflict(self.api, KIND_POD,
                                      p.metadata.name, mutate,
                                      p.metadata.namespace,
                                      component="serving-load")
                except NotFound:
                    pass        # scaled down mid-stamp

    def _record_serving_binds(self) -> None:
        for svc in SERVICES:
            for p in self.api.list(
                    KIND_POD, namespace=svc.namespace,
                    label_selector={C.LABEL_SERVICE: svc.name}):
                if not p.spec.node_name \
                        or p.metadata.name in self._serving_seen:
                    continue
                self._serving_seen.add(p.metadata.name)
                if self.eng.now() < WARMUP_S:
                    # cold-start provisioning (the first carve of an
                    # empty cluster) is not a serving-SLO event — the
                    # SLO engine's windows start at warmup too
                    continue
                self.serving_latencies.append(
                    self.eng.now() - p.metadata.creation_timestamp)

    def _record_batch_binds(self) -> None:
        bound = {p.metadata.name for p in self.api.list(KIND_POD)
                 if p.spec.node_name and p.status.phase == RUNNING}
        for job in self.jobs.values():
            if job.bound_at is None and job.pod in bound:
                job.bound_at = self.eng.now()
                self.batch_latencies.append(self.eng.now() - job.created)

    def _track_replicas(self) -> None:
        for svc in SERVICES:
            load = self.traces[svc.key].load_at(self.eng.now())
            desired = min(svc.max_replicas, max(
                svc.min_replicas,
                math.ceil(load / svc.target_load_per_replica)))
            live = len(self.api.list(
                KIND_POD, namespace=svc.namespace,
                label_selector={C.LABEL_SERVICE: svc.name},
                filter_fn=lambda p: p.status.phase in (PENDING, RUNNING)))
            self.replica_series[svc.key].append(
                (round(self.eng.now(), 2), round(load, 2), live, desired))

    def _sample_utilization(self) -> None:
        used = serving_used = 0.0
        for p in self.api.list(KIND_POD):
            if p.spec.node_name and p.status.phase == RUNNING:
                eq = chip_equiv(p)
                used += eq
                if p.metadata.namespace == "serve":
                    serving_used += eq
        utilization = min(1.0, used / TOTAL_CHIPS)
        REGISTRY.set("nos_tpu_cluster_utilization", utilization)
        if self.eng.now() < WARMUP_S:
            return
        self._util_area += utilization * TICK_S
        self._batch_util_area += min(
            1.0, (used - serving_used) / TOTAL_CHIPS) * TICK_S
        self._util_time += TICK_S

    # -- main loop ----------------------------------------------------------
    def _tick(self) -> None:
        self._tick_no += 1
        tick = self._tick_no
        self._complete_finished()
        self._spawn()
        if tick % STAMP_EVERY_TICKS == 1:
            self._stamp_loads()
        self.autoscaler.reconcile()
        t0 = time.perf_counter()
        self.scheduler.run_cycle()
        self.cycle_wall_ms.append((time.perf_counter() - t0) * 1e3)
        self._requeue_evicted()
        self.slice_ctl.process_if_ready()
        self.ts_ctl.process_if_ready()
        for a in list(self.agents.values()):
            a.tick()
        self.eq_reconciler.reconcile_all()
        self._record_serving_binds()
        self._record_batch_binds()
        if tick % STAMP_EVERY_TICKS == 0:
            self._track_replicas()
        self._sample_utilization()
        if self.eng.now() >= WARMUP_S:
            self.slo_engine.tick()

    def run(self) -> dict:
        self._tick_no = 0
        self.eng.tick_loop(TICK_S, self._tick, until=TRACE_S,
                           label="ctl-tick")
        self.eng.run()
        return self._report()

    def _cache_request(self, event: str, pod) -> None:
        if event == "ADDED" and pod.metadata.namespace in (
                "batch", "besteffort"):
            self._job_requests[pod.metadata.name] = pod_request(pod)

    def _tracking_stats(self) -> dict:
        out: dict[str, dict] = {}
        for svc in SERVICES:
            rows = [r for r in self.replica_series[svc.key]
                    if r[0] >= WARMUP_S]
            if not rows:
                out[svc.key] = {"samples": 0}
                continue
            # "keeps up with demand": live >= desired - 1.  Running
            # ABOVE desired is the scale-down cooldown doing its job
            # (SLO-safe over-provision), not a tracking failure; the
            # direction-change count guards flapping separately.
            within = sum(1 for _, _, live, desired in rows
                         if live >= desired - 1)
            flips = 0
            last_dir = 0
            prev = rows[0][2]
            for _, _, live, _ in rows[1:]:
                d = (live > prev) - (live < prev)
                if d and last_dir and d != last_dir:
                    flips += 1
                if d:
                    last_dir = d
                prev = live
            out[svc.key] = {
                "samples": len(rows),
                "within_one": round(within / len(rows), 4),
                "direction_changes": flips,
                "replicas_min": min(r[2] for r in rows),
                "replicas_max": max(r[2] for r in rows),
                "load_min": min(r[1] for r in rows),
                "load_max": max(r[1] for r in rows),
            }
        return out

    def _report(self) -> dict:
        pct = percentile
        lat_ms = [x * 1e3 for x in self.serving_latencies]
        return {
            "seed": self.seed,
            "trace_seconds": TRACE_S,
            "utilization_pct": round(
                self._util_area / self._util_time, 4)
            if self._util_time else 0.0,
            "batch_utilization_pct": round(
                self._batch_util_area / self._util_time, 4)
            if self._util_time else 0.0,
            "serving": {
                "binds": len(self.serving_latencies),
                "p50_ms": pct(lat_ms, 0.50, 2),
                "p99_ms": pct(lat_ms, 0.99, 2),
                "max_ms": (round(max(lat_ms), 2) if lat_ms else None),
                "preempted": self.serving_preempted,
                "tracking": self._tracking_stats(),
            },
            "batch": {
                "jobs_completed": self.completed,
                "p50_schedule_latency_s": pct(self.batch_latencies,
                                              0.50, 3),
                "p90_schedule_latency_s": pct(self.batch_latencies,
                                              0.90, 3),
                "preemptions": self.preemptions,
                "preempted_pods": self.preempted_pods,
            },
            "scheduler_cycle_wall_ms_p50": pct(self.cycle_wall_ms,
                                               0.50, 2),
            "scheduler_cycle_wall_ms_p99": pct(self.cycle_wall_ms,
                                               0.99, 2),
            "slo": self.slo_engine.report(),
        }


def run_seeds(seeds=range(3)) -> dict:
    runs = [Sim(seed=s).run() for s in seeds]
    lat_ms: list[float] = []
    serving_binds = sum(r["serving"]["binds"] for r in runs)
    utils = [r["utilization_pct"] for r in runs]
    slo_verdicts = []
    for r in runs:
        for v in r["slo"]["verdicts"]:
            slo_verdicts.append({**v, "seed": r["seed"]})
    # pooled p99 across seeds from the per-seed p99s is wrong; keep the
    # per-seed maxima honest instead
    p99s = [r["serving"]["p99_ms"] for r in runs
            if r["serving"]["p99_ms"] is not None]
    first = runs[0]
    return {
        "seeds": [r["seed"] for r in runs],
        "trace_seconds": first["trace_seconds"],
        "utilization_pct": round(sum(utils) / len(utils), 4),
        "utilization_min": round(min(utils), 4),
        "vs_utilization_target": round(
            (sum(utils) / len(utils)) / UTILIZATION_TARGET, 4),
        "serving": {
            "binds": serving_binds,
            "p99_ms_per_seed": p99s,
            "p99_ms_worst": max(p99s) if p99s else None,
            "p99_target_ms": SERVING_P99_TARGET_S * 1e3,
            "preempted": sum(r["serving"]["preempted"] for r in runs),
            "tracking": {r["seed"]: r["serving"]["tracking"]
                         for r in runs},
        },
        "batch": {
            "jobs_completed": sum(r["batch"]["jobs_completed"]
                                  for r in runs),
            "preemptions": sum(r["batch"]["preemptions"] for r in runs),
            "preempted_pods": sum(r["batch"]["preempted_pods"]
                                  for r in runs),
        },
        "scheduler_cycle_wall_ms_p99": max(
            r["scheduler_cycle_wall_ms_p99"] for r in runs),
        "slo": {
            "fast_window_s": first["slo"]["fast_window_s"],
            "slow_window_s": first["slo"]["slow_window_s"],
            "burn_threshold": first["slo"]["burn_threshold"],
            "objectives": first["slo"]["objectives"],
            "verdicts": slo_verdicts,
            "breaches": sum(1 for v in slo_verdicts if v["breached"]),
        },
        "per_seed": runs,
    }


def run_smoke() -> dict:
    """The serving regression gate (scripts/check.sh): one seed on a
    shortened trace.  Asserts the serving plane END TO END — the
    serving class's bucket series on /metrics, an SLO verdict for the
    millisecond objective, ZERO serving preemption victims, the
    autoscaler tracking its signal, and the wall bound.  Raises
    AssertionError on regression."""
    global TRACE_S, WARMUP_S, SLO_FAST_WINDOW_S, SLO_SLOW_WINDOW_S, \
        SERVING_MIN_EVENTS
    prev = (TRACE_S, WARMUP_S, SLO_FAST_WINDOW_S, SLO_SLOW_WINDOW_S,
            SERVING_MIN_EVENTS)
    TRACE_S, WARMUP_S = 90.0, 30.0
    # windows wide (and min_events low) enough that the shortened
    # trace's serving binds produce a JUDGED verdict with a real value
    SLO_FAST_WINDOW_S, SLO_SLOW_WINDOW_S = 15.0, 45.0
    SERVING_MIN_EVENTS = 1
    t0 = time.perf_counter()
    try:
        sim = Sim(seed=0)
        result = sim.run()
    finally:
        (TRACE_S, WARMUP_S, SLO_FAST_WINDOW_S, SLO_SLOW_WINDOW_S,
         SERVING_MIN_EVENTS) = prev
    wall = time.perf_counter() - t0

    serving = result["serving"]
    assert serving["binds"] > 0, "no serving replicas ever bound"
    assert serving["preempted"] == 0, \
        f"{serving['preempted']} serving pod(s) were preemption victims"
    assert serving["p99_ms"] is not None \
        and serving["p99_ms"] < SERVING_P99_TARGET_S * 1e3, \
        f"serving p99 {serving['p99_ms']} ms >= 100 ms"
    render = REGISTRY.render()
    assert 'nos_tpu_schedule_latency_seconds_bucket{class="serving"' \
        in render, "/metrics missing the serving-class bucket series"
    verdicts = result["slo"]["verdicts"]
    ms_verdicts = [v for v in verdicts
                   if v["objective"] == "serving-schedule-latency"]
    assert ms_verdicts, "no verdict for the serving millisecond SLO"
    for v in ms_verdicts:
        for field in ("burn_fast", "burn_slow", "budget_remaining",
                      "breached", "target"):
            assert field in v, f"verdict missing {field}: {v}"
        assert not v["breached"], f"serving SLO breached in smoke: {v}"
    # the gate must judge REAL events: a verdict whose value never
    # populated (windows unobservable) would make the breach assert
    # above vacuously green no matter what the engine does
    assert any(v["value"] is not None for v in ms_verdicts), \
        f"serving SLO verdict never judged real events: {ms_verdicts[-1]}"
    for svc_key, stats in serving["tracking"].items():
        assert stats.get("samples", 0) > 0, f"no tracking samples: {svc_key}"
        assert stats["within_one"] >= 0.9, \
            f"{svc_key} tracked within one replica only " \
            f"{stats['within_one']:.0%} of samples"
    assert wall < 300.0, f"smoke trace took {wall:.1f}s (> 300s bound)"
    # off means off: the request data plane (bench_requests.Sim with
    # the router disabled) must journal the byte-identical decision
    # sequence of this bench — the plane's existence cannot perturb
    # the annotation-driven path it replaces.  Lazy import: this bench
    # is the protected side, that one the overlay.
    import bench_requests

    identical, detail = bench_requests.check_byte_identity()
    assert identical, f"router-disabled path not byte-identical: {detail}"
    return {
        "smoke": "ok",
        "byte_identity": detail,
        "wall_s": round(wall, 1),
        "serving_binds": serving["binds"],
        "serving_p99_ms": serving["p99_ms"],
        "serving_preempted": serving["preempted"],
        "utilization_pct": result["utilization_pct"],
        "tracking": serving["tracking"],
        "slo": result["slo"],
    }


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        description="serving-tier SLO + autoscaler bench")
    ap.add_argument("--smoke", action="store_true",
                    help="1-seed shortened-trace serving gate")
    ap.add_argument("--seeds", type=int, default=3)
    ap.add_argument("--serving-report", default="",
                    help="also write the serving+SLO block to this "
                         "file (CI uploads it as an artifact)")
    args = ap.parse_args(argv)
    if args.smoke:
        out = run_smoke()
    else:
        out = run_seeds(range(args.seeds))
    write_report(args.serving_report,
                 {k: v for k, v in out.items() if k != "per_seed"},
                 note="serving report")
    emit(out)


if __name__ == "__main__":
    main()
