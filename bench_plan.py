"""Planning/scheduling hot-path microbench: COW snapshots + incremental cache.

Drives the real `MultiHostGeometryPlanner.plan()` on a synthetic v5e-256
(64 hosts x 8 chips across 4 ICI domains; half the hosts genuinely full)
against a 200-pod mixed pending batch, and `Scheduler.run_cycle()` over
the same cluster, printing one JSON line:

  {"plan_wall_ms": {"p50": .., "p99": ..},
   "fork_clones_per_plan": ..,
   "eager_plan_wall_ms": {"p50": .., "p99": ..},
   "eager_fork_clones_per_plan": ..,
   "plan_speedup_vs_eager": ..,
   "scheduler_cycle_wall_ms": {"p50": .., "p99": ..}}

The eager numbers re-measure the seed's fork semantics (every node
cloned per fork) through the same machinery, so the speedup claim is
measured in-repo, not remembered.

`--smoke` is the CI gate (scripts/check.sh): one plan under a generous
wall bound plus a clone-count bound — re-introducing an O(nodes) copy
per fork fails here, not in review.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from nos_tpu.api import constants as C
from nos_tpu.cmd.assembly import build_scheduler
from nos_tpu.kube.client import APIServer, KIND_NODE, KIND_POD
from nos_tpu.partitioning.core import ClusterSnapshot
from nos_tpu.partitioning.slicepart import (
    SlicePartitionCalculator, SliceProfileCalculator, SliceSnapshotTaker,
)
from nos_tpu.partitioning.slicepart.calculators import SliceProfileFilter
from nos_tpu.partitioning.slicepart.group import MultiHostGeometryPlanner
from nos_tpu.partitioning.state import ClusterState
from nos_tpu.scheduler.framework import Framework
from nos_tpu.testing.factory import make_pod, make_slice_pod, make_tpu_node

HOSTS = 64                       # v5e-256: 64 hosts x 8 chips
DOMAINS = 4                      # 4 ICI domains of 16 hosts
FULL_HOSTS = 32                  # genuinely full (bound filler pods)
PENDING_PODS = 200
# mixed batch: (profile, weight) — sub-host demand the planner re-carves
# for, plus multi-host 4x4 demand that exercises the group pass
POD_MIX = [("1x1", 8), ("1x2", 6), ("2x2", 4), ("2x4", 2), ("4x4", 2)]

SMOKE_WALL_BOUND_MS = 5000.0
# COW contract: clones per plan <= forks + dirty; forks <= candidate
# hosts (32 free).  3x headroom still sits far below the O(N^2) regime
# (32 candidates x 64 clones = 2048).
SMOKE_CLONE_BOUND = 3 * (HOSTS - FULL_HOSTS)


class EagerForkSnapshot(ClusterSnapshot):
    """The seed's fork semantics (clone every node per fork), measured
    through the same COW machinery as the baseline for the speedup."""

    def fork(self):
        super().fork()
        for name in list(self._nodes):
            self.get_node_for_write(name)


class _SeedFramework(Framework):
    """The seed's plugin dispatch: a runtime-checkable Protocol
    isinstance on every run_* call (55% of the pre-PR plan profile)."""

    def run_pre_filter_plugins(self, state, pod, nodes):
        from nos_tpu.scheduler.framework import PreFilterPlugin, Status
        for p in self.plugins:
            if isinstance(p, PreFilterPlugin) and hasattr(p, "pre_filter"):
                st = p.pre_filter(state, pod, nodes)
                if not st.is_success:
                    return st
        return Status.ok()

    def run_filter_plugins(self, state, pod, node_info):
        from nos_tpu.scheduler.framework import FilterPlugin, Status
        for p in self.plugins:
            if isinstance(p, FilterPlugin) and hasattr(p, "filter"):
                st = p.filter(state, pod, node_info)
                if not st.is_success:
                    return st
        return Status.ok()


class _SeedPlanner(MultiHostGeometryPlanner):
    """The seed's per-node planning loop, verbatim semantics: eager
    forks feed it (the caller passes an EagerForkSnapshot), the what-if
    SharedLister is reconstructed from all N NodeInfos per candidate,
    placements run an O(n) pods.remove inside the loop, and every
    pending pod re-runs the full pipeline per candidate (no
    equivalence memo)."""

    def plan(self, snapshot, pending_pods):
        from nos_tpu.partitioning.core.actuator import (
            compute_partitioning_state,
        )
        from nos_tpu.partitioning.core.tracker import SliceTracker
        from nos_tpu.scheduler.framework import SharedLister

        tracker = SliceTracker(snapshot, self._calculator, pending_pods)
        if not tracker.empty:
            self._group_pass(snapshot, tracker.lacking, pending_pods)
        tracker = SliceTracker(snapshot, self._calculator, pending_pods)
        if tracker.empty:
            return compute_partitioning_state(
                snapshot, self._partition_calculator)
        pods = [p for p in self._sorter.sort(pending_pods)
                if self._calculator.requested_profiles(p)]
        candidate_names = [n.name for n in snapshot.get_candidate_nodes()]
        for node_name in candidate_names:
            if tracker.empty:
                break
            snapshot.fork()
            node = snapshot.get_node_for_write(node_name)
            node.update_geometry_for(tracker.lacking)
            lister = SharedLister(
                pn.node_info() for pn in snapshot.nodes().values())
            placed = 0
            for pod in list(pods):
                if tracker.empty:
                    break
                if self._try_add_pod(snapshot, lister, node_name, pod):
                    tracker.remove(pod)
                    pods.remove(pod)
                    placed += 1
            if placed > 0:
                snapshot.commit()
            else:
                snapshot.revert()
        return compute_partitioning_state(
            snapshot, self._partition_calculator)


def percentile(xs: list[float], q: float) -> float:
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


def wall_summary(samples_ms: list[float]) -> dict:
    return {"p50": round(percentile(samples_ms, 0.50), 3),
            "p99": round(percentile(samples_ms, 0.99), 3)}


def make_cluster_state() -> ClusterState:
    state = ClusterState()
    per_domain = HOSTS // DOMAINS
    for i in range(HOSTS):
        pod_id = f"pod-{i // per_domain}"
        host_index = i % per_domain
        if i < FULL_HOSTS:
            # full host: a bound filler consumes everything, so it is
            # not a candidate — matching a saturated trace where only
            # part of the fleet has re-carvable headroom
            node = make_tpu_node(f"host-{i}", pod_id=pod_id,
                                 host_index=host_index,
                                 status_geometry={"used": {"2x4": 1}})
            filler = make_pod(name=f"filler-{i}", node_name=f"host-{i}",
                              resources=dict(node.status.allocatable))
            state.update_node(node, [filler])
        else:
            node = make_tpu_node(f"host-{i}", pod_id=pod_id,
                                 host_index=host_index,
                                 status_geometry={"free": {"2x4": 1}})
            state.update_node(node, [])
    return state


def make_pending_batch() -> list:
    pods = []
    i = 0
    while len(pods) < PENDING_PODS:
        for profile, weight in POD_MIX:
            for _ in range(weight):
                if len(pods) >= PENDING_PODS:
                    break
                labels = ({C.LABEL_POD_GROUP: f"gang-{i}"}
                          if profile == "4x4" else None)
                pods.append(make_slice_pod(
                    profile, 1, name=f"pending-{i}", labels=labels,
                    priority=i % 3))
                i += 1
    return pods


def make_planner(seed_baseline: bool = False) -> MultiHostGeometryPlanner:
    cls = _SeedPlanner if seed_baseline else MultiHostGeometryPlanner
    fw = _SeedFramework() if seed_baseline else Framework()
    return cls(
        framework=fw,
        calculator=SliceProfileCalculator(),
        partition_calculator=SlicePartitionCalculator(),
    )


def run_plan_bench(repeats: int = 10, seed_baseline: bool = False) -> dict:
    state = make_cluster_state()
    pods = make_pending_batch()
    planner = make_planner(seed_baseline)
    taker = SliceSnapshotTaker()
    walls_ms: list[float] = []
    clones: list[int] = []
    for _ in range(repeats):
        snap = taker.take_snapshot(state)
        if seed_baseline:
            snap = EagerForkSnapshot(snap.nodes(), SliceProfileFilter())
        t0 = time.perf_counter()
        planner.plan(snap, pods)
        walls_ms.append((time.perf_counter() - t0) * 1e3)
        clones.append(snap.cow_clones)
    return {"wall_ms": wall_summary(walls_ms),
            "clones_per_plan": round(sum(clones) / len(clones), 1)}


def run_cycle_bench(cycles: int = 20) -> dict:
    api = APIServer()
    per_domain = HOSTS // DOMAINS
    for i in range(HOSTS):
        geometry = ({"used": {"2x4": 1}} if i < FULL_HOSTS
                    else {"free": {"2x4": 1}})
        api.create(KIND_NODE, make_tpu_node(
            f"host-{i}", pod_id=f"pod-{i // per_domain}",
            host_index=i % per_domain, status_geometry=geometry))
    for i in range(FULL_HOSTS):
        api.create(KIND_POD, make_pod(
            name=f"filler-{i}", node_name=f"host-{i}",
            resources=dict(api.get(KIND_NODE,
                                   f"host-{i}").status.allocatable)))
    for pod in make_pending_batch():
        api.create(KIND_POD, pod)
    scheduler = build_scheduler(api)
    walls_ms: list[float] = []
    for _ in range(cycles):
        t0 = time.perf_counter()
        scheduler.run_cycle()
        walls_ms.append((time.perf_counter() - t0) * 1e3)
    return {"wall_ms": wall_summary(walls_ms)}


def run_bench(plan_repeats: int = 10, cycles: int = 20,
              compare_eager: bool = True) -> dict:
    plan = run_plan_bench(repeats=plan_repeats)
    out = {
        "plan_wall_ms": plan["wall_ms"],
        "fork_clones_per_plan": plan["clones_per_plan"],
        "scheduler_cycle_wall_ms": run_cycle_bench(cycles)["wall_ms"],
    }
    if compare_eager:
        eager = run_plan_bench(repeats=max(2, plan_repeats // 2),
                               seed_baseline=True)
        out["eager_plan_wall_ms"] = eager["wall_ms"]
        out["eager_fork_clones_per_plan"] = eager["clones_per_plan"]
        if plan["wall_ms"]["p50"] > 0:
            out["plan_speedup_vs_eager"] = round(
                eager["wall_ms"]["p50"] / plan["wall_ms"]["p50"], 2)
    return out


def run_smoke() -> int:
    plan = run_plan_bench(repeats=2)
    failures = []
    if plan["wall_ms"]["p50"] > SMOKE_WALL_BOUND_MS:
        failures.append(
            f"plan p50 {plan['wall_ms']['p50']:.1f} ms exceeds the "
            f"{SMOKE_WALL_BOUND_MS:.0f} ms smoke bound")
    if plan["clones_per_plan"] > SMOKE_CLONE_BOUND:
        failures.append(
            f"{plan['clones_per_plan']:.0f} fork clones per plan exceeds "
            f"the COW bound {SMOKE_CLONE_BOUND} (O(nodes) copy per fork "
            f"re-introduced?)")
    print(json.dumps({"smoke": "fail" if failures else "ok",
                      "plan_wall_ms": plan["wall_ms"],
                      "fork_clones_per_plan": plan["clones_per_plan"],
                      "failures": failures}))
    return 1 if failures else 0


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--smoke", action="store_true",
                        help="fast CI gate: wall + clone-count bounds")
    parser.add_argument("--repeats", type=int, default=10)
    parser.add_argument("--cycles", type=int, default=20)
    parser.add_argument("--no-eager", action="store_true",
                        help="skip the eager-fork baseline comparison")
    args = parser.parse_args()
    if args.smoke:
        return run_smoke()
    print(json.dumps(run_bench(plan_repeats=args.repeats,
                               cycles=args.cycles,
                               compare_eager=not args.no_eager)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
