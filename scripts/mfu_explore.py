"""MFU exploration on the real chip: sweep train-step configs.

Times the BENCH_350M train step across {fused projections} x {batch} x
{remat policy} using bench_compute's slope methodology, printing one JSON
line per variant so the best config can be promoted into bench_compute.py.

Usage: python scripts/mfu_explore.py [--quick]
"""

from __future__ import annotations

import dataclasses
import json
import sys

sys.path.insert(0, ".")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from bench_compute import _slope, make_step_chain, model_flops_per_step, \
    peak_for  # noqa: E402
from nos_tpu.models.llama import BENCH_350M  # noqa: E402
from nos_tpu.models.train import ShardedTrainer  # noqa: E402
from nos_tpu.parallel.mesh import MeshSpec, make_mesh  # noqa: E402

SEQ = 2048


def time_variant(batch, fused, remat_policy, peak):
    cfg = dataclasses.replace(
        BENCH_350M, attn_impl="flash", remat_policy=remat_policy,
        scan_layers=False, fused_qkv=fused, fused_gate_up=fused)
    mesh = make_mesh(MeshSpec.for_device_count(1), devices=jax.devices()[:1])
    trainer = ShardedTrainer(cfg, mesh, batch_size=batch, seq_len=SEQ)
    state = trainer.init_state(0)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, SEQ), 0, cfg.vocab_size, jnp.int32)
    t = _slope(make_step_chain(jax, trainer, state, tokens),
               n1=4, n2=12, reps=3)
    flops = model_flops_per_step(cfg, batch, SEQ)
    return {
        "batch": batch, "fused": fused, "remat": remat_policy,
        "step_ms": round(t * 1e3, 2),
        "tokens_per_s": round(batch * SEQ / t),
        "mfu": round(flops / t / peak, 4),
    }


def main():
    if jax.default_backend() != "tpu":
        print(json.dumps({"skipped": "not on tpu"}))
        return
    peak = peak_for(jax.devices()[0].device_kind)
    quick = "--quick" in sys.argv
    variants = [
        (8, False, "mats"),    # round-2 best (control)
        (8, True, "mats"),
        (16, True, "mats"),
        (16, False, "mats"),
        (16, True, "all_mats"),
        (32, True, "mats"),
    ]
    if quick:
        variants = variants[:3]
    for batch, fused, remat in variants:
        try:
            r = time_variant(batch, fused, remat, peak)
        except Exception as e:  # noqa: BLE001 — keep sweeping (OOM etc.)
            r = {"batch": batch, "fused": fused, "remat": remat,
                 "error": str(e)[:200]}
        print(json.dumps(r), flush=True)


if __name__ == "__main__":
    main()
