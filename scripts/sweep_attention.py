"""Sweep flash-attention block sizes on real hardware.

Times the pallas forward and forward+backward at the training shapes for a
grid of (block_q, block_k), using bench_compute's chained-iteration slope
methodology (the tunneled platform hides completion behind an RTT — the
slope of wall time vs chained iterations cancels it).

    python scripts/sweep_attention.py

Output: one line per config with fwd/bwd ms and TFLOP/s; the winner feeds
the defaults in nos_tpu/ops/attention.py.
"""

from __future__ import annotations

import itertools
import json
import sys

sys.path.insert(0, ".")

from bench_compute import _slope  # noqa: E402 — same slope as the bench


def main() -> None:
    import jax
    import jax.numpy as jnp

    from nos_tpu.ops.attention import flash_attention

    if jax.default_backend() != "tpu":
        print(json.dumps({"skipped": "not on tpu"}))
        return

    B, S, H, D = 8, 2048, 8, 128  # the BENCH_350M training shapes
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.bfloat16)
               for kk in jax.random.split(key, 3))
    fwd_flops = 4 * B * H * S * S * D * 0.5          # causal
    bwd_flops = 3.5 * fwd_flops   # dq kernel 3 dots + dkv 4 vs fwd 2

    results = []
    for bq, bk in itertools.product([128, 256, 512, 1024],
                                    [128, 256, 512, 1024]):
        if S % bq or S % bk:
            continue

        def build(bq, bk):
            # block sizes stay Python ints via closure (pallas needs them
            # concrete); the trip count is traced so each config compiles
            # its fwd/bwd program exactly once
            @jax.jit
            def run_fwd(q, k, v, iters):
                def body(i, acc):
                    return flash_attention(acc, k, v, True, bq, bk)
                return jax.lax.fori_loop(0, iters, body, q)[0, 0, 0, 0]

            def loss(qq, kk, vv):
                return jnp.sum(
                    flash_attention(qq, kk, vv, True, bq, bk)
                    .astype(jnp.float32) ** 2)

            @jax.jit
            def run_bwd(q, k, v, iters):
                def body(i, acc):
                    # grads flow to q, k AND v so neither backward
                    # kernel can be dead-code-eliminated
                    gq, gk, gv = jax.grad(loss, (0, 1, 2))(acc, k, v)
                    return gq + gk + gv
                return jax.lax.fori_loop(0, iters, body, q)[0, 0, 0, 0]
            return run_fwd, run_bwd

        run_fwd, run_bwd = build(bq, bk)

        def make_fwd(iters):
            i = jnp.int32(iters)
            return lambda: float(run_fwd(q, k, v, i))

        def make_bwd(iters):
            i = jnp.int32(iters)
            return lambda: float(run_bwd(q, k, v, i))

        try:
            t_fwd = _slope(make_fwd)
            t_tot = _slope(make_bwd)
        except Exception as e:  # noqa: BLE001 — keep sweeping
            results.append({"block_q": bq, "block_k": bk, "error": str(e)[:120]})
            print(json.dumps(results[-1]), flush=True)
            continue
        t_bwd = max(t_tot - t_fwd, 1e-9)
        results.append({
            "block_q": bq, "block_k": bk,
            "fwd_ms": round(t_fwd * 1e3, 3),
            "fwd_tflops": round(fwd_flops / t_fwd / 1e12, 1),
            "fwdbwd_ms": round(t_tot * 1e3, 3),
            "bwd_ms": round(t_bwd * 1e3, 3),
            "bwd_tflops": round(bwd_flops / t_bwd / 1e12, 1),
        })
        print(json.dumps(results[-1]), flush=True)

    ok = [r for r in results if "error" not in r]
    if ok:
        best_f = min(ok, key=lambda r: r["fwd_ms"])
        best_t = min(ok, key=lambda r: r["fwdbwd_ms"])
        print(json.dumps({"best_fwd": best_f, "best_fwdbwd": best_t}))


if __name__ == "__main__":
    main()
