"""Sweep the flash BACKWARD implementations/blocks on real hardware.

Compares the classic dq/dkv split against the fused 5-matmul kernel at
the training shapes over a small (block_q, block_k) grid, with
bench_compute's chained-iteration slope methodology.

    python scripts/sweep_bwd.py

The winner feeds _BWD_IMPL / DEFAULT_BLOCK_* in nos_tpu/ops/attention.py.
"""

from __future__ import annotations

import itertools
import json
import sys

sys.path.insert(0, ".")

from bench_compute import _slope  # noqa: E402


def main() -> None:
    import jax
    import jax.numpy as jnp

    from nos_tpu.ops import attention as A

    if jax.default_backend() != "tpu":
        print(json.dumps({"skipped": "not on tpu"}))
        return

    B, S, H, D = 8, 2048, 8, 128  # BENCH_350M training shapes
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.bfloat16)
               for kk in jax.random.split(key, 3))
    fwd_flops = 4 * B * H * S * S * D * 0.5          # causal
    bwd_flops = 3.5 * fwd_flops   # bench accounting (split's 7 dots)

    def grad_maker(bq, bk):
        def loss(qq, kk2, vv):
            return jnp.sum(A.flash_attention(
                qq, kk2, vv, True, bq, bk).astype(jnp.float32) ** 2)

        def gstep(qx):
            gq, gk, gv = jax.grad(loss, (0, 1, 2))(qx, k, v)
            return gq + gk + gv

        @jax.jit
        def run(q, k, v, iters):
            return jax.lax.fori_loop(
                0, iters, lambda i, acc: gstep(acc), q)[0, 0, 0, 0]

        def make(iters):
            i = jnp.int32(iters)
            return lambda: float(run(q, k, v, i))
        return make

    def fwd_maker(bq, bk):
        @jax.jit
        def run(q, k, v, iters):
            return jax.lax.fori_loop(
                0, iters,
                lambda i, acc: A.flash_attention(acc, k, v, True, bq, bk),
                q)[0, 0, 0, 0]

        def make(iters):
            i = jnp.int32(iters)
            return lambda: float(run(q, k, v, i))
        return make

    results = []
    for impl, (bq, bk) in itertools.product(
            ["fused", "split"],
            [(512, 512), (256, 512), (512, 256), (1024, 512), (512, 1024),
             (256, 1024), (1024, 256), (2048, 512), (512, 2048)]):
        if S % bq or S % bk:
            continue
        A.set_backward_impl(impl)
        try:
            t_fwd = _slope(fwd_maker(bq, bk), n1=40, n2=160)
            t_grad = _slope(grad_maker(bq, bk))
            t_bwd = max(t_grad - t_fwd, 1e-9)
            r = {"impl": impl, "bq": bq, "bk": bk,
                 "fwd_ms": round(t_fwd * 1e3, 3),
                 "bwd_ms": round(t_bwd * 1e3, 3),
                 "bwd_tflops": round(bwd_flops / t_bwd / 1e12, 1)}
        except Exception as e:  # noqa: BLE001 — sweep must survive one bad config
            r = {"impl": impl, "bq": bq, "bk": bk, "error": str(e)[:200]}
        results.append(r)
        print(json.dumps(r), flush=True)
    A.set_backward_impl("fused")
    ok = [r for r in results if "bwd_ms" in r]
    if ok:
        best = min(ok, key=lambda r: r["bwd_ms"])
        print(json.dumps({"best": best}))


if __name__ == "__main__":
    main()
