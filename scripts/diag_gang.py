"""Diagnose the gang-4x8 schedule-latency tail (VERDICT r4 task #5).

Runs the utilization sim with per-cycle probes answering: when a 4x8
gang is waiting, does it hold the window lease (or is another class
hogging it)?  How drained is the leased window?  Do candidate windows
even exist?  Prints a JSON summary per seed.

    python scripts/diag_gang.py [seed ...]
"""

from __future__ import annotations

import json
import sys

sys.path.insert(0, ".")

from bench_utilization import Sim, TICK_S, TRACE_S  # noqa: E402

from nos_tpu.kube.client import KIND_POD  # noqa: E402


def run(seed: int) -> dict:
    sim = Sim(seed=seed)
    sched = sim.scheduler
    probe = {
        "cycles": 0,
        "cycles_with_pending_4x8": 0,
        "lease_held_by": {},          # class of lease holder while a 4x8 waits
        "no_lease_while_4x8_waits": 0,
        "waits": [],                  # per completed wait: cycles waited
    }
    waiting: dict[str, int] = {}      # gang name -> cycles waited so far

    probe["lease_moves"] = 0          # window changed under the same gang
    probe["binds_onto_leased_hosts"] = 0
    probe["leased_busy_chips_series"] = []
    last_lease = [None]               # (gang_key, hosts)
    pre_nodes = [set()]

    orig_cycle = sched.run_cycle

    def instrumented():
        # what was bound to the leased hosts before this cycle
        lease_before = sched._lease
        before = set()
        if lease_before is not None:
            before = {p.metadata.name for p in sim.api.list(
                KIND_POD,
                filter_fn=lambda p: p.spec.node_name in lease_before[1])}
        out = orig_cycle()
        lease_now = sched._lease
        if lease_now is not None and lease_before is not None \
                and lease_now[0] == lease_before[0] \
                and lease_now[1] != lease_before[1]:
            probe["lease_moves"] += 1
        if lease_before is not None and lease_now is not None \
                and lease_now[0] == lease_before[0]:
            after = {p.metadata.name for p in sim.api.list(
                KIND_POD,
                filter_fn=lambda p: p.spec.node_name in lease_before[1])}
            probe["binds_onto_leased_hosts"] += len(after - before)
        probe["cycles"] += 1
        pending_4x8 = {
            j.name for j in sim.jobs.values()
            if j.cls == "gang-4x8" and j.bound_at is None
            # only count gangs whose pods exist and are unbound
            and any(p.spec.node_name == ""
                    for p in sim.api.list(
                        KIND_POD,
                        filter_fn=lambda p, n=j.name:
                        p.metadata.name.startswith(n + "-")))
        }
        for g in list(waiting):
            if g not in pending_4x8:
                probe["waits"].append(waiting.pop(g))
        for g in pending_4x8:
            waiting[g] = waiting.get(g, 0) + 1
        if pending_4x8:
            probe["cycles_with_pending_4x8"] += 1
            lease = sched._lease
            if lease is None:
                probe["no_lease_while_4x8_waits"] += 1
            else:
                (ns, gname), hosts = lease
                job = sim.jobs.get(gname)
                cls = job.cls if job else "gone"
                key = f"{cls}({len(hosts)}h)"
                probe["lease_held_by"][key] = \
                    probe["lease_held_by"].get(key, 0) + 1
        return out

    sched.run_cycle = instrumented
    result = sim.run()
    waits = sorted(probe["waits"])
    return {
        "seed": seed,
        "gang_4x8": result["schedule_latency_by_class"].get("gang-4x8"),
        "gang_4x4": result["schedule_latency_by_class"].get("gang-4x4"),
        "utilization": result["utilization_pct"],
        "cycles_with_pending_4x8_pct": round(
            probe["cycles_with_pending_4x8"] / probe["cycles"], 3),
        "lease_held_by_while_4x8_waits": probe["lease_held_by"],
        "no_lease_while_4x8_waits": probe["no_lease_while_4x8_waits"],
        "wait_cycles_p50": waits[len(waits) // 2] if waits else None,
        "wait_cycles_p90": waits[int(len(waits) * 0.9)] if waits else None,
        "lease_moves": probe["lease_moves"],
        "binds_onto_leased_hosts": probe["binds_onto_leased_hosts"],
        "ticks_per_second": 1 / TICK_S,
    }


def main() -> None:
    seeds = [int(s) for s in sys.argv[1:]] or [0, 1]
    for seed in seeds:
        print(json.dumps(run(seed)), flush=True)


if __name__ == "__main__":
    main()
