"""Chaos soak runner: seeded fault-injection sweeps with one-command repro.

Drives the same slice e2e soak the test suite runs (tests/test_chaos.py
run_slice_soak) over a seed range, with tunable fault rates and cluster
size, and prints a JSON line per failure naming the seed — so a CI or
overnight soak failure reproduces with:

    python scripts/diag_chaos.py --seed <N>

Sweeps:

    python scripts/diag_chaos.py                      # seeds 0..99
    python scripts/diag_chaos.py --seeds 1000 --hosts 4 --pods 7
    python scripts/diag_chaos.py --conflict-rate 0.4 --drop-rate 0.3
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

_REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(_REPO))

from nos_tpu.utils import retry as retry_mod  # noqa: E402

# The soak harness lives with the tests so the acceptance gate and this
# runner can never drift apart.
sys.path.insert(0, str(_REPO / "tests"))
from test_chaos import run_slice_soak  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--seed", type=int, default=None,
                    help="run exactly one seed (repro mode, verbose)")
    ap.add_argument("--seeds", type=int, default=100,
                    help="sweep seeds 0..N-1 (default 100)")
    ap.add_argument("--hosts", type=int, default=2)
    ap.add_argument("--pods", type=int, default=3)
    ap.add_argument("--conflict-rate", type=float, default=0.15)
    ap.add_argument("--transient-rate", type=float, default=0.10)
    ap.add_argument("--drop-rate", type=float, default=0.10)
    ap.add_argument("--real-backoff", action="store_true",
                    help="keep real retry sleeps (slower, timing-true)")
    args = ap.parse_args(argv)

    if not args.real_backoff:
        retry_mod.sleep = lambda s: None

    seeds = [args.seed] if args.seed is not None else range(args.seeds)
    failures = 0
    t0 = time.monotonic()
    for seed in seeds:
        r = run_slice_soak(seed, hosts=args.hosts, pods=args.pods,
                           conflict_rate=args.conflict_rate,
                           transient_rate=args.transient_rate,
                           drop_watch_rate=args.drop_rate)
        lock_problems = ([i.render() for i in r.lock_graph.inversions]
                         + r.lock_graph.unguarded_writes)
        ok = r.converged and not r.errors and not lock_problems
        if not ok or args.seed is not None:
            print(json.dumps({
                "seed": seed, "ok": ok, "rounds": r.rounds,
                "stats": r.api.stats, "errors": r.errors[:5],
                "lock_violations": lock_problems[:5],
                "quarantined": sorted(r.quarantined),
                "repro": f"python scripts/diag_chaos.py --seed {seed}",
            }))
        if not ok:
            failures += 1
    n = len(list(seeds))
    print(json.dumps({
        "seeds": n, "failures": failures,
        "elapsed_s": round(time.monotonic() - t0, 2),
    }))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
