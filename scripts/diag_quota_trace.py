#!/usr/bin/env python3
"""Experiment harness behind the round-5 quota-trace decisions: re-run
any variant of bench_utilization on chosen seeds and print the metrics
the tuning judged by.  Every PARITY.md round-5 claim about a measured
win or dead end is reproducible from here.

    python3 scripts/diag_quota_trace.py baseline 0 1
    python3 scripts/diag_quota_trace.py backfill 0       # dead end
    python3 scripts/diag_quota_trace.py stale45 4        # dead end
    python3 scripts/diag_quota_trace.py noquota 0        # control
    python3 scripts/diag_quota_trace.py nokill 0         # control

Variants (implemented through bench_utilization's own toggles —
CREATE_QUOTAS / BACKLOG_STALE_S / SCHEDULER_EXTRA_KWARGS_FN — so the
variants can never drift from the bench's spawn/scheduler logic):
- baseline: the published configuration (quota enforced, gang priority,
  node loss, hybrid hosts).
- nokill:   no node-loss injection.
- noquota:  nokill WITHOUT any ElasticQuota objects.  Its comparator is
  `nokill`, NOT baseline — both controls disable the node kill so the
  delta isolates quota enforcement alone (the r5 pair measured 0.9176
  vs 0.9180 on seed 0: enforcement costs ~nothing).
- backfill: duration-aware drain-window backfill ON (measured: -1.4
  util points on seed 0 — why it ships opt-in-off).
- stale45:  jobs pending >45 s stop counting against the spawn target
  (teams keep submitting past a stuck gang).  Measured: +1 util point
  on the weakest seed but gang-4x4 p90 37.5 -> 73.5 s — rejected.

One variant+seed list per process run: bench_utilization's module
constants are patched in place.
"""

from __future__ import annotations

import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import bench_utilization as B  # noqa: E402

VARIANTS = ("baseline", "noquota", "nokill", "backfill", "stale45")


def _backfill_kwargs(sim: "B.Sim") -> dict:
    """Estimator fns over the sim's job table (the production analog is
    duration/deadline annotations)."""
    def remaining(pod):
        job = sim._pod_job.get(pod.metadata.name)
        if job is None:
            return None
        if job.bound_at is None:
            return job.duration
        return max(0.0, job.bound_at + job.duration - sim.now[0])

    def duration(pod):
        job = sim._pod_job.get(pod.metadata.name)
        return None if job is None else job.duration

    return {"backfill_remaining_fn": remaining,
            "backfill_duration_fn": duration}


def apply_variant(variant: str) -> None:
    if variant == "noquota":
        B.CREATE_QUOTAS = False
        B.NODE_KILL_T = B.NODE_RESTORE_T = 1e18
    elif variant == "nokill":
        B.NODE_KILL_T = B.NODE_RESTORE_T = 1e18
    elif variant == "backfill":
        B.SCHEDULER_EXTRA_KWARGS_FN = _backfill_kwargs
    elif variant == "stale45":
        B.BACKLOG_STALE_S = 45.0


def main() -> int:
    if len(sys.argv) < 2 or sys.argv[1] not in VARIANTS:
        print(f"usage: {sys.argv[0]} {{{'|'.join(VARIANTS)}}} "
              f"[seed ...]", file=sys.stderr)
        return 2
    variant = sys.argv[1]
    seeds = [int(s) for s in sys.argv[2:]] or [0]
    apply_variant(variant)
    for seed in seeds:
        sim = B.Sim(seed=seed)
        out = sim.run()
        cls = out["schedule_latency_by_class"]
        print(json.dumps({
            "variant": variant, "seed": seed,
            "util": out["utilization_pct"],
            "p90": out["p90_schedule_latency_s"],
            "gang4x8": cls.get("gang-4x8"),
            "gang4x4": cls.get("gang-4x4"),
            "slice2x2": cls.get("slice-2x2"),
            "preemptions": out["quota"]["preemptions"],
            "invariant_violations": sum(
                out["quota"]["invariant_violations"].values()),
            "node_loss": out["node_loss"],
            "cycle_p50_ms": out["scheduler_cycle_wall_ms_p50"],
        }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
