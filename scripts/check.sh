#!/usr/bin/env bash
# Single-command correctness gate: noslint + mypy + tier-1 pytest.
#
#   ./scripts/check.sh            # everything
#   ./scripts/check.sh --fast     # noslint + mypy only (no pytest)
#
# Exit non-zero if any stage fails.  CI runs this verbatim; run it
# before pushing.  docs/static-analysis.md describes the rules.

set -u -o pipefail

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
cd "$ROOT"

FAST=0
[ "${1:-}" = "--fast" ] && FAST=1

rc=0

# Result cache (.noslint_cache/, content-hashed + rule-versioned) keeps
# the dataflow rules fast on unchanged files; --no-cache to bypass.
echo "==> noslint (python -m nos_tpu.analysis, rules N001-N012)"
if ! python -m nos_tpu.analysis; then
    rc=1
fi

# Dual-run determinism gate (noslint v3's dynamic half): run the
# benchmark trace in child interpreters across PYTHONHASHSEED x
# plan_workers x incremental {on,off} and byte-diff the decision
# journals — the incremental axis is the ISSUE 18 anchor (dirty-set
# scheduling + persistent indexes + native hot loops must reproduce
# the full-rescan journals byte-for-byte).  ~10 s wall for the
# 12-cell matrix; each child is hard-bounded (CHILD_TIMEOUT_S = 120 in
# analysis/determinism.py) and the whole gate by the timeout below, so
# a hung child can never wedge CI.  On failure: the report names the
# first differing journal record — docs/troubleshooting.md ("plans
# differ across runs" / "incremental and full journals diverge") is
# the playbook.
echo "==> nosdiff (python -m nos_tpu.analysis --determinism)"
if ! timeout -k 10 900 env JAX_PLATFORMS=cpu \
        python -m nos_tpu.analysis --determinism; then
    rc=1
fi

# Interleaving explorer regression corpus (the DPOR-lite model
# checker): the seeded critical pairs must reach their pinned verdicts
# — the buggy replay_dropped model rediscovered inside the
# 5000-schedule budget, every fixed pair certified clean to
# completion.  Sub-second; tests/test_interleave.py holds the budget
# assertions.
echo "==> interleave corpus (pytest -m interleave)"
if ! env JAX_PLATFORMS=cpu python -m pytest tests/test_interleave.py \
        -q -m interleave -p no:cacheprovider; then
    rc=1
fi

echo "==> mypy (strict: topology/, partitioning/core/, utils/, scheduler/, obs/, serving/, requests/, capacity/, analysis/, sim/, testing/{lockcheck,interleave})"
if python -c "import mypy" 2>/dev/null; then
    # mypy.ini pins the per-package strictness tiers
    if ! python -m mypy --config-file mypy.ini \
            nos_tpu/topology nos_tpu/partitioning/core nos_tpu/utils \
            nos_tpu/scheduler nos_tpu/obs nos_tpu/serving \
            nos_tpu/requests nos_tpu/capacity nos_tpu/analysis \
            nos_tpu/sim \
            nos_tpu/testing/lockcheck.py nos_tpu/testing/interleave.py; then
        rc=1
    fi
else
    # The hermetic test image does not bake mypy in; the gate degrades
    # loudly instead of failing silently or pip-installing.
    echo "    mypy not installed — skipping (install mypy to enable)"
fi

echo "==> obs selftest (python -m nos_tpu.obs --selftest)"
if ! python -m nos_tpu.obs --selftest; then
    rc=1
fi

echo "==> bench_plan.py --smoke (COW clone-count + plan wall gate)"
if ! env JAX_PLATFORMS=cpu python bench_plan.py --smoke; then
    rc=1
fi

echo "==> bench_fleet.py --smoke (shard-count + sharded plan wall gate)"
if ! env JAX_PLATFORMS=cpu python bench_fleet.py --smoke; then
    rc=1
fi

echo "==> perf-gate: bench_fleet.py --scale-smoke (incremental decision plane: steady cycle p99 + delta plan p50)"
if ! env JAX_PLATFORMS=cpu python bench_fleet.py --scale-smoke; then
    rc=1
fi

echo "==> bench_utilization.py --smoke (SLO telemetry gate + chip-second waste conservation)"
if ! env JAX_PLATFORMS=cpu python bench_utilization.py --smoke \
        --slo-report "${SLO_REPORT_PATH:-/tmp/nos_tpu_slo_report.json}" \
        --waste-report "${WASTE_REPORT_PATH:-/tmp/nos_tpu_waste_report.json}" \
        > /dev/null; then
    rc=1
fi

echo "==> bench_serving.py --smoke (serving gate: class=serving buckets, zero serving preemptions, p99 < 100 ms)"
if ! env JAX_PLATFORMS=cpu python bench_serving.py --smoke \
        --serving-report "${SERVING_REPORT_PATH:-/tmp/nos_tpu_serving_report.json}" \
        > /dev/null; then
    rc=1
fi

echo "==> bench_requests.py --smoke (request gate: per-request p99 < SLO, zero serving preemptions, KV occupancy under ceiling, saturation curve)"
if ! env JAX_PLATFORMS=cpu python bench_requests.py --smoke \
        --requests-report "${REQUESTS_REPORT_PATH:-/tmp/nos_tpu_requests_report.json}" \
        > /dev/null; then
    rc=1
fi

echo "==> bench_nodeloss.py --smoke (node-loss gate: never_rebound = 0, rebind p90 bound, lost chip-seconds halved, disabled byte-identity)"
if ! env JAX_PLATFORMS=cpu python bench_nodeloss.py --smoke \
        --nodeloss-report "${NODELOSS_REPORT_PATH:-/tmp/nos_tpu_nodeloss_report.json}" \
        > /dev/null; then
    rc=1
fi

echo "==> bench_defrag.py --smoke (defrag gate: utilization floor, frag halving, churn bound, disabled byte-identity)"
if ! env JAX_PLATFORMS=cpu python bench_defrag.py --smoke \
        --defrag-report "${DEFRAG_REPORT_PATH:-/tmp/nos_tpu_defrag_report.json}" \
        > /dev/null; then
    rc=1
fi

echo "==> bench_capacity.py --smoke (capacity gate: swing round-trip >= 0.95 util, stockout-storm borrowing, disabled byte-identity)"
if ! env JAX_PLATFORMS=cpu python bench_capacity.py --smoke \
        --capacity-report "${CAPACITY_REPORT_PATH:-/tmp/nos_tpu_capacity_report.json}" \
        > /dev/null; then
    rc=1
fi

echo "==> worst-week gate: python -m nos_tpu.sim --smoke (composed chaos day: ledger conservation + every SLO breach explained)"
if ! env JAX_PLATFORMS=cpu SIM_REPORT_PATH="${SIM_REPORT_PATH:-/tmp/nos_tpu_sim_report.json}" \
        python -m nos_tpu.sim --smoke > /dev/null; then
    rc=1
fi

echo "==> bench_compute.py --smoke (MFU gate: interpret-mode kernels + scan + ring overlap)"
if ! env JAX_PLATFORMS=cpu python bench_compute.py --smoke \
        --report "${COMPUTE_REPORT_PATH:-/tmp/nos_tpu_compute_report.json}" \
        > /dev/null; then
    rc=1
fi

if [ "$FAST" -eq 0 ]; then
    echo "==> tier-1 pytest (-m 'not slow')"
    if ! env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
            --continue-on-collection-errors -p no:cacheprovider; then
        rc=1
    fi
fi

if [ "$rc" -eq 0 ]; then
    echo "==> check.sh: ALL GREEN"
else
    echo "==> check.sh: FAILED (see above)" >&2
fi
exit "$rc"
