#!/usr/bin/env python3
"""nosdiff: dual-run determinism gate for the decision plane.

Thin wrapper over ``python -m nos_tpu.analysis --determinism``
(nos_tpu/analysis/determinism.py): runs the benchmark trace in child
interpreters across a PYTHONHASHSEED x plan_workers x incremental
matrix and byte-diffs the decision journals.  Exit 0 = byte-identical
everywhere — including between the incremental (dirty-set) and
full-rescan scheduler paths, the ISSUE 18 equivalence anchor.

  scripts/nosdiff.py                  # the CI gate (scripts/check.sh)
  scripts/nosdiff.py --json           # machine-readable report
  scripts/nosdiff.py --seeds 0 7 --workers 1 2 8 --cycles 3
  scripts/nosdiff.py --incremental on # pin one side of the axis

When this gate fails, start at docs/troubleshooting.md ("plans differ
across runs"): the report names the first differing journal record,
which is the decision a hash-order iteration or a stale cache leaked
into.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from nos_tpu.analysis.determinism import (  # noqa: E402
    DEFAULT_CYCLES, HASH_SEEDS, INCREMENTAL, PLAN_WORKERS, run_matrix,
)


def main() -> int:
    parser = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    parser.add_argument("--seeds", nargs="+", default=list(HASH_SEEDS),
                        help="PYTHONHASHSEED values (default: "
                        f"{' '.join(HASH_SEEDS)})")
    parser.add_argument("--workers", nargs="+", type=int,
                        default=list(PLAN_WORKERS),
                        help="plan_workers values (default: "
                        f"{' '.join(str(w) for w in PLAN_WORKERS)})")
    parser.add_argument("--incremental", nargs="+",
                        choices=("on", "off"),
                        default=list(INCREMENTAL),
                        help="incremental scheduler modes (default: "
                        f"{' '.join(INCREMENTAL)})")
    parser.add_argument("--cycles", type=int, default=DEFAULT_CYCLES,
                        help="scheduler cycles per child run")
    parser.add_argument("--json", action="store_true",
                        help="emit the report as JSON")
    args = parser.parse_args()
    report = run_matrix(hash_seeds=tuple(args.seeds),
                        plan_workers=tuple(args.workers),
                        incremental=tuple(args.incremental),
                        cycles=args.cycles,
                        verbose=not args.json)
    if args.json:
        print(json.dumps(report.to_dict(), indent=2))
    elif report.ok:
        print(f"nosdiff: OK — {len(report.cells)} runs, "
              f"{report.records} journal record(s), byte-identical")
    else:
        for failure in report.failures:
            print(f"nosdiff: FAIL — {failure}")
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
