"""Diagnose the batch-16 remote-compile rejection (VERDICT r4 task #3).

r3 wrote it off in one line: "the tunnel's remote-compile helper rejects
the programs, consistently".  This script runs a variant matrix and
prints the VERBATIM error for each failing one.

FINDINGS (round 1 + 2, recorded in PARITY.md): the HTTP 500s are HBM
OOM in the AOT compiler, not a tunnel limit — "mats" remat saves ~10 GB
of activations at batch 16 (350M/S2048), which does not fit beside
params+optimizer on a 16 GB v5e; batch 12 mats, batch 16 mlp and batch
12 all_mats OOM too.  Every variant that fits loses to batch 8 + mats
(0.544): batch 16 attn 0.462, batch 16 full remat 0.464.  Batch 8 is
the memory-feasibility frontier; edit VARIANTS to probe further.

    python scripts/diag_batch16.py
"""

from __future__ import annotations

import dataclasses
import json
import sys
import traceback

sys.path.insert(0, ".")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from bench_compute import _slope, make_step_chain, model_flops_per_step, \
    peak_for  # noqa: E402
from nos_tpu.models.llama import BENCH_350M  # noqa: E402
from nos_tpu.models.train import ShardedTrainer  # noqa: E402
from nos_tpu.parallel.mesh import MeshSpec, make_mesh  # noqa: E402

SEQ = 2048


def try_variant(batch, scan, remat_policy, layers, peak):
    cfg = dataclasses.replace(
        BENCH_350M, attn_impl="flash", remat_policy=remat_policy,
        scan_layers=scan, num_layers=layers)
    mesh = make_mesh(MeshSpec.for_device_count(1),
                     devices=jax.devices()[:1])
    trainer = ShardedTrainer(cfg, mesh, batch_size=batch, seq_len=SEQ)
    state = trainer.init_state(0)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch, SEQ), 0, cfg.vocab_size, jnp.int32)
    t = _slope(make_step_chain(jax, trainer, state, tokens),
               n1=2, n2=6, reps=2)
    flops = model_flops_per_step(cfg, batch, SEQ)
    return {"step_ms": round(t * 1e3, 2),
            "mfu": round(flops / t / peak, 4),
            "tokens_per_s": round(batch * SEQ / t)}


def main() -> None:
    if jax.default_backend() != "tpu":
        print(json.dumps({"skipped": "not on tpu"}))
        return
    peak = peak_for(jax.devices()[0].device_kind)
    VARIANTS = [
        # (batch, scan_layers, remat, n_layers).  Round 3 of the matrix
        # re-tested remat policies at batch 8 with the FUSED backward:
        # all_mats 0.5478 / mats 0.5456 / dots 0.5409 MFU — a plateau
        # within tunnel noise; the binding constraint is HBM traffic,
        # not recompute, exactly as r3 concluded with the split kernels.
        (8, False, "all_mats", 24),
        (8, False, "dots", 24),
        (8, False, "mats", 24),     # control
    ]
    for batch, scan, remat, layers in VARIANTS:
        tag = {"batch": batch, "scan": scan, "remat": remat,
               "layers": layers}
        try:
            tag.update(try_variant(batch, scan, remat, layers, peak))
        except Exception as e:  # noqa: BLE001 — the error IS the data
            tag["error"] = f"{type(e).__name__}: {e}"[:800]
            tag["trace_tail"] = traceback.format_exc()[-400:]
        print(json.dumps(tag), flush=True)


if __name__ == "__main__":
    main()
