"""Denoised head-to-head of the top backward configs from sweep_bwd.py:
3 repeats each, min-of-reps slope.  Prints JSON lines + the winner.

    python scripts/confirm_bwd.py
"""

from __future__ import annotations

import json
import sys

sys.path.insert(0, ".")

from bench_compute import _slope  # noqa: E402


def main() -> None:
    import jax
    import jax.numpy as jnp

    from nos_tpu.ops import attention as A

    if jax.default_backend() != "tpu":
        print(json.dumps({"skipped": "not on tpu"}))
        return

    B, S, H, D = 8, 2048, 8, 128
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.bfloat16)
               for kk in jax.random.split(key, 3))
    fwd_flops = 4 * B * H * S * S * D * 0.5
    bwd_flops = 3.5 * fwd_flops

    def grad_maker(bq, bk):
        def loss(qq, kk2, vv):
            return jnp.sum(A.flash_attention(
                qq, kk2, vv, True, bq, bk).astype(jnp.float32) ** 2)

        def gstep(qx):
            gq, gk, gv = jax.grad(loss, (0, 1, 2))(qx, k, v)
            return gq + gk + gv

        @jax.jit
        def run(q, k, v, iters):
            return jax.lax.fori_loop(
                0, iters, lambda i, acc: gstep(acc), q)[0, 0, 0, 0]

        def make(iters):
            i = jnp.int32(iters)
            return lambda: float(run(q, k, v, i))
        return make

    CONFIGS = [
        ("split", 1024, 512), ("split", 512, 512),
        ("fused", 512, 1024), ("fused", 1024, 512), ("fused", 512, 512),
    ]
    results = []
    for impl, bq, bk in CONFIGS:
        A.set_backward_impl(impl)
        times = []
        for _ in range(3):
            times.append(_slope(grad_maker(bq, bk)))
        t = min(times)
        r = {"impl": impl, "bq": bq, "bk": bk,
             "grad_ms_minrep": round(t * 1e3, 3),
             "all_ms": [round(x * 1e3, 3) for x in times]}
        results.append((t, r))
        print(json.dumps(r), flush=True)
    A.set_backward_impl("fused")
    best = min(results)[1]
    print(json.dumps({"best": best, "note": "grad time = fwd+bwd chained"}))


if __name__ == "__main__":
    main()
