{{/*
Common helpers.  The template language is deliberately restricted to the
subset the repo's renderer test understands (tests/test_deploy.py):
.Values/.Release/.Chart lookups, `default`, if/end blocks, and these
named helpers — keep new templates inside that subset so `pytest` keeps
proving the chart renders.
*/}}

{{- define "nos-tpu.tag" -}}
{{ .Values.image.tag | default .Chart.AppVersion }}
{{- end -}}

{{- define "nos-tpu.labels" -}}
app.kubernetes.io/part-of: nos-tpu
app.kubernetes.io/managed-by: Helm
{{- end -}}
